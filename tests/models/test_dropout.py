"""Dropout semantics: attention/hidden dropout with an explicitly-threaded
rng (reference C7 applies torch nn.Dropout inside attention/MLP/embeddings;
here the rng rides the batch dict so train steps stay pure functions).

Covers: eval identity (rng=None), train-mode stochasticity + rng
determinism, inverted-dropout scaling, the chunked-accumulation path, the
SPMD distributed path on the virtual mesh, and the encoder-decoder stack.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs, TrainArgs
from hetu_galvatron_tpu.models import modules as M
from hetu_galvatron_tpu.models.builder import (
    causal_lm_loss,
    forward_causal_lm,
    init_causal_lm,
)
from hetu_galvatron_tpu.runtime.dataloader import make_batch
from hetu_galvatron_tpu.runtime.optimizer import make_optimizer
from hetu_galvatron_tpu.runtime.trainer import make_loss_fn, make_train_step

pytestmark = [pytest.mark.model]

CFG = ModelArgs(
    hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
    vocab_size=128, max_position_embeddings=64, seq_length=16,
    hidden_act="swiglu", normalization="rmsnorm",
    position_embedding_type="rope", tie_word_embeddings=False,
    add_bias_linear=False, add_qkv_bias=False,
    make_vocab_size_divisible_by=1, ffn_hidden_size=128,
    hidden_dropout=0.5, attention_dropout=0.25,
)
EVAL_CFG = CFG.model_copy(update={"hidden_dropout": 0.0,
                                  "attention_dropout": 0.0})


def _batch(bsz=4, seed=0):
    data = np.random.RandomState(seed).randint(
        0, 128, (bsz, CFG.seq_length + 1))
    return jax.tree.map(jnp.asarray, make_batch(data))


def test_dropout_unit_scaling_and_identity():
    x = jnp.ones((64, 64))
    assert M.dropout(x, 0.5, None) is x  # eval: identity, no copy
    rng = jax.random.key(0)
    y = np.asarray(M.dropout(x, 0.5, rng))
    kept = y != 0.0
    # inverted dropout: survivors scaled by 1/(1-rate)
    np.testing.assert_allclose(y[kept], 2.0)
    assert 0.3 < kept.mean() < 0.7


def test_forward_eval_identity_and_train_stochasticity():
    params, _ = init_causal_lm(jax.random.key(0), CFG)
    tokens = _batch()["tokens"]
    # rng=None on a dropout-enabled cfg == the dropout-free cfg exactly
    a = forward_causal_lm(params, tokens, CFG, compute_dtype=jnp.float32)
    b = forward_causal_lm(params, tokens, EVAL_CFG, compute_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # same rng => identical; different rng => different
    r1, r2 = jax.random.key(1), jax.random.key(2)
    o1 = forward_causal_lm(params, tokens, CFG, compute_dtype=jnp.float32,
                           dropout_rng=r1)
    o1b = forward_causal_lm(params, tokens, CFG, compute_dtype=jnp.float32,
                            dropout_rng=r1)
    o2 = forward_causal_lm(params, tokens, CFG, compute_dtype=jnp.float32,
                           dropout_rng=r2)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o1b))
    assert np.abs(np.asarray(o1) - np.asarray(o2)).max() > 1e-3
    assert np.abs(np.asarray(o1) - np.asarray(a)).max() > 1e-3


def test_train_step_rng_in_batch_and_chunks():
    tx = make_optimizer(TrainArgs(lr=1e-3, lr_decay_style="constant"))
    params, _ = init_causal_lm(jax.random.key(0), CFG)
    loss_fn = make_loss_fn(CFG, compute_dtype=jnp.float32)
    batch = _batch(bsz=4)
    for chunks in (1, 2):
        step = jax.jit(make_train_step(loss_fn, tx, chunks=chunks))
        opt = tx.init(params)
        b = dict(batch)
        b["dropout_rng"] = jax.random.key(7)
        p1, _, m1 = step(params, opt, b)
        p1b, _, m1b = step(params, opt, dict(b))
        b2 = dict(batch)
        b2["dropout_rng"] = jax.random.key(8)
        p2, _, m2 = step(params, opt, b2)
        assert float(m1["loss"]) == pytest.approx(float(m1b["loss"]))
        assert float(m1["loss"]) != pytest.approx(float(m2["loss"]),
                                                  abs=1e-6)
        # batch dict passed in is not mutated by the step
        assert "dropout_rng" in b


def test_dropout_grads_flow_and_masked_positions_get_zero_grad():
    """Gradient sanity: with dropout the grads still differentiate the same
    graph (no rng leakage into tangents), and eval-mode grads match the
    dropout-free config."""
    params, _ = init_causal_lm(jax.random.key(0), CFG)
    batch = _batch()
    g_eval = jax.grad(lambda p: causal_lm_loss(
        p, batch, CFG, compute_dtype=jnp.float32))(params)
    g_ref = jax.grad(lambda p: causal_lm_loss(
        p, batch, EVAL_CFG, compute_dtype=jnp.float32))(params)
    for a, b in zip(jax.tree.leaves(g_eval), jax.tree.leaves(g_ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.distributed
def test_spmd_dropout_runs_and_is_rng_deterministic():
    from hetu_galvatron_tpu.parallel.spmd import (
        make_spmd_train_step,
        shard_params,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.mesh import build_mesh

    devices = jax.devices("cpu")[:4]
    args = CoreArgs(model=CFG.model_dump())
    args.parallel.global_tp_deg = 2
    args.parallel.global_train_batch_size = 4
    hpc = get_hybrid_parallel_config(args, 4)
    mesh = build_mesh(4, 1, devices=devices)
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    tx = make_optimizer(TrainArgs(lr=1e-3, lr_decay_style="constant"))
    step, pspecs, opt_specs, batch_shd = make_spmd_train_step(
        CFG, hpc, mesh, axes, tx, params, compute_dtype=jnp.float32,
        donate=False)
    params = shard_params(params, pspecs, mesh)
    opt = jax.jit(tx.init)(params)
    batch = jax.device_put(_batch(bsz=4), batch_shd)

    with pytest.raises(ValueError, match="dropout_rng"):
        step(params, opt, dict(batch))

    b = dict(batch)
    b["dropout_rng"] = jax.random.key(3)
    _, _, m1 = step(params, opt, b)
    _, _, m1b = step(params, opt, dict(b))
    b2 = dict(batch)
    b2["dropout_rng"] = jax.random.key(4)
    _, _, m2 = step(params, opt, b2)
    assert float(m1["loss"]) == pytest.approx(float(m1b["loss"]), rel=1e-6)
    assert float(m1["loss"]) != pytest.approx(float(m2["loss"]), abs=1e-6)


def test_encdec_dropout_paths():
    t5 = CFG.model_copy(update={
        "model_type": "t5", "num_encoder_layers": 2, "hidden_act": "relu",
        "position_embedding_type": "rope"})
    from hetu_galvatron_tpu.models.encdec import init_encdec

    params, _ = init_encdec(jax.random.key(0), t5)
    rs = np.random.RandomState(0)
    batch = {
        "enc_tokens": jnp.asarray(rs.randint(0, 128, (2, 8))),
        "tokens": jnp.asarray(rs.randint(0, 128, (2, 8))),
        "labels": jnp.asarray(rs.randint(0, 128, (2, 8))),
    }
    l_eval = causal_lm_loss(params, batch, t5, compute_dtype=jnp.float32)
    b = dict(batch)
    b["dropout_rng"] = jax.random.key(5)
    l1 = causal_lm_loss(params, b, t5, compute_dtype=jnp.float32)
    l1b = causal_lm_loss(params, dict(b), t5, compute_dtype=jnp.float32)
    assert float(l1) == pytest.approx(float(l1b))
    assert float(l1) != pytest.approx(float(l_eval), abs=1e-6)


@pytest.mark.distributed
def test_pipeline_engine_dropout_rng_deterministic():
    """pp>1 dropout: the same per-step key gives the same loss (the
    backward's remat recomputation reuses the forward's masks), a different
    key gives a different loss, and a dropout-off cfg through the engine
    still matches the single-device loss."""
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine

    args = CoreArgs(model=CFG.model_dump())
    args.parallel.pp_deg = 2
    args.parallel.chunks = 2
    args.parallel.global_train_batch_size = 4
    hpc = get_hybrid_parallel_config(args, 4)
    tr = TrainArgs(lr=1e-3, lr_decay_style="constant")
    eng = PipelineEngine(CFG, hpc, tr, devices=jax.devices("cpu")[:4],
                         compute_dtype=jnp.float32)
    params, axes = init_causal_lm(jax.random.key(0), CFG)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    raw = {k: np.asarray(v) for k, v in _batch(bsz=4).items()}

    b1 = dict(raw)
    b1["dropout_rng"] = jax.random.key(11)
    _, _, m1 = eng.train_step(sp, so, b1)
    _, _, m1b = eng.train_step(sp, so, dict(b1))
    b2 = dict(raw)
    b2["dropout_rng"] = jax.random.key(12)
    _, _, m2 = eng.train_step(sp, so, b2)
    assert m1["loss"] == pytest.approx(m1b["loss"], rel=1e-6)
    assert m1["loss"] != pytest.approx(m2["loss"], abs=1e-6)

    # dropout-off cfg through the engine matches the single-device loss
    eng0 = PipelineEngine(EVAL_CFG, hpc, tr, devices=jax.devices("cpu")[:4],
                          compute_dtype=jnp.float32)
    sp0 = eng0.split_params(params, axes)
    so0 = eng0.init_opt(sp0, axes)
    _, _, m0 = eng0.train_step(sp0, so0, dict(raw))
    ref = float(causal_lm_loss(params, _batch(bsz=4), EVAL_CFG,
                               compute_dtype=jnp.float32))
    assert m0["loss"] == pytest.approx(ref, rel=1e-4)


def test_attention_dropout_refuses_custom_kernels():
    """attention_dropout>0 with an installed flash/ring/Ulysses kernel must
    refuse loudly, not silently swap in the score-materializing XLA core."""
    params, _ = init_causal_lm(jax.random.key(0), CFG)
    tokens = _batch()["tokens"]
    fake_kernel = lambda q, k, v, causal=True: M.xla_sdpa(q, k, v,
                                                          causal=causal)
    with pytest.raises(NotImplementedError, match="attention_dropout"):
        forward_causal_lm(
            params, tokens, CFG, compute_dtype=jnp.float32,
            dropout_rng=jax.random.key(0),
            layer_overrides={i: {"sdpa_fn": fake_kernel}
                             for i in range(CFG.num_hidden_layers)})
