"""Shared backoff/retry policy (utils/retrying.py): jitter envelope,
retryability routing, attempt budgets — the primitive every fault-tolerant
I/O path (checkpoint reads, object-store fetches, restart supervisor)
leans on."""

import random

import pytest

from hetu_galvatron_tpu.utils.retrying import (
    backoff_delay,
    backoff_delays,
    retry_call,
    set_fault_injector,
)

pytestmark = [pytest.mark.utils, pytest.mark.robustness]


def test_backoff_envelope_is_capped_exponential():
    assert backoff_delay(0, base=1.0, cap=30.0, jitter=False) == 1.0
    assert backoff_delay(1, base=1.0, cap=30.0, jitter=False) == 2.0
    assert backoff_delay(3, base=1.0, cap=30.0, jitter=False) == 8.0
    assert backoff_delay(10, base=1.0, cap=30.0, jitter=False) == 30.0  # cap


def test_backoff_jitter_stays_inside_envelope():
    rng = random.Random(0)
    for a in range(8):
        for _ in range(20):
            d = backoff_delay(a, base=0.5, cap=4.0, rng=rng)
            assert 0.0 <= d <= min(4.0, 0.5 * 2 ** a)


def test_backoff_jitter_decorrelates():
    """Full jitter: two workers with different rngs must not sleep the
    same schedule (the thundering-herd property the supervisor needs)."""
    a = list(backoff_delays(6, base=1.0, cap=60.0, rng=random.Random(1)))
    b = list(backoff_delays(6, base=1.0, cap=60.0, rng=random.Random(2)))
    assert len(a) == len(b) == 5  # no sleep after the final attempt
    assert a != b


def test_retry_call_retries_then_succeeds():
    calls, sleeps = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise IOError("SlowDown")
        return "ok"

    out = retry_call(flaky, attempts=4, base=0.1, sleep=sleeps.append,
                     rng=random.Random(0))
    assert out == "ok"
    assert len(calls) == 3
    assert len(sleeps) == 2 and all(s >= 0 for s in sleeps)


def test_retry_call_nonretryable_fails_fast():
    calls = []

    def gone():
        calls.append(1)
        raise FileNotFoundError("404")

    with pytest.raises(FileNotFoundError):
        retry_call(gone, attempts=5,
                   retryable=lambda e: not isinstance(e, FileNotFoundError),
                   sleep=lambda s: None)
    assert len(calls) == 1  # a permanent error never burns the budget


def test_retry_call_exhausts_budget_and_raises_last():
    calls = []

    def always():
        calls.append(1)
        raise IOError(f"attempt {len(calls)}")

    with pytest.raises(IOError, match="attempt 3"):
        retry_call(always, attempts=3, sleep=lambda s: None)
    assert len(calls) == 3


def test_retry_call_counts_in_registry(monkeypatch):
    from hetu_galvatron_tpu.observability import registry as reg_mod

    reg = reg_mod.MetricsRegistry()
    monkeypatch.setattr(reg_mod, "get_registry", lambda: reg)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise IOError("x")
        return 1

    retry_call(flaky, attempts=2, op="test.op", sleep=lambda s: None)
    assert reg.counter("retry/attempts", op="test.op").value == 1


def test_on_retry_hook_sees_error_and_delay():
    seen = []
    retry_call(
        lambda: (_ for _ in ()).throw(IOError("x")) if not seen else "ok",
        attempts=2, sleep=lambda s: None,
        on_retry=lambda e, a, d: seen.append((type(e).__name__, a)))
    assert seen == [("OSError", 0)]  # IOError is an OSError alias


def test_deadline_caps_total_elapsed():
    """A slow failing fn must surface its error once deadline_s of wall
    has elapsed, even with attempts remaining — the attempt budget alone
    would let a hung mount stall a resume for attempts x hang time."""
    now = [0.0]
    calls = []

    def slow_fail():
        calls.append(1)
        now[0] += 4.0  # each attempt burns 4s of (fake) wall
        raise IOError("mount hung")

    with pytest.raises(IOError, match="mount hung"):
        retry_call(slow_fail, attempts=10, sleep=lambda s: None,
                   deadline_s=10.0, clock=lambda: now[0])
    assert len(calls) == 3  # 4s + 4s + 4s crossed the 10s deadline


def test_deadline_clamps_backoff_sleep():
    """The last pre-deadline sleep is truncated to the remaining budget,
    not the full jittered envelope."""
    now = [0.0]
    sleeps = []

    def fake_sleep(s):
        sleeps.append(s)
        now[0] += s

    def fail():
        now[0] += 1.0
        raise IOError("x")

    with pytest.raises(IOError):
        retry_call(fail, attempts=50, base=100.0, cap=100.0,
                   sleep=fake_sleep, deadline_s=5.0,
                   clock=lambda: now[0],
                   rng=random.Random(0))
    assert sleeps and all(s <= 5.0 for s in sleeps)


def test_deadline_counts_in_registry(monkeypatch):
    from hetu_galvatron_tpu.observability import registry as reg_mod

    reg = reg_mod.MetricsRegistry()
    monkeypatch.setattr(reg_mod, "get_registry", lambda: reg)
    now = [0.0]

    def fail():
        now[0] += 9.0
        raise IOError("x")

    with pytest.raises(IOError):
        retry_call(fail, attempts=5, op="test.op", sleep=lambda s: None,
                   deadline_s=8.0, clock=lambda: now[0])
    assert reg.counter("retry/deadline_exceeded", op="test.op").value == 1


def test_fault_injector_fires_by_op_and_restores():
    """The chaos seam: an installed injector fails matching ops (counted
    against the SAME retry budget), and set_fault_injector returns the
    previous injector so harnesses can nest/restore."""
    hits = []

    def inject(op):
        if "checkpoint" in op and len(hits) < 2:
            hits.append(op)
            return OSError("injected")
        return None

    prev = set_fault_injector(inject)
    try:
        out = retry_call(lambda: "ok", attempts=3, op="checkpoint.read",
                         sleep=lambda s: None)
        assert out == "ok"
        assert hits == ["checkpoint.read", "checkpoint.read"]
        # a non-matching op is untouched
        assert retry_call(lambda: "ok", attempts=1, op="dataset.fetch",
                          sleep=lambda s: None) == "ok"
    finally:
        restored = set_fault_injector(prev)
        assert restored is inject


def test_fault_injector_exhausting_budget_raises_injected():
    def inject(op):
        return OSError("always down")

    prev = set_fault_injector(inject)
    try:
        with pytest.raises(OSError, match="always down"):
            retry_call(lambda: "ok", attempts=2, sleep=lambda s: None)
    finally:
        set_fault_injector(prev)
