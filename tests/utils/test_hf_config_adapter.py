"""HF config adapter tests (parity with reference adapter behavior:
family detection of norm/act/rope/bias + layertype splitting for MoE)."""

import pytest

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.utils.hf_config_adapter import (
    model_layer_configs,
    model_name,
    populate_model_args_from_hf,
)

pytestmark = pytest.mark.utils


LLAMA_CFG = {
    "model_type": "llama",
    "_name_or_path": "meta-llama/Llama-2-7b-hf",
    "hidden_size": 4096,
    "num_hidden_layers": 32,
    "num_attention_heads": 32,
    "num_key_value_heads": 32,
    "intermediate_size": 11008,
    "vocab_size": 32000,
    "max_position_embeddings": 4096,
    "rms_norm_eps": 1e-5,
    "rope_theta": 10000.0,
    "tie_word_embeddings": False,
    "attention_bias": False,
}

GPT2_CFG = {
    "model_type": "gpt2",
    "n_embd": 768,
    "n_layer": 12,
    "n_head": 12,
    "vocab_size": 50257,
    "n_positions": 1024,
    "layer_norm_epsilon": 1e-5,
}

QWEN2_CFG = {
    "model_type": "qwen2",
    "hidden_size": 3584,
    "num_hidden_layers": 28,
    "num_attention_heads": 28,
    "num_key_value_heads": 4,
    "intermediate_size": 18944,
    "vocab_size": 152064,
    "max_position_embeddings": 32768,
    "rms_norm_eps": 1e-6,
    "tie_word_embeddings": False,
}

MIXTRAL_CFG = {
    "model_type": "mixtral",
    "hidden_size": 4096,
    "num_hidden_layers": 32,
    "num_attention_heads": 32,
    "num_key_value_heads": 8,
    "intermediate_size": 14336,
    "vocab_size": 32000,
    "num_local_experts": 8,
    "num_experts_per_tok": 2,
}


def test_llama_family_detection():
    m = populate_model_args_from_hf(LLAMA_CFG)
    assert m.model_type == "llama"
    assert m.normalization == "rmsnorm"
    assert m.hidden_act == "swiglu"
    assert m.position_embedding_type == "rope"
    assert m.hidden_size == 4096 and m.ffn_dim == 11008
    assert not m.tie_word_embeddings
    assert not m.add_qkv_bias and not m.add_bias_linear


def test_gpt2_family_detection():
    m = populate_model_args_from_hf(GPT2_CFG)
    assert m.model_type == "gpt"
    assert m.normalization == "layernorm"
    assert m.hidden_act == "gelu"
    assert m.position_embedding_type == "learned"
    assert m.hidden_size == 768 and m.num_hidden_layers == 12
    assert m.max_position_embeddings == 1024
    assert m.add_qkv_bias and m.add_bias_linear  # gpt2 has all biases


def test_qwen2_bias_detection():
    m = populate_model_args_from_hf(QWEN2_CFG)
    assert m.add_qkv_bias  # qwen2: qkv bias on
    assert not m.add_bias_linear  # but no mlp bias
    assert m.kv_heads == 4  # GQA


def test_moe_detection_and_layer_split():
    m = populate_model_args_from_hf(MIXTRAL_CFG)
    assert m.model_type == "moe"
    assert m.num_experts == 8 and m.moe_topk == 2
    cfgs = model_layer_configs(m)
    # every layer of mixtral is MoE (moe_layer_freq=1) => single MoE layertype
    assert len(cfgs) == 1
    assert cfgs[0]["layer_num"] == 32
    assert cfgs[0]["num_experts"] == 8


def test_moe_alternating_layer_split():
    m = ModelArgs(num_hidden_layers=24, num_experts=16, moe_layer_freq=2)
    cfgs = model_layer_configs(m)
    assert len(cfgs) == 2
    dense, moe = cfgs
    assert dense["layer_num"] + moe["layer_num"] == 24
    assert moe["layer_num"] == 12 and "num_experts" in moe


def test_dense_layer_configs():
    m = ModelArgs()
    cfgs = model_layer_configs(m)
    assert len(cfgs) == 1
    assert cfgs[0]["layer_num"] == m.num_hidden_layers
    assert cfgs[0]["vocab_size"] == m.padded_vocab_size


def test_model_name_sanitized():
    m = populate_model_args_from_hf(LLAMA_CFG)
    assert "/" not in model_name(m)


def test_gemma2_refused_and_decoupled_head_dim_generic():
    from hetu_galvatron_tpu.utils.hf_config_adapter import (
        populate_model_args_from_hf,
    )

    with pytest.raises(NotImplementedError, match="gemma2"):
        populate_model_args_from_hf({"model_type": "gemma2",
                                     "hidden_size": 64})
    # decoupled head_dim comes through the shared field map for ANY family
    # (mistral-nemo: 5120 hidden, 32 heads, head_dim 128)
    cfg = populate_model_args_from_hf({
        "model_type": "mistral", "hidden_size": 5120,
        "num_hidden_layers": 2, "num_attention_heads": 32,
        "num_key_value_heads": 8, "intermediate_size": 14336,
        "vocab_size": 1024, "head_dim": 128, "max_position_embeddings": 64})
    assert cfg.head_dim == 128
