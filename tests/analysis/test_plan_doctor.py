"""Plan doctor (Pass 1): malformed-plan corpus + engine/kernel reports.

The contract under test: ``diagnose_plan`` NEVER raises on a malformed
plan — every corpus entry yields ``ok=False`` with an actionable
diagnostic naming the offending key/value — and on valid plans its
engine/kernel verdict is the runtime's verdict (the shared predicates in
``analysis/eligibility.py``).
"""

import io
import json
import os

import pytest

from hetu_galvatron_tpu.analysis.plan_doctor import diagnose_plan
from hetu_galvatron_tpu.core.args_schema import ModelArgs

pytestmark = [pytest.mark.staticcheck, pytest.mark.utils]


def tiny_model(**kw) -> ModelArgs:
    base = dict(hidden_size=64, num_hidden_layers=4, num_attention_heads=4,
                vocab_size=256, seq_length=16, max_position_embeddings=32,
                hidden_act="swiglu", normalization="rmsnorm",
                position_embedding_type="rope", tie_word_embeddings=False,
                add_bias_linear=False, add_qkv_bias=False,
                make_vocab_size_divisible_by=1, ffn_hidden_size=128)
    base.update(kw)
    return ModelArgs(**base)


def good_plan(**kw):
    plan = {
        "pp_deg": 2, "tp_sizes_enc": "2,2,2,2",
        "tp_consecutive_flags": "1,1,1,1", "dp_types_enc": "0,0,0,0",
        "use_sp": "0,0,0,0", "cp_sizes_enc": "1,1,1,1",
        "checkpoint": "0,0,0,0", "global_bsz": 4, "chunks": 2,
        "pp_division": "2,2", "pipeline_type": "pipedream_flush",
        "default_dp_type": "ddp", "vtp": 2, "vsp": 0, "embed_sdp": 0,
    }
    plan.update(kw)
    return plan


# one malformed plan per failure class; every entry must produce a
# diagnostic CONTAINING the expected substring, and never a traceback
MALFORMED_CORPUS = [
    ("missing_pp_deg",
     {k: v for k, v in good_plan().items() if k != "pp_deg"}, "pp_deg"),
    ("missing_tp_vector",
     {k: v for k, v in good_plan().items() if k != "tp_sizes_enc"},
     "tp_sizes_enc"),
    ("non_integer_pp_deg", good_plan(pp_deg="two"), "integer"),
    ("fractional_pp_deg", good_plan(pp_deg=2.5), "integer"),
    ("non_integer_vector", good_plan(cp_sizes_enc="1,x,1,1"),
     "cp_sizes_enc"),
    ("wrong_length_vector", good_plan(dp_types_enc="0,0"), "dp_types_enc"),
    ("zero_layers", good_plan(tp_sizes_enc=""), "zero layers"),
    ("negative_pp", good_plan(pp_deg=-2), "pp_deg"),
    ("non_pow2_tp", good_plan(tp_sizes_enc="3,6,3,3"), "not divisible"),
    ("bad_dp_type", good_plan(default_dp_type="zero9"), "default_dp_type"),
    ("tp_exceeds_world", good_plan(tp_sizes_enc="16,16,16,16"),
     "not divisible"),
    ("division_sum_mismatch", good_plan(pp_division="3,2"), "pp_division"),
    ("division_len_mismatch", good_plan(pp_division="1,1,2"),
     "pp_division"),
    ("bsz_not_multiple_of_chunks", good_plan(global_bsz=3), "chunks"),
    ("layer_count_mismatch", good_plan(
        tp_sizes_enc="2,2", tp_consecutive_flags="1,1",
        dp_types_enc="0,0", use_sp="0,0", cp_sizes_enc="1,1",
        checkpoint="0,0", pp_division="1,1"), "model has"),
    ("non_object_plan", ["not", "a", "plan"], "object"),
]


@pytest.mark.parametrize("name,plan,needle",
                         [(n, p, s) for n, p, s in MALFORMED_CORPUS])
def test_malformed_plan_yields_diagnostic_not_traceback(name, plan, needle):
    report = diagnose_plan(plan, tiny_model(), 8)
    assert not report.ok, name
    assert report.errors, name
    joined = " | ".join(report.errors)
    assert needle in joined, f"{name}: {joined!r} lacks {needle!r}"
    # the report must render without raising even when broken
    report.render(io.StringIO())


def test_malformed_json_file_is_diagnosed(tmp_path):
    p = tmp_path / "broken.json"
    p.write_text("{ this is not json")
    report = diagnose_plan(str(p), tiny_model(), 8)
    assert not report.ok
    assert any("invalid JSON" in e for e in report.errors)
    assert str(p) in report.errors[0]


def test_missing_file_is_diagnosed(tmp_path):
    report = diagnose_plan(str(tmp_path / "nope.json"), tiny_model(), 8)
    assert not report.ok
    assert any("cannot read plan" in e for e in report.errors)


def test_acceptance_plan_gets_compiled_engine_and_rings():
    from hetu_galvatron_tpu.cli.check import ACCEPTANCE_PLAN

    report = diagnose_plan(ACCEPTANCE_PLAN, tiny_model(), 8)
    assert report.ok, report.errors
    assert report.engine == "compiled"
    assert len(report.layers) == 4
    assert all(d.projections == "ring_overlap" for d in report.layers)
    assert [d.stage for d in report.layers] == [0, 0, 1, 1]


def test_heterogeneous_division_falls_back_to_host_with_reason():
    model = tiny_model(num_hidden_layers=5)
    plan = good_plan(
        tp_sizes_enc="2,2,2,2,2", tp_consecutive_flags="1,1,1,1,1",
        dp_types_enc="0,0,0,0,0", use_sp="0,0,0,0,0",
        cp_sizes_enc="1,1,1,1,1", checkpoint="0,0,0,0,0",
        pp_division="3,2")
    report = diagnose_plan(plan, model, 8)
    assert report.ok, report.errors  # valid plan — just not compiled
    assert report.engine == "host"
    assert "heterogeneous per-stage layer counts" in report.engine_reason


def test_per_layer_kernel_dispatch_cp_and_ulysses():
    plan = good_plan(pp_deg=1, tp_sizes_enc="2,2,2,1",
                     use_sp="0,1,0,0", cp_sizes_enc="1,1,2,1",
                     pp_division="4", global_bsz=8, chunks=1)
    report = diagnose_plan(plan, tiny_model(), 8)
    assert report.ok, report.errors
    assert report.engine == "spmd"
    att = [d.attention for d in report.layers]
    assert att[1] == "ulysses_a2a"
    assert att[2] == "ring"
    # per-layer overlap fallbacks carry the canonical reasons
    assert report.layers[0].projections == "ring_overlap"
    assert "ulysses" in report.layers[1].overlap_reason
    assert "cp layer" in report.layers[2].overlap_reason
    assert "tp == 1" in report.layers[3].overlap_reason


def test_world_mismatch_still_renders_the_layer_table():
    """A format-valid plan against the wrong world fails with the
    divisibility error but STILL shows the per-layer table (unresolved
    dp), so the operator sees what the plan wants."""
    report = diagnose_plan(good_plan(), tiny_model(), 6)  # 6 % (2*2) != 0
    assert not report.ok
    assert any("not divisible" in e for e in report.errors)
    assert len(report.layers) == 4
    assert any("UNRESOLVED dp" in w for w in report.warnings)


def test_integral_float_degrees_are_tolerated():
    """JSON round-trip artifacts (2.0) parse; fractional floats do not
    (covered in the corpus above)."""
    report = diagnose_plan(good_plan(pp_deg=2.0, vtp=2.0), tiny_model(), 8)
    assert report.ok, report.errors


def test_doctor_without_world_assumes_smallest_and_warns():
    report = diagnose_plan(good_plan(), tiny_model())
    assert report.world_size == 4  # pp2 * tp2
    assert any("smallest world" in w for w in report.warnings)


def test_plan_format_error_carries_key_and_path(tmp_path):
    from hetu_galvatron_tpu.utils.strategy import (
        PlanFormatError,
        config2strategy,
        load_strategy_config,
        save_strategy_config,
    )

    with pytest.raises(PlanFormatError) as ei:
        config2strategy(good_plan(ep_sizes_enc="1,1"))
    assert ei.value.key == "ep_sizes_enc"
    p = tmp_path / "x.json"
    p.write_text("[1, 2]")
    with pytest.raises(PlanFormatError) as ei:
        load_strategy_config(str(p))
    assert ei.value.path == str(p)
    # the validating writer refuses to write a malformed plan...
    with pytest.raises(PlanFormatError):
        save_strategy_config(str(tmp_path / "bad.json"),
                             good_plan(use_sp="1"))
    assert not os.path.exists(tmp_path / "bad.json")
    # ...and round-trips a good one
    save_strategy_config(str(tmp_path / "ok.json"), good_plan(),
                         world_size=8)
    assert json.loads((tmp_path / "ok.json").read_text())["pp_deg"] == 2
