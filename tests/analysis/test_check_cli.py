"""The CI gate: ``cli/check.py --all`` must be green at HEAD.

The tier-1 test runs the passes in-process (cheap: tracing only); the
subprocess test pins the CLI contract itself (exit codes, a standalone
process forcing the CPU platform) and rides the slow tier.
"""

import subprocess
import sys

import pytest

from hetu_galvatron_tpu.cli import check as check_cli

pytestmark = [pytest.mark.staticcheck, pytest.mark.core]


def test_check_all_is_green_at_head(capsys):
    """Every pass — plan doctor over the committed example plans, the
    census with the exact-count cross-check, the memory doctor with the
    cost-model cross-check, the sharding-flow byte census, the lint
    baseline gate — exits clean at HEAD."""
    rc = check_cli.run_all()
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "plan doctor: OK" in out
    assert "census: OK" in out
    assert "memory doctor: OK (all plans)" in out
    assert "flow: OK" in out
    assert "lint: OK" in out
    assert "check --all: OK" in out
    # the memory pass prints a per-device peak and unit ratios
    assert "per-device peak" in out
    assert "cross-check ratios" in out
    # the flow pass prints the exact byte prediction it matched
    assert "plan arithmetic predicts" in out


def test_check_memory_hbm_gate_rejects_oversized_plan(capsys):
    """--memory --hbm-gb: a budget below the predicted peak turns the
    pass red with the OOM diagnostic; a roomy budget stays green."""
    rc = check_cli.main(["--memory", "--hbm-gb", "1e-05"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "exceeds the --hbm-gb budget" in out
    rc = check_cli.main(["--memory", "--hbm-gb", "16"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "memory doctor: OK (all plans)" in out


def test_check_doctor_flags_a_corrupted_plan(tmp_path, capsys):
    """A deliberately corrupted committed plan fails Pass 1 with a
    diagnostic naming the broken key, exit code 1."""
    import json

    with open(check_cli.ACCEPTANCE_PLAN) as f:
        plan = json.load(f)
    plan["cp_sizes_enc"] = "1,1"  # wrong-length vector
    p = tmp_path / "corrupt.json"
    p.write_text(json.dumps(plan))
    rc = check_cli.main(["--plan", str(p), "--world", "8"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "cp_sizes_enc" in out
    assert "Traceback" not in out


def test_check_usage_exit_code():
    assert check_cli.main([]) == 2


def test_prune_baseline_cli_clears_stale_gate(monkeypatch, capsys,
                                              tmp_path):
    """--prune-baseline end to end on a COPY of the committed baseline
    (the real file stays untouched): a stale entry fails the gate, the
    prune removes exactly it, and the gate goes green."""
    import json
    import shutil

    from hetu_galvatron_tpu.analysis import lint as lint_mod

    copy = tmp_path / "baseline.json"
    shutil.copy(lint_mod.DEFAULT_BASELINE, copy)
    obj = json.loads(copy.read_text())
    obj["findings"]["GAL001:gone.py:f:x#0"] = "fixed code"
    copy.write_text(json.dumps(obj))
    # redirect every default-path read/write in run_lint to the copy
    monkeypatch.setattr(lint_mod, "DEFAULT_BASELINE", str(copy))

    rc = check_cli.run_lint()
    out = capsys.readouterr().out
    assert rc == 1 and "stale" in out

    rc = check_cli.run_lint(prune_stale=True)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "pruned 1 stale baseline entry" in out
    assert "lint: OK" in out
    after = json.loads(copy.read_text())["findings"]
    assert "GAL001:gone.py:f:x#0" not in after


def test_stale_baseline_fails_the_lint_gate(monkeypatch, capsys):
    """A baselined finding that no longer occurs must turn the gate red
    (same contract as the tier-1 test), not just print a hint."""
    from hetu_galvatron_tpu.analysis import lint as lint_mod

    real = lint_mod.load_baseline()
    # run_lint from-imports load_baseline at CALL time, so patching the
    # module attribute reaches it
    monkeypatch.setattr(
        lint_mod, "load_baseline",
        lambda path=None: {**real, "GAL001:gone.py:f:x#0": "fixed code"})
    rc = check_cli.run_lint()
    out = capsys.readouterr().out
    assert rc == 1
    assert "stale" in out


# the subprocess spins up its own jax on a fresh virtual platform (~tens
# of seconds of import + trace): slow tier
@pytest.mark.slow
def test_check_cli_subprocess_all():
    """The standalone CLI contract (the exact command CI and
    __graft_entry__.dryrun_multichip run)."""
    rc = subprocess.run(
        [sys.executable, "-m", "hetu_galvatron_tpu.cli.check", "--all"],
        capture_output=True, text=True, timeout=560)
    assert rc.returncode == 0, f"{rc.stdout}\n{rc.stderr}"
    assert "check --all: OK" in rc.stdout
