"""AST lint (Pass 3): synthetic offending snippets + the baseline gate.

Each rule is exercised against a minimal offending snippet AND a minimal
clean one; the tier-1 gate test asserts the real package produces zero
findings outside the committed baseline (zero-NEW, not zero — accepted
host-boundary syncs stay baselined with a justification each).
"""

import textwrap

import pytest

from hetu_galvatron_tpu.analysis.lint import (
    lint_file,
    lint_package,
    load_baseline,
    new_findings,
    stale_baseline,
)

pytestmark = [pytest.mark.staticcheck, pytest.mark.utils]


def lint_src(tmp_path, src, rel="runtime/trainer.py", hot_path=True):
    p = tmp_path / "snippet.py"
    p.write_text(textwrap.dedent(src))
    return lint_file(str(p), rel, hot_path=hot_path)


def rules(findings):
    return [f.rule for f in findings]


def test_gal001_host_sync_in_hot_path(tmp_path):
    src = """
    import numpy as np
    def step(metrics, arr):
        a = metrics["loss"].item()
        b = np.asarray(arr)
        c = jax.device_get(arr)
        return a, b, c
    """
    fs = lint_src(tmp_path, src)
    assert rules(fs) == ["GAL001", "GAL001", "GAL001"]
    # the same code OUTSIDE a hot-path module is not a finding
    assert lint_src(tmp_path, src, rel="cli/summarize.py",
                    hot_path=False) == []


def test_gal002_jit_inside_loop(tmp_path):
    bad = """
    import jax
    def train(fns):
        for m in range(4):
            fns[m] = jax.jit(lambda x: x)
    """
    good = """
    import jax
    def build():
        return jax.jit(lambda x: x)
    """
    assert rules(lint_src(tmp_path, bad, hot_path=False)) == ["GAL002"]
    assert lint_src(tmp_path, good, hot_path=False) == []


def test_gal003_axis_name_canon(tmp_path):
    bad = """
    import jax
    from jax.sharding import PartitionSpec as P
    def f(x):
        y = jax.lax.psum(x, "tp")          # not a mesh axis name
        spec = P("stage", None)
        return jax.lax.ppermute(y, "model", [(0, 1)])
    """
    good = """
    import jax
    from jax.sharding import PartitionSpec as P
    def f(x, axes):
        y = jax.lax.psum(x, "d0")
        spec = P("pp", ("d0", "d1"), None)
        return jax.lax.ppermute(y, axes, [(0, 1)])
    """
    assert rules(lint_src(tmp_path, bad, hot_path=False)) == \
        ["GAL003", "GAL003", "GAL003"]
    assert lint_src(tmp_path, good, hot_path=False) == []


def test_gal004_dynamic_named_scope(tmp_path):
    bad = """
    import jax
    def f(i):
        with jax.named_scope(f"layer{i}/ring"):
            pass
        with jax.named_scope("ring" + str(i)):
            pass
    """
    good = """
    import jax
    SCOPE = "tp_ring"
    def f():
        with jax.named_scope(SCOPE):
            pass
        with jax.named_scope("cp_ring"):
            pass
    """
    assert rules(lint_src(tmp_path, bad, hot_path=False)) == \
        ["GAL004", "GAL004"]
    assert lint_src(tmp_path, good, hot_path=False) == []
    # hier_stage_scope(CONSTANT/NAME, ...) is marker-preserving by
    # contract (the base scope stays a prefix of the returned name) —
    # exempt; a COMPUTED base would break matching and stays a finding
    preserving = """
    import jax
    from hetu_galvatron_tpu.ops.hier_reduce import (
        HIER_DP_RS_SCOPE, hier_stage_scope)
    def f(i, B):
        with jax.named_scope(hier_stage_scope(HIER_DP_RS_SCOPE, i, B)):
            pass
        with jax.named_scope(hier_stage_scope("hier_dp_ag", i, B)):
            pass
    """
    assert lint_src(tmp_path, preserving, hot_path=False) == []
    computed_base = """
    import jax
    from hetu_galvatron_tpu.ops.hier_reduce import hier_stage_scope
    def f(i, B):
        with jax.named_scope(hier_stage_scope("x" + str(i), i, B)):
            pass
    """
    assert rules(lint_src(tmp_path, computed_base, hot_path=False)) == \
        ["GAL004"]


def test_gal005_exception_swallowing(tmp_path):
    bad = """
    def f():
        try:
            g()
        except:
            pass
    def h():
        try:
            g()
        except Exception:
            pass
    """
    good = """
    def f(log):
        try:
            g()
        except ValueError:
            pass
        except Exception as e:
            log(f"swallowed: {e}")
    """
    assert rules(lint_src(tmp_path, bad, hot_path=False)) == \
        ["GAL005", "GAL005"]
    assert lint_src(tmp_path, good, hot_path=False) == []


def test_gal002_str_lower_is_not_a_lowering(tmp_path):
    """str.lower() in a loop (zero-arg by definition) must not read as
    jit AOT lowering; fn.lower(avals) in a loop must."""
    strings = """
    def norm(keys):
        out = []
        for k in keys:
            out.append(k.lower())
        return out
    """
    aot = """
    def costs(fn, shapes):
        for s in shapes:
            fn.lower(s)
    """
    assert lint_src(tmp_path, strings, hot_path=False) == []
    assert rules(lint_src(tmp_path, aot, hot_path=False)) == ["GAL002"]


def test_gal002_def_inside_loop_is_not_flagged(tmp_path):
    """A def nested in a loop runs only when called — the enclosing loop
    must not taint it; a jit INSIDE a comprehension is a per-element
    construction and IS flagged."""
    nested_def = """
    import jax
    def build(buckets):
        for b in buckets:
            def make():
                return jax.jit(lambda x: x)
    """
    comprehension = """
    import jax
    def build(fs):
        return [jax.jit(f) for f in fs]
    """
    assert lint_src(tmp_path, nested_def, hot_path=False) == []
    assert rules(lint_src(tmp_path, comprehension,
                          hot_path=False)) == ["GAL002"]


def test_fingerprints_are_line_number_free(tmp_path):
    a = lint_src(tmp_path, """
    def step(m):
        return m.item()
    """)
    b = lint_src(tmp_path, """
    # a comment pushing everything down


    def step(m):
        return m.item()
    """)
    assert a[0].fingerprint == b[0].fingerprint
    assert a[0].line != b[0].line


def test_duplicate_snippets_get_distinct_occurrences(tmp_path):
    fs = lint_src(tmp_path, """
    def step(a, b):
        x = a.item()
        x += 1
        x = a.item()
        return x
    """)
    assert len(fs) == 2
    assert fs[0].fingerprint != fs[1].fingerprint


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    fs = lint_src(tmp_path, "def broken(:\n", hot_path=False)
    assert rules(fs) == ["GAL000"]


def test_package_has_zero_new_findings():
    """THE tier-1 gate: every current finding is baselined (with a
    justification) and no baselined finding went stale without pruning."""
    findings = lint_package()
    baseline = load_baseline()
    new = new_findings(findings, baseline)
    assert new == [], (
        "new lint findings — fix them or baseline with a justification "
        "(python -m hetu_galvatron_tpu.cli.check --update-baseline):\n"
        + "\n".join(str(f) for f in new))
    stale = stale_baseline(findings, baseline)
    assert stale == [], (
        "baselined findings no longer occur; prune with --update-baseline: "
        f"{stale}")
    # every accepted finding carries a real justification
    assert all(j and not j.startswith("TODO") for j in baseline.values())


def test_gal006_env_read_outside_schema(tmp_path):
    """Every os.environ read form is flagged outside the schema/CLI
    boundary — and exempt inside it."""
    src = """
    import os
    def conf():
        a = os.environ.get("MY_KNOB")
        b = os.environ["MY_KNOB"]
        c = os.getenv("MY_KNOB", "1")
        return a, b, c
    """
    fs = lint_src(tmp_path, src, rel="runtime/newmod.py", hot_path=False)
    assert rules(fs) == ["GAL006", "GAL006", "GAL006"]
    # the schema and the CLI boundary are exempt
    for exempt in ("core/args_schema.py", "cli/serve.py"):
        assert lint_src(tmp_path, src, rel=exempt, hot_path=False) == []


def test_prune_baseline_roundtrip(tmp_path):
    """--prune-baseline: stale fingerprints are removed IN PLACE, live
    justifications survive untouched, and new findings are never
    auto-accepted — the committed baseline round-trips."""
    import json

    from hetu_galvatron_tpu.analysis.lint import prune_baseline

    src = """
    import os
    def conf():
        return os.getenv("X")
    """
    fs = lint_src(tmp_path, src, rel="runtime/m.py", hot_path=False)
    assert len(fs) == 1
    live = fs[0].fingerprint
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"findings": {
        live: "audited: reason",
        "GAL006:runtime/gone.py:f:os.getenv('Y')#0": "stale entry",
    }}))
    removed = prune_baseline(fs, str(bl))
    assert removed == ["GAL006:runtime/gone.py:f:os.getenv('Y')#0"]
    after = json.loads(bl.read_text())["findings"]
    assert after == {live: "audited: reason"}
    # idempotent: nothing stale left, file untouched
    assert prune_baseline(fs, str(bl)) == []
    assert json.loads(bl.read_text())["findings"] == after
    # a NEW finding (not in the baseline) is NOT added by pruning
    assert live in after and len(after) == 1


def test_injected_hot_path_item_fails_the_gate(tmp_path):
    """The acceptance drill: an injected .item() in step code is a NEW
    finding naming the file."""
    src = """
    def train_step(sp, opt, batch, metrics):
        loss = metrics["loss"].item()
        return loss
    """
    fs = lint_src(tmp_path, src, rel="runtime/trainer.py", hot_path=True)
    baseline = load_baseline()
    new = new_findings(fs, baseline)
    assert len(new) == 1
    assert new[0].rule == "GAL001"
    assert "runtime/trainer.py" in str(new[0])
    assert ".item()" in new[0].message
