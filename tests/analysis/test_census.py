"""Jaxpr collective census (Pass 2): hand-math, markers, injections.

The acceptance drill: on the searched tp2 x dp2 x pp2 plan the census of
the compiled 1F1B step must match the plan arithmetic EXACTLY —
T = m + 2(pp-1) ticks, 12 rings x (tp-1) hops per layer-slot-tick, 2 stage
rotations per tick — and every permute must carry its named_scope marker.
Injected regressions (an unmarked ppermute, a host callback) must each
fail the pass with a diagnostic naming the program.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from hetu_galvatron_tpu.analysis.census import (
    CensusResult,
    census_compiled_step,
    census_jaxpr,
    census_serving_programs,
    check_census,
)
from hetu_galvatron_tpu.core.args_schema import CoreArgs, ServingArgs
from hetu_galvatron_tpu.observability.telemetry import plan_collective_counts
from hetu_galvatron_tpu.runtime.hybrid_config import (
    get_hybrid_parallel_config,
)

pytestmark = [pytest.mark.staticcheck, pytest.mark.distributed]


def tiny_args(**parallel):
    return CoreArgs.model_validate({
        "model": {
            "hidden_size": 64, "num_hidden_layers": 4,
            "num_attention_heads": 4, "vocab_size": 256, "seq_length": 16,
            "max_position_embeddings": 32, "hidden_act": "swiglu",
            "normalization": "rmsnorm", "position_embedding_type": "rope",
            "tie_word_embeddings": False, "add_bias_linear": False,
            "add_qkv_bias": False, "make_vocab_size_divisible_by": 1,
            "ffn_hidden_size": 128,
        },
        "parallel": parallel,
    })


# ---------------------------------------------------------------------------
# census mechanics on synthetic jaxprs
# ---------------------------------------------------------------------------


def test_scan_multiplier_and_recursion():
    def body(c, _):
        return c + jax.lax.psum(c, "i"), None

    def fn(x):
        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    mesh = Mesh(np.array(jax.devices()[:2]), ("i",))
    shmapped = shard_map(fn, mesh, in_specs=P("i"), out_specs=P("i"),
                         check_rep=False)
    c = census_jaxpr(jax.make_jaxpr(shmapped)(jnp.zeros(2)))
    assert c.counts == {"all_reduce": 5}


def test_unmarked_permute_is_flagged_and_marked_is_not():
    mesh = Mesh(np.array(jax.devices()[:2]), ("i",))
    perm = [(0, 1), (1, 0)]

    def unmarked(x):
        return jax.lax.ppermute(x, "i", perm)

    def marked(x):
        with jax.named_scope("tp_ring"):
            return jax.lax.ppermute(x, "i", perm)

    for fn, want_unmarked in ((unmarked, 1), (marked, 0)):
        sm = shard_map(fn, mesh, in_specs=P("i"), out_specs=P("i"),
                       check_rep=False)
        c = census_jaxpr(jax.make_jaxpr(sm)(jnp.zeros(2)))
        assert c.counts.get("ppermute") == 1
        assert c.permutes_by_marker.get("<unmarked>", 0) == want_unmarked
        problems = check_census(c, program="drill")
        if want_unmarked:
            assert problems and "drill" in problems[0] \
                and "named_scope" in problems[0]
        else:
            assert problems == []


def test_host_callback_is_flagged():
    def fn(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2, jax.ShapeDtypeStruct((2,),
                                                              jnp.float32),
            x)

    c = census_jaxpr(jax.make_jaxpr(fn)(jnp.zeros(2)))
    assert c.callbacks
    problems = check_census(c, program="step")
    assert problems and "host callback" in problems[0]
    assert check_census(c, program="step", allow_callbacks=True) == []


def test_predicted_count_mismatch_is_reported():
    c = CensusResult(counts={"ppermute": 4},
                     permutes_by_marker={"pp_rotate": 4})
    problems = check_census(c, {"ppermute_pp": 8}, program="step")
    assert problems and "predicts 8" in problems[0]


def test_surplus_permute_in_unpredicted_category_is_caught():
    """Total-strict: a permute under a marker the plan never billed (here
    a cp ring appearing in a plan priced without cp) must fail even though
    its own key is absent from the prediction."""
    c = CensusResult(counts={"ppermute": 10},
                     permutes_by_marker={"pp_rotate": 8, "cp_ring": 2})
    problems = check_census(c, {"ppermute_pp": 8}, program="step")
    assert problems and "bills 8 collective-permutes in total" in \
        problems[0]


# ---------------------------------------------------------------------------
# the real programs
# ---------------------------------------------------------------------------


# NOTE the three real-program tests below trace the full compiled 1F1B /
# serving programs (~seconds each) and ride the slow tier: the tier-1
# budget is nearly saturated, and the SAME exact-count cross-check runs
# in tier-1 anyway inside tests/analysis/test_check_cli.py::
# test_check_all_is_green_at_head (cli.check.run_census fails on any
# census/prediction mismatch, unmarked permute, callback, or missing
# donation).
@pytest.mark.slow
def test_compiled_step_census_matches_hand_math():
    """tp2 x dp2 x pp2, chunks m=2 on the 8-device virtual mesh:
    T = m + 2(pp-1) = 4 ticks; per tick each of the lps=2 layer slots runs
    4 forward rings + (4 recompute + 4 backward) rings of (tp-1)=1
    ppermute hop each -> 4*2*12 = 96 tp-ring permutes; stage rotation =
    2 per tick -> 8 pp permutes. The census and the plan arithmetic
    (plan_collective_counts) must both land exactly there."""
    args = tiny_args(global_tp_deg=2, pp_deg=2, chunks=2, vocab_tp=2,
                     pipeline_type="pipedream_flush",
                     global_train_batch_size=4)
    hpc = get_hybrid_parallel_config(args, 8)
    predicted = plan_collective_counts(hpc, args.model, tp_overlap=True)
    assert predicted == {"ppermute_pp": 8, "ppermute_tp": 96}
    c = census_compiled_step(args.model, hpc, args.train, tp_overlap=True)
    assert c.permutes_by_marker.get("tp_ring") == 96
    assert c.permutes_by_marker.get("pp_rotate") == 8
    assert c.permutes_by_marker.get("<unmarked>", 0) == 0
    assert c.counts["ppermute"] == 104
    assert c.callbacks == []
    assert c.donated_args > 0  # the fused step donates (params, opt)
    assert check_census(c, predicted, program="compiled_step") == []


@pytest.mark.slow
def test_compiled_step_census_without_rings_has_only_rotations():
    args = tiny_args(global_tp_deg=2, pp_deg=2, chunks=2, vocab_tp=2,
                     pipeline_type="pipedream_flush",
                     global_train_batch_size=4)
    hpc = get_hybrid_parallel_config(args, 8)
    c = census_compiled_step(args.model, hpc, args.train, tp_overlap=False)
    assert c.permutes_by_marker == {"pp_rotate": 8}
    predicted = plan_collective_counts(hpc, args.model, tp_overlap=False)
    assert check_census(c, predicted, program="compiled_step") == []


def test_plan_collective_counts_rejects_unmodeled_shapes():
    args = tiny_args(global_tp_deg=1, global_cp_deg=2, pp_deg=1, chunks=1,
                     global_train_batch_size=8)
    hpc = get_hybrid_parallel_config(args, 8)
    with pytest.raises(ValueError):
        plan_collective_counts(hpc, args.model)
    # the hier lane-path relaxation (cp/sp predictable with tp_overlap
    # off) is a pp = 1 property: the pp engines keep their ring/a2a
    # kernels and reject hier for cp/sp layers, so a pp > 1 cp plan has
    # no hier program to predict — it must still raise, not return
    # counts no engine can census-match
    assert plan_collective_counts(hpc, args.model, tp_overlap=False,
                                  hier_dp=True)["reduce_scatter"] == 1
    args_pp = tiny_args(global_tp_deg=1, global_cp_deg=2, pp_deg=2,
                        chunks=2, global_train_batch_size=8)
    hpc_pp = get_hybrid_parallel_config(args_pp, 8)
    with pytest.raises(ValueError):
        plan_collective_counts(hpc_pp, args_pp.model, tp_overlap=False,
                               hier_dp=True)
    from hetu_galvatron_tpu.observability.telemetry import (
        plan_collective_bytes,
    )

    with pytest.raises(ValueError):
        plan_collective_bytes(hpc_pp, args_pp.model, tp_overlap=False,
                              hier_dp=True)


@pytest.mark.slow
def test_serving_programs_have_no_callbacks_or_unmarked_permutes():
    args = tiny_args()
    serving = ServingArgs(max_batch_size=2, kv_block_size=8,
                          max_seq_len=32, num_kv_blocks=10)
    results = census_serving_programs(args.model, serving=serving)
    assert set(results) == {"prefill_8", "decode"}
    for name, c in results.items():
        assert check_census(c, program=name) == [], name
