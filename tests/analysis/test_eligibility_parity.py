"""Eligibility parity: the cost model's gates vs the runtime's predicates.

The drift class this pins: the search engine pricing the compiled
schedule's dispatch waiver (or the tp-overlap discount) into a plan the
runtime then rejects at startup — or refusing a discount the runtime would
happily run. Both sides now call ``analysis/eligibility.py``; the sweep
here guards the ADAPTERS (SearchStrategy degrees vs LayerStrategy plans vs
ModelArgs widths) against re-diverging.
"""

import itertools
from types import SimpleNamespace

import pytest

from hetu_galvatron_tpu.analysis import eligibility
from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.core.cost_model.cost import (
    CostContext,
    tp_overlap_expressible,
)
from hetu_galvatron_tpu.core.search_engine.strategies import SearchStrategy
from hetu_galvatron_tpu.runtime.compiled_pipeline import (
    CompiledPipelineEngine,
)
from hetu_galvatron_tpu.utils.strategy import LayerStrategy

pytestmark = [pytest.mark.staticcheck, pytest.mark.search_engine]


def model(**kw) -> ModelArgs:
    base = dict(hidden_size=64, num_hidden_layers=4, num_attention_heads=4,
                vocab_size=256, seq_length=16, max_position_embeddings=32,
                hidden_act="swiglu", tie_word_embeddings=False,
                make_vocab_size_divisible_by=1, ffn_hidden_size=128)
    base.update(kw)
    return ModelArgs(**base)


def hpc_of(layers, pp_division, pipeline_type="pipedream_flush", vpp=1):
    return SimpleNamespace(
        layers=layers, pp_deg=layers[0].pp_deg, pp_division=pp_division,
        pipeline_type=pipeline_type, vpp_deg=vpp)


# ---------------------------------------------------------------------------
# compiled-schedule expressibility: search gate vs runtime reason
# ---------------------------------------------------------------------------


def test_compiled_gate_parity_sweep():
    """Sweep the structural plan space the SEARCH can see (pipeline type,
    stage partition, strategy uniformity): the cost model's dispatch
    waiver must fire IFF the runtime's unsupported_reason is None."""
    base = LayerStrategy(pp_deg=2, tp_size=2, dp_size=2)
    other = LayerStrategy(pp_deg=2, tp_size=1, dp_size=4)
    cfg = model()
    checked = 0
    for pipeline_type, partition, uniform in itertools.product(
            ("pipedream_flush", "gpipe"),
            ([2, 2], [3, 1], [1, 1, 1, 1][:2]),
            (True, False)):
        layers = [base] * 4 if uniform else [base, base, other, other]
        # runtime side: engine predicate on the resolved plan
        reason = CompiledPipelineEngine.unsupported_reason(
            cfg, hpc_of(layers, partition, pipeline_type))
        # search side: degree-level gate on the same candidate (the search
        # strategy objects compare by value, like LayerStrategy rows)
        s_base = SearchStrategy(pp=2, tp=2, dp=2)
        s_other = SearchStrategy(pp=2, tp=1, dp=4)
        slist = [s_base] * 4 if uniform else [s_base, s_base,
                                              s_other, s_other]
        waiver = eligibility.search_compiled_expressible(
            "compiled", pipeline_type, partition, slist)
        assert waiver == (reason is None), (
            f"drift: pipeline_type={pipeline_type} partition={partition} "
            f"uniform={uniform}: search waiver {waiver} vs runtime "
            f"reason {reason!r}")
        checked += 1
    assert checked == 12


def test_compiled_gate_model_level_reasons_are_runtime_only():
    """Model-level gates the search cannot see (t5 / MoE / vpp / packed
    docs) must still refuse on the runtime side — and the SHARED predicate
    is the one refusing."""
    layers = [LayerStrategy(pp_deg=2, tp_size=2, dp_size=2)] * 4
    hpc = hpc_of(layers, [2, 2])
    assert CompiledPipelineEngine.unsupported_reason(model(), hpc) is None
    assert "pair carry" in CompiledPipelineEngine.unsupported_reason(
        model(model_type="t5", num_encoder_layers=2), hpc)
    assert "MoE" in CompiledPipelineEngine.unsupported_reason(
        model(num_experts=4, model_type="moe"), hpc)
    hpc_v = hpc_of(layers, [1, 1, 1, 1], vpp=2)
    assert "vpp" in CompiledPipelineEngine.unsupported_reason(
        model(), hpc_v)
    packed = SimpleNamespace(reset_position_ids=True,
                             reset_attention_mask=False)
    assert "packed-document" in CompiledPipelineEngine.unsupported_reason(
        model(), hpc, data=packed)


def test_host_schedule_never_gets_the_waiver():
    s = [SearchStrategy(pp=2, tp=2, dp=2)] * 4
    assert not eligibility.search_compiled_expressible(
        "host", "pipedream_flush", [2, 2], s)


# ---------------------------------------------------------------------------
# tp-overlap eligibility: cost gate vs runtime per-layer dispatch
# ---------------------------------------------------------------------------


def test_tp_overlap_gate_parity_sweep():
    """On a width-divisible model the degree-level cost gate and the
    runtime's per-layer reason must agree exactly; on an indivisible
    model the runtime may refuse MORE (widths are invisible to the
    search) but never less."""
    cfg = model()  # every width divides tp in {2, 4}
    ctx = CostContext(tp_overlap=True)
    for tp, cp, sp in itertools.product((1, 2, 4), (1, 2), (False, True)):
        if sp and tp == 1:
            continue  # Ulysses encodes its degree in tp; tp1+sp is dp-only
        if sp and cp > 1:
            continue  # exclusive per LayerStrategy.validate
        # search view: Ulysses layers arrive as sp=deg, tp=1
        s = SearchStrategy(pp=1, tp=1 if sp else tp, sp=tp if sp else 1,
                           cp=cp, dp=8 // (tp * cp))
        cost_gate = tp_overlap_expressible(s, ctx)
        # runtime view: plan rows
        strat = LayerStrategy(pp_deg=1, tp_size=tp, cp_size=cp,
                              dp_size=8 // (tp * cp), sp=sp)
        reasons = eligibility.plan_overlap_reasons(
            cfg, SimpleNamespace(layers=[strat]))
        runtime_ok = reasons[0][1] is None
        assert cost_gate == runtime_ok, (
            f"drift at tp={tp} cp={cp} sp={sp}: cost gate {cost_gate}, "
            f"runtime reason {reasons[0][1]!r}")


def test_tp_overlap_runtime_refuses_indivisible_widths():
    """Degrees say yes, widths say no: the runtime must refuse with the
    divisibility reason (the half of the predicate the search cannot
    evaluate) — one-directional by design."""
    cfg = model(seq_length=18)  # 18 % 4 != 0
    s = SearchStrategy(pp=1, tp=4, dp=2)
    assert tp_overlap_expressible(s, CostContext(tp_overlap=True))
    reason = eligibility.overlap_unsupported_reason(
        cfg, ulysses=False, has_cp=False, tp=4)
    assert reason is not None and "sequence length" in reason


def test_disabled_overlap_gates_everything():
    s = SearchStrategy(pp=1, tp=4, dp=2)
    assert not tp_overlap_expressible(s, CostContext(tp_overlap=False))


def test_reason_strings_are_shared_verbatim():
    """The launcher logs ops.overlap reasons and the doctor prints
    eligibility reasons — they must be the SAME objects, not copies that
    can drift."""
    import hetu_galvatron_tpu.ops.overlap as ov

    assert ov.layer_overlap_reason is eligibility.layer_overlap_reason
    assert ov.plan_overlap_reasons is eligibility.plan_overlap_reasons
    assert ov.T5_REASON is eligibility.T5_REASON
    assert ov.MOE_REASON is eligibility.MOE_REASON
