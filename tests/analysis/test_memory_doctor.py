"""Memory doctor (Pass 4): malformed corpus, the hand-math HBM pin on
the acceptance plan, cost-model cross-check sweeps, and search==check
budget parity.

The hand-math test recomputes every component of the tp2 x dp2 x pp2
acceptance plan from raw integers — params, optimizer states, 1F1B
activation accumulation, the compiled engine's stage buffer, vocab
replication, and the serving KV pool — so the doctor's arithmetic is
pinned to something a reviewer can check with a pencil, not to itself.
"""

import io
import json
import os

import pytest

from hetu_galvatron_tpu.analysis.memory_doctor import (
    cross_check_cost_model,
    diagnose_memory,
    hbm_budget_reason,
    search_result_hbm_reason,
)
from hetu_galvatron_tpu.core.args_schema import ModelArgs, ServingArgs
from hetu_galvatron_tpu.utils.strategy import config2strategy

pytestmark = [pytest.mark.staticcheck, pytest.mark.utils]

MB = 1024 * 1024
PLAN_DIR = os.path.join(
    os.path.dirname(__file__), "..", "..", "hetu_galvatron_tpu",
    "profiles", "example_plans")
ACCEPTANCE = os.path.join(PLAN_DIR,
                          "galvatron_config_acceptance_tp2dp2pp2.json")


def tiny_model(**kw) -> ModelArgs:
    base = dict(hidden_size=64, num_hidden_layers=4, num_attention_heads=4,
                vocab_size=256, seq_length=16, max_position_embeddings=32,
                hidden_act="swiglu", normalization="rmsnorm",
                position_embedding_type="rope", tie_word_embeddings=False,
                add_bias_linear=False, add_qkv_bias=False,
                make_vocab_size_divisible_by=1, ffn_hidden_size=128)
    base.update(kw)
    return ModelArgs(**base)


def good_plan(**kw):
    plan = {
        "pp_deg": 2, "tp_sizes_enc": "2,2,2,2",
        "tp_consecutive_flags": "1,1,1,1", "dp_types_enc": "0,0,0,0",
        "use_sp": "0,0,0,0", "cp_sizes_enc": "1,1,1,1",
        "checkpoint": "0,0,0,0", "global_bsz": 4, "chunks": 2,
        "pp_division": "2,2", "pipeline_type": "pipedream_flush",
        "default_dp_type": "ddp", "vtp": 2, "vsp": 0, "embed_sdp": 0,
    }
    plan.update(kw)
    return plan


# ---------------------------------------------------------------------------
# malformed corpus: diagnostics, never tracebacks
# ---------------------------------------------------------------------------

MALFORMED_CORPUS = [
    ("zero_layer_stage", good_plan(pp_division="0,4"), "zero-layer"),
    ("missing_vocab_config", good_plan(vtp=0), "vtp"),
    ("missing_pp_deg",
     {k: v for k, v in good_plan().items() if k != "pp_deg"}, "pp_deg"),
    ("wrong_length_vector", good_plan(cp_sizes_enc="1,1"), "cp_sizes_enc"),
    ("non_object_plan", ["not", "a", "plan"], "object"),
    ("division_sum_mismatch", good_plan(pp_division="3,2"), "pp_division"),
    ("chunks_cannot_fill_pipeline", good_plan(chunks=1, global_bsz=4),
     "chunks"),
]


@pytest.mark.parametrize("name,plan,needle",
                         [(n, p, s) for n, p, s in MALFORMED_CORPUS])
def test_malformed_plan_yields_diagnostic_not_traceback(name, plan, needle):
    report = diagnose_memory(plan, tiny_model(), 8)
    assert not report.ok, name
    assert report.errors, name
    joined = " | ".join(report.errors)
    assert needle in joined, f"{name}: {joined!r} lacks {needle!r}"
    report.render(io.StringIO())  # renders even when broken


def test_negative_hbm_budget_is_a_diagnostic():
    for bad in (-4.0, 0.0):
        report = diagnose_memory(good_plan(), tiny_model(), 8, hbm_gb=bad)
        assert not report.ok
        assert any("hbm-gb" in e for e in report.errors)
        report.render(io.StringIO())


def test_unreadable_plan_file_is_diagnosed(tmp_path):
    report = diagnose_memory(str(tmp_path / "nope.json"), tiny_model(), 8)
    assert not report.ok and report.errors


# ---------------------------------------------------------------------------
# the hand-math HBM pin (acceptance plan, raw-integer arithmetic)
# ---------------------------------------------------------------------------


def test_hand_math_pin_acceptance_plan():
    """tp2 x dp2 x pp2, chunks 2, gbsz 4, bf16 activations, fp32-unit
    states; model h=64 L=4 heads=4 kv=4 ffn=128 swiglu vocab=256 seq=16
    rope untied. Every expected number below is hand-derived."""
    model = tiny_model()
    serving = ServingArgs(max_batch_size=2, kv_block_size=8,
                          max_seq_len=32, num_kv_blocks=10)
    report = diagnose_memory(ACCEPTANCE, model, 8, serving=serving)
    assert report.ok, report.errors
    s0, s1 = report.stages

    # params/opt row: per-layer fp32 params = qkv+out (4*h*h=16384) +
    # gated mlp (3*h*f=24576) + two norms (2*h=128) = 41088 elems.
    # states = 4x (param+grad+2 moments) / tp2; 2 layers per stage.
    param_elems = 4 * 64 * 64 + 3 * 64 * 128 + 2 * 64
    states_b = 2 * (4 * param_elems * 4 // 2)
    assert s0.components["model_states_mb"] * MB == pytest.approx(states_b)
    assert s1.components["model_states_mb"] * MB == pytest.approx(states_b)

    # activation row: per-sample saved set (bf16, flash-style) =
    # attn 7168 + mlp 9216 = 16384 elems; / tp_sp 2; lbsz = 4/2/2 = 1;
    # 1F1B in-flight microbatches: stage0 holds pp-0 = 2, stage1 holds 1.
    act_elems = (16 * 64 * 4 + 16 * (64 + 2 * 64)) \
        + (16 * 64 * 2 + 16 * 128 * 2 + 16 * 128 + 16 * 64)
    assert act_elems == 16384
    per_layer_b = act_elems * 2 // 2
    assert s0.components["activation_mb"] * MB == \
        pytest.approx(2 * 2 * per_layer_b)
    assert s1.components["activation_mb"] * MB == \
        pytest.approx(2 * 1 * per_layer_b)

    # compiled stage buffer: depth (2pp-1) + 2 carries = 5 slices of
    # [lbsz=1, seq/tp=8, h=64] bf16.
    slice_b = 1 * 8 * 64 * 2
    assert s0.components["stage_buffer_mb"] * MB == \
        pytest.approx(5 * slice_b)

    # vocab states: embed table 256*64 fp32 (rope: no position table),
    # head untied 256*64, prenorm 64; 4x states over vtp=2 — REPLICATED
    # on both stages by the compiled engine.
    v_first_b = 4 * (256 * 64 * 4) // 2
    v_last_b = 4 * ((256 * 64 + 64) * 4) // 2
    for st in (s0, s1):
        assert st.components["vocab_states_mb"] * MB == \
            pytest.approx(v_first_b + v_last_b)

    # KV pool row: 10 blocks x 2(k+v) x 4 layers x 8 tokens x 4 kv-heads
    # x 16 head_dim x bf16, kv-head axis sharded over tp2.
    kv_b = 10 * 2 * 4 * 8 * 4 * 16 * 2 // 2
    assert s0.components["kv_pool_mb"] * MB == pytest.approx(kv_b)

    # and the peak is the stage-0 total, exactly
    total0 = (states_b + 2 * 2 * per_layer_b + 5 * slice_b
              + v_first_b + v_last_b
              + (16 * 64 // 2) * 2 * 2  # first-stage vocab act, 2 in flight
              + kv_b)
    assert report.peak_mb * MB == pytest.approx(total0)


def test_vocab_first_stage_activation_hand_math():
    """The one fiddly row the peak test folds in: first-stage vocab
    activation = embed output [seq, h]/vtp in bf16, times the pipedream
    in-flight count (pp=2) at lbsz 1."""
    model = tiny_model()
    report = diagnose_memory(ACCEPTANCE, model, 8)
    s0, s1 = report.stages
    first_b = (16 * 64 // 2) * 2 * 2 * 1
    last_b = ((16 * 64 // 2) + (16 * 256 // 2)) * 2 * 1 * 1
    assert s0.components["vocab_activation_mb"] * MB == pytest.approx(
        first_b)
    assert s1.components["vocab_activation_mb"] * MB == pytest.approx(
        last_b)


# ---------------------------------------------------------------------------
# cost-model cross-check: ratio 1.0 across a plan sweep
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mutation", [
    {},                                     # the acceptance shape
    {"dp_types_enc": "1,1,1,1"},            # zero3 layers
    {"checkpoint": "1,1,1,1"},              # remat layers
    {"default_dp_type": "zero2"},           # zero2 default
    {"use_sp": "1,1,1,1", "vsp": 1},        # ulysses layers
    {"pp_deg": 1, "pp_division": "4", "chunks": 1, "global_bsz": 8,
     "tp_sizes_enc": "2,2,2,2"},            # pp=1 SPMD
    {"tp_sizes_enc": "1,1,1,1", "cp_sizes_enc": "2,2,2,2", "vtp": 1},
])
def test_cross_check_ratio_is_one(mutation):
    plan = good_plan(**mutation)
    layers, vocab, extras = config2strategy(plan, world_size=8)
    ratios, problems = cross_check_cost_model(
        layers, vocab, tiny_model(),
        global_bsz=extras["global_bsz"], chunks=max(extras["chunks"], 1),
        pp_division=extras["pp_division"],
        pipeline_type=extras["pipeline_type"], world_size=8)
    assert problems == [], problems
    for name, r in ratios.items():
        assert r == pytest.approx(1.0, abs=1e-9), (name, r)


def test_cross_check_catches_drifted_component(monkeypatch):
    """Simulated arithmetic drift: scale the doctor's activation model
    and the cross-check must name the activation component."""
    import hetu_galvatron_tpu.analysis.memory_doctor as md

    real = md.activation_per_sample_mb
    calls = {"n": 0}

    def skewed(model, elem_bytes=2):
        # the CostContext side is built FIRST (call 1, unskewed); the
        # doctor's accounting side (call 2+) drifts by 10%
        calls["n"] += 1
        return real(model, elem_bytes) * (1.1 if calls["n"] >= 2 else 1.0)

    monkeypatch.setattr(md, "activation_per_sample_mb", skewed)
    plan = good_plan()
    layers, vocab, extras = config2strategy(plan, world_size=8)
    _, problems = md.cross_check_cost_model(
        layers, vocab, tiny_model(), global_bsz=4, chunks=2,
        pp_division=[2, 2], pipeline_type="pipedream_flush", world_size=8)
    assert problems and "activation" in problems[0]


# ---------------------------------------------------------------------------
# budget gate + search == check parity
# ---------------------------------------------------------------------------


def test_budget_gate_rejects_oversized_plan():
    model = tiny_model()
    peak_gb = diagnose_memory(ACCEPTANCE, model, 8).peak_mb / 1024.0
    tight = diagnose_memory(ACCEPTANCE, model, 8, hbm_gb=peak_gb * 0.5)
    assert not tight.ok
    assert any("OOM" in e or "exceeds" in e for e in tight.errors)
    roomy = diagnose_memory(ACCEPTANCE, model, 8, hbm_gb=peak_gb * 2.0)
    assert roomy.ok, roomy.errors


def test_search_gate_matches_check_gate():
    """search == check parity: the SearchStrategy-shaped predicate the
    engine prunes with and the plan-JSON doctor agree at both sides of
    the budget boundary."""
    from hetu_galvatron_tpu.core.search_engine.strategies import (
        SearchStrategy,
    )
    from hetu_galvatron_tpu.utils.strategy import DPType

    model = tiny_model()
    peak_gb = diagnose_memory(ACCEPTANCE, model, 8).peak_mb / 1024.0
    strategies = [SearchStrategy(pp=2, tp=2, dp=2, dp_type=DPType.DDP)] * 4
    for budget, fits in ((peak_gb * 0.5, False), (peak_gb * 2.0, True)):
        reason = search_result_hbm_reason(
            strategies, [2, 2], model, global_bsz=4, chunks=2,
            pipeline_type="pipedream_flush", schedule_impl="compiled",
            hbm_gb=budget, vocab_tp_sp=2)
        check = diagnose_memory(ACCEPTANCE, model, 8, hbm_gb=budget)
        assert (reason is None) == fits
        assert check.ok == fits
        if not fits:
            assert reason == check.errors[-1]


def test_search_engine_hbm_gate_prunes(capsys):
    """The engine-level hook: a feasible TaskResult is replaced by an
    infeasible one (and logged) when the budget is busted, untouched
    when it fits or the gate is off."""
    from hetu_galvatron_tpu.core.args_schema import SearchArgs
    from hetu_galvatron_tpu.core.search_engine.engine import (
        SearchEngine,
        TaskResult,
    )
    from hetu_galvatron_tpu.core.search_engine.strategies import (
        SearchStrategy,
    )

    model = tiny_model()
    peak_gb = diagnose_memory(ACCEPTANCE, model, 8).peak_mb / 1024.0
    r = TaskResult(throughput=1.0, time_cost=1.0,
                   strategy_list=[SearchStrategy(pp=2, tp=2, dp=2)] * 4,
                   pp_size=2, pp_stage_list=[2, 2], vocab_tp_sp=2,
                   bsz=4, chunks=2)

    def engine_with(budget):
        args = SearchArgs(num_nodes=1, num_devices_per_node=8,
                          hbm_budget_gb=budget,
                          pipeline_type="pipedream_flush",
                          pipeline_schedule_impl="compiled")
        return SearchEngine(args, model_cfg=model)

    pruned = engine_with(peak_gb * 0.5)._hbm_gate(r)
    assert pruned.strategy_list is None
    assert "hbm gate: pruned" in capsys.readouterr().out
    kept = engine_with(peak_gb * 2.0)._hbm_gate(r)
    assert kept is r
    off = engine_with(0.0)._hbm_gate(r)
    assert off is r


# ---------------------------------------------------------------------------
# serving-mode sizing parity with the live engine
# ---------------------------------------------------------------------------


def test_kv_pool_sizing_matches_live_engine():
    """resolve_num_blocks IS the engine's pool sizing: a default-pool
    engine allocates exactly what the doctor predicts."""
    import jax

    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.serving.engine import ServingEngine
    from hetu_galvatron_tpu.serving.kv_cache import resolve_num_blocks

    model = tiny_model()
    serving = ServingArgs(max_batch_size=2, kv_block_size=8,
                          max_seq_len=32, num_kv_blocks=0)
    params, _ = init_causal_lm(jax.random.key(0), model)
    eng = ServingEngine(params, model, serving)
    try:
        assert eng.kv.num_blocks == resolve_num_blocks(serving, model)
    finally:
        eng.close()


def test_plan_file_report_roundtrips_through_json(tmp_path):
    """A plan dict and the same plan on disk produce identical numbers."""
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(good_plan()))
    model = tiny_model()
    a = diagnose_memory(good_plan(), model, 8)
    b = diagnose_memory(str(p), model, 8)
    assert a.ok and b.ok
    assert [s.components for s in a.stages] == \
        [s.components for s in b.stages]
