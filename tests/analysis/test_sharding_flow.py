"""Sharding-flow analysis (Pass 5): byte census mechanics, the exact
acceptance-plan cross-check, and the injected drills the acceptance
criteria name — an undonated-buffer step and a stray weight all-gather
must each fail the pass with a diagnostic naming the program and eqn.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.analysis.sharding_flow import (
    check_donation,
    check_flow,
    donation_report,
    flow_compiled_step,
    flow_jaxpr,
    flow_serving_programs,
    hlo_collectives,
    reshard_findings,
)
from hetu_galvatron_tpu.core.args_schema import CoreArgs, ServingArgs
from hetu_galvatron_tpu.observability.telemetry import plan_collective_bytes
from hetu_galvatron_tpu.runtime.hybrid_config import (
    get_hybrid_parallel_config,
)

pytestmark = [pytest.mark.staticcheck, pytest.mark.distributed]

MB = 1024 * 1024


def tiny_args(**parallel):
    return CoreArgs.model_validate({
        "model": {
            "hidden_size": 64, "num_hidden_layers": 4,
            "num_attention_heads": 4, "vocab_size": 256, "seq_length": 16,
            "max_position_embeddings": 32, "hidden_act": "swiglu",
            "normalization": "rmsnorm", "position_embedding_type": "rope",
            "tie_word_embeddings": False, "add_bias_linear": False,
            "add_qkv_bias": False, "make_vocab_size_divisible_by": 1,
            "ffn_hidden_size": 128,
        },
        "parallel": parallel,
    })


ACCEPTANCE = "hetu_galvatron_tpu/profiles/example_plans/" \
    "galvatron_config_acceptance_tp2dp2pp2.json"


def acceptance_setup():
    import os

    root = os.path.join(os.path.dirname(__file__), "..", "..")
    args = tiny_args(config_mode="json",
                     galvatron_config_path=os.path.join(root, ACCEPTANCE))
    return args, get_hybrid_parallel_config(args, 8)


# ---------------------------------------------------------------------------
# byte-walk mechanics on synthetic jaxprs
# ---------------------------------------------------------------------------


def test_scan_multiplies_bytes():
    def body(c, _):
        return c + jax.lax.psum(c, "i"), None

    def fn(x):
        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    mesh = Mesh(np.array(jax.devices()[:2]), ("i",))
    sm = shard_map(fn, mesh, in_specs=P("i"), out_specs=P("i"),
                   check_rep=False)
    # local shard: 128 f32 elems = 512 B per psum, 5 scan trips
    flow = flow_jaxpr(jax.make_jaxpr(sm)(jnp.zeros(256, jnp.float32)))
    assert flow.mb_by_cat["all_reduce"] * MB == pytest.approx(5 * 512)


def test_permute_bytes_split_by_marker():
    mesh = Mesh(np.array(jax.devices()[:2]), ("i",))
    perm = [(0, 1), (1, 0)]

    def fn(x):
        with jax.named_scope("tp_ring"):
            y = jax.lax.ppermute(x, "i", perm)
        return y + jax.lax.ppermute(y, "i", perm)  # unmarked

    sm = shard_map(fn, mesh, in_specs=P("i"), out_specs=P("i"),
                   check_rep=False)
    flow = flow_jaxpr(jax.make_jaxpr(sm)(jnp.zeros(512, jnp.float32)))
    each = 256 * 4
    assert flow.permute_mb_by_marker["tp_ring"] * MB == pytest.approx(each)
    assert flow.permute_mb_by_marker["<unmarked>"] * MB == \
        pytest.approx(each)
    assert flow.mb_by_cat["ppermute"] * MB == pytest.approx(2 * each)


def test_byte_mismatch_is_reported():
    from hetu_galvatron_tpu.analysis.sharding_flow import FlowResult

    flow = FlowResult(mb_by_cat={"ppermute": 1.0},
                      permute_mb_by_marker={"pp_rotate": 1.0})
    problems = check_flow(flow, {"ppermute_pp": 2.0}, program="step")
    assert problems and "2.000000" in problems[0]
    assert check_flow(flow, {"ppermute_pp": 1.0}, program="step") == []


def test_surplus_bytes_under_unbilled_marker_are_caught():
    from hetu_galvatron_tpu.analysis.sharding_flow import FlowResult

    flow = FlowResult(
        mb_by_cat={"ppermute": 3.0},
        permute_mb_by_marker={"pp_rotate": 1.0, "cp_ring": 2.0})
    problems = check_flow(flow, {"ppermute_pp": 1.0}, program="step")
    assert problems and "in total" in problems[-1]


# ---------------------------------------------------------------------------
# the acceptance drill: exact bytes, zero reshards, donation clean
# ---------------------------------------------------------------------------


def test_acceptance_plan_bytes_match_plan_arithmetic_exactly():
    """tp2 x dp2 x pp2: the traced compiled step's per-marker megabytes
    equal telemetry.plan_collective_bytes with NO tolerance, there are
    zero reshard findings, and the donation audit passes. The numbers
    themselves are pinned by hand: T=4 ticks, 12 rings x (tp-1)=1 hop x
    2 layer slots on [1,8,64] f32 chunks; 2 rotations x 4 ticks on the
    same slice."""
    args, hpc = acceptance_setup()
    pf = flow_compiled_step(args.model, hpc, args.train, tp_overlap=True)
    predicted = plan_collective_bytes(hpc, args.model, tp_overlap=True)

    hop_b = 1 * 8 * 64 * 4
    assert predicted["ppermute_tp"] * MB == pytest.approx(
        4 * 2 * 12 * 1 * hop_b)
    assert predicted["ppermute_pp"] * MB == pytest.approx(2 * 4 * hop_b)

    assert check_flow(pf.flow, predicted, program="compiled_step") == []
    assert pf.flow.permute_mb_by_marker["tp_ring"] == \
        predicted["ppermute_tp"]
    assert pf.flow.permute_mb_by_marker["pp_rotate"] == \
        predicted["ppermute_pp"]
    assert pf.reshard_problems == []
    assert check_donation(pf.donation, program="compiled_step") == []
    assert pf.donation.donated_mb > pf.donation.undonated_mb


def test_remat_plan_bytes_match(tmp_path):
    """checkpointed layers add the 4-ring forward recompute: 16 rings
    per layer slot per tick, still exact."""
    import json
    import os

    root = os.path.join(os.path.dirname(__file__), "..", "..")
    with open(os.path.join(root, ACCEPTANCE)) as f:
        plan = json.load(f)
    plan["checkpoint"] = "1,1,1,1"
    p = str(tmp_path / "ckpt_plan_flow.json")
    with open(p, "w") as f:
        json.dump(plan, f)
    args = tiny_args(config_mode="json", galvatron_config_path=p)
    hpc = get_hybrid_parallel_config(args, 8)
    pf = flow_compiled_step(args.model, hpc, args.train, tp_overlap=True)
    predicted = plan_collective_bytes(hpc, args.model, tp_overlap=True)
    assert predicted["ppermute_tp"] * MB == pytest.approx(
        4 * 2 * 16 * 1 * (8 * 64 * 4))
    assert check_flow(pf.flow, predicted, program="compiled_step") == []


def test_undonated_buffer_drill():
    """The injected regression the acceptance criteria name: the same
    step built with donate=False must FAIL the donation audit with a
    diagnostic naming the program and the largest undonated buffer."""
    args, hpc = acceptance_setup()
    pf = flow_compiled_step(args.model, hpc, args.train, tp_overlap=True,
                            donate=False)
    problems = check_donation(pf.donation, program="compiled_step")
    assert problems, "undonated step must fail the audit"
    assert "compiled_step" in problems[0]
    assert "undonated" in problems[0]
    # the report names concrete buffers with shapes and sizes
    assert pf.donation.largest_undonated
    assert pf.donation.largest_undonated[0][1] > 0


# ---------------------------------------------------------------------------
# reshard drills
# ---------------------------------------------------------------------------


def test_stray_weight_all_gather_drill():
    """An explicit all-gather materializing a >= 1 MB weight inside the
    step path is flagged, naming program + eqn + shape; a tiny gather
    stays under the threshold."""
    mesh = Mesh(np.array(jax.devices()[:2]), ("i",))

    def gather_big(w):
        return jax.lax.all_gather(w, "i", tiled=True)

    big = shard_map(gather_big, mesh, in_specs=P("i", None),
                    out_specs=P(None, None), check_rep=False)
    j = jax.make_jaxpr(big)(jnp.zeros((1024, 512), jnp.float32))
    problems = reshard_findings(j, program="drill_step")
    assert problems, "weight-sized gather must be flagged"
    assert "drill_step" in problems[0] and "eqn" in problems[0]
    assert "1024,512" in problems[0].replace(" ", "") or \
        "1024" in problems[0]

    small = shard_map(gather_big, mesh, in_specs=P("i", None),
                      out_specs=P(None, None), check_rep=False)
    j2 = jax.make_jaxpr(small)(jnp.zeros((16, 16), jnp.float32))
    assert reshard_findings(j2, program="drill_step") == []


def test_double_reshard_drill():
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("a", "b"))

    def double(x):
        y = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("a", None)))
        return jax.lax.with_sharding_constraint(
            y, NamedSharding(mesh, P(None, "b")))

    with mesh:
        j = jax.make_jaxpr(double)(jnp.zeros((8, 8), jnp.float32))
    problems = reshard_findings(j, program="drill")
    assert problems and "twice" in problems[0]

    def single(x):
        y = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("a", None)))
        return y * 2.0

    with mesh:
        j2 = jax.make_jaxpr(single)(jnp.zeros((8, 8), jnp.float32))
    assert reshard_findings(j2, program="drill") == []


# ---------------------------------------------------------------------------
# serving programs: clean flows, pools donated
# ---------------------------------------------------------------------------


def test_serving_programs_flow_clean():
    args = tiny_args()
    serving = ServingArgs(max_batch_size=2, kv_block_size=8,
                          max_seq_len=32, num_kv_blocks=10,
                          prefix_cache=True, spec_decode=True, spec_k=2)
    flows = flow_serving_programs(args.model, serving=serving)
    assert set(flows) >= {"decode", "prefill_8"}
    for name, pf in flows.items():
        assert pf.reshard_problems == [], name
        # pools are donated in every program family
        assert pf.donation.donated_mb > 0, name


# ---------------------------------------------------------------------------
# partition-time HLO walk
# ---------------------------------------------------------------------------


def test_hlo_walk_flags_partition_time_weight_gather():
    """GSPMD forced to re-materialize a sharded weight: the compiled-HLO
    walk reports the all-gather with its size and flags it above the
    weight threshold."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("d0",))
    w = jax.device_put(jnp.zeros((1024, 512), jnp.float32),
                       NamedSharding(mesh, P("d0", None)))
    f = jax.jit(lambda w: w + 1.0, out_shardings=NamedSharding(mesh, P()))
    txt = f.lower(w).compile().as_text()
    cats, findings = hlo_collectives(txt, weight_gather_mb=1.0)
    assert cats["all-gather"]["count"] >= 1
    assert cats["all-gather"]["mb"] >= 2.0
    assert findings and "all-gather" in findings[0]
    assert "1024,512" in findings[0]


def test_hlo_walk_measures_async_start_by_gathered_result():
    """Async collective pairs: the -start op's tuple result lists
    (operand shard, gathered result) — the walk must measure the
    GATHERED size, or a full-weight re-gather at high tp slips under the
    threshold by its shard size; -done halves add no bytes."""
    txt = (
        "  %ag = (f32[1024,128]{1,0}, f32[1024,1024]{1,0}) "
        "all-gather-start(f32[1024,128]{1,0} %p), dimensions={1}\n"
        "  %agd = f32[1024,1024]{1,0} all-gather-done((f32[1024,128]{1,0},"
        " f32[1024,1024]{1,0}) %ag)\n")
    cats, findings = hlo_collectives(txt, weight_gather_mb=2.0)
    assert cats["all-gather"]["count"] == 1
    assert cats["all-gather"]["mb"] == pytest.approx(4.0)
    assert findings and "1024,1024" in findings[0]


def test_hlo_walk_full_compiled_step():
    """The heavy leg (slow tier): compile the acceptance plan's fused
    step and walk its partitioned HLO — the GSPMD-inserted collectives
    are reported, and no full decoder weight is re-gathered (weights
    stay sharded end to end)."""
    import jax.numpy as jnp

    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.runtime.compiled_pipeline import (
        CompiledPipelineEngine,
    )

    args, hpc = acceptance_setup()
    eng = CompiledPipelineEngine(args.model, hpc, args.train,
                                 compute_dtype=jnp.float32,
                                 tp_overlap=True, donate=True)
    params, axes = init_causal_lm(jax.random.key(0), args.model)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, args.model.padded_vocab_size,
                       (hpc.global_bsz, args.model.seq_length + 1))
    batch = {"tokens": toks[:, :-1].astype(np.int32),
             "labels": toks[:, 1:].astype(np.int32)}
    txt = eng.step_lowered(sp, so, batch).compile().as_text()
    # full (unsharded) decoder weight threshold: the largest leaf is the
    # stacked gated fc1 [pp, h, 2f] f32 = 2*64*256*4 B per stage pair —
    # use half of it so ANY full-weight gather trips
    weight_mb = (2 * 64 * 256 * 4) / MB / 2
    cats, findings = hlo_collectives(txt, weight_gather_mb=weight_mb)
    assert findings == [], findings
    # the partitioned program does contain GSPMD collectives (dp grad
    # all-reduce at minimum) — the walk sees what the jaxpr cannot
    assert any(k in cats for k in ("all-reduce", "collective-permute"))
