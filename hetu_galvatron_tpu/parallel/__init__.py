from hetu_galvatron_tpu.parallel.spmd import (  # noqa: F401
    batch_sharding,
    layer_shardings,
    make_boundary_fn,
    make_spmd_train_step,
    opt_state_specs,
    param_specs,
    shard_params,
)
