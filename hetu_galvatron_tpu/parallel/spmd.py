"""SPMD model assembly: strategies + mesh -> sharded params, train step.

Capability parity with the reference's hybrid-parallel model construction
(runtime/hybrid_parallel_model.py:107 ``construct_hybrid_parallel_model_api``
+ runtime/parallel.py:307-387 per-layer FSDP wrapping): the per-layer strategy
vectors become per-param `PartitionSpec`s (TP via logical weight axes, ZeRO-3
via dp-sharded params, ZeRO-2 via dp-sharded optimizer moments) and
layer-boundary `with_sharding_constraint`s (the reference's relocation,
parallel.py:272-304). One `jax.jit` with in/out shardings replaces the whole
wrapper stack; XLA emits the all-gathers/reduce-scatters the reference issues
through NCCL.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.models.builder import causal_lm_loss
from hetu_galvatron_tpu.runtime.hybrid_config import HybridParallelConfig
from hetu_galvatron_tpu.runtime.mesh import (
    LayerSharding,
    lower_strategy,
    lower_vocab_strategy,
    spec_tree,
)
from hetu_galvatron_tpu.runtime.trainer import make_train_step

Params = Dict[str, Any]


def layer_shardings(
    hpc: HybridParallelConfig, mesh: Mesh
) -> Tuple[List[LayerSharding], LayerSharding]:
    """Lower every decoder layer + the vocab strategy onto the mesh
    (reference gen_comm_groups + hp_config_whole_model in one step)."""
    per_layer = [lower_strategy(s, mesh) for s in hpc.layers]
    vocab = lower_vocab_strategy(hpc.vocab, mesh, hpc.default_dp_type)
    return per_layer, vocab


# shared logical-axes -> PartitionSpec lowering (runtime/mesh.py)
_spec_tree = spec_tree


def param_specs(
    axes_tree: Params,
    per_layer: List[LayerSharding],
    vocab: LayerSharding,
    *,
    opt: bool = False,
    enc_per_layer: Optional[List[LayerSharding]] = None,
) -> Params:
    """PartitionSpec pytree mirroring the params tree: decoder layers use
    their own sharding, embed/prenorm/head use the vocab sharding (reference
    whole-model rows, hybrid_parallel_config.py:276-293). Encoder-decoder
    models (t5) shard each encoder layer with its own strategy from the
    combined-stack plan (``enc_per_layer``); legacy callers that pass only
    decoder shardings fall back to cloning the first decoder strategy."""
    out = {
        "embed": _spec_tree(axes_tree["embed"], vocab, opt),
        "layers": tuple(
            _spec_tree(a, sh, opt)
            for a, sh in zip(axes_tree["layers"], per_layer)),
        "prenorm": _spec_tree(axes_tree["prenorm"], vocab, opt),
        "head": _spec_tree(axes_tree["head"], vocab, opt),
    }
    if "enc_layers" in axes_tree:
        enc = (enc_per_layer if enc_per_layer is not None
               else [per_layer[0]] * len(axes_tree["enc_layers"]))
        out["enc_layers"] = tuple(
            _spec_tree(a, sh, opt)
            for a, sh in zip(axes_tree["enc_layers"], enc))
        out["enc_norm"] = _spec_tree(axes_tree["enc_norm"], vocab, opt)
    return out


def opt_state_specs(
    tx: optax.GradientTransformation,
    params: Params,
    opt_param_specs: Params,
) -> Any:
    """Specs for the optimizer state: leaves whose tree path ends with a
    param's path (adam mu/nu mirror the params tree) get that param's
    opt-spec; everything else (step counts) is replicated."""
    state_shape = jax.eval_shape(tx.init, params)
    flat_specs = {
        tuple(str(k) for k in path): spec
        for path, spec in jax.tree_util.tree_flatten_with_path(
            opt_param_specs,
            is_leaf=lambda x: isinstance(x, P))[0]
    }
    param_paths = list(flat_specs)

    def for_leaf(path, leaf):
        key = tuple(str(k) for k in path)
        for ppath in param_paths:
            if len(key) >= len(ppath) and key[-len(ppath):] == ppath:
                # moments mirror the param exactly; anything else that
                # happens to share the path suffix (unlikely) differs in rank
                if len(flat_specs[ppath]) == leaf.ndim:
                    return flat_specs[ppath]
        return P()

    return jax.tree_util.tree_map_with_path(for_leaf, state_shape)


def attention_overrides(
    per_layer: List[LayerSharding],
    mesh: Mesh,
    *,
    use_flash: Optional[bool] = None,
    with_cross: bool = False,
    cp_zigzag: bool = False,
    flash_interpret: bool = False,
) -> Dict[int, Dict[str, Any]]:
    """Per-layer attention-impl dispatch (reference attention.py:664-720):
    cp > 1 layers swap in the ring-attention kernel over their cp axes;
    other layers get the Pallas flash kernel on TPU (``use_flash`` defaults
    to platform == tpu); everything else keeps the XLA core (GSPMD inserts
    the collectives).

    Ulysses layers get the explicit head-scatter all-to-all attention
    (ops/ulysses.py, reference _SeqAllToAll) instead of leaving GSPMD to
    infer collectives for a sequence-sharded softmax; on TPU the local core
    inside the a2a sandwich is the flash kernel.

    ``with_cross=True`` (t5 decoder layers) also sets ``cross_sdpa_fn``:
    ring and ulysses layers pin cross-attention to the XLA core (the ring
    kernel needs equal q/kv sequence lengths and the a2a sandwich assumes
    self-attention geometry; GSPMD inserts the collectives instead), while
    flash layers reuse the flash kernel, which handles causal=False and
    falls back internally on mismatched lengths.

    ``flash_interpret=True`` runs the Pallas kernels in interpret mode —
    CPU parity drills forcing ``use_flash=True`` on the virtual mesh (the
    compiled-vs-host kernel drills run the SAME kernel on both sides)."""
    from functools import partial as _partial

    from hetu_galvatron_tpu.models.modules import xla_sdpa
    from hetu_galvatron_tpu.ops.ring_attention import make_ring_sdpa
    from hetu_galvatron_tpu.ops.ulysses import make_ulysses_sdpa

    if use_flash is None:
        use_flash = all(d.platform == "tpu"
                        for d in mesh.devices.flat[:1])
    out: Dict[int, Dict[str, Any]] = {}
    for i, sh in enumerate(per_layer):
        if sh.cp_axes:
            out[i] = {"sdpa_fn": make_ring_sdpa(
                mesh, sh.cp_axes, dp_axes=sh.dp_axes, tp_axes=sh.tp_axes,
                use_flash=use_flash, zigzag=cp_zigzag,
                data_zigzagged=cp_zigzag, interpret=flash_interpret)}
            if with_cross:
                out[i]["cross_sdpa_fn"] = xla_sdpa
        elif sh.ulysses and sh.tp_axes:
            local = None
            if use_flash:
                from hetu_galvatron_tpu.ops.pallas.flash_attention import (
                    flash_sdpa,
                )

                local = (_partial(flash_sdpa, interpret=True)
                         if flash_interpret else flash_sdpa)
            out[i] = {"sdpa_fn": make_ulysses_sdpa(
                mesh, sh.tp_axes, dp_axes=sh.dp_axes, local_sdpa=local)}
            if with_cross:
                out[i]["cross_sdpa_fn"] = xla_sdpa
        elif use_flash:
            from hetu_galvatron_tpu.ops.pallas.flash_attention import (
                make_flash_sdpa,
            )

            out[i] = {"sdpa_fn": make_flash_sdpa(
                mesh, dp_axes=sh.dp_axes, tp_axes=sh.tp_axes,
                interpret=flash_interpret)}
    return out


def tp_overlap_overrides(
    per_layer: List[LayerSharding],
    mesh: Mesh,
    cfg: ModelArgs,
    *,
    is_moe_layer_fn: Optional[Any] = None,
) -> Tuple[Dict[int, Dict[str, Any]], List[Tuple[int, str]]]:
    """Per-layer overlapped-TP matmul dispatch (the ``matmul_fns`` analogue
    of :func:`attention_overrides`): eligible Megatron-TP layers get the
    decomposed ring all-gather/reduce-scatter matmuls (ops/overlap.py);
    everything else stays on GSPMD. Returns (overrides, fallbacks) where
    ``fallbacks`` lists (layer index, unsupported_reason) for layers the
    caller asked to overlap but could not — the launcher logs them."""
    from hetu_galvatron_tpu.analysis.eligibility import (
        MOE_REASON,
        T5_REASON,
        layer_overlap_reason,
    )
    from hetu_galvatron_tpu.models.moe import is_moe_layer
    from hetu_galvatron_tpu.ops.overlap import make_layer_matmuls
    from hetu_galvatron_tpu.runtime.mesh import axes_size

    moe_of = is_moe_layer_fn or is_moe_layer
    out: Dict[int, Dict[str, Any]] = {}
    fallbacks: List[Tuple[int, str]] = []
    cache: Dict[Tuple, Dict[str, Any]] = {}
    for i, sh in enumerate(per_layer):
        if cfg.model_type == "t5":
            fallbacks.append((i, T5_REASON))
            continue
        if moe_of(cfg, i):
            fallbacks.append((i, MOE_REASON))
            continue
        tp_axes = sh.weight_tp_axes
        reason = layer_overlap_reason(cfg, sh, axes_size(mesh, tp_axes))
        if reason is not None:
            fallbacks.append((i, reason))
            continue
        key = (sh.dp_axes, tp_axes)
        if key not in cache:
            cache[key] = {"matmul_fns": make_layer_matmuls(
                mesh, sh.dp_axes, tp_axes)}
        out[i] = cache[key]
    return out, fallbacks


def make_boundary_fn(
    per_layer: List[LayerSharding],
    vocab: LayerSharding,
    mesh: Mesh,
) -> Callable[[int, jax.Array], jax.Array]:
    """Resharding constraints at layer boundaries — GSPMD's version of the
    reference's Module_with_relocation split/all-gather (parallel.py:272-304,
    redistribute.py:345-415). Boundary i < n constrains the input of layer i;
    boundary n (after the last layer) re-constrains for prenorm/head."""
    n = len(per_layer)

    def boundary(i: int, x: jax.Array) -> jax.Array:
        sh = per_layer[i] if i < n else vocab
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, sh.act_spec()))

    return boundary


def make_embed_use_constraint(
    embed_axes: Params, vocab: LayerSharding, mesh: Mesh
) -> Callable[[Params], Params]:
    """ZeRO-3 shards the embedding table's hidden dim across dp; the table
    must be (all-)gathered before the token lookup. State that explicitly
    with a use-site `with_sharding_constraint` (hidden dim unsharded, vocab
    dim still vtp-sharded) so the partitioner doesn't solve the gather with
    a hidden-sharded output and then full-rematerialize it to the batch/seq
    activation layout — the `spmd_partitioner.cc` "Involuntary full
    rematerialization" warning. Backward gets the transpose for free: the
    wte grad is formed in the gathered layout and reduce-scattered back to
    the ZeRO-3 spec by the constraint's adjoint. This is the relocation the
    reference does by hand (runtime/redistribute.py:345-415)."""
    is_axes = lambda x: (isinstance(x, tuple)
                         and all(isinstance(s, str) for s in x))
    specs = jax.tree.map(
        lambda la: vocab.param_spec(la, zero3_override=False),
        embed_axes, is_leaf=is_axes)

    def constrain(embed_params: Params) -> Params:
        return jax.tree.map(
            lambda x, s: jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, s)),
            embed_params, specs)

    return constrain


def shard_params(params: Params, specs: Params, mesh: Mesh) -> Params:
    """Place an (unsharded, host/single-device) params tree onto the mesh."""
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)


def batch_sharding(
    per_layer: List[LayerSharding], mesh: Mesh
) -> NamedSharding:
    """Input batch layout: shard over the first decoder layer's dp axes (and
    cp axes along sequence); interior constraints reshard per layer."""
    return NamedSharding(mesh, per_layer[0].batch_spec())


def _lower_specs(hpc: HybridParallelConfig, mesh: Mesh, axes_tree: Params):
    """Shared lowering preamble: strategies -> (per-layer shardings, vocab
    sharding, param PartitionSpec tree) with the t5 combined-stack split."""
    per_layer_all, vocab = layer_shardings(hpc, mesh)
    n_enc = hpc.num_encoder_layers
    enc_per, per_layer = per_layer_all[:n_enc], per_layer_all[n_enc:]
    pspecs = param_specs(axes_tree, per_layer, vocab,
                         enc_per_layer=enc_per or None)
    return enc_per, per_layer, vocab, pspecs


def build_spmd_loss_fn(
    cfg: ModelArgs,
    hpc: HybridParallelConfig,
    mesh: Mesh,
    axes_tree: Params,
    *,
    compute_dtype=jnp.bfloat16,
    layer_overrides: Optional[Dict[int, Dict[str, Any]]] = None,
    with_moe_stats: bool = False,
    tp_overlap: bool = False,
    lane_dp: bool = False,
):
    """The plan-lowered loss closure shared by the train and eval steps:
    per-layer shardings, boundary constraints, attention-impl dispatch,
    remat flags, fused CE, and the ZeRO-3 embed use-site constraint.
    Returns (loss_fn, pspecs, batch_shd, per_layer, vocab, enc_per).
    ``tp_overlap`` swaps eligible Megatron-TP layers' projection matmuls
    for the decomposed ring collectives (:func:`tp_overlap_overrides`);
    ineligible layers silently keep GSPMD — the launcher logs the reasons.

    ``lane_dp`` builds the hierarchical-dp LANE variant: the interior
    activation constraints drop the dp axes (each lane's batch slice lives
    entirely inside one dp group, so a dp-sharded constraint under the
    per-lane vmap would force a per-layer reshard of every lane), and the
    lane axis itself is pinned to the dp mesh axes by the caller's
    ``jax.vmap(..., spmd_axis_name=dp_axes)``. Param specs and the
    returned batch sharding stay the FLAT plan's (params are unmapped;
    the lane reshape happens inside the step). cp/Ulysses layers keep
    their GSPMD attention core under ``lane_dp`` instead of the ring /
    a2a shard_map kernels (which cannot nest under the lane vmap,
    eligibility.HIER_KERNEL_REASON): the partitioner inserts the
    sequence collectives inside each lane — same math, collective
    association differs within float tolerance."""
    from dataclasses import replace as _replace

    enc_per, per_layer, vocab, pspecs = _lower_specs(hpc, mesh, axes_tree)
    if lane_dp:
        lane = lambda sh: _replace(sh, dp_axes=())
        b_layers = [lane(sh) for sh in per_layer]
        b_vocab = lane(vocab)
        b_enc = [lane(sh) for sh in enc_per]
    else:
        b_layers, b_vocab, b_enc = per_layer, vocab, enc_per
    boundary = make_boundary_fn(b_layers, b_vocab, mesh)
    enc_boundary = (make_boundary_fn(b_enc, b_vocab, mesh)
                    if b_enc else None)
    use_flash = None if cfg.use_flash_attn else False
    if lane_dp:
        # no shard_map kernels under the lane vmap: cp/ulysses layers run
        # the XLA core (GSPMD partitions the sequence-sharded softmax per
        # lane); flash/fused-CE/tp_overlap are gated off by the callers
        # (make_spmd_train_step raises HIER_KERNEL_REASON first)
        ring = {}
        enc_overrides = None
    else:
        ring = attention_overrides(
            b_layers, mesh, use_flash=use_flash,
            with_cross=cfg.model_type == "t5",
            cp_zigzag=getattr(hpc, "cp_zigzag", False))
        enc_overrides = (attention_overrides(b_enc, mesh,
                                             use_flash=use_flash)
                         if b_enc else None)
    if tp_overlap:
        overlap_ov, _ = tp_overlap_overrides(per_layer, mesh, cfg)
        # merged UNDER ring/caller overrides per key: an explicit
        # sdpa_fn/matmul_fns from either always wins
        for i, kw in overlap_ov.items():
            ring[i] = {**kw, **ring.get(i, {})}
    if ring:
        # per-key merge: a caller override on a cp layer must not drop the
        # ring sdpa_fn unless it sets sdpa_fn itself
        merged = dict(layer_overrides or {})
        for i, kw in ring.items():
            merged[i] = {**kw, **merged.get(i, {})}
        layer_overrides = merged
    remat = [sh.checkpoint for sh in per_layer]
    enc_remat = [sh.checkpoint for sh in enc_per]
    batch_shd = batch_sharding(per_layer, mesh)

    enc_kwargs = {}
    if cfg.model_type == "t5":
        # always pass the explicit per-layer list: None would trigger the
        # legacy clone-remat_flags[0] fallback in forward_encdec
        enc_kwargs = dict(
            enc_remat_flags=enc_remat,
            enc_layer_overrides=enc_overrides,
            enc_boundary_fn=enc_boundary)

    # Fused CE on a mesh: a bare Pallas call is a custom call GSPMD cannot
    # partition, so distributed runs get the shard_map vocab-parallel
    # wrapper matched to the head's sharding (pmax/psum logsumexp merge
    # across vocab shards — the reference's Triton vocab-parallel CE
    # semantics); single-device runs use the kernel directly.
    fused_ce = cfg.use_fused_ce
    if fused_ce and mesh.size > 1:
        from hetu_galvatron_tpu.ops.pallas.cross_entropy import (
            make_vocab_parallel_ce,
        )

        fused_ce = make_vocab_parallel_ce(mesh, vocab)

    constrain_embed = make_embed_use_constraint(
        axes_tree["embed"], vocab, mesh)

    def loss_fn(p, batch):
        p = {**p, "embed": constrain_embed(p["embed"])}
        return causal_lm_loss(
            p, batch, cfg, compute_dtype=compute_dtype,
            remat_flags=remat if any(remat) else None,
            layer_overrides=layer_overrides, boundary_fn=boundary,
            fused_ce=fused_ce, with_moe_stats=with_moe_stats, **enc_kwargs)

    return loss_fn, pspecs, batch_shd, per_layer, vocab, enc_per


def make_spmd_eval_step(
    cfg: ModelArgs,
    hpc: HybridParallelConfig,
    mesh: Mesh,
    axes_tree: Params,
    *,
    compute_dtype=jnp.bfloat16,
    layer_overrides: Optional[Dict[int, Dict[str, Any]]] = None,
    tp_overlap: bool = False,
):
    """Jitted held-out loss under the SAME plan shardings as training
    (reference evaluate(), training.py side of dataloader.py:462): no
    optimizer, no dropout (eval semantics are the loss_fn default when the
    batch carries no 'dropout_rng'). Returns (eval_fn(params, batch) ->
    loss, batch_shd)."""
    if hpc.pp_deg != 1:
        raise ValueError("make_spmd_eval_step is the pp=1 path; use "
                         "PipelineEngine.eval_step for pp>1")
    loss_fn, pspecs, batch_shd, _, _, _ = build_spmd_loss_fn(
        cfg, hpc, mesh, axes_tree, compute_dtype=compute_dtype,
        layer_overrides=layer_overrides, tp_overlap=tp_overlap)
    nshd = jax.tree.map(
        lambda s: NamedSharding(mesh, s), pspecs,
        is_leaf=lambda x: isinstance(x, P))
    return jax.jit(loss_fn, in_shardings=(nshd, batch_shd)), batch_shd


def make_spmd_train_step(
    cfg: ModelArgs,
    hpc: HybridParallelConfig,
    mesh: Mesh,
    axes_tree: Params,
    tx: optax.GradientTransformation,
    params: Params,
    *,
    compute_dtype=jnp.bfloat16,
    layer_overrides: Optional[Dict[int, Dict[str, Any]]] = None,
    donate: bool = True,
    chunks: Optional[int] = None,
    tp_overlap: bool = False,
    hier_dp: bool = False,
    dcn_slices: int = 1,
    hier_bucket_mb: float = 0.0,
    dp_schedule: Optional[str] = None,
):
    """Build the jitted hybrid-parallel train step (no pipeline; pp=1).

    Returns (train_step, pspecs, opt_specs, batch_shd). The caller places
    params/opt_state with :func:`shard_params` and feeds batches laid out by
    ``batch_shd``. The pipeline engine (pp>1) wraps this per-stage.
    ``chunks`` overrides the plan's microbatch count (batch-size ramp:
    the launcher rebuilds the step per chunk count at a fixed micro size).
    ``tp_overlap`` runs eligible TP layers' projections as decomposed
    ring-collective matmuls (ops/overlap.py). ``hier_dp`` swaps the
    implicit GSPMD dp gradient all-reduce for the explicit hierarchical
    reduce-scatter/all-reduce/all-gather path (ops/hier_reduce.py), with
    the slice/host split taken from ``dcn_slices`` and the bucketed
    software-pipelining granularity from ``hier_bucket_mb``
    (``parallel.hier_bucket_mb``; 0 = one monolithic bucket); ineligible
    plans raise with the shared eligibility reason (the launcher logs and
    falls back). ``dp_schedule`` (``parallel.dp_schedule``, hier_dp only)
    swaps the hand-implemented rs/ar/ag program for a synthesized,
    verified, emitted collective schedule (``collectives/``) — the plan
    JSON records the family the search priced cheapest.
    """
    if hpc.pp_deg != 1:
        raise ValueError("make_spmd_train_step is the pp=1 path; use the "
                         "pipeline engine for pp>1")
    moe_stats = bool(cfg.num_experts)
    if hier_dp:
        from hetu_galvatron_tpu.analysis.eligibility import (
            HIER_KERNEL_REASON,
            plan_hier_dp_reason,
        )

        reason = plan_hier_dp_reason(cfg, hpc)
        if reason is None and tp_overlap:
            reason = HIER_KERNEL_REASON
        if reason is None and cfg.use_flash_attn and all(
                d.platform == "tpu" for d in mesh.devices.flat[:1]):
            reason = HIER_KERNEL_REASON
        if reason is None and cfg.use_fused_ce and mesh.size > 1:
            reason = HIER_KERNEL_REASON  # vocab-parallel CE is a shard_map
        if reason is not None:
            raise ValueError(f"hier_dp unsupported: {reason}")
    loss_fn, pspecs, batch_shd, per_layer, vocab, enc_per = (
        build_spmd_loss_fn(
            cfg, hpc, mesh, axes_tree, compute_dtype=compute_dtype,
            layer_overrides=layer_overrides, with_moe_stats=moe_stats,
            tp_overlap=tp_overlap, lane_dp=hier_dp))
    opt_pspecs = param_specs(axes_tree, per_layer, vocab, opt=True,
                             enc_per_layer=enc_per or None)
    opt_specs = opt_state_specs(tx, params, opt_pspecs)
    chunks = max(chunks if chunks is not None else hpc.chunks, 1)
    hier = None
    if hier_dp:
        from hetu_galvatron_tpu.ops.hier_reduce import make_hier_reducer

        hier = make_hier_reducer(mesh, per_layer, vocab, axes_tree,
                                 dcn_slices=dcn_slices,
                                 bucket_mb=hier_bucket_mb,
                                 schedule=dp_schedule or None)
    constrain_mbs = None
    if hier is None and chunks > 1:
        # flat-path microbatch pin (ROADMAP embed-ZeRO-3 BUG, fixed): the
        # [B] -> [chunks, B/chunks] reshape naturally absorbs the OUTER dp
        # mesh axis into the chunk dim, so every scanned microbatch arrives
        # batch-sharded over only the inner dp axes — a layout whose
        # ZeRO-3 gradient program the partitioner gets numerically WRONG
        # (wte rows at grad magnitude under vtp>1; every dp-sharded leaf
        # drifts). Pin the chunk axis replicated and the sample axis to
        # the plan's own batch sharding: each microbatch's embed-grad
        # reduce-scatter then materializes per microbatch in the correct
        # layout — the same pinning discipline hier.lane_batch always had
        # (which is why the hier path was exact where flat drifted).
        mb_spec = NamedSharding(mesh, P(None, *per_layer[0].batch_spec()))

        def constrain_mbs(mbs):
            return jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(x, mb_spec), mbs)

    step = make_train_step(loss_fn, tx, chunks=chunks, aux_stats=moe_stats,
                           hier=hier, constrain_microbatches=constrain_mbs)

    nshd = lambda tree: jax.tree.map(
        lambda s: NamedSharding(mesh, s), tree,
        is_leaf=lambda x: isinstance(x, P))
    use_dropout = cfg.hidden_dropout > 0.0 or cfg.attention_dropout > 0.0
    if use_dropout:
        # the rng key can't ride inside the batch at the jit boundary: the
        # batch in-sharding is ONE NamedSharding broadcast over every leaf,
        # and a scalar key has no batch axes. Jit a 4-arg step (key
        # replicated) and keep the public 3-arg contract with a wrapper that
        # pops the "dropout_rng" the trainer put in the batch dict.
        jitted = jax.jit(
            lambda p, o, b, rng: step(p, o, {**b, "dropout_rng": rng}),
            in_shardings=(nshd(pspecs), nshd(opt_specs), batch_shd,
                          NamedSharding(mesh, P())),
            out_shardings=(nshd(pspecs), nshd(opt_specs), None),
            donate_argnums=(0, 1) if donate else (),
        )

        def train_step(params, opt_state, batch):
            batch = dict(batch)
            rng = batch.pop("dropout_rng", None)
            if rng is None:
                raise ValueError(
                    "cfg enables dropout but the batch has no 'dropout_rng' "
                    "key; train_loop adds it automatically — manual callers "
                    "must pass one per step")
            return jitted(params, opt_state, batch, rng)
    else:
        train_step = jax.jit(
            step,
            in_shardings=(nshd(pspecs), nshd(opt_specs), batch_shd),
            out_shardings=(nshd(pspecs), nshd(opt_specs), None),
            donate_argnums=(0, 1) if donate else (),
        )
    return train_step, pspecs, opt_specs, batch_shd


def make_spmd_generate(
    cfg: ModelArgs,
    hpc: HybridParallelConfig,
    mesh: Mesh,
    axes_tree: Params,
    max_new_tokens: int,
    **gen_kwargs,
):
    """Distributed autoregressive generation (pp=1): jit models/generate.py's
    fully-jittable generate() under the plan's GSPMD shardings and let
    propagation shard the KV cache off the (tp-sharded) k/v projections —
    batch rides the dp axes, kv heads the tp axes, with zero changes to the
    decode loop. The reference ships only inference-context stubs
    (transformer/attention.py inference params); this is a working
    tensor/data-parallel decode path.

    Returns (generate_fn(params, tokens, key) -> tokens, pspecs, batch_shd).
    Params must be placed with :func:`shard_params` first.
    """
    from hetu_galvatron_tpu.models.generate import generate, generate_encdec

    if hpc.pp_deg != 1:
        raise ValueError("make_spmd_generate is the pp=1 path")
    _, per_layer, vocab, pspecs = _lower_specs(hpc, mesh, axes_tree)
    # tokens: batch over the first layer's dp axes only (sequence stays
    # local — the decode step is one position wide)
    tok_spec = P(per_layer[0].batch_spec()[0])
    batch_shd = NamedSharding(mesh, tok_spec)
    nshd = lambda tree: jax.tree.map(
        lambda s: NamedSharding(mesh, s), tree,
        is_leaf=lambda x: isinstance(x, P))

    if cfg.model_type == "t5":
        # seq2seq: tokens are the ENCODER source; the decoder stream and
        # both caches take their shardings from propagation exactly like
        # the causal path (cross k/v shard off the tp-sharded wkv)
        decode = lambda p, tokens, key: generate_encdec(
            p, tokens, cfg, max_new_tokens, key=key, **gen_kwargs)
    else:
        decode = lambda p, tokens, key: generate(
            p, tokens, cfg, max_new_tokens, key=key, **gen_kwargs)
    fn = jax.jit(
        decode,
        in_shardings=(nshd(pspecs), batch_shd, NamedSharding(mesh, P())),
        out_shardings=batch_shd,
    )
    return fn, pspecs, batch_shd
