"""Radix prefix cache: shared-prefix KV reuse over the paged block pool.

Production traffic is dominated by a handful of system prompts: when most
requests open with the same tokens, most prefill FLOPs recompute k/v the
pool already holds. This module keys the pool's blocks by their token
content in a radix tree (the SGLang RadixAttention recipe, adapted to our
block tables):

* **Block-granular edges.** Every tree edge is a run of FULL blocks
  (``block_size`` tokens each); children are keyed by their first block's
  token tuple, so matching and edge-splitting are always block-aligned and
  a matched prefix maps 1:1 onto pool block ids a request's table can
  point at copy-free.
* **Refcounted sharing.** The tree holds one allocator reference per
  adopted block (``BlockAllocator.incref``); a running request that
  matched a path pins its nodes (``node.ref``) so eviction can never pull
  a block out from under a live table. Retirement releases pins and
  decrefs — nothing is ever freed while shared
  (``kv_cache.BlockAccountingError`` guards the strict path).
* **LRU eviction over refcount-0 nodes.** When the pool cannot satisfy an
  allocation (or the tree exceeds ``max_blocks``), unpinned LEAF nodes are
  evicted oldest-first; inner nodes become leaves as their children go, so
  cold prompt families drain from the tips inward.

The cache stores only what a prefill actually wrote: :meth:`insert` adopts
a request's full prompt blocks after its prefill, deduping against any
path already present (first writer wins — a concurrently-prefilled twin
keeps its private blocks and they simply retire with it).

Content equality is exact token-id equality over whole blocks. Matched
blocks are bit-identical to what the requesting prompt's own prefill
would have produced: k/v at position p depends only on tokens[0..p], and
the bucketed prefill program is row-wise bit-stable across bucket widths
(the engine's offline-parity drills pin exactly that).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from hetu_galvatron_tpu.serving.kv_cache import BlockAllocator

BlockKey = Tuple[int, ...]  # one block's tokens (length == block_size)


@dataclass
class RadixNode:
    """One edge+node of the tree: ``tokens`` is the edge label (a multiple
    of block_size tokens), ``blocks`` the pool ids holding their k/v.
    ``ref`` counts live requests pinning this node (match() .. release());
    ``stamp`` is the LRU clock value of the last touch."""

    tokens: Tuple[int, ...]
    blocks: List[int]
    parent: Optional["RadixNode"]
    children: Dict[BlockKey, "RadixNode"] = field(default_factory=dict)
    ref: int = 0
    stamp: int = 0
    # detached by invalidate() while still pinned by a live request: the
    # node no longer matches (its k/v was computed under superseded
    # weights) but its blocks stay live until the last pin releases
    zombie: bool = False


class PrefixCache:
    """The radix tree one engine owns (host-side, no jax).

    All block ownership flows through the shared :class:`BlockAllocator`:
    the tree is just another owner. ``max_blocks`` caps how many blocks
    the tree may hold (0 = bounded only by the pool); either way,
    :meth:`evict` reclaims unpinned nodes LRU-first when the allocator
    runs dry.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int,
                 max_blocks: int = 0):
        if block_size < 1:
            raise ValueError(f"block_size {block_size}")
        self.allocator = allocator
        self.block_size = int(block_size)
        self.max_blocks = int(max_blocks)
        self.root = RadixNode(tokens=(), blocks=[], parent=None)
        self._clock = itertools.count(1)
        self.blocks_held = 0
        # nodes detached by invalidate() while pinned: kept only so
        # release() can drop their blocks when the last pin goes
        self._zombies: List[RadixNode] = []
        # telemetry: lookups/hits/tokens served from cache/evicted blocks
        self.lookups = 0
        self.hits = 0
        self.cached_tokens_served = 0
        self.evicted_blocks = 0

    # -- matching -----------------------------------------------------------

    @property
    def hit_rate(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    def note_lookup(self, cached_len: int) -> None:
        """Record one REQUEST's cache outcome for the hit-rate telemetry.
        Deliberately separate from :meth:`match`: admission re-matches a
        deferred head-of-queue request every engine step, and counting
        each retry would inflate the gauge."""
        self.lookups += 1
        if cached_len:
            self.hits += 1
            self.cached_tokens_served += cached_len

    def _touch(self, node: RadixNode) -> None:
        node.stamp = next(self._clock)

    def match(self, tokens: Sequence[int]
              ) -> Tuple[int, List[int], Tuple[RadixNode, ...]]:
        """Longest cached block-aligned prefix of ``tokens``. Returns
        ``(cached_len, blocks, path)``: ``cached_len`` tokens (a multiple
        of block_size, at most ``len(tokens) // bs * bs``) are already in
        the pool at ``blocks``; every node in ``path`` is PINNED (ref+1)
        until the caller passes it back to :meth:`release` — a partially
        used edge pins its node too (its blocks are in the table). Stats
        are NOT recorded here (:meth:`note_lookup` is the per-request
        accounting hook)."""
        bs = self.block_size
        toks = tuple(tokens)
        want = len(toks) // bs * bs  # only whole blocks can be shared
        node = self.root
        i = 0
        blocks: List[int] = []
        path: List[RadixNode] = []
        while i < want:
            child = node.children.get(toks[i:i + bs])
            if child is None:
                break
            # block-by-block common prefix along this edge
            n_match = 0
            for j in range(len(child.blocks)):
                lo = i + j * bs
                if lo + bs > want or child.tokens[j * bs:(j + 1) * bs] \
                        != toks[lo:lo + bs]:
                    break
                n_match += 1
            if n_match == 0:
                break
            child.ref += 1
            self._touch(child)
            path.append(child)
            blocks.extend(child.blocks[:n_match])
            i += n_match * bs
            if n_match < len(child.blocks):
                break
            node = child
        return i, blocks, tuple(path)

    def release(self, path: Sequence[RadixNode]) -> None:
        """Drop a request's pins (retirement). Idempotence is the
        caller's job — each match() pin is released exactly once. A
        zombie node (detached by :meth:`invalidate` while pinned) drops
        its block references when its last pin goes."""
        for node in path:
            if node.ref < 1:
                raise ValueError("release of an unpinned radix node")
            node.ref -= 1
            self._touch(node)
            if node.zombie and node.ref == 0:
                self.allocator.decref(node.blocks)
                self.blocks_held -= len(node.blocks)
                self.evicted_blocks += len(node.blocks)
                self._zombies.remove(node)

    # -- insertion ----------------------------------------------------------

    def insert(self, tokens: Sequence[int], blocks: Sequence[int]
               ) -> List[int]:
        """Adopt a prefilled prompt's full blocks into the tree. ``tokens``
        is truncated to whole blocks; ``blocks`` maps 1:1 onto them and
        must already be live in the allocator (the inserting request's
        references). Returns the block ids the tree newly adopted (it
        increfs them; the request keeps its own references and decrefs at
        retirement as usual). Paths already present keep their existing
        blocks — the duplicate suffix is simply not adopted."""
        bs = self.block_size
        toks = tuple(tokens)
        n_full = len(toks) // bs
        toks = toks[: n_full * bs]
        blocks = list(blocks)[:n_full]
        if len(blocks) != n_full:
            raise ValueError(
                f"insert: {n_full} full blocks of tokens but "
                f"{len(blocks)} block ids")
        node = self.root
        i = 0
        adopted: List[int] = []
        while i < len(toks):
            key = toks[i:i + bs]
            child = node.children.get(key)
            if child is None:
                new = RadixNode(tokens=toks[i:], blocks=blocks[i // bs:],
                                parent=node)
                self.allocator.incref(new.blocks)
                adopted.extend(new.blocks)
                self.blocks_held += len(new.blocks)
                node.children[key] = new
                self._touch(new)
                break
            # advance along the edge's common block prefix
            n_match = 0
            for j in range(len(child.blocks)):
                lo = i + j * bs
                if lo >= len(toks) or child.tokens[j * bs:(j + 1) * bs] \
                        != toks[lo:lo + bs]:
                    break
                n_match += 1
            if n_match < len(child.blocks) and i + n_match * bs < len(toks):
                # diverging mid-edge: split the edge at the boundary
                child = self._split(child, n_match)
            self._touch(child)
            i += n_match * bs
            node = child
            if n_match == len(node.blocks) and i >= len(toks):
                break
            if n_match < len(node.blocks):
                # insert path is a strict prefix of the edge: nothing new
                break
        if self.max_blocks and self.blocks_held > self.max_blocks:
            self.evict(self.blocks_held - self.max_blocks)
        return adopted

    def _split(self, node: RadixNode, n_blocks: int) -> RadixNode:
        """Split ``node``'s edge after ``n_blocks`` blocks; returns the new
        upper node (which keeps the prefix), with ``node`` demoted to its
        child carrying the remainder. Pins (ref) stay on the lower node —
        eviction is leaf-only, so an ancestor whose descendant is pinned
        can never be evicted, and inheriting the pin here would leak it
        when the pinning request releases the (lower) node it recorded."""
        bs = self.block_size
        cut = n_blocks * bs
        upper = RadixNode(tokens=node.tokens[:cut],
                          blocks=node.blocks[:n_blocks],
                          parent=node.parent, ref=0,
                          stamp=node.stamp)
        parent = node.parent
        parent.children[upper.tokens[:bs]] = upper
        node.tokens = node.tokens[cut:]
        node.blocks = node.blocks[n_blocks:]
        node.parent = upper
        upper.children[node.tokens[:bs]] = node
        return upper

    # -- eviction -----------------------------------------------------------

    def _leaves(self) -> List[RadixNode]:
        out = []
        stack = [self.root]
        while stack:
            n = stack.pop()
            kids = list(n.children.values())
            if not kids and n is not self.root:
                out.append(n)
            stack.extend(kids)
        return out

    def evict(self, n_blocks: int) -> int:
        """Reclaim at least ``n_blocks`` tree-held blocks if possible:
        repeatedly drop the LRU unpinned leaf (decref its blocks — a block
        an active request still owns survives in ITS table; the tree just
        stops advertising it). Returns how many blocks left the tree."""
        freed = 0
        while freed < n_blocks:
            victims = [n for n in self._leaves() if n.ref == 0]
            if not victims:
                break
            victim = min(victims, key=lambda n: n.stamp)
            self.allocator.decref(victim.blocks)
            freed += len(victim.blocks)
            self.blocks_held -= len(victim.blocks)
            self.evicted_blocks += len(victim.blocks)
            del victim.parent.children[victim.tokens[:self.block_size]]
        return freed

    # -- invalidation (weight swap) ------------------------------------------

    def invalidate(self) -> int:
        """Drop every cached prefix — the weight-swap contract: pooled
        k/v was computed under the OLD weights, so a post-swap request
        must never splice it (its stream would not match a cold engine on
        the new checkpoint). Unpinned nodes free their blocks now; nodes
        pinned by in-flight requests detach as ZOMBIES whose blocks free
        at their last :meth:`release` (the in-flight request keeps its own
        allocator refs and finishes under the mixed-context contract).
        Returns how many blocks left the tree immediately."""
        nodes: List[RadixNode] = []
        stack = [self.root]
        while stack:
            n = stack.pop()
            if n is not self.root:
                nodes.append(n)
            stack.extend(n.children.values())
        dropped = 0
        for n in nodes:
            n.children = {}
            if n.ref > 0:
                n.zombie = True
                self._zombies.append(n)
            else:
                self.allocator.decref(n.blocks)
                dropped += len(n.blocks)
                self.blocks_held -= len(n.blocks)
                self.evicted_blocks += len(n.blocks)
        self.root = RadixNode(tokens=(), blocks=[], parent=None)
        return dropped

    # -- defrag support ------------------------------------------------------

    def export_tables(self) -> Tuple[List[RadixNode], List[List[int]]]:
        """Every node's block list, for compaction: the scheduler passes
        these alongside the sequences' tables so ``defrag_plan`` renames
        EVERY referencing view (satellite contract: a radix node's table
        is a first-class block table). Zombie nodes (detached by
        :meth:`invalidate`, blocks still live until their pins release)
        are included — their blocks are pool blocks like any other."""
        nodes: List[RadixNode] = []
        stack = [self.root]
        while stack:
            n = stack.pop()
            if n is not self.root:
                nodes.append(n)
            stack.extend(n.children.values())
        nodes.extend(self._zombies)
        return nodes, [list(n.blocks) for n in nodes]

    def adopt_tables(self, nodes: Sequence[RadixNode],
                     tables: Sequence[Sequence[int]]) -> None:
        for n, t in zip(nodes, tables):
            n.blocks = list(t)
