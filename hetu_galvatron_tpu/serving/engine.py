"""Serving engine: jitted paged prefill/decode + continuous batching loop.

A small set of programs, compiled once each (prefill once per length
bucket), drives all traffic:

* **prefill** — one request's (right-padded, bucketed) prompt through the
  stack with the same attention math as offline ``models/generate.prefill``,
  k/v written straight into the request's pool blocks, first token sampled
  from the last real position's logits.
* **prefix prefill** (``serving.prefix_cache`` on) — the same, but only
  over the UNCACHED suffix of a prompt whose block-aligned prefix the
  radix cache (``prefix_cache.py``) already holds: queries are the suffix
  bucket, keys are the gathered full block table, and the cached-prefix
  FLOPs are simply never spent. A fully-cached prompt dispatches no
  prefill at all — its slot enters at ``pos = len-1`` and the next decode
  step produces the first token (bit-identical: the decode program's
  single-row math equals the prefill row's).
* **decode** — one token for every slot at a FIXED batch shape
  ``[max_batch_size]``: per-slot positions, per-slot block tables, per-slot
  sampling params. Retired slots alias the scratch block and their outputs
  are discarded, so admission/retirement never changes the compiled shape —
  steady state runs with zero recompiles (``compile_count()`` lets tests
  pin this).
* **verify** (``serving.spec_decode`` on) — the speculative window: every
  slot's ``[last_token, draft_1..draft_K]`` through the stack at one fixed
  ``[max_batch_size, K+1]`` shape (``kv_cache.paged_sdpa_window`` masks
  row j at position pos+j), returning the target model's choice after
  every drafted token. Greedy acceptance keeps the stream bit-identical
  to plain decode while emitting up to K+1 tokens per step
  (``spec_decode.py`` holds the draft providers + acceptance rule).

Plan-aware SPMD: given a mesh + :class:`HybridParallelConfig`, params are
sharded by the plan's PartitionSpecs (``parallel/spmd.py``) and the KV pool's
kv-head axis rides each layer's attention tp axes (``kv_cache.pool_pspecs``)
— the searched plan picks the decode-time sharding just as it picks the
train-time one. Without a mesh the same programs jit on one device.

Determinism contract: a request's token stream depends only on (params,
prompt, its own sampling seed/temperature) — greedy rows are argmax rows and
sampled rows fold the request seed with the emitted-token index — never on
which neighbors share the batch. The continuous-batching drill pins stream
equality against offline ``generate()``.

Host/device cadence: every step syncs the sampled tokens to the host (they
feed the streams and the retirement logic). Decode steps are latency-bound
anyway; the sync is the product, not overhead.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import ModelArgs, ServingArgs
from hetu_galvatron_tpu.models import modules as M
from hetu_galvatron_tpu.observability.events import EventStream
from hetu_galvatron_tpu.observability.recorder import FlightRecorder
from hetu_galvatron_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
)
from hetu_galvatron_tpu.observability.trace_analysis import (
    maybe_record_jit_cost,
)
from hetu_galvatron_tpu.serving.kv_cache import (
    SCRATCH_BLOCK,
    PagedKVCache,
    copy_block,
    gather_pages,
    paged_sdpa,
    paged_sdpa_window,
    scatter_prefill,
    resolve_num_blocks,
    scatter_token,
    scatter_window,
)
from hetu_galvatron_tpu.serving.prefix_cache import PrefixCache
from hetu_galvatron_tpu.serving.scheduler import (
    Request,
    RequestHandle,
    Scheduler,
    Slot,
)
from hetu_galvatron_tpu.serving.spec_decode import accept_length, make_draft

Params = Dict[str, Any]


class WeightSwapError(ValueError):
    """``swap_weights`` rejected the new checkpoint: its tree structure,
    shapes, or dtypes differ from the serving model's — a hot swap may
    only replace VALUES (same architecture), never recompile programs
    mid-traffic."""


def _check_supported(cfg: ModelArgs, params: Params) -> None:
    if cfg.post_norm or cfg.model_type in ("bert", "t5"):
        raise NotImplementedError(
            "ServingEngine serves dense causal decoder families; bert/t5 "
            "have no paged decode path")
    if any("moe" in lp for lp in params["layers"]):
        raise NotImplementedError("ServingEngine: dense layers only")


def default_buckets(block_size: int, cap_tokens: int) -> List[int]:
    """Every prefill bucket ``bucket_length`` can produce: the power-of-two
    ladder plus the capped (possibly non-power-of-two) top bucket — warmup
    must cover the cap too or the first long prompt recompiles
    mid-serving."""
    out = []
    b = block_size
    while b < cap_tokens:
        out.append(b)
        b *= 2
    out.append(cap_tokens)
    return out


def _make_sampler(cfg: ModelArgs, top_k: Optional[int]):
    """[S, V] logits -> [S] tokens. Greedy rows (temp <= 0) take the
    argmax; sampling rows draw categorical from a per-request key
    (fold_in(seed, emitted-token index)) so a request's stream is
    batch-composition invariant. Vocab-padding columns are never produced
    (mirrors ``models/generate._sample_pick``)."""
    valid = jnp.arange(cfg.padded_vocab_size) < cfg.vocab_size
    neg = jnp.float32(jnp.finfo(jnp.float32).min)

    def sample(logits, temps, seeds, gen_idx):
        logits = jnp.where(valid, logits.astype(jnp.float32), neg)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def one(row, t, s, g):
            key = jax.random.fold_in(jax.random.key(s), g)
            ll = row / jnp.maximum(t, jnp.float32(1e-6))
            if top_k:
                kth = jax.lax.top_k(ll, top_k)[0][-1]
                ll = jnp.where(ll < kth, neg, ll)
            return jax.random.categorical(key, ll).astype(jnp.int32)

        sampled = jax.vmap(one)(logits, temps.astype(jnp.float32),
                                seeds, gen_idx)
        return jnp.where(temps <= 0.0, greedy, sampled)

    return sample


class ServingEngine:
    """Continuous-batching inference over a loaded checkpoint + plan.

    ``params`` is the (host or sharded) params tree from
    ``models/builder.init_causal_lm`` / checkpoint restore; with
    ``mesh``/``hpc``/``axes_tree`` the engine places it under the plan's
    GSPMD shardings itself. ``submit()`` returns a
    :class:`~hetu_galvatron_tpu.serving.scheduler.RequestHandle` streaming
    tokens; drive the loop with :meth:`step` / :meth:`run_until_idle`, or
    :meth:`start` a background thread.
    """

    def __init__(
        self,
        params: Params,
        cfg: ModelArgs,
        serving: Optional[ServingArgs] = None,
        *,
        mesh=None,
        hpc=None,
        axes_tree: Optional[Params] = None,
        registry: Optional[MetricsRegistry] = None,
        compute_dtype=jnp.bfloat16,
        kv_dtype=None,
        draft_params: Optional[Params] = None,
        draft_cfg: Optional[ModelArgs] = None,
    ):
        serving = serving if serving is not None else ServingArgs()
        _check_supported(cfg, params)
        if mesh is not None and (hpc is None or axes_tree is None):
            raise ValueError("mesh serving needs hpc + axes_tree (the plan "
                             "and the params' logical axes)")
        self.cfg = cfg
        self.serving = serving
        self.mesh = mesh
        self.registry = registry if registry is not None else get_registry()
        self.compute_dtype = compute_dtype
        self.S = int(serving.max_batch_size)

        max_seq_len = serving.max_seq_len or cfg.max_position_embeddings
        # pool sizing is shared with the static memory doctor
        # (kv_cache.resolve_num_blocks), so `check --memory --serving`
        # predicts exactly the pool this engine allocates
        num_blocks = resolve_num_blocks(serving, cfg)

        layer_shards = None
        self._pspecs = None
        if mesh is not None:
            from hetu_galvatron_tpu.parallel.spmd import (
                layer_shardings,
                param_specs,
                shard_params,
            )

            if hpc.pp_deg != 1:
                raise ValueError("ServingEngine is the pp=1 decode path")
            per_layer_all, vocab_sh = layer_shardings(hpc, mesh)
            layer_shards = per_layer_all[hpc.num_encoder_layers:]
            self._pspecs = param_specs(axes_tree, layer_shards, vocab_sh)
            params = shard_params(params, self._pspecs, mesh)
        self.params = params

        self.kv = PagedKVCache(
            cfg, num_blocks=num_blocks, block_size=serving.kv_block_size,
            max_seq_len=max_seq_len,
            dtype=kv_dtype if kv_dtype is not None else compute_dtype,
            mesh=mesh, layer_shardings=layer_shards)
        from hetu_galvatron_tpu.core.cost_model.cost import (
            model_flops_per_token,
        )

        self.prefix: Optional[PrefixCache] = None
        if serving.prefix_cache:
            self.prefix = PrefixCache(
                self.kv.allocator, self.kv.block_size,
                max_blocks=serving.prefix_cache_max_blocks)
        # request-lifecycle tracing (observability/events.py): the sink
        # stream is gated on serving.trace_requests (zero JSONL growth by
        # default). The flight recorder taps the stream whenever its ring
        # can matter — tracing on, or a dump directory configured — so
        # crash dumps carry last-N-events context; with BOTH off, no tap
        # is attached and emit() is a single attribute check per event
        # (the default serving path pays nothing per token)
        self.events = EventStream(self.registry,
                                  enabled=serving.trace_requests)
        self.recorder = FlightRecorder(
            registry=self.registry, out_dir=serving.flight_dir,
            capacity=serving.flight_events)
        if serving.trace_requests or serving.flight_dir:
            self.recorder.attach(self.events)
        self.scheduler = Scheduler(
            self.kv, max_slots=self.S,
            max_position_embeddings=cfg.max_position_embeddings,
            prefill_flops_budget=serving.prefill_flops_budget_g * 1e9,
            # cost-model FLOPs are fwd+bwd (bwd counted 2x); prefill is
            # forward-only
            flops_per_token=model_flops_per_token(cfg) / 3.0,
            max_prefill_tokens=serving.max_prefill_tokens,
            prefix_cache=self.prefix, events=self.events)

        # rope/position tables cover every storable position
        self._table_len = self.kv.max_blocks_per_seq * self.kv.block_size
        self._rope = None
        if cfg.position_embedding_type == "rope":
            self._rope = M.rope_cos_sin(self._table_len, cfg.head_dim,
                                        cfg.rope_theta,
                                        scaling=cfg.rope_scaling)
        self._sample = _make_sampler(cfg, serving.top_k)
        self._decode_fn = self._build_decode()
        self._prefill_fns: Dict[int, Callable] = {}
        self._prefix_fns: Dict[int, Callable] = {}
        self._cow_fn: Optional[Callable] = None
        # speculative decoding: draft provider + the [S, K+1] verify
        # program (None when serving.spec_decode is off)
        if serving.spec_decode and serving.spec_k < 1:
            raise ValueError(f"serving.spec_k must be >= 1, "
                             f"got {serving.spec_k}")
        self._draft = make_draft(serving, draft_params=draft_params,
                                 draft_cfg=draft_cfg)
        self._verify_fn = (self._build_verify()
                           if self._draft is not None else None)
        self._drafted_total = 0
        self._accepted_total = 0

        # Prometheus /metrics endpoint (serving.metrics_port): off unless
        # asked for; port 0 binds ephemeral and .metrics_port reports it
        self.metrics_server = None
        self.metrics_port: Optional[int] = None
        if serving.metrics_port is not None:
            from hetu_galvatron_tpu.observability.prometheus import (
                MetricsHTTPServer,
            )

            self.metrics_server = MetricsHTTPServer(
                self.registry, port=int(serving.metrics_port),
                host=serving.metrics_host)
            self.metrics_port = self.metrics_server.start()

        # SLO attainment accounting (serving.slo_ttft_ms / slo_itl_ms):
        # plain host-side counts; flush() exports the attainment gauges
        self._ttft_n = self._ttft_ok = 0
        self._itl_n = self._itl_ok = 0

        self._lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._steps = 0
        self._emitted_window: List[tuple] = []  # (t, cumulative tokens)
        self._emitted_total = 0
        self._closed = False
        self.error: Optional[BaseException] = None  # fatal thread error

    # -- jitted programs ----------------------------------------------------

    def _shd(self, spec):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, spec)

    def _pool_shardings(self):
        return [{"k": self._shd(s), "v": self._shd(s)}
                for s in self.kv.pspecs]

    def _jit(self, fn, n_extra: int):
        """jit with pools donated (arg 1); under a mesh, params/pools keep
        their plan shardings and every batch array is replicated. Both
        programs return (pools, tokens)."""
        if self.mesh is None:
            return jax.jit(fn, donate_argnums=(1,))
        from jax.sharding import PartitionSpec as P

        rep = self._shd(P())
        nshd = jax.tree.map(self._shd, self._pspecs,
                            is_leaf=lambda x: isinstance(x, P))
        pools = self._pool_shardings()
        return jax.jit(
            fn,
            in_shardings=(nshd, pools) + (rep,) * n_extra,
            out_shardings=(pools, rep),
            donate_argnums=(1,),
        )

    def _layer_stack(self, params, pools, x, rope, sdpa_for):
        """Shared decoder-stack walk for prefill and decode: layer i runs
        with an sdpa closure that updates/reads pools[i]."""
        cfg = self.cfg
        new_pools = list(pools)
        for i, lp in enumerate(params["layers"]):
            cell: Dict[str, jax.Array] = {}
            sdpa = sdpa_for(i, new_pools, cell)
            x = M.apply_decoder_layer(lp, x, cfg, rope=rope, sdpa_fn=sdpa,
                                      compute_dtype=self.compute_dtype)
            new_pools[i] = {"k": cell["k"], "v": cell["v"]}
        x = M.apply_norm(params["prenorm"], x, cfg)
        logits = M.apply_lm_head(params["head"], x, cfg,
                                 wte=params["embed"]["wte"],
                                 compute_dtype=self.compute_dtype)
        return new_pools, logits

    def _build_prefill(self, bucket: int):
        """(params, pools, tokens [1, bucket], table [bucket//bs],
        true_len, temp, seed) -> (pools, first_token). Causal attention
        over the right-padded prompt — pad rows never influence rows
        < true_len — with k/v scattered into the slot's blocks."""
        cfg = self.cfg
        maxpos = cfg.max_position_embeddings

        def fn(params, pools, tokens, table, true_len, temp, seed):
            rope = None
            if self._rope is not None:
                rope = (self._rope[0][:bucket], self._rope[1][:bucket])
            pos_ids = None
            if "wpe" in params["embed"]:
                pos_ids = jnp.minimum(jnp.arange(bucket), maxpos - 1)[None]
            x = M.apply_embedding(params["embed"], tokens, cfg,
                                  compute_dtype=self.compute_dtype,
                                  position_ids=pos_ids)

            def sdpa_for(i, new_pools, cell):
                def sdpa(q, k, v, *, causal=True):
                    cell["k"] = scatter_prefill(new_pools[i]["k"], k[0],
                                                table)
                    cell["v"] = scatter_prefill(new_pools[i]["v"], v[0],
                                                table)
                    return M.xla_sdpa(q, k, v, causal=causal)

                return sdpa

            new_pools, logits = self._layer_stack(params, pools, x, rope,
                                                  sdpa_for)
            last = jax.lax.dynamic_slice_in_dim(
                logits[0], true_len - 1, 1, axis=0)  # [1, V]
            tok = self._sample(
                last, jnp.asarray([temp], jnp.float32),
                jnp.asarray([seed], jnp.int32),
                jnp.zeros((1,), jnp.int32))
            return new_pools, tok[0]

        return self._jit(fn, n_extra=5)

    def _build_decode(self):
        """(params, pools, tokens [S], pos [S], tables [S, MB], temps [S],
        seeds [S], gen_idx [S]) -> (pools, next_tokens [S]). One fixed
        shape for any mix of live/retired lanes."""
        from hetu_galvatron_tpu.models.generate import _embed_at

        cfg = self.cfg
        S = self.S
        bs = self.kv.block_size

        def fn(params, pools, tokens, pos, tables, temps, seeds, gen_idx):
            # per-lane positions: the offline decode-step embedding with a
            # zero shift vector (scheduler admission guarantees pos stays
            # inside max_position_embeddings; parked lanes sit at 0)
            x = _embed_at(params["embed"], tokens, pos, cfg,
                          self.compute_dtype, shift=jnp.zeros_like(pos))
            rope = None
            if self._rope is not None:
                rope = (self._rope[0][pos][:, None],
                        self._rope[1][pos][:, None])
            blks = tables[jnp.arange(S), pos // bs]
            offs = pos % bs

            def sdpa_for(i, new_pools, cell):
                def sdpa(q, k, v, *, causal=True):
                    pk = scatter_token(new_pools[i]["k"], k[:, 0], blks, offs)
                    pv = scatter_token(new_pools[i]["v"], v[:, 0], blks, offs)
                    cell["k"], cell["v"] = pk, pv
                    ck = gather_pages(pk, tables)
                    cv = gather_pages(pv, tables)
                    return paged_sdpa(q, ck, cv, pos)

                return sdpa

            new_pools, logits = self._layer_stack(params, pools, x, rope,
                                                  sdpa_for)
            toks = self._sample(logits[:, 0], temps, seeds, gen_idx)
            return new_pools, toks

        return self._jit(fn, n_extra=6)

    def _build_prefix_prefill(self, bucket: int):
        """(params, pools, tokens [1, bucket], full_table [MB], ctx,
        true_len, temp, seed) -> (pools, first_token). The shared-prefix
        suffix prefill: queries are the UNCACHED suffix tokens at absolute
        positions ctx..ctx+bucket-1; keys are the slot's whole assembled
        page table (the cached prefix + the suffix being written), masked
        per row — bit-identical to having prefilled the whole prompt
        (``paged_sdpa_window`` mirrors the decode/prefill arithmetic).
        Pad lanes past the per-sequence table capacity write to scratch
        (a pow-of-two bucket may overshoot the capacity a deep prefix
        leaves)."""
        cfg = self.cfg
        maxpos = cfg.max_position_embeddings
        bs = self.kv.block_size
        MB = self.kv.max_blocks_per_seq

        def fn(params, pools, tokens, table, ctx, true_len, temp, seed):
            rope = None
            if self._rope is not None:
                rope = (
                    jax.lax.dynamic_slice_in_dim(self._rope[0], ctx, bucket),
                    jax.lax.dynamic_slice_in_dim(self._rope[1], ctx, bucket))
            pos_ids = None
            if "wpe" in params["embed"]:
                pos_ids = jnp.minimum(ctx + jnp.arange(bucket),
                                      maxpos - 1)[None]
            x = M.apply_embedding(params["embed"], tokens, cfg,
                                  compute_dtype=self.compute_dtype,
                                  position_ids=pos_ids)
            idx = ctx // bs + jnp.arange(bucket // bs)
            sblocks = jnp.where(idx < MB, table[jnp.minimum(idx, MB - 1)],
                                SCRATCH_BLOCK)

            def sdpa_for(i, new_pools, cell):
                def sdpa(q, k, v, *, causal=True):
                    pk = scatter_prefill(new_pools[i]["k"], k[0], sblocks)
                    pv = scatter_prefill(new_pools[i]["v"], v[0], sblocks)
                    cell["k"], cell["v"] = pk, pv
                    ck = gather_pages(pk, table[None])
                    cv = gather_pages(pv, table[None])
                    return paged_sdpa_window(q, ck, cv, ctx)

                return sdpa

            new_pools, logits = self._layer_stack(params, pools, x, rope,
                                                  sdpa_for)
            last = jax.lax.dynamic_slice_in_dim(
                logits[0], true_len - 1, 1, axis=0)  # [1, V]
            tok = self._sample(
                last, jnp.asarray([temp], jnp.float32),
                jnp.asarray([seed], jnp.int32),
                jnp.zeros((1,), jnp.int32))
            return new_pools, tok[0]

        return self._jit(fn, n_extra=6)

    def _build_verify(self):
        """(params, pools, tokens [S, K+1], pos [S], tables [S, MB],
        temps [S], seeds [S], gen_idx [S], limit [S]) -> (pools,
        targets [S, K+1]). The speculative window: lane s's tokens are
        [last_token, draft_1..draft_K] at positions pos..pos+K; row j's
        target is what the model emits AFTER seeing the drafts before j —
        the same arithmetic as j+1 sequential decode steps. Writes past a
        lane's position budget (``limit``) land on the scratch block;
        rejected drafts leave garbage k/v beyond the accepted point that
        the position mask hides until a later step overwrites it (the
        standard retired-lane contract). The [S, K+1] embedding below
        mirrors ``models/generate._embed_at`` (same op order: wte gather,
        wpe add, embedding norm, gemma scale, cast)."""
        cfg = self.cfg
        S = self.S
        K1 = int(self.serving.spec_k) + 1
        bs = self.kv.block_size
        tl = self._table_len
        maxpos = cfg.max_position_embeddings

        def fn(params, pools, tokens, pos, tables, temps, seeds, gen_idx,
               limit):
            p_j = pos[:, None] + jnp.arange(K1)[None, :]  # [S, K1] abs pos
            pc = jnp.minimum(p_j, tl - 1)
            x = jnp.take(params["embed"]["wte"], tokens, axis=0)
            if "wpe" in params["embed"]:
                x = x + jnp.take(params["embed"]["wpe"],
                                 jnp.minimum(p_j, maxpos - 1), axis=0)
            if "ln" in params["embed"]:
                x = M.apply_norm(params["embed"]["ln"], x, cfg)
            if cfg.scale_embeddings:
                x = x * jnp.sqrt(
                    jnp.float32(cfg.hidden_size)).astype(x.dtype)
            x = x.astype(self.compute_dtype)
            rope = None
            if self._rope is not None:
                rope = (self._rope[0][pc], self._rope[1][pc])
            write_ok = p_j <= limit[:, None]
            blks = jnp.where(
                write_ok, tables[jnp.arange(S)[:, None], pc // bs],
                SCRATCH_BLOCK)
            offs = pc % bs

            def sdpa_for(i, new_pools, cell):
                def sdpa(q, k, v, *, causal=True):
                    pk = scatter_window(new_pools[i]["k"], k, blks, offs)
                    pv = scatter_window(new_pools[i]["v"], v, blks, offs)
                    cell["k"], cell["v"] = pk, pv
                    ck = gather_pages(pk, tables)
                    cv = gather_pages(pv, tables)
                    return paged_sdpa_window(q, ck, cv, pos)

                return sdpa

            new_pools, logits = self._layer_stack(params, pools, x, rope,
                                                  sdpa_for)
            outs = [self._sample(logits[:, j], temps, seeds, gen_idx + j)
                    for j in range(K1)]
            return new_pools, jnp.stack(outs, axis=1)

        return self._jit(fn, n_extra=7)

    def _build_cow(self):
        """(params, pools, src, dst) -> (pools, 0): duplicate one block in
        every layer's k/v pool — the copy-on-write a fully-cached prompt
        needs before its bootstrap decode step rewrites the last prompt
        position (which lives in a SHARED block)."""

        def fn(params, pools, src, dst):
            out = [{"k": copy_block(pl["k"], src, dst),
                    "v": copy_block(pl["v"], src, dst)} for pl in pools]
            return out, jnp.zeros((), jnp.int32)

        return self._jit(fn, n_extra=2)

    def compile_count(self) -> int:
        """Total compiled-program count across decode/verify/copy-block +
        prefill and prefix-prefill buckets (tests pin this flat across
        steady state)."""
        fns = ([self._decode_fn] + list(self._prefill_fns.values())
               + list(self._prefix_fns.values()))
        if self._verify_fn is not None:
            fns.append(self._verify_fn)
        if self._cow_fn is not None:
            fns.append(self._cow_fn)
        return sum(f._cache_size() for f in fns)

    def step_jaxprs(self, bucket: Optional[int] = None) -> Dict[str, Any]:
        """ClosedJaxprs of every program family in the token-latency path
        — decode, one prefill bucket, and (when enabled) the
        prefix-prefill bucket and the speculative verify window — the
        static-analysis hook (``analysis/census.py`` censuses them for
        host callbacks / unmarked collectives). Tracing only: nothing
        executes, the donated pools are untouched, and the traced programs
        land in the normal jit caches."""
        if bucket is None:
            bucket = default_buckets(self.kv.block_size, self._table_len)[0]
        prefill = self._prefill_for(bucket)
        table = np.zeros((bucket // self.kv.block_size,), np.int32)
        pre_args = (self.params, self.kv.pools,
                    jnp.zeros((1, bucket), jnp.int32), jnp.asarray(table),
                    1, 0.0, 0)
        state = self.scheduler.decode_state()
        dec_args = (self.params, self.kv.pools,
                    jnp.asarray(state["tokens"], jnp.int32),
                    jnp.asarray(state["pos"], jnp.int32),
                    jnp.asarray(state["tables"], jnp.int32),
                    jnp.asarray(state["temps"], jnp.float32),
                    jnp.asarray(state["seeds"], jnp.int32),
                    jnp.asarray(state["gen_idx"], jnp.int32))
        out = {f"prefill_{bucket}": jax.make_jaxpr(prefill)(*pre_args),
               "decode": jax.make_jaxpr(self._decode_fn)(*dec_args)}
        if self.prefix is not None:
            fnp = self._prefix_prefill_for(bucket)
            full = jnp.zeros((self.kv.max_blocks_per_seq,), jnp.int32)
            ppre_args = (self.params, self.kv.pools,
                         jnp.zeros((1, bucket), jnp.int32), full, 0, 1,
                         0.0, 0)
            out[f"prefix_prefill_{bucket}"] = \
                jax.make_jaxpr(fnp)(*ppre_args)
        if self._verify_fn is not None:
            K1 = int(self.serving.spec_k) + 1
            ver_args = (self.params, self.kv.pools,
                        jnp.zeros((self.S, K1), jnp.int32),
                        jnp.asarray(state["pos"], jnp.int32),
                        jnp.asarray(state["tables"], jnp.int32),
                        jnp.asarray(state["temps"], jnp.float32),
                        jnp.asarray(state["seeds"], jnp.int32),
                        jnp.asarray(state["gen_idx"], jnp.int32),
                        jnp.asarray(state["limit"], jnp.int32))
            out["verify"] = jax.make_jaxpr(self._verify_fn)(*ver_args)
        return out

    def warmup(self, buckets: Optional[List[int]] = None) -> None:
        """Pre-compile every program traffic can reach — the decode (or,
        under spec decode, verify) step, the given prefill buckets
        (defaults to every power-of-two bucket up to the pool's
        per-sequence capacity), their prefix-prefill twins, and the
        copy-on-write block duplicator — so steady state never compiles.
        Dummy runs write only the scratch block, so a warm engine is
        still empty."""
        if buckets is None:
            buckets = default_buckets(self.kv.block_size, self._table_len)
        for b in buckets:
            fn = self._prefill_for(b)
            table = np.zeros((b // self.kv.block_size,), np.int32)
            args = (self.params, self.kv.pools,
                    jnp.zeros((1, b), jnp.int32),
                    jnp.asarray(table), 1, 0.0, 0)
            # record the bucket's XLA flops/bytes here, off the request
            # path: the one-shot lower() is a full retrace, and TTFT must
            # never pay it (BEFORE the call — the program donates pools)
            maybe_record_jit_cost(f"serve/prefill_{b}", fn, args,
                                  registry=self.registry)
            new_pools, tok = fn(*args)
            self.kv.pools = new_pools
            jax.block_until_ready(tok)
            if self.prefix is not None:
                fnp = self._prefix_prefill_for(b)
                full = jnp.zeros((self.kv.max_blocks_per_seq,), jnp.int32)
                pargs = (self.params, self.kv.pools,
                         jnp.zeros((1, b), jnp.int32), full, 0, 1, 0.0, 0)
                maybe_record_jit_cost(f"serve/prefix_prefill_{b}", fnp,
                                      pargs, registry=self.registry)
                new_pools, tok = fnp(*pargs)
                self.kv.pools = new_pools
                jax.block_until_ready(tok)
        if self.prefix is not None:
            self._cow_copy(SCRATCH_BLOCK, SCRATCH_BLOCK)
        state = self.scheduler.decode_state()
        if self._draft is not None:
            # both step programs: verify drives greedy lanes; a step
            # whose live lanes are ALL sampled (which never speculate)
            # falls back to the cheaper plain decode
            drafted = [[0] * int(self.serving.spec_k)
                       for _ in range(self.S)]
            toks = self._run_decode(state, drafted=drafted)
            del toks
        toks = self._run_decode(state)
        del toks

    # -- zero-downtime weight swap ------------------------------------------

    def swap_weights(self, new_params: Params) -> float:
        """Hot-swap the serving checkpoint without dropping a request.

        Double-buffered: the new tree is validated (same structure,
        shapes, dtypes — :class:`WeightSwapError` otherwise), staged onto
        the devices under the engine's existing shardings, and fully
        materialized OFF the serving lock, so for a moment both
        checkpoints are resident (the HBM headroom a swap needs). Only
        the pointer flip and the prefix-cache invalidation hold the lock
        — the TTFT/ITL blip is bounded by one in-flight engine step plus
        that flip, and is reported as ``serve/swap_stall_ms``.

        Contract mid-swap: in-flight requests keep their KV (computed
        under the old weights) and finish decoding under the new ones —
        the standard mixed-context rollout semantics; nothing is dropped
        or recomputed. Requests admitted after the swap run entirely
        under the new checkpoint and bit-match a cold engine serving it:
        the radix prefix cache is invalidated at the flip (old-weight k/v
        must never splice into new-weight prefills), and the jitted
        programs are untouched — same shapes, same shardings, zero
        recompiles. Returns the lock-held stall in milliseconds."""
        def sig(t):
            return (tuple(t.shape), jnp.result_type(t))

        try:
            mismatch = jax.tree.map(
                lambda old, new: sig(old) != sig(new),
                self.params, new_params)
        except (ValueError, TypeError, KeyError) as e:
            raise WeightSwapError(
                f"new checkpoint's tree structure differs from the "
                f"serving model's: {e}") from e
        if any(jax.tree.leaves(mismatch)):
            raise WeightSwapError(
                "new checkpoint's shapes/dtypes differ from the serving "
                "model's — a hot swap may only replace values; start a "
                "new engine for a different architecture")
        # stage OFF-lock: place under the plan shardings (or on-device)
        # and block until materialized, so the lock-held flip is a
        # pointer move, never a transfer
        if self.mesh is not None:
            from hetu_galvatron_tpu.parallel.spmd import shard_params

            staged = shard_params(new_params, self._pspecs, self.mesh)
        else:
            staged = jax.tree.map(jnp.asarray, new_params)
        jax.block_until_ready(staged)
        t0 = time.perf_counter()
        with self._lock:
            self.params = staged
            dropped = 0
            if self.prefix is not None:
                dropped = self.prefix.invalidate()
            stall_ms = (time.perf_counter() - t0) * 1000.0
            self.registry.counter("serve/weight_swaps").inc()
            self.registry.histogram("serve/swap_stall_ms").observe(stall_ms)
            self.events.emit("weight_swap", stall_ms=stall_ms,
                             prefix_blocks_dropped=dropped)
        return stall_ms

    # -- the serving loop ---------------------------------------------------

    def submit(
        self,
        tokens: List[int],
        *,
        max_new_tokens: Optional[int] = None,
        temperature: Optional[float] = None,
        eos_id: Optional[int] = "default",
        seed: int = 0,
        timeout_s: Optional[float] = None,
    ) -> RequestHandle:
        s = self.serving
        req = Request(
            tokens=[int(t) for t in tokens],
            max_new_tokens=int(max_new_tokens if max_new_tokens is not None
                               else s.max_new_tokens),
            temperature=float(temperature if temperature is not None
                              else s.temperature),
            eos_id=s.eos_id if eos_id == "default" else eos_id,
            seed=int(seed),
            timeout_s=float(timeout_s if timeout_s is not None
                            else s.request_timeout_s),
        )
        with self._lock:
            self.registry.counter("serve/requests_submitted").inc()
            if self.error is not None:
                # dead engine thread: resolve immediately rather than
                # queueing work nothing will ever step
                handle = RequestHandle(req)
                handle._finish("error", f"engine error: {self.error}")
                self.registry.counter("serve/requests_rejected").inc()
                self.events.emit("submit", req.rid,
                                 prompt_len=len(req.tokens),
                                 max_new=req.max_new_tokens)
                self.events.emit("retire", req.rid, status="error",
                                 reason="engine dead", generated=0)
                return handle
            handle = self.scheduler.submit(req)
            if handle.status == "rejected":
                self.registry.counter("serve/requests_rejected").inc()
            return handle

    def step(self) -> bool:
        """One engine iteration: sweep retirements, admit + prefill the
        uncached suffixes (fully-cached prompts dispatch NO prefill — the
        decode step below produces their first token), one decode/verify
        step. Returns whether any work happened."""
        with self._lock:
            did = self._sweep() > 0
            admitted = self.scheduler.admit()
            for slot, bucket in admitted:
                h = slot.handle
                if h.admitted_t is not None:
                    self.registry.histogram("serve/queue_wait_ms").observe(
                        (h.admitted_t - h.submitted_t) * 1000.0)
                if slot.cached_len:
                    self.registry.counter("serve/prefix_hits").inc()
                    self.registry.counter("serve/prefix_cached_tokens").inc(
                        slot.cached_len)
                if slot.cow is not None:
                    self._cow_copy(*slot.cow)
                    slot.cow = None
                if bucket:
                    self._prefill_slot(slot, bucket)
                self.scheduler.note_prefilled(slot)
                did = True
            if self.scheduler.slots:
                self._decode_active()
                did = True
            if did:
                # idle iterations advance nothing: a parked background
                # engine must not flush duplicate snapshots forever
                self._steps += 1
                self._telemetry_step()
        return did

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        for _ in range(max_steps):
            if not self.scheduler.has_work():
                break
            self.step()
        self.flush()

    def start(self) -> None:
        """Background serving thread (idle-spins gently when no work). A
        step that raises aborts every in-flight and queued request with
        status "error" — handles must never block forever on a dead
        engine thread."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    did = self.step()
                except Exception as e:  # noqa: BLE001 — must resolve handles
                    self._abort(e)
                    return
                if not did:
                    time.sleep(0.001)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="serving-engine")
        self._thread.start()

    def _abort(self, exc: BaseException) -> None:
        """Resolve every outstanding handle after a fatal engine error.
        Every retirement is attributed (``serve/errors`` labelled with the
        exception class, retire events per request) and the flight
        recorder dumps a postmortem — dump() never raises, so the real
        fault always reaches ``self.error`` / the caller untouched."""
        self.error = exc
        with self._lock:
            self.registry.counter("serve/engine_errors").inc()
            self.registry.counter("serve/errors",
                                  error=type(exc).__name__).inc()
            self.events.emit("engine_error", error=type(exc).__name__,
                             message=str(exc))
            for slot in list(self.scheduler.slots.values()):
                self.scheduler.retire(slot, "error", f"engine error: {exc}")
            for h in self.scheduler.waiting:
                h._finish("error", f"engine error: {exc}")
                self.events.emit("retire", h.request.rid, status="error",
                                 reason="engine error", generated=0,
                                 queued=True)
            self.scheduler.waiting = []
            self.recorder.dump("engine_error", exc=exc)

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join()
        self._thread = None
        self.flush()

    # -- internals ----------------------------------------------------------

    def _sweep(self) -> int:
        """Retire cancelled/expired work — active slots AND still-queued
        requests (both count toward the cancel/timeout metrics, so
        submitted == completed + rejected + cancelled + timeout)."""
        now = time.monotonic()
        sc, st = self.scheduler.sweep(now)
        wc, wt = self.scheduler.sweep_waiting(now)
        if sc + wc:
            self.registry.counter("serve/requests_cancelled").inc(sc + wc)
        if st + wt:
            self.registry.counter("serve/requests_timeout").inc(st + wt)
        return sc + st + wc + wt

    def _prefill_for(self, bucket: int) -> Callable:
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = self._build_prefill(bucket)
            self._prefill_fns[bucket] = fn
        return fn

    def _prefix_prefill_for(self, bucket: int) -> Callable:
        fn = self._prefix_fns.get(bucket)
        if fn is None:
            fn = self._build_prefix_prefill(bucket)
            self._prefix_fns[bucket] = fn
        return fn

    def _cow_copy(self, src: int, dst: int) -> None:
        if self._cow_fn is None:
            self._cow_fn = self._build_cow()
        new_pools, _ = self._cow_fn(self.params, self.kv.pools, src, dst)
        self.kv.pools = new_pools

    def _prefill_slot(self, slot: Slot, bucket: int) -> None:
        t0 = time.perf_counter()
        req = slot.request
        prompt_len = len(req.tokens)
        cached = slot.cached_len
        suffix = req.tokens[cached:]
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(suffix)] = suffix
        if cached:
            fn = self._prefix_prefill_for(bucket)
            name = f"serve/prefix_prefill_{bucket}"
            full = jnp.asarray(self.scheduler.padded_table(slot.blocks),
                               jnp.int32)
            args = (self.params, self.kv.pools, jnp.asarray(padded),
                    full, cached, len(suffix),
                    float(req.temperature), int(req.seed))
        else:
            table = np.asarray(slot.blocks[: bucket // self.kv.block_size],
                               np.int32)
            fn = self._prefill_for(bucket)
            name = f"serve/prefill_{bucket}"
            args = (self.params, self.kv.pools, jnp.asarray(padded),
                    jnp.asarray(table), prompt_len,
                    float(req.temperature), int(req.seed))
        # fallback for buckets warmup() never covered — warmed buckets
        # were recorded there, so this early-outs to a set lookup and the
        # request path never pays the lower() retrace (BEFORE the call —
        # the program donates the pools)
        maybe_record_jit_cost(name, fn, args, registry=self.registry)
        new_pools, tok = fn(*args)
        self.kv.pools = new_pools
        tok = int(np.asarray(tok))
        # dispatch-to-sync host wall for this slot's prefill: the TTFT
        # component split in _emit (queue + prefill + decode == ttft)
        # reads it, so set it BEFORE the first-token emit below
        slot.prefill_ms = (time.perf_counter() - t0) * 1000.0
        self.registry.counter("serve/prefill_tokens").inc(len(suffix))
        self.events.emit("prefill", req.rid, bucket=bucket,
                         suffix=len(suffix), cached=cached,
                         ms=slot.prefill_ms)
        self._emit(slot, tok, first=True)

    def _run_decode(self, state, drafted=None) -> np.ndarray:
        if drafted is None:
            fn, name = self._decode_fn, "serve/decode"
            args = (self.params, self.kv.pools,
                    jnp.asarray(state["tokens"], jnp.int32),
                    jnp.asarray(state["pos"], jnp.int32),
                    jnp.asarray(state["tables"], jnp.int32),
                    jnp.asarray(state["temps"], jnp.float32),
                    jnp.asarray(state["seeds"], jnp.int32),
                    jnp.asarray(state["gen_idx"], jnp.int32))
        else:
            fn, name = self._verify_fn, "serve/verify"
            window = [[t] + list(d)
                      for t, d in zip(state["tokens"], drafted)]
            args = (self.params, self.kv.pools,
                    jnp.asarray(window, jnp.int32),
                    jnp.asarray(state["pos"], jnp.int32),
                    jnp.asarray(state["tables"], jnp.int32),
                    jnp.asarray(state["temps"], jnp.float32),
                    jnp.asarray(state["seeds"], jnp.int32),
                    jnp.asarray(state["gen_idx"], jnp.int32),
                    jnp.asarray(state["limit"], jnp.int32))
        maybe_record_jit_cost(name, fn, args, registry=self.registry)
        new_pools, toks = fn(*args)
        self.kv.pools = new_pools
        return np.asarray(toks)

    def _decode_active(self) -> None:
        if self._draft is not None and any(
                s.request.temperature <= 0.0
                for s in self.scheduler.slots.values()):
            # at least one greedy lane can profit from drafts; sampled
            # lanes ride along untouched (they never speculate)
            self._verify_active()
            return
        state = self.scheduler.decode_state()
        toks = self._run_decode(state)
        for slot in list(self.scheduler.slots.values()):
            slot.pos += 1
            self.events.emit("decode", slot.request.rid, pos=slot.pos, n=1)
            # a fully-cached prompt skipped prefill entirely: its FIRST
            # token comes from this decode step (TTFT records here)
            self._emit(slot, int(toks[slot.index]),
                       first=slot.generated == 0)
        self.registry.counter("serve/decode_tokens").inc(
            sum(state["active"]))

    def _verify_active(self) -> None:
        """One speculative step: draft K tokens per live lane (host-side),
        verify the whole window in one fixed-shape pass, emit the accepted
        prefix + the bonus token. Greedy lanes emit exactly the
        non-speculative stream; sampled lanes do not speculate (row 0's
        sample uses the same per-request fold_in key plain decode
        would)."""
        K = int(self.serving.spec_k)
        state = self.scheduler.decode_state()
        slots = list(self.scheduler.slots.values())
        drafted = [[0] * K for _ in range(self.S)]
        for slot in slots:
            if slot.request.temperature > 0.0:
                continue  # sampled lanes never speculate: don't pay the
                # O(context) draft scan or skew the accept-rate stats
            ctx = list(slot.request.tokens) + slot.handle.output
            prop = list(self._draft.propose(ctx, K))[:K]
            drafted[slot.index][: len(prop)] = prop
        out = self._run_decode(state, drafted=drafted)
        emitted = 0
        for slot in slots:
            req = slot.request
            row = out[slot.index].tolist()
            budget = req.max_new_tokens - slot.generated
            k_eff = (min(K, max(budget - 1, 0))
                     if req.temperature <= 0.0 else 0)
            a = accept_length(drafted[slot.index], row, k_eff)
            # accepted is the window outcome; the EMITTED count is bounded
            # by accepted+1 but can be cut short by mid-window EOS/length
            # retirement — retire.generated stays the authoritative total
            self.events.emit("verify", req.rid, drafted=k_eff, accepted=a)
            if req.temperature <= 0.0:
                self._drafted_total += K
                self._accepted_total += a
                self.registry.counter("serve/drafted_tokens").inc(K)
            if a:
                self.registry.counter("serve/spec_accepted_tokens").inc(a)
            for tok in row[: a + 1]:
                slot.pos += 1
                self._emit(slot, int(tok), first=slot.generated == 0)
                emitted += 1
                if slot.index not in self.scheduler.slots:
                    break  # retired (eos / length) mid-window
        self.registry.counter("serve/decode_tokens").inc(emitted)

    def _emit(self, slot: Slot, tok: int, first: bool = False) -> None:
        """Record one generated token: stream it, time it, retire on
        EOS / length budget."""
        req = slot.request
        now = time.monotonic()
        slot.generated += 1
        slot.last_token = tok
        h = slot.handle
        if first:
            ttft_ms = (now - h.submitted_t) * 1000.0
            self.registry.histogram("serve/ttft_ms").observe(ttft_ms)
            self._ttft_n += 1
            if ttft_ms <= self.serving.slo_ttft_ms:
                self._ttft_ok += 1
            # additive TTFT split: queue (submit -> slot granted) +
            # prefill (this slot's dispatch wall) + decode (residual —
            # fully-cached prompts bootstrap through the decode step, so
            # their whole post-admit latency lands here). Components sum
            # to the measured TTFT by construction.
            queue_ms = ((h.admitted_t - h.submitted_t) * 1000.0
                        if h.admitted_t is not None else 0.0)
            self.events.emit(
                "first_token", req.rid, ttft_ms=ttft_ms, queue_ms=queue_ms,
                prefill_ms=slot.prefill_ms,
                decode_ms=max(ttft_ms - queue_ms - slot.prefill_ms, 0.0))
        else:
            itl_ms = (now - slot.last_token_t) * 1000.0
            self.registry.histogram("serve/itl_ms").observe(itl_ms)
            self._itl_n += 1
            if itl_ms <= self.serving.slo_itl_ms:
                self._itl_ok += 1
        slot.last_token_t = now
        slot.handle._emit(tok)
        self._emitted_total += 1
        if req.eos_id is not None and tok == req.eos_id:
            self.scheduler.retire(slot, "done", "eos")
            self.registry.counter("serve/requests_completed").inc()
        elif slot.generated >= req.max_new_tokens:
            self.scheduler.retire(slot, "done", "length")
            self.registry.counter("serve/requests_completed").inc()

    # -- telemetry ----------------------------------------------------------

    def _telemetry_step(self) -> None:
        reg = self.registry
        reg.counter("serve/steps").inc()
        if self.metrics_server is not None:
            self.metrics_server.note_step()  # /healthz last-step age
        now = time.monotonic()
        self._emitted_window.append((now, self._emitted_total))
        if len(self._emitted_window) > 64:
            self._emitted_window = self._emitted_window[-64:]
        if self._steps % max(self.serving.flush_interval, 1) == 0:
            self.flush()

    def tokens_per_sec(self) -> float:
        w = self._emitted_window
        if len(w) < 2 or w[-1][0] <= w[0][0]:
            return 0.0
        return (w[-1][1] - w[0][1]) / (w[-1][0] - w[0][0])

    def spec_accept_rate(self) -> float:
        """Fraction of drafted tokens the verify pass accepted (0 when
        spec decode is off or nothing was drafted yet)."""
        if not self._drafted_total:
            return 0.0
        return self._accepted_total / self._drafted_total

    def defrag(self) -> None:
        """Compact live pool blocks to the low indices (pool-shrink /
        snapshot): delegates to the scheduler, which rewrites every
        referencing table — active sequences AND radix prefix nodes."""
        with self._lock:
            self.scheduler.defrag()

    def flush(self) -> None:
        reg = self.registry
        reg.gauge("serve/queue_depth").set(self.scheduler.queue_depth)
        reg.gauge("serve/active_requests").set(len(self.scheduler.slots))
        reg.gauge("serve/kv_occupancy").set(self.kv.occupancy)
        reg.gauge("serve/kv_blocks_used").set(self.kv.allocator.used)
        reg.gauge("serve/tokens_per_sec").set(self.tokens_per_sec())
        reg.gauge("serve/jit_programs").set(self.compile_count())
        if self.prefix is not None:
            reg.gauge("serve/prefix_hit_rate").set(self.prefix.hit_rate)
            reg.gauge("serve/prefix_cache_blocks").set(
                self.prefix.blocks_held)
        if self._draft is not None:
            reg.gauge("serve/spec_accept_rate").set(self.spec_accept_rate())
        # SLO attainment (serving.slo_ttft_ms / slo_itl_ms > 0): share of
        # observations inside the target, exported for the Prometheus
        # endpoint and the summarize SLO report
        if self.serving.slo_ttft_ms > 0:
            reg.gauge("serve/slo_ttft_ms").set(self.serving.slo_ttft_ms)
            reg.gauge("serve/slo_ttft_attainment").set(
                self._ttft_ok / self._ttft_n if self._ttft_n else 1.0)
        if self.serving.slo_itl_ms > 0:
            reg.gauge("serve/slo_itl_ms").set(self.serving.slo_itl_ms)
            reg.gauge("serve/slo_itl_attainment").set(
                self._itl_ok / self._itl_n if self._itl_n else 1.0)
        reg.flush(step=self._steps)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.stop()
        self.flush()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
