"""Continuous-batching scheduler: admission, slots, retirement, recycling.

The serving engine runs two jitted programs — bucketed prefill and a
fixed-shape decode step — and this module decides what feeds them:

* **Admission** is token-budget based. A request is admitted when (1) a
  decode slot is free, (2) the paged KV pool can hold its whole budget
  (prompt + ``max_new_tokens`` — allocated up front so a running sequence
  can never OOM the pool mid-decode), and (3) the step's prefill budget
  has room. The budget is expressed in FLOPs via the cost model's
  per-token accounting (``core/cost_model/cost.py model_flops_per_token``,
  forward-only), so "how much prefill can ride one engine step without
  starving decode" is the same arithmetic the search engine trusts.
  Requests that can NEVER be served (longer than the pool / the model's
  positions) are rejected immediately, not queued forever.
* **Slots** are fixed: ``max_batch_size`` sequences decode together at one
  jitted shape. Retired slots park on the scratch block and recycle on the
  next admission — no recompiles in steady state.
* **Retirement** is per-sequence: EOS, length budget, cancellation, or
  timeout. Retirement DECREFS (never strict-frees): a retiring sequence
  drops its references and blocks the radix prefix cache co-owns stay
  resident for future shared-prefix hits.
* **Prefix-aware admission** (``prefix_cache.PrefixCache``): the cached
  block-aligned prefix of a prompt is matched copy-free into the block
  table, and only the UNCACHED suffix is charged against the prefill
  budget — a fully-cached prompt charges nothing, dispatches no prefill,
  and bootstraps its first token through the regular decode step (its
  last prompt position's block is copy-on-write duplicated so the decode
  write never touches a shared block).

Prompt lengths are bucketed to ``block_size * 2^k`` so the set of prefill
programs is logarithmic in the max prompt length.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from hetu_galvatron_tpu.serving.kv_cache import PagedKVCache, SCRATCH_BLOCK
from hetu_galvatron_tpu.serving.prefix_cache import PrefixCache

_req_counter = itertools.count()

# terminal states a handle can land in
FINISHED = ("done", "cancelled", "timeout", "rejected", "error")


@dataclass
class Request:
    """One generation request. ``seed`` drives the per-request sampling
    stream (folded with the emitted-token index), so a request's tokens do
    not depend on which neighbors share its batch."""

    tokens: List[int]
    max_new_tokens: int = 64
    temperature: float = 0.0
    eos_id: Optional[int] = None
    seed: int = 0
    timeout_s: float = 0.0  # 0 = no deadline
    rid: int = field(default_factory=lambda: next(_req_counter))


class RequestHandle:
    """Caller-facing stream for one request.

    ``tokens()`` yields generated ids as they are produced (blocking
    iterator, ends at retirement); ``result()`` waits for completion and
    returns the full list; ``cancel()`` asks the engine to retire the
    request at the next step boundary.
    """

    _SENTINEL = object()

    def __init__(self, request: Request):
        self.request = request
        self.status = "queued"
        self.finish_reason: Optional[str] = None
        self.cached_tokens = 0  # prompt tokens served by the prefix cache
        self.output: List[int] = []
        self.submitted_t = time.monotonic()
        self.admitted_t: Optional[float] = None  # slot granted (queue end)
        self.first_token_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self._q: "queue.Queue[Any]" = queue.Queue()
        self._done = threading.Event()
        self._cancel = False

    # -- engine side --------------------------------------------------------

    def _emit(self, token: int) -> None:
        now = time.monotonic()
        if self.first_token_t is None:
            self.first_token_t = now
        self.output.append(int(token))
        self._q.put(int(token))

    def _finish(self, status: str, reason: str) -> None:
        self.status = status
        self.finish_reason = reason
        self.finished_t = time.monotonic()
        self._q.put(self._SENTINEL)
        self._done.set()

    # -- caller side --------------------------------------------------------

    @property
    def cancelled(self) -> bool:
        return self._cancel

    def cancel(self) -> None:
        self._cancel = True

    def done(self) -> bool:
        return self._done.is_set()

    def tokens(self):
        """Blocking per-token stream; terminates when the request retires.
        Safe to call again after the stream drained (returns immediately
        instead of blocking on the already-consumed sentinel)."""
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                if self._done.is_set():
                    return
                continue
            if item is self._SENTINEL:
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.request.rid} still running")
        return list(self.output)

    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submitted_t


@dataclass
class Slot:
    """One decode lane: the sequence occupying it plus its paged-cache
    view. ``pos`` is the context length (tokens already in the cache);
    ``last_token`` is the next decode step's input.

    Prefix-cache bookkeeping: ``blocks`` is the TABLE view (shared prefix
    blocks + private blocks); ``owned_blocks`` are the ones this sequence
    allocated (decref'd at retirement — shared blocks are pinned via
    ``prefix_path`` instead). ``cached_len`` prompt tokens were served
    from the cache; ``cow`` asks the engine to copy one block
    (src, dst) before the slot's first decode step; ``limit`` is the last
    absolute position this sequence may ever write (spec-decode windows
    mask writes past it at the scratch block)."""

    index: int
    handle: RequestHandle
    blocks: List[int]
    pos: int
    last_token: int
    generated: int = 0
    last_token_t: float = 0.0
    prefill_ms: float = 0.0  # host wall of this slot's prefill dispatch
    cached_len: int = 0
    owned_blocks: List[int] = field(default_factory=list)
    shared_blocks: List[int] = field(default_factory=list)
    prefix_path: Tuple = ()
    cow: Optional[Tuple[int, int]] = None
    limit: int = 0

    @property
    def request(self) -> Request:
        return self.handle.request


def bucket_length(prompt_len: int, block_size: int,
                  cap_tokens: int) -> int:
    """Smallest ``block_size * 2^k`` >= prompt_len (capped at the pool's
    per-sequence table capacity ``cap_tokens``): prefill programs exist per
    bucket, not per length, so steady-state traffic stops compiling once
    the buckets are warm."""
    b = block_size
    while b < prompt_len and b < cap_tokens:
        b *= 2
    return min(b, cap_tokens)


class Scheduler:
    """Queue + slots + allocator choreography (host-side, no jax)."""

    def __init__(
        self,
        kv: PagedKVCache,
        *,
        max_slots: int,
        max_position_embeddings: int,
        prefill_flops_budget: float = 0.0,
        flops_per_token: float = 0.0,
        max_prefill_tokens: int = 0,
        prefix_cache: Optional[PrefixCache] = None,
        events: Optional[Any] = None,
    ):
        self.kv = kv
        self.prefix = prefix_cache
        # request-lifecycle event stream (observability/events.py): the
        # scheduler emits the transitions it owns — submit, admit (incl.
        # the cold-retry livelock fallback), retire — with the stable
        # Request.rid; None degrades every emit to a no-op
        self.events = events
        self.max_slots = int(max_slots)
        self.max_positions = int(max_position_embeddings)
        # per-step prefill token budget: the tighter of the explicit token
        # cap and the FLOPs budget / cost-model per-token FLOPs
        caps = []
        if max_prefill_tokens > 0:
            caps.append(max_prefill_tokens)
        if prefill_flops_budget > 0 and flops_per_token > 0:
            caps.append(max(int(prefill_flops_budget // flops_per_token), 1))
        self.prefill_token_cap = min(caps) if caps else 0  # 0 = unlimited
        self.waiting: List[RequestHandle] = []
        self.slots: Dict[int, Slot] = {}
        self._free_slots = list(range(self.max_slots - 1, -1, -1))
        self.rejected = 0
        self.completed = 0

    # -- intake -------------------------------------------------------------

    def _blocks_needed(self, prompt_len: int, max_new: int) -> int:
        """Blocks a request holds while running: its total-length need or
        its prefill bucket, whichever is larger (the bucket may overshoot
        ceil(total/bs) for prompts just past a power-of-two boundary)."""
        bucket = bucket_length(
            prompt_len, self.kv.block_size,
            self.kv.max_blocks_per_seq * self.kv.block_size)
        return max(self.kv.blocks_for(prompt_len + max_new),
                   bucket // self.kv.block_size)

    def _emit(self, ev: str, rid: int, **fields) -> None:
        if self.events is not None:
            self.events.emit(ev, rid, **fields)

    def submit(self, request: Request) -> RequestHandle:
        handle = RequestHandle(request)
        self._emit("submit", request.rid, prompt_len=len(request.tokens),
                   max_new=request.max_new_tokens)
        total = len(request.tokens) + request.max_new_tokens
        if (not request.tokens or request.max_new_tokens < 1
                or not self.kv.fits(total)
                or total > self.max_positions
                # can NEVER be satisfied even by an empty pool -> reject
                # now instead of queueing forever
                or (self._blocks_needed(len(request.tokens),
                                        request.max_new_tokens)
                    > self.kv.num_blocks - 1)):
            self.rejected += 1
            handle._finish("rejected", "capacity")
            self._emit("retire", request.rid, status="rejected",
                       reason="capacity", generated=0)
            return handle
        handle.status = "queued"
        self.waiting.append(handle)
        return handle

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def active(self) -> List[Slot]:
        return [self.slots[i] for i in sorted(self.slots)]

    def has_work(self) -> bool:
        return bool(self.waiting or self.slots)

    # -- admission ----------------------------------------------------------

    def _alloc_or_evict(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` blocks, evicting cold radix nodes LRU-first when
        the free list alone cannot satisfy the request (active sequences'
        blocks are pinned and never touched)."""
        blocks = self.kv.allocator.alloc(n)
        if blocks is None and self.prefix is not None:
            self.prefix.evict(n - self.kv.allocator.available)
            blocks = self.kv.allocator.alloc(n)
        return blocks

    def _need_for(self, prompt_len: int, max_new: int, cached_len: int,
                  bucket: int) -> int:
        """Private blocks an admission must allocate on top of its
        ``cached_len`` shared prefix tokens."""
        bs = self.kv.block_size
        n_cached = cached_len // bs
        total_need = self.kv.blocks_for(prompt_len + max_new)
        if prompt_len - cached_len:
            # the rest of the budget, or the suffix bucket's overshoot
            # past a power-of-two boundary — clipped at the per-sequence
            # table capacity (the prefix prefill routes bucket lanes past
            # it to scratch)
            cover = min(n_cached + bucket // bs,
                        self.kv.max_blocks_per_seq)
            return max(total_need, cover) - n_cached
        # fully cached: +1 for the copy-on-write duplicate of the block
        # holding the last prompt position (the bootstrap decode step
        # rewrites that position)
        return total_need - n_cached + 1

    def admit(self) -> List[Tuple[Slot, int]]:
        """Admit waiting requests into free slots under the block + prefill
        budget. Returns ``(slot, suffix_bucket)`` pairs: the engine must
        prefill each slot's UNCACHED prompt suffix this step
        (``suffix_bucket == 0`` means fully cached — no prefill dispatch
        at all; the slot enters decode directly and its first token comes
        from the next decode step). Only the uncached suffix is charged
        against the prefill budget. At least one request is admitted per
        call when a slot and blocks are available, even if its bucket
        exceeds the prefill cap — a cap below the smallest bucket must not
        deadlock."""
        self._drop_cancelled_waiting()
        admitted: List[Tuple[Slot, int]] = []
        budget_used = 0
        cap_tokens = self.kv.max_blocks_per_seq * self.kv.block_size
        bs = self.kv.block_size
        while self.waiting and self._free_slots:
            handle = self.waiting[0]
            req = handle.request
            prompt_len = len(req.tokens)
            cached_len, shared, path = 0, [], ()
            if self.prefix is not None:
                cached_len, shared, path = self.prefix.match(req.tokens)
                if not getattr(handle, "_prefix_counted", False):
                    # stats once per REQUEST: a deferred head-of-queue
                    # request is re-matched every step and must not
                    # inflate the hit-rate gauge on each retry
                    handle._prefix_counted = True
                    self.prefix.note_lookup(cached_len)
            suffix = prompt_len - cached_len
            bucket = bucket_length(suffix, bs, cap_tokens) if suffix else 0
            if self.prefill_token_cap and admitted and bucket and (
                    budget_used + bucket > self.prefill_token_cap):
                if path:
                    self.prefix.release(path)
                break
            need = self._need_for(prompt_len, req.max_new_tokens,
                                  cached_len, bucket)
            owned = self._alloc_or_evict(need)
            cold_retry = False
            if owned is None and path:
                # the match itself pins the path, which can make the
                # request UNADMITTABLE forever (its own cached blocks are
                # the only evictable ones) — retry as a cold request with
                # the pins dropped before concluding the pool is full
                self.prefix.release(path)
                cached_len, shared, path = 0, [], ()
                cold_retry = True
                suffix = prompt_len
                bucket = bucket_length(suffix, bs, cap_tokens)
                if self.prefill_token_cap and admitted and (
                        budget_used + bucket > self.prefill_token_cap):
                    break  # requeued; admits (cold or hit) next step
                need = self._need_for(prompt_len, req.max_new_tokens,
                                      0, bucket)
                owned = self._alloc_or_evict(need)
            if owned is None:
                if path:
                    self.prefix.release(path)
                break  # pool full; FIFO order preserved
            # the request takes its own reference on every matched block
            # (on top of the node pins), so a stray free() of a block a
            # live sequence is reading raises instead of corrupting it
            self.kv.allocator.incref(shared)
            self.waiting.pop(0)
            idx = self._free_slots.pop()
            cow = None
            if suffix:
                table = shared + owned
            else:
                cow = (shared[-1], owned[0])
                table = shared[:-1] + owned
            now = time.monotonic()
            slot = Slot(index=idx, handle=handle, blocks=table,
                        pos=prompt_len - (0 if suffix else 1),
                        last_token=req.tokens[-1],
                        last_token_t=now,
                        cached_len=cached_len, owned_blocks=owned,
                        shared_blocks=list(shared),
                        prefix_path=path, cow=cow,
                        limit=prompt_len + req.max_new_tokens - 1)
            handle.status = "running"
            handle.cached_tokens = cached_len
            handle.admitted_t = now
            self.slots[idx] = slot
            admitted.append((slot, bucket))
            budget_used += bucket
            self._emit("admit", req.rid, slot=idx,
                       queue_ms=(now - handle.submitted_t) * 1000.0,
                       cached_len=cached_len, hit_blocks=len(shared),
                       suffix=suffix, bucket=bucket, cold_retry=cold_retry)
        return admitted

    def note_prefilled(self, slot: Slot) -> List[int]:
        """Offer a freshly prefilled prompt's full blocks to the radix
        cache (the engine calls this right after the prefill dispatch).
        Returns the block ids the tree adopted (it holds its own
        references; the slot keeps decref'ing its ``owned_blocks`` at
        retirement as usual)."""
        if self.prefix is None:
            return []
        n_full = len(slot.request.tokens) // self.kv.block_size
        if n_full == 0 or slot.cached_len >= n_full * self.kv.block_size:
            return []
        return self.prefix.insert(
            slot.request.tokens[: n_full * self.kv.block_size],
            slot.blocks[:n_full])

    def _drop_cancelled_waiting(self) -> None:
        self.sweep_waiting()

    def sweep_waiting(self, now: Optional[float] = None
                      ) -> Tuple[int, int]:
        """Resolve cancelled and deadline-expired requests still in the
        queue (a request whose timeout lapsed while queued must not be
        admitted, prefilled, and only then retired — that wastes device
        work and pollutes the TTFT histogram). Returns
        ``(n_cancelled, n_timeout)``."""
        now = time.monotonic() if now is None else now
        n_cancel = n_timeout = 0
        still = []
        for h in self.waiting:
            if h.cancelled:
                h._finish("cancelled", "cancelled")
                self._emit("retire", h.request.rid, status="cancelled",
                           reason="cancelled", generated=0, queued=True)
                n_cancel += 1
            elif (h.request.timeout_s > 0
                  and now - h.submitted_t > h.request.timeout_s):
                h._finish("timeout", "timeout")
                self._emit("retire", h.request.rid, status="timeout",
                           reason="timeout", generated=0, queued=True)
                n_timeout += 1
            else:
                still.append(h)
        self.waiting = still
        return n_cancel, n_timeout

    # -- retirement ---------------------------------------------------------

    def retire(self, slot: Slot, status: str, reason: str) -> None:
        """Drop the slot's block references (decref, NOT strict free —
        blocks the radix cache adopted stay resident for future hits),
        unpin its prefix path, recycle the lane, resolve the handle."""
        self.kv.allocator.decref(slot.owned_blocks)
        if slot.shared_blocks:
            self.kv.allocator.decref(slot.shared_blocks)
        if slot.prefix_path:
            self.prefix.release(slot.prefix_path)
        del self.slots[slot.index]
        self._free_slots.append(slot.index)
        if status == "done":
            self.completed += 1
        slot.handle._finish(status, reason)
        self._emit("retire", slot.request.rid, status=status, reason=reason,
                   generated=slot.generated)

    def sweep(self, now: Optional[float] = None) -> Tuple[int, int]:
        """Retire cancelled / deadline-expired active sequences; returns
        ``(n_cancelled, n_timeout)`` — the single home of the retirement
        predicate (the engine's metric split reads these counts rather
        than re-deriving them)."""
        now = time.monotonic() if now is None else now
        n_cancel = n_timeout = 0
        for slot in list(self.slots.values()):
            h = slot.handle
            if h.cancelled:
                self.retire(slot, "cancelled", "cancelled")
                n_cancel += 1
            elif (h.request.timeout_s > 0
                  and now - h.submitted_t > h.request.timeout_s):
                self.retire(slot, "timeout", "timeout")
                n_timeout += 1
        return n_cancel, n_timeout

    # -- decode batch view --------------------------------------------------

    def padded_table(self, blocks: Sequence[int]) -> List[int]:
        t = list(blocks)[: self.kv.max_blocks_per_seq]
        return t + [SCRATCH_BLOCK] * (self.kv.max_blocks_per_seq - len(t))

    def decode_state(self) -> Dict[str, List]:
        """Fixed-shape per-lane arrays for the decode program. Inactive
        lanes feed token 0 at position 0 against the scratch block; their
        outputs are discarded host-side. ``limit`` bounds each lane's
        writable positions (the speculative verify window routes writes
        past it at the scratch block; parked lanes sit at 0 so their whole
        window lands on scratch)."""
        S, MB = self.max_slots, self.kv.max_blocks_per_seq
        state = {
            "tokens": [0] * S,
            "pos": [0] * S,
            "tables": [[SCRATCH_BLOCK] * MB for _ in range(S)],
            "temps": [0.0] * S,
            "seeds": [0] * S,
            "gen_idx": [0] * S,
            "active": [False] * S,
            "limit": [0] * S,
        }
        for i, slot in self.slots.items():
            req = slot.request
            state["tokens"][i] = slot.last_token
            state["pos"][i] = slot.pos
            state["tables"][i] = self.padded_table(slot.blocks)
            state["temps"][i] = float(req.temperature)
            state["seeds"][i] = int(req.seed)
            state["gen_idx"][i] = slot.generated
            state["active"][i] = True
            state["limit"][i] = slot.limit
        return state

    # -- maintenance --------------------------------------------------------

    def defrag(self) -> None:
        """Compact live blocks to the low pool indices, rewriting EVERY
        referencing view: each active sequence's table and ownership list
        AND every radix node's block list (a node's table is as live as a
        sequence's — a stale one would hand future hits permuted ids)."""
        slots = self.active
        tables: List[List[int]] = [list(s.blocks) for s in slots]
        tables += [list(s.owned_blocks) for s in slots]
        tables += [list(s.shared_blocks) for s in slots]
        nodes: List = []
        if self.prefix is not None:
            nodes, node_tables = self.prefix.export_tables()
            tables += node_tables
        new = self.kv.defrag(tables)
        n = len(slots)
        for s, t in zip(slots, new[:n]):
            s.blocks = t
        for s, t in zip(slots, new[n:2 * n]):
            s.owned_blocks = t
        for s, t in zip(slots, new[2 * n:3 * n]):
            s.shared_blocks = t
        if self.prefix is not None:
            self.prefix.adopt_tables(nodes, new[3 * n:])
