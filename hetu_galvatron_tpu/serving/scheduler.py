"""Continuous-batching scheduler: admission, slots, retirement, recycling.

The serving engine runs two jitted programs — bucketed prefill and a
fixed-shape decode step — and this module decides what feeds them:

* **Admission** is token-budget based. A request is admitted when (1) a
  decode slot is free, (2) the paged KV pool can hold its whole budget
  (prompt + ``max_new_tokens`` — allocated up front so a running sequence
  can never OOM the pool mid-decode), and (3) the step's prefill budget
  has room. The budget is expressed in FLOPs via the cost model's
  per-token accounting (``core/cost_model/cost.py model_flops_per_token``,
  forward-only), so "how much prefill can ride one engine step without
  starving decode" is the same arithmetic the search engine trusts.
  Requests that can NEVER be served (longer than the pool / the model's
  positions) are rejected immediately, not queued forever.
* **Slots** are fixed: ``max_batch_size`` sequences decode together at one
  jitted shape. Retired slots park on the scratch block and recycle on the
  next admission — no recompiles in steady state.
* **Retirement** is per-sequence: EOS, length budget, cancellation, or
  timeout. Freed blocks return to the allocator LIFO.

Prompt lengths are bucketed to ``block_size * 2^k`` so the set of prefill
programs is logarithmic in the max prompt length.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from hetu_galvatron_tpu.serving.kv_cache import PagedKVCache, SCRATCH_BLOCK

_req_counter = itertools.count()

# terminal states a handle can land in
FINISHED = ("done", "cancelled", "timeout", "rejected", "error")


@dataclass
class Request:
    """One generation request. ``seed`` drives the per-request sampling
    stream (folded with the emitted-token index), so a request's tokens do
    not depend on which neighbors share its batch."""

    tokens: List[int]
    max_new_tokens: int = 64
    temperature: float = 0.0
    eos_id: Optional[int] = None
    seed: int = 0
    timeout_s: float = 0.0  # 0 = no deadline
    rid: int = field(default_factory=lambda: next(_req_counter))


class RequestHandle:
    """Caller-facing stream for one request.

    ``tokens()`` yields generated ids as they are produced (blocking
    iterator, ends at retirement); ``result()`` waits for completion and
    returns the full list; ``cancel()`` asks the engine to retire the
    request at the next step boundary.
    """

    _SENTINEL = object()

    def __init__(self, request: Request):
        self.request = request
        self.status = "queued"
        self.finish_reason: Optional[str] = None
        self.output: List[int] = []
        self.submitted_t = time.monotonic()
        self.first_token_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self._q: "queue.Queue[Any]" = queue.Queue()
        self._done = threading.Event()
        self._cancel = False

    # -- engine side --------------------------------------------------------

    def _emit(self, token: int) -> None:
        now = time.monotonic()
        if self.first_token_t is None:
            self.first_token_t = now
        self.output.append(int(token))
        self._q.put(int(token))

    def _finish(self, status: str, reason: str) -> None:
        self.status = status
        self.finish_reason = reason
        self.finished_t = time.monotonic()
        self._q.put(self._SENTINEL)
        self._done.set()

    # -- caller side --------------------------------------------------------

    @property
    def cancelled(self) -> bool:
        return self._cancel

    def cancel(self) -> None:
        self._cancel = True

    def done(self) -> bool:
        return self._done.is_set()

    def tokens(self):
        """Blocking per-token stream; terminates when the request retires.
        Safe to call again after the stream drained (returns immediately
        instead of blocking on the already-consumed sentinel)."""
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                if self._done.is_set():
                    return
                continue
            if item is self._SENTINEL:
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.request.rid} still running")
        return list(self.output)

    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submitted_t


@dataclass
class Slot:
    """One decode lane: the sequence occupying it plus its paged-cache
    view. ``pos`` is the context length (tokens already in the cache);
    ``last_token`` is the next decode step's input."""

    index: int
    handle: RequestHandle
    blocks: List[int]
    pos: int
    last_token: int
    generated: int = 0
    last_token_t: float = 0.0

    @property
    def request(self) -> Request:
        return self.handle.request


def bucket_length(prompt_len: int, block_size: int,
                  cap_tokens: int) -> int:
    """Smallest ``block_size * 2^k`` >= prompt_len (capped at the pool's
    per-sequence table capacity ``cap_tokens``): prefill programs exist per
    bucket, not per length, so steady-state traffic stops compiling once
    the buckets are warm."""
    b = block_size
    while b < prompt_len and b < cap_tokens:
        b *= 2
    return min(b, cap_tokens)


class Scheduler:
    """Queue + slots + allocator choreography (host-side, no jax)."""

    def __init__(
        self,
        kv: PagedKVCache,
        *,
        max_slots: int,
        max_position_embeddings: int,
        prefill_flops_budget: float = 0.0,
        flops_per_token: float = 0.0,
        max_prefill_tokens: int = 0,
    ):
        self.kv = kv
        self.max_slots = int(max_slots)
        self.max_positions = int(max_position_embeddings)
        # per-step prefill token budget: the tighter of the explicit token
        # cap and the FLOPs budget / cost-model per-token FLOPs
        caps = []
        if max_prefill_tokens > 0:
            caps.append(max_prefill_tokens)
        if prefill_flops_budget > 0 and flops_per_token > 0:
            caps.append(max(int(prefill_flops_budget // flops_per_token), 1))
        self.prefill_token_cap = min(caps) if caps else 0  # 0 = unlimited
        self.waiting: List[RequestHandle] = []
        self.slots: Dict[int, Slot] = {}
        self._free_slots = list(range(self.max_slots - 1, -1, -1))
        self.rejected = 0
        self.completed = 0

    # -- intake -------------------------------------------------------------

    def _blocks_needed(self, prompt_len: int, max_new: int) -> int:
        """Blocks a request holds while running: its total-length need or
        its prefill bucket, whichever is larger (the bucket may overshoot
        ceil(total/bs) for prompts just past a power-of-two boundary)."""
        bucket = bucket_length(
            prompt_len, self.kv.block_size,
            self.kv.max_blocks_per_seq * self.kv.block_size)
        return max(self.kv.blocks_for(prompt_len + max_new),
                   bucket // self.kv.block_size)

    def submit(self, request: Request) -> RequestHandle:
        handle = RequestHandle(request)
        total = len(request.tokens) + request.max_new_tokens
        if (not request.tokens or request.max_new_tokens < 1
                or not self.kv.fits(total)
                or total > self.max_positions
                # can NEVER be satisfied even by an empty pool -> reject
                # now instead of queueing forever
                or (self._blocks_needed(len(request.tokens),
                                        request.max_new_tokens)
                    > self.kv.num_blocks - 1)):
            self.rejected += 1
            handle._finish("rejected", "capacity")
            return handle
        handle.status = "queued"
        self.waiting.append(handle)
        return handle

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def active(self) -> List[Slot]:
        return [self.slots[i] for i in sorted(self.slots)]

    def has_work(self) -> bool:
        return bool(self.waiting or self.slots)

    # -- admission ----------------------------------------------------------

    def admit(self) -> List[Tuple[Slot, int]]:
        """Admit waiting requests into free slots under the block + prefill
        budget. Returns ``(slot, bucket_len)`` pairs the engine must
        prefill this step. At least one request is admitted per call when a
        slot and blocks are available, even if its bucket exceeds the
        prefill cap — a cap below the smallest bucket must not deadlock."""
        self._drop_cancelled_waiting()
        admitted: List[Tuple[Slot, int]] = []
        budget_used = 0
        while self.waiting and self._free_slots:
            handle = self.waiting[0]
            req = handle.request
            prompt_len = len(req.tokens)
            bucket = bucket_length(
                prompt_len, self.kv.block_size,
                self.kv.max_blocks_per_seq * self.kv.block_size)
            if self.prefill_token_cap and admitted and (
                    budget_used + bucket > self.prefill_token_cap):
                break
            n_blocks = self._blocks_needed(prompt_len, req.max_new_tokens)
            blocks = self.kv.allocator.alloc(n_blocks)
            if blocks is None:
                break  # pool full; FIFO order preserved
            self.waiting.pop(0)
            idx = self._free_slots.pop()
            slot = Slot(index=idx, handle=handle, blocks=blocks,
                        pos=prompt_len, last_token=req.tokens[-1],
                        last_token_t=time.monotonic())
            handle.status = "running"
            self.slots[idx] = slot
            admitted.append((slot, bucket))
            budget_used += bucket
        return admitted

    def _drop_cancelled_waiting(self) -> None:
        self.sweep_waiting()

    def sweep_waiting(self, now: Optional[float] = None
                      ) -> Tuple[int, int]:
        """Resolve cancelled and deadline-expired requests still in the
        queue (a request whose timeout lapsed while queued must not be
        admitted, prefilled, and only then retired — that wastes device
        work and pollutes the TTFT histogram). Returns
        ``(n_cancelled, n_timeout)``."""
        now = time.monotonic() if now is None else now
        n_cancel = n_timeout = 0
        still = []
        for h in self.waiting:
            if h.cancelled:
                h._finish("cancelled", "cancelled")
                n_cancel += 1
            elif (h.request.timeout_s > 0
                  and now - h.submitted_t > h.request.timeout_s):
                h._finish("timeout", "timeout")
                n_timeout += 1
            else:
                still.append(h)
        self.waiting = still
        return n_cancel, n_timeout

    # -- retirement ---------------------------------------------------------

    def retire(self, slot: Slot, status: str, reason: str) -> None:
        """Free the slot's blocks, recycle the lane, resolve the handle."""
        self.kv.allocator.free(slot.blocks)
        del self.slots[slot.index]
        self._free_slots.append(slot.index)
        if status == "done":
            self.completed += 1
        slot.handle._finish(status, reason)

    def sweep(self, now: Optional[float] = None) -> Tuple[int, int]:
        """Retire cancelled / deadline-expired active sequences; returns
        ``(n_cancelled, n_timeout)`` — the single home of the retirement
        predicate (the engine's metric split reads these counts rather
        than re-deriving them)."""
        now = time.monotonic() if now is None else now
        n_cancel = n_timeout = 0
        for slot in list(self.slots.values()):
            h = slot.handle
            if h.cancelled:
                self.retire(slot, "cancelled", "cancelled")
                n_cancel += 1
            elif (h.request.timeout_s > 0
                  and now - h.submitted_t > h.request.timeout_s):
                self.retire(slot, "timeout", "timeout")
                n_timeout += 1
        return n_cancel, n_timeout

    # -- decode batch view --------------------------------------------------

    def padded_table(self, blocks: Sequence[int]) -> List[int]:
        t = list(blocks)[: self.kv.max_blocks_per_seq]
        return t + [SCRATCH_BLOCK] * (self.kv.max_blocks_per_seq - len(t))

    def decode_state(self) -> Dict[str, List]:
        """Fixed-shape per-lane arrays for the decode program. Inactive
        lanes feed token 0 at position 0 against the scratch block; their
        outputs are discarded host-side."""
        S, MB = self.max_slots, self.kv.max_blocks_per_seq
        state = {
            "tokens": [0] * S,
            "pos": [0] * S,
            "tables": [[SCRATCH_BLOCK] * MB for _ in range(S)],
            "temps": [0.0] * S,
            "seeds": [0] * S,
            "gen_idx": [0] * S,
            "active": [False] * S,
        }
        for i, slot in self.slots.items():
            req = slot.request
            state["tokens"][i] = slot.last_token
            state["pos"][i] = slot.pos
            state["tables"][i] = self.padded_table(slot.blocks)
            state["temps"][i] = float(req.temperature)
            state["seeds"][i] = int(req.seed)
            state["gen_idx"][i] = slot.generated
            state["active"][i] = True
        return state
