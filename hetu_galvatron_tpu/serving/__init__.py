"""Inference serving subsystem: paged KV cache, continuous batching, engine.

The training stack stops at offline fixed-batch decode
(``models/generate.py``); this package adds the online-serving workload the
ROADMAP's "heavy traffic" north star implies:

* ``kv_cache.py`` — a paged KV cache: fixed-size blocks in a preallocated
  pool with a per-sequence block table (vLLM's PagedAttention layout,
  expressed as gather/scatter over jax arrays so the whole decode step
  stays one jitted program).
* ``scheduler.py`` — a continuous-batching scheduler: FIFO admission under
  a KV-block + prefill-FLOPs budget (``cost_model/cost.py`` accounting),
  per-sequence EOS/length/timeout retirement, slot recycling at a fixed
  jitted batch shape.
* ``prefix_cache.py`` — the shared-prefix radix cache: block-granular
  radix tree keyed on token ids over the same pool, refcount-shared
  blocks (a cached prompt prefix skips its prefill copy-free), LRU
  eviction over unpinned nodes.
* ``spec_decode.py`` — lossless speculative decoding: pluggable drafts
  (n-gram prompt-lookup, small draft model) verified in one batched
  fixed-shape pass; greedy streams stay bit-identical to plain decode.
* ``engine.py`` — the serving engine: jitted paged prefill/decode (+
  prefix-prefill and speculative-verify) programs (plan-aware GSPMD
  sharding when given a mesh + HybridParallelConfig), per-request token
  streams, cancellation, timeouts, and serving telemetry wired into
  ``observability/``.

Front ends: ``cli/serve.py`` (file/stdin request streams) and
``tools/serve_bench.py`` (closed-loop load generator, shared-prefix
traces).
"""

from hetu_galvatron_tpu.serving.engine import ServingEngine
from hetu_galvatron_tpu.serving.kv_cache import (
    BlockAccountingError,
    BlockAllocator,
    PagedKVCache,
)
from hetu_galvatron_tpu.serving.prefix_cache import PrefixCache
from hetu_galvatron_tpu.serving.scheduler import (
    Request,
    RequestHandle,
    Scheduler,
)
from hetu_galvatron_tpu.serving.spec_decode import (
    ModelDraft,
    NgramDraft,
)

__all__ = [
    "BlockAccountingError",
    "BlockAllocator",
    "ModelDraft",
    "NgramDraft",
    "PagedKVCache",
    "PrefixCache",
    "Request",
    "RequestHandle",
    "Scheduler",
    "ServingEngine",
]
