"""Lossless speculative decoding: pluggable drafts + batched verify.

One decode step emits one token per sequence no matter how fast the
hardware is — the step is latency-bound, not FLOPs-bound. Speculative
decoding drafts K candidate tokens per sequence CHEAPLY (host-side n-gram
lookup by default; optionally a small draft model) and verifies all of
them in ONE batched pass through a fixed-shape ``[max_batch_size, K+1]``
jitted program (``ServingEngine._build_verify``), accepting the longest
prefix the target model agrees with. Accepted tokens cost one step
instead of one step each.

**Losslessness.** The verify program computes, for every window row j,
the target model's next token given the context *including the drafted
tokens before j* — the same arithmetic as j sequential decode steps
(``kv_cache.paged_sdpa_window`` mirrors the decode attention bit for
bit). Greedy acceptance keeps a drafted token only while it EQUALS the
target's own choice, so the emitted stream is exactly the non-speculative
stream: the draft only ever changes how many steps it takes, never the
tokens. Sampled (temperature > 0) rows do not speculate — row 0's sample
uses the same per-request ``fold_in(seed, emitted-index)`` key the plain
decode would, so those streams are unchanged too.

**Drafts.** A draft is anything with ``propose(context, k) -> tokens``:

* :class:`NgramDraft` — prompt-lookup decoding: find the most recent
  earlier occurrence of the context's trailing n-gram and propose the
  tokens that followed it. Free (no model, no device work) and strong on
  the copy/repetition structure real generations are full of.
* :class:`ModelDraft` — a small draft model behind the same interface:
  greedy continuation via the offline ``models/generate.generate`` on a
  bucketed (left-padded) context window, one jitted program per (bucket,
  k). A wrong draft costs nothing but the wasted lane — verification
  guarantees the stream either way.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Protocol, Sequence, runtime_checkable


@runtime_checkable
class DraftProvider(Protocol):
    """The pluggable draft interface: given the request's full context
    (prompt + emitted tokens, host-side ints), propose up to ``k`` next
    tokens. Fewer (or zero) proposals are fine — unfilled lanes are
    padded and simply fail verification."""

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        ...


class NgramDraft:
    """Prompt-lookup decoding (n-gram matching against the request's own
    context). Tries the longest trailing n-gram first (``max_n`` down to
    ``min_n``); on a hit at position i, proposes ``context[i+n : i+n+k]``
    — the continuation observed last time this n-gram appeared."""

    def __init__(self, max_n: int = 3, min_n: int = 1):
        if not (1 <= min_n <= max_n):
            raise ValueError(f"bad ngram range [{min_n}, {max_n}]")
        self.max_n = int(max_n)
        self.min_n = int(min_n)

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        ctx = list(context)
        L = len(ctx)
        for n in range(min(self.max_n, L - 1), self.min_n - 1, -1):
            tail = ctx[L - n:]
            # most recent earlier occurrence (scan right to left, the
            # continuation seen last is likeliest to repeat)
            for i in range(L - n - 1, -1, -1):
                if ctx[i:i + n] == tail:
                    return ctx[i + n:i + n + k]
        return []


class ModelDraft:
    """A small causal LM as the draft, behind the same ``propose``
    interface. The context is clipped to its trailing ``window`` tokens
    and left-padded to a power-of-two bucket so there is one jitted
    program per (bucket, k) — the clip is an approximation the verifier
    makes harmless."""

    def __init__(self, params, cfg, *, window: int = 128,
                 compute_dtype=None):
        import jax.numpy as jnp

        self.params = params
        self.cfg = cfg
        self.window = int(min(window, cfg.max_position_embeddings))
        self.compute_dtype = (compute_dtype if compute_dtype is not None
                              else jnp.float32)
        self._fns: Dict[tuple, object] = {}

    def _fn_for(self, bucket: int, k: int):
        import jax

        from hetu_galvatron_tpu.models.generate import generate

        key = (bucket, k)
        fn = self._fns.get(key)
        if fn is None:
            fn = jax.jit(lambda p, t, n: generate(
                p, t, self.cfg, k, prompt_lens=n, pad_id=0,
                compute_dtype=self.compute_dtype))
            self._fns[key] = fn
        return fn

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        import jax.numpy as jnp
        import numpy as np

        ctx = [t for t in context if t < self.cfg.vocab_size][-self.window:]
        if not ctx or k < 1:
            return []
        bucket = 8
        while bucket < len(ctx):
            bucket *= 2
        bucket = min(bucket, self.window)
        ctx = ctx[-bucket:]
        if len(ctx) + k > self.cfg.max_position_embeddings:
            return []
        padded = np.zeros((1, bucket), np.int32)
        padded[0, bucket - len(ctx):] = ctx
        out = self._fn_for(bucket, k)(
            self.params, jnp.asarray(padded),
            jnp.asarray([len(ctx)], jnp.int32))
        return np.asarray(out)[0, bucket:].tolist()

    def compile_count(self) -> int:
        return sum(f._cache_size() for f in self._fns.values())


def make_draft(serving, *, draft_params=None, draft_cfg=None
               ) -> Optional[DraftProvider]:
    """Build the draft the ServingArgs ask for (None when spec decode is
    off). ``spec_draft="model"`` needs the draft checkpoint passed to the
    engine (``draft_params``/``draft_cfg``)."""
    if not serving.spec_decode:
        return None
    if serving.spec_draft == "model":
        if draft_params is None or draft_cfg is None:
            raise ValueError(
                "serving.spec_draft='model' needs draft_params + draft_cfg "
                "(the small draft checkpoint) passed to ServingEngine")
        return ModelDraft(draft_params, draft_cfg)
    return NgramDraft(max_n=serving.spec_ngram_max,
                      min_n=serving.spec_ngram_min)


def accept_length(drafted: Sequence[int], targets: Sequence[int],
                  k_eff: int) -> int:
    """Greedy acceptance: the longest prefix of ``drafted`` the target
    model reproduced. ``targets[j]`` is the model's choice AFTER seeing
    drafted[0..j-1]; drafted[j] survives iff it equals targets[j]. The
    emitted tokens are then ``targets[0..a]`` (a accepted drafts + the
    bonus token), which is exactly the non-speculative stream."""
    a = 0
    while a < k_eff and a < len(drafted) and drafted[a] == targets[a]:
        a += 1
    return a
