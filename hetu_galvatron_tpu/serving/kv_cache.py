"""Paged KV cache: fixed-size blocks in a preallocated pool + block tables.

The offline decode path (``models/generate.py``) allocates a dense
``[batch, max_len]`` cache per call — fine for one fixed batch, hopeless for
serving, where sequences of wildly different lengths come and go: a dense
cache sized for the longest request wastes HBM proportional to the spread,
and admitting a new request would reshape (recompile) the program.

This module keeps ONE preallocated pool per layer, carved into fixed-size
blocks (the PagedAttention layout), with a per-sequence *block table* mapping
logical positions to pool blocks:

    pool[layer]["k"] : [num_blocks, block_size, kv_heads, head_dim]
    table[seq]       : [max_blocks_per_seq] int32 block ids

Alloc/free is host-side free-list bookkeeping (:class:`BlockAllocator`);
reads/writes are jax gather/scatter (:func:`scatter_prefill`,
:func:`scatter_token`, :func:`gather_pages`) so the whole decode step jits
once and never reshapes. Block 0 is a reserved scratch block: retired slots
point their writes at it, keeping the batch shape fixed without conditional
control flow.

GQA-aware: blocks store ``cfg.kv_heads`` heads (not query heads), so a
GQA model's pool is ``num_attention_heads / kv_heads`` times smaller.

Sharding: the kv-head axis of every block carries the SAME mesh axes
``runtime/mesh.py`` assigns to that layer's attention weights (the layer's
tp axes — or replication under Ulysses, whose "tp" axes carry sequence, not
heads), so plan-sharded params and the cache agree without resharding at
the attention boundary. See :func:`pool_pspecs`.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.core.args_schema import ModelArgs

# block 0 is never allocated: retired slots write into it so the decode
# batch keeps a fixed shape with no per-slot control flow
SCRATCH_BLOCK = 0

Pools = List[Dict[str, jax.Array]]


class BlockAccountingError(ValueError):
    """A block lifecycle violation: double free, strict-freeing a block
    other owners still reference, or touching the refcount of a block that
    was never allocated. Typed so callers (and tests) can distinguish a
    bookkeeping bug from ordinary ValueErrors."""


class BlockAllocator:
    """Host-side refcounted free-list allocator over the pool's block ids.

    Blocks are position-independent (the table indirection absorbs any
    ordering), so there is no fragmentation in the contiguous-memory sense;
    :meth:`defrag_plan` exists to compact live blocks to the low indices
    (pool-shrink / snapshot use cases), not to satisfy allocations.

    Refcounts make KV *sharing* copy-free (the radix prefix cache,
    ``serving/prefix_cache.py``): every owner — a running sequence, the
    radix tree — holds one reference, and a block returns to the free list
    only when the last one drops it. :meth:`alloc` hands out blocks at
    refcount 1; co-owners :meth:`incref`; owners release via
    :meth:`decref`. :meth:`free` stays the STRICT single-owner path:
    freeing a block somebody else still references (or freeing twice)
    raises :class:`BlockAccountingError` instead of silently corrupting a
    neighbor's cache.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (one is scratch), got "
                             f"{num_blocks}")
        self.num_blocks = num_blocks
        # LIFO free list: recycled blocks are reused first (warm pages)
        self._free = list(range(num_blocks - 1, SCRATCH_BLOCK, -1))
        self._rc = [0] * num_blocks  # per-block owner count; 0 = free

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def used(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def refcount(self, block: int) -> int:
        self._check_id(block)
        return self._rc[block]

    def _check_id(self, b: int) -> None:
        if not (SCRATCH_BLOCK < b < self.num_blocks):
            raise BlockAccountingError(f"invalid block id {b} "
                                       f"(pool has 1..{self.num_blocks - 1})")

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks at refcount 1, or None when the pool cannot satisfy
        the request (caller keeps the sequence queued — never a partial
        grant)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._rc[b] = 1
        return out

    def incref(self, blocks: Sequence[int]) -> None:
        """A new owner (e.g. the radix tree adopting a prompt's blocks)
        takes a reference. Only allocated blocks can gain owners."""
        for b in blocks:
            self._check_id(b)
            if self._rc[b] < 1:
                raise BlockAccountingError(
                    f"incref of unallocated block {b}")
        for b in blocks:
            self._rc[b] += 1

    @staticmethod
    def _check_unique(blocks: Sequence[int]) -> None:
        # a duplicated id in ONE call would pass per-block validation
        # (the refcount only drops in the mutation phase) and then
        # double-release: the free list would hand the same block to two
        # sequences — silent cross-request KV corruption
        if len(set(blocks)) != len(blocks):
            dup = sorted(b for b in set(blocks)
                         if list(blocks).count(b) > 1)
            raise BlockAccountingError(
                f"duplicate block id(s) {dup} in one release call")

    def decref(self, blocks: Sequence[int]) -> List[int]:
        """Drop one reference per block; blocks whose last owner left are
        recycled and returned. Decref of an already-free block is a double
        free; so is the same id twice in one call."""
        self._check_unique(blocks)
        for b in blocks:
            self._check_id(b)
            if self._rc[b] < 1:
                raise BlockAccountingError(f"double free of block {b}")
        freed: List[int] = []
        for b in blocks:
            self._rc[b] -= 1
            if self._rc[b] == 0:
                self._free.append(b)
                freed.append(b)
        return freed

    def free(self, blocks: Sequence[int]) -> None:
        """Strict sole-owner release: every block must be allocated with
        refcount exactly 1. Freeing a SHARED block this way raises —
        co-owned blocks must go through :meth:`decref` so the other
        owners' tables stay valid."""
        self._check_unique(blocks)
        for b in blocks:
            self._check_id(b)
            if self._rc[b] == 0:
                raise BlockAccountingError(f"double free of block {b}")
            if self._rc[b] > 1:
                raise BlockAccountingError(
                    f"free() of shared block {b} "
                    f"(refcount {self._rc[b]}); other owners still "
                    "reference it — use decref()")
        for b in blocks:
            self._rc[b] = 0
            self._free.append(b)

    def defrag_plan(self, tables: Sequence[Sequence[int]]
                    ) -> Tuple[List[int], List[List[int]]]:
        """Compaction plan: live blocks (every id referenced by ``tables``)
        move to ids 1..n_live, preserving first-reference order. Returns
        ``(perm, new_tables)`` where ``perm[new_id] = old_id`` is the pool
        gather order (length num_blocks; scratch stays at 0) and
        ``new_tables`` mirror ``tables`` under the renaming. The caller
        applies ``perm`` to the pool arrays (:meth:`PagedKVCache.defrag`)
        and adopts the new tables; the free list is rebuilt as the tail.

        ``tables`` must cover EVERY referencing view of every live block —
        all sequences' tables, their ownership lists, and the radix prefix
        cache's node tables (:meth:`Scheduler.defrag` collects them) — or
        an unlisted view would silently keep pointing at a permuted id.
        Shared blocks may appear in many tables; refcounts survive the
        renaming unchanged."""
        remap: Dict[int, int] = {SCRATCH_BLOCK: SCRATCH_BLOCK}
        for table in tables:
            for b in table:
                if b not in remap:
                    self._check_id(b)
                    if self._rc[b] < 1:
                        raise BlockAccountingError(
                            f"defrag table references free block {b}")
                    remap[b] = len(remap)
        n_live = len(remap) - 1
        if n_live != self.used:
            raise ValueError(
                f"tables reference {n_live} blocks but allocator has "
                f"{self.used} outstanding — tables and allocator disagree")
        perm = [SCRATCH_BLOCK] * self.num_blocks
        for old, new in remap.items():
            perm[new] = old
        # unreferenced (free) blocks fill the tail in id order
        tail = [b for b in range(1, self.num_blocks) if b not in remap]
        for i, old in enumerate(tail):
            perm[n_live + 1 + i] = old
        new_tables = [[remap[b] for b in t] for t in tables]
        self._free = list(range(self.num_blocks - 1, n_live, -1))
        self._rc = [self._rc[perm[new]] for new in range(self.num_blocks)]
        return perm, new_tables


# ---------------------------------------------------------------------------
# pure pool ops (jit-friendly)
# ---------------------------------------------------------------------------


def scatter_prefill(pool: jax.Array, kv: jax.Array,
                    table: jax.Array) -> jax.Array:
    """Write a prefilled [S, K, D] k-or-v run into its blocks. ``S`` must be
    a multiple of block_size (prefill buckets are); ``table`` holds the
    S/block_size destination block ids."""
    bs = pool.shape[1]
    nb = kv.shape[0] // bs
    return pool.at[table].set(
        kv.reshape(nb, bs, *kv.shape[1:]).astype(pool.dtype))


def scatter_token(pool: jax.Array, kv: jax.Array, blocks: jax.Array,
                  offsets: jax.Array) -> jax.Array:
    """Write one decode-step token per slot: kv [S, K, D] lands at
    (blocks[s], offsets[s]). Retired slots alias the scratch block —
    colliding scratch writes are unordered but never read."""
    return pool.at[blocks, offsets].set(kv.astype(pool.dtype))


def gather_pages(pool: jax.Array, tables: jax.Array) -> jax.Array:
    """Assemble each slot's logical cache: tables [S, MB] -> [S, MB*bs, K, D]
    (positions past the slot's length are garbage; the attention mask in
    :func:`paged_sdpa` hides them)."""
    S, MB = tables.shape
    bs = pool.shape[1]
    pages = pool[tables]  # [S, MB, bs, K, D]
    return pages.reshape(S, MB * bs, *pool.shape[2:])


def paged_sdpa(q: jax.Array, ck: jax.Array, cv: jax.Array,
               pos: jax.Array) -> jax.Array:
    """Per-slot cached attention: q [S,1,Nq,D] against assembled pages
    [S,T,K,D]; key positions > pos[s] are masked. Delegates to the ONE
    dense-cache attention implementation
    (``models/generate._cached_sdpa``, which accepts per-row positions),
    so a paged decode reproduces the offline decode bit-for-bit on the
    live positions — by construction, not by parallel maintenance."""
    from hetu_galvatron_tpu.models.generate import _cached_sdpa

    return _cached_sdpa(q, ck, cv, pos)


def paged_sdpa_window(q: jax.Array, ck: jax.Array, cv: jax.Array,
                      start) -> jax.Array:
    """Windowed cached attention: q [S,W,Nq,D] holds W consecutive query
    positions per slot starting at absolute position ``start[s]`` (scalar
    or [S]); row j attends key positions <= start[s] + j of the assembled
    pages [S,T,K,D]. Delegates to the ONE dense-cache attention
    implementation (``models/generate._cached_sdpa``, W-wide), so the
    speculative verify program and the prefix-suffix prefill are
    bit-identical to W sequential decode steps — by construction, not by
    parallel maintenance (:func:`paged_sdpa` is the W=1 view of the same
    delegation)."""
    from hetu_galvatron_tpu.models.generate import _cached_sdpa

    return _cached_sdpa(q, ck, cv, start)


def scatter_window(pool: jax.Array, kv: jax.Array, blocks: jax.Array,
                   offsets: jax.Array) -> jax.Array:
    """Write a window of tokens per slot: kv [S, W, K, D] lands at
    (blocks[s, j], offsets[s, j]). The verify program routes
    out-of-budget lanes at the scratch block — colliding scratch writes
    are unordered but never read (same contract as
    :func:`scatter_token`)."""
    return pool.at[blocks, offsets].set(kv.astype(pool.dtype))


def copy_block(pool: jax.Array, src, dst) -> jax.Array:
    """Duplicate one block's contents (copy-on-write for a fully-cached
    prompt: the block holding the last prompt position must be private
    before the bootstrap decode step overwrites that position)."""
    return pool.at[dst].set(pool[src])


# module-level so repeated defrag() calls hit the jit cache instead of
# recompiling the gather every time
_permute_pools = jax.jit(
    lambda pools, idx: jax.tree.map(lambda a: a[idx], pools))


def pool_pspecs(layer_shardings: Optional[Sequence[Any]],
                num_layers: int, kv_heads: int) -> List[P]:
    """Per-layer PartitionSpec for [num_blocks, block_size, kv_heads,
    head_dim] pool arrays: kv heads ride the layer's tp axes exactly like
    the attention weights (``runtime/mesh.py`` qkv logical axis), replicated
    under Ulysses (whose tp axes carry sequence) or when tp does not divide
    the kv-head count (kv heads replicate, reference GQA grouping)."""
    if layer_shardings is None:
        return [P(None, None, None, None)] * num_layers
    specs = []
    for sh in layer_shardings:
        axes = () if sh.ulysses else sh.tp_axes
        tp = 1
        for a in axes:
            tp *= 2  # binary mesh axes
        if not axes or kv_heads % tp:
            specs.append(P(None, None, None, None))
        else:
            specs.append(P(None, None, axes, None))
    return specs


def resolve_num_blocks(serving: Any, cfg: ModelArgs) -> int:
    """The pool size an engine with these args will actually allocate:
    ``serving.num_kv_blocks`` verbatim, or the default pool where every
    decode lane can hold one full-length sequence (+ the reserved scratch
    block). Pure arithmetic, shared by :class:`ServingEngine` and the
    static memory doctor (``analysis/memory_doctor.py``) so the doctor's
    HBM accounting can never drift from what the engine allocates."""
    if serving.num_kv_blocks:
        return int(serving.num_kv_blocks)
    max_seq_len = serving.max_seq_len or cfg.max_position_embeddings
    per_seq = -(-max_seq_len // serving.kv_block_size)
    return 1 + int(serving.max_batch_size) * per_seq


def kv_pool_mb(serving: Any, cfg: ModelArgs, *, kv_elem_bytes: int = 2,
               tp: int = 1) -> float:
    """Per-device megabytes of the preallocated paged KV pool under these
    serving args: ``num_blocks`` blocks of
    ``2 (k+v) * layers * block_size * kv_heads * head_dim`` elements, the
    kv-head axis sharded over ``tp`` exactly when tp divides the kv-head
    count (:func:`pool_pspecs`; replicated otherwise). ``kv_elem_bytes``
    defaults to bf16 — the engine's default ``kv_dtype``."""
    num_blocks = resolve_num_blocks(serving, cfg)
    shard = tp if (tp > 1 and cfg.kv_heads % tp == 0) else 1
    per_block = (2 * cfg.num_hidden_layers * serving.kv_block_size
                 * cfg.kv_heads * cfg.head_dim * kv_elem_bytes)
    return num_blocks * per_block / shard / (1024 * 1024)


class PagedKVCache:
    """The pool + allocator pair one engine owns.

    ``pools`` is a per-layer list of ``{"k", "v"}`` arrays that flows
    through the jitted prefill/decode programs (donated and replaced each
    call); the allocator and block tables stay host-side.
    """

    def __init__(
        self,
        cfg: ModelArgs,
        *,
        num_blocks: int,
        block_size: int,
        max_seq_len: int,
        dtype=jnp.bfloat16,
        mesh: Optional[Mesh] = None,
        layer_shardings: Optional[Sequence[Any]] = None,
    ):
        if block_size < 1:
            raise ValueError(f"block_size {block_size}")
        self.cfg = cfg
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_seq_len = int(max_seq_len)
        self.max_blocks_per_seq = max(
            math.ceil(self.max_seq_len / self.block_size), 1)
        self.dtype = dtype
        self.mesh = mesh
        self.allocator = BlockAllocator(self.num_blocks)
        L = cfg.num_hidden_layers
        shape = (self.num_blocks, self.block_size, cfg.kv_heads, cfg.head_dim)
        self.pspecs = pool_pspecs(layer_shardings, L, cfg.kv_heads)
        self.pools: Pools = []
        for i in range(L):
            k = jnp.zeros(shape, dtype)
            v = jnp.zeros(shape, dtype)
            if mesh is not None:
                shd = NamedSharding(mesh, self.pspecs[i])
                k = jax.device_put(k, shd)
                v = jax.device_put(v, shd)
            self.pools.append({"k": k, "v": v})

    # -- sizing -------------------------------------------------------------

    def blocks_for(self, total_tokens: int) -> int:
        """Blocks a sequence of ``total_tokens`` (prompt + generation
        budget) needs."""
        return max(math.ceil(total_tokens / self.block_size), 1)

    def fits(self, total_tokens: int) -> bool:
        """Whether a sequence of this total length can EVER be served
        (table capacity), regardless of current occupancy."""
        return (total_tokens <= self.max_seq_len
                and self.blocks_for(total_tokens) <= self.max_blocks_per_seq)

    @property
    def occupancy(self) -> float:
        """Fraction of allocatable blocks currently held."""
        cap = self.num_blocks - 1
        return self.allocator.used / cap if cap else 0.0

    def bytes_per_block(self) -> int:
        elt = jnp.dtype(self.dtype).itemsize
        return (2 * self.cfg.num_hidden_layers * self.block_size
                * self.cfg.kv_heads * self.cfg.head_dim * elt)

    # -- maintenance --------------------------------------------------------

    def defrag(self, tables: Sequence[Sequence[int]]) -> List[List[int]]:
        """Compact live blocks to the low pool indices: permutes the pool
        arrays (one jitted gather) and returns the renamed tables. Contents
        seen through the tables are unchanged."""
        perm, new_tables = self.allocator.defrag_plan(tables)
        self.pools = _permute_pools(self.pools, jnp.asarray(perm, jnp.int32))
        return new_tables
