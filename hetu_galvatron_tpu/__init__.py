"""hetu_galvatron_tpu — TPU-native automatic hybrid-parallel training framework.

A from-scratch JAX/XLA re-design of the capabilities of PKU-DAIR/Hetu-Galvatron
(reference surveyed in SURVEY.md): a Profiler -> Search Engine -> Runtime system
that trains Transformers with *layer-wise* hybrid parallelism — DP / ZeRO-2/3 /
TP (+sequence parallel) / Ulysses-SP / ring-attention CP / PP (GPipe & 1F1B) /
EP / activation checkpointing — chosen automatically per layer by a
cost-model-driven dynamic-programming search.

TPU-first design notes (vs the torch/NCCL reference):
  - process groups        -> `jax.sharding.Mesh` views + named-axis collectives
  - FSDP wrapping         -> parameter/optimizer PartitionSpecs on the `dp` axis
  - Megatron TP layers    -> GSPMD-sharded einsums (XLA inserts the collectives)
  - NCCL p2p pipeline     -> per-stage jitted programs + sharded device_put
  - flash-attn CUDA ops   -> Pallas flash attention kernel
  - Triton kernels        -> Pallas
  - activation relocation -> `with_sharding_constraint` resharding at boundaries
"""

__version__ = "0.2.0"

from hetu_galvatron_tpu.core.arguments import (  # noqa: F401
    args_from_cli,
    load_config,
)
from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs  # noqa: F401
from hetu_galvatron_tpu.utils.strategy import (  # noqa: F401
    DPType,
    EmbeddingLMHeadStrategy,
    LayerStrategy,
    PlanFormatError,
    config2strategy,
    strategy_list2config,
)


def __getattr__(name):
    """Lazy heavyweight entry points (importing them pulls in jax)."""
    if name == "SearchEngine":
        from hetu_galvatron_tpu.core.search_engine.engine import SearchEngine

        return SearchEngine
    if name == "PipelineEngine":
        from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine

        return PipelineEngine
    if name == "build_mesh":
        from hetu_galvatron_tpu.runtime.mesh import build_mesh

        return build_mesh
    if name == "get_hybrid_parallel_config":
        from hetu_galvatron_tpu.runtime.hybrid_config import (
            get_hybrid_parallel_config,
        )

        return get_hybrid_parallel_config
    if name == "generate":
        from hetu_galvatron_tpu.models.generate import generate

        return generate
    raise AttributeError(name)
