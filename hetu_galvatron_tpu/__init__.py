"""hetu_galvatron_tpu — TPU-native automatic hybrid-parallel training framework.

A from-scratch JAX/XLA re-design of the capabilities of PKU-DAIR/Hetu-Galvatron
(reference surveyed in SURVEY.md): a Profiler -> Search Engine -> Runtime system
that trains Transformers with *layer-wise* hybrid parallelism — DP / ZeRO-2/3 /
TP (+sequence parallel) / Ulysses-SP / ring-attention CP / PP (GPipe & 1F1B) /
EP / activation checkpointing — chosen automatically per layer by a
cost-model-driven dynamic-programming search.

TPU-first design notes (vs the torch/NCCL reference):
  - process groups        -> `jax.sharding.Mesh` views + named-axis collectives
  - FSDP wrapping         -> parameter/optimizer PartitionSpecs on the `dp` axis
  - Megatron TP layers    -> GSPMD-sharded einsums (XLA inserts the collectives)
  - NCCL p2p pipeline     -> `shard_map` over the `pp` axis with `lax.ppermute`
  - flash-attn CUDA ops   -> Pallas flash/splash attention kernels
  - Triton kernels        -> Pallas kernels
  - activation relocation -> `with_sharding_constraint` resharding at boundaries
"""

__version__ = "0.1.0"

from hetu_galvatron_tpu.utils.strategy import (  # noqa: F401
    DPType,
    LayerStrategy,
    strategy_list2config,
    config2strategy,
)
