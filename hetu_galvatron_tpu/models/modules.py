"""Transformer building blocks as pure functions over explicit param pytrees.

Capability parity with the reference's module zoo (runtime/models/modules.py,
runtime/transformer/attention.py:111-720, mlp.py, norm.py:6,
rotary_pos_embedding.py): embedding, decoder layer (attention + MLP with
RMS/LayerNorm, RoPE or learned positions, GQA, SwiGLU/GeGLU/GeLU), final norm,
and LM head with a numerically-stable cross-entropy.

TPU-first design, deliberately unlike the torch reference:

* **Pure functions + pytrees.** Each module is an ``init_*`` returning
  ``(params, logical_axes)`` and an ``apply_*``; no module objects, no hidden
  state. The whole model is a nested dict that `jax.jit`/`pjit` shard by a
  matching tree of :data:`PartitionSpec`s.
* **Logical axis names.** ``init_*`` returns, alongside every param, a tuple of
  logical axis names (``("embed", "qkv")`` etc). The mesh layer
  (``runtime/mesh.py``) maps logical names -> mesh axes *per layer*, which is
  how the reference's per-layer strategy vectors (tp/sp/cp/dp-type) become
  GSPMD shardings instead of Megatron process groups.
* **MXU-friendly shapes.** QKV is one fused matmul ((nq+2*nkv)*head_dim wide),
  SwiGLU gate+up is one fused matmul; weights live in fp32, compute runs in
  bf16 with fp32 accumulation (``preferred_element_type``).
* **Swappable attention core.** ``apply_attention`` takes an ``sdpa_fn`` so the
  same layer runs XLA attention, a Pallas flash kernel, Ulysses all-to-all, or
  ring attention depending on the layer's strategy (reference dispatch:
  attention.py:664-720).
* **Swappable projection matmuls.** ``apply_attention`` / ``apply_mlp`` take a
  ``matmul_fns`` dict ({"qkv", "out"} / {"fc1", "fc2"}) so tensor-parallel
  layers can run the decomposed ring all-gather/reduce-scatter matmuls
  (ops/overlap.py) instead of leaving the collectives to GSPMD — same
  per-layer dispatch idiom as ``sdpa_fn``. Each fn maps (x, w) to the fp32
  product the default einsum would produce.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import ModelArgs

Params = Dict[str, Any]
Axes = Dict[str, Any]

# ---------------------------------------------------------------------------
# initializers
# ---------------------------------------------------------------------------


def _normal(key, shape, std, dtype=jnp.float32):
    return std * jax.random.normal(key, shape, dtype)


def param_dtype_of(cfg: ModelArgs) -> jnp.dtype:
    return jnp.float32  # master weights are always fp32; compute casts down


def compute_dtype_of(mixed_precision: str) -> jnp.dtype:
    return {"bf16": jnp.bfloat16, "fp16": jnp.float16, "fp32": jnp.float32}[
        mixed_precision
    ]


# ---------------------------------------------------------------------------
# norms
# ---------------------------------------------------------------------------


def init_norm(cfg: ModelArgs) -> Tuple[Params, Axes]:
    # zero-centered (gemma) weights store the offset from 1, so init is 0
    init = 0.0 if cfg.norm_zero_centered else 1.0
    p: Params = {"scale": jnp.full((cfg.hidden_size,), init, jnp.float32)}
    a: Axes = {"scale": ("embed",)}
    if cfg.normalization == "layernorm":
        p["bias"] = jnp.zeros((cfg.hidden_size,), jnp.float32)
        a["bias"] = ("embed",)
    return p, a


def apply_norm(p: Params, x: jax.Array, cfg: ModelArgs) -> jax.Array:
    """RMSNorm or LayerNorm, computed in fp32 regardless of activation dtype
    (matches the reference's fp32 norm path, norm.py:6). Empty params =
    identity (post-norm families have no final pre-head norm)."""
    if not p:
        return x
    dtype = x.dtype
    x = x.astype(jnp.float32)
    scale = p["scale"]
    if cfg.norm_zero_centered:
        scale = 1.0 + scale  # gemma RMSNorm: x * (1 + weight)
    if cfg.normalization == "rmsnorm":
        var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        y = x * jax.lax.rsqrt(var + cfg.layernorm_epsilon) * scale
    else:
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + cfg.layernorm_epsilon)
        y = y * scale + p["bias"]
    return y.astype(dtype)


# ---------------------------------------------------------------------------
# rotary position embedding
# ---------------------------------------------------------------------------


def _scale_inv_freq(inv_freq: jax.Array, scaling: Optional[dict]) -> jax.Array:
    """HF-style ``rope_scaling``: "linear" divides frequencies by ``factor``;
    "llama3" keeps high-frequency bands, divides low-frequency bands by
    ``factor``, and smoothly interpolates between the two wavelength
    thresholds (the public llama-3.1 rope recipe; parity-tested against
    transformers' _compute_llama3_parameters)."""
    if not scaling:
        return inv_freq
    rope_type = scaling.get("rope_type", scaling.get("type", "linear"))
    factor = float(scaling.get("factor", 1.0))
    if rope_type == "linear":
        return inv_freq / factor
    if rope_type == "llama3":
        low = float(scaling["low_freq_factor"])
        high = float(scaling["high_freq_factor"])
        orig = float(scaling["original_max_position_embeddings"])
        wavelen = 2.0 * jnp.pi / inv_freq
        smooth = (orig / wavelen - low) / (high - low)
        smooth = jnp.clip(smooth, 0.0, 1.0)
        return (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    raise ValueError(f"unsupported rope_scaling type {rope_type!r} "
                     "(supported: linear, llama3)")


def rope_cos_sin(
    seq_len: int, head_dim: int, theta: float, dtype=jnp.float32,
    scaling: Optional[dict] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Precompute RoPE tables [seq, head_dim//2] (reference
    rotary_pos_embedding.py builds the same inv-freq table)."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    inv_freq = _scale_inv_freq(inv_freq, scaling)
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [S, D/2]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def mrope_cos_sin(
    position_ids: jax.Array,  # [3, B, S] (temporal, height, width)
    head_dim: int, theta: float, sections, dtype=jnp.float32,
    scaling: Optional[dict] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Multimodal rotary tables (reference
    rotary_pos_embedding.py MultimodalRotaryEmbedding / HF Qwen2-VL mrope):
    the D/2 frequency dims split into ``sections`` (sum = D/2); section j's
    rotations use the j-th position row, so temporal/height/width positions
    each drive their own frequency band. With the three rows identical this
    reduces EXACTLY to :func:`rope_cos_sin` over those positions (the
    text-only case — parity-tested). Returns cos/sin [B, S, D/2], the
    gathered-per-token layout :func:`apply_rope` accepts."""
    sections = tuple(int(s) for s in sections)
    if sum(sections) != head_dim // 2:
        raise ValueError(
            f"mrope sections {sections} must sum to head_dim//2 "
            f"= {head_dim // 2}")
    if position_ids.ndim != 3 or position_ids.shape[0] != len(sections):
        raise ValueError(
            f"mrope position_ids must be [{len(sections)}, B, S], got "
            f"{position_ids.shape}")
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    inv_freq = _scale_inv_freq(inv_freq, scaling)
    # [3, B, S, D/2]; frequency dim d draws from position row row[d]
    freqs = position_ids.astype(jnp.float32)[..., None] * inv_freq
    row = jnp.concatenate([
        jnp.full((s,), j, jnp.int32) for j, s in enumerate(sections)])
    sel = jnp.einsum("rbsd,dr->bsd", freqs,
                     jax.nn.one_hot(row, len(sections), dtype=jnp.float32))
    return jnp.cos(sel).astype(dtype), jnp.sin(sel).astype(dtype)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, S, N, D]; rotate-half convention (llama-style). cos/sin are
    [S, D/2] (positions in order) or [B, S, D/2] (gathered per-token
    position ids — packed samples with reset_position_ids)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    if cos.ndim == 2:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    else:
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------


def init_attention(key: jax.Array, cfg: ModelArgs) -> Tuple[Params, Axes]:
    h, hd = cfg.hidden_size, cfg.head_dim
    nq, nkv = cfg.num_attention_heads, cfg.kv_heads
    k1, k2 = jax.random.split(key)
    std = 0.02
    # fused qkv: one MXU matmul; layout [q | k | v] along the wide axis
    p: Params = {
        "wqkv": _normal(k1, (h, (nq + 2 * nkv) * hd), std),
        "wo": _normal(k2, (nq * hd, h), std / math.sqrt(2 * cfg.num_hidden_layers)),
    }
    a: Axes = {"wqkv": ("embed", "qkv"), "wo": ("heads", "embed")}
    if cfg.add_qkv_bias:
        p["bqkv"] = jnp.zeros(((nq + 2 * nkv) * hd,), jnp.float32)
        a["bqkv"] = ("qkv",)
    if cfg.add_bias_linear:
        p["bo"] = jnp.zeros((h,), jnp.float32)
        a["bo"] = ("embed",)
    return p, a


def remat(fn, cfg: ModelArgs):
    """Per-layer activation checkpointing with the configured policy
    (reference parallel.py:213-243 wraps with torch checkpoint_wrapper; the
    TPU lever is WHICH values the backward may keep — saving MXU outputs
    ("dots") trades a little memory for skipping matmul recompute)."""
    if cfg.remat_policy == "dots":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.checkpoint_dots)
    if cfg.remat_policy == "dots_no_batch":
        return jax.checkpoint(
            fn,
            policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims)
    if cfg.remat_policy != "full":
        # model_copy(update=...) skips pydantic validation, so a typo'd
        # policy would otherwise silently run full recompute
        raise ValueError(f"unknown remat_policy {cfg.remat_policy!r} "
                         "(full | dots | dots_no_batch)")
    return jax.checkpoint(fn)


# fold_in stream bases partitioning one per-step dropout key into disjoint
# substreams: decoder layers use their index i directly; these bases keep
# embeddings / encoder layers clear of that range
DROPOUT_STREAM_EMBED = 1 << 20        # (decoder-side) embedding
DROPOUT_STREAM_EMBED_ENC = (1 << 20) + 1  # encoder-side embedding (t5)
DROPOUT_STREAM_ENC = 1 << 21          # + j for encoder layer j


def fold_dropout_rng(rng: Optional[jax.Array], cfg: ModelArgs,
                     idx: int) -> Optional[jax.Array]:
    """None-propagating fold_in, also None when both dropout rates are 0 —
    the single place the per-step key is partitioned (builder, encdec, and
    the pipeline stage programs all route through here)."""
    if rng is None or (cfg.hidden_dropout <= 0.0
                       and cfg.attention_dropout <= 0.0):
        return None
    return jax.random.fold_in(rng, idx)


def dropout(x: jax.Array, rate: float, rng: Optional[jax.Array]) -> jax.Array:
    """Inverted dropout; identity when ``rng is None`` (eval) or rate 0.
    The reference inherits torch's nn.Dropout semantics; here the rng is
    threaded explicitly so training steps stay pure functions."""
    if rng is None or rate <= 0.0:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def xla_sdpa(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = True,
    dropout_rate: float = 0.0, dropout_rng: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Reference attention core on XLA: [B,S,N,D] x [B,T,K,D] -> [B,S,N,D].

    GQA handled by reshaping q into [B,S,K,G,D] groups. Softmax in fp32.
    Swapped out for the Pallas flash kernel / ring attention by the strategy
    dispatch (reference attention.py:664-720 has the same three-way switch).
    ``dropout_rate`` applies attention-probability dropout (reference
    attention.py passes attention_dropout into its cores).
    ``segment_ids`` [B, S] (self-attention only, S == T) block-diagonalizes
    the mask so packed documents cannot attend across boundaries (the
    reference's reset_attention_mask, Megatron
    get_ltor_masks_and_position_ids).
    """
    B, S, N, D = q.shape
    K = k.shape[2]
    G = N // K
    qg = q.reshape(B, S, K, G, D)
    scores = jnp.einsum(
        "bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32
    ) / math.sqrt(D)
    if causal:
        # queries own absolute positions [T-S, T): supports S<T (inference)
        qpos = jnp.arange(S)[:, None] + (k.shape[1] - S)
        kpos = jnp.arange(k.shape[1])[None, :]
        scores = jnp.where(qpos >= kpos, scores, jnp.finfo(jnp.float32).min)
    if segment_ids is not None:
        if k.shape[1] != S:
            raise ValueError("segment_ids require self-attention (S == T)")
        same = segment_ids[:, None, None, :, None] == \
            segment_ids[:, None, None, None, :]
        scores = jnp.where(same, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    probs = dropout(probs, dropout_rate, dropout_rng)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, S, N, D).astype(q.dtype)


def apply_attention(
    p: Params,
    x: jax.Array,
    cfg: ModelArgs,
    rope: Optional[Tuple[jax.Array, jax.Array]] = None,
    sdpa_fn: Callable[..., jax.Array] = xla_sdpa,
    compute_dtype=jnp.bfloat16,
    causal: bool = True,
    dropout_rng: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    matmul_fns: Optional[Dict[str, Callable]] = None,
) -> jax.Array:
    B, S, H = x.shape
    hd = cfg.head_dim
    nq, nkv = cfg.num_attention_heads, cfg.kv_heads
    mm = matmul_fns or {}
    w = p["wqkv"].astype(compute_dtype)
    if "qkv" in mm:
        qkv = mm["qkv"](x.astype(compute_dtype), w)
    else:
        qkv = jnp.einsum("bsh,hf->bsf", x.astype(compute_dtype), w,
                         preferred_element_type=jnp.float32)
    if "bqkv" in p:
        qkv = qkv + p["bqkv"]
    qkv = qkv.astype(compute_dtype)
    q, k, v = jnp.split(qkv, [nq * hd, (nq + nkv) * hd], axis=-1)
    q = q.reshape(B, S, nq, hd)
    k = k.reshape(B, S, nkv, hd)
    v = v.reshape(B, S, nkv, hd)
    if rope is not None:
        cos, sin = rope
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
    use_dropout = dropout_rng is not None and cfg.attention_dropout > 0.0
    if use_dropout:
        # probability dropout lives inside the attention core: the XLA core
        # and the Pallas flash kernel implement it (flash regenerates a
        # counter-based mask per tile in fwd+bwd — the reference's CUDA
        # flash-attn dropout variant). Silently swapping a ring/Ulysses
        # kernel for the score-materializing XLA core would be an OOM/perf
        # cliff on the long-context plans those kernels exist for — refuse.
        if sdpa_fn is xla_sdpa or getattr(sdpa_fn, "supports_dropout",
                                          False):
            out = sdpa_fn(q, k, v, causal=causal,
                          dropout_rate=cfg.attention_dropout,
                          dropout_rng=dropout_rng, segment_ids=segment_ids)
        else:
            raise NotImplementedError(
                "attention_dropout > 0 is only supported with the XLA "
                "attention core and the Pallas flash kernel; the installed "
                "ring/Ulysses kernel has no dropout variant. Avoid "
                "cp/ulysses layers or set model.attention_dropout=0; "
                "hidden_dropout works with every kernel")
    elif segment_ids is not None:
        # packed-document masking: the XLA core, the Pallas flash kernel
        # (per-tile in-kernel) and ring attention (k-side segments rotate
        # with their block) implement it; Ulysses does not
        if sdpa_fn is xla_sdpa or getattr(sdpa_fn, "supports_segments",
                                          False):
            out = sdpa_fn(q, k, v, causal=causal, segment_ids=segment_ids)
        else:
            raise NotImplementedError(
                "reset_attention_mask is not supported by the installed "
                "Ulysses attention kernel; use flash, ring, or the XLA "
                "core for packed-document layers, or set "
                "data.reset_attention_mask=false")
    else:
        out = sdpa_fn(q, k, v, causal=causal)
    out = out.reshape(B, S, nq * hd)
    wo = p["wo"].astype(compute_dtype)
    if "out" in mm:
        y = mm["out"](out, wo)
    else:
        y = jnp.einsum("bsf,fh->bsh", out, wo,
                       preferred_element_type=jnp.float32)
    if "bo" in p:
        y = y + p["bo"]
    return y.astype(compute_dtype)


# ---------------------------------------------------------------------------
# MLP
# ---------------------------------------------------------------------------


def _is_gated(act: str) -> bool:
    return act in ("swiglu", "geglu")


def init_mlp(key: jax.Array, cfg: ModelArgs,
             ffn_dim: Optional[int] = None) -> Tuple[Params, Axes]:
    h = cfg.hidden_size
    f = ffn_dim or cfg.ffn_dim
    k1, k2 = jax.random.split(key)
    std = 0.02
    gated = _is_gated(cfg.hidden_act)
    # gated acts fuse gate+up into one [H, 2F] matmul (one MXU pass)
    p: Params = {
        "win": _normal(k1, (h, 2 * f if gated else f), std),
        "wout": _normal(k2, (f, h), std / math.sqrt(2 * cfg.num_hidden_layers)),
    }
    a: Axes = {"win": ("embed", "mlp"), "wout": ("mlp", "embed")}
    if cfg.add_bias_linear:
        p["bin"] = jnp.zeros((2 * f if gated else f,), jnp.float32)
        p["bout"] = jnp.zeros((h,), jnp.float32)
        a["bin"] = ("mlp",)
        a["bout"] = ("embed",)
    return p, a


_ACTS = {
    "gelu": partial(jax.nn.gelu, approximate=True),
    "gelu_exact": partial(jax.nn.gelu, approximate=False),  # HF BERT erf gelu
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
    "swiglu": jax.nn.silu,  # gate activation
    "geglu": partial(jax.nn.gelu, approximate=True),
}


def apply_mlp(p: Params, x: jax.Array, cfg: ModelArgs,
              compute_dtype=jnp.bfloat16,
              matmul_fns: Optional[Dict[str, Callable]] = None) -> jax.Array:
    act = _ACTS[cfg.hidden_act]
    mm = matmul_fns or {}
    win = p["win"].astype(compute_dtype)
    gated = _is_gated(cfg.hidden_act)
    if gated and "fc1_pair" in mm:
        # overlapped gated fc1: one ring over both weight halves keeps the
        # gate/up PRODUCT shard-aligned — splitting the fused [B, S, 2F]
        # output globally resharded activations per token; the pair form
        # pays only a weight-half reshard instead
        # (ops/overlap.make_ag_matmul_pair)
        F = p["wout"].shape[0]
        gate, up = mm["fc1_pair"](x.astype(compute_dtype),
                                  win[:, :F], win[:, F:])
        if "bin" in p:
            gate = gate + p["bin"][:F]
            up = up + p["bin"][F:]
        hproj = act(gate.astype(compute_dtype)) * up.astype(compute_dtype)
    else:
        if "fc1" in mm:
            hproj = mm["fc1"](x.astype(compute_dtype), win)
        else:
            hproj = jnp.einsum("bsh,hf->bsf", x.astype(compute_dtype), win,
                               preferred_element_type=jnp.float32)
        if "bin" in p:
            hproj = hproj + p["bin"]
        hproj = hproj.astype(compute_dtype)
        if gated:
            gate, up = jnp.split(hproj, 2, axis=-1)
            hproj = act(gate) * up
        else:
            hproj = act(hproj)
    wout = p["wout"].astype(compute_dtype)
    if "fc2" in mm:
        y = mm["fc2"](hproj, wout)
    else:
        y = jnp.einsum("bsf,fh->bsh", hproj, wout,
                       preferred_element_type=jnp.float32)
    if "bout" in p:
        y = y + p["bout"]
    return y.astype(compute_dtype)


# ---------------------------------------------------------------------------
# decoder layer
# ---------------------------------------------------------------------------


def init_decoder_layer(key: jax.Array, cfg: ModelArgs) -> Tuple[Params, Axes]:
    k1, k2 = jax.random.split(key)
    attn_p, attn_a = init_attention(k1, cfg)
    mlp_p, mlp_a = init_mlp(k2, cfg)
    ln1_p, ln1_a = init_norm(cfg)
    ln2_p, ln2_a = init_norm(cfg)
    return (
        {"ln1": ln1_p, "attn": attn_p, "ln2": ln2_p, "mlp": mlp_p},
        {"ln1": ln1_a, "attn": attn_a, "ln2": ln2_a, "mlp": mlp_a},
    )


def apply_decoder_layer(
    p: Params,
    x: jax.Array,
    cfg: ModelArgs,
    rope: Optional[Tuple[jax.Array, jax.Array]] = None,
    sdpa_fn: Callable[..., jax.Array] = xla_sdpa,
    compute_dtype=jnp.bfloat16,
    causal: Optional[bool] = None,
    dropout_rng: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    matmul_fns: Optional[Dict[str, Callable]] = None,
) -> jax.Array:
    """Pre-norm residual block (reference GalvatronDecoderLayer,
    modules.py:233). Encoder families (bert, t5 encoder stack) run the same
    block with bidirectional attention; ``causal=None`` derives from the
    model family. ``dropout_rng`` enables attention/hidden dropout
    (HF semantics: sublayer output dropped before the residual add).
    ``matmul_fns`` ({"qkv", "out", "fc1", "fc2"}) swaps the projection
    matmuls for overlapped tensor-parallel impls (ops/overlap.py)."""
    if causal is None:
        causal = cfg.model_type != "bert"
    r_attn = r_res1 = r_res2 = None
    if dropout_rng is not None:
        r_attn, r_res1, r_res2 = jax.random.split(dropout_rng, 3)

    def drop_h(y, rng):
        return dropout(y, cfg.hidden_dropout, rng)

    if cfg.post_norm:
        # HF BertLayer: residual-then-norm (attention.output.LayerNorm,
        # output.LayerNorm)
        x = apply_norm(
            p["ln1"],
            x + drop_h(apply_attention(p["attn"], x, cfg, rope=rope,
                                       sdpa_fn=sdpa_fn,
                                       compute_dtype=compute_dtype,
                                       causal=causal, dropout_rng=r_attn,
                                       segment_ids=segment_ids,
                                       matmul_fns=matmul_fns),
                       r_res1),
            cfg)
        return apply_norm(
            p["ln2"],
            x + drop_h(apply_mlp(p["mlp"], x, cfg,
                                 compute_dtype=compute_dtype,
                                 matmul_fns=matmul_fns), r_res2),
            cfg)
    h = apply_norm(p["ln1"], x, cfg)
    x = x + drop_h(apply_attention(p["attn"], h, cfg, rope=rope,
                                   sdpa_fn=sdpa_fn,
                                   compute_dtype=compute_dtype, causal=causal,
                                   dropout_rng=r_attn,
                                   segment_ids=segment_ids,
                                   matmul_fns=matmul_fns), r_res1)
    h = apply_norm(p["ln2"], x, cfg)
    x = x + drop_h(apply_mlp(p["mlp"], h, cfg, compute_dtype=compute_dtype,
                             matmul_fns=matmul_fns),
                   r_res2)
    return x


# ---------------------------------------------------------------------------
# embedding / lm head / loss
# ---------------------------------------------------------------------------


def init_embedding(key: jax.Array, cfg: ModelArgs) -> Tuple[Params, Axes]:
    k1, k2 = jax.random.split(key)
    p: Params = {"wte": _normal(k1, (cfg.padded_vocab_size, cfg.hidden_size), 0.02)}
    a: Axes = {"wte": ("vocab", "embed")}
    if cfg.position_embedding_type == "learned":
        p["wpe"] = _normal(k2, (cfg.max_position_embeddings, cfg.hidden_size), 0.02)
        a["wpe"] = ("pos", "embed")
    if cfg.post_norm:
        # HF BertEmbeddings applies LayerNorm after summing the tables;
        # token-type embeddings (single-segment type 0) are folded into wpe
        # by the HF converter (runtime/checkpoint.py)
        ln_p, ln_a = init_norm(cfg)
        p["ln"] = ln_p
        a["ln"] = ln_a
    return p, a


def apply_embedding(p: Params, tokens: jax.Array, cfg: ModelArgs,
                    compute_dtype=jnp.bfloat16,
                    dropout_rng: Optional[jax.Array] = None,
                    position_ids: Optional[jax.Array] = None) -> jax.Array:
    x = jnp.take(p["wte"], tokens, axis=0)
    if "wpe" in p:
        if position_ids is not None:  # packed samples: per-token positions
            x = x + jnp.take(p["wpe"], position_ids, axis=0)
        else:
            S = tokens.shape[1]
            x = x + p["wpe"][:S][None, :, :]
    if "ln" in p:
        x = apply_norm(p["ln"], x, cfg)
    if cfg.scale_embeddings:
        # gemma: hidden states enter the stack scaled by sqrt(hidden)
        x = x * jnp.sqrt(jnp.float32(cfg.hidden_size)).astype(x.dtype)
    # HF GPT2Model.drop / BertEmbeddings.dropout: after sum (+LN for bert)
    x = dropout(x, cfg.hidden_dropout, dropout_rng)
    return x.astype(compute_dtype)


def init_lm_head(key: jax.Array, cfg: ModelArgs) -> Tuple[Params, Axes]:
    if cfg.model_type == "bert":
        # HF BertLMPredictionHead: dense -> act -> LayerNorm -> (tied)
        # decoder + vocab bias (cls.predictions.*)
        k1, k2 = jax.random.split(key)
        ln_p, ln_a = init_norm(cfg)
        p: Params = {"wt": _normal(k1, (cfg.hidden_size, cfg.hidden_size), 0.02),
                     "bt": jnp.zeros((cfg.hidden_size,), jnp.float32),
                     "ln": ln_p,
                     "bias": jnp.zeros((cfg.padded_vocab_size,), jnp.float32)}
        # wt stays un-TP-sharded ("pos" = neutral axis): the transform is one
        # [H,H] matmul whose output feeds a full-width LayerNorm — TP-sharding
        # it would force an all-gather straight after
        a: Axes = {"wt": ("pos", "embed"), "bt": ("embed",),
                   "ln": ln_a, "bias": ("vocab",)}
        if not cfg.tie_word_embeddings:
            p["whead"] = _normal(k2, (cfg.hidden_size, cfg.padded_vocab_size),
                                 0.02)
            a["whead"] = ("embed", "vocab")
        return p, a
    if cfg.tie_word_embeddings:
        return {}, {}
    return (
        {"whead": _normal(key, (cfg.hidden_size, cfg.padded_vocab_size), 0.02)},
        {"whead": ("embed", "vocab")},
    )


def apply_lm_head(
    p: Params,
    x: jax.Array,
    cfg: ModelArgs,
    wte: Optional[jax.Array] = None,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Returns fp32 logits [B, S, V]; tied weights reuse the embedding table
    (reference GalvatronCausalLMHead, modules.py:316-339). The bert path
    runs the HF MLM transform (dense -> act -> LN) and adds the vocab bias.
    A params tree that carries ``whead`` uses it even when the config says
    tied — the pipeline engine's last stage holds the transposed tied copy
    instead of a wte reference (runtime/pipeline.py split_params)."""
    if "wt" in p:
        x = jnp.einsum("bsh,hk->bsk", x.astype(compute_dtype),
                       p["wt"].astype(compute_dtype),
                       preferred_element_type=jnp.float32) + p["bt"]
        x = apply_norm(p["ln"], _ACTS[cfg.hidden_act](x), cfg)
        x = x.astype(compute_dtype)
    w = p["whead"] if "whead" in p else wte.T
    logits = jnp.einsum("bsh,hv->bsv", x.astype(compute_dtype),
                        w.astype(compute_dtype),
                        preferred_element_type=jnp.float32)
    if "bias" in p:
        logits = logits + p["bias"]
    return logits


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    loss_mask: Optional[jax.Array] = None,
    z_loss: float = 0.0,
    fused=False,
) -> jax.Array:
    """Stable mean CE over masked tokens; fp32 throughout.

    Vocab-parallel ready: under GSPMD a vocab-sharded logits array flows
    through logsumexp/take with XLA-inserted collectives, replacing the
    reference's hand-written fused_vocab_parallel_cross_entropy
    (tensor_parallel/triton_cross_entropy.py:219-270).

    ``fused=True`` routes the per-token NLL through the Pallas online
    logsumexp+gather kernel (ops/pallas/cross_entropy.py) on one device;
    distributed callers pass a callable instead (a shard_map nll_fn from
    ``make_vocab_parallel_ce``, matched to the head's sharding). Untileable
    shapes silently use the XLA path (both forms return None for them).
    """
    nll = None
    if callable(fused):
        nll = fused(logits, labels, z_loss=z_loss)
    elif fused:
        from hetu_galvatron_tpu.ops.pallas.cross_entropy import fused_ce_nll

        nll = fused_ce_nll(logits, labels, z_loss=z_loss)
    if nll is None:
        logits = logits.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        nll = lse - gold
        if z_loss:
            nll = nll + z_loss * jnp.square(lse)
    if loss_mask is None:
        return jnp.mean(nll)
    loss_mask = loss_mask.astype(jnp.float32)
    return jnp.sum(nll * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)
