from hetu_galvatron_tpu.models.builder import (  # noqa: F401
    MODULE_REGISTRY,
    build_causal_lm_arch,
    causal_lm_loss,
    forward_causal_lm,
    init_causal_lm,
    model_flops_per_token,
    param_count,
)
