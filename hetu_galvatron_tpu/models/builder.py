"""Generic causal-LM assembly: arch list -> params/axes pytrees -> forward.

Capability parity with the reference's model builder
(runtime/models/builder.py:42-121 ``build_causal_lm_arch`` /
``build_sequential_from_arch`` + MODULE_REGISTRY, modules.py): every supported
model family (gpt2/llama/qwen/mistral/mixtral) is one generic decoder stack
parameterized by :class:`ModelArgs`.

TPU design: the "model" is data, not objects — ``init_causal_lm`` returns a
nested params dict plus a parallel tree of logical-axis names; ``forward``
is a pure function. Per-layer heterogeneity (different sharding, remat flag,
attention impl per layer) enters through ``layer_overrides`` rather than
module wrappers, so one traced program covers any searched strategy.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.models import modules as M

Params = Dict[str, Any]

# Registry of arch-entry -> (init, apply); mirrors the reference
# MODULE_REGISTRY (builder.py:41) keyed by the same role names.
MODULE_REGISTRY: Dict[str, Tuple[Callable, Callable]] = {
    "embed": (M.init_embedding, M.apply_embedding),
    "decoder": (M.init_decoder_layer, M.apply_decoder_layer),
    "prenorm": (M.init_norm, M.apply_norm),
    "head": (M.init_lm_head, M.apply_lm_head),
}


def build_causal_lm_arch(cfg: ModelArgs) -> List[str]:
    """Arch role list (reference build_causal_lm_arch builder.py:111-121)."""
    return ["embed"] + ["decoder"] * cfg.num_hidden_layers + ["prenorm", "head"]


def init_causal_lm(key: jax.Array, cfg: ModelArgs) -> Tuple[Params, Params]:
    """Returns (params, logical_axes) with layers as a per-layer tuple so the
    axes tree mirrors params exactly (required for tree-mapped shardings).
    MoE models alternate dense/MoE layers per moe_layer_freq; t5 builds the
    encoder-decoder pair (models/encdec.py)."""
    from hetu_galvatron_tpu.models.moe import init_moe_decoder_layer, is_moe_layer

    if cfg.model_type == "t5":
        from hetu_galvatron_tpu.models.encdec import init_encdec

        return init_encdec(key, cfg)

    n = cfg.num_hidden_layers
    keys = jax.random.split(key, n + 2)
    embed_p, embed_a = M.init_embedding(keys[0], cfg)
    layers = [
        (init_moe_decoder_layer(keys[1 + i], cfg) if is_moe_layer(cfg, i)
         else M.init_decoder_layer(keys[1 + i], cfg))
        for i in range(n)
    ]
    if cfg.post_norm:
        # post-norm families (bert) end each block already normalized; the
        # MLM head's transform LayerNorm is the final norm (HF BertLayer +
        # BertLMPredictionHead layout) — apply_norm({}) is the identity
        prenorm_p, prenorm_a = {}, {}
    else:
        prenorm_p, prenorm_a = M.init_norm(cfg)
    head_p, head_a = M.init_lm_head(keys[n + 1], cfg)
    params = {
        "embed": embed_p,
        "layers": tuple(lp for lp, _ in layers),
        "prenorm": prenorm_p,
        "head": head_p,
    }
    axes = {
        "embed": embed_a,
        "layers": tuple(la for _, la in layers),
        "prenorm": prenorm_a,
        "head": head_a,
    }
    return params, axes


def forward_causal_lm(
    params: Params,
    tokens: jax.Array,
    cfg: ModelArgs,
    *,
    compute_dtype=jnp.bfloat16,
    remat_flags: Optional[Sequence[bool]] = None,
    layer_overrides: Optional[Dict[int, Dict[str, Any]]] = None,
    boundary_fn: Optional[Callable[[int, jax.Array], jax.Array]] = None,
    logits_fp32: bool = True,
    with_aux: bool = False,
    dropout_rng: Optional[jax.Array] = None,
    position_ids: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    mrope_position_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """tokens [B, S] -> logits [B, S, V].

    ``dropout_rng`` (training only) enables cfg.attention_dropout /
    cfg.hidden_dropout; ``None`` (the default) is eval semantics — dropout
    layers are the identity, so existing callers are unchanged.

    ``position_ids`` / ``segment_ids`` [B, S] implement the reference's
    reset_position_ids / reset_attention_mask for packed multi-document
    samples: positions restart at 0 after each eod and attention is
    block-diagonalized per document (dataloader.packed_doc_fields).

    ``remat_flags[i]`` turns on `jax.checkpoint` for layer i (the reference's
    per-layer checkpoint_flags_enc, parallel.py:213-243). ``layer_overrides``
    maps layer index -> kwargs for :func:`modules.apply_decoder_layer`
    (e.g. a different ``sdpa_fn`` for Ulysses/ring layers). ``boundary_fn(i,
    x)`` is applied to the hidden state before layer i and once after the last
    layer (i == num layers) — the SPMD layer uses it to place
    `with_sharding_constraint` resharding at layer boundaries, replacing the
    reference's relocation wrappers (runtime/parallel.py:272-304).
    """
    from hetu_galvatron_tpu.models.moe import apply_moe_decoder_layer

    S = tokens.shape[1]
    rope = None
    if cfg.position_embedding_type == "rope" and cfg.mrope_section:
        # multimodal rope: per-axis positions [3, B, S]; text-only callers
        # (no mrope_position_ids) broadcast their 1-D positions, which is
        # exactly standard rope (modules.mrope_cos_sin docstring)
        mpos = mrope_position_ids
        if mpos is None:
            base = (position_ids if position_ids is not None
                    else jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32),
                                          tokens.shape))
            mpos = jnp.broadcast_to(base[None],
                                    (len(cfg.mrope_section),) + base.shape)
        rope = M.mrope_cos_sin(mpos, cfg.head_dim, cfg.rope_theta,
                               sections=cfg.mrope_section,
                               scaling=cfg.rope_scaling)
    elif cfg.position_embedding_type == "rope":
        cos, sin = M.rope_cos_sin(S, cfg.head_dim, cfg.rope_theta,
                                  scaling=cfg.rope_scaling)
        if position_ids is not None:
            # packed samples: gather per-token rows -> [B, S, D/2]
            cos, sin = cos[position_ids], sin[position_ids]
        rope = (cos, sin)
    x = M.apply_embedding(
        params["embed"], tokens, cfg, compute_dtype=compute_dtype,
        dropout_rng=M.fold_dropout_rng(dropout_rng, cfg,
                                       M.DROPOUT_STREAM_EMBED),
        position_ids=position_ids)
    aux_total = jnp.zeros((), jnp.float32)
    moe_stats: Dict[str, Dict[str, jax.Array]] = {}
    for i, lp in enumerate(params["layers"]):
        if boundary_fn is not None:
            x = boundary_fn(i, x)
        kwargs: Dict[str, Any] = dict(rope=rope, compute_dtype=compute_dtype)
        if segment_ids is not None:
            kwargs["segment_ids"] = segment_ids
        if dropout_rng is not None:
            kwargs["dropout_rng"] = M.fold_dropout_rng(dropout_rng, cfg, i)
        if layer_overrides and i in layer_overrides:
            kwargs.update(layer_overrides[i])
        if "moe" in lp:
            fn = lambda p, h, kw=kwargs: apply_moe_decoder_layer(
                p, h, cfg, **kw)
        else:
            fn = lambda p, h, kw=kwargs: (
                M.apply_decoder_layer(p, h, cfg, **kw),
                jnp.zeros((), jnp.float32), {})
        if remat_flags is not None and remat_flags[i]:
            fn = M.remat(fn, cfg)
        x, aux, stats = fn(lp, x)
        aux_total = aux_total + aux
        if stats:
            # per-layer balance tracker (reference moe_utils.py:547-644)
            moe_stats[f"layer{i}"] = stats
    if boundary_fn is not None:
        x = boundary_fn(len(params["layers"]), x)
    x = M.apply_norm(params["prenorm"], x, cfg)
    logits = M.apply_lm_head(
        params["head"], x, cfg,
        wte=params["embed"]["wte"], compute_dtype=compute_dtype,
    )
    logits = logits if logits_fp32 else logits.astype(compute_dtype)
    return (logits, aux_total, moe_stats) if with_aux else logits


def causal_lm_loss(
    params: Params,
    batch: Dict[str, jax.Array],
    cfg: ModelArgs,
    *,
    compute_dtype=jnp.bfloat16,
    remat_flags: Optional[Sequence[bool]] = None,
    layer_overrides: Optional[Dict[int, Dict[str, Any]]] = None,
    boundary_fn: Optional[Callable[[int, jax.Array], jax.Array]] = None,
    enc_remat_flags: Optional[Sequence[bool]] = None,
    enc_layer_overrides: Optional[Dict[int, Dict[str, Any]]] = None,
    enc_boundary_fn: Optional[Callable[[int, jax.Array], jax.Array]] = None,
    fused_ce: Union[None, bool, Callable] = None,
    with_moe_stats: bool = False,
) -> jax.Array:
    """batch: tokens [B,S], labels [B,S], optional loss_mask [B,S] -> scalar
    (or (scalar, per-layer MoE stats dict) with ``with_moe_stats=True`` —
    the reference's aux-losses tracker, moe_utils.py:547-644).

    Equivalent role to the reference's loss closure from the dataloader
    (dataloader.py:558 _loss_func + train_dist.py forward_backward wiring).
    t5 batches route to the encoder-decoder loss; the ``enc_*`` knobs index
    the encoder stack and are only meaningful there.

    ``fused_ce`` overrides ``cfg.use_fused_ce``: True runs the Pallas CE
    kernel directly (single device); on multi-device meshes the distributed
    builder passes a shard_map nll callable from ``make_vocab_parallel_ce``
    instead (a bare Pallas call is a custom call GSPMD cannot partition).
    """
    fused = cfg.use_fused_ce if fused_ce is None else fused_ce
    if cfg.model_type == "t5":
        from hetu_galvatron_tpu.models.encdec import encdec_loss

        loss = encdec_loss(params, batch, cfg, compute_dtype=compute_dtype,
                           remat_flags=remat_flags,
                           enc_remat_flags=enc_remat_flags,
                           boundary_fn=boundary_fn,
                           enc_boundary_fn=enc_boundary_fn,
                           layer_overrides=layer_overrides,
                           enc_layer_overrides=enc_layer_overrides,
                           fused_ce=fused)
        return (loss, {}) if with_moe_stats else loss
    logits, aux, moe_stats = forward_causal_lm(
        params, batch["tokens"], cfg,
        compute_dtype=compute_dtype, remat_flags=remat_flags,
        layer_overrides=layer_overrides, boundary_fn=boundary_fn,
        with_aux=True, dropout_rng=batch.get("dropout_rng"),
        position_ids=batch.get("position_ids"),
        segment_ids=batch.get("segment_ids"),
        mrope_position_ids=batch.get("mrope_position_ids"),
    )
    ce = M.cross_entropy_loss(logits, batch["labels"], batch.get("loss_mask"),
                              fused=fused)
    loss = ce + aux
    return (loss, moe_stats) if with_moe_stats else loss


def param_count(params: Params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


def model_flops_per_token(cfg: ModelArgs, seq_len: Optional[int] = None) -> float:
    """Approximate training FLOPs per token (6*N params + attention term),
    used by the MFU computation in bench/profilers."""
    s = seq_len or cfg.seq_length
    h, f, v = cfg.hidden_size, cfg.ffn_dim, cfg.padded_vocab_size
    nq, nkv, hd = cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim
    per_layer = 2 * h * (nq + 2 * nkv) * hd  # qkv
    per_layer += 2 * nq * hd * h  # proj
    per_layer += 2 * h * f * (3 if M._is_gated(cfg.hidden_act) else 2)  # mlp
    attn = 2 * 2 * s * nq * hd  # qk^T + pv per token
    dense = cfg.num_hidden_layers * (per_layer + attn) + 2 * h * v
    return 3.0 * dense  # fwd + bwd(2x)
