"""Encoder-decoder (T5-family) stacks: cross-attention + seq2seq assembly.

Completes the BASELINE milestone-4 family (T5-style encoder-decoder with
asymmetric stacks). The reference snapshot ships no T5 runtime — this is
built on the same functional-module vocabulary as the decoder
(models/modules.py): an encoder of bidirectional blocks, a decoder whose
blocks add cross-attention over the encoder output, and a shared token
embedding. Positions use the configured scheme (RoPE/learned) in both stacks
rather than T5's relative bias — the parallelism machinery (this framework's
subject) is position-scheme agnostic.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.models import modules as M

Params = Dict[str, Any]


def encoder_layers(cfg: ModelArgs) -> int:
    return (cfg.num_encoder_layers if cfg.num_encoder_layers is not None
            else cfg.num_hidden_layers)


def init_cross_attention(key: jax.Array, cfg: ModelArgs) -> Tuple[Params, Params]:
    """Q from the decoder stream, fused KV from the encoder output."""
    h, hd = cfg.hidden_size, cfg.head_dim
    nq, nkv = cfg.num_attention_heads, cfg.kv_heads
    k1, k2, k3 = jax.random.split(key, 3)
    std = 0.02
    p: Params = {
        "wq": M._normal(k1, (h, nq * hd), std),
        "wkv": M._normal(k2, (h, 2 * nkv * hd), std),
        "wo": M._normal(k3, (nq * hd, h),
                        std / math.sqrt(2 * cfg.num_hidden_layers)),
    }
    a: Params = {"wq": ("embed", "qkv"), "wkv": ("embed", "qkv"),
                 "wo": ("heads", "embed")}
    if cfg.add_qkv_bias:
        p["bq"] = jnp.zeros((nq * hd,), jnp.float32)
        p["bkv"] = jnp.zeros((2 * nkv * hd,), jnp.float32)
        a["bq"] = ("qkv",)
        a["bkv"] = ("qkv",)
    if cfg.add_bias_linear:
        p["bo"] = jnp.zeros((h,), jnp.float32)
        a["bo"] = ("embed",)
    return p, a


def cross_kv(p: Params, memory: jax.Array, cfg: ModelArgs,
             compute_dtype=jnp.bfloat16) -> Tuple[jax.Array, jax.Array]:
    """Project the encoder memory to cross-attention (k, v) [B, S, Nkv, D].
    Decode caches this once per layer (the memory never changes during
    generation) instead of re-projecting every step."""
    nkv, hd = cfg.kv_heads, cfg.head_dim
    kv = jnp.einsum("bsh,hf->bsf", memory.astype(compute_dtype),
                    p["wkv"].astype(compute_dtype),
                    preferred_element_type=jnp.float32)
    if "bkv" in p:
        kv = kv + p["bkv"]
    k, v = jnp.split(kv.astype(compute_dtype), 2, axis=-1)
    S = memory.shape[1]
    return k.reshape(-1, S, nkv, hd), v.reshape(-1, S, nkv, hd)


def apply_cross_attention(
    p: Params,
    x: jax.Array,       # decoder stream [B, T, H]
    memory: jax.Array,  # encoder output [B, S, H]
    cfg: ModelArgs,
    sdpa_fn: Callable[..., jax.Array] = M.xla_sdpa,
    compute_dtype=jnp.bfloat16,
    dropout_rng=None,
    cached_kv: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> jax.Array:
    B, T, H = x.shape
    hd = cfg.head_dim
    nq = cfg.num_attention_heads
    q = jnp.einsum("bth,hf->btf", x.astype(compute_dtype),
                   p["wq"].astype(compute_dtype),
                   preferred_element_type=jnp.float32)
    if "bq" in p:
        q = q + p["bq"]
    q = q.astype(compute_dtype).reshape(B, T, nq, hd)
    k, v = (cached_kv if cached_kv is not None
            else cross_kv(p, memory, cfg, compute_dtype))
    # decoder sees the whole source; probability dropout mirrors
    # modules.apply_attention (HF T5Attention drops attention weights in
    # BOTH self- and cross-attention): the XLA core and dropout-capable
    # kernels (flash) implement it in-place; others refuse loudly
    if dropout_rng is not None and cfg.attention_dropout > 0.0:
        if sdpa_fn is M.xla_sdpa or getattr(sdpa_fn, "supports_dropout",
                                            False):
            out = sdpa_fn(q, k, v, causal=False,
                          dropout_rate=cfg.attention_dropout,
                          dropout_rng=dropout_rng)
        else:
            raise NotImplementedError(
                "attention_dropout > 0 needs the XLA attention core or a "
                "dropout-capable kernel (flash) for cross-attention "
                "(see modules.apply_attention)")
    else:
        out = sdpa_fn(q, k, v, causal=False)
    y = jnp.einsum("btf,fh->bth", out.reshape(B, T, nq * hd),
                   p["wo"].astype(compute_dtype),
                   preferred_element_type=jnp.float32)
    if "bo" in p:
        y = y + p["bo"]
    return y.astype(compute_dtype)


def init_cross_decoder_layer(key: jax.Array, cfg: ModelArgs
                             ) -> Tuple[Params, Params]:
    k1, k2, k3 = jax.random.split(key, 3)
    self_p, self_a = M.init_attention(k1, cfg)
    cross_p, cross_a = init_cross_attention(k2, cfg)
    mlp_p, mlp_a = M.init_mlp(k3, cfg)
    ln1_p, ln1_a = M.init_norm(cfg)
    lnx_p, lnx_a = M.init_norm(cfg)
    ln2_p, ln2_a = M.init_norm(cfg)
    return (
        {"ln1": ln1_p, "attn": self_p, "lnx": lnx_p, "cross": cross_p,
         "ln2": ln2_p, "mlp": mlp_p},
        {"ln1": ln1_a, "attn": self_a, "lnx": lnx_a, "cross": cross_a,
         "ln2": ln2_a, "mlp": mlp_a},
    )


def apply_cross_decoder_layer(
    p: Params,
    x: jax.Array,
    memory: jax.Array,
    cfg: ModelArgs,
    rope=None,
    sdpa_fn: Callable[..., jax.Array] = M.xla_sdpa,
    cross_sdpa_fn: Optional[Callable[..., jax.Array]] = None,
    compute_dtype=jnp.bfloat16,
    dropout_rng=None,
    cached_cross_kv: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> jax.Array:
    """Pre-norm: causal self-attention -> cross-attention -> MLP.

    ``sdpa_fn`` drives the (causal) self-attention; cross-attention uses
    ``cross_sdpa_fn`` when given, else ``sdpa_fn`` — the dispatch layer
    (parallel/spmd.py attention_overrides) passes a non-causal-capable kernel
    here (flash handles causal=False; ring layers fall back to the XLA core
    because the decoder/encoder sequence lengths differ)."""
    r_attn = r_xattn = r1 = r2 = r3 = None
    if dropout_rng is not None:
        r_attn, r_xattn, r1, r2, r3 = jax.random.split(dropout_rng, 5)

    def drop_h(y, rng):
        return M.dropout(y, cfg.hidden_dropout, rng)

    h = M.apply_norm(p["ln1"], x, cfg)
    x = x + drop_h(M.apply_attention(p["attn"], h, cfg, rope=rope,
                                     sdpa_fn=sdpa_fn,
                                     compute_dtype=compute_dtype, causal=True,
                                     dropout_rng=r_attn), r1)
    h = M.apply_norm(p["lnx"], x, cfg)
    x = x + drop_h(apply_cross_attention(p["cross"], h, memory, cfg,
                                         sdpa_fn=cross_sdpa_fn or sdpa_fn,
                                         compute_dtype=compute_dtype,
                                         dropout_rng=r_xattn,
                                         cached_kv=cached_cross_kv), r2)
    h = M.apply_norm(p["ln2"], x, cfg)
    x = x + drop_h(M.apply_mlp(p["mlp"], h, cfg,
                               compute_dtype=compute_dtype), r3)
    return x


def init_encdec(key: jax.Array, cfg: ModelArgs) -> Tuple[Params, Params]:
    """Full T5-style model: shared embedding, encoder stack, decoder stack
    with cross-attention, final norm, (un)tied head."""
    n_enc = encoder_layers(cfg)
    n_dec = cfg.num_hidden_layers
    keys = jax.random.split(key, n_enc + n_dec + 3)
    embed_p, embed_a = M.init_embedding(keys[0], cfg)
    enc = [M.init_decoder_layer(keys[1 + i], cfg) for i in range(n_enc)]
    dec = [init_cross_decoder_layer(keys[1 + n_enc + i], cfg)
           for i in range(n_dec)]
    enc_norm_p, enc_norm_a = M.init_norm(cfg)
    prenorm_p, prenorm_a = M.init_norm(cfg)
    head_p, head_a = M.init_lm_head(keys[-1], cfg)
    params = {
        "embed": embed_p,
        "enc_layers": tuple(p for p, _ in enc),
        "enc_norm": enc_norm_p,
        "layers": tuple(p for p, _ in dec),
        "prenorm": prenorm_p,
        "head": head_p,
    }
    axes = {
        "embed": embed_a,
        "enc_layers": tuple(a for _, a in enc),
        "enc_norm": enc_norm_a,
        "layers": tuple(a for _, a in dec),
        "prenorm": prenorm_a,
        "head": head_a,
    }
    return params, axes


def encode(params: Params, enc_tokens: jax.Array, cfg: ModelArgs, *,
           compute_dtype=jnp.bfloat16) -> jax.Array:
    """Encoder-only forward -> memory [B, S, H] (the encoder runs ONCE per
    generation; decode steps reuse the memory via cached cross k/v)."""
    rope_enc = None
    if cfg.position_embedding_type == "rope":
        rope_enc = M.rope_cos_sin(enc_tokens.shape[1], cfg.head_dim,
                                  cfg.rope_theta, scaling=cfg.rope_scaling)
    mem = M.apply_embedding(params["embed"], enc_tokens, cfg,
                            compute_dtype=compute_dtype)
    for lp in params["enc_layers"]:
        mem = M.apply_decoder_layer(lp, mem, cfg, rope=rope_enc,
                                    compute_dtype=compute_dtype,
                                    causal=False)
    return M.apply_norm(params["enc_norm"], mem, cfg)


def forward_encdec(
    params: Params,
    enc_tokens: jax.Array,
    dec_tokens: jax.Array,
    cfg: ModelArgs,
    *,
    compute_dtype=jnp.bfloat16,
    remat_flags=None,
    enc_remat_flags=None,
    boundary_fn=None,
    enc_boundary_fn=None,
    layer_overrides=None,
    enc_layer_overrides=None,
    logits_fp32: bool = True,
    dropout_rng=None,
) -> jax.Array:
    """(enc_tokens [B,S], dec_tokens [B,T]) -> logits [B,T,V].

    Per-layer knobs mirror the decoder-only builder (models/builder.py):
    ``remat_flags`` / ``boundary_fn`` / ``layer_overrides`` index DECODER
    layers; the ``enc_*`` triplet indexes ENCODER layers (heterogeneous
    per-layer encoder plans — the combined-stack strategy list of
    runtime/hybrid_config.py). When ``enc_remat_flags`` is None the encoder
    falls back to ``remat_flags[0]`` uniformly (legacy behavior)."""
    rope_enc = rope_dec = None
    if cfg.position_embedding_type == "rope":
        rope_enc = M.rope_cos_sin(enc_tokens.shape[1], cfg.head_dim,
                                  cfg.rope_theta, scaling=cfg.rope_scaling)
        rope_dec = M.rope_cos_sin(dec_tokens.shape[1], cfg.head_dim,
                                  cfg.rope_theta, scaling=cfg.rope_scaling)

    if enc_remat_flags is None and remat_flags:
        enc_remat_flags = [bool(remat_flags[0])] * len(params["enc_layers"])
    # disjoint fold_in streams: encoder layers, decoder layers, embeddings
    r_embed_e = M.fold_dropout_rng(dropout_rng, cfg,
                                   M.DROPOUT_STREAM_EMBED_ENC)
    r_embed_d = M.fold_dropout_rng(dropout_rng, cfg, M.DROPOUT_STREAM_EMBED)
    mem = M.apply_embedding(params["embed"], enc_tokens, cfg,
                            compute_dtype=compute_dtype,
                            dropout_rng=r_embed_e)
    for i, lp in enumerate(params["enc_layers"]):
        if enc_boundary_fn is not None:
            mem = enc_boundary_fn(i, mem)
        kwargs: Dict[str, Any] = dict(rope=rope_enc,
                                      compute_dtype=compute_dtype,
                                      causal=False)
        if dropout_rng is not None:
            kwargs["dropout_rng"] = M.fold_dropout_rng(
                dropout_rng, cfg, M.DROPOUT_STREAM_ENC + i)
        if enc_layer_overrides and i in enc_layer_overrides:
            kwargs.update(enc_layer_overrides[i])
        kwargs.pop("cross_sdpa_fn", None)  # encoder blocks have no cross-attn
        fn = lambda p, h, kw=kwargs: M.apply_decoder_layer(p, h, cfg, **kw)
        if enc_remat_flags is not None and enc_remat_flags[i]:
            fn = M.remat(fn, cfg)
        mem = fn(lp, mem)
    if enc_boundary_fn is not None:
        mem = enc_boundary_fn(len(params["enc_layers"]), mem)
    mem = M.apply_norm(params["enc_norm"], mem, cfg)

    x = M.apply_embedding(params["embed"], dec_tokens, cfg,
                          compute_dtype=compute_dtype,
                          dropout_rng=r_embed_d)
    for i, lp in enumerate(params["layers"]):
        if boundary_fn is not None:
            x = boundary_fn(i, x)
        kwargs = dict(rope=rope_dec, compute_dtype=compute_dtype)
        if dropout_rng is not None:
            kwargs["dropout_rng"] = M.fold_dropout_rng(dropout_rng, cfg, i)
        if layer_overrides and i in layer_overrides:
            kwargs.update(layer_overrides[i])
        fn = lambda p, h, m, kw=kwargs: apply_cross_decoder_layer(
            p, h, m, cfg, **kw)
        if remat_flags is not None and remat_flags[i]:
            fn = M.remat(fn, cfg)
        x = fn(lp, x, mem)
    if boundary_fn is not None:
        x = boundary_fn(len(params["layers"]), x)
    x = M.apply_norm(params["prenorm"], x, cfg)
    logits = M.apply_lm_head(params["head"], x, cfg,
                             wte=params["embed"]["wte"],
                             compute_dtype=compute_dtype)
    return logits if logits_fp32 else logits.astype(compute_dtype)


def encdec_loss(
    params: Params,
    batch: Dict[str, jax.Array],
    cfg: ModelArgs,
    *,
    compute_dtype=jnp.bfloat16,
    remat_flags=None,
    enc_remat_flags=None,
    boundary_fn=None,
    enc_boundary_fn=None,
    layer_overrides=None,
    enc_layer_overrides=None,
    fused_ce=False,  # bool, or a shard_map nll callable (see builder)
) -> jax.Array:
    """batch: enc_tokens [B,S], tokens (decoder input) [B,T], labels [B,T],
    optional loss_mask."""
    logits = forward_encdec(params, batch["enc_tokens"], batch["tokens"],
                            cfg, compute_dtype=compute_dtype,
                            remat_flags=remat_flags,
                            enc_remat_flags=enc_remat_flags,
                            boundary_fn=boundary_fn,
                            enc_boundary_fn=enc_boundary_fn,
                            layer_overrides=layer_overrides,
                            enc_layer_overrides=enc_layer_overrides,
                            dropout_rng=batch.get("dropout_rng"))
    return M.cross_entropy_loss(logits, batch["labels"],
                                batch.get("loss_mask"), fused=fused_ce)
