"""Mixture-of-Experts layer: routers + token dispatchers + grouped MLPs.

Capability parity with the reference MoE runtime (runtime/moe/router.py:98
``TopKRouter`` with aux/z-losses, sinkhorn load balancing and the
aux-loss-free expert-bias correction; token_dispatcher.py:116/287/942
allgather/alltoall/flex dispatchers; mlp.py:26 ``GroupedMLP``;
moe_utils.py:166 aux-loss scaling).

TPU-first: two dispatch formulations replace the reference's three torch
dispatchers —

* ``capacity`` (GShard one-hot einsums): dispatch/combine are dense einsums
  over a fixed per-expert capacity; sharding the ``expert`` axis over the ep
  mesh axes makes GSPMD insert the token all-to-alls the reference issues by
  hand. Over-capacity tokens are dropped (weights renormalized). This is the
  expert-parallel mode — every shape is static and ep/etp-shardable.
* ``dropless`` (sort + ``lax.ragged_dot``): token slots are sorted by expert
  and the expert MLPs run as grouped ragged matmuls — no token is ever
  dropped and no capacity buffer is materialized (the reference's alltoall
  dropless dispatcher, token_dispatcher.py:287). Static [T*K] shapes keep it
  jit-clean; HF Mixtral numerics reproduce exactly (see
  tests/models/test_moe.py Mixtral parity).

Routers: softmax top-k (optionally with the DeepSeek-style expert-bias
selection correction, reference router.py expert_bias) and sinkhorn load
balancing (selection via a no-grad sinkhorn normalization, weights via
sigmoid/softmax of the raw logits — reference sinkhorn_load_balancing).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.models import modules as M

Params = Dict[str, Any]


def is_moe_layer(cfg: ModelArgs, layer_idx: int) -> bool:
    """Dense/MoE alternation: every moe_layer_freq-th layer is MoE
    (reference moe_layer_freq semantics, hf adapter layertype split)."""
    if not cfg.num_experts:
        return False
    freq = max(cfg.moe_layer_freq, 1)
    return (layer_idx + 1) % freq == 0


def moe_capacity(cfg: ModelArgs, tokens: int,
                 capacity_factor: Optional[float] = None) -> int:
    """Per-expert token capacity (reference capacity-factor dispatch)."""
    cf = capacity_factor if capacity_factor is not None \
        else cfg.moe_capacity_factor
    return max(int(math.ceil(tokens * cfg.moe_topk / cfg.num_experts
                             * cf)), cfg.moe_topk)


def sinkhorn(logits: jax.Array, n_iters: int = 8) -> jax.Array:
    """Sinkhorn normalization of a [T, E] score matrix (reference
    moe_utils.sinkhorn, fixed iteration count for jit)."""
    cost = jnp.exp(logits.astype(jnp.float32))
    T, E = cost.shape
    d1 = jnp.ones((E,), jnp.float32)

    def body(_, d1):
        d0 = 1.0 / T / jnp.maximum((cost * d1[None, :]).sum(-1), 1e-9)
        return 1.0 / E / jnp.maximum((cost * d0[:, None]).sum(0), 1e-9)

    d1 = jax.lax.fori_loop(0, n_iters, body, d1)
    d0 = 1.0 / T / jnp.maximum((cost * d1[None, :]).sum(-1), 1e-9)
    return d0[:, None] * cost * d1[None, :]


def route_tokens(
    p: Params, xt: jax.Array, cfg: ModelArgs, compute_dtype=jnp.bfloat16
) -> Tuple[jax.Array, jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Router: [T, H] tokens -> (topk_idx [T,K] int, weights [T,K] fp32,
    aux_loss scalar, stats dict).

    ``stats`` carries the per-layer balance observables the reference logs
    through its aux-losses tracker (moe_utils.py:547-644
    save_to_aux_losses_tracker / reduce_aux_losses_tracker_across_ranks):
    the load-balance loss, the z-loss, and tokens_per_expert [E].

    topk: softmax probs; selection optionally corrected by a no-grad expert
    bias (p["expert_bias"], reference moe_router_enable_expert_bias — the
    bias steers WHICH experts are picked, never the combine weights);
    weights renormalized over the selected k (HF Mixtral convention).
    sinkhorn: selection from a no-grad sinkhorn normalization; weights are
    sigmoid (k=1) / softmax (k>1) of the raw logits (reference
    sinkhorn_load_balancing; aux loss unsupported there)."""
    E, K = cfg.num_experts, cfg.moe_topk
    router_dtype = jnp.float32 if cfg.moe_router_dtype == "float32" \
        else compute_dtype
    logits = jnp.einsum("th,he->te", xt.astype(router_dtype),
                        p["router"].astype(router_dtype),
                        preferred_element_type=jnp.float32)

    if cfg.moe_router_type == "sinkhorn":
        if cfg.moe_aux_loss_coeff:
            raise ValueError(
                "sinkhorn routing does not support the aux loss "
                "(reference router.py:158); set moe_aux_loss_coeff=0")
        norm = jax.lax.stop_gradient(sinkhorn(logits))
        _, topk_idx = jax.lax.top_k(norm, K)
        scores = (jax.nn.sigmoid(logits) if K == 1
                  else jax.nn.softmax(logits, axis=-1))
        w = jnp.take_along_axis(scores, topk_idx, axis=-1)
        aux = jnp.zeros((), jnp.float32)
        zloss = jnp.zeros((), jnp.float32)
        if cfg.moe_z_loss_coeff:
            z = jax.scipy.special.logsumexp(logits, axis=-1)
            zloss = cfg.moe_z_loss_coeff * jnp.mean(jnp.square(z))
            aux = zloss
        counts = jnp.sum(jax.nn.one_hot(topk_idx, E, dtype=jnp.float32),
                         axis=(0, 1))
        stats = {"load_balance_loss": jnp.zeros((), jnp.float32),
                 "z_loss": zloss,
                 "tokens_per_expert": jax.lax.stop_gradient(counts)}
        return topk_idx, w.astype(jnp.float32), aux, stats

    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]
    select_scores = probs
    if "expert_bias" in p:
        select_scores = probs + jax.lax.stop_gradient(p["expert_bias"])
    _, topk_idx = jax.lax.top_k(select_scores, K)
    bias_term = None
    if "expert_bias" in p:
        # aux-loss-free maintenance, routed THROUGH the gradient: this term
        # has value 0 but d/d(expert_bias) = -update, and the optimizer
        # applies plain SGD(lr=1) to expert_bias paths
        # (runtime/optimizer.py partition), so bias_new = bias + update —
        # the reference's buffer update (router.py:116) without mutating
        # state inside a pure function. stop_gradient everywhere else keeps
        # the model's real gradients untouched.
        counts = jnp.sum(jax.nn.one_hot(topk_idx, E, dtype=jnp.float32),
                         axis=(0, 1))
        update = update_expert_bias(jnp.zeros((E,), jnp.float32), counts,
                                    cfg.moe_expert_bias_update_rate)
        term = jnp.sum(jax.lax.stop_gradient(-update) * p["expert_bias"])
        bias_term = term - jax.lax.stop_gradient(term)
    topk_probs = jnp.take_along_axis(probs, topk_idx, axis=-1)
    # renormalize over the selected k (HF Mixtral convention; the reference's
    # moe_router_topk_scaling path covers the same role)
    topk_probs = topk_probs / jnp.maximum(
        jnp.sum(topk_probs, axis=-1, keepdims=True), 1e-9)

    # aux losses (reference router.py aux/z-loss; moe_utils.py:166 scaling)
    sel = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)  # [T, K, E]
    tokens_per_expert = jnp.sum(sel, axis=(0, 1))  # [E]
    frac_tokens = jnp.mean(jnp.sum(sel, axis=1), axis=0)  # f_e
    frac_probs = jnp.mean(probs, axis=0)  # P_e
    balance = cfg.moe_aux_loss_coeff * E * jnp.sum(frac_tokens * frac_probs)
    zloss = jnp.zeros((), jnp.float32)
    if cfg.moe_z_loss_coeff:
        z = jax.scipy.special.logsumexp(logits, axis=-1)
        zloss = cfg.moe_z_loss_coeff * jnp.mean(jnp.square(z))
    aux = balance + zloss
    if bias_term is not None:
        aux = aux + bias_term  # value 0; carries the bias-maintenance grad
    stats = {"load_balance_loss": jax.lax.stop_gradient(balance),
             "z_loss": jax.lax.stop_gradient(zloss),
             "tokens_per_expert": jax.lax.stop_gradient(tokens_per_expert)}
    return topk_idx, topk_probs.astype(jnp.float32), aux, stats


def update_expert_bias(expert_bias: jax.Array, tokens_per_expert: jax.Array,
                       update_rate: float = 1e-3) -> jax.Array:
    """Aux-loss-free balancing step (reference expert-bias maintenance):
    nudge under-loaded experts' selection bias up, over-loaded down. The
    trainer calls this outside the gradient path with the batch's per-expert
    token counts."""
    err = jnp.mean(tokens_per_expert) - tokens_per_expert
    return expert_bias + update_rate * jnp.sign(err)


def init_moe_mlp(key: jax.Array, cfg: ModelArgs) -> Tuple[Params, Params]:
    h = cfg.hidden_size
    f = cfg.moe_ffn_hidden_size or cfg.ffn_dim
    e = cfg.num_experts
    gated = M._is_gated(cfg.hidden_act)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    std = 0.02
    p: Params = {
        "router": M._normal(k1, (h, e), std),
        "win": M._normal(k2, (e, h, 2 * f if gated else f), std),
        "wout": M._normal(k3, (e, f, h),
                          std / math.sqrt(2 * cfg.num_hidden_layers)),
    }
    a: Params = {
        "router": ("embed", "expert_out"),
        "win": ("expert", "embed", "mlp"),
        "wout": ("expert", "mlp", "embed"),
    }
    if cfg.num_shared_experts:
        sp, sa = M.init_mlp(k4, cfg,
                            ffn_dim=f * cfg.num_shared_experts)
        p["shared"] = sp
        a["shared"] = sa
    if cfg.moe_router_enable_expert_bias:
        # selection-only bias, updated outside the gradient path via
        # update_expert_bias (reference expert_bias buffer, router.py:116)
        p["expert_bias"] = jnp.zeros((e,), jnp.float32)
        a["expert_bias"] = ("expert_out",)
    return p, a


def _expert_act(hproj: jax.Array, cfg: ModelArgs,
                compute_dtype=jnp.bfloat16) -> jax.Array:
    hproj = hproj.astype(compute_dtype)
    act = M._ACTS[cfg.hidden_act]
    if M._is_gated(cfg.hidden_act):
        gate, up = jnp.split(hproj, 2, axis=-1)
        return act(gate) * up
    return act(hproj)


def _capacity_dispatch(
    p: Params, xt: jax.Array, topk_idx: jax.Array, w: jax.Array,
    cfg: ModelArgs, compute_dtype, capacity_factor: Optional[float],
) -> jax.Array:
    """GShard one-hot capacity dispatch: position of each (token, k) slot
    within its expert's capacity buffer; over-capacity slots drop (weights
    renormalized over the survivors)."""
    T, _ = xt.shape
    E, K = cfg.num_experts, cfg.moe_topk
    C = moe_capacity(cfg, T, capacity_factor)
    sel = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)  # [T, K, E]
    flat_sel = sel.reshape(T * K, E)
    pos = jnp.cumsum(flat_sel, axis=0) * flat_sel - 1.0  # [T*K, E]
    in_cap = (pos >= 0) & (pos < C)
    pos_oh = jax.nn.one_hot(pos, C, dtype=jnp.float32) * \
        in_cap[..., None]  # [T*K, E, C]
    dispatch = pos_oh.reshape(T, K, E, C).sum(axis=1)  # [T, E, C]
    # redistribute dropped slots' weight over the survivors, preserving the
    # token's total combine weight (for the renormalized topk router this is
    # the reference's renormalize-over-survivors; sinkhorn scales survive
    # unchanged when nothing drops)
    kept = (flat_sel * in_cap.astype(jnp.float32)).sum(-1).reshape(T, K)
    wk = w * kept
    wk = wk * (jnp.sum(w, axis=-1, keepdims=True)
               / jnp.maximum(jnp.sum(wk, axis=-1, keepdims=True), 1e-9))
    combine = jnp.einsum("tkec,tk->tec", pos_oh.reshape(T, K, E, C), wk)

    # expert compute: [E, C, H] -> [E, C, F] -> [E, C, H]
    xe = jnp.einsum("tec,th->ech", dispatch.astype(compute_dtype),
                    xt.astype(compute_dtype),
                    preferred_element_type=jnp.float32).astype(compute_dtype)
    hproj = jnp.einsum("ech,ehf->ecf", xe, p["win"].astype(compute_dtype),
                       preferred_element_type=jnp.float32)
    hproj = _expert_act(hproj, cfg, compute_dtype)
    ye = jnp.einsum("ecf,efh->ech", hproj, p["wout"].astype(compute_dtype),
                    preferred_element_type=jnp.float32)
    return jnp.einsum("tec,ech->th", combine.astype(compute_dtype),
                      ye.astype(compute_dtype),
                      preferred_element_type=jnp.float32)


def _dropless_dispatch(
    p: Params, xt: jax.Array, topk_idx: jax.Array, w: jax.Array,
    cfg: ModelArgs, compute_dtype,
) -> jax.Array:
    """Dropless grouped-matmul dispatch (reference alltoall dropless
    dispatcher, token_dispatcher.py:287, re-designed for XLA): the [T*K]
    token slots sort by expert id (stable, so intra-expert order is token
    order), the expert MLPs run as ``lax.ragged_dot`` grouped matmuls over
    the sorted buffer, and a scatter-add combines weighted outputs. Every
    shape is static; no token is dropped; renormalized top-k weights make
    HF Mixtral numerics exact."""
    T, H = xt.shape
    E, K = cfg.num_experts, cfg.moe_topk
    eid = topk_idx.reshape(T * K)
    order = jnp.argsort(eid, stable=True)
    tok = jnp.arange(T * K, dtype=jnp.int32) // K  # slot -> token
    tok_sorted = tok[order]
    xs = xt[tok_sorted].astype(compute_dtype)  # [T*K, H]
    group_sizes = jnp.bincount(eid, length=E).astype(jnp.int32)
    hproj = jax.lax.ragged_dot(xs, p["win"].astype(compute_dtype),
                               group_sizes,
                               preferred_element_type=jnp.float32)
    hproj = _expert_act(hproj, cfg, compute_dtype)
    ys = jax.lax.ragged_dot(hproj, p["wout"].astype(compute_dtype),
                            group_sizes,
                            preferred_element_type=jnp.float32)
    ws = w.reshape(T * K)[order]
    return jnp.zeros((T, H), jnp.float32).at[tok_sorted].add(
        ys * ws[:, None])


def apply_moe_mlp(
    p: Params,
    x: jax.Array,
    cfg: ModelArgs,
    compute_dtype=jnp.bfloat16,
    capacity_factor: Optional[float] = None,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """x [B,S,H] -> (y [B,S,H], aux_loss scalar, router stats dict).

    Router per ``cfg.moe_router_type`` (see :func:`route_tokens`), dispatch
    per ``cfg.moe_dispatcher``: "capacity" (GShard, ep-shardable) or
    "dropless" (ragged grouped matmuls, exact numerics).
    """
    B, S, H = x.shape
    xt = x.reshape(B * S, H)
    topk_idx, w, aux, stats = route_tokens(p, xt, cfg, compute_dtype)
    if cfg.moe_dispatcher == "dropless":
        y = _dropless_dispatch(p, xt, topk_idx, w, cfg, compute_dtype)
    else:
        y = _capacity_dispatch(p, xt, topk_idx, w, cfg, compute_dtype,
                               capacity_factor)
    if "shared" in p:
        y = y + M.apply_mlp(p["shared"], xt[None], cfg,
                            compute_dtype=compute_dtype)[0]
    return y.reshape(B, S, H).astype(compute_dtype), aux, stats


def init_moe_decoder_layer(key: jax.Array, cfg: ModelArgs
                           ) -> Tuple[Params, Params]:
    k1, k2 = jax.random.split(key)
    attn_p, attn_a = M.init_attention(k1, cfg)
    moe_p, moe_a = init_moe_mlp(k2, cfg)
    ln1_p, ln1_a = M.init_norm(cfg)
    ln2_p, ln2_a = M.init_norm(cfg)
    return (
        {"ln1": ln1_p, "attn": attn_p, "ln2": ln2_p, "moe": moe_p},
        {"ln1": ln1_a, "attn": attn_a, "ln2": ln2_a, "moe": moe_a},
    )


def apply_moe_decoder_layer(
    p: Params,
    x: jax.Array,
    cfg: ModelArgs,
    rope=None,
    sdpa_fn=M.xla_sdpa,
    compute_dtype=jnp.bfloat16,
    dropout_rng=None,
    segment_ids=None,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Pre-norm block with an MoE FFN; returns (x, aux_loss, router
    stats) — stats feed the per-layer balance tracker (reference
    moe_utils.py:547-644)."""
    r_attn = r_res1 = r_res2 = None
    if dropout_rng is not None:
        r_attn, r_res1, r_res2 = jax.random.split(dropout_rng, 3)
    h = M.apply_norm(p["ln1"], x, cfg)
    x = x + M.dropout(
        M.apply_attention(p["attn"], h, cfg, rope=rope, sdpa_fn=sdpa_fn,
                          compute_dtype=compute_dtype, dropout_rng=r_attn,
                          segment_ids=segment_ids),
        cfg.hidden_dropout, r_res1)
    h = M.apply_norm(p["ln2"], x, cfg)
    y, aux, stats = apply_moe_mlp(p["moe"], h, cfg,
                                  compute_dtype=compute_dtype)
    return x + M.dropout(y, cfg.hidden_dropout, r_res2), aux, stats
