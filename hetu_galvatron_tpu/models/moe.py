"""Mixture-of-Experts layer: top-k router + expert-parallel grouped MLPs.

Capability parity with the reference MoE runtime (runtime/moe/router.py:98
``TopKRouter`` with aux/z-losses, token_dispatcher.py:116/287/942 dispatchers,
mlp.py:26 ``GroupedMLP``, moe_utils.py:166 aux-loss scaling): a softmax top-k
router with load-balancing and router-z losses, capacity-bounded token
dispatch, and per-expert MLPs evaluated as one grouped einsum.

TPU-first: instead of permute/unpermute kernels + all-to-all dispatchers,
dispatch/combine are one-hot einsums (the GShard formulation) — XLA lowers
them to gather/scatter fused with the expert matmuls, and sharding the
``expert`` axis over the ep mesh axes makes GSPMD insert the token
all-to-alls the reference issues by hand. Over-capacity tokens are dropped
(weights renormalized), the standard capacity-factor treatment.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.models import modules as M

Params = Dict[str, Any]


def is_moe_layer(cfg: ModelArgs, layer_idx: int) -> bool:
    """Dense/MoE alternation: every moe_layer_freq-th layer is MoE
    (reference moe_layer_freq semantics, hf adapter layertype split)."""
    if not cfg.num_experts:
        return False
    freq = max(cfg.moe_layer_freq, 1)
    return (layer_idx + 1) % freq == 0


def moe_capacity(cfg: ModelArgs, tokens: int, capacity_factor: float = 1.25
                 ) -> int:
    """Per-expert token capacity (reference capacity-factor dispatch)."""
    return max(int(math.ceil(tokens * cfg.moe_topk / cfg.num_experts
                             * capacity_factor)), cfg.moe_topk)


def init_moe_mlp(key: jax.Array, cfg: ModelArgs) -> Tuple[Params, Params]:
    h = cfg.hidden_size
    f = cfg.moe_ffn_hidden_size or cfg.ffn_dim
    e = cfg.num_experts
    gated = M._is_gated(cfg.hidden_act)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    std = 0.02
    p: Params = {
        "router": M._normal(k1, (h, e), std),
        "win": M._normal(k2, (e, h, 2 * f if gated else f), std),
        "wout": M._normal(k3, (e, f, h),
                          std / math.sqrt(2 * cfg.num_hidden_layers)),
    }
    a: Params = {
        "router": ("embed", "expert_out"),
        "win": ("expert", "embed", "mlp"),
        "wout": ("expert", "mlp", "embed"),
    }
    if cfg.num_shared_experts:
        sp, sa = M.init_mlp(k4, cfg,
                            ffn_dim=f * cfg.num_shared_experts)
        p["shared"] = sp
        a["shared"] = sa
    return p, a


def apply_moe_mlp(
    p: Params,
    x: jax.Array,
    cfg: ModelArgs,
    compute_dtype=jnp.bfloat16,
    capacity_factor: float = 1.25,
) -> Tuple[jax.Array, jax.Array]:
    """x [B,S,H] -> (y [B,S,H], aux_loss scalar).

    aux_loss = load-balancing loss (num_experts * sum_e f_e * P_e, Switch
    formulation — reference router.py aux_loss) + z-loss on router logits.
    """
    B, S, H = x.shape
    E, K = cfg.num_experts, cfg.moe_topk
    T = B * S
    xt = x.reshape(T, H)

    router_dtype = jnp.float32 if cfg.moe_router_dtype == "float32" \
        else compute_dtype
    logits = jnp.einsum("th,he->te", xt.astype(router_dtype),
                        p["router"].astype(router_dtype),
                        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]

    topk_probs, topk_idx = jax.lax.top_k(probs, K)  # [T, K]

    # aux losses (reference router.py aux/z-loss; moe_utils.py:166 scaling)
    sel = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)  # [T, K, E]
    frac_tokens = jnp.mean(jnp.sum(sel, axis=1), axis=0)  # f_e
    frac_probs = jnp.mean(probs, axis=0)  # P_e
    aux = cfg.moe_aux_loss_coeff * E * jnp.sum(frac_tokens * frac_probs)
    if cfg.moe_z_loss_coeff:
        z = jax.scipy.special.logsumexp(logits, axis=-1)
        aux = aux + cfg.moe_z_loss_coeff * jnp.mean(jnp.square(z))

    # capacity-bounded dispatch (GShard): position of each (token, k) slot
    # within its expert's capacity buffer
    C = moe_capacity(cfg, T, capacity_factor)
    flat_sel = sel.reshape(T * K, E)
    pos = jnp.cumsum(flat_sel, axis=0) * flat_sel - 1.0  # [T*K, E]
    in_cap = (pos >= 0) & (pos < C)
    pos_oh = jax.nn.one_hot(pos, C, dtype=jnp.float32) * \
        in_cap[..., None]  # [T*K, E, C]
    dispatch = pos_oh.reshape(T, K, E, C).sum(axis=1)  # [T, E, C]
    # renormalize over the slots that survived capacity, so a token whose
    # top expert overflowed still gets a unit-sum combine weight
    kept = (flat_sel * in_cap.astype(jnp.float32)).sum(-1).reshape(T, K)
    w = topk_probs.astype(jnp.float32) * kept
    w = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-9)
    combine = jnp.einsum("tkec,tk->tec", pos_oh.reshape(T, K, E, C), w)

    # expert compute: [E, C, H] -> [E, C, F] -> [E, C, H]
    xe = jnp.einsum("tec,th->ech", dispatch.astype(compute_dtype),
                    xt.astype(compute_dtype),
                    preferred_element_type=jnp.float32).astype(compute_dtype)
    hproj = jnp.einsum("ech,ehf->ecf", xe, p["win"].astype(compute_dtype),
                       preferred_element_type=jnp.float32)
    hproj = hproj.astype(compute_dtype)
    act = M._ACTS[cfg.hidden_act]
    if M._is_gated(cfg.hidden_act):
        gate, up = jnp.split(hproj, 2, axis=-1)
        hproj = act(gate) * up
    else:
        hproj = act(hproj)
    ye = jnp.einsum("ecf,efh->ech", hproj, p["wout"].astype(compute_dtype),
                    preferred_element_type=jnp.float32)
    y = jnp.einsum("tec,ech->th", combine.astype(compute_dtype),
                   ye.astype(compute_dtype),
                   preferred_element_type=jnp.float32)

    if "shared" in p:
        y = y + M.apply_mlp(p["shared"], xt[None], cfg,
                            compute_dtype=compute_dtype)[0]
    return y.reshape(B, S, H).astype(compute_dtype), aux


def init_moe_decoder_layer(key: jax.Array, cfg: ModelArgs
                           ) -> Tuple[Params, Params]:
    k1, k2 = jax.random.split(key)
    attn_p, attn_a = M.init_attention(k1, cfg)
    moe_p, moe_a = init_moe_mlp(k2, cfg)
    ln1_p, ln1_a = M.init_norm(cfg)
    ln2_p, ln2_a = M.init_norm(cfg)
    return (
        {"ln1": ln1_p, "attn": attn_p, "ln2": ln2_p, "moe": moe_p},
        {"ln1": ln1_a, "attn": attn_a, "ln2": ln2_a, "moe": moe_a},
    )


def apply_moe_decoder_layer(
    p: Params,
    x: jax.Array,
    cfg: ModelArgs,
    rope=None,
    sdpa_fn=M.xla_sdpa,
    compute_dtype=jnp.bfloat16,
) -> Tuple[jax.Array, jax.Array]:
    """Pre-norm block with an MoE FFN; returns (x, aux_loss)."""
    h = M.apply_norm(p["ln1"], x, cfg)
    x = x + M.apply_attention(p["attn"], h, cfg, rope=rope, sdpa_fn=sdpa_fn,
                              compute_dtype=compute_dtype)
    h = M.apply_norm(p["ln2"], x, cfg)
    y, aux = apply_moe_mlp(p["moe"], h, cfg, compute_dtype=compute_dtype)
    return x + y, aux
