"""Autoregressive generation with a KV cache (single device).

The reference ships only inference-context stubs in its attention layer
(transformer/attention.py inference params); this module provides a working
TPU-native decode path: static-shape KV cache buffers, a `lax.scan` decode
loop (one compiled step reused for every position), greedy or
temperature/top-k sampling, and EOS masking — no data-dependent Python
control flow, so the whole generate() jits.

The transformer math is NOT re-implemented here: both prefill and the
decode step run `modules.apply_decoder_layer` with an `sdpa_fn` closure
that captures (and, when decoding, updates) the rope-applied k/v — the
same hook the distributed layer uses for flash/ring/Ulysses attention, so
any change to the block stays in one place.

Scope: dense causal decoder families (gpt/llama/qwen/mistral: pre-norm,
learned or rope positions, GQA, biases) via generate(), plus t5-style
encoder-decoder decode via generate_encdec() (encoder once, cached cross
k/v). MoE decode is out of scope here.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.models import modules as M

Params = Dict[str, Any]


def _check_supported(cfg: ModelArgs, params: Params) -> None:
    if cfg.post_norm or cfg.model_type == "bert":
        raise NotImplementedError("generate(): causal decoder families only")
    if cfg.model_type == "t5":
        raise NotImplementedError(
            "generate() is the causal-decoder path; use generate_encdec() "
            "for t5 (encoder once + cached cross-attention decode)")
    if any("moe" in lp for lp in params["layers"]):
        raise NotImplementedError("generate(): dense layers only")


def _cached_sdpa(q, ck, cv, pos, shift=None):
    """q [B,W,Nq,D] — a window of W consecutive query positions per row
    (W=1 is the plain decode step) — against the full cache [B,T,Nkv,D];
    window row j sits at absolute position pos(+j), and key positions
    beyond it are masked (static T => one compiled shape for the whole
    decode scan). ``pos`` is a scalar (one shared position, the offline
    scan) or [B] (per-row positions — the serving engine's paged decode
    delegates here, as do its W-wide speculative-verify and
    prefix-suffix-prefill programs via ``kv_cache.paged_sdpa_window``:
    ONE implementation keeps the multi-row passes bit-identical to W
    sequential decode steps by construction, not by parallel
    maintenance). ``shift`` [B] (left-padded ragged prompts) additionally
    masks the leading pad positions < shift[b]."""
    B, W, nq, D = q.shape
    T, nkv = ck.shape[1], ck.shape[2]
    G = nq // nkv
    qg = q.reshape(B, W, nkv, G, D).astype(jnp.float32)
    s = jnp.einsum("bwkgd,btkd->bwkgt", qg, ck.astype(jnp.float32))
    s = s / jnp.sqrt(jnp.float32(D))
    t = jnp.arange(T)[None, None, None, None, :]
    pos = jnp.asarray(pos)
    base = pos[:, None, None, None, None] if pos.ndim else pos
    row = jnp.arange(W)[None, :, None, None, None]
    mask = t <= (base + row)
    if shift is not None:
        mask = mask & (t >= shift[:, None, None, None, None])
    s = jnp.where(mask, s, jnp.float32(jnp.finfo(jnp.float32).min))
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bwkgt,btkd->bwkgd", w, cv.astype(jnp.float32))
    return out.reshape(B, W, nq, D).astype(q.dtype)


def _embed_at(p: Params, tokens: jax.Array, pos, cfg: ModelArgs,
              compute_dtype, shift=None):
    """Token embedding for one decode step at absolute position ``pos``
    (per-row LOGICAL position ``pos - shift[b]`` for left-padded rows).
    Mirrors ``modules.apply_embedding`` — including the embedding LayerNorm
    and the gemma sqrt(hidden) scaling — so decode steps see the same
    hidden-state distribution prefill produced."""
    x = jnp.take(p["wte"], tokens[:, None], axis=0)  # [B,1,H]
    if "wpe" in p:
        if shift is not None:
            x = x + jnp.take(p["wpe"], pos - shift, axis=0)[:, None]
        else:
            x = x + jax.lax.dynamic_slice_in_dim(p["wpe"], pos, 1)[None]
    if "ln" in p:
        x = M.apply_norm(p["ln"], x, cfg)
    if cfg.scale_embeddings:
        x = x * jnp.sqrt(jnp.float32(cfg.hidden_size)).astype(x.dtype)
    return x.astype(compute_dtype)


def init_kv_cache(cfg: ModelArgs, batch: int, max_len: int,
                  dtype=jnp.bfloat16):
    n = cfg.num_hidden_layers
    shape = (batch, max_len, cfg.kv_heads, cfg.head_dim)
    return [{"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
            for _ in range(n)]


def prefill(params: Params, tokens: jax.Array, cfg: ModelArgs, max_len: int,
            *, compute_dtype=jnp.bfloat16, prompt_lens=None):
    """Run the prompt through the stack, filling the cache; returns
    (cache, logits_last [B, V]).

    ``prompt_lens`` [B] supports ragged batched prompts, LEFT-padded to the
    common width S0 (row b's real tokens occupy columns [S0 - len_b, S0)):
    positions restart at 0 on the first real token and the pad prefix is
    masked out of attention, so every row reproduces its unpadded
    single-row prefill exactly."""
    B, S0 = tokens.shape
    shift = position_ids = segment_ids = None
    if prompt_lens is not None:
        shift = jnp.asarray(S0, jnp.int32) - prompt_lens.astype(jnp.int32)
        idx = jnp.arange(S0, dtype=jnp.int32)[None]
        position_ids = jnp.maximum(idx - shift[:, None], 0)
        segment_ids = (idx >= shift[:, None]).astype(jnp.int32)
    rope = None
    if cfg.position_embedding_type == "rope":
        rope = M.rope_cos_sin(S0, cfg.head_dim, cfg.rope_theta,
                              scaling=cfg.rope_scaling)
        if position_ids is not None:
            rope = (rope[0][position_ids], rope[1][position_ids])
    cache = init_kv_cache(cfg, B, max_len, compute_dtype)
    x = M.apply_embedding(params["embed"], tokens, cfg,
                          compute_dtype=compute_dtype,
                          position_ids=position_ids)
    for i, lp in enumerate(params["layers"]):
        cell = {}

        def sdpa(q, k, v, *, causal=True, segment_ids=None, cell=cell):
            cell["k"], cell["v"] = k, v  # rope-applied, pre-attention
            return M.xla_sdpa(q, k, v, causal=causal,
                              segment_ids=segment_ids)

        sdpa.supports_segments = True
        x = M.apply_decoder_layer(lp, x, cfg, rope=rope, sdpa_fn=sdpa,
                                  compute_dtype=compute_dtype,
                                  segment_ids=segment_ids)
        cache[i] = {
            "k": jax.lax.dynamic_update_slice_in_dim(
                cache[i]["k"], cell["k"].astype(cache[i]["k"].dtype), 0,
                axis=1),
            "v": jax.lax.dynamic_update_slice_in_dim(
                cache[i]["v"], cell["v"].astype(cache[i]["v"].dtype), 0,
                axis=1),
        }
    x = M.apply_norm(params["prenorm"], x, cfg)
    logits = M.apply_lm_head(params["head"], x[:, -1:], cfg,
                             wte=params["embed"]["wte"],
                             compute_dtype=compute_dtype)
    return cache, logits[:, 0]


def decode_step(params: Params, cache, tokens: jax.Array, pos, cfg: ModelArgs,
                *, rope_full=None, compute_dtype=jnp.bfloat16, shift=None):
    """One token per sequence at absolute position ``pos`` (a traced
    scalar); returns (cache, logits [B, V]). ``shift`` [B] carries the
    left-pad offsets of a ragged prefill: rope/learned positions use the
    logical ``pos - shift[b]`` and the pad prefix stays masked."""
    x = _embed_at(params["embed"], tokens, pos, cfg, compute_dtype,
                  shift=shift)
    step_rope = None
    if rope_full is not None:
        cos, sin = rope_full
        if shift is not None:
            step_rope = (cos[pos - shift][:, None], sin[pos - shift][:, None])
        else:
            step_rope = (jax.lax.dynamic_slice_in_dim(cos, pos, 1),
                         jax.lax.dynamic_slice_in_dim(sin, pos, 1))
    for i, lp in enumerate(params["layers"]):
        cell = {}

        def sdpa(q, k, v, *, causal=True, i=i, cell=cell):
            ck = jax.lax.dynamic_update_slice_in_dim(
                cache[i]["k"], k.astype(cache[i]["k"].dtype), pos, axis=1)
            cv = jax.lax.dynamic_update_slice_in_dim(
                cache[i]["v"], v.astype(cache[i]["v"].dtype), pos, axis=1)
            cell["k"], cell["v"] = ck, cv
            return _cached_sdpa(q, ck, cv, pos, shift=shift)

        x = M.apply_decoder_layer(lp, x, cfg, rope=step_rope, sdpa_fn=sdpa,
                                  compute_dtype=compute_dtype)
        cache[i] = {"k": cell["k"], "v": cell["v"]}
    x = M.apply_norm(params["prenorm"], x, cfg)
    logits = M.apply_lm_head(params["head"], x, cfg,
                             wte=params["embed"]["wte"],
                             compute_dtype=compute_dtype)
    return cache, logits[:, 0]


def generate(
    params: Params,
    tokens: jax.Array,  # [B, S0] prompt
    cfg: ModelArgs,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,  # 0 => greedy
    top_k: Optional[int] = None,
    eos_id: Optional[int] = None,
    pad_id: Optional[int] = None,
    prompt_lens: Optional[jax.Array] = None,
    key: Optional[jax.Array] = None,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Returns [B, S0 + max_new_tokens]. Fully jittable (static shapes;
    scan over positions).

    Retirement contract: once a row has emitted ``eos_id`` it is retired —
    every later position carries ``pad_id`` (``eos_id`` when pad_id is
    None, the legacy layout), NOT live samples. With greedy decoding
    (temperature 0) this makes a row's whole output independent of which
    neighbors share the batch; with temperature > 0 the live tokens still
    draw from ONE shared key over the [B, V] batch (a row's samples depend
    on batch size/row index — the serving engine uses per-request keys
    instead), but the retired tail is masked either way. The serving
    engine's per-request streams are checked against exactly this contract
    (rows trimmed at their first eos).

    ``prompt_lens`` [B] enables ragged batched prompts, LEFT-padded to
    width S0: each row decodes as if it were the only (unpadded) sequence
    — pad prefix masked from attention, positions starting at 0 on the
    first real token.
    """
    _check_supported(cfg, params)
    B, S0 = tokens.shape
    total = S0 + max_new_tokens
    if total > cfg.max_position_embeddings and "wpe" in params["embed"]:
        raise ValueError(f"{total} exceeds max_position_embeddings")
    rope_full = None
    if cfg.position_embedding_type == "rope":
        rope_full = M.rope_cos_sin(total, cfg.head_dim, cfg.rope_theta,
                                   scaling=cfg.rope_scaling)
    if key is None:
        key = jax.random.key(0)
    shift = None
    if prompt_lens is not None:
        shift = jnp.asarray(S0, jnp.int32) - prompt_lens.astype(jnp.int32)

    cache, logits = prefill(params, tokens, cfg, total,
                            compute_dtype=compute_dtype,
                            prompt_lens=prompt_lens)
    pick = _sample_pick(cfg, tokens.dtype, temperature, top_k)
    fill = eos_id if pad_id is None else pad_id

    def body(carry, _):
        cache, logits, pos, done, k = carry
        k, sub = jax.random.split(k)
        nxt = pick(logits, sub)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(fill, nxt.dtype), nxt)
            done = done | (nxt == eos_id)
        cache, logits = decode_step(params, cache, nxt, pos, cfg,
                                    rope_full=rope_full,
                                    compute_dtype=compute_dtype,
                                    shift=shift)
        return (cache, logits, pos + 1, done, k), nxt

    done0 = jnp.zeros((B,), bool)
    (_, logits, _, done, _), toks = jax.lax.scan(
        body, (cache, logits, jnp.int32(S0), done0, key), None,
        length=max_new_tokens)
    return jnp.concatenate([tokens, toks.T], axis=1)


# ---------------------------------------------------------------------------
# encoder-decoder (t5) decode: encoder once + cached cross-attention k/v +
# cached causal self-attention (reference ships only inference-context stubs,
# transformer/attention.py inference params). NOTE: this runtime is
# position-scheme agnostic (no T5 relative bias — models/encdec.py docstring
# + the HF converter note, runtime/checkpoint.py _t5_hf_to_params), so
# imported HF T5 weights fine-tune rather than bit-match HF generation; the
# decode contract tested instead is incremental == full teacher-forced
# forward (tests/models/test_t5.py).
# ---------------------------------------------------------------------------


def _sample_pick(cfg, tokens_dtype, temperature, top_k):
    """Per-step token selection shared by the causal and encoder-decoder
    decode loops: greedy / temperature / top-k, with the vocab-padding
    columns (untrained head rows) never sampled."""
    valid = jnp.arange(cfg.padded_vocab_size) < cfg.vocab_size

    def pick(logits, k):
        logits = jnp.where(valid, logits, jnp.finfo(logits.dtype).min)
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(tokens_dtype)
        logits = logits / temperature
        if top_k:
            kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
            logits = jnp.where(logits < kth,
                               jnp.finfo(logits.dtype).min, logits)
        return jax.random.categorical(k, logits, axis=-1).astype(tokens_dtype)

    return pick


def prefill_encdec(params: Params, mem: jax.Array, dec_tokens: jax.Array,
                   cfg: ModelArgs, max_len: int, *,
                   compute_dtype=jnp.bfloat16):
    """Decoder prefill over the start tokens against encoder memory ``mem``:
    fills the self-attention cache, projects + caches the cross k/v once
    per layer. Returns (cache, cross_cache, logits_last [B, V])."""
    from hetu_galvatron_tpu.models.encdec import (
        apply_cross_decoder_layer,
        cross_kv,
    )

    B, T0 = dec_tokens.shape
    rope = None
    if cfg.position_embedding_type == "rope":
        rope = M.rope_cos_sin(T0, cfg.head_dim, cfg.rope_theta,
                              scaling=cfg.rope_scaling)
    cache = init_kv_cache(cfg, B, max_len, compute_dtype)
    cross = [cross_kv(lp["cross"], mem, cfg, compute_dtype)
             for lp in params["layers"]]
    x = M.apply_embedding(params["embed"], dec_tokens, cfg,
                          compute_dtype=compute_dtype)
    for i, lp in enumerate(params["layers"]):
        cell = {}

        def sdpa(q, k, v, *, causal=True, cell=cell):
            cell["k"], cell["v"] = k, v  # rope-applied, pre-attention
            return M.xla_sdpa(q, k, v, causal=causal)

        x = apply_cross_decoder_layer(lp, x, mem, cfg, rope=rope,
                                      sdpa_fn=sdpa,
                                      cross_sdpa_fn=M.xla_sdpa,
                                      compute_dtype=compute_dtype,
                                      cached_cross_kv=cross[i])
        cache[i] = {
            "k": jax.lax.dynamic_update_slice_in_dim(
                cache[i]["k"], cell["k"].astype(cache[i]["k"].dtype), 0,
                axis=1),
            "v": jax.lax.dynamic_update_slice_in_dim(
                cache[i]["v"], cell["v"].astype(cache[i]["v"].dtype), 0,
                axis=1),
        }
    x = M.apply_norm(params["prenorm"], x, cfg)
    logits = M.apply_lm_head(params["head"], x[:, -1:], cfg,
                             wte=params["embed"]["wte"],
                             compute_dtype=compute_dtype)
    return cache, cross, logits[:, 0]


def decode_step_encdec(params: Params, cache, cross, mem, tokens: jax.Array,
                       pos, cfg: ModelArgs, *, rope_full=None,
                       compute_dtype=jnp.bfloat16):
    """One decoder token at absolute position ``pos``: cached causal
    self-attention + cached cross k/v. Returns (cache, logits [B, V])."""
    from hetu_galvatron_tpu.models.encdec import apply_cross_decoder_layer

    x = _embed_at(params["embed"], tokens, pos, cfg, compute_dtype)
    step_rope = None
    if rope_full is not None:
        cos, sin = rope_full
        step_rope = (jax.lax.dynamic_slice_in_dim(cos, pos, 1),
                     jax.lax.dynamic_slice_in_dim(sin, pos, 1))
    for i, lp in enumerate(params["layers"]):
        cell = {}

        def sdpa(q, k, v, *, causal=True, i=i, cell=cell):
            ck = jax.lax.dynamic_update_slice_in_dim(
                cache[i]["k"], k.astype(cache[i]["k"].dtype), pos, axis=1)
            cv = jax.lax.dynamic_update_slice_in_dim(
                cache[i]["v"], v.astype(cache[i]["v"].dtype), pos, axis=1)
            cell["k"], cell["v"] = ck, cv
            return _cached_sdpa(q, ck, cv, pos)

        x = apply_cross_decoder_layer(lp, x, mem, cfg, rope=step_rope,
                                      sdpa_fn=sdpa,
                                      cross_sdpa_fn=M.xla_sdpa,
                                      compute_dtype=compute_dtype,
                                      cached_cross_kv=cross[i])
        cache[i] = {"k": cell["k"], "v": cell["v"]}
    x = M.apply_norm(params["prenorm"], x, cfg)
    logits = M.apply_lm_head(params["head"], x, cfg,
                             wte=params["embed"]["wte"],
                             compute_dtype=compute_dtype)
    return cache, logits[:, 0]


def generate_encdec(
    params: Params,
    enc_tokens: jax.Array,  # [B, S] source sequence
    cfg: ModelArgs,
    max_new_tokens: int,
    *,
    decoder_start_token_id: int = 0,
    temperature: float = 0.0,  # 0 => greedy
    top_k: Optional[int] = None,
    eos_id: Optional[int] = None,
    key: Optional[jax.Array] = None,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Seq2seq generation: encoder ONCE, then a `lax.scan` decode loop with
    cached self-attention k/v and per-layer cached cross k/v. Returns the
    decoder tokens [B, 1 + max_new_tokens] (start token included). Fully
    jittable (static shapes)."""
    from hetu_galvatron_tpu.models.encdec import encode

    if cfg.model_type != "t5":
        raise ValueError("generate_encdec() is the t5/encoder-decoder path")
    B = enc_tokens.shape[0]
    total = 1 + max_new_tokens
    if total > cfg.max_position_embeddings and "wpe" in params["embed"]:
        raise ValueError(f"{total} exceeds max_position_embeddings")
    rope_full = None
    if cfg.position_embedding_type == "rope":
        rope_full = M.rope_cos_sin(total, cfg.head_dim, cfg.rope_theta,
                                   scaling=cfg.rope_scaling)
    if key is None:
        key = jax.random.key(0)

    mem = encode(params, enc_tokens, cfg, compute_dtype=compute_dtype)
    start = jnp.full((B, 1), decoder_start_token_id, jnp.int32)
    cache, cross, logits = prefill_encdec(params, mem, start, cfg, total,
                                          compute_dtype=compute_dtype)
    pick = _sample_pick(cfg, start.dtype, temperature, top_k)

    def body(carry, _):
        cache, logits, pos, done, k = carry
        k, sub = jax.random.split(k)
        nxt = pick(logits, sub)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
            done = done | (nxt == eos_id)
        cache, logits = decode_step_encdec(
            params, cache, cross, mem, nxt, pos, cfg,
            rope_full=rope_full, compute_dtype=compute_dtype)
        return (cache, logits, pos + 1, done, k), nxt

    done0 = jnp.zeros((B,), bool)
    (_, _, _, _, _), toks = jax.lax.scan(
        body, (cache, logits, jnp.int32(1), done0, key), None,
        length=max_new_tokens)
    return jnp.concatenate([start, toks.T], axis=1)
