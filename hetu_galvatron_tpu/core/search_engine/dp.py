"""Per-stage knapsack DP over (layer, memory budget, strategy).

Capability parity with the reference DP machinery
(core/search_engine/dynamic_programming.py:12-115 DPAlg + csrc/dp_core.cpp):
the C++ core is compiled lazily with g++ and bound via ctypes (this image has
no pybind11, matching the reference's lazy dataset-helper build pattern,
runtime/initialize.py:163-187); a vectorized NumPy implementation is the
fallback and the cross-check.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from hetu_galvatron_tpu.utils.native import load_native


def _configure(lib: ctypes.CDLL) -> None:
    lib.dp_solve.restype = ctypes.c_int
    lib.dp_solve.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        ctypes.c_int, ctypes.c_double,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int),
    ]


def _load_cpp_core() -> Optional[ctypes.CDLL]:
    return load_native("libdp_core.so", "dp_core.cpp", _configure)


def dp_solve(
    mem_cost: np.ndarray,      # [layers, strategies] int MB
    intra_cost: np.ndarray,    # [layers, strategies] seconds
    inter_cost: np.ndarray,    # [layers, strategies, strategies]
    max_mem: int,
    other_mem: int,
    other_time: float,
    use_cpp_core: bool = True,
) -> Tuple[float, Optional[list], int]:
    """Minimize sum of intra+inter costs subject to the per-stage memory
    budget. Returns (total_cost, per-layer strategy indices | None, remaining
    memory). Semantics match the reference C++ core (dp_core.cpp:24-121):
    the vocab-layer memory shrinks the budget and its time adds to the total.
    """
    layers, strat = intra_cost.shape
    budget = max_mem + 1  # budgets 0..max_mem inclusive
    v = np.ascontiguousarray(mem_cost, np.int32)
    intra = np.ascontiguousarray(intra_cost, np.float64)
    inter = np.ascontiguousarray(inter_cost, np.float64)

    if use_cpp_core and (lib := _load_cpp_core()) is not None:
        mark = np.empty((layers, budget, strat), np.int32)
        f = np.zeros((budget, strat), np.float64)
        res = np.empty((layers,), np.int32)
        total = ctypes.c_double()
        remain = ctypes.c_int()
        rc = lib.dp_solve(layers, budget, strat, v, inter, intra,
                          int(other_mem), float(other_time),
                          mark, f, res, ctypes.byref(total),
                          ctypes.byref(remain))
        if rc != 0:
            return np.inf, None, -1
        return float(total.value), [int(x) for x in res], int(remain.value)

    # numpy fallback: same recurrence, vectorized over the memory axis
    f = np.zeros((budget, strat), np.float64)
    mark = np.full((layers, budget, strat), -1, np.int32)
    for i in range(layers):
        new_f = np.full((budget, strat), np.inf, np.float64)
        for s in range(strat):
            need = int(v[i, s])
            if need > max_mem:
                continue
            # candidates[m, si] = f[m - need, si] + inter[i, si, s]
            cand = f[:budget - need, :] + inter[i, :, s][None, :]
            best_si = np.argmin(cand, axis=1)
            rows = np.arange(budget - need)
            new_f[need:, s] = cand[rows, best_si] + intra[i, s]
            mark[i, need:, s] = best_si
        f = new_f

    b = max_mem - other_mem
    if b < 0:
        return np.inf, None, -1
    next_index = int(np.argmin(f[b]))
    total = f[b, next_index]
    if not total < np.inf:
        return np.inf, None, -1
    total += other_time
    next_v = b
    res = [-1] * layers
    res[layers - 1] = next_index
    for i in range(layers - 1, 0, -1):
        cur = next_index
        next_index = int(mark[i, next_v, next_index])
        next_v -= int(v[i, cur])
        res[i - 1] = next_index
    return float(total), res, next_v - int(v[0, next_index])
