"""Profiled-config parsing and hardware latency tables for the search engine.

Capability parity with the reference's profile ingestion: the missing half of
C20 (utils/config_utils.py:48-185 ``read_allreduce_bandwidth_config`` /
``read_p2p_bandwidth_config`` / ``remap_config`` / ``remap_config_for_latency``)
plus the model-profile parsing + curve fitting
(search_engine.py:286-417 ``get_profiled_model_configs``): static mode reads
single points, batch mode fits time linear in batch size, sequence mode fits
time quadratic in sequence length; memory in sequence mode is scaled from the
longest profiled sequence.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np
from scipy.optimize import curve_fit


def read_json(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def write_json(cfg: Dict[str, Any], path: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump(cfg, f, indent=4)


def int_keys(d: Any) -> Any:
    """'8' -> 8 recursively (reference convert_keys_to_int)."""
    if isinstance(d, dict):
        return {(int(k) if isinstance(k, str) and k.isdigit() else k):
                int_keys(v) for k, v in d.items()}
    return d


def fit_linear(x: Sequence[float], y: Sequence[float]) -> np.ndarray:
    popt, _ = curve_fit(lambda v, m, c: m * v + c, x, y)
    return popt


def fit_quadratic(x: Sequence[float], y: Sequence[float]) -> np.ndarray:
    popt, _ = curve_fit(lambda v, a, b, c: a * v * v + b * v + c, x, y)
    return popt


# ---------------------------------------------------------------------------
# hardware configs
# ---------------------------------------------------------------------------


def read_allreduce_bandwidth(config: Any, device_num: int
                             ) -> Tuple[Dict[str, float], Dict[str, float]]:
    """(bandwidth MB/ms, latency ms/MB) dicts keyed '<size>[_consec]'
    (reference read_allreduce_bandwidth_config, config_utils.py:48-76).
    The full-world group has no non-consecutive variant."""
    env = read_json(config) if isinstance(config, str) else config
    bw: Dict[str, float] = {}
    coe: Dict[str, float] = {}
    n = device_num
    if n >= 2:
        v = env[f"allreduce_size_{n}_consec_1"]
        for k in (f"{n}", f"{n}_1", f"{n}_0"):
            bw[k] = v
            coe[k] = 1.0 / v
    n //= 2
    while n >= 2:
        for consec in (0, 1):
            v = env[f"allreduce_size_{n}_consec_{consec}"]
            bw[f"{n}_{consec}"] = v
            coe[f"{n}_{consec}"] = 1.0 / v
        n //= 2
    for k in ("1", "1_1", "1_0"):
        bw[k] = np.inf
        coe[k] = 0.0
    return bw, coe


def read_alpha_beta(config: Any) -> Dict[str, Tuple[float, float]]:
    """Fitted latency-bandwidth pairs per (group size, consecutiveness)
    from the allreduce-bandwidth JSON: ``allreduce_size_{n}_consec_{c}_
    alpha_ms`` / ``..._beta_mb_per_ms`` keys (written by
    ``hardware_profiler.profile_alpha_beta``) -> {"{n}_{c}": (α ms,
    β MB/ms)}. Legacy bandwidth-only JSONs simply yield an empty dict —
    the cost model then falls back to the measured latency tables, so old
    profiles keep producing byte-identical golden costs."""
    env = read_json(config) if isinstance(config, str) else config
    out: Dict[str, Tuple[float, float]] = {}
    for key, val in env.items():
        if not (key.startswith("allreduce_size_")
                and key.endswith("_alpha_ms")):
            continue
        if "_alg_" in key:
            # namespaced per-algorithm/per-level pairs
            # (profile_alpha_beta_algos) — parsed by
            # :func:`read_alpha_beta_algos`; pairing one of their alphas
            # with the FLAT beta key here would corrupt the legacy table
            continue
        parts = key.split("_")  # allreduce_size_{n}_consec_{c}_alpha_ms
        n, c = parts[2], parts[4]
        beta = env.get(f"allreduce_size_{n}_consec_{c}_beta_mb_per_ms")
        if beta:
            out[f"{n}_{c}"] = (float(val), float(beta))
    return out


def read_alpha_beta_algos(config: Any
                          ) -> Dict[str, Dict[str, Tuple[float, float]]]:
    """Per-algorithm, per-level fitted pairs from the allreduce-bandwidth
    JSON: ``allreduce_size_{n}_consec_{c}_alg_{ring|tree}_lvl_{ici|dcn}_
    alpha_ms`` / ``..._beta_mb_per_ms`` keys (written by
    ``hardware_profiler.profile_alpha_beta_algos``) ->
    ``{"{n}_{c}": {"{alg}_{lvl}": (α ms, β MB/ms)}}``. The cost model
    prices a collective as the MIN over the curves available at its size
    and level; profiles without the namespaced keys yield an empty dict
    and every golden cost stays byte-identical."""
    env = read_json(config) if isinstance(config, str) else config
    out: Dict[str, Dict[str, Tuple[float, float]]] = {}
    for key, val in env.items():
        if not (key.startswith("allreduce_size_") and "_alg_" in key
                and key.endswith("_alpha_ms")):
            continue
        # allreduce_size_{n}_consec_{c}_alg_{alg}_lvl_{lvl}_alpha_ms
        parts = key.split("_")
        n, c, alg, lvl = parts[2], parts[4], parts[6], parts[8]
        beta = env.get(
            f"allreduce_size_{n}_consec_{c}_alg_{alg}_lvl_{lvl}"
            "_beta_mb_per_ms")
        if beta:
            out.setdefault(f"{n}_{c}", {})[f"{alg}_{lvl}"] = (
                float(val), float(beta))
    return out


def read_profile_provenance(config: Any) -> Dict[str, Any]:
    """The ``calibration_meta`` provenance payload of a hardware profile
    (written by ``observability.calibration.refit_profile``: source tag,
    per-curve point counts + fit method, fit window, fingerprint), or
    ``{}`` for plain profiled JSONs. Both α-β parsers above skip the key
    entirely, so provenance is free to ride along in the same file."""
    env = read_json(config) if isinstance(config, str) else config
    meta = env.get("calibration_meta") if isinstance(env, dict) else None
    return meta if isinstance(meta, dict) else {}


def merge_calibrated_profile(prior: Dict[str, Any],
                             calibrated: Dict[str, Any]) -> Dict[str, Any]:
    """Overlay runtime-calibrated curves on a profiled prior: calibrated
    α-β (and ``calibration_meta``) keys win, every other prior key — bare
    bandwidth entries, p2p tables, anything the profiler wrote — carries
    over untouched. The result is a complete standalone hardware profile:
    point ``allreduce_bandwidth_config_path`` (or the audit hook) at it
    and curves the traces re-fit replace the one-shot ones while
    unfitted curves keep their prior."""
    out = dict(prior or {})
    out.update(calibrated or {})
    return out


def read_p2p_bandwidth(config: Any) -> Tuple[Dict[int, float], Dict[int, float]]:
    """pp_size -> (bandwidth, 1/bandwidth) (reference config_utils.py:77-89)."""
    env = read_json(config) if isinstance(config, str) else config
    bw, coe = {}, {}
    for key, val in env.items():
        if "pp_size_" in key:
            bw[int(key.split("_")[-1])] = val
            coe[int(key.split("_")[-1])] = 1.0 / val
    return bw, coe


def remap_collective_bytes(config: Dict[str, float], op: str
                           ) -> Dict[int, Dict[Any, float]]:
    """sp-time entries -> {world: {bytes: ms, 'popt': fit}} (reference
    remap_config, config_utils.py:108-145); allreduce halves to the
    all-gather/reduce-scatter equivalent."""
    out: Dict[int, Dict[Any, float]] = {}
    for key, val in config.items():
        if key.startswith(op):
            if op == "allreduce":
                val = val / 2
            split = key.split("_")
            world, mb = int(split[-3]), int(split[-2][:-2])
            out.setdefault(world, {})[mb * 1024 * 1024] = val
    for world, table in out.items():
        x = [sz // 1024 // 1024 for sz in table]
        y = list(table.values())
        if len(x) < 8:
            raise ValueError(
                f"{op} profile needs >=8 message sizes, got {len(x)}")
        table["popt"] = fit_linear(x, y)
    return out


def remap_collective_latency(config: Dict[str, float], op: str
                             ) -> Dict[int, Dict[Any, float]]:
    """{world: {MB: ms, 'popt': fit}} latency tables (reference
    remap_config_for_latency, config_utils.py:147-185). 'allgather' derives
    from the allreduce rows at half time."""
    key_string = {"allreduce": "allreduce_size", "all2all": "all2all_size",
                  "allgather": "allreduce_size"}[op]
    factor = 0.5 if op == "allgather" else 1.0
    out: Dict[int, Dict[Any, float]] = {}
    for key, val in config.items():
        if key.startswith(key_string):
            split = key.split("_")
            world, mb = int(split[-3]), int(split[-2][:-2])
            out.setdefault(world, {})[mb] = val * factor
    for world, table in out.items():
        x = list(table.keys())
        y = list(table.values())
        if len(x) < 8:
            raise ValueError(
                f"{op} profile needs >=8 message sizes, got {len(x)}")
        table["popt"] = fit_linear(x, y)
    return out


@dataclass
class HardwareProfile:
    """All hardware latency tables the cost models consume."""

    allreduce_bandwidth: Dict[str, float]
    allreduce_coe: Dict[str, float]  # ms/MB
    p2p_bandwidth: Dict[int, float]
    p2p_coe: Dict[int, float]
    overlap_coe: float
    sp_allreduce: Dict[int, Dict[Any, float]]
    sp_all2all: Dict[int, Dict[Any, float]]
    allreduce_latency: Dict[int, Dict[Any, float]]
    allgather_latency: Dict[int, Dict[Any, float]]
    all2all_latency: Dict[int, Dict[Any, float]]
    # fitted α-β pairs per "{size}_{consec}" (empty for legacy profiles)
    alpha_beta: Dict[str, Tuple[float, float]] = field(default_factory=dict)
    # per-algorithm/per-level pairs: "{size}_{consec}" ->
    # {"{ring|tree}_{ici|dcn}": (α, β)} (empty for legacy profiles)
    alpha_beta_algos: Dict[str, Dict[str, Tuple[float, float]]] = field(
        default_factory=dict)


def load_hardware_profile(
    *,
    allreduce_path: str,
    p2p_path: str,
    overlap_path: str,
    sp_time_path: str,
    world_size: int,
) -> HardwareProfile:
    """Read the four hardware_configs JSONs (reference
    get_profiled_hardware_configs, search_engine.py:419-462)."""
    bw, coe = read_allreduce_bandwidth(allreduce_path, world_size)
    alpha_beta = read_alpha_beta(allreduce_path)
    alpha_beta_algos = read_alpha_beta_algos(allreduce_path)
    p2p_bw, p2p_coe = read_p2p_bandwidth(p2p_path)
    overlap = read_json(overlap_path)["overlap_coe"]
    sp = read_json(sp_time_path)
    return HardwareProfile(
        allreduce_bandwidth=bw,
        allreduce_coe=coe,
        p2p_bandwidth=p2p_bw,
        p2p_coe=p2p_coe,
        overlap_coe=overlap,
        sp_allreduce=remap_collective_bytes(sp, "allreduce"),
        sp_all2all=remap_collective_bytes(sp, "all2all"),
        allreduce_latency=remap_collective_latency(sp, "allreduce"),
        allgather_latency=remap_collective_latency(sp, "allgather"),
        all2all_latency=remap_collective_latency(sp, "all2all"),
        alpha_beta=alpha_beta,
        alpha_beta_algos=alpha_beta_algos,
    )


# ---------------------------------------------------------------------------
# model profiles (computation time + memory)
# ---------------------------------------------------------------------------


@dataclass
class ModelProfile:
    """Per-layertype computation fits + memory tables (reference
    get_profiled_model_configs outputs)."""

    time_profiled_list: List[Any]  # scalar or popt per layertype
    other_time_profiled_list: List[Any]
    param_sizes: List[float]
    act_sizes: List[Dict[Any, float]]
    other_memory_pp_off: Dict[str, Dict[int, float]]
    other_memory_pp_on: Dict[str, Dict[str, Dict[int, float]]]


def parse_time_config(
    time_config: Dict[str, float],
    *,
    mode: str,
    num_layertype: int,
    seqlen_list: Sequence[int],
) -> Tuple[List[Any], List[Any]]:
    """static: raw ms values; batch: linear fit of t*bsz vs bsz; sequence:
    quadratic (layers) / linear (vocab) fit over seq evaluated at the target
    seqlen (search_engine.py:289-361)."""
    times: List[Any] = []
    others: List[Any] = []
    if mode == "static":
        for i in range(num_layertype):
            for key, t in time_config.items():
                if key.startswith(f"layertype_{i}_"):
                    times.append(t)
                if key.startswith("layertype_other_"):
                    others.append(t)
    elif mode == "batch":
        for i in range(num_layertype):
            xs, ys = [], []
            for key, t in time_config.items():
                if key.startswith(f"layertype_{i}_") and \
                        f"_seq{seqlen_list[i]}" in key:
                    bsz = int(key.split("_")[-2][3:])
                    xs.append(bsz)
                    ys.append(t * bsz)
            if len(xs) < 8:
                raise ValueError(
                    f"batch-mode profile needs >=8 bsz points, got {len(xs)}")
            times.append(fit_linear(xs, ys))
        for i in range(num_layertype):
            xs, ys = [], []
            for key, t in time_config.items():
                if key.startswith("layertype_other_") and \
                        f"_seq{seqlen_list[i]}" in key:
                    bsz = int(key.split("_")[-2][3:])
                    xs.append(bsz)
                    ys.append(t * bsz)
            if len(xs) < 8:
                raise ValueError(
                    f"batch-mode profile needs >=8 bsz points, got {len(xs)}")
            others.append(fit_linear(xs, ys))
    elif mode == "sequence":
        for i in range(num_layertype):
            xs, ys = [], []
            for key, t in time_config.items():
                if key.startswith(f"layertype_{i}_") and "_bsz1_" in key:
                    xs.append(int(key.split("seq")[-1]))
                    ys.append(t)
            popt = fit_quadratic(xs, ys)
            times.append(popt[0] * seqlen_list[i] ** 2 +
                         popt[1] * seqlen_list[i] + popt[2])
        for i in range(num_layertype):
            xs, ys = [], []
            for key, t in time_config.items():
                if key.startswith("layertype_other_") and "_bsz1_" in key:
                    xs.append(int(key.split("seq")[-1]))
                    ys.append(t)
            popt = fit_linear(xs, ys)
            others.append(popt[0] * seqlen_list[i] + popt[1])
    else:
        raise ValueError(f"unknown time profile mode {mode}")
    return times, others


def parse_memory_config(
    memory_config: Dict[str, Any],
    *,
    mode: str,
    num_layertype: int,
    seqlen_list: Sequence[int],
    sequence_parallel: bool,
) -> Tuple[List[float], List[Dict], Dict, Dict]:
    """Returns (param_sizes, act_sizes, other_pp_off, other_pp_on)
    (search_engine.py:362-417)."""
    memory_config = int_keys(memory_config)
    sp_suffix = "_sp" if sequence_parallel else ""
    param_sizes: List[float] = [0.0] * num_layertype
    act_sizes: List[Dict] = [{} for _ in range(num_layertype)]

    if mode == "sequence":
        if not sequence_parallel:
            raise ValueError("sequence memory profiling requires "
                             "sequence_parallel")
        # (the reference restricts sequence-mode memory profiles to one
        # layertype; the per-layertype loop below is generic, which lets
        # encoder-decoder searches scale each stack's activations by its own
        # sequence length)
        maxseq_list = []
        for i in range(num_layertype):
            layer_mem = memory_config[f"layertype_{i}_sp"]
            seqs = [int(s) for s in layer_mem.keys()]
            maxseq, minseq = max(seqs), min(seqs)
            maxseq_list.append(maxseq)
            param_sizes[i] = layer_mem[minseq]["parameter_size"]
            act = dict(layer_mem[maxseq]["tp_activation_per_bsz_dict"])
            act_sizes[i] = {k: v / maxseq * seqlen_list[i]
                            for k, v in act.items()}
        off = memory_config["other_memory_pp_off_sp"][maxseq_list[0]]
        on = {"first_stage":
              memory_config["other_memory_pp_on_first_sp"][maxseq_list[0]],
              "last_stage":
              memory_config["other_memory_pp_on_last_sp"][maxseq_list[-1]]}
        for tp in off["activation"]:
            off["activation"][tp] = (off["activation"][tp] / maxseq_list[0] *
                                     seqlen_list[0])
            on["first_stage"]["activation"][tp] = (
                on["first_stage"]["activation"][tp] / maxseq_list[0] *
                seqlen_list[0])
            on["last_stage"]["activation"][tp] = (
                on["last_stage"]["activation"][tp] / maxseq_list[-1] *
                seqlen_list[-1])
    elif mode == "static":
        for i in range(num_layertype):
            layer_mem = memory_config[f"layertype_{i}{sp_suffix}"]
            param_sizes[i] = layer_mem[seqlen_list[i]]["parameter_size"]
            act_sizes[i] = dict(
                layer_mem[seqlen_list[i]]["tp_activation_per_bsz_dict"])
        seq_key = (seqlen_list[0] if len(seqlen_list) == 1
                   else "_".join(str(s) for s in seqlen_list))
        off = memory_config[f"other_memory_pp_off{sp_suffix}"][seq_key]
        on = {"first_stage":
              memory_config[f"other_memory_pp_on_first{sp_suffix}"][seq_key],
              "last_stage":
              memory_config[f"other_memory_pp_on_last{sp_suffix}"][seq_key]}
    else:
        raise ValueError(f"unknown memory profile mode {mode}")
    return param_sizes, act_sizes, off, on


def load_model_profile(
    *,
    time_path: str,
    memory_path: str,
    time_mode: str,
    memory_mode: str,
    num_layertype: int,
    seqlen_list: Sequence[int],
    sequence_parallel: bool,
) -> ModelProfile:
    times, others = parse_time_config(
        read_json(time_path), mode=time_mode, num_layertype=num_layertype,
        seqlen_list=seqlen_list)
    params, acts, off, on = parse_memory_config(
        read_json(memory_path), mode=memory_mode, num_layertype=num_layertype,
        seqlen_list=seqlen_list, sequence_parallel=sequence_parallel)
    return ModelProfile(
        time_profiled_list=times, other_time_profiled_list=others,
        param_sizes=params, act_sizes=acts,
        other_memory_pp_off=off, other_memory_pp_on=on)
