from hetu_galvatron_tpu.core.search_engine.engine import (  # noqa: F401
    SearchEngine,
    TaskResult,
)
from hetu_galvatron_tpu.core.search_engine.strategies import (  # noqa: F401
    SearchSpaceLimits,
    SearchStrategy,
    enumerate_strategies,
    pp_division_even,
)
