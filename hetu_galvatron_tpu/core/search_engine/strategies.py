"""Search-space strategy representation and enumeration.

Capability parity with the reference's search-side strategy machinery
(utils/strategy_utils.py:36-230 strategy dataclasses + ordering,
core/search_engine/search_engine.py:106-255 ``generate_strategy_list`` /
``filter_strategy_list``): a single :class:`SearchStrategy` dataclass covers
the reference's Attention/FFN/Layer variants (they differ only in class name),
plus an embedding/LM-head variant without the checkpoint bit.

The total ordering (field-lexicographic: pp, tp, sp, cp, dp, dp_type,
checkpoint) matters: the DP breaks ties by first-seen order, so enumeration
order is part of golden-value parity with the reference search test.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import List, Tuple

from hetu_galvatron_tpu.utils.strategy import DPType, LayerStrategy


def is_power_of_two(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


@dataclass(frozen=True, order=False)
class SearchStrategy:
    """One candidate per-layer plan in the search space. ``sp_size`` is the
    Ulysses degree (exclusive with tp>1); ``tp_sp`` is whichever is active."""

    pp: int = 1
    tp: int = 1
    sp: int = 1
    cp: int = 1
    dp: int = 1
    dp_type: DPType = DPType.DDP
    checkpoint: bool = False
    is_vocab: bool = False  # embedding/LM-head row: no checkpoint dimension

    def __post_init__(self):
        if self.tp > 1 and self.sp > 1:
            raise ValueError("tp and sp (Ulysses) are exclusive")
        # a strategy with no sharded-dp group degenerates to DDP (reference
        # _check_and_fix_sdp, strategy_utils.py:49-52)
        if self.sdp == 1 and self.dp_type != DPType.DDP:
            object.__setattr__(self, "dp_type", DPType.DDP)

    @property
    def tp_sp(self) -> int:
        return max(self.tp, self.sp)

    @property
    def sdp(self) -> int:
        """The group size ZeRO shards states over: dp x sp x cp (reference
        sdp_size, strategy_utils.py:62-64)."""
        return self.dp * self.sp * self.cp

    @property
    def world(self) -> int:
        return self.pp * self.tp * self.sp * self.cp * self.dp

    def sort_key(self) -> Tuple:
        return (self.pp, self.tp, self.sp, self.cp, self.dp,
                self.dp_type.value, self.checkpoint)

    def vocab_variant(self) -> "SearchStrategy":
        return replace(self, checkpoint=False, is_vocab=True)

    def simple_string(self) -> str:
        """Compact form matching the reference to_simple_string
        (strategy_utils.py:73-92): pp-tpsp[*]-dp[f][-c][-sp]."""
        s = f"{self.pp}-"
        s += f"{self.tp_sp}*-" if self.tp_sp != 1 else f"{self.tp_sp}-"
        s += f"{self.dp}f" if self.dp_type == DPType.ZERO3 else f"{self.dp}"
        if self.checkpoint:
            s += "-c"
        if self.sp > 1:
            s += "-sp"
        return s

    def to_runtime(self) -> LayerStrategy:
        """Convert to the runtime LayerStrategy (tp carries the Ulysses
        degree with the sp flag set)."""
        return LayerStrategy(
            pp_deg=self.pp, tp_size=self.tp_sp, dp_size=self.dp,
            cp_size=self.cp, sp=self.sp > 1, dp_type=self.dp_type,
            checkpoint=self.checkpoint,
        )


@dataclass
class SearchSpaceLimits:
    """Enumeration bounds + disable switches (reference
    SearchEngineSearchSpaceArgs, search_engine/args_schema.py:27-41)."""

    max_pp_deg: int = 8
    max_tp_deg: int = 8
    max_sp_deg: int = 8
    max_cp_deg: int = 8
    disable_pp: int = 0
    disable_tp: int = 0
    disable_sp: int = 0
    disable_cp: int = 1
    disable_dp: int = 0
    disable_ckpt: int = 0
    disable_fsdp: int = 0
    disable_vocab_tp: int = 0
    disable_vocab_sp: int = 0


def enumerate_strategies(
    world_size: int,
    total_layer_num: int,
    limits: SearchSpaceLimits,
    default_dp_type: str = "ddp",
) -> Tuple[List[SearchStrategy], List[SearchStrategy]]:
    """Power-of-two sweep over pp x {tp|sp} x cp x dp-type x checkpoint
    (reference generate_strategy_list, search_engine.py:106-181). Returns
    (layer strategies, vocab strategies), each sorted and deduped."""
    degrees = []
    d = 1
    while d <= world_size:
        degrees.append(d)
        d *= 2

    out: List[SearchStrategy] = []
    for pp in degrees:
        if pp > total_layer_num or pp > limits.max_pp_deg:
            continue
        for mode in ("tp", "sp"):
            for tp_sp in degrees:
                if mode == "tp" and limits.max_tp_deg != -1 and \
                        tp_sp > limits.max_tp_deg:
                    continue
                if mode == "sp" and limits.max_sp_deg != -1 and \
                        tp_sp > limits.max_sp_deg:
                    continue
                if tp_sp * pp > world_size:
                    continue
                for cp in degrees:
                    if limits.max_cp_deg != -1 and cp > limits.max_cp_deg:
                        continue
                    if pp * tp_sp * cp > world_size:
                        continue
                    dp = world_size // pp // tp_sp // cp
                    if dp == 1 and cp == 1:
                        dp_types = [DPType.DDP]
                    elif dp == 1:
                        # cp>1 with dp=1: ZeRO still shards states over the
                        # ring group (sdp = dp*sp*cp > 1) — without this the
                        # long-sequence cp regime would carry fully
                        # replicated model states (beyond the reference,
                        # which never enumerates cp)
                        dp_types = ([DPType.DDP, DPType.ZERO3]
                                    if default_dp_type == "ddp"
                                    else [DPType.ZERO2, DPType.ZERO3])
                    elif default_dp_type == "ddp":
                        dp_types = [DPType.DDP, DPType.ZERO3]
                    else:
                        dp_types = [DPType.ZERO2, DPType.ZERO3]
                    for dpt in dp_types:
                        for ckpt in (False, True):
                            out.append(SearchStrategy(
                                pp=pp,
                                tp=tp_sp if mode == "tp" else 1,
                                sp=tp_sp if mode == "sp" else 1,
                                cp=cp, dp=dp, dp_type=dpt, checkpoint=ckpt))
    layer = sorted(set(out), key=SearchStrategy.sort_key)
    vocab = sorted({s.vocab_variant() for s in layer},
                   key=SearchStrategy.sort_key)
    return filter_strategies(layer, limits), filter_strategies(
        vocab, limits, vocab=True)


def filter_strategies(
    strategies: List[SearchStrategy],
    limits: SearchSpaceLimits,
    vocab: bool = False,
) -> List[SearchStrategy]:
    """Apply the disable_* switches (reference filter_strategy_list,
    search_engine.py:182-255)."""
    out = strategies
    if limits.disable_pp:
        out = [s for s in out if s.pp == 1]
    if limits.disable_tp or (vocab and limits.disable_vocab_tp):
        out = [s for s in out if s.tp == 1]
    if limits.disable_sp or (vocab and limits.disable_vocab_sp):
        out = [s for s in out if s.sp == 1]
    if limits.disable_cp:
        out = [s for s in out if s.cp == 1]
    if limits.disable_dp:
        out = [s for s in out if s.dp == 1]
    if limits.disable_ckpt and not vocab:
        out = [s for s in out if not s.checkpoint]
    if limits.disable_fsdp:
        out = [s for s in out if s.dp_type != DPType.ZERO3]
    return sorted(set(out), key=SearchStrategy.sort_key)


def pp_division_even(layernum_list: List[int], pp_deg: int) -> List[int]:
    """Even stage division, remainder to the last stage (reference
    pp_division_even, search_engine.py:1094-1099)."""
    total = sum(layernum_list)
    avg = total // pp_deg
    return [avg] * (pp_deg - 1) + [total - avg * (pp_deg - 1)]
