"""Layer-wise hybrid-parallel strategy search engine.

Capability parity with the reference search engine
(core/search_engine/search_engine.py:21-820 GalvatronSearchEngine +
dynamic_programming.py:117-648 DpOnModel): enumerate candidate per-layer
strategies, evaluate them with the analytical cost models against profiled
model/hardware data, and solve a per-pipeline-stage knapsack DP over
(layer, memory, strategy) with inter-layer transition costs — then write the
winning plan as a ``galvatron_config_*.json`` the runtime consumes.

The outer loop sweeps (global bsz, microbatch chunks, pp degree, tp-vs-ulysses
mode, max tp degree); each task runs the DP per stage per vocab-layer strategy
and scores the full plan with the pipeline cost model. Cost arithmetic is kept
exactly reference-equivalent (golden regression:
tests/search_engine/test_search_golden.py).
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from hetu_galvatron_tpu.core.args_schema import SearchArgs
from hetu_galvatron_tpu.core.cost_model.cost import (
    CostContext,
    embed_memory_cost,
    embed_time_cost,
    layer_memory_cost,
    layer_time_components,
    layer_time_cost,
    pipeline_time_cost,
)
from hetu_galvatron_tpu.core.search_engine.dp import dp_solve
from hetu_galvatron_tpu.core.search_engine.profiles import (
    HardwareProfile,
    ModelProfile,
    load_hardware_profile,
    load_model_profile,
    write_json,
)
from hetu_galvatron_tpu.core.search_engine.strategies import (
    SearchSpaceLimits,
    SearchStrategy,
    enumerate_strategies,
    is_power_of_two,
    pp_division_even,
)
from hetu_galvatron_tpu.utils.strategy import (
    DPType,
    EmbeddingLMHeadStrategy,
    strategy_list2config,
)


@dataclass
class TaskResult:
    throughput: float = -1.0
    time_cost: float = float("inf")
    strategy_list: Optional[List[SearchStrategy]] = None
    pp_size: int = 1
    pp_stage_list: Optional[List[int]] = None
    memory_remain: Optional[List[int]] = None
    memory_cost: Optional[List[float]] = None
    vocab_tp_sp: int = -1
    vocab_sp: int = 0
    vocab_sdp: int = 0
    bsz: int = 0
    chunks: int = 1


def _match_except(former: SearchStrategy, latter: SearchStrategy,
                  diff: Sequence[str]) -> bool:
    """True when the two strategies agree on everything except (exactly) the
    ``diff`` dimensions (reference match_strategy,
    dynamic_programming.py:161-210). Used for the DP's tiny tie-break biases
    that order fsdp/checkpoint/sp transitions."""
    diff = sorted(diff)
    same = {
        "pp": former.pp == latter.pp,
        "tp": former.tp == latter.tp,
        "sp": former.sp == latter.sp,
        "tp_sp": former.tp_sp == latter.tp_sp,
        "dp": former.dp == latter.dp,
        "dp_type": former.dp_type == latter.dp_type,
        "checkpoint": former.checkpoint == latter.checkpoint,
    }
    if diff == ["sp"]:
        return (same["pp"] and same["tp_sp"] and same["dp"]
                and same["checkpoint"] and same["dp_type"] and not same["sp"])
    if diff == ["fsdp"]:
        return (same["pp"] and same["tp"] and same["sp"] and same["dp"]
                and same["checkpoint"] and not same["dp_type"])
    if diff == ["cpt"]:
        return (same["pp"] and same["tp"] and same["sp"] and same["dp"]
                and same["dp_type"] and not same["checkpoint"])
    if diff == sorted(["fsdp", "cpt"]):
        return (same["pp"] and same["tp"] and same["sp"] and same["dp"]
                and not (same["dp_type"] and same["checkpoint"]))
    return True


class SearchEngine:
    """Offline planner: profiled JSONs in, galvatron_config JSON out."""

    def __init__(self, args: SearchArgs, *, mixed_precision: str = "bf16",
                 default_dp_type: Optional[str] = None,
                 pipeline_type: Optional[str] = None,
                 model_cfg: Any = None):
        self.args = args
        self.world_size = args.num_nodes * args.num_devices_per_node
        self.memory_constraint = int(args.memory_constraint * 1024)  # MB
        self.mixed_precision = mixed_precision
        self.default_dp_type = default_dp_type or args.default_dp_type
        self.pipeline_type = pipeline_type or args.pipeline_type
        self.model_name: Optional[str] = None
        self.hardware: Optional[HardwareProfile] = None
        self.profile: Optional[ModelProfile] = None
        # ModelArgs for the static HBM gate (args.hbm_budget_gb): the
        # profiled memory the DP enforces and the doctor's analytic
        # accounting are independent models, and the gate makes the
        # search reject exactly what `check --memory --hbm-gb` would
        self.model_cfg = model_cfg

    # ---------------- setup ----------------

    def set_model_info(self, model_layer_configs: List[Dict[str, Any]],
                       model_name: str, model_type: str = "gpt") -> None:
        """model_layer_configs rows: hidden_size / seq_len / layer_num
        (reference set_model_layer_configs, search_engine.py:84-91).
        Encoder-decoder models (t5) search the combined enc+dec stack:
        layertype 0 is the encoder, the plan JSON records the split point
        (num_encoder_layers) and the runtime pipelines either stack."""
        self.num_encoder_layers: Optional[int] = None
        if model_type == "t5":
            # adapter convention: layertype 0 is the encoder, omitted when
            # the model has zero encoder layers
            self.num_encoder_layers = (
                model_layer_configs[0]["layer_num"]
                if len(model_layer_configs) > 1 else 0)
        self.hiddensize_list = [c["hidden_size"] for c in model_layer_configs]
        self.layernum_list = [c["layer_num"] for c in model_layer_configs]
        self.seqlen_list = [c["seq_len"] for c in model_layer_configs]
        self.num_layertype = len(self.layernum_list)
        self.total_layernum = sum(self.layernum_list)
        self.model_name = model_name

    def _limits(self) -> SearchSpaceLimits:
        a = self.args
        return SearchSpaceLimits(
            max_pp_deg=a.max_pp_deg, max_tp_deg=a.max_tp_deg,
            max_sp_deg=a.max_sp_deg, max_cp_deg=a.max_cp_deg,
            disable_pp=a.disable_pp, disable_tp=a.disable_tp,
            disable_sp=a.disable_ulysses, disable_cp=a.disable_cp,
            disable_dp=a.disable_dp, disable_ckpt=a.disable_ckpt,
            disable_fsdp=a.disable_sdp, disable_vocab_tp=a.disable_vtp,
            disable_vocab_sp=a.disable_vsp)

    def initialize(self) -> None:
        """Strategy enumeration + profile loading + cost-context construction
        (reference initialize_search_engine, search_engine.py:97-108)."""
        a = self.args
        self.layer_strategies, self.vocab_strategies = enumerate_strategies(
            self.world_size, self.total_layernum, self._limits(),
            self.default_dp_type)
        self.profile = load_model_profile(
            time_path=a.time_profiling_path,
            memory_path=a.memory_profiling_path,
            time_mode=a.time_profile_mode,
            memory_mode=a.memory_profile_mode,
            num_layertype=self.num_layertype,
            seqlen_list=self.seqlen_list,
            sequence_parallel=a.sequence_parallel)
        self.hardware = load_hardware_profile(
            allreduce_path=a.allreduce_bandwidth_config_path,
            p2p_path=a.p2p_bandwidth_config_path,
            overlap_path=a.overlap_coe_path,
            sp_time_path=a.sp_time_path,
            world_size=self.world_size)
        self.contexts = [self._make_context(i)
                         for i in range(self.num_layertype)]

    def _make_context(self, i: int) -> CostContext:
        hw, mp = self.hardware, self.profile
        return CostContext(
            parameter_size=mp.param_sizes[i],
            seq_length=self.seqlen_list[i],
            hidden_size=self.hiddensize_list[i],
            layer_num=self.layernum_list[i],
            mixed_precision=self.mixed_precision != "fp32",
            async_grad_reduce=self.args.async_grad_reduce,
            sequence_parallel=self.args.sequence_parallel,
            pipeline_type=self.pipeline_type,
            forward_computation_time=mp.time_profiled_list[i],
            other_time_profiled=mp.other_time_profiled_list[
                min(i, len(mp.other_time_profiled_list) - 1)],
            tp_activation_per_bsz_dict=mp.act_sizes[i],
            other_memory_pp_off=mp.other_memory_pp_off,
            other_memory_pp_on=mp.other_memory_pp_on,
            comm_coe_dict=hw.allreduce_coe,
            dp_overlap_coe=hw.overlap_coe,
            bct_overlap_coe=hw.overlap_coe,
            p2p_comm_coe_dict=hw.p2p_coe,
            costmodel_coe=self.args.costmodel_coe,
            allgather_latency=hw.allgather_latency,
            all2all_latency=hw.all2all_latency,
            allreduce_latency=hw.allreduce_latency,
            dispatch_us=self.args.dispatch_us,
            schedule_impl=self.args.pipeline_schedule_impl,
            tp_alpha_beta=hw.alpha_beta,
            tp_overlap=bool(self.args.tp_overlap),
            alpha_beta_algos=hw.alpha_beta_algos,
            hier_dp=bool(self.args.hier_dp),
            hier_bucket_mb=float(getattr(self.args, "hier_bucket_mb", 0.0)),
            # the search's topology model: nodes are the cross-DCN level
            # (mesh.dcn_factor_shape's slice granularity)
            dcn_slices=max(self.args.num_nodes, 1),
        )

    # ---------------- outer loop ----------------

    def _bsz_candidates(self) -> List[int]:
        a = self.args
        if a.settle_bsz and a.settle_bsz > 0:
            return [a.settle_bsz]
        lo = max(a.min_bsz, a.bsz_scale)
        return list(range(lo, a.max_bsz + 1, a.bsz_scale))

    def optimize(self) -> float:
        """Full sweep; returns max throughput in samples/s and writes the
        winning plan (reference parallelism_optimization,
        search_engine.py:520-644)."""
        a = self.args
        pp_range = sorted({s.pp for s in self.vocab_strategies})
        tasks = []
        for gbsz in self._bsz_candidates():
            chunk_list = ([a.settle_chunks] if a.settle_chunks != -1
                          else range(1, gbsz + 1))
            for chunks in chunk_list:
                if gbsz % chunks:
                    continue
                for pp in pp_range:
                    if pp > chunks or pp > self.total_layernum:
                        continue
                    max_tp = self.world_size // pp
                    if a.max_tp_deg != -1:
                        max_tp = min(max_tp, a.max_tp_deg)
                    max_dp = max(min(gbsz // chunks, self.world_size // pp), 1)
                    min_tp = max(self.world_size // pp // max_dp, 1)
                    for mode in ("tp_only", "sp_only", "tp_with_sp"):
                        if mode == "sp_only":
                            tp_caps = [max_tp]
                        else:
                            tp_caps = [t for t in range(min_tp, max_tp + 1)
                                       if is_power_of_two(t)
                                       and t * pp <= self.world_size]
                        for cap in tp_caps:
                            tasks.append((gbsz, chunks, pp, mode, cap))

        solve = lambda t: self.solve_task(t[0], t[1], t[2], t[4], t[3])
        if a.parallel_search and len(tasks) > 1:
            # thread pool (reference search_engine.py:579-610): the C++ DP
            # core runs outside the GIL, so threads overlap the hot loop
            import concurrent.futures as cf

            with cf.ThreadPoolExecutor(max_workers=min(8, len(tasks))) as ex:
                results = list(ex.map(solve, tasks))
        else:
            results = list(map(solve, tasks))
        results = [self._hbm_gate(r) for r in results]
        best = TaskResult()
        for r in results:
            if r.throughput > best.throughput:
                best = r
        self._write_search_trace(tasks, results, best)
        if best.throughput > 0:
            self.save_results(best, runner_ups=self._runner_ups(results,
                                                                best))
        return best.throughput

    def _runner_ups(self, results: List[TaskResult], best: TaskResult
                    ) -> List[Dict[str, Any]]:
        """The top-``args.runner_up_k`` feasible non-winning candidates
        (deduped by plan signature, throughput-ordered) in the stored
        shape ``cost_model.reprice_stored_plan_ms`` prices — embedded in
        the plan JSON so the runtime's plan-regret sentinel
        (``observability.calibration``) can re-price "the plans the search
        almost picked" under calibrated curves long after the search
        ran."""
        from hetu_galvatron_tpu.utils.strategy import form_strategy

        k = max(int(getattr(self.args, "runner_up_k", 0) or 0), 0)
        if k == 0:
            return []

        def sig(r: TaskResult) -> Tuple:
            return (r.bsz, r.chunks, r.pp_size,
                    tuple(s.to_runtime().key() for s in r.strategy_list),
                    tuple(r.pp_stage_list or ()))

        seen = {sig(best)} if best.strategy_list is not None else set()
        out: List[Dict[str, Any]] = []
        for r in sorted((r for r in results
                         if r.strategy_list is not None
                         and r.throughput > 0),
                        key=lambda r: -r.throughput):
            s = sig(r)
            if s in seen:
                continue
            seen.add(s)
            layers = []
            for st in r.strategy_list:
                rt = st.to_runtime()
                layers.append({
                    "tp": rt.tp_size, "dp": rt.dp_size, "cp": rt.cp_size,
                    "sp": int(rt.sp), "ckpt": int(rt.checkpoint),
                    "consec": int(rt.tp_consecutive)})
            out.append({
                "throughput": round(r.throughput, 6),
                "time_cost_ms": round(r.time_cost * 1e3, 6),
                "bsz": r.bsz, "chunks": r.chunks, "pp": r.pp_size,
                "strategies": [form_strategy(st.to_runtime())
                               for st in r.strategy_list],
                "layers": layers})
            if len(out) >= k:
                break
        return out

    def _write_search_trace(self, tasks, results, best: TaskResult) -> None:
        """Audit trail: one JSONL event per explored task + the winner
        (args.search_trace_path; observability/sinks.py record schema), so
        "why did the search pick this plan" is answerable after the fact."""
        if not self.args.search_trace_path:
            return
        import time as _time

        from hetu_galvatron_tpu.observability.sinks import JsonlSink
        from hetu_galvatron_tpu.utils.strategy import form_strategy

        sink = JsonlSink(self.args.search_trace_path)
        for (gbsz, chunks, pp, mode, cap), r in zip(tasks, results):
            data = {"bsz": gbsz, "chunks": chunks, "pp": pp, "mode": mode,
                    "max_tp": cap, "throughput": r.throughput,
                    "time_cost": (None if r.time_cost == float("inf")
                                  else r.time_cost),
                    "feasible": r.strategy_list is not None}
            if r.strategy_list is not None:
                data["pp_division"] = r.pp_stage_list
                data["memory_cost_mb"] = r.memory_cost
                data["vocab"] = {"vtp": r.vocab_tp_sp, "vsp": r.vocab_sp,
                                 "embed_sdp": r.vocab_sdp}
            sink.write({"t": _time.time(), "kind": "event",
                        "name": "search_task", "data": data})
        win = {"throughput": best.throughput, "bsz": best.bsz,
               "chunks": best.chunks, "pp": best.pp_size,
               "feasible": best.strategy_list is not None}
        if best.strategy_list is not None:
            win["strategies"] = [form_strategy(s.to_runtime())
                                 for s in best.strategy_list]
        sink.write({"t": _time.time(), "kind": "event",
                    "name": "search_best", "data": win})
        sink.close()

    def _hbm_gate(self, r: TaskResult) -> TaskResult:
        """Static HBM gate (``args.hbm_budget_gb`` > 0, model config
        known): prune a feasible candidate whose memory-doctor peak
        busts the budget — the SAME predicate ``cli/check.py --memory
        --hbm-gb`` applies to the written plan
        (``analysis/memory_doctor.py::search_result_hbm_reason``).
        Always accounted under the COMPILED-engine convention (the
        checker's default, and the strict upper bound: it adds the
        stage-input buffer and the vocab replication premium the host
        engine doesn't pay), so a plan the search emits can never be one
        ``check --memory --hbm-gb`` rejects — regardless of which
        schedule impl the search was pricing time for.

        Known altitude limitation: the gate runs POST-DP, on each
        (bsz, chunks, pp) task's time-optimal winner — a pruned task may
        still have a slower within-budget runner-up the DP never
        surfaced (the DP's own memory constraint is the PROFILED
        ``memory_constraint``, not this analytic one). Folding the
        analytic predicate into candidate filtering is future work; the
        gate's contract today is a backstop, not an optimizer."""
        a = self.args
        if (a.hbm_budget_gb <= 0 or self.model_cfg is None
                or r.strategy_list is None):
            return r
        from hetu_galvatron_tpu.analysis.memory_doctor import (
            search_result_hbm_reason,
        )

        reason = search_result_hbm_reason(
            r.strategy_list, r.pp_stage_list, self.model_cfg,
            global_bsz=r.bsz, chunks=r.chunks,
            pipeline_type=self.pipeline_type,
            schedule_impl="compiled",
            hbm_gb=a.hbm_budget_gb,
            vocab_tp_sp=r.vocab_tp_sp, vocab_sp=bool(r.vocab_sp),
            vocab_sdp=bool(r.vocab_sdp),
            mixed_precision=self.mixed_precision != "fp32")
        if reason is None:
            return r
        print(f"hbm gate: pruned candidate (bsz {r.bsz} chunks {r.chunks} "
              f"pp {r.pp_size}): {reason}")
        return TaskResult(bsz=r.bsz, chunks=r.chunks)

    # ---------------- per-task DP ----------------

    def _filter_for_task(self, strategies, pp, max_tp, max_dp, mode):
        out = [s for s in strategies if s.pp == pp and s.tp_sp <= max_tp
               and s.dp <= max_dp]
        if mode == "tp_only":
            out = [s for s in out if s.sp == 1]
        elif mode == "sp_only":
            out = [s for s in out if s.tp == 1]
        return out

    def _global_buffer_mb(self, gbsz, chunks, pp, cap, mode) -> float:
        """Megatron global memory buffer reserve (dynamic_programming.py:
        232-239). NOTE: the reference halves this whenever mixed_precision is
        a non-empty string — i.e. always, even for fp32; replicated for
        golden parity."""
        a = self.args
        if not (a.sequence_parallel and a.global_memory_buffer
                and mode != "sp_only"):
            return 0.0
        cur_dp = self.world_size // pp // cap
        cur_lbsz = gbsz / chunks / cur_dp
        mb = (cur_lbsz * self.hiddensize_list[0] * max(self.seqlen_list)
              * 4 / 1024 / 1024)
        return mb / 2

    def _inter_layer_cost(self, layer_strategies, gbsz, chunks, pp
                          ) -> np.ndarray:
        """Transition costs between adjacent layers with different strategies:
        a real resharding cost when tp_sp changes, else epsilon tie-breaks
        (dynamic_programming.py:467-517)."""
        n = len(layer_strategies)
        total = self.total_layernum
        out = np.zeros((total, n, n))
        for t in range(self.num_layertype):
            res = np.zeros((n, n))
            for fi, former in enumerate(layer_strategies):
                for li, latter in enumerate(layer_strategies):
                    if fi == li:
                        continue
                    if (self.args.sequence_parallel
                            and former.tp_sp != latter.tp_sp):
                        big = max(former.tp_sp, latter.tp_sp)
                        cur_dp = self.world_size // pp // big
                        cur_lbsz = gbsz / chunks / cur_dp
                        sample = (self.seqlen_list[t] * self.hiddensize_list[0]
                                  * (4 if self.mixed_precision == "fp32"
                                     else 2))
                        cost = (big - 1) / big * cur_lbsz * sample
                        coe_dict = self.hardware.allreduce_coe
                        if big == 1 or cur_dp == 1:
                            coe = coe_dict.get(f"{big}",
                                               coe_dict.get(f"{big}_1"))
                        else:
                            coe = coe_dict[f"{big}_1"]
                        res[fi, li] = cost * coe * 1e-7
                    else:
                        if _match_except(former, latter, ["sp"]) \
                                and latter.sp > 1:
                            res[fi, li] = 1e-10
                        if _match_except(former, latter, ["fsdp"]) \
                                and latter.dp_type == DPType.ZERO3:
                            res[fi, li] = 1e-9
                        if _match_except(former, latter, ["cpt"]) \
                                and latter.checkpoint:
                            res[fi, li] = 2e-9
                        if _match_except(former, latter, ["fsdp", "cpt"]) \
                                and latter.dp_type == DPType.ZERO3 \
                                and latter.checkpoint:
                            res[fi, li] = 3e-9
                        if (_match_except(former, latter, ["fsdp", "cpt"])
                                and not _match_except(former, latter, ["fsdp"])
                                and not _match_except(former, latter, ["cpt"])
                                and former.dp_type == DPType.ZERO3
                                and latter.checkpoint):
                            res[fi, li] = 1e-9
            lo = sum(self.layernum_list[:t])
            out[lo:lo + self.layernum_list[t]] = res
        out[0, :, :] = 0  # first layer has no predecessor
        return out

    def pp_division_balanced(self, gbsz: int, chunks: int, pp: int
                             ) -> List[int]:
        """Memory-balanced stage division (reference
        pp_division_memory_balanced, search_engine.py:954-1058): greedily
        fill stages to the average memory of a ZeRO-2 dp baseline (gpipe
        accounting), then rebalance overweight/empty stages. Used for
        multi-layertype models, where even layer counts put uneven memory
        on stages (reference get_pp_stage_for_bsz single_layer_even)."""
        if pp == 1:
            return [self.total_layernum]
        base = SearchStrategy(pp=pp, tp=1, sp=1, cp=1,
                              dp=self.world_size // pp,
                              dp_type=DPType.ZERO2)
        per_type = [layer_memory_cost(base, self.contexts[t], gbsz, chunks,
                                      stage_idx=0, pipeline_type="gpipe")
                    for t in range(self.num_layertype)]
        layer_costs: List[float] = []
        for t, n in enumerate(self.layernum_list):
            layer_costs += [per_type[t]] * n
        other = list(embed_memory_cost(base.vocab_variant(),
                                       self.contexts[0], gbsz, chunks,
                                       pipeline_type="gpipe"))
        avg = (sum(layer_costs) + sum(other)) / pp

        divide = [0] * pp
        stage_mem = list(other)
        idx = 0
        for i in range(pp):
            while idx < len(layer_costs):
                if i < pp - 1 and avg - stage_mem[i] < 0.5 * layer_costs[idx]:
                    break
                stage_mem[i] += layer_costs[idx]
                idx += 1
                divide[i] += 1
        # drain overweight early stages forward
        for i in range(pp - 1):
            left = sum(divide[:i])
            right = left + divide[i]
            cur = sum(layer_costs[left:right]) + other[i]
            while cur > avg * 1.3 and divide[i] > 0:
                divide[i] -= 1
                divide[i + 1] += 1
                right -= 1
                cur -= layer_costs[right]
        # no empty stages
        for i in range(pp - 1):
            while divide[i] <= 0:
                divide[i] += 1
                divide[i + 1] -= 1
        for i in range(pp - 1, 0, -1):
            while divide[i] <= 0:
                divide[i] += 1
                divide[i - 1] -= 1
        return divide

    def check_cost_model(self, gbsz: int, chunks: int,
                         strategies: Optional[List[SearchStrategy]] = None
                         ) -> List[Dict[str, Any]]:
        """Developer introspection (reference check_cost_model,
        search_engine.py:788): evaluate every candidate strategy's per-layer
        time and per-stage memory at (gbsz, chunks), print a table, and
        return the rows for programmatic use."""
        rows: List[Dict[str, Any]] = []
        for s in (strategies if strategies is not None
                  else self.layer_strategies):
            if s.pp > chunks or gbsz // chunks < s.dp:
                continue
            time_sync, time_nosync = layer_time_cost(
                s, self.contexts[0], gbsz, chunks)
            mem = [layer_memory_cost(s, self.contexts[0], gbsz, chunks,
                                     stage_idx=st,
                                     pipeline_type=self.pipeline_type)
                   for st in range(s.pp)]
            vs = s.vocab_variant()
            vmem = embed_memory_cost(vs, self.contexts[0], gbsz, chunks,
                                     pipeline_type=self.pipeline_type)
            row = {"strategy": s.simple_string(), "time": time_sync,
                   "time_no_sync": time_nosync, "layer_memory": mem,
                   "vocab_memory": list(vmem)}
            rows.append(row)
            print(f"check_cost_model[{s.simple_string()}]: "
                  f"time {time_sync * 1e3:.3f} ms "
                  f"(no-sync {time_nosync * 1e3:.3f}) "
                  f"mem/layer {mem[0]:.1f} MB vocab {vmem[0]:.1f} MB")
        return rows

    def solve_task(self, gbsz: int, chunks: int, pp: int, cap: int,
                   mode: str) -> TaskResult:
        """One (bsz, chunks, pp, mode, max-tp) cell (reference
        search_for_single_task + _build_dp_and_run_multi_layer_type)."""
        max_dp = max(min(gbsz // chunks, self.world_size // pp), 1)
        layer_list = self._filter_for_task(
            self.layer_strategies, pp, cap, max_dp, mode)
        vocab_list = self._filter_for_task(
            self.vocab_strategies, pp, cap, max_dp, mode)
        if not layer_list or not vocab_list:
            return TaskResult(bsz=gbsz, chunks=chunks)
        vocab_list = sorted(vocab_list, key=SearchStrategy.sort_key)
        # single-layertype models keep the reference's even split (golden
        # parity); multi-layertype (t5/moe) stacks balance stage memory
        partition = (pp_division_even(self.layernum_list, pp)
                     if self.num_layertype == 1
                     else self.pp_division_balanced(gbsz, chunks, pp))

        # memory budget with the reserved allocator cache
        # (dynamic_programming.py:154-159)
        max_mem = self.memory_constraint
        mem_cache = 0
        if max_mem // 1024 > 20:
            mem_cache = int(max_mem * 0.2)
            max_mem -= mem_cache
        global_mb = self._global_buffer_mb(gbsz, chunks, pp, cap, mode)

        if not self.args.fine_grained_mode:
            return self._solve_coarse(gbsz, chunks, pp, partition, layer_list,
                                      max_mem, mem_cache, global_mb)

        n = len(layer_list)
        total = self.total_layernum
        intra = np.zeros((total, n))
        for t in range(self.num_layertype):
            row = [layer_time_cost(s, self.contexts[t], gbsz, chunks)[0]
                   for s in layer_list]
            lo = sum(self.layernum_list[:t])
            intra[lo:lo + self.layernum_list[t]] = np.asarray(row)

        mem = [np.zeros((total, n), np.int64) for _ in range(pp)]
        for stage in range(pp):
            for t in range(self.num_layertype):
                row = np.ceil([layer_memory_cost(
                    s, self.contexts[t], gbsz, chunks, stage_idx=stage,
                    pipeline_type=self.pipeline_type) for s in layer_list]
                ).astype(np.int64)
                lo = sum(self.layernum_list[:t])
                mem[stage][lo:lo + self.layernum_list[t]] = row
        inter = self._inter_layer_cost(layer_list, gbsz, chunks, pp)

        best = TaskResult(bsz=gbsz, chunks=chunks, pp_size=pp,
                          pp_stage_list=partition)
        for vs in vocab_list:
            vtime, vtime_nosync = embed_time_cost(
                vs, self.contexts[0], gbsz, chunks, self.seqlen_list)
            vmem = np.ceil(embed_memory_cost(
                vs, self.contexts[0], gbsz, chunks,
                pipeline_type=self.pipeline_type)).astype(int)

            plan: List[SearchStrategy] = []
            remain, used = [], []
            feasible = True
            start = 0
            for stage in range(pp):
                cnt = partition[stage]
                cost, idxs, rem = dp_solve(
                    mem[stage][start:start + cnt],
                    intra[start:start + cnt],
                    inter[start:start + cnt],
                    max_mem,
                    int(vmem[stage] + int(global_mb)),
                    float(vtime[stage]),
                    use_cpp_core=self.args.use_cpp_core)
                if idxs is None:
                    feasible = False
                    break
                plan.extend(layer_list[i] for i in idxs)
                remain.append(rem)
                used.append(max_mem - rem + mem_cache)
                start += cnt
            if not feasible:
                continue
            cost = pipeline_time_cost(
                self.layernum_list, self.contexts, plan, partition, chunks,
                gbsz, pp, vtime_nosync)
            if cost < best.time_cost:
                best = TaskResult(
                    throughput=gbsz / cost, time_cost=cost,
                    strategy_list=plan, pp_size=pp, pp_stage_list=partition,
                    memory_remain=remain, memory_cost=used,
                    vocab_tp_sp=vs.tp_sp, vocab_sp=int(vs.sp > 1),
                    vocab_sdp=int(vs.dp_type == DPType.ZERO3),
                    bsz=gbsz, chunks=chunks)
        return best

    def _solve_coarse(self, gbsz, chunks, pp, partition, layer_list,
                      max_mem, mem_cache, global_mb) -> TaskResult:
        """Uniform-strategy mode: every layer shares one strategy
        (dynamic_programming.py:243-360)."""
        best = TaskResult(bsz=gbsz, chunks=chunks, pp_size=pp,
                          pp_stage_list=partition)
        for ls in layer_list:
            vs = ls.vocab_variant()
            _, vtime_nosync = embed_time_cost(
                vs, self.contexts[0], gbsz, chunks, self.seqlen_list)
            vmem = embed_memory_cost(vs, self.contexts[0], gbsz, chunks,
                                     pipeline_type=self.pipeline_type)
            oom = False
            used, remain = [], []
            start = 0
            for stage in range(pp):
                u = math.ceil(global_mb) + math.ceil(vmem[stage])
                for li in range(start, start + partition[stage]):
                    u += math.ceil(self._stage_layer_mem(
                        ls, gbsz, chunks, stage, li))
                start += partition[stage]
                used.append(u)
                if u > max_mem:
                    oom = True
                    break
            if oom:
                continue
            remain = [max_mem - u for u in used]
            used = [u + mem_cache for u in used]
            plan = [ls] * self.total_layernum
            cost = pipeline_time_cost(
                self.layernum_list, self.contexts, plan, partition, chunks,
                gbsz, pp, vtime_nosync)
            if cost < best.time_cost:
                best = TaskResult(
                    throughput=gbsz / cost, time_cost=cost, strategy_list=plan,
                    pp_size=pp, pp_stage_list=partition, memory_remain=remain,
                    memory_cost=used, vocab_tp_sp=vs.tp_sp,
                    vocab_sp=int(vs.sp > 1),
                    vocab_sdp=int(vs.dp_type == DPType.ZERO3),
                    bsz=gbsz, chunks=chunks)
        return best

    def _stage_layer_mem(self, s, gbsz, chunks, stage, layer_idx) -> float:
        """Layer layer_idx's memory at a given stage (layertype-resolved)."""
        t = 0
        acc = 0
        for ti, cnt in enumerate(self.layernum_list):
            if layer_idx < acc + cnt:
                t = ti
                break
            acc += cnt
        return layer_memory_cost(s, self.contexts[t], gbsz, chunks,
                                 stage_idx=stage,
                                 pipeline_type=self.pipeline_type)

    # ---------------- output ----------------

    def save_results(self, best: TaskResult,
                     runner_ups: Optional[List[Dict[str, Any]]] = None
                     ) -> str:
        """Write the interchange JSON (reference save_results,
        search_engine.py:749-785). ``runner_ups`` (see
        :meth:`_runner_ups`) and the winner's own priced total ride along
        as extra keys — ``config2strategy`` ignores them, so old readers
        are unaffected — giving the runtime's plan-regret sentinel its
        re-pricing baseline."""
        default_dp = DPType.from_name(self.default_dp_type)
        runtime = []
        for s in best.strategy_list:
            r = s.to_runtime()
            if r.dp_size == 1:
                # dp=1 carries no dp flavour; encode as the default type
                from dataclasses import replace as _replace
                r = _replace(r, dp_type=default_dp)
            runtime.append(r)
        # embed the winner's per-layer compute prediction (fct+bct, ms) so
        # the runtime's plan audit diffs the EXACT model that picked the
        # plan — without this the audit's compute row is measured-only
        pred_ms: List[float] = []
        li = 0
        for lt, n in enumerate(self.layernum_list):
            ctx = self.contexts[lt]
            for _ in range(n):
                comp = layer_time_components(
                    best.strategy_list[li], ctx, best.bsz, best.chunks)
                pred_ms.append(round(comp["fct_ms"] + comp["bct_ms"], 6))
                li += 1
        # record the hierarchical dp choice when the hierarchical term
        # priced EVERY layer's dp reduction (cost.hier_dp_wins) — the
        # runtime then enables the matching ops/hier_reduce.py path
        hier_chosen = False
        hier_bucket = 0.0
        dp_sched_name = None
        dp_sched_ranks = None
        if self.args.hier_dp:
            from hetu_galvatron_tpu.core.cost_model.cost import (
                dp_schedule_choice,
                hier_dp_best_bucket,
                hier_dp_wins,
                hier_grad_payload_mb,
            )

            li = 0
            flags = []
            for lt, n in enumerate(self.layernum_list):
                for _ in range(n):
                    flags.append(hier_dp_wins(
                        best.strategy_list[li], self.contexts[lt],
                        best.bsz, best.chunks))
                    li += 1
            hier_chosen = bool(flags) and all(flags)
            if hier_chosen:
                # record the bucket granularity the price assumed: the
                # configured size, or — auto mode (hier_bucket_mb < 0) —
                # the sweep's argmin over the first layertype's whole
                # grad payload, so the runtime pipelines at exactly the
                # granularity the search paid for
                ctx0 = self.contexts[0]
                s0 = best.strategy_list[0]
                if ctx0.hier_bucket_mb < 0:
                    _, hier_bucket = hier_dp_best_bucket(
                        s0, ctx0, hier_grad_payload_mb(s0, ctx0))
                else:
                    hier_bucket = max(ctx0.hier_bucket_mb, 0.0)
                # collective-compiler record: price the synthesized
                # schedule space for the winning plan's dp group and name
                # the cheapest family (cost.dp_schedule_choice). The
                # emitted programs are monolithic, so a bucketed plan
                # keeps the hand-implemented pipelined path instead.
                if hier_bucket == 0.0:
                    choice = dp_schedule_choice(
                        s0, ctx0, hier_grad_payload_mb(s0, ctx0))
                    if choice is not None:
                        dp_sched_name, ranks = choice
                        dp_sched_ranks = {
                            k: round(v, 6) for k, v in sorted(
                                ranks.items(), key=lambda kv: kv[1])}
        cfg = strategy_list2config(
            runtime, global_bsz=best.bsz, chunks=best.chunks,
            pipeline_type=self.pipeline_type,
            default_dp_type=self.default_dp_type,
            vocab=EmbeddingLMHeadStrategy(
                vtp=best.vocab_tp_sp, vsp=bool(best.vocab_sp),
                embed_sdp=bool(best.vocab_sdp)),
            pp_division=best.pp_stage_list,
            num_encoder_layers=getattr(self, "num_encoder_layers", None),
            predicted_layer_compute_ms=pred_ms,
            hier_dp=hier_chosen, hier_bucket_mb=hier_bucket,
            dp_schedule=dp_sched_name)
        if dp_sched_ranks:
            # the full priced space rides along (cheapest first) so plan
            # readers can see HOW the family won, not just that it did
            cfg["dp_schedule_rankings"] = dp_sched_ranks
        if best.time_cost != float("inf"):
            cfg["predicted_time_cost_ms"] = round(best.time_cost * 1e3, 6)
        if runner_ups:
            cfg["runner_ups"] = runner_ups
        a = self.args
        off = [name for flag, name in (
            (a.disable_dp, "dp"), (a.disable_tp, "tp"), (a.disable_pp, "pp"),
            (a.disable_sdp, "fsdp"), (a.disable_ckpt, "ckpt")) if flag]
        name = ("galvatron_config_%s_%dnodes_%dgpus_per_node_%dGB"
                % (self.model_name, a.num_nodes, a.num_devices_per_node,
                   self.memory_constraint // 1024))
        name += "_%s" % self.mixed_precision
        if a.settle_bsz > 0:
            name += "_bsz%d" % a.settle_bsz
        if off:
            name += "_[%s_off]" % "_".join(off)
        path = os.path.join(a.output_config_path or "configs",
                            name + ".json")
        # validating writer (utils/strategy.py): the plan must round-trip
        # through config2strategy + per-layer LayerStrategy.validate at the
        # searcher's world size BEFORE it lands on disk — a serialization
        # bug surfaces here, not on the TPU fleet at load time
        from hetu_galvatron_tpu.utils.strategy import save_strategy_config

        save_strategy_config(path, cfg, world_size=self.world_size)
        return path
