"""YAML config loading with dotted overrides.

Replaces the reference's `load_with_hydra` (core/arguments.py:125-155) without a
Hydra dependency: a YAML file is deep-merged over schema defaults, then
``key.sub=value`` / ``++key.sub=value`` command-line overrides are applied, and
the result is validated into :class:`CoreArgs`. Supports an ``include:`` key for
YAML composition (the subset of Hydra "defaults" Galvatron actually uses).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Union

import yaml

from hetu_galvatron_tpu.core.args_schema import CoreArgs


def _deep_merge(base: Dict[str, Any], over: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _load_yaml(path: str) -> Dict[str, Any]:
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    includes = raw.pop("include", None)
    if includes:
        if isinstance(includes, str):
            includes = [includes]
        merged: Dict[str, Any] = {}
        for inc in includes:
            inc_path = inc if os.path.isabs(inc) else os.path.join(
                os.path.dirname(os.path.abspath(path)), inc
            )
            merged = _deep_merge(merged, _load_yaml(inc_path))
        raw = _deep_merge(merged, raw)
    return raw


def _parse_scalar(text: str) -> Any:
    """YAML-parse a single override value ('8'->int, 'true'->bool, 'a,b'->str)."""
    try:
        val = yaml.safe_load(text)
    except yaml.YAMLError:
        return text
    if isinstance(val, str):
        # YAML 1.1 misses bare scientific notation like '1e-4'
        try:
            return int(val)
        except ValueError:
            pass
        try:
            return float(val)
        except ValueError:
            pass
    return val


def _apply_override(tree: Dict[str, Any], dotted: str, value: Any) -> None:
    keys = dotted.split(".")
    node = tree
    for k in keys[:-1]:
        node = node.setdefault(k, {})
        if not isinstance(node, dict):
            raise ValueError(f"override {dotted}: {k} is not a mapping")
    node[keys[-1]] = value


def parse_overrides(overrides: Sequence[str]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for item in overrides:
        item = item.strip()
        if not item:
            continue
        item = item.lstrip("+")  # accept hydra-style '++key=value'
        if "=" not in item:
            raise ValueError(f"override '{item}' is not key=value")
        key, _, val = item.partition("=")
        _apply_override(tree, key.strip(), _parse_scalar(val.strip()))
    return tree


def load_config(
    config: Union[str, Dict[str, Any], None] = None,
    overrides: Optional[Sequence[str]] = None,
    mode: str = "train_dist",
) -> CoreArgs:
    """Load a YAML path (or dict) + overrides into a validated CoreArgs.

    Equivalent entry point to the reference's
    ``load_with_hydra(path, overrides, mode)`` (core/arguments.py:125).
    """
    if config is None:
        tree: Dict[str, Any] = {}
    elif isinstance(config, str):
        tree = _load_yaml(config)
    else:
        tree = dict(config)
    if overrides:
        tree = _deep_merge(tree, parse_overrides(overrides))
    tree.setdefault("mode", mode)
    return CoreArgs.model_validate(tree)


def args_from_cli(argv: Sequence[str], mode: str) -> CoreArgs:
    """CLI convention shared by all launchers:
    ``python train_dist.py <config.yaml> [key=value ...]``."""
    cfg_path: Optional[str] = None
    overrides: List[str] = []
    for a in argv:
        if cfg_path is None and "=" not in a and (a.endswith(".yaml") or a.endswith(".yml")):
            cfg_path = a
        else:
            overrides.append(a)
    return load_config(cfg_path, overrides, mode)
