from hetu_galvatron_tpu.core.cost_model.cost import (  # noqa: F401
    CostContext,
    embed_memory_cost,
    embed_time_cost,
    layer_memory_cost,
    layer_time_cost,
    pipeline_time_cost,
)
