"""Analytical time/memory cost models for the strategy search.

Capability parity with the reference cost models
(core/cost_model/components/layer_cost.py:9-328 TimeCostModelBase /
MemoryCostModelBase, embedding_lmhead_cost.py:9-313, cost_model_handler.py:16
pipeline_costmodel). The arithmetic is kept semantically identical — the
golden-value search regression (tests/search_engine/
test_parallelsim_optimization.py) depends on it — but the structure is
plain functions over one flat :class:`CostContext` instead of the reference's
five arg-dataclasses merged through SimpleNamespaces.

Units: memory in MB, profiled times in ms, returned times in seconds.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import (
    TYPE_CHECKING,
    Any,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
    Union,
)

import numpy as np

from hetu_galvatron_tpu.analysis.eligibility import (
    search_compiled_expressible,
    search_hier_dp_expressible,
    search_tp_overlap_expressible,
)
from hetu_galvatron_tpu.utils.strategy import DPType

if TYPE_CHECKING:  # typing only — a runtime import would be circular
    # (search_engine/__init__ imports engine, engine imports this module)
    from hetu_galvatron_tpu.core.search_engine.strategies import SearchStrategy

Fit = Union[float, np.ndarray, Tuple[float, float]]


def _linear(x, popt):
    return popt[0] * x + popt[1]


def _lookup_latency(select: Dict[Any, float], message_mb: float) -> float:
    """Measured table hit, else the fitted linear extrapolation (reference
    layer_cost.py:143-148)."""
    if message_mb in select:
        return select[message_mb]
    return _linear(message_mb, select["popt"])


@dataclass
class CostContext:
    """Everything one layertype's cost evaluation needs: model shape,
    profiled model costs, and hardware latency tables (reference ModelArgs /
    TrainArgs / ParallelArgs / ProfileModelArgs / ProfileHardwareArgs,
    cost_model_args.py)."""

    # model
    parameter_size: float = 48.0  # MB per layer
    seq_length: int = 1024
    hidden_size: int = 4096
    layer_num: int = 16
    # train
    mixed_precision: bool = True
    async_grad_reduce: bool = True
    pytorch_context_mem: float = 1024.0
    # parallel
    sequence_parallel: bool = True
    pipeline_type: str = "gpipe"
    # profiled model costs
    forward_computation_time: Fit = 1.0  # ms/sample (or linear fit popt)
    other_time_profiled: Fit = 0.0
    tp_activation_per_bsz_dict: Dict[Any, float] = field(default_factory=dict)
    other_memory_pp_off: Dict[str, Dict[int, float]] = field(default_factory=dict)
    other_memory_pp_on: Dict[str, Dict[str, Dict[int, float]]] = field(
        default_factory=dict)
    # profiled hardware
    bct_fct_coe: float = 2.0
    extra_overhead: float = 0.0
    comm_coe_dict: Dict[str, float] = field(default_factory=dict)  # ms/MB
    dp_overlap_coe: float = 1.3
    bct_overlap_coe: float = 1.3
    p2p_comm_coe_dict: Optional[Dict[int, float]] = None
    costmodel_coe: float = 1.0
    allgather_latency: Dict[int, Dict[Any, float]] = field(default_factory=dict)
    all2all_latency: Dict[int, Dict[Any, float]] = field(default_factory=dict)
    allreduce_latency: Dict[int, Dict[Any, float]] = field(default_factory=dict)
    # host-sequenced pipeline dispatch overhead (beyond the reference):
    # the host engine pays ~dispatch_us of wall time per already-compiled
    # stage-jit call — 2 (fwd + bwd) * pp * chunks calls per step — while
    # the compiled single-program schedule (pipeline.schedule_impl=
    # compiled) pays none. Measured by tools/pipeline_dispatch_bench.py;
    # 0.0 (the default) keeps the reference-equivalent arithmetic exact.
    dispatch_us: float = 0.0
    schedule_impl: str = "host"
    # latency-aware (α-β) TP collective model + overlapped-TP discount
    # (beyond the reference, which prices TP purely from the measured
    # latency tables): tp_alpha_beta maps "{size}_{consec}" -> (alpha_ms,
    # beta_mb_per_ms) fitted by hardware_profiler.profile_alpha_beta on
    # the ALLREDUCE curve; a Megatron-SP ag/rs-equivalent message costs
    # 0.5 * (α + size/β). Empty dict (legacy profiles) falls back to the
    # measured latency-table lookup, leaving golden costs byte-identical.
    # tp_overlap=True applies the max(comm, compute)-style discount of the
    # decomposed ring matmuls (ops/overlap.py) to overlap-expressible
    # layers only (tp > 1, no cp, not under the compiled pipeline engine).
    tp_alpha_beta: Dict[str, Tuple[float, float]] = field(default_factory=dict)
    tp_overlap: bool = False
    # per-algorithm, per-LEVEL collective curves (beyond the single fitted
    # curve): "{size}_{consec}" -> {"{ring|tree}_{ici|dcn}": (α ms,
    # β MB/ms)}, fitted by hardware_profiler.profile_alpha_beta_algos over
    # algorithm-SHAPED schedules (ring reduce-scatter/all-gather vs
    # recursive halving-doubling) on intra-host/ICI vs cross-slice/DCN
    # groups. A collective is priced as the MIN over the curves available
    # at its size and level — "Revisiting the Time Cost Model of
    # AllReduce": ring and tree have materially different (α, β) regimes,
    # and the win comes from CHOOSING per collective ("The Big Send-off").
    # Empty dict (legacy profiles) keeps every golden cost byte-identical.
    alpha_beta_algos: Dict[str, Dict[str, Tuple[float, float]]] = field(
        default_factory=dict)
    # hierarchical dp gradient reduction pricing (search.hier_dp +
    # ops/hier_reduce.py): when the per-level curves are available, an
    # eligible layer's dp term may be priced as reduce-scatter intra-host
    # at full grad volume + all-reduce across slices on the 1/intra shard
    # + all-gather back (un-overlapped — the runtime reduces once at step
    # end), and the layer cost takes min(flat, hierarchical). dcn_slices
    # (the search maps num_nodes onto it) fixes the slice/host split with
    # the same pp-first absorption as mesh.dcn_factor_shape.
    hier_dp: bool = False
    dcn_slices: int = 1
    # bucketed software pipelining of the hierarchical reduction
    # (ops/hier_reduce.py wavefront emission): > 0 splits the grad payload
    # into <=hier_bucket_mb-MB buckets and prices the pipelined schedule —
    # first bucket pays the full rs+ar+ag chain, every further bucket pays
    # only the bottleneck stage max(T_ici, T_dcn) (fill-drain), so the α
    # overhead grows per bucket while the slow link hides behind the fast
    # ones. 0 keeps the monolithic rs+ar+ag sum — byte-identical goldens.
    hier_bucket_mb: float = 0.0


def _zero_ratios(chunks: int, mixed_precision: bool, async_grad_reduce: bool):
    """(zero2_ratio, zero3_ratio) closures over the shard degree d
    (reference layer_cost.py:289-300; the +0.003 is the reference's
    flat all-gather bookkeeping overhead)."""
    if chunks == 1:
        z2 = (lambda d: 7 / 8 * (1 / d + 0.003) + 1 / 8) if mixed_precision \
            else (lambda d: 3 / 4 * (1 / d + 0.003) + 1 / 4)
        z3 = lambda d: 1 / d + 0.003
    elif async_grad_reduce:
        z2 = (lambda d: 6 / 8 * (1 / d + 0.003) + 2 / 8) if mixed_precision \
            else (lambda d: 2 / 4 * (1 / d + 0.003) + 2 / 4)
        z3 = (lambda d: 7 / 8 * (1 / d + 0.003) + 1 / 8) if mixed_precision \
            else (lambda d: 3 / 4 * (1 / d + 0.003) + 1 / 4)
    else:
        # sync grad reduce with microbatching keeps an fp32 grad copy (x5/4)
        z2 = (lambda d: (7 / 8 * (1 / d + 0.003) + 1 / 8) * 5 / 4) \
            if mixed_precision else (lambda d: 3 / 4 * (1 / d + 0.003) + 1 / 4)
        z3 = lambda d: (1 / d + 0.003) * 5 / 4
    return z2, z3


# ---------------------------------------------------------------------------
# decoder-layer time
# ---------------------------------------------------------------------------


def tp_overlap_expressible(s: "SearchStrategy", ctx: CostContext) -> bool:
    """Can this layer run the decomposed ring-overlap matmuls
    (eligibility.overlap_unsupported_reason, the shape checks aside — the
    search works in degrees, not concrete widths)? Megatron TP only
    (Ulysses has s.tp == 1 here) and no cp. Since the compiled 1F1B engine
    de-vmapped its stage axis (round 12), the rings run INSIDE the fused
    program too — pp > 1 under ``schedule_impl="compiled"`` keeps the
    discount, so the overlap hiding and the dispatch waiver COMPOSE on
    deep-pp plans. The predicate is shared with the runtime dispatch via
    ``analysis/eligibility.py`` (the parity test pins it)."""
    return search_tp_overlap_expressible(s.tp, s.cp, ctx.tp_overlap)


def _overlap_window(comm: float, comp: float, coe: float) -> float:
    """Wall time of (collective ∥ dependent compute), mirroring the dp
    ``overlap()`` split (layer_cost.py:161-178): both sides run slowed by
    the profiled overlap coefficient until the shorter one drains, the
    remainder finishes at full speed."""
    comm_ov, comp_ov = comm * coe, comp * coe
    if comm_ov > comp_ov:
        return comp_ov + (comm - comp_ov / coe)
    if comm_ov < comp_ov:
        return comm_ov + (comp - comm_ov / coe)
    return comm_ov


def _algo_min_ms(ctx: CostContext, size: int, consec: int, level: str,
                 message_mb: float) -> Optional[float]:
    """Cheapest ALLREDUCE time at ``message_mb`` over the per-algorithm
    curves fitted for group ``(size, consec)`` at the given topology
    ``level`` (``ici`` | ``dcn``); None when no curve covers it. This is
    where the algorithm CHOICE happens: small messages ride the
    latency-optimal halving-doubling curve, large ones the
    bandwidth-optimal ring, per collective and per size."""
    table = ctx.alpha_beta_algos.get(f"{size}_{consec}")
    if not table:
        return None
    best = None
    suffix = f"_{level}"
    for key, (alpha, beta) in table.items():
        if not key.endswith(suffix):
            continue
        t = alpha + message_mb / beta
        if best is None or t < best:
            best = t
    return best


def _tp_message_ms(s: "SearchStrategy", ctx: CostContext,
                   message_mb: float) -> float:
    """One Megatron-SP ag/rs-equivalent collective of ``message_mb`` MB:
    the cheapest of the fitted curves when the profile carries them — the
    flat α-β pair AND the per-algorithm ICI curves, each at half the
    allreduce time (matching profiles.remap_collective_latency's allgather
    derivation) — else the legacy measured-table lookup. Only called with
    s.tp > 1; tp groups are consecutive (the same assumption the legacy
    dc_key encodes), so the "{n}_1" pair applies and the level is ici."""
    candidates = []
    ab = ctx.tp_alpha_beta.get(f"{s.tp}_1")
    if ab is not None:
        alpha, beta = ab
        candidates.append(alpha + message_mb / beta)
    algo = _algo_min_ms(ctx, s.tp, 1, "ici", message_mb)
    if algo is not None:
        candidates.append(algo)
    if candidates:
        return 0.5 * min(candidates)
    return _lookup_latency(ctx.allgather_latency[s.tp], message_mb)


def _hier_dp_split(s: "SearchStrategy", ctx: CostContext
                   ) -> Optional[Tuple[int, int]]:
    """(cross, intra) split of the layer's DP group — the group the
    runtime's lane reduction actually covers (``mesh.hier_cross_degree``
    splits dp, not sdp; the leftover cp/sp partial sums stay in-lane) —
    mirroring the pp-first slice absorption; None when the leftover
    slices cannot divide dp (the runtime would reject too)."""
    import math as _math

    dcn = max(ctx.dcn_slices, 1)
    left = dcn // _math.gcd(dcn, max(s.pp, 1))
    if s.dp % left:
        return None
    return left, s.dp // left


def hier_grad_payload_mb(s: "SearchStrategy", ctx: CostContext) -> float:
    """Per-device megabytes of the hierarchical reduction's grad payload:
    the whole model's layer params on this tp shard, in the training
    dtype. THE one formula — `layer_time_cost`'s pricing, the audit's
    dp decomposition, and the search engine's plan-bucket recording all
    call it, so the bucket size written into the plan JSON can never
    desynchronize from the payload the price assumed."""
    return (ctx.parameter_size / s.tp * ctx.layer_num
            * (0.5 if ctx.mixed_precision else 1.0))


def hier_dp_buckets(grad_mb: float, bucket_mb: float) -> int:
    """Bucket count of the pipelined hierarchical schedule for a
    ``grad_mb`` payload at ``bucket_mb`` granularity (1 = monolithic) —
    the cost model's degree-level mirror of the runtime's exact
    ``ops.hier_reduce.hier_bucket_layout`` (which works in padded
    elements; the search prices in MB)."""
    if bucket_mb <= 0 or grad_mb <= 0:
        return 1
    import math as _math

    return max(int(_math.ceil(grad_mb / bucket_mb)), 1)


def hier_dp_reduce_ms(s: "SearchStrategy", ctx: CostContext,
                      grad_mb: float) -> Optional[float]:
    """Hierarchical dp gradient-reduction time for ``grad_mb`` (the
    per-device grad volume): rs-intra at full volume + ar-cross on the
    1/intra shard + ag-intra back, each priced off the per-level algorithm
    curves (rs/ag at half the allreduce curve, the repo-wide convention).
    None when ineligible or any needed curve is missing — the caller then
    keeps the flat pricing, so legacy profiles stay byte-identical.

    ``ctx.hier_bucket_mb > 0`` prices the bucketed SOFTWARE-PIPELINED
    schedule (ops/hier_reduce.py wavefront emission): with B buckets of
    ``grad_mb / B`` each, the first bucket pays its full three-stage
    chain and every further bucket pays only the bottleneck stage —
    ``T = t_ici + t_dcn + (B-1) * max(t_ici, t_dcn)`` where ``t_ici`` is
    the per-bucket rs+ag (one ICI allreduce-curve hit) and ``t_dcn`` the
    per-bucket cross-slice allreduce on the 1/intra shard. Each stage
    re-pays its α per bucket, so the model prices the real trade: more
    buckets hide more of the slow link but spend more latency. B = 1
    reproduces the monolithic sum exactly.

    cp/Ulysses-bearing layers (sdp > dp) add the IN-LANE residual: the
    per-lane grads stay partial over the cp/sp group, which the
    partitioner reduces over the ICI-local ``sdp/dp``-sized group —
    priced as one allreduce-curve hit at full grad volume (the same
    once-per-step granularity the flat model uses)."""
    if not search_hier_dp_expressible(s, ctx.hier_dp):
        return None
    if ctx.hier_bucket_mb < 0:
        # auto mode (search.hier_bucket_mb < 0): the price IS the best
        # bucket size's price — the search picks the granularity, and
        # hier_dp_best_bucket reports which one for the plan record
        return hier_dp_best_bucket(s, ctx, grad_mb)[0]
    split = _hier_dp_split(s, ctx)
    if split is None:
        return None
    cross, intra = split
    B = hier_dp_buckets(grad_mb, ctx.hier_bucket_mb)
    msg = grad_mb / B
    t_ici = 0.0
    if intra > 1:
        rs = _algo_min_ms(ctx, intra, 1, "ici", msg)
        if rs is None:
            return None
        t_ici = rs  # 0.5 rs + 0.5 ag of the same curve
    t_dcn = 0.0
    if cross > 1:
        ar = _algo_min_ms(ctx, cross, 0, "dcn", msg / intra)
        if ar is None:
            ar = _algo_min_ms(ctx, cross, 1, "dcn", msg / intra)
        if ar is None:
            return None
        t_dcn = ar
    if intra == 1 and cross == 1:
        return None
    total = t_ici + t_dcn + (B - 1) * max(t_ici, t_dcn)
    csp = s.sdp // max(s.dp, 1)
    if csp > 1:
        resid = _algo_min_ms(ctx, csp, 1, "ici", grad_mb)
        if resid is None:
            return None
        total += resid
    return total


def dp_schedule_rankings(s: "SearchStrategy", ctx: CostContext,
                         grad_mb: float) -> Dict[str, float]:
    """α-β prices (ms) of every synthesizable dp-schedule family for this
    layer's dp group at ``grad_mb`` payload — the collective compiler's
    search hook. The families come from
    ``collectives.synthesize.synthesize_space`` (ring, halving-doubling,
    latency-optimal tree broadcast, 2D torus, hierarchical rings — what
    the shape admits), priced by ``collectives.pricing`` over per-LINK
    curves inverted out of the profiled per-algorithm ring fits
    (``ctx.alpha_beta_algos``); min-over-curves, so a family a missing
    curve cannot price is simply absent. Empty when the plan is not
    hierarchically expressible or the profile carries no algorithm
    curves — the caller then records no schedule and the legacy pricing
    is untouched (the golden-search pins rely on that)."""
    if not search_hier_dp_expressible(s, ctx.hier_dp):
        return {}
    split = _hier_dp_split(s, ctx)
    if split is None or s.dp < 2:
        return {}
    cross, intra = split
    from hetu_galvatron_tpu.collectives.pricing import (
        link_curves_from_algos,
        price_space,
    )
    from hetu_galvatron_tpu.collectives.synthesize import synthesize_space

    curves = link_curves_from_algos(
        ctx.alpha_beta_algos, intra if cross > 1 else s.dp, cross)
    if not curves:
        return {}
    return price_space(synthesize_space(s.dp, cross=cross), grad_mb,
                       curves)


def dp_schedule_choice(s: "SearchStrategy", ctx: CostContext,
                       grad_mb: float
                       ) -> Optional[Tuple[str, Dict[str, float]]]:
    """(winning family name, full rankings) for the plan record, or None
    when nothing priced. The winner is informational — it names the
    emitted program the runtime should execute (plan JSON
    ``dp_schedule``) — and deliberately does NOT perturb the plan's
    predicted time, so legacy profiles price byte-identically."""
    ranks = dp_schedule_rankings(s, ctx, grad_mb)
    if not ranks:
        return None
    return min(ranks, key=ranks.get), ranks


def _tp_terms(s: "SearchStrategy", ctx: CostContext, gbsz: int, chunks: int
              ) -> Tuple[float, float, float]:
    """Shared per-layer (fct, bct, tp_time) arithmetic — consumed by both
    :func:`layer_time_cost` (the price the search optimizes) and
    :func:`tp_overlap_hidden_frac` (the diagnostic), so the two can never
    drift apart.

    computation (layer_cost.py:88-103): cp shards the sequence, so the
    per-device compute divides by cp too (zigzag ring keeps the causal
    work balanced across the ring — ops/ring_attention.py).
    tp/sp collectives (layer_cost.py:119-150): the Megatron-TP path
    prices one message via the α-β fit when present (_tp_message_ms)."""
    lbsz = gbsz // chunks // s.dp
    n = ctx.layer_num
    fct_in = ctx.forward_computation_time
    if isinstance(fct_in, (np.ndarray, tuple, list)):
        fct = _linear(lbsz / s.tp_sp / s.cp, fct_in) * n
    else:
        fct = fct_in * lbsz / s.tp_sp / s.cp * n
    bct = fct * ctx.bct_fct_coe
    if s.checkpoint:
        bct += fct

    if s.tp_sp == 1:
        tp_time = 0.0
    else:
        message_mb = (lbsz * ctx.seq_length * ctx.hidden_size *
                      (2 if ctx.mixed_precision else 4) / 1024 / 1024)
        if s.tp == 1:  # Ulysses: 2 a2a fwd + 2 bwd per layer
            comm_num = 4 * n
            per_msg = _lookup_latency(ctx.all2all_latency[s.sp], message_mb)
        else:  # Megatron TP+SP: 3 ag-equivalents fwd + 3 bwd per layer
            comm_num = 6 * n
            per_msg = _tp_message_ms(s, ctx, message_mb)
        if s.checkpoint:
            comm_num *= 1.5
        tp_time = per_msg * comm_num
    return fct, bct, tp_time


def layer_time_cost(
    s: "SearchStrategy", ctx: CostContext, gbsz: int, chunks: int
) -> Tuple[float, float]:
    """Per-layer time in seconds: (with grad sync, without). Mirrors
    TimeCostModelBase end-to-end (layer_cost.py:88-213)."""
    lbsz = gbsz // chunks // s.dp
    param_mb = ctx.parameter_size / s.tp
    n = ctx.layer_num

    fct, bct, tp_time = _tp_terms(s, ctx, gbsz, chunks)

    # dp gradient sync (layer_cost.py:105-116)
    dp_message = 2 * (s.sdp - 1) * (param_mb / s.sdp) * n
    if ctx.mixed_precision:
        dp_message /= 2
    fsdp_allgather = dp_message * 0.5
    dc_key = f"{s.sdp}_0" if s.tp != 1 else f"{s.sdp}_1"
    dc = ctx.comm_coe_dict[dc_key]
    dc_overlap = dc * ctx.dp_overlap_coe

    # cp ring-attention communication (beyond the reference, which ships
    # cp disabled — search_engine/args_schema.py:29): each ring step
    # exchanges this rank's K and V blocks with a neighbour; the backward
    # rings K/V again plus the dK/dV accumulators (ops/ring_attention.py).
    cp_time = 0.0
    if s.cp > 1:
        block_mb = (lbsz * ctx.seq_length * ctx.hidden_size / s.cp *
                    (2 if ctx.mixed_precision else 4) / 1024 / 1024)
        hops = 2 * (s.cp - 1)          # K + V per ring pass
        ring_mb = block_mb * hops * 3  # fwd + bwd(K/V + dK/dV)
        cp_key = f"{s.cp}_0" if s.tp != 1 else f"{s.cp}_1"
        cp_coe = ctx.comm_coe_dict.get(
            cp_key, ctx.comm_coe_dict.get(f"{s.cp}"))
        cp_time = ring_mb * cp_coe * n

    # pp p2p (layer_cost.py:152-159)
    p2p_coe = None
    p2p_message = 0.0
    if s.pp > 1 and ctx.p2p_comm_coe_dict is not None:
        p2p_coe = ctx.p2p_comm_coe_dict[s.pp]
        p2p_message = (s.pp * 2 * lbsz * ctx.seq_length * ctx.hidden_size *
                       4 / 1024 / 1024)
        if ctx.mixed_precision:
            p2p_message /= 2

    def overlap(dp_msg: float) -> Tuple[float, float]:
        """Backward-compute/dp-comm overlap split (layer_cost.py:161-178)."""
        dp_t = dp_msg * dc_overlap
        bct_t = bct * ctx.bct_overlap_coe
        if dp_t > bct_t:
            return bct_t, (dp_msg - bct_t / dc_overlap) * dc
        if dp_t < bct_t:
            return dp_t, bct - dp_t / ctx.bct_overlap_coe
        return bct_t, 0.0

    # overlapped-TP discount: the decomposed ring matmuls hide the TP
    # collectives under the dependent chunk compute. dp=1 layers overlap
    # against the full fwd+bwd matmul window; layers that also overlap dp
    # comm against the backward keep only the forward window free.
    overlap_tp = tp_overlap_expressible(s, ctx) and tp_time > 0

    # hierarchical dp alternative (hier_dp_reduce_ms): the full per-device
    # grad volume reduced ONCE at step end (un-overlapped — the runtime's
    # lane accumulation defers the reduction out of the backward), priced
    # per level off the algorithm curves; None keeps flat-only pricing
    hier_ms = hier_dp_reduce_ms(s, ctx, hier_grad_payload_mb(s, ctx))

    def tp_term(window: float) -> float:
        """Exposed TP comm time beyond the compute window it hides under."""
        if not overlap_tp:
            return tp_time
        return _overlap_window(tp_time, window, ctx.bct_overlap_coe) - window

    def result(no_sync: bool) -> float:
        factor = 0 if no_sync else 1
        if s.tp_sp == 1 and s.dp > 1:
            ov, rest = overlap(dp_message * factor)
            r = fct + ov + rest + ctx.extra_overhead
        elif s.dp == 1 and s.tp_sp > 1:
            r = fct + bct + tp_term(fct + bct)
        elif s.dp == 1 and s.tp_sp == 1:
            r = fct + bct
        else:
            ov, rest = overlap(dp_message * factor)
            r = fct + ov + rest + tp_term(fct) + ctx.extra_overhead
        if factor and hier_ms is not None:
            # hierarchical dp candidate: backward runs un-overlapped (the
            # reduction happens once after accumulation), dp comm is the
            # three-level schedule; the layer takes whichever is cheaper
            if s.tp_sp == 1 and s.dp > 1:
                r_h = fct + bct + hier_ms + ctx.extra_overhead
            elif s.dp > 1 and s.tp_sp > 1:
                r_h = (fct + bct + tp_term(fct) + hier_ms
                       + ctx.extra_overhead)
            else:
                r_h = None
            if r_h is not None:
                r = min(r, r_h)
        if s.dp_type == DPType.ZERO3:
            r += fsdp_allgather * dc
        if s.pp > 1 and p2p_coe is not None:
            r += p2p_message * p2p_coe
        r += cp_time
        return r * 0.001 * ctx.costmodel_coe / n

    return result(False), result(True)


# candidate bucket sizes for auto mode (hier_bucket_mb < 0): monolithic
# plus power-of-two granularities covering the sub-MB-α to tens-of-MB-β
# regimes the fitted curves span
_BUCKET_SWEEP_MB: Tuple[float, ...] = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0,
                                       32.0, 64.0)


def hier_dp_best_bucket(s: "SearchStrategy", ctx: CostContext,
                        grad_mb: float
                        ) -> Tuple[Optional[float], float]:
    """(best hierarchical ms, chosen bucket_mb) over the candidate bucket
    sweep — the "search picks the bucket size" entry: the engine records
    the winning granularity in the plan JSON (``"hier_bucket_mb"``) so the
    runtime pipelines at exactly the size the price assumed. (None, 0.0)
    when the hierarchical term is unavailable at every size."""
    from dataclasses import replace as _replace

    best: Tuple[Optional[float], float] = (None, 0.0)
    for cand in _BUCKET_SWEEP_MB:
        ms = hier_dp_reduce_ms(
            s, _replace(ctx, hier_bucket_mb=cand), grad_mb)
        if ms is not None and (best[0] is None or ms < best[0]):
            best = (ms, cand)
    return best


def hier_dp_wins(s: "SearchStrategy", ctx: CostContext, gbsz: int,
                 chunks: int) -> bool:
    """Did the hierarchical dp term price this layer's chosen cost (i.e.
    enabling ``ctx.hier_dp`` strictly lowered the with-sync layer cost)?
    The search engine records ``"hier_dp": 1`` in the winning plan when
    every layer says yes, so the runtime enables the matching execution
    path."""
    if not search_hier_dp_expressible(s, ctx.hier_dp):
        return False
    from dataclasses import replace as _replace

    off = _replace(ctx, hier_dp=False)
    return (layer_time_cost(s, ctx, gbsz, chunks)[0]
            < layer_time_cost(s, off, gbsz, chunks)[0])


def tp_overlap_hidden_frac(s: "SearchStrategy", ctx: CostContext,
                           gbsz: int, chunks: int) -> float:
    """Predicted fraction of one layer's TP collective time hidden under
    the decomposed matmuls' compute, from the same arithmetic the search
    prices (``layer_time_cost``'s tp_term): 0.0 for inexpressible layers,
    approaching ``2 - overlap_coe`` in the compute-bound regime. This is
    the cost-side per-layer prediction (it needs the profiled hardware
    tables, so it lives with the search); the runtime's
    ``tp/comm_hidden_frac`` gauge instead reports profile-free COVERAGE
    (observability.telemetry.plan_tp_overlap_hidden_frac)."""
    if not tp_overlap_expressible(s, ctx):
        return 0.0
    fct, bct, tp_time = _tp_terms(s, ctx, gbsz, chunks)
    if tp_time <= 0:
        return 0.0
    window = (fct + bct) if s.dp == 1 else fct
    exposed = _overlap_window(tp_time, window, ctx.bct_overlap_coe) - window
    return max(0.0, min(1.0, 1.0 - exposed / tp_time))


def layer_time_components(s: "SearchStrategy", ctx: CostContext,
                          gbsz: int, chunks: int) -> Dict[str, float]:
    """Decomposed per-layer predicted times in ms: the same arithmetic
    :func:`layer_time_cost` folds into one scalar, kept separated so the
    plan audit (``observability/trace_analysis.py``) can compare each
    component against the measured device-time attribution. Components are
    the UN-overlapped magnitudes — the audit's measured side (per-HLO-op
    category time) also counts collectives at face value, so the two sides
    are comparable; the overlap splits are a property of the folded total,
    not of the per-component prediction."""
    n = ctx.layer_num
    lbsz = gbsz // chunks // s.dp
    fct, bct, tp_time = _tp_terms(s, ctx, gbsz, chunks)

    param_mb = ctx.parameter_size / s.tp
    dp_message = 2 * (s.sdp - 1) * (param_mb / s.sdp) * n
    if ctx.mixed_precision:
        dp_message /= 2
    dc_key = f"{s.sdp}_0" if s.tp != 1 else f"{s.sdp}_1"
    # the folded model only charges the gradient ring when dp > 1 (both
    # result() overlap branches gate on s.dp); a dp==1 plan whose sdp > 1
    # via cp/ulysses replicas pays only the ZeRO-3 all-gather premium —
    # charging dp_message here would invent a component the search never
    # priced, and total_ms must reconcile with layer_time_cost
    dp_time = dp_message * ctx.comm_coe_dict[dc_key] if s.dp > 1 else 0.0
    if s.dp > 1 and hier_dp_wins(s, ctx, gbsz, chunks):
        # the chosen price was the hierarchical schedule: the audit must
        # compare measured dp time against THAT decomposition
        dp_time = hier_dp_reduce_ms(s, ctx, hier_grad_payload_mb(s, ctx))
    if s.dp_type == DPType.ZERO3 and s.sdp > 1:
        dp_time += dp_message * 0.5 * ctx.comm_coe_dict[dc_key]

    cp_time = 0.0
    if s.cp > 1:
        block_mb = (lbsz * ctx.seq_length * ctx.hidden_size / s.cp *
                    (2 if ctx.mixed_precision else 4) / 1024 / 1024)
        cp_key = f"{s.cp}_0" if s.tp != 1 else f"{s.cp}_1"
        cp_coe = ctx.comm_coe_dict.get(
            cp_key, ctx.comm_coe_dict.get(f"{s.cp}"))
        cp_time = block_mb * 2 * (s.cp - 1) * 3 * cp_coe * n

    pp_time = 0.0
    if s.pp > 1 and ctx.p2p_comm_coe_dict is not None:
        p2p_message = (s.pp * 2 * lbsz * ctx.seq_length * ctx.hidden_size *
                       4 / 1024 / 1024)
        if ctx.mixed_precision:
            p2p_message /= 2
        pp_time = p2p_message * ctx.p2p_comm_coe_dict[s.pp]

    scale = ctx.costmodel_coe / n
    out = {"fct_ms": fct * scale, "bct_ms": bct * scale,
           "tp_ms": tp_time * scale, "dp_ms": dp_time * scale,
           "cp_ms": cp_time * scale, "pp_ms": pp_time * scale}
    out["total_ms"] = sum(out.values())
    return out


# ---------------------------------------------------------------------------
# decoder-layer memory
# ---------------------------------------------------------------------------


def layer_memory_components(
    s: "SearchStrategy",
    ctx: CostContext,
    gbsz: int,
    chunks: int,
    stage_idx: int = 0,
    pipeline_type: Optional[str] = None,
) -> Dict[str, float]:
    """Per-layer memory in MB, decomposed into the model-states and
    activation terms (MemoryCostModelBase, layer_cost.py:261-328). The
    memory doctor (``analysis/memory_doctor.py``) cross-checks its own
    first-principles accounting against each component separately, so the
    split is part of the contract; :func:`layer_memory_cost` folds the
    same dict into the scalar the search optimizes — one arithmetic, two
    views (the ``layer_time_components`` pattern)."""
    pipeline_type = pipeline_type or ctx.pipeline_type
    lbsz = gbsz // chunks // s.dp
    if s.pp == 1:
        cumulative = 1
    else:
        if chunks < s.pp:
            raise ValueError(f"chunks {chunks} < pp {s.pp}")
        cumulative = (s.pp - stage_idx if pipeline_type == "pipedream_flush"
                      else chunks)
    cum_lbsz = cumulative * lbsz

    z2, z3 = _zero_ratios(chunks, ctx.mixed_precision, ctx.async_grad_reduce)
    param_mem = ctx.parameter_size / s.tp
    model_states = 4 * param_mem
    if s.dp_type == DPType.ZERO3:
        model_states *= z3(s.sdp)
    elif s.dp_type == DPType.ZERO2:
        model_states *= z2(s.sdp)

    act = ctx.tp_activation_per_bsz_dict
    if s.checkpoint:
        activation = act["checkpoint"] * cum_lbsz
        if s.sp > 1 or (s.tp > 1 and ctx.sequence_parallel):
            activation /= s.tp_sp
    else:
        activation = act[s.tp_sp] * cum_lbsz
    # cp shards the sequence (ring attention): activations divide by cp;
    # model states do not (weights replicate over cp, but ZeRO already
    # shards states over sdp = dp*sp*cp above)
    activation /= s.cp
    return {"model_states_mb": model_states, "activation_mb": activation,
            "total_mb": model_states + activation}


def layer_memory_cost(
    s: "SearchStrategy",
    ctx: CostContext,
    gbsz: int,
    chunks: int,
    stage_idx: int = 0,
    pipeline_type: Optional[str] = None,
) -> float:
    """Per-layer memory in MB: model states + activations
    (MemoryCostModelBase, layer_cost.py:261-328)."""
    return layer_memory_components(
        s, ctx, gbsz, chunks, stage_idx, pipeline_type)["total_mb"]


# ---------------------------------------------------------------------------
# embedding / LM-head time
# ---------------------------------------------------------------------------


def embed_time_cost(
    s: "SearchStrategy",
    ctx: CostContext,
    gbsz: int,
    chunks: int,
    seq_len_list: Sequence[int],
) -> Tuple[List[float], List[float]]:
    """Per-pipeline-stage vocab-layer times in seconds (with, without grad
    sync); only first/last stages are nonzero (EmbeddingLMHeadTimeCostModel,
    embedding_lmhead_cost.py:59-184)."""
    lbsz = gbsz // chunks // s.dp
    pp = s.pp

    fct = [0.0] * pp
    ot = ctx.other_time_profiled
    if isinstance(ot, (np.ndarray, tuple, list)):
        fct_time = _linear(lbsz / s.tp_sp / s.cp, ot)
    else:
        fct_time = ot * lbsz / s.tp_sp / s.cp
    if pp == 1:
        fct[0] = fct_time
    else:
        fct[0] = fct_time / 2
        fct[-1] = fct_time / 2

    key = f"{s.sdp}_0" if s.tp != 1 else f"{s.sdp}_1"
    dp_coe = ctx.comm_coe_dict[key] * (s.sdp - 1) / s.sdp
    factor = 0.5 if ctx.mixed_precision else 1.0
    dp_message = [0.0] * pp
    if pp == 1:
        dp_message[0] = ctx.other_memory_pp_off["model_states"][s.tp] / 4 * factor
    else:
        dp_message[0] = (ctx.other_memory_pp_on["first_stage"]["model_states"]
                         [s.tp] / 4 * factor)
        dp_message[-1] = (ctx.other_memory_pp_on["last_stage"]["model_states"]
                          [s.tp] / 4 * factor)
    if s.dp_type == DPType.ZERO3:
        fwd_factor, bwd_factor = 0.5, 1.0
    else:
        fwd_factor, bwd_factor = 0.0, 0.5

    tp_sp_time = [0.0] * pp
    per_seq = []
    for seq in seq_len_list:
        if s.tp_sp == 1 or s.tp == 1:
            per_seq.append(0.0)
        else:
            message_mb = (lbsz * seq * ctx.hidden_size *
                          (2 if ctx.mixed_precision else 4) / 1024 / 1024)
            if not ctx.sequence_parallel:
                raise ValueError("sequence_parallel required when tp > 1")
            per_seq.append(
                _lookup_latency(ctx.allgather_latency[s.tp], message_mb))
    if pp == 1:
        tp_sp_time[0] = per_seq[0] + per_seq[-1]
    else:
        tp_sp_time[0] = per_seq[0]
        tp_sp_time[-1] = per_seq[-1]

    def overlap_time(f_comm, f_comp, b_comm, b_comp, tp_t):
        """Compute/comm overlap (embedding_lmhead_cost.py:155-166)."""
        f_comp = f_comp * ctx.dp_overlap_coe
        b_comp = b_comp * ctx.dp_overlap_coe
        fwd = (f_comm + (f_comp - f_comm) / ctx.dp_overlap_coe
               if f_comp > f_comm else f_comm)
        bwd = (b_comm + (b_comp - b_comm) / ctx.dp_overlap_coe
               if b_comp > b_comm else b_comm)
        return fwd + bwd + tp_t

    ms = 0.001
    cost = [0.0] * pp
    cost_no_sync = [0.0] * pp
    for idx in ([0] if pp == 1 else [0, pp - 1]):
        cost[idx] = ms * overlap_time(
            dp_message[idx] * dp_coe * fwd_factor, fct[idx],
            dp_message[idx] * dp_coe * bwd_factor,
            fct[idx] * ctx.bct_fct_coe, tp_sp_time[idx])
        cost_no_sync[idx] = ms * overlap_time(
            dp_message[idx] * dp_coe * fwd_factor, fct[idx],
            dp_message[idx] * dp_coe * (bwd_factor - 0.5),
            fct[idx] * ctx.bct_fct_coe, tp_sp_time[idx])
    return cost, cost_no_sync


# ---------------------------------------------------------------------------
# embedding / LM-head memory
# ---------------------------------------------------------------------------


def embed_memory_components(
    s: "SearchStrategy",
    ctx: CostContext,
    gbsz: int,
    chunks: int,
    pipeline_type: Optional[str] = None,
) -> Dict[str, List[float]]:
    """Per-stage vocab-layer memory in MB, decomposed
    (EmbeddingLMHeadMemoryCostModel, embedding_lmhead_cost.py:187-313) —
    the cross-checkable view of :func:`embed_memory_cost`, which sums the
    same three per-stage vectors (model states, activation, the flat
    allocator-context reserve)."""
    pipeline_type = pipeline_type or ctx.pipeline_type
    lbsz = gbsz // chunks // s.dp
    pp = s.pp
    z2, z3 = _zero_ratios(chunks, ctx.mixed_precision, ctx.async_grad_reduce)
    if s.dp_type == DPType.ZERO3:
        scale = z3(s.sdp)
    elif s.dp_type == DPType.ZERO2:
        scale = z2(s.sdp)
    else:
        scale = 1.0

    model_states = [0.0] * pp
    if pp == 1:
        model_states[0] = ctx.other_memory_pp_off["model_states"][s.tp] * scale
    else:
        model_states[0] = (ctx.other_memory_pp_on["first_stage"]
                           ["model_states"][s.tp] * scale)
        model_states[-1] = (ctx.other_memory_pp_on["last_stage"]
                            ["model_states"][s.tp] * scale)

    activation = [0.0] * pp
    if pp == 1:
        activation[0] = (ctx.other_memory_pp_off["activation"][s.tp_sp] * lbsz
                         / s.cp)
    else:
        if chunks < pp:
            raise ValueError(f"chunks {chunks} < pp {pp}")
        if pipeline_type == "pipedream_flush":
            cum_first, cum_last = pp, 1
        else:
            cum_first, cum_last = chunks, chunks
        activation[0] = (ctx.other_memory_pp_on["first_stage"]["activation"]
                         [s.tp_sp] * cum_first * lbsz / s.cp)
        activation[-1] = (ctx.other_memory_pp_on["last_stage"]["activation"]
                          [s.tp_sp] * cum_last * lbsz / s.cp)

    return {"model_states_mb": model_states, "activation_mb": activation,
            "context_mb": [ctx.pytorch_context_mem] * pp}


def embed_memory_cost(
    s: "SearchStrategy",
    ctx: CostContext,
    gbsz: int,
    chunks: int,
    pipeline_type: Optional[str] = None,
) -> List[float]:
    """Per-stage vocab-layer memory in MB (EmbeddingLMHeadMemoryCostModel,
    embedding_lmhead_cost.py:187-313)."""
    comp = embed_memory_components(s, ctx, gbsz, chunks, pipeline_type)
    return [m + a + c for m, a, c in zip(
        comp["model_states_mb"], comp["activation_mb"], comp["context_mb"])]


# ---------------------------------------------------------------------------
# model FLOPs accounting (telemetry: MFU denominator numerator)
# ---------------------------------------------------------------------------


def model_flops_per_token(model: Any, seq_length: Optional[int] = None
                          ) -> float:
    """Matmul FLOPs per token for one training step (forward + backward,
    backward counted as 2x forward). ``model`` is a
    ``core.args_schema.ModelArgs``-shaped object (duck-typed so this module
    stays import-light).

    Conventions (the standard MFU accounting, PaLM appendix B style):
    the [S, S] attention score/value matmuls are counted dense — no causal
    discount — and non-matmul work (norms, softmax, embedding lookup) is
    ignored. MoE layers count only the ACTIVE experts (top-k + shared);
    with ``moe_layer_freq = k`` every k-th layer is MoE and the rest are
    dense (models/builder.py layer alternation).
    """
    h = model.hidden_size
    s = seq_length or model.seq_length
    nd = model.num_attention_heads * model.head_dim
    kd = model.kv_heads * model.head_dim
    # q/k/v/out projections + the two [S, S] batched matmuls (QK^T, PV)
    attn = 2 * h * nd + 2 * 2 * h * kd + 2 * nd * h + 2 * 2 * s * nd
    gated = model.hidden_act in ("swiglu", "geglu")

    def mlp_flops(ffn: int) -> float:
        return (3 if gated else 2) * 2 * h * ffn

    dense_layer = attn + mlp_flops(model.ffn_dim)
    layers = model.num_hidden_layers + (model.num_encoder_layers or 0
                                        if model.model_type == "t5" else 0)
    if model.num_experts:
        moe_ffn = model.moe_ffn_hidden_size or model.ffn_dim
        active = model.moe_topk + model.num_shared_experts
        moe_layer = (attn + 2 * h * model.num_experts  # router
                     + active * mlp_flops(moe_ffn))
        freq = max(model.moe_layer_freq, 1)
        n_moe = layers // freq
        fwd = n_moe * moe_layer + (layers - n_moe) * dense_layer
    else:
        fwd = layers * dense_layer
    fwd += 2 * h * model.padded_vocab_size  # LM head
    return 3.0 * fwd


# ---------------------------------------------------------------------------
# pipeline schedule cost
# ---------------------------------------------------------------------------


def pipeline_time_cost(
    layer_num_list: Sequence[int],
    contexts: Sequence[CostContext],
    strategy_list: Sequence["SearchStrategy"],
    partition: Sequence[int],
    chunks: int,
    gbsz: int,
    pp_size: int,
    other_time_cost: Sequence[float],
) -> float:
    """End-to-end pipeline time for a concrete per-layer plan (reference
    pipeline_costmodel, cost_model_handler.py:16-99): per-stage sums of
    per-layer costs, a warmup/cooldown bubble estimate, and the straggling
    gradient-reduce tail."""
    total = sum(layer_num_list)
    assert len(strategy_list) == total
    layertype_of = []
    for t, n in enumerate(layer_num_list):
        layertype_of.extend([t] * n)

    uniq = list(set(strategy_list))
    sync_cost: Dict[Tuple[int, "SearchStrategy"], float] = {}
    nosync_cost: Dict[Tuple[int, "SearchStrategy"], float] = {}
    for t in range(len(layer_num_list)):
        for s in uniq:
            w, wo = layer_time_cost(s, contexts[t], gbsz, chunks)
            sync_cost[(t, s)] = w
            nosync_cost[(t, s)] = wo

    per_layer_sync = [sync_cost[(layertype_of[i], strategy_list[i])]
                      for i in range(total)]
    per_layer_nosync = [nosync_cost[(layertype_of[i], strategy_list[i])]
                        for i in range(total)]

    def stage_sums(vals):
        out, start = [], 0
        for n in partition:
            out.append(float(np.sum(vals[start:start + n])))
            start += n
        return out

    stage_sync = stage_sums(per_layer_sync)
    stage_compute = stage_sums(per_layer_nosync)
    assert len(other_time_cost) == len(stage_compute)
    stage_compute = [c + o for c, o in zip(stage_compute, other_time_cost)]

    result = float(np.sum(stage_compute)) + stage_compute[-1] * (chunks - 1)
    # warmup/cooldown bubbles partially overlap (handler.py:82-85)
    result = max(
        result,
        max(min(pp_size - 1, chunks - 1) * stage_compute[0] * 1 / 3,
            float(np.sum(stage_compute[1:])) * 1 / 3)
        + max(min(pp_size - 1, chunks - 1) * stage_compute[0] * 2 / 3,
              float(np.sum(stage_compute[1:])) * 2 / 3)
        + stage_compute[0] * max(0, chunks + 1 - pp_size))

    stage_reduce = list(stage_sync)
    for i in range(pp_size):
        stage_reduce[i] -= float(np.sum(stage_compute[:i + 1]))
    reduce_tail = max(stage_reduce)
    result += reduce_tail if reduce_tail > 0 else 0.0

    # host-sequenced dispatch overhead (tools/pipeline_dispatch_bench.py):
    # every (stage, microbatch) leg costs one fwd + one bwd jitted-call
    # dispatch on the host, which the single-program compiled schedule
    # eliminates. This is what lets the search's pp choice price the two
    # pipeline.schedule_impl flavours differently: deep pp under the host
    # impl pays dispatch linearly in pp * chunks. The waiver only applies
    # to plans the compiled engine can EXPRESS (it falls back to the host
    # engine otherwise — CompiledPipelineEngine.unsupported_reason): 1F1B
    # only, uniform stage partition, uniform per-layer strategy. cp plans
    # qualify since the engine de-vmapped its stage axis (the ring kernel
    # runs inside the fused program), so on an overlap-expressible tp plan
    # the dispatch waiver and the tp_overlap discount now COMPOSE — the
    # product neither effect produces alone (tests/search_engine/
    # test_dispatch_cost.py pins a plan flip that needs both).
    ctx0 = contexts[0]
    if pp_size > 1 and ctx0.dispatch_us:
        if not search_compiled_expressible(
                ctx0.schedule_impl, ctx0.pipeline_type, partition,
                strategy_list):
            result += ctx0.dispatch_us * 1e-6 * 2 * pp_size * chunks
    return result


# ---------------------------------------------------------------------------
# stored-plan re-pricing (calibration / plan-regret sentinel)
# ---------------------------------------------------------------------------


def reprice_stored_plan_ms(
    plan: Dict[str, Any],
    *,
    seq_len: int,
    hidden_size: int,
    param_mb: float,
    mixed_precision: bool = True,
    alpha_beta: Optional[Dict[str, Tuple[float, float]]] = None,
    alpha_beta_algos: Optional[
        Dict[str, Dict[str, Tuple[float, float]]]] = None,
) -> Optional[float]:
    """Per-device per-step collective ms of a stored strategy spec under a
    given α-β curve set — the pricing half of the plan-regret sentinel
    (``observability.calibration``).

    ``plan`` is the shape ``SearchEngine.save_results`` embeds per
    runner-up: ``{"layers": [{"tp", "dp", "cp", "sp", "ckpt",
    "consec"}, ...], "pp", "bsz", "chunks"}``. The arithmetic mirrors
    ``trace_analysis.predicted_comm_per_step``'s flat tp/dp pricing (same
    message sizes, counts and per-pp scaling), so re-pricing a plan under
    the curves the calibrator fit from audit residuals compares
    like-for-like with the audit's own predictions. Returns None when no
    curve prices any component (then the caller must not fabricate a
    regret from a half-priced plan)."""
    mb_unit = 1024 * 1024
    ab = alpha_beta or {}
    ab_algos = alpha_beta_algos or {}
    pp = max(int(plan.get("pp", 1) or 1), 1)
    chunks = max(int(plan.get("chunks", 1) or 1), 1)
    bsz = max(int(plan.get("bsz", 1) or 1), 1)
    elem = 2 if mixed_precision else 4
    total = 0.0
    priced = False
    for layer in plan.get("layers") or []:
        if not isinstance(layer, dict):
            continue
        tp_full = max(int(layer.get("tp", 1) or 1), 1)
        sp = bool(layer.get("sp", 0))
        tp = 1 if sp else tp_full
        dp = max(int(layer.get("dp", 1) or 1), 1)
        cp = max(int(layer.get("cp", 1) or 1), 1)
        ckpt = bool(layer.get("ckpt", 0))
        if tp > 1:
            lbsz = max(bsz // chunks // dp, 1)
            act_mb = lbsz * seq_len * hidden_size * elem / mb_unit
            n_msgs = 6 * chunks * (1.5 if ckpt else 1.0)
            scale = n_msgs * 0.5 / pp
            cands = []
            pair = ab.get(f"{tp}_1")
            if pair:
                cands.append((pair[0] + act_mb / pair[1]) * scale)
            for alg_lvl, (alpha, beta) in (
                    ab_algos.get(f"{tp}_1") or {}).items():
                if alg_lvl.endswith("_ici") and beta:
                    cands.append((alpha + act_mb / beta) * scale)
            if cands:
                total += min(cands)
                priced = True
        sdp = max(dp * cp * (tp_full if sp else 1), 1)
        if sdp > 1:
            consec = 1 if tp == 1 else 0
            pair = (ab.get(f"{sdp}_{consec}") or ab.get(f"{sdp}_1")
                    or ab.get(f"{sdp}_0"))
            if pair:
                grad_mb = param_mb / max(tp, 1) * \
                    (0.5 if mixed_precision else 1.0)
                total += (pair[0] + grad_mb / pair[1]) / pp
                priced = True
    return total if priced else None
