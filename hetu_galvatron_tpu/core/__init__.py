from hetu_galvatron_tpu.core.args_schema import (  # noqa: F401
    CoreArgs,
    ModelArgs,
    ParallelArgs,
    TrainArgs,
    CheckpointArgs,
    ProfileArgs,
    SearchArgs,
    HardwareProfileArgs,
    ModelProfileArgs,
)
from hetu_galvatron_tpu.core.arguments import load_config  # noqa: F401
