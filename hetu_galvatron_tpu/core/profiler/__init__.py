from hetu_galvatron_tpu.core.profiler.hardware_profiler import (  # noqa: F401
    HardwareProfiler,
)
from hetu_galvatron_tpu.core.profiler.model_profiler import (  # noqa: F401
    ModelProfiler,
)
from hetu_galvatron_tpu.core.profiler.runtime_profiler import (  # noqa: F401
    RuntimeProfiler,
    compiled_memory_mb,
    device_memory_mb,
)
