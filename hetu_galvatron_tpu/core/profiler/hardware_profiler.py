"""Hardware profiler: ICI/DCN collective microbenchmarks.

Capability parity with the reference hardware profiling stack
(core/profiler/hardware_profiler.py:39-229 script generation +
profile_hardware/profile_allreduce.py:84-162, profile_p2p.py:19,
profile_all2all.py, profile_overlap.py:10-60): measures
- all-reduce bandwidth (MB/ms) per group size, consecutive and strided
- p2p (ppermute ring) bandwidth per pipeline degree
- all-reduce / all-to-all latency vs message size (the sp_time tables)
- the compute/comm overlap slowdown coefficient
and writes the same JSON schemas the search engine reads
(hardware_configs/*.json).

TPU-native: instead of spawning torchrun scripts per benchmark, collectives
run as jitted `shard_map` programs over sub-meshes of the current platform's
devices — the same code path measures ICI on a TPU slice and host rings on
the virtual CPU mesh (tests).
"""

from __future__ import annotations

import os
import time
from functools import partial
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.core.args_schema import HardwareProfileArgs
from hetu_galvatron_tpu.core.search_engine.profiles import write_json


def _time_fn(fn, arg, *, warmup: int, iters: int, inner: int = 1) -> float:
    """Median wall-clock ms of fn(arg) (reference uses trimmed means over 20
    x10-iter samples, profile_allreduce.py:14-17,129-133)."""
    for _ in range(warmup):
        out = fn(arg)
    jax.block_until_ready(out)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn(arg)
        jax.block_until_ready(out)
        samples.append((time.perf_counter() - t0) / inner * 1000.0)
    return float(np.median(samples))


def _group_devices(devices: Sequence, size: int, consecutive: bool,
                   world: int) -> List:
    """First group of `size` devices: adjacent chips (ICI neighbours) or
    strided across the world (the reference's consec 1/0 groupings,
    comm_groups.py:96-100)."""
    if consecutive:
        return list(devices[:size])
    stride = world // size
    return [devices[i * stride] for i in range(size)]


class HardwareProfiler:
    def __init__(self, args: HardwareProfileArgs,
                 devices: Optional[Sequence] = None):
        self.args = args
        self.devices = list(devices if devices is not None else jax.devices())
        self.world = min(len(self.devices),
                         args.num_nodes * args.num_devices_per_node)

    # -- collective runners -------------------------------------------------

    def _collective_ms(self, op: str, group: List, message_mb: float) -> float:
        """Time one collective over `group` with a message of `message_mb`
        MB per device (fp32)."""
        n = len(group)
        mesh = Mesh(np.array(group), ("g",))
        elems = max(int(message_mb * 1024 * 1024 // 4), n)
        elems = (elems // n) * n
        x = jax.device_put(
            jnp.ones((elems,), jnp.float32),
            NamedSharding(mesh, P(None)))

        # NOTE: this jax pin has no top-level jax.shard_map; the
        # experimental entry point (check_rep kwarg) is the one that works
        from jax.experimental.shard_map import shard_map

        if op == "allreduce":
            fn = shard_map(lambda v: jax.lax.psum(v, "g"), mesh=mesh,
                           in_specs=P(None), out_specs=P(None),
                           check_rep=False)
        elif op == "allgather":
            x = jax.device_put(jnp.ones((elems,), jnp.float32),
                               NamedSharding(mesh, P("g")))
            fn = shard_map(lambda v: jax.lax.all_gather(v, "g", tiled=True),
                           mesh=mesh, in_specs=P("g"), out_specs=P(None),
                           check_rep=False)
        elif op == "all2all":
            x = jax.device_put(jnp.ones((n, elems // n), jnp.float32),
                               NamedSharding(mesh, P("g", None)))
            fn = shard_map(
                lambda v: jax.lax.all_to_all(v, "g", split_axis=1,
                                             concat_axis=0, tiled=True),
                mesh=mesh, in_specs=P("g", None), out_specs=P(None, "g"),
                check_rep=False)
        elif op == "p2p":
            perm = [(i, (i + 1) % n) for i in range(n)]
            fn = shard_map(lambda v: jax.lax.ppermute(v, "g", perm),
                           mesh=mesh, in_specs=P(None), out_specs=P(None),
                           check_rep=False)
        else:
            raise ValueError(op)
        jfn = jax.jit(fn)
        return _time_fn(jfn, x, warmup=self.args.warmup_iters,
                        iters=self.args.profile_iters)

    # -- benchmark suites ---------------------------------------------------

    def profile_allreduce_bandwidth(self, message_mb: int = 64
                                    ) -> Dict[str, float]:
        """allreduce_bandwidth_*.json: MB/ms per (group size, consec) with
        the 2x(n-1)/n algorithmic volume (profile_allreduce.py:84-162)."""
        out: Dict[str, float] = {}
        size = self.world
        while size >= 2:
            for consec in ([1] if size == self.world else [1, 0]):
                group = _group_devices(self.devices, size, bool(consec),
                                       self.world)
                ms = self._collective_ms("allreduce", group, message_mb)
                volume = 2 * (size - 1) / size * message_mb
                out[f"allreduce_size_{size}_consec_{consec}"] = round(
                    volume / ms, 3)
            size //= 2
        return out

    def profile_p2p_bandwidth(self, message_mb: int = 64) -> Dict[str, float]:
        """p2p_bandwidth_*.json: MB/ms per pipeline degree
        (profile_p2p.py:19)."""
        out: Dict[str, float] = {}
        pp = 2
        while pp <= min(self.world, self.args.max_pp_deg):
            group = _group_devices(self.devices, pp, True, self.world)
            ms = self._collective_ms("p2p", group, message_mb)
            out[f"pp_size_{pp}"] = round(message_mb / ms, 3)
            pp *= 2
        return out

    def _sub_mb_sizes(self) -> List[float]:
        """Sub-MB message sizes (MB) for the α (latency) fit: halvings of
        start_mb down to sub_mb_floor_kb. Layer-wise TP puts per-collective
        messages well under a megabyte, where the latency term dominates
        ("Revisiting the Time Cost Model of AllReduce", PAPERS.md) — the
        integer-MB sweep alone cannot see it."""
        out: List[float] = []
        kb = self.args.start_mb * 1024 // 2
        while kb >= self.args.sub_mb_floor_kb:
            out.append(kb / 1024.0)
            kb //= 2
        return sorted(out)

    def profile_sp_time(self) -> Dict[str, float]:
        """sp_time_*.json: all-reduce + all-to-all latency (ms) per group
        size per message size in MB (profile_allreduce.py latency mode +
        profile_all2all.py), plus sub-MB all-reduce points under the
        ``sub_`` prefix (KB-keyed; invisible to the legacy remap parsers,
        consumed by :meth:`profile_alpha_beta`'s α-β fit)."""
        out: Dict[str, float] = {}
        sizes = []
        mb = self.args.start_mb
        while mb <= self.args.end_mb:
            sizes.append(mb)
            mb *= self.args.scale
        size = self.world
        while size >= 2:
            group = _group_devices(self.devices, size, True, self.world)
            for mb in sizes:
                out[f"allreduce_size_{size}_{mb}MB_time"] = \
                    self._collective_ms("allreduce", group, mb)
            for mb in sizes:
                out[f"all2all_size_{size}_{mb}MB_time"] = \
                    self._collective_ms("all2all", group, mb)
            for mb in self._sub_mb_sizes():
                kb = int(round(mb * 1024))
                out[f"sub_allreduce_size_{size}_{kb}KB_time"] = \
                    self._collective_ms("allreduce", group, mb)
            size //= 2
        return out

    def profile_alpha_beta(self, sp_times: Optional[Dict[str, float]] = None
                           ) -> Dict[str, float]:
        """Latency-aware collective fit: per (group size, consecutiveness),
        fit the allreduce time curve ``t(size) = α + size / β`` over the
        sub-MB + integer-MB points and emit ``allreduce_size_{n}_consec_
        {c}_alpha_ms`` / ``..._beta_mb_per_ms`` keys (merged into the
        bandwidth JSON alongside the legacy keys — profiles.read_alpha_beta
        parses them, legacy readers ignore them). Consecutive groups reuse
        ``sp_times`` measurements when provided; non-consecutive (strided)
        groups are measured here."""
        fit_sizes = self._sub_mb_sizes() + [float(self.args.start_mb),
                                            float(self.args.start_mb * 2),
                                            float(self.args.start_mb * 4)]
        out: Dict[str, float] = {}
        size = self.world
        while size >= 2:
            for consec in ([1] if size == self.world else [1, 0]):
                xs, ys = [], []
                group = _group_devices(self.devices, size, bool(consec),
                                       self.world)
                for mb in fit_sizes:
                    t = None
                    if consec and sp_times is not None:
                        if mb < 1:
                            t = sp_times.get(
                                f"sub_allreduce_size_{size}_"
                                f"{int(round(mb * 1024))}KB_time")
                        else:
                            t = sp_times.get(
                                f"allreduce_size_{size}_{int(mb)}MB_time")
                    if t is None:
                        t = self._collective_ms("allreduce", group, mb)
                    xs.append(mb)
                    ys.append(t)
                slope, alpha = np.polyfit(xs, ys, 1)
                alpha = max(float(alpha), 0.0)
                beta = 1.0 / max(float(slope), 1e-9)
                out[f"allreduce_size_{size}_consec_{consec}_alpha_ms"] = \
                    round(alpha, 6)
                out[f"allreduce_size_{size}_consec_{consec}_beta_mb_per_ms"] \
                    = round(beta, 3)
            size //= 2
        return out

    def profile_overlap_coefficient(self, message_mb: int = 64) -> Dict:
        """overlap_coefficient.json: slowdown of compute when a collective
        runs concurrently (reference profile_overlap.py:10-60 measures with
        separate CUDA streams; here one jitted program interleaves a matmul
        chain with psums and XLA overlaps them on the TPU's async fabric)."""
        n = self.world
        if n < 2:
            return {"overlap_coe": 1.0}
        mesh = Mesh(np.array(self.devices[:n]), ("g",))
        k = 1024
        a = jax.device_put(jnp.ones((k, k), jnp.bfloat16),
                           NamedSharding(mesh, P(None, None)))
        elems = int(message_mb * 1024 * 1024 // 4)
        x = jax.device_put(jnp.ones((elems,), jnp.float32),
                           NamedSharding(mesh, P(None)))
        from jax.experimental.shard_map import shard_map

        def compute_only(m):
            for _ in range(8):
                m = jnp.tanh(m @ m)
            return m

        @partial(shard_map, mesh=mesh, in_specs=(P(None, None), P(None)),
                 out_specs=(P(None, None), P(None)), check_rep=False)
        def both(m, v):
            v = jax.lax.psum(v, "g")
            for _ in range(8):
                m = jnp.tanh(m @ m)
            return m, v

        t_comp = _time_fn(jax.jit(compute_only), a,
                          warmup=self.args.warmup_iters,
                          iters=self.args.profile_iters)
        comm_fn = jax.jit(shard_map(lambda v: jax.lax.psum(v, "g"), mesh=mesh,
                                    in_specs=P(None), out_specs=P(None),
                                    check_rep=False))
        t_comm = _time_fn(comm_fn, x, warmup=self.args.warmup_iters,
                          iters=self.args.profile_iters)
        jboth = jax.jit(lambda m, v: both(m, v))
        for _ in range(self.args.warmup_iters):
            out = jboth(a, x)
        jax.block_until_ready(out)
        samples = []
        for _ in range(self.args.profile_iters):
            t0 = time.perf_counter()
            out = jboth(a, x)
            jax.block_until_ready(out)
            samples.append((time.perf_counter() - t0) * 1000.0)
        t_both = float(np.median(samples))
        overlap = max(t_both / max(max(t_comp, t_comm), 1e-9), 1.0)
        return {"overlap_coe": round(overlap, 4)}

    # -- output -------------------------------------------------------------

    def run_all(self, output_dir: Optional[str] = None) -> Dict[str, str]:
        """Run every benchmark and write the four hardware_configs JSONs
        (reference generate_script outputs, hardware_profiler.py:39-155)."""
        a = self.args
        out_dir = output_dir or a.output_dir
        tag = f"{a.num_nodes}nodes_{a.num_devices_per_node}gpus_per_node"
        sp_times = self.profile_sp_time()
        bandwidth = self.profile_allreduce_bandwidth()
        # α-β pairs ride the bandwidth JSON next to the legacy keys
        bandwidth.update(self.profile_alpha_beta(sp_times))
        paths = {}
        for name, cfg in [
            (f"allreduce_bandwidth_{tag}.json", bandwidth),
            (f"p2p_bandwidth_{tag}.json", self.profile_p2p_bandwidth()),
            (f"sp_time_{tag}.json", sp_times),
            ("overlap_coefficient.json", self.profile_overlap_coefficient()),
        ]:
            path = os.path.join(out_dir, name)
            write_json(cfg, path)
            paths[name] = path
        return paths
