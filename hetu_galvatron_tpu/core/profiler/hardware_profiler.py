"""Hardware profiler: ICI/DCN collective microbenchmarks.

Capability parity with the reference hardware profiling stack
(core/profiler/hardware_profiler.py:39-229 script generation +
profile_hardware/profile_allreduce.py:84-162, profile_p2p.py:19,
profile_all2all.py, profile_overlap.py:10-60): measures
- all-reduce bandwidth (MB/ms) per group size, consecutive and strided
- p2p (ppermute ring) bandwidth per pipeline degree
- all-reduce / all-to-all latency vs message size (the sp_time tables)
- the compute/comm overlap slowdown coefficient
and writes the same JSON schemas the search engine reads
(hardware_configs/*.json).

TPU-native: instead of spawning torchrun scripts per benchmark, collectives
run as jitted `shard_map` programs over sub-meshes of the current platform's
devices — the same code path measures ICI on a TPU slice and host rings on
the virtual CPU mesh (tests).
"""

from __future__ import annotations

import os
import time
import warnings
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.core.args_schema import HardwareProfileArgs
from hetu_galvatron_tpu.core.search_engine.profiles import write_json


def _time_fn(fn, arg, *, warmup: int, iters: int, inner: int = 1) -> float:
    """Median wall-clock ms of fn(arg) (reference uses trimmed means over 20
    x10-iter samples, profile_allreduce.py:14-17,129-133)."""
    out = None
    for _ in range(warmup):
        out = fn(arg)
    if out is not None:
        jax.block_until_ready(out)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn(arg)
        jax.block_until_ready(out)
        samples.append((time.perf_counter() - t0) / inner * 1000.0)
    return float(np.median(samples))


# the profiler's private single-axis benchmark mesh name (the legacy
# literal uses are baselined in analysis/lint_baseline.json; new code
# routes through this constant so GAL003 stays at zero new findings)
_G_AXIS = "g"

# slope floor for the α-β fit (ms per MB): measurement noise on sub-MB
# points can tilt the fitted line flat or NEGATIVE, and 1/slope would then
# be a nonsense β (infinite-or-negative bandwidth). Below the floor the
# fit is rejected and the legacy single-point bandwidth stays the model.
_MIN_SLOPE_MS_PER_MB = 1e-7


def fit_alpha_beta(xs: Sequence[float], ys: Sequence[float], *,
                   label: str = "") -> Optional[Tuple[float, float]]:
    """Least-squares ``t(size) = α + size/β`` fit over (MB, ms) points.
    Returns (α ms ≥ 0, β MB/ms) — or None with a warning when the slope is
    degenerate (≤ :data:`_MIN_SLOPE_MS_PER_MB`): writing a garbage pair
    would poison every cost the search prices with it, while an ABSENT
    pair falls back to the measured latency tables."""
    slope, alpha = np.polyfit(list(xs), list(ys), 1)
    if float(slope) <= _MIN_SLOPE_MS_PER_MB:
        warnings.warn(
            f"alpha-beta fit {label or '<unnamed>'}: degenerate slope "
            f"{float(slope):.3e} ms/MB (noisy sub-MB points?); skipping "
            "the pair — the legacy single-point bandwidth stays in effect",
            stacklevel=2)
        return None
    return max(float(alpha), 0.0), 1.0 / float(slope)


def _group_devices(devices: Sequence, size: int, consecutive: bool,
                   world: int) -> List:
    """First group of `size` devices: adjacent chips (ICI neighbours) or
    strided across the world (the reference's consec 1/0 groupings,
    comm_groups.py:96-100)."""
    if consecutive:
        return list(devices[:size])
    stride = world // size
    return [devices[i * stride] for i in range(size)]


def _dcn_group_devices(devices: Sequence, size: int, world: int
                       ) -> Tuple[List, str]:
    """A ``size``-device group whose links actually cross the DCN seam,
    plus the level-source tag recorded in the fitted JSON metadata.

    Multi-process jobs (``jax.process_count() > 1``) pick devices
    round-robin across processes (slice boundaries granule by process on
    pods without ``slice_index``), so every hop in the benchmarked
    collective crosses a host/slice boundary — a TRUE DCN measurement.
    Single-process runs (CPU tests, one-slice jobs) keep the maximally
    STRIDED proxy group with a warning: its hops measure intra-host
    stride, not a slice boundary, so the fitted "dcn" α/β only bound the
    topology model until a real multi-slice fleet re-measures them
    (tools/tpu_measure_all.py)."""
    devices = list(devices[:world])
    by_proc: Dict[int, List] = {}
    for d in devices:
        by_proc.setdefault(getattr(d, "process_index", 0), []).append(d)
    if len(by_proc) > 1:
        # interleave one device per process until the group is full:
        # adjacent group members always sit in different processes
        group: List = []
        ranks = sorted(by_proc)
        i = 0
        while len(group) < size:
            proc = by_proc[ranks[i % len(ranks)]]
            if proc:
                group.append(proc.pop(0))
            i += 1
            if i > 10 * size * len(ranks):  # all pools drained
                break
        if len(group) == size:
            return group, "multihost"
    warnings.warn(
        "profile_alpha_beta_algos: single-process fleet — the 'dcn' "
        "level falls back to the strided intra-host PROXY group, which "
        "measures stride, not a slice boundary; re-measure on a "
        "multi-slice fleet before trusting the DCN α/β",
        stacklevel=2)
    return _group_devices(devices, size, False, world), "proxy-strided"


class HardwareProfiler:
    def __init__(self, args: HardwareProfileArgs,
                 devices: Optional[Sequence] = None):
        self.args = args
        self.devices = list(devices if devices is not None else jax.devices())
        self.world = min(len(self.devices),
                         args.num_nodes * args.num_devices_per_node)

    # -- collective runners -------------------------------------------------

    def _collective_ms(self, op: str, group: List, message_mb: float) -> float:
        """Time one collective over `group` with a message of `message_mb`
        MB per device (fp32)."""
        n = len(group)
        mesh = Mesh(np.array(group), ("g",))
        elems = max(int(message_mb * 1024 * 1024 // 4), n)
        elems = (elems // n) * n
        x = jax.device_put(
            jnp.ones((elems,), jnp.float32),
            NamedSharding(mesh, P(None)))

        # NOTE: this jax pin has no top-level jax.shard_map; the
        # experimental entry point (check_rep kwarg) is the one that works
        from jax.experimental.shard_map import shard_map

        if op == "allreduce":
            fn = shard_map(lambda v: jax.lax.psum(v, "g"), mesh=mesh,
                           in_specs=P(None), out_specs=P(None),
                           check_rep=False)
        elif op == "allgather":
            x = jax.device_put(jnp.ones((elems,), jnp.float32),
                               NamedSharding(mesh, P("g")))
            fn = shard_map(lambda v: jax.lax.all_gather(v, "g", tiled=True),
                           mesh=mesh, in_specs=P("g"), out_specs=P(None),
                           check_rep=False)
        elif op == "all2all":
            x = jax.device_put(jnp.ones((n, elems // n), jnp.float32),
                               NamedSharding(mesh, P("g", None)))
            fn = shard_map(
                lambda v: jax.lax.all_to_all(v, "g", split_axis=1,
                                             concat_axis=0, tiled=True),
                mesh=mesh, in_specs=P("g", None), out_specs=P(None, "g"),
                check_rep=False)
        elif op == "p2p":
            perm = [(i, (i + 1) % n) for i in range(n)]
            fn = shard_map(lambda v: jax.lax.ppermute(v, "g", perm),
                           mesh=mesh, in_specs=P(None), out_specs=P(None),
                           check_rep=False)
        else:
            raise ValueError(op)
        jfn = jax.jit(fn)
        return _time_fn(jfn, x, warmup=self.args.warmup_iters,
                        iters=self.args.profile_iters)

    # -- benchmark suites ---------------------------------------------------

    def profile_allreduce_bandwidth(self, message_mb: int = 64
                                    ) -> Dict[str, float]:
        """allreduce_bandwidth_*.json: MB/ms per (group size, consec) with
        the 2x(n-1)/n algorithmic volume (profile_allreduce.py:84-162)."""
        out: Dict[str, float] = {}
        size = self.world
        while size >= 2:
            for consec in ([1] if size == self.world else [1, 0]):
                group = _group_devices(self.devices, size, bool(consec),
                                       self.world)
                ms = self._collective_ms("allreduce", group, message_mb)
                volume = 2 * (size - 1) / size * message_mb
                out[f"allreduce_size_{size}_consec_{consec}"] = round(
                    volume / ms, 3)
            size //= 2
        return out

    def profile_p2p_bandwidth(self, message_mb: int = 64) -> Dict[str, float]:
        """p2p_bandwidth_*.json: MB/ms per pipeline degree
        (profile_p2p.py:19)."""
        out: Dict[str, float] = {}
        pp = 2
        while pp <= min(self.world, self.args.max_pp_deg):
            group = _group_devices(self.devices, pp, True, self.world)
            ms = self._collective_ms("p2p", group, message_mb)
            out[f"pp_size_{pp}"] = round(message_mb / ms, 3)
            pp *= 2
        return out

    def _sub_mb_sizes(self) -> List[float]:
        """Sub-MB message sizes (MB) for the α (latency) fit: halvings of
        start_mb down to sub_mb_floor_kb. Layer-wise TP puts per-collective
        messages well under a megabyte, where the latency term dominates
        ("Revisiting the Time Cost Model of AllReduce", PAPERS.md) — the
        integer-MB sweep alone cannot see it."""
        out: List[float] = []
        kb = self.args.start_mb * 1024 // 2
        while kb >= self.args.sub_mb_floor_kb:
            out.append(kb / 1024.0)
            kb //= 2
        return sorted(out)

    def profile_sp_time(self) -> Dict[str, float]:
        """sp_time_*.json: all-reduce + all-to-all latency (ms) per group
        size per message size in MB (profile_allreduce.py latency mode +
        profile_all2all.py), plus sub-MB all-reduce points under the
        ``sub_`` prefix (KB-keyed; invisible to the legacy remap parsers,
        consumed by :meth:`profile_alpha_beta`'s α-β fit)."""
        out: Dict[str, float] = {}
        sizes = []
        mb = self.args.start_mb
        while mb <= self.args.end_mb:
            sizes.append(mb)
            mb *= self.args.scale
        size = self.world
        while size >= 2:
            group = _group_devices(self.devices, size, True, self.world)
            for mb in sizes:
                out[f"allreduce_size_{size}_{mb}MB_time"] = \
                    self._collective_ms("allreduce", group, mb)
            for mb in sizes:
                out[f"all2all_size_{size}_{mb}MB_time"] = \
                    self._collective_ms("all2all", group, mb)
            for mb in self._sub_mb_sizes():
                kb = int(round(mb * 1024))
                out[f"sub_allreduce_size_{size}_{kb}KB_time"] = \
                    self._collective_ms("allreduce", group, mb)
            size //= 2
        return out

    def profile_alpha_beta(self, sp_times: Optional[Dict[str, float]] = None
                           ) -> Dict[str, float]:
        """Latency-aware collective fit: per (group size, consecutiveness),
        fit the allreduce time curve ``t(size) = α + size / β`` over the
        sub-MB + integer-MB points and emit ``allreduce_size_{n}_consec_
        {c}_alpha_ms`` / ``..._beta_mb_per_ms`` keys (merged into the
        bandwidth JSON alongside the legacy keys — profiles.read_alpha_beta
        parses them, legacy readers ignore them). Consecutive groups reuse
        ``sp_times`` measurements when provided; non-consecutive (strided)
        groups are measured here."""
        fit_sizes = self._sub_mb_sizes() + [float(self.args.start_mb),
                                            float(self.args.start_mb * 2),
                                            float(self.args.start_mb * 4)]
        out: Dict[str, float] = {}
        size = self.world
        while size >= 2:
            for consec in ([1] if size == self.world else [1, 0]):
                xs, ys = [], []
                group = _group_devices(self.devices, size, bool(consec),
                                       self.world)
                for mb in fit_sizes:
                    t = None
                    if consec and sp_times is not None:
                        if mb < 1:
                            t = sp_times.get(
                                f"sub_allreduce_size_{size}_"
                                f"{int(round(mb * 1024))}KB_time")
                        else:
                            t = sp_times.get(
                                f"allreduce_size_{size}_{int(mb)}MB_time")
                    if t is None:
                        t = self._collective_ms("allreduce", group, mb)
                    xs.append(mb)
                    ys.append(t)
                pair = fit_alpha_beta(
                    xs, ys,
                    label=f"allreduce_size_{size}_consec_{consec}")
                if pair is None:
                    # degenerate slope: no pair is written, so the cost
                    # model keeps pricing this (size, consec) off the
                    # legacy single-point bandwidth / latency tables
                    continue
                alpha, beta = pair
                out[f"allreduce_size_{size}_consec_{consec}_alpha_ms"] = \
                    round(alpha, 6)
                out[f"allreduce_size_{size}_consec_{consec}_beta_mb_per_ms"] \
                    = round(beta, 3)
            size //= 2
        return out

    # -- per-algorithm schedules (ring vs recursive halving-doubling) -------

    def _algo_allreduce_ms(self, alg: str, group: List,
                           message_mb: float) -> float:
        """Time one all-reduce of ``message_mb`` MB/device over ``group``
        running an EXPLICIT algorithm-shaped schedule instead of whatever
        the runtime lowers psum to:

        * ``ring`` — reduce-scatter then all-gather rings: 2(n-1) hops of
          1/n-sized chunks (`lax.ppermute`), the bandwidth-optimal,
          latency-poor shape.
        * ``tree`` — recursive halving-doubling: log2(n) pairwise
          exchange rounds with halving payloads then the doubling gather
          back — 2·log2(n) hops, the latency-optimal shape for small
          messages ("Revisiting the Time Cost Model of AllReduce").

        The two schedules have materially different (α, β) regimes; the
        fitted pairs let the cost model price each collective as the MIN
        over algorithms at its message size and level. The bodies are the
        canonical hand-built programs in ``collectives.reference`` — the
        collective compiler's emitted ring / halving-doubling schedules
        are pinned bit-identical to them."""
        from hetu_galvatron_tpu.collectives.reference import (
            handbuilt_allreduce_body,
        )
        n = len(group)
        if n < 2 or (n & (n - 1)):
            raise ValueError(f"algorithm schedules need a power-of-two "
                             f"group, got {n}")
        mesh = Mesh(np.array(group), (_G_AXIS,))
        elems = max(int(message_mb * 1024 * 1024 // 4), 2 * n)
        elems = (elems // (2 * n)) * (2 * n)
        x = jax.device_put(jnp.ones((elems,), jnp.float32),
                           NamedSharding(mesh, P(None)))
        from jax.experimental.shard_map import shard_map

        body = handbuilt_allreduce_body(alg, n, _G_AXIS)
        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P(None),
                               out_specs=P(None), check_rep=False))
        return _time_fn(fn, x, warmup=self.args.warmup_iters,
                        iters=self.args.profile_iters)

    def profile_alpha_beta_algos(self) -> Dict[str, float]:
        """Per-algorithm, per-LEVEL latency-bandwidth fits: for each group
        size, each algorithm schedule (ring / tree) is benchmarked over an
        intra-host/ICI group (adjacent devices, ``consec=1``, level
        ``ici``) and a cross-slice/DCN proxy group (maximally strided,
        ``consec=0``, level ``dcn`` — the grouping
        ``mesh.dcn_factor_shape`` puts across slices), and the
        ``t = α + size/β`` curve is fitted over the sub-MB + integer-MB
        sweep. Emitted keys extend the flat :meth:`profile_alpha_beta`
        namespace::

            allreduce_size_{n}_consec_{c}_alg_{ring|tree}_lvl_{ici|dcn}_
            alpha_ms / ..._beta_mb_per_ms

        ``profiles.read_alpha_beta_algos`` parses them; the flat reader
        and every legacy parser skip them. Degenerate fits are dropped
        with a warning (:func:`fit_alpha_beta`), falling back per
        (size, algorithm, level) to whatever coarser model remains.

        The ``dcn`` level's group is TRUE multi-host when the job spans
        processes (one device per process round-robin,
        :func:`_dcn_group_devices` — every hop crosses the DCN seam);
        single-process runs keep the strided intra-host proxy with a
        warning, and the emitted ``dcn_level_source`` metadata key
        records which one measured the curves ("multihost" |
        "proxy-strided") so a fitted JSON can never silently pass a
        proxy off as a fleet measurement. Legacy parsers skip the
        non-``allreduce_size_`` key."""
        fit_sizes = self._sub_mb_sizes() + [float(self.args.start_mb),
                                            float(self.args.start_mb * 2),
                                            float(self.args.start_mb * 4)]
        out: Dict[str, float] = {}
        dcn_source: Optional[str] = None
        size = self.world
        while size >= 2:
            levels = [("ici", 1)]
            if size < self.world:
                levels.append(("dcn", 0))
            for lvl, consec in levels:
                if lvl == "dcn":
                    group, src = _dcn_group_devices(self.devices, size,
                                                    self.world)
                    dcn_source = dcn_source or src
                else:
                    group = _group_devices(self.devices, size, bool(consec),
                                           self.world)
                for alg in ("ring", "tree"):
                    xs, ys = [], []
                    for mb in fit_sizes:
                        xs.append(mb)
                        ys.append(self._algo_allreduce_ms(alg, group, mb))
                    key = (f"allreduce_size_{size}_consec_{consec}"
                           f"_alg_{alg}_lvl_{lvl}")
                    pair = fit_alpha_beta(xs, ys, label=key)
                    if pair is None:
                        continue
                    alpha, beta = pair
                    out[f"{key}_alpha_ms"] = round(alpha, 6)
                    out[f"{key}_beta_mb_per_ms"] = round(beta, 3)
            size //= 2
        if dcn_source is not None:
            out["dcn_level_source"] = dcn_source
        return out

    def profile_overlap_coefficient(self, message_mb: int = 64) -> Dict:
        """overlap_coefficient.json: slowdown of compute when a collective
        runs concurrently (reference profile_overlap.py:10-60 measures with
        separate CUDA streams; here one jitted program interleaves a matmul
        chain with psums and XLA overlaps them on the TPU's async fabric)."""
        n = self.world
        if n < 2:
            return {"overlap_coe": 1.0}
        mesh = Mesh(np.array(self.devices[:n]), ("g",))
        k = 1024
        a = jax.device_put(jnp.ones((k, k), jnp.bfloat16),
                           NamedSharding(mesh, P(None, None)))
        elems = int(message_mb * 1024 * 1024 // 4)
        x = jax.device_put(jnp.ones((elems,), jnp.float32),
                           NamedSharding(mesh, P(None)))
        from jax.experimental.shard_map import shard_map

        def compute_only(m):
            for _ in range(8):
                m = jnp.tanh(m @ m)
            return m

        @partial(shard_map, mesh=mesh, in_specs=(P(None, None), P(None)),
                 out_specs=(P(None, None), P(None)), check_rep=False)
        def both(m, v):
            v = jax.lax.psum(v, "g")
            for _ in range(8):
                m = jnp.tanh(m @ m)
            return m, v

        t_comp = _time_fn(jax.jit(compute_only), a,
                          warmup=self.args.warmup_iters,
                          iters=self.args.profile_iters)
        comm_fn = jax.jit(shard_map(lambda v: jax.lax.psum(v, "g"), mesh=mesh,
                                    in_specs=P(None), out_specs=P(None),
                                    check_rep=False))
        t_comm = _time_fn(comm_fn, x, warmup=self.args.warmup_iters,
                          iters=self.args.profile_iters)
        jboth = jax.jit(lambda m, v: both(m, v))
        for _ in range(self.args.warmup_iters):
            out = jboth(a, x)
        jax.block_until_ready(out)
        samples = []
        for _ in range(self.args.profile_iters):
            t0 = time.perf_counter()
            out = jboth(a, x)
            jax.block_until_ready(out)
            samples.append((time.perf_counter() - t0) * 1000.0)
        t_both = float(np.median(samples))
        overlap = max(t_both / max(max(t_comp, t_comm), 1e-9), 1.0)
        return {"overlap_coe": round(overlap, 4)}

    # -- output -------------------------------------------------------------

    def run_all(self, output_dir: Optional[str] = None) -> Dict[str, str]:
        """Run every benchmark and write the four hardware_configs JSONs
        (reference generate_script outputs, hardware_profiler.py:39-155)."""
        a = self.args
        out_dir = output_dir or a.output_dir
        tag = f"{a.num_nodes}nodes_{a.num_devices_per_node}gpus_per_node"
        sp_times = self.profile_sp_time()
        bandwidth = self.profile_allreduce_bandwidth()
        # α-β pairs ride the bandwidth JSON next to the legacy keys
        bandwidth.update(self.profile_alpha_beta(sp_times))
        if a.profile_algos:
            # per-algorithm / per-level pairs (ring vs halving-doubling,
            # ICI vs DCN-proxy groups) extend the same namespace
            bandwidth.update(self.profile_alpha_beta_algos())
        paths = {}
        for name, cfg in [
            (f"allreduce_bandwidth_{tag}.json", bandwidth),
            (f"p2p_bandwidth_{tag}.json", self.profile_p2p_bandwidth()),
            (f"sp_time_{tag}.json", sp_times),
            ("overlap_coefficient.json", self.profile_overlap_coefficient()),
        ]:
            path = os.path.join(out_dir, name)
            write_json(cfg, path)
            paths[name] = path
        return paths
