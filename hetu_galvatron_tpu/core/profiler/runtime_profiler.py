"""Runtime profiler: per-iteration timing + device memory accounting.

Capability parity with the reference runtime profiler
(core/profiler/runtime_profiler.py:12-370): wall-clock per-iteration timing
with warmup and a 3-sigma outlier filter, per-phase device memory peaks, an
iteration log line, and the computation/memory JSON writers the model
profiler post-processes.

TPU-native measurement: timing is host wall-clock around `block_until_ready`
(XLA has no CUDA events; dispatch is async so this measures true device
time once warm), memory uses `device.memory_stats()` when the backend
provides it (TPU does) and falls back to the jitted executable's
`memory_analysis()` — XLA's own static accounting — on backends without
allocator stats (CPU tests).

Everything measured here is also routed through the observability metrics
registry (``observability/registry.py``): iteration times land in the
``profiler/iter_time_ms`` histogram, memory probes in ``profiler/mem_mb``
gauges, and the MoE balance tracker in ``moe/*`` gauges, so a configured
JSONL/TensorBoard sink sees the profiler's view of the run without any
extra plumbing. The XLA trace window is delegated to
``observability.tracing.TraceCapture``.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import jax

from hetu_galvatron_tpu.core.args_schema import CoreArgs
from hetu_galvatron_tpu.core.search_engine.profiles import write_json
from hetu_galvatron_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
)
from hetu_galvatron_tpu.observability.tracing import TraceCapture

MB = 1024 * 1024


def device_memory_mb(device=None) -> Optional[Dict[str, float]]:
    """Current/peak bytes in use from the backend allocator, or None when
    unsupported (CPU)."""
    device = device or jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return None
    return {
        "current": stats.get("bytes_in_use", 0) / MB,
        "peak": stats.get("peak_bytes_in_use", 0) / MB,
    }


def compiled_memory_mb(compiled) -> Dict[str, float]:
    """Static memory accounting from a lowered+compiled jit function
    (the TPU-native analogue of torch.cuda.max_memory_allocated for
    profiling: XLA reports argument/output/temp/generated sizes)."""
    m = compiled.memory_analysis()
    if m is None:
        return {}
    def g(name):
        return getattr(m, name, 0) or 0
    return {
        "arguments": g("argument_size_in_bytes") / MB,
        "outputs": g("output_size_in_bytes") / MB,
        "temps": g("temp_size_in_bytes") / MB,
        "total": (g("argument_size_in_bytes") + g("output_size_in_bytes")
                  + g("temp_size_in_bytes")) / MB,
    }


class RuntimeProfiler:
    """Hooks into the train loop: time_start/time_end around the step,
    memory probes at phase boundaries (reference profile_memory :105,
    post_profile_memory :134, profile_time_start :218)."""

    def __init__(self, args: CoreArgs, world_size: int = 1, rank: int = 0,
                 registry: Optional[MetricsRegistry] = None):
        self.args = args
        self.world_size = world_size
        self.rank = rank
        # None = late-bind the process default at USE time, so a profiler
        # constructed before the train launcher configures sinks still
        # lands its metrics in the configured stream
        self._registry = registry
        self.time_samples: List[float] = []
        self.memory_samples: Dict[str, Dict[str, float]] = {}
        self._t0: Optional[float] = None
        self.enabled = bool(args.profile.profile)
        p = args.profile
        self._trace = TraceCapture(
            p.trace_dir, start_iter=p.profile_warmup,
            num_iters=p.trace_iters, enabled=bool(p.trace_dir and rank == 0))
        self._tracing_now = False

    @property
    def registry(self) -> MetricsRegistry:
        return self._registry if self._registry is not None else get_registry()

    # -- timing -------------------------------------------------------------

    def time_start(self, it: int) -> None:
        # XLA trace window [warmup, warmup + trace_iters): the TPU
        # counterpart of the reference's torch.profiler capture
        # (observability/tracing.py — window-based so checkpoint-resumed
        # runs whose first iteration is already past warmup still capture)
        self._tracing_now = self._trace.step(it)
        if not self.enabled or it < self.args.profile.profile_warmup:
            return
        if self._tracing_now:
            # trace instrumentation inflates step time; traced iterations
            # stay out of time_samples so filtered_time_ms (and the
            # computation profiles the search engine fits) stay clean
            return
        self._t0 = time.perf_counter()

    def stop_trace(self) -> None:
        """Idempotent; also called at loop exit so short runs still flush."""
        self._trace.stop()

    def analyze_trace(self):
        """Device-time attribution of the flushed capture window
        (``observability/trace_analysis.attribute``), or None when no
        window was configured or ever flushed."""
        if not self._trace.enabled:
            return None
        from hetu_galvatron_tpu.observability.trace_analysis import (
            attribute,
            load_trace,
        )

        try:
            return attribute(load_trace(self._trace.trace_dir))
        except FileNotFoundError:
            return None

    def time_end(self, it: int, sync: Any = None) -> None:
        if self._t0 is None:
            return
        if sync is not None:
            jax.block_until_ready(sync)
        ms = (time.perf_counter() - self._t0) * 1000.0
        self.time_samples.append(ms)
        self.registry.histogram("profiler/iter_time_ms").observe(ms)
        self._t0 = None

    def filtered_time_ms(self) -> float:
        """Mean after dropping >3-sigma outliers (reference
        _filtered_time_samples, runtime_profiler.py:312)."""
        if not self.time_samples:
            return 0.0
        arr = np.asarray(self.time_samples)
        mean, std = arr.mean(), arr.std()
        keep = arr[np.abs(arr - mean) <= 3 * std] if std > 0 else arr
        return float(keep.mean())

    # -- memory -------------------------------------------------------------

    def probe_memory(self, phase: str, device=None) -> None:
        if not self.enabled:
            return
        stats = device_memory_mb(device)
        if stats is not None:
            self.memory_samples[phase] = stats
            for stat, v in stats.items():
                self.registry.gauge("profiler/mem_mb", phase=phase,
                                    stat=stat).set(v)

    def record_static_memory(self, compiled) -> None:
        if not self.enabled:
            return
        mem = compiled_memory_mb(compiled)
        self.memory_samples["compiled"] = mem
        for stat, v in mem.items():
            self.registry.gauge("profiler/mem_mb", phase="compiled",
                                stat=stat).set(v)

    # -- logging + output ---------------------------------------------------

    def iteration_log(self, it: int, metrics: Dict[str, Any],
                      lr: Optional[float] = None) -> str:
        """One line per iteration (reference runtime_profiler.py:333-370).

        Returns EXACTLY the line that was printed, or "" on non-printing
        iterations (rank != 0 or off the log interval) — the return value
        is consistent for every caller, and off-interval iterations pay
        ZERO device-to-host syncs: all float()/asarray() formatting
        (including the MoE balance tracker) is gated behind the interval,
        never half of it.
        """
        printing = (self.rank == 0 and self.args.logging.log_interval
                    and it % self.args.logging.log_interval == 0)
        if not printing:
            return ""
        bits = [f"iter {it}"]
        if "loss" in metrics:
            bits.append(f"loss {float(metrics['loss']):.4f}")
        if "grad_norm" in metrics:
            bits.append(f"grad-norm {float(metrics['grad_norm']):.3f}")
        if lr is not None:
            bits.append(f"lr {lr:.3e}")
        if self.time_samples:
            bits.append(f"iter-time {self.time_samples[-1]:.1f}ms")
        if "moe" in metrics:
            # per-layer balance tracker (reference moe_utils.py:608-644
            # track_moe_metrics log lines): aux/z-loss per MoE layer plus
            # the tokens-per-expert imbalance max/mean; the converted
            # scalars also land in the registry as moe/* gauges
            for name in sorted(metrics["moe"]):
                st = metrics["moe"][name]
                tpe = np.asarray(st["tokens_per_expert"], dtype=float)
                imb = float(tpe.max() / max(tpe.mean(), 1e-9))
                aux = float(st["load_balance_loss"])
                z = float(st["z_loss"])
                bits.append(f"moe[{name}] aux {aux:.3e} "
                            f"z {z:.3e} imb {imb:.2f}")
                self.registry.gauge("moe/aux_loss", layer=name).set(aux)
                self.registry.gauge("moe/z_loss", layer=name).set(z)
                self.registry.gauge("moe/imbalance", layer=name).set(imb)
        line = " | ".join(bits)
        print(line, flush=True)
        return line

    def computation_profile_key(self, layertype: int, bsz: int,
                                seq: int) -> str:
        return f"layertype_{layertype}_bsz{bsz}_seq{seq}"

    def save_computation_profile(self, path: str, entries: Dict[str, float]
                                 ) -> None:
        """Merge per-run timing entries into computation_profiling_*.json."""
        import json, os

        existing = {}
        if os.path.exists(path):
            with open(path) as f:
                existing = json.load(f)
        existing.update(entries)
        write_json(existing, path)

    def save_memory_profile(self, path: str, entries: Dict[str, Any]) -> None:
        import json, os

        existing = {}
        if os.path.exists(path):
            with open(path) as f:
                existing = json.load(f)
        existing.update(entries)
        write_json(existing, path)
