"""Runtime profiler: per-iteration timing + device memory accounting.

Capability parity with the reference runtime profiler
(core/profiler/runtime_profiler.py:12-370): wall-clock per-iteration timing
with warmup and a 3-sigma outlier filter, per-phase device memory peaks, an
iteration log line, and the computation/memory JSON writers the model
profiler post-processes.

TPU-native measurement: timing is host wall-clock around `block_until_ready`
(XLA has no CUDA events; dispatch is async so this measures true device
time once warm), memory uses `device.memory_stats()` when the backend
provides it (TPU does) and falls back to the jitted executable's
`memory_analysis()` — XLA's own static accounting — on backends without
allocator stats (CPU tests).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import jax

from hetu_galvatron_tpu.core.args_schema import CoreArgs
from hetu_galvatron_tpu.core.search_engine.profiles import write_json

MB = 1024 * 1024


def device_memory_mb(device=None) -> Optional[Dict[str, float]]:
    """Current/peak bytes in use from the backend allocator, or None when
    unsupported (CPU)."""
    device = device or jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return None
    return {
        "current": stats.get("bytes_in_use", 0) / MB,
        "peak": stats.get("peak_bytes_in_use", 0) / MB,
    }


def compiled_memory_mb(compiled) -> Dict[str, float]:
    """Static memory accounting from a lowered+compiled jit function
    (the TPU-native analogue of torch.cuda.max_memory_allocated for
    profiling: XLA reports argument/output/temp/generated sizes)."""
    m = compiled.memory_analysis()
    if m is None:
        return {}
    def g(name):
        return getattr(m, name, 0) or 0
    return {
        "arguments": g("argument_size_in_bytes") / MB,
        "outputs": g("output_size_in_bytes") / MB,
        "temps": g("temp_size_in_bytes") / MB,
        "total": (g("argument_size_in_bytes") + g("output_size_in_bytes")
                  + g("temp_size_in_bytes")) / MB,
    }


class RuntimeProfiler:
    """Hooks into the train loop: time_start/time_end around the step,
    memory probes at phase boundaries (reference profile_memory :105,
    post_profile_memory :134, profile_time_start :218)."""

    def __init__(self, args: CoreArgs, world_size: int = 1, rank: int = 0):
        self.args = args
        self.world_size = world_size
        self.rank = rank
        self.time_samples: List[float] = []
        self.memory_samples: Dict[str, Dict[str, float]] = {}
        self._t0: Optional[float] = None
        self.enabled = bool(args.profile.profile)
        self._tracing = False
        self._traced_iters = 0

    # -- timing -------------------------------------------------------------

    def time_start(self, it: int) -> None:
        p = self.args.profile
        if p.trace_dir and self.rank == 0:
            # XLA trace window [warmup, warmup + trace_iters): the TPU
            # counterpart of the reference's torch.profiler capture.
            # Window-based (not ==) so checkpoint-resumed runs whose first
            # iteration is already past warmup still capture a window.
            if (not self._tracing and self._traced_iters == 0
                    and it >= p.profile_warmup):
                jax.profiler.start_trace(p.trace_dir)
                self._tracing = True
            elif self._tracing:
                self._traced_iters += 1
                if self._traced_iters >= p.trace_iters:
                    self.stop_trace()
        if not self.enabled or it < self.args.profile.profile_warmup:
            return
        if self._tracing:
            # trace instrumentation inflates step time; traced iterations
            # stay out of time_samples so filtered_time_ms (and the
            # computation profiles the search engine fits) stay clean
            return
        self._t0 = time.perf_counter()

    def stop_trace(self) -> None:
        """Idempotent; also called at loop exit so short runs still flush."""
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False

    def time_end(self, it: int, sync: Any = None) -> None:
        if self._t0 is None:
            return
        if sync is not None:
            jax.block_until_ready(sync)
        self.time_samples.append((time.perf_counter() - self._t0) * 1000.0)
        self._t0 = None

    def filtered_time_ms(self) -> float:
        """Mean after dropping >3-sigma outliers (reference
        _filtered_time_samples, runtime_profiler.py:312)."""
        if not self.time_samples:
            return 0.0
        arr = np.asarray(self.time_samples)
        mean, std = arr.mean(), arr.std()
        keep = arr[np.abs(arr - mean) <= 3 * std] if std > 0 else arr
        return float(keep.mean())

    # -- memory -------------------------------------------------------------

    def probe_memory(self, phase: str, device=None) -> None:
        if not self.enabled:
            return
        stats = device_memory_mb(device)
        if stats is not None:
            self.memory_samples[phase] = stats

    def record_static_memory(self, compiled) -> None:
        if not self.enabled:
            return
        self.memory_samples["compiled"] = compiled_memory_mb(compiled)

    # -- logging + output ---------------------------------------------------

    def iteration_log(self, it: int, metrics: Dict[str, Any],
                      lr: Optional[float] = None) -> str:
        """One line per iteration (reference runtime_profiler.py:333-370)."""
        bits = [f"iter {it}"]
        if "loss" in metrics:
            bits.append(f"loss {float(metrics['loss']):.4f}")
        if "grad_norm" in metrics:
            bits.append(f"grad-norm {float(metrics['grad_norm']):.3f}")
        if lr is not None:
            bits.append(f"lr {lr:.3e}")
        if self.time_samples:
            bits.append(f"iter-time {self.time_samples[-1]:.1f}ms")
        printing = (self.rank == 0 and self.args.logging.log_interval
                    and it % self.args.logging.log_interval == 0)
        if "moe" in metrics and printing:
            # per-layer balance tracker (reference moe_utils.py:608-644
            # track_moe_metrics log lines): aux/z-loss per MoE layer plus
            # the tokens-per-expert imbalance max/mean. Formatted only when
            # the line prints — float()/asarray() are blocking
            # device-to-host syncs that must not tax every iteration
            import numpy as _np

            for name in sorted(metrics["moe"]):
                st = metrics["moe"][name]
                tpe = _np.asarray(st["tokens_per_expert"], dtype=float)
                imb = float(tpe.max() / max(tpe.mean(), 1e-9))
                bits.append(
                    f"moe[{name}] aux {float(st['load_balance_loss']):.3e} "
                    f"z {float(st['z_loss']):.3e} imb {imb:.2f}")
        line = " | ".join(bits)
        if printing:
            print(line, flush=True)
        return line

    def computation_profile_key(self, layertype: int, bsz: int,
                                seq: int) -> str:
        return f"layertype_{layertype}_bsz{bsz}_seq{seq}"

    def save_computation_profile(self, path: str, entries: Dict[str, float]
                                 ) -> None:
        """Merge per-run timing entries into computation_profiling_*.json."""
        import json, os

        existing = {}
        if os.path.exists(path):
            existing = json.load(open(path))
        existing.update(entries)
        write_json(existing, path)

    def save_memory_profile(self, path: str, entries: Dict[str, Any]) -> None:
        import json, os

        existing = {}
        if os.path.exists(path):
            existing = json.load(open(path))
        existing.update(entries)
        write_json(existing, path)
