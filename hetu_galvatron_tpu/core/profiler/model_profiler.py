"""Model profiler: per-layer time/memory isolation via difference-of-runs.

Capability parity with the reference model profiler
(core/profiler/model_profiler.py:15-1034): sweep (layernum_min, layernum_max)
x batch sizes x sequence lengths x tp degrees x checkpoint, take differences
between the max- and min-layer runs to isolate ONE decoder layer's
time/memory, attribute the residual to the embedding/LM-head ("other"), and
write ``computation_profiling_*.json`` / ``memory_profiling_*.json`` in the
exact schema the search engine parses (profiles.py).

TPU-native: the reference launches a torchrun subprocess per grid point
(model_profiler.py:231-343); here each point is an in-process jit of the real
model — timing from executed steps, memory from XLA's own compiled
``memory_analysis`` (per-device under GSPMD partitioning), so the sweep also
runs on the virtual CPU mesh in CI.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs
from hetu_galvatron_tpu.core.profiler.runtime_profiler import (
    compiled_memory_mb,
)
from hetu_galvatron_tpu.core.search_engine.profiles import write_json
from hetu_galvatron_tpu.models.builder import (
    forward_causal_lm,
    init_causal_lm,
    param_count,
)

MB = 1024 * 1024


def _param_size_mb(params: Dict[str, Any]) -> float:
    return param_count(params) * 4 / MB  # fp32 master weights


class ModelProfiler:
    def __init__(self, args: CoreArgs, devices: Optional[Sequence] = None):
        self.args = args
        self.devices = list(devices if devices is not None else jax.devices())
        self.prof = args.model_profiler

    def _cfg(self, layernum: int, seq: int) -> ModelArgs:
        return self.args.model.model_copy(update={
            "num_hidden_layers": layernum,
            "seq_length": seq,
            "max_position_embeddings": max(
                seq, self.args.model.max_position_embeddings),
        })

    # -- computation --------------------------------------------------------

    def _forward_ms(self, cfg: ModelArgs, bsz: int,
                    warmup: int = 2, iters: Optional[int] = None) -> float:
        if iters is None:  # more reps on hardware: amortized-loop timing
            iters = 20 if self.devices[0].platform == "tpu" else 5
        params, _ = init_causal_lm(jax.random.key(0), cfg)
        tokens = jnp.zeros((bsz, cfg.seq_length), jnp.int32)
        if cfg.model_type == "t5":
            from hetu_galvatron_tpu.models.encdec import forward_encdec

            half = max(cfg.seq_length // 2, 1)
            enc = jnp.zeros((bsz, half), jnp.int32)
            dec = jnp.zeros((bsz, cfg.seq_length - half), jnp.int32)
            fwd = jax.jit(lambda p, t: forward_encdec(
                p, enc, dec, cfg, compute_dtype=jnp.bfloat16))
        else:
            fwd = jax.jit(lambda p, t: forward_causal_lm(
                p, t, cfg, compute_dtype=jnp.bfloat16))
        # Sync on a HOST TRANSFER of one output element, never
        # block_until_ready: through the axon tunnel block_until_ready has
        # been observed returning before queued dispatches executed, which
        # made per-iteration timings pure noise (sub-dispatch-latency
        # "forward times"). Queue all iters back-to-back and divide: the
        # device serializes them, so total/iters is the per-step time with
        # dispatch overhead amortized instead of sampled.
        def sync(o):
            leaf = jax.tree_util.tree_leaves(o)[0]
            return float(leaf.reshape(-1)[0].astype(jnp.float32))

        for _ in range(warmup):
            out = fwd(params, tokens)
        sync(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fwd(params, tokens)
        sync(out)
        return (time.perf_counter() - t0) * 1000.0 / iters

    def profile_computation(self) -> Dict[str, float]:
        """Per-layer + "other" forward ms per (bsz, seq) grid point
        (reference _launch_computation_profiling + process_profiled_data:
        per-layer = (run[max] - run[min]) / (max - min), residual = other)."""
        p = self.prof
        if p.profile_mode == "batch":
            bszs = list(range(p.profile_min_batch_size,
                              p.profile_max_batch_size + 1,
                              p.profile_batch_size_step))
            seqs = [p.profile_seq_length_list[0]]
        elif p.profile_mode == "sequence":
            bszs = [1]
            seqs = list(range(p.profile_min_seq_length,
                              p.profile_max_seq_length + 1,
                              p.profile_seq_length_step))
        else:
            bszs = [p.profile_batch_size]
            seqs = list(p.profile_seq_length_list)

        out: Dict[str, float] = {}
        n_min, n_max = p.layernum_min, p.layernum_max
        for seq in seqs:
            for bsz in bszs:
                t_min = self._forward_ms(self._cfg(n_min, seq), bsz)
                t_max = self._forward_ms(self._cfg(n_max, seq), bsz)
                per_layer = max((t_max - t_min) / (n_max - n_min), 0.0)
                other = max(t_min - n_min * per_layer, 0.0)
                out[f"layertype_0_bsz{bsz}_seq{seq}"] = per_layer
                out[f"layertype_other_bsz{bsz}_seq{seq}"] = other
        return out

    # -- memory -------------------------------------------------------------

    def _step_memory_mb(self, cfg: ModelArgs, bsz: int, tp: int,
                        checkpoint: bool) -> Dict[str, float]:
        """Compile a full train step under a tp x dp sharding and read XLA's
        per-device memory accounting."""
        from hetu_galvatron_tpu.parallel.spmd import make_spmd_train_step
        from hetu_galvatron_tpu.runtime.hybrid_config import (
            get_hybrid_parallel_config,
        )
        from hetu_galvatron_tpu.runtime.mesh import build_mesh
        from hetu_galvatron_tpu.runtime.optimizer import make_optimizer

        world = tp  # one tp group; dp handled analytically by the cost model
        devices = self.devices[:world]
        if len(devices) < world:
            raise ValueError(f"need {world} devices for tp={tp}")
        args = self.args.model_copy(deep=True)
        args.model = cfg
        args.parallel.global_tp_deg = tp
        args.parallel.pp_deg = 1
        args.parallel.global_checkpoint = int(checkpoint)
        args.parallel.global_train_batch_size = bsz
        hpc = get_hybrid_parallel_config(args, world)
        mesh = build_mesh(world, 1, devices=devices)
        params, axes = init_causal_lm(jax.random.key(0), cfg)
        tx = make_optimizer(self.args.train)
        step, pspecs, _, batch_shd = make_spmd_train_step(
            cfg, hpc, mesh, axes, tx, params, donate=False)
        tokens = jax.ShapeDtypeStruct((bsz, cfg.seq_length), jnp.int32)
        batch = {"tokens": tokens, "labels": tokens}
        if cfg.model_type == "t5":
            half = max(cfg.seq_length // 2, 1)
            batch = {
                "enc_tokens": jax.ShapeDtypeStruct((bsz, half), jnp.int32),
                "tokens": jax.ShapeDtypeStruct(
                    (bsz, cfg.seq_length - half), jnp.int32),
                "labels": jax.ShapeDtypeStruct(
                    (bsz, cfg.seq_length - half), jnp.int32),
            }
        pshape = jax.eval_shape(lambda: params)
        oshape = jax.eval_shape(tx.init, params)
        compiled = step.lower(pshape, oshape, batch).compile()
        return compiled_memory_mb(compiled)

    def profile_memory(self) -> Dict[str, Any]:
        """memory_profiling_*.json in search-engine schema: per-layer
        parameter_size + tp_activation_per_bsz_dict (per tp degree +
        checkpoint), and the pp-off/first/last "other" tables."""
        p = self.prof
        seq = p.profile_seq_length_list[0]
        bsz = p.profile_batch_size
        n_min, n_max = p.layernum_min, p.layernum_max
        sp_suffix = "_sp"  # GSPMD sequence sharding is always on with tp

        cfg_min, cfg_max = self._cfg(n_min, seq), self._cfg(n_max, seq)
        params_min, _ = init_causal_lm(jax.random.key(0), cfg_min)
        params_max, _ = init_causal_lm(jax.random.key(0), cfg_max)
        layer_param_mb = (_param_size_mb(params_max) -
                         _param_size_mb(params_min)) / (n_max - n_min)
        other_param_mb = _param_size_mb(params_min) - n_min * layer_param_mb

        tp_degs = []
        tp = 1
        while tp <= min(p.max_tp_deg, len(self.devices)):
            tp_degs.append(tp)
            tp *= 2

        act_per_bsz: Dict[Any, float] = {}
        other_act: Dict[Any, float] = {}
        for tp in tp_degs:
            m_min = self._step_memory_mb(cfg_min, bsz, tp, False)
            m_max = self._step_memory_mb(cfg_max, bsz, tp, False)
            per_layer = max(
                (m_max["temps"] - m_min["temps"]) / (n_max - n_min), 0.0)
            act_per_bsz[tp] = per_layer / bsz
            other_act[tp] = max(
                (m_min["temps"] - n_min * per_layer), 0.0) / bsz
        m_ck = self._step_memory_mb(cfg_max, bsz, 1, True)
        m_ck_min = self._step_memory_mb(cfg_min, bsz, 1, True)
        act_per_bsz["checkpoint"] = max(
            (m_ck["temps"] - m_ck_min["temps"]) / (n_max - n_min), 0.0) / bsz

        # other model states: embed/head params x4 (params+grads+adam) per tp
        other_states = {tp: 4 * other_param_mb / tp for tp in tp_degs}
        half = {tp: v / 2 for tp, v in other_states.items()}
        out = {
            f"layertype_0{sp_suffix}": {
                str(seq): {
                    "parameter_size": layer_param_mb,
                    "tp_activation_per_bsz_dict": act_per_bsz,
                }
            },
            f"other_memory_pp_off{sp_suffix}": {
                str(seq): {"model_states": other_states,
                           "activation": other_act}
            },
            f"other_memory_pp_on_first{sp_suffix}": {
                str(seq): {"model_states": half,
                           "activation": {k: v / 2
                                          for k, v in other_act.items()}}
            },
            f"other_memory_pp_on_last{sp_suffix}": {
                str(seq): {"model_states": half,
                           "activation": {k: v / 2
                                          for k, v in other_act.items()}}
            },
        }
        return out

    # -- entry --------------------------------------------------------------

    def run(self, output_dir: Optional[str] = None) -> Dict[str, str]:
        import os

        p = self.prof
        out_dir = output_dir or p.output_dir
        name = self.args.model.model_name.replace("/", "_")
        precision = p.mixed_precision
        paths = {}
        if p.profile_type == "computation":
            path = os.path.join(
                out_dir, f"computation_profiling_{precision}_{name}_all.json")
            write_json(self.profile_computation(), path)
            paths["computation"] = path
        else:
            path = os.path.join(
                out_dir, f"memory_profiling_{precision}_{name}_all.json")
            write_json(self.profile_memory(), path)
            paths["memory"] = path
        return paths
