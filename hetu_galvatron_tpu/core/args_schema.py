"""Pydantic argument schemas.

Capability parity with the reference's Hydra+Pydantic config stack
(core/args_schema.py:46-52, runtime/args_schema.py:344-386,
profiler/args_schema.py, search_engine/args_schema.py:65-75): a validated
`CoreArgs` tree with per-domain submodels, YAML-loadable with dotted overrides
(loader in ``core/arguments.py``). Hydra itself is not a dependency; the loader
implements the subset Galvatron uses (compose a YAML + ``key=value`` /
``++key=value`` overrides).

TPU notes: `mixed_precision` defaults to bf16 (TPU-native), there is no NCCL
backend/timeout knob — the distributed "backend" is the XLA runtime — and
device-count fields describe chips in a `jax.sharding.Mesh`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Literal, Optional

from pydantic import BaseModel, Field, field_validator, model_validator


class ModelArgs(BaseModel):
    """Architecture hyperparameters for the generic causal-LM decoder stack
    (reference models share one decoder arch parameterized by YAML —
    models/model_configs/*.yaml, runtime/models/builder.py:111-121)."""

    model_name: str = "gpt2-small"
    model_type: Literal["gpt", "llama", "bert", "t5", "moe"] = "gpt"
    hidden_size: int = 768
    num_hidden_layers: int = 12  # decoder layers (t5: decoder stack depth)
    num_encoder_layers: Optional[int] = None  # t5 only; None => same as dec
    num_attention_heads: int = 12
    num_key_value_heads: Optional[int] = None  # None => MHA
    ffn_hidden_size: Optional[int] = None  # None => 4*hidden (or 8/3 for swiglu)
    vocab_size: int = 50257
    max_position_embeddings: int = 1024
    seq_length: int = 1024
    hidden_act: Literal["gelu", "gelu_exact", "swiglu", "geglu", "relu", "silu"] = "gelu"
    normalization: Literal["layernorm", "rmsnorm"] = "layernorm"
    # None derives from the family: "post" for bert (HF BertLayer applies
    # LN after each residual; embeddings get their own LN and the final
    # norm lives in the MLM transform head), "pre" for everything else
    norm_position: Optional[Literal["pre", "post"]] = None
    layernorm_epsilon: float = 1e-5
    position_embedding_type: Literal["learned", "rope"] = "learned"
    rope_theta: float = 10000.0
    # HF-style rope_scaling dict: {"rope_type": "linear"|"llama3",
    # "factor": ..., and for llama3 "low_freq_factor"/"high_freq_factor"/
    # "original_max_position_embeddings"} — llama-3.1+ checkpoints need it
    # for >8k contexts (BASELINE milestone 5)
    rope_scaling: Optional[Dict[str, Any]] = None
    # multimodal rope (qwen2-vl style; reference rotary_pos_embedding.py):
    # the head_dim//2 frequency dims split into per-axis sections
    # (temporal, height, width); batches supply "mrope_position_ids"
    # [3, B, S]. Text-only inputs reduce exactly to standard rope.
    mrope_section: Optional[List[int]] = None
    tie_word_embeddings: bool = True
    use_flash_attn: bool = True
    # Pallas fused CE kernel for the single-device loss path (distributed
    # runs keep the GSPMD vocab-parallel CE; see modules.cross_entropy_loss)
    use_fused_ce: bool = False
    # rematerialization policy for per-layer activation checkpointing:
    # "full" recomputes everything (min memory); "dots" saves matmul outputs
    # so the backward recomputes only cheap elementwise ops (MXU FLOPs are
    # the expensive part on TPU); "dots_no_batch" saves only non-batch dots
    # (XLA's offloading-friendly middle ground)
    remat_policy: Literal["full", "dots", "dots_no_batch"] = "full"
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0
    # gemma-family numerics: RMSNorm computes x * (1 + scale) (zero-centered
    # weights), embeddings are scaled by sqrt(hidden_size), and head_dim may
    # differ from hidden/heads
    norm_zero_centered: bool = False
    scale_embeddings: bool = False
    head_dim_override: Optional[int] = None
    make_vocab_size_divisible_by: int = 128
    untie_streams: bool = False
    # MoE
    num_experts: int = 0  # 0 => dense model
    moe_topk: int = 2
    moe_ffn_hidden_size: Optional[int] = None
    num_shared_experts: int = 0
    moe_aux_loss_coeff: float = 1e-2
    moe_z_loss_coeff: float = 0.0
    moe_router_dtype: Literal["float32", "bfloat16"] = "float32"
    moe_layer_freq: int = 1  # every k-th layer is MoE
    # dispatch: "capacity" = GShard one-hot (ep-shardable, drops over-capacity
    # tokens), "dropless" = sorted ragged grouped matmuls (exact numerics,
    # reference alltoall dropless dispatcher)
    moe_dispatcher: Literal["capacity", "dropless"] = "capacity"
    moe_capacity_factor: float = 1.25
    # router: softmax topk (optionally expert-bias-corrected selection) or
    # sinkhorn load balancing (reference router.py:98)
    moe_router_type: Literal["topk", "sinkhorn"] = "topk"
    moe_router_enable_expert_bias: bool = False
    moe_expert_bias_update_rate: float = 1e-3

    @property
    def kv_heads(self) -> int:
        return self.num_key_value_heads or self.num_attention_heads

    @property
    def ffn_dim(self) -> int:
        if self.ffn_hidden_size is not None:
            return self.ffn_hidden_size
        return 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        # decoupled head dim (gemma-7b: 16 heads x 256 over hidden 3072);
        # None derives the usual hidden/heads
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.hidden_size // self.num_attention_heads

    @property
    def padded_vocab_size(self) -> int:
        m = self.make_vocab_size_divisible_by
        return ((self.vocab_size + m - 1) // m) * m

    @property
    def post_norm(self) -> bool:
        """True = residual-then-norm blocks (HF BERT layout)."""
        pos = self.norm_position or (
            "post" if self.model_type == "bert" else "pre")
        return pos == "post"

    # bias flags (HF adapter detects these per family, e.g. qwen2 qkv bias)
    add_bias_linear: bool = True
    add_qkv_bias: bool = False


class ParallelArgs(BaseModel):
    """GLOBAL-mode uniform strategy knobs + JSON-mode pointer, mirroring
    hybrid_parallel_config.py:18-130's two config modes."""

    # strategy source: 'global' (uniform knobs below) or 'json' (searched plan)
    config_mode: Literal["global", "json"] = "global"
    galvatron_config_path: Optional[str] = None
    # GLOBAL mode knobs
    pp_deg: int = 1
    global_tp_deg: int = 1
    global_tp_consec: int = 1
    global_cp_deg: int = 1
    # zigzag-balanced cp with the layout applied in the DATALOADER
    # (reference get_batch zigzag slice, utils.py:295): sequences arrive
    # pre-permuted, position ids ride the batch, and ring layers skip the
    # per-call layout reshard — the long-sequence deployment mode. Needs a
    # uniform cp degree across all layers (causal families only).
    cp_zigzag: bool = False
    global_ep_deg: int = 1  # expert parallel (MoE), carved from dp
    global_etp_deg: int = 1  # tp inside each expert
    sdp: int = 0  # 1 => force zero3 on all layers
    default_dp_type: Literal["ddp", "zero2", "zero3"] = "ddp"
    global_checkpoint: int = 0
    use_ulysses: bool = False
    vocab_tp: int = 1
    vocab_sp: int = 0
    vocab_cp: int = 1
    embed_sdp: int = 0
    # schedule
    pipeline_type: Literal["gpipe", "pipedream_flush"] = "gpipe"
    chunks: int = -1  # -1 => auto from global bsz (hybrid_parallel_config.py:359)
    # interleaved virtual stages (Megatron-style; BEYOND the reference, which
    # has no interleaved schedule): each physical stage hosts vpp
    # non-contiguous layer chunks, cutting the warmup/cooldown bubble by ~vpp
    virtual_pp_deg: int = 1
    # data
    global_train_batch_size: int = 8
    # precision
    mixed_precision: Literal["fp32", "bf16", "fp16"] = "bf16"
    # world
    num_devices: int = 0  # 0 => use every visible chip
    dp_axis_on_dcn: bool = True  # outermost dp/pp on DCN for multi-host pods
    # multi-host runtime init (reference _initialize_distributed,
    # runtime/initialize.py:114-160, reads torchrun's RANK/WORLD_SIZE; the
    # TPU equivalent is jax.distributed.initialize, auto-detecting on pods).
    # 0 processes => single-process; unset fields fall back to the
    # COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID env (launcher-set) or,
    # on Cloud TPU pods, to the metadata service autodetection.
    num_processes: int = 0
    coordinator_address: Optional[str] = None
    process_id: Optional[int] = None
    # DCN topology: number of ICI slices (pods) the job spans; >1 arranges
    # the mesh so pp + outer dp axes cross DCN and tp/cp stay ICI-local
    dcn_slices: int = 1
    # hierarchical dp/sdp gradient reduction (ops/hier_reduce.py): swap the
    # flat GSPMD dp grad all-reduce for the explicit two-level schedule —
    # reduce-scatter intra-host at full volume, all-reduce across slices on
    # the 1/k shard, all-gather back — with the slice/host split derived
    # from dcn_slices (pp-first absorption). Per-dp-lane grads accumulate
    # reduction-free through the microbatch scan, so the dp traffic is paid
    # ONCE per step instead of once per microbatch. Ineligible plans
    # (cp/ulysses/MoE/t5/dropout/non-uniform; shard_map kernels under the
    # lane vmap) fall back to the flat path with a logged reason. A
    # searched plan may also carry "hier_dp": 1 (either source enables it)
    hier_dp: bool = False
    # bucketed software pipelining of the hierarchical reduction
    # (ops/hier_reduce.py hier_bucket_layout): the concatenated grad
    # payload splits into <=hier_bucket_mb-MB buckets whose rs-intra /
    # ar-cross / ag-intra chains are emitted in wavefront order, so bucket
    # i's DCN stage overlaps bucket i±1's ICI stages — steady state
    # approaches max(sum T_ici, T_dcn) instead of their sum. 0 (default)
    # keeps today's single monolithic bucket, byte-identical program. A
    # searched plan may carry "hier_bucket_mb" (parallel setting wins when
    # nonzero); results are bit-consistent across bucket sizes (each
    # element rides the same three-collective association)
    hier_bucket_mb: float = 0.0
    # synthesized collective schedule for the hierarchical dp reduction
    # (collectives/: "ring", "tree_hd", "tree_bcast", "torus2d",
    # "hier_rings", or the "*_handbuilt" reference bodies): the reduction
    # executes through the verified emitted program instead of the
    # hand-implemented three-stage path. "" (default) = hand-implemented;
    # a searched plan may carry "dp_schedule" (parallel setting wins when
    # nonempty). Inexpressible combinations (pp > 1, bucketed pipelining,
    # non-power-of-two lanes for the tree families) fall back with a
    # logged reason — eligibility.dp_schedule_unsupported_reason
    dp_schedule: str = ""

    @model_validator(mode="after")
    def _check(self):
        if self.config_mode == "json" and not self.galvatron_config_path:
            raise ValueError("config_mode=json requires galvatron_config_path")
        if self.hier_bucket_mb < 0:
            # the <0 auto-sweep convention is SEARCH-side only
            # (search.hier_bucket_mb); the runtime needs an explicit size,
            # and a truthy negative would silently override a plan's
            # recorded bucket size into the monolithic schedule
            raise ValueError(
                "parallel.hier_bucket_mb must be >= 0 (the < 0 auto-sweep "
                "mode lives in search.hier_bucket_mb; the winning plan "
                "records the chosen size)")
        return self


class PipelineArgs(BaseModel):
    """Pipeline-schedule execution knobs (pp_deg > 1 only).

    ``schedule_impl`` selects how the 1F1B schedule executes:

    * ``host`` — the general engine (runtime/pipeline.py): one jitted GSPMD
      program per stage on its own submesh, the host sequences the schedule
      and relies on JAX async dispatch for overlap. Supports every plan
      shape (vpp interleaving, uneven pp_division, t5, MoE, ring/flash
      kernels, packed documents).
    * ``compiled`` — the single-program schedule
      (runtime/compiled_pipeline.py): the ENTIRE 1F1B step (all stages, all
      microbatches, grad accumulation, tied-embedding exchange, clip,
      optimizer update) is one donated jit over a mesh with a real ``pp``
      axis; inter-stage transfers are `lax.ppermute` collective-permutes
      XLA overlaps with compute. Plans the compiled path cannot express
      fall back to ``host`` with a logged reason.
    """

    schedule_impl: Literal["host", "compiled"] = "host"


class TpOverlapArgs(BaseModel):
    """Overlapped tensor-parallel collective knobs (``ops/overlap.py``).

    ``enable`` swaps every eligible Megatron-TP layer's four projection
    matmuls (attention qkv/out, MLP fc1/fc2) for decomposed ring
    all-gather/reduce-scatter matmuls under full-manual ``shard_map``: the
    sequence chunks `lax.ppermute` around the tp ring while each rank
    multiplies the chunk it already holds, so the transfer hides behind
    dependent compute instead of serializing against it (GSPMD's
    auto-partitioned all-gather -> matmul). Layers the path cannot express
    fall back to GSPMD with a logged ``unsupported_reason``: tp == 1,
    Ulysses (tp axes carry sequence), cp layers, tp not dividing the
    sequence/projection widths, MoE/t5 layers. The rings run under BOTH
    pipeline schedule impls — per stage submesh on the host engine, and
    as stage-stacked full-manual shard_maps (``stage_axis="pp"``) inside
    the compiled engine's fused single program (round 12's de-vmapped
    stage axis)."""

    enable: bool = False


class TrainArgs(BaseModel):
    lr: float = 1e-4
    min_lr: float = 1e-5
    weight_decay: float = 0.01
    adam_beta1: float = 0.9
    adam_beta2: float = 0.95
    adam_eps: float = 1e-8
    clip_grad: float = 1.0
    train_iters: int = 20
    lr_decay_style: Literal["constant", "linear", "cosine", "inverse-square-root", "WSD"] = (
        "cosine"
    )
    lr_warmup_iters: int = 0
    lr_decay_iters: Optional[int] = None
    lr_wsd_decay_iters: int = 0
    seed: int = 1234
    eval_interval: int = 0
    eval_iters: int = 0
    check_loss: bool = False
    deterministic_mode: bool = False
    # batch-size ramp [start, increment, ramp_samples] (reference
    # --rampup-batch-size, num_microbatches_calculator.py:193-258);
    # None = constant global batch size
    rampup_batch_size: Optional[List[int]] = None
    decrease_batch_size_if_needed: bool = False


class CheckpointArgs(BaseModel):
    save: Optional[str] = None
    load: Optional[str] = None
    save_interval: int = 0
    load_format: Literal["galvatron", "hf"] = "galvatron"
    async_save: bool = False
    distributed_checkpoint: bool = True
    # retention: keep only the newest N committed step dirs (0 = keep all);
    # partial dirs from crashed saves are garbage-collected either way
    keep_last: int = 0
    # time-based cadence alongside save_interval (seconds; 0 = step
    # cadence only): a save triggers when EITHER is due, so elastic RPO
    # is bounded in wall-clock even when steps slow down
    interval_s: float = 0.0
    # split each save into an on-step jitted device snapshot (bounded
    # stall, measured as checkpoint/snapshot_stall_ms) + a background
    # host-gather/write/commit thread (runtime/checkpoint.AsyncCheckpointer;
    # single-controller only — multi-process pods fall back to the
    # orbax async path with a logged reason)
    snapshot_async: bool = False
    # watchdog deadline for one background write: an in-flight save older
    # than this is declared hung (checkpoint/hung_saves) and the exit
    # drain stops waiting on it instead of blocking shutdown forever
    save_timeout_s: float = 120.0


class DataArgs(BaseModel):
    dataset: Literal["random", "indexed"] = "random"
    data_path: List[str] = Field(default_factory=list)
    split: str = "969,30,1"
    tokenizer_type: str = "none"
    tokenizer_path: Optional[str] = None
    num_workers: int = 0
    reset_position_ids: bool = False
    reset_attention_mask: bool = False
    eod_mask_loss: bool = False


class ProfileArgs(BaseModel):
    """Runtime-profiler switches (reference profile flags on the train run)."""

    profile: int = 0
    profile_type: Literal["memory", "computation"] = "computation"
    profile_forward: int = 0
    save_profiled_memory: int = 0
    profiler_dir: str = "configs"
    profile_iters: int = 5
    profile_warmup: int = 2
    # non-empty => capture an XLA/jax.profiler trace of iterations
    # [profile_warmup, profile_warmup + trace_iters) into this directory
    # (view with tensorboard / xprof — the TPU counterpart of the
    # reference's torch.profiler traces, profile_overlap.py:10-60)
    trace_dir: str = ""
    trace_iters: int = 3


class LoggingArgs(BaseModel):
    log_interval: int = 1
    tensorboard_dir: Optional[str] = None
    wandb_project: Optional[str] = None
    log_level: str = "info"


class ObservabilityArgs(BaseModel):
    """Unified telemetry layer knobs (``observability/``): metrics registry
    sinks, derived training stats, and the flush cadence."""

    enabled: bool = False
    # JSONL metrics file; None derives <logging.tensorboard_dir or .>/
    # metrics.jsonl at train time
    metrics_path: Optional[str] = None
    # mirror metrics into TensorBoard event files (needs tensorboardX /
    # torch; silently skipped when absent — the path CI exercises)
    tensorboard: bool = False
    flush_interval: int = 16  # steps between registry flushes
    # per-chip peak TFLOP/s override for MFU when the device_kind table
    # (observability/telemetry.py) does not know the hardware (CPU smoke
    # runs, new TPU generations); 0 = autodetect-or-skip
    peak_tflops: float = 0.0
    # predicted-vs-actual plan audit (observability/trace_analysis.py):
    # when a trace window was captured (profile.trace_dir), attribute the
    # device time and diff it against the plan's cost-model predictions at
    # loop exit, emitting audit/* gauges + the plan_audit event
    audit: bool = True
    # allreduce-bandwidth JSON (hardware_profiler output) whose fitted α-β
    # pairs price the audit's predicted collective times; None = volume-
    # only audit (no fitted hardware profile at hand)
    audit_hardware_config: Optional[str] = None
    # crash-forensics flight recorder (observability/recorder.py):
    # directory for flight_<ts>.json dumps on crash / trapped signal /
    # rerun-machine halt. None derives the metrics stream's directory
    # when observability is enabled; setting it explicitly enables the
    # recorder even with enabled=false
    flight_dir: Optional[str] = None
    flight_events: int = 256
    # self-calibrating cost model (observability/calibration.py): a
    # directory enables the loop-exit calibration pass — every plan audit
    # appends its per-curve residual points to
    # <calibration_dir>/residuals.jsonl (fingerprint-keyed, accumulated
    # across runs) and re-fits α-β curves over the accumulated points,
    # writing <calibration_dir>/calibrated_profile.json in the same key
    # namespace audit_hardware_config uses, provenance-tagged under
    # "calibration_meta" ({"source": "runtime-calibrated", per-curve
    # point counts + fit method, fit window, fingerprint}) — point
    # audit_hardware_config (or the search engine's
    # allreduce_bandwidth_config_path) at it to consume the posterior.
    # None = calibration off (audit-only, the pre-calibration behaviour)
    calibration_dir: Optional[str] = None
    # minimum accumulated points per curve before the re-fitter trusts a
    # full regression; below it a prior-anchored scale calibration (or
    # nothing, with no prior) is used instead
    calibration_min_points: int = 4
    # residual-store decay: drop accumulated points older than this many
    # days at load time (hardware changes age out of the posterior
    # instead of anchoring it forever). 0 = keep everything
    calibration_window_days: float = 0.0
    # residual-store windowing: keep at most this many NEWEST points per
    # curve key (bounds residuals.jsonl growth across long fleets).
    # 0 = unlimited
    calibration_max_points: int = 0
    # plan-regret sentinel alarm threshold, as a fraction of the
    # incumbent's adjusted step time: a plan_regret event fires when a
    # stored runner-up, re-priced under the calibrated curves, beats the
    # incumbent by more than this (the calibration/plan_regret_ms gauge
    # publishes the margin regardless)
    regret_threshold: float = 0.05


class ServingArgs(BaseModel):
    """Inference-serving engine knobs (``serving/``): continuous batching,
    paged KV cache, admission control, streaming."""

    # decode lanes: sequences decoded together at one jitted batch shape
    max_batch_size: int = 8
    # paged KV cache geometry; block 0 is reserved scratch. num_kv_blocks=0
    # derives a pool that holds max_batch_size full-length sequences
    kv_block_size: int = 16
    num_kv_blocks: int = 0
    # per-sequence cap (prompt + generation); 0 = model max positions
    max_seq_len: int = 0
    # default per-request generation budget (requests may override)
    max_new_tokens: int = 64
    # admission control: per-engine-step prefill budget, either as GFLOPs
    # (converted via the cost model's forward FLOPs/token) or a direct
    # token cap; 0 = that bound unlimited. The tighter one wins.
    prefill_flops_budget_g: float = 0.0
    max_prefill_tokens: int = 0
    # shared-prefix radix cache (serving/prefix_cache.py): cached
    # block-aligned prompt prefixes skip their prefill entirely (block
    # tables point at refcount-shared pool blocks copy-free); eviction is
    # LRU over unpinned radix nodes. prefix_cache_max_blocks caps how many
    # blocks the tree may hold (0 = bounded only by the pool)
    prefix_cache: bool = False
    prefix_cache_max_blocks: int = 0
    # lossless speculative decoding (serving/spec_decode.py): draft
    # spec_k tokens per lane per step and verify them in one batched
    # [max_batch_size, spec_k+1] pass — greedy streams stay bit-identical
    # to plain decode. spec_draft picks the draft provider: "ngram"
    # (prompt-lookup, free) or "model" (a small draft checkpoint passed
    # to ServingEngine via draft_params/draft_cfg)
    spec_decode: bool = False
    spec_k: int = 4
    spec_draft: Literal["ngram", "model"] = "ngram"
    spec_ngram_max: int = 3
    spec_ngram_min: int = 1
    # sampling defaults (per-request temperature/eos override these);
    # top_k is engine-static (shapes the jitted sampler)
    temperature: float = 0.0
    top_k: Optional[int] = None
    eos_id: Optional[int] = None
    # retire requests older than this many seconds (0 = no deadline)
    request_timeout_s: float = 0.0
    # registry flush cadence, in engine steps
    flush_interval: int = 32
    # JSONL metrics file for cli/serve.py; None derives ./serve_metrics.jsonl
    metrics_path: Optional[str] = None
    # Prometheus text endpoint (observability/prometheus.py) exposing the
    # serve/* registry metrics over stdlib HTTP: None = off (default),
    # 0 = bind an ephemeral port (tests; the engine records the bound
    # port), N = bind that port
    metrics_port: Optional[int] = None
    # bind address for the endpoint; loopback by default — the endpoint
    # is unauthenticated, so exposing it (0.0.0.0) is an explicit choice
    metrics_host: str = "127.0.0.1"
    # per-request lifecycle tracing (observability/events.py): structured
    # submit/admit/prefill/decode/retire events with a stable request id,
    # written through the metrics sinks; cli/summarize.py rebuilds
    # timelines and the TTFT component breakdown. Off by default — the
    # JSONL stream grows per token when on
    trace_requests: bool = False
    # SLO targets in milliseconds (0 = none): when set, the engine
    # exports serve/slo_ttft_attainment / serve/slo_itl_attainment
    # gauges (share of observations inside the target)
    slo_ttft_ms: float = 0.0
    slo_itl_ms: float = 0.0
    # crash-forensics flight recorder (observability/recorder.py):
    # directory for flight_<ts>.json dumps on a fatal engine error; None
    # keeps the in-memory ring only (no artifact)
    flight_dir: Optional[str] = None
    flight_events: int = 256


class RerunArgs(BaseModel):
    """Fault-detection state machine knobs (reference rerun_state_machine.py)."""

    enable: bool = False
    mode: Literal[
        "disabled", "validate_results", "report_stats"
    ] = "disabled"
    error_injection_rate: float = 0.0
    error_injection_type: Literal[
        "transient_error", "persistent_error", "correct_result"
    ] = "transient_error"
    check_for_nan: bool = True
    check_for_spike: bool = True
    spike_factor: float = 10.0
    # deterministic at-step-k fault drills (runtime/rerun_machine.FaultDrill):
    # corrupt ("nan"/"spike"), crash ("crash" raises InjectedCrash), or
    # preempt ("preempt" delivers a real SIGTERM) exactly once, at
    # inject_at_iter, on fresh (non-resumed) runs
    inject_kind: Literal["none", "nan", "spike", "crash", "preempt"] = "none"
    inject_at_iter: int = -1
    inject_spike_scale: float = 100.0

    @field_validator("inject_kind", mode="before")
    @classmethod
    def _nan_is_a_name_here(cls, v):
        # the YAML override parser reads a bare `inject_kind=nan` as float
        # NaN; in this field it names the drill kind
        import math

        if isinstance(v, float) and math.isnan(v):
            return "nan"
        return v


class ChaosArgs(BaseModel):
    """Seeded fault-injection harness knobs (runtime/chaos.py) — the
    generalization of ``rerun.inject_kind`` from one at-step drill to a
    fault PLAN driven through the real process supervisor."""

    enable: bool = False
    # JSON fault-plan file ({"seed": n, "faults": [{"kind", "at_iter",
    # ...}, ...]}); wins over the inline kind/at_iter pair below
    plan: Optional[str] = None
    # inline single-fault plan (the chaos matrix cases):
    #   crash         — raise InjectedCrash at the step boundary
    #   sigterm       — deliver a real SIGTERM mid-step (preempt path)
    #   sigkill       — SIGKILL the process mid-step (no cleanup at all)
    #   kill_mid_save — SIGKILL from inside the save's pre-commit hook
    #                   (torn staging dir, no COMMITTED marker)
    #   hung_save     — stall the pre-commit hook past the watchdog
    #   corrupt_meta  — overwrite the newest commit's meta.json with junk
    #   truncate_meta — truncate the newest commit's meta.json mid-record
    #   io_error      — transient OSErrors through utils/retrying.py
    kind: Literal["none", "crash", "sigterm", "sigkill", "kill_mid_save",
                  "hung_save", "corrupt_meta", "truncate_meta",
                  "io_error"] = "none"
    at_iter: int = -1
    seed: int = 0
    # io_error: how many injected failures before the op succeeds (must
    # stay under the retry attempt budget to model a TRANSIENT fault)
    io_error_count: int = 2
    # io_error: only retry ops whose label contains this substring are
    # targeted ("" = every op)
    io_error_op: str = "checkpoint"
    # hung_save: how long the pre-commit hook stalls
    hang_s: float = 5.0
    # cross-process one-shot markers (CHAOS_FIRED_<i>) live here so a
    # fault does not re-fire on the relaunched attempt; None derives
    # ckpt.save (the dir that already survives the process boundary)
    state_dir: Optional[str] = None


class SupervisorArgs(BaseModel):
    """Preemption/restart supervisor knobs (runtime/supervisor.py)."""

    # trap SIGTERM/SIGINT and checkpoint-and-exit at the next step boundary
    graceful_signals: bool = True
    # wrap the training attempt in run_with_restarts: restartable exit
    # codes (16 resume-to-disambiguate, 18 preempted) and crashes resume
    # from the last committed checkpoint; code 17 surfaces immediately
    auto_restart: bool = False
    max_restarts: int = 3
    backoff_base_s: float = 1.0
    backoff_max_s: float = 60.0
    restart_on_error: bool = True
    # how the restart loop runs (only with auto_restart):
    #   inprocess — run_with_restarts re-invokes train() in THIS process
    #               (drills; world/device list frozen at backend init)
    #   process   — cli/supervise.py relaunches train_dist as a child
    #               process per attempt (production: exit codes, restart
    #               budget, RESUME_PIN and world changes are real across
    #               the process boundary)
    mode: Literal["inprocess", "process"] = "inprocess"
    # process mode: SIGTERM forwarded to the child escalates to SIGKILL
    # after this grace window (Cloud TPU preemption grants ~30s total;
    # the supervisor must leave headroom for its own shutdown)
    term_grace_s: float = 15.0
    # process mode: tmp+rename-atomic supervisor state file (attempt
    # count, restart budget, world-change budget, last-commit receipt);
    # None derives <ckpt.save>/SUPERVISOR_STATE.json
    state_file: Optional[str] = None
    # process mode: how many observed topology changes may reset the
    # restart budget before a flapping fleet stops counting as progress
    max_world_changes: int = 8
    # process mode: serve supervisor liveness on /healthz (+/metrics);
    # -1 = off, 0 = ephemeral port (logged), >0 = fixed port
    metrics_port: int = -1
    # process mode: child poll + commit-receipt refresh cadence
    poll_interval_s: float = 0.5


class SearchArgs(BaseModel):
    """Search-engine knobs (reference search_engine/args_schema.py:65-75)."""

    num_nodes: int = 1
    num_devices_per_node: int = 8
    memory_constraint: float = 16.0  # GB of HBM budget per chip
    min_bsz: int = 8
    max_bsz: int = 64
    bsz_scale: int = 8
    settle_bsz: int = -1  # >0 => search exactly this global bsz
    settle_chunks: int = -1
    search_space: Literal["full", "dp+tp", "dp+pp", "3d", "dp", "tp", "pp", "sdp"] = "full"
    disable_dp: int = 0
    disable_tp: int = 0
    disable_pp: int = 0
    disable_sdp: int = 0  # alias: disable_fsdp (zero3)
    disable_ckpt: int = 0
    disable_tp_consec: int = 1  # non-consecutive tp rarely wins on ICI
    disable_cp: int = 1
    disable_ulysses: int = 0  # alias: disable_sp
    disable_vtp: int = 0
    disable_vsp: int = 0
    max_tp_deg: int = 8
    max_pp_deg: int = 8
    max_sp_deg: int = 8
    max_cp_deg: int = 8
    sequence_parallel: bool = True  # Megatron-SP assumed on with TP
    global_memory_buffer: bool = True
    async_grad_reduce: bool = True
    time_profile_mode: Literal["static", "batch", "sequence"] = "static"
    memory_profile_mode: Literal["static", "batch", "sequence"] = "static"
    default_dp_type: Literal["ddp", "zero2", "zero3"] = "ddp"
    fine_grained_mode: int = 1
    sequence_parallel_mode: Literal["megatron", "ulysses"] = "megatron"
    pipeline_type: Literal["gpipe", "pipedream_flush"] = "pipedream_flush"
    mixed_precision: Literal["bf16", "fp32"] = "bf16"
    use_cpp_core: bool = True
    parallel_search: bool = False
    log_dir: str = "logs"
    # non-empty => append one JSONL record per explored (bsz, chunks, pp,
    # mode, tp-cap) task + the winning plan, so search decisions are
    # auditable after the fact (observability/sinks.py schema)
    search_trace_path: Optional[str] = None
    output_config_path: Optional[str] = None
    # profiled-data locations
    time_profiling_path: Optional[str] = None
    memory_profiling_path: Optional[str] = None
    allreduce_bandwidth_config_path: Optional[str] = None
    # auto-feed the calibration loop's posterior
    # (observability.calibration_dir/calibrated_profile.json) into the
    # search: when the calibrated profile exists and its fingerprint
    # matches this search's hardware/model key, it is preferred over
    # allreduce_bandwidth_config_path with a logged provenance line.
    # 0 opts out (profiled-priors-only, the pre-PR-16 behaviour)
    use_calibrated: int = 1
    p2p_bandwidth_config_path: Optional[str] = None
    overlap_coe_path: Optional[str] = None
    sp_time_path: Optional[str] = None
    sequence_length: Optional[int] = None
    costmodel_coe: float = 1.0
    # Host-dispatch overhead pricing (tools/pipeline_dispatch_bench.py):
    # one already-compiled stage-jit call costs ~dispatch_us of host wall
    # time, and the host-sequenced schedule pays 2 (fwd+bwd) * pp * chunks
    # of them per step. The compiled schedule (pipeline.schedule_impl=
    # compiled) pays none, so the search prices pp differently per impl —
    # cranking dispatch_us pushes the host-impl search away from deep pp.
    dispatch_us: float = 0.0
    pipeline_schedule_impl: Literal["host", "compiled"] = "host"
    # Static HBM gate (analysis/memory_doctor.py): > 0 prunes candidate
    # plans whose statically-accounted per-device peak exceeds this many
    # GB — the EXACT predicate `cli/check.py --memory --hbm-gb` applies
    # to plan JSONs (search == check parity), evaluated on the analytic
    # model shapes rather than the profiled memory the DP knapsack uses.
    # 0 (default) keeps the search's profiled-memory-only behavior.
    # Needs the searcher to know the model config (SearchEngine
    # model_cfg; cli/search_dist.py passes it).
    hbm_budget_gb: float = 0.0
    # Overlapped-TP pricing (ops/overlap.py + the α-β collective model):
    # 1 prices eligible Megatron-TP layers with the max(comm, compute)-style
    # overlap discount (cost_model/cost.py layer_time_cost), mirroring a
    # runtime that sets tp_overlap.enable. The α (latency) term itself is
    # independent: it activates whenever the allreduce-bandwidth JSON
    # carries fitted alpha/beta keys (hardware_profiler.profile_alpha_beta)
    # and falls back to the legacy latency tables otherwise, so legacy
    # profiles reproduce golden costs exactly.
    tp_overlap: int = 0
    # Hierarchical dp gradient-reduction pricing (ops/hier_reduce.py + the
    # per-algorithm/per-level α-β curves): 1 prices eligible candidates'
    # dp term as min(flat overlapped ring, hierarchical rs-intra +
    # ar-cross-on-shard + ag-intra) using the per-level fitted curves
    # (hardware_profiler.profile_alpha_beta_algos). Without per-level
    # curves in the bandwidth JSON the hierarchical term is unavailable
    # and every golden cost stays byte-identical. The winning plan records
    # "hier_dp": 1 when the hierarchical term priced its dp reduction.
    hier_dp: int = 0
    # Bucketed software-pipelining granularity for the hierarchical dp
    # pricing (cost_model.cost.hier_dp_reduce_ms): > 0 prices the
    # pipelined schedule at that bucket size (fill-drain: first bucket
    # pays the full rs+ar+ag chain, the rest pay the bottleneck stage —
    # per-bucket α overhead vs overlap win); < 0 sweeps power-of-two
    # bucket sizes (1..64 MB) and records the argmin in the winning plan
    # ("hier_bucket_mb"); 0 keeps the monolithic three-collective price,
    # byte-identical goldens.
    hier_bucket_mb: float = 0.0
    # Plan-regret sentinel support (observability/calibration.py): embed
    # this many runner-up candidates — the feasible plans the search
    # almost picked, deduped + throughput-ordered, each with its priced
    # time_cost_ms and per-layer degrees — in the winning plan JSON as
    # "runner_ups" (plus the winner's own "predicted_time_cost_ms").
    # config2strategy ignores the extra keys; 0 disables the embedding.
    runner_up_k: int = 3


class ModelProfileArgs(BaseModel):
    """Model-profiler sweep description (reference profiler/args_schema.py)."""

    profile_type: Literal["computation", "memory"] = "computation"
    profile_mode: Literal["static", "batch", "sequence"] = "static"
    profile_batch_size: int = 1
    profile_min_batch_size: int = 1
    profile_max_batch_size: int = 8
    profile_batch_size_step: int = 1
    profile_seq_length_list: List[int] = Field(default_factory=lambda: [1024])
    profile_min_seq_length: int = 1024
    profile_max_seq_length: int = 8192
    profile_seq_length_step: int = 1024
    layernum_min: int = 2
    layernum_max: int = 4
    max_tp_deg: int = 8
    profile_dp_type: Literal["ddp", "zero2", "zero3"] = "ddp"
    mixed_precision: Literal["bf16", "fp32"] = "bf16"
    use_flash_attn: bool = True
    output_dir: str = "configs"
    extra_args_str: str = ""


class HardwareProfileArgs(BaseModel):
    """Hardware-profiler knobs: ICI/DCN collective microbenchmarks replacing the
    reference's NCCL benchmarks (profile_hardware/*, hardware_profiler.py)."""

    num_nodes: int = 1
    num_devices_per_node: int = 8
    max_pp_deg: int = 8
    max_tp_deg: int = 8
    start_mb: int = 1
    end_mb: int = 512
    scale: int = 2
    # smallest sub-MB all-reduce point (KB) for the α-β latency fit
    # (profile_sp_time 'sub_' keys + profile_alpha_beta); layer-wise TP
    # messages live in this regime, where the α term dominates
    sub_mb_floor_kb: int = 64
    # per-algorithm / per-level fits (profile_alpha_beta_algos): benchmark
    # ring vs recursive halving-doubling shaped schedules over ICI and
    # DCN-proxy groups and fit distinct (α, β) pairs per
    # (size, algorithm, level) — the cost model then prices each
    # collective as the min over available curves. 0 skips the sweep
    # (legacy-sized profiling runs)
    profile_algos: int = 1
    warmup_iters: int = 5
    profile_iters: int = 20
    avg_or_min_or_first: Literal["avg", "min", "first"] = "avg"
    output_dir: str = "hardware_configs"
    backend: Literal["auto", "tpu", "cpu"] = "auto"


class CoreArgs(BaseModel):
    """Top-level validated argument tree (reference core/args_schema.py:46)."""

    mode: Literal["train_dist", "search", "model_profiler", "profile_hardware"] = (
        "train_dist"
    )
    model: ModelArgs = Field(default_factory=ModelArgs)
    parallel: ParallelArgs = Field(default_factory=ParallelArgs)
    pipeline: PipelineArgs = Field(default_factory=PipelineArgs)
    tp_overlap: TpOverlapArgs = Field(default_factory=TpOverlapArgs)
    train: TrainArgs = Field(default_factory=TrainArgs)
    ckpt: CheckpointArgs = Field(default_factory=CheckpointArgs)
    data: DataArgs = Field(default_factory=DataArgs)
    profile: ProfileArgs = Field(default_factory=ProfileArgs)
    logging: LoggingArgs = Field(default_factory=LoggingArgs)
    observability: ObservabilityArgs = Field(default_factory=ObservabilityArgs)
    serving: ServingArgs = Field(default_factory=ServingArgs)
    rerun: RerunArgs = Field(default_factory=RerunArgs)
    chaos: ChaosArgs = Field(default_factory=ChaosArgs)
    supervisor: SupervisorArgs = Field(default_factory=SupervisorArgs)
    search: SearchArgs = Field(default_factory=SearchArgs)
    model_profiler: ModelProfileArgs = Field(default_factory=ModelProfileArgs)
    hardware_profiler: HardwareProfileArgs = Field(default_factory=HardwareProfileArgs)
    extra: Dict[str, Any] = Field(default_factory=dict)
