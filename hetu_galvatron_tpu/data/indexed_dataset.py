"""Memory-mapped indexed token dataset (Megatron-style .bin/.idx pair).

Capability parity with the reference dataset stack (runtime/datasets/megatron/
indexed_dataset.py:506 ``IndexedDataset``, gpt_dataset.py:65 ``GPTDataset``,
helpers.cpp sample builders, blended_megatron_dataset_builder.py:39): a
binary token file + document-offset index read via numpy memmap, a GPT-style
sample view that concatenates documents into fixed-length training samples,
and a blended multi-corpus wrapper. The sample mapping is built by the C++
helper (csrc/dataset_helpers.cpp, lazily compiled + ctypes-bound exactly like
the DP core) with a numpy fallback.

File format (ours, versioned): ``<name>.bin`` is raw little-endian token ids;
``<name>.idx`` holds a header (magic/version/dtype/doc count) followed by
int64 document offsets (in tokens). A converter from token iterators is
provided for corpus preparation.
"""

from __future__ import annotations

import ctypes
import struct
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from hetu_galvatron_tpu.utils.native import load_native

_MAGIC = b"HGTPUIDX"
_VERSION = 1
_DTYPES = {1: np.uint16, 2: np.int32, 3: np.int64}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def _configure(lib: ctypes.CDLL) -> None:
    lib.build_sample_idx.restype = ctypes.c_int64
    lib.build_sample_idx.argtypes = [
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
    ]


def _load_helpers():
    return load_native("libdataset_helpers.so", "dataset_helpers.cpp",
                       _configure)


def build_sample_idx(doc_lens: np.ndarray, seq_len: int,
                     num_samples: int) -> np.ndarray:
    """[num_samples, 2] (doc index, in-doc offset) per sample start; C++
    helper when available, vectorized numpy otherwise."""
    doc_lens = np.ascontiguousarray(doc_lens, np.int64)
    lib = _load_helpers()
    if lib is not None:
        out_doc = np.empty((num_samples,), np.int64)
        out_off = np.empty((num_samples,), np.int64)
        n = lib.build_sample_idx(doc_lens, len(doc_lens), seq_len,
                                 num_samples, out_doc, out_off)
        return np.stack([out_doc[:n], out_off[:n]], axis=1)
    ends = np.cumsum(doc_lens)
    total = int(ends[-1]) if len(ends) else 0
    starts_tok = np.arange(num_samples, dtype=np.int64) * seq_len
    starts_tok = starts_tok[starts_tok + seq_len + 1 <= total]
    doc = np.searchsorted(ends, starts_tok, side="right")
    doc_start = np.concatenate([[0], ends[:-1]])
    return np.stack([doc, starts_tok - doc_start[doc]], axis=1)


def write_indexed_dataset(
    prefix: str, documents: Iterable[Sequence[int]],
    dtype=np.int32,
) -> Dict[str, int]:
    """Token documents -> <prefix>.bin/.idx (corpus-prep utility; the
    reference ships external preprocess scripts for this)."""
    dtype = np.dtype(dtype)
    offsets: List[int] = [0]
    count = 0
    with open(prefix + ".bin", "wb") as f:
        for doc in documents:
            arr = np.asarray(doc, dtype=dtype)
            arr.tofile(f)
            count += arr.size
            offsets.append(count)
    with open(prefix + ".idx", "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<HHq", _VERSION, _DTYPE_CODES[dtype],
                            len(offsets) - 1))
        np.asarray(offsets, np.int64).tofile(f)
    return {"documents": len(offsets) - 1, "tokens": count}


class IndexedDataset:
    """mmap view over a .bin/.idx pair (reference IndexedDataset,
    indexed_dataset.py:506)."""

    def __init__(self, prefix: str):
        with open(prefix + ".idx", "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{prefix}.idx: bad magic {magic!r}")
            version, dtype_code, num_docs = struct.unpack("<HHq", f.read(12))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            self.dtype = np.dtype(_DTYPES[dtype_code])
            self.offsets = np.fromfile(f, np.int64, num_docs + 1)
        self.tokens = np.memmap(prefix + ".bin", dtype=self.dtype, mode="r")
        self.num_docs = num_docs

    def __len__(self) -> int:
        return self.num_docs

    @property
    def total_tokens(self) -> int:
        return int(self.offsets[-1])

    @property
    def doc_lens(self) -> np.ndarray:
        return np.diff(self.offsets)

    def get_doc(self, i: int) -> np.ndarray:
        return np.asarray(self.tokens[self.offsets[i]:self.offsets[i + 1]])

    def get_span(self, doc: int, offset: int, length: int) -> np.ndarray:
        """`length` tokens starting at (doc, offset), crossing document
        boundaries (GPT concatenated-stream semantics)."""
        start = int(self.offsets[doc] + offset)
        return np.asarray(self.tokens[start:start + length])


class GPTDataset:
    """Fixed-length sample view with a per-epoch reshuffled sample order
    (reference GPTDataset builds an epoch-aware shuffle_idx,
    gpt_dataset.py:65): index i in epoch e = i // len uses a permutation
    seeded by (seed, e), so multi-epoch runs never repeat batch order."""

    def __init__(self, indexed: IndexedDataset, seq_length: int,
                 seed: int = 1234, shuffle: bool = True,
                 doc_range: Optional[tuple] = None):
        """``doc_range`` (lo, hi) restricts the view to a contiguous slice
        of documents — the train/valid/test split unit (reference split
        matrix, blended_megatron_dataset_builder.py:39). A document range is
        a contiguous token span in the .bin stream, so sample spans built
        from the subset's doc_lens never cross into another split."""
        self.indexed = indexed
        self.seq_length = seq_length
        self.seed = seed
        self.shuffle = shuffle
        lo, hi = doc_range if doc_range is not None else (0, len(indexed))
        if not (0 <= lo <= hi <= len(indexed)):
            raise ValueError(f"doc_range {doc_range} outside "
                             f"[0, {len(indexed)}]")
        self._doc_lo = lo
        doc_lens = indexed.doc_lens[lo:hi]
        total = int(doc_lens.sum())
        max_samples = max((total - 1) // seq_length, 0)
        self.sample_idx = build_sample_idx(
            np.ascontiguousarray(doc_lens), seq_length, max_samples)
        self._epoch = -1
        self._order = np.arange(len(self.sample_idx))

    def __len__(self) -> int:
        return len(self.sample_idx)

    def _order_for(self, epoch: int) -> np.ndarray:
        if epoch != self._epoch:
            order = np.arange(len(self.sample_idx))
            if self.shuffle:
                np.random.RandomState(self.seed + epoch).shuffle(order)
            self._epoch, self._order = epoch, order
        return self._order

    def __getitem__(self, i: int) -> np.ndarray:
        n = max(len(self), 1)
        order = self._order_for(i // n)
        doc, off = self.sample_idx[order[i % n]]
        return self.indexed.get_span(int(doc) + self._doc_lo, int(off),
                                     self.seq_length + 1).astype(np.int32)


class BlendedDataset:
    """Sample-proportional blend of several GPTDatasets (reference
    BlendedMegatronDatasetBuilder, blended_megatron_dataset_builder.py:39)."""

    def __init__(self, datasets: Sequence[GPTDataset],
                 weights: Optional[Sequence[float]] = None, seed: int = 1234):
        if not datasets:
            raise ValueError("empty dataset blend")
        self.datasets = list(datasets)
        w = np.asarray(weights if weights is not None
                       else [len(d) for d in self.datasets], np.float64)
        self.weights = w / w.sum()
        rng = np.random.RandomState(seed)
        self._picks = rng.choice(len(self.datasets), size=65536,
                                 p=self.weights)
        # prefix counts make access stateless: within-dataset index of pick
        # table position i is how many earlier picks chose the same dataset
        onehot = self._picks[:, None] == np.arange(len(self.datasets))[None]
        cum = np.cumsum(onehot, axis=0)
        self._within = cum[np.arange(len(self._picks)), self._picks] - 1
        self._per_cycle = cum[-1]

    def __len__(self) -> int:
        return sum(len(d) for d in self.datasets)

    def __getitem__(self, i: int) -> np.ndarray:
        """Deterministic: the same i always yields the same sample."""
        cycle, pos = divmod(i, len(self._picks))
        d = int(self._picks[pos])
        idx = cycle * int(self._per_cycle[d]) + int(self._within[pos])
        return self.datasets[d][idx]


def split_doc_ranges(n_docs: int, split: str) -> List[tuple]:
    """Partition ``n_docs`` documents into train/valid/test ranges by the
    comma-separated ratio string (reference --split '969,30,1',
    blended_megatron_dataset_builder.py:39). Ratios are normalized; a zero
    ratio yields an empty range. Boundaries round so every doc lands in
    exactly one split."""
    ratios = [float(x) for x in str(split).split(",")]
    if len(ratios) != 3 or any(r < 0 for r in ratios) or sum(ratios) <= 0:
        raise ValueError(
            f"data.split must be three non-negative ratios, got {split!r}")
    total = sum(ratios)
    bounds = [0]
    acc = 0.0
    for r in ratios:
        acc += r
        bounds.append(int(round(n_docs * acc / total)))
    bounds[-1] = n_docs
    return [(bounds[i], bounds[i + 1]) for i in range(3)]


def indexed_batches(prefix_or_paths, seq_length: int, global_batch_size: int,
                    *, seed: int = 1234,
                    weights: Optional[Sequence[float]] = None,
                    split: Optional[str] = None,
                    split_index: int = 0,
                    shuffle: bool = True,
                    ) -> Iterator[Dict[str, np.ndarray]]:
    """Batch iterator over (blended) indexed corpora matching the synthetic
    iterator's contract (dataloader.get_data_iterator). ``split`` +
    ``split_index`` select the train(0)/valid(1)/test(2) document range of
    each corpus (reference get_train_valid_test_data_iterators,
    runtime/dataloader.py:462); evaluation streams pass ``shuffle=False``
    so held-out loss is computed over a stable batch order."""
    from hetu_galvatron_tpu.runtime.dataloader import make_batch

    paths = ([prefix_or_paths] if isinstance(prefix_or_paths, str)
             else list(prefix_or_paths))
    ds_list = []
    for p in paths:
        idx = IndexedDataset(p)
        rng = (split_doc_ranges(len(idx), split)[split_index]
               if split is not None else None)
        ds_list.append(GPTDataset(idx, seq_length, seed=seed,
                                  shuffle=shuffle, doc_range=rng))
    ds = (ds_list[0] if len(ds_list) == 1
          else BlendedDataset(ds_list, weights=weights, seed=seed))
    if len(ds) == 0:
        # raised EAGERLY (not from the generator's first next()) so callers
        # can degrade an empty eval split before spending any training time
        name = {0: "train", 1: "valid", 2: "test"}.get(split_index, "?")
        raise ValueError(
            f"indexed corpus {name} split smaller than one sample "
            f"(split={split!r}; grow the corpus or the split ratio)")

    def gen():
        i = 0
        while True:
            rows = [ds[i * global_batch_size + j]
                    for j in range(global_batch_size)]
            yield make_batch(np.stack(rows))
            i += 1

    return gen()
