"""S3-backed corpus prefixes: download-once local caching.

Capability parity with the reference's S3 indexed-dataset support
(runtime/datasets/megatron/indexed_dataset.py:506 ``S3 path detection`` +
object_storage_utils cache_dir download): an ``s3://bucket/key`` corpus
prefix is localized by downloading ``<prefix>.idx`` / ``<prefix>.bin``
(and the optional ``<prefix>.meta.json`` tokenizer sidecar) into a local
cache, after which the mmap dataset machinery runs unchanged — TPU VMs
read training shards from GCS/S3 exactly this way.

The client is injected (anything with ``download_file(bucket, key, path)``)
so tests run without boto3; the default client requires boto3 at call time
with an actionable error (this image does not bundle it).
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

from hetu_galvatron_tpu.utils.retrying import retry_call

_SCHEME = "s3://"

# per-object download attempts for transient failures (throttling, 5xx,
# connection resets); 404-class absence never retries. Override via env
# for flaky links (HGTPU_S3_RETRIES) — backoff is jittered exponential
# from the shared utils/retrying policy.
def _fetch_attempts() -> int:
    return max(int(os.environ.get("HGTPU_S3_RETRIES", "3")), 1)


def is_object_path(path: str) -> bool:
    return str(path).startswith(_SCHEME)


def _default_cache_dir() -> str:
    return os.environ.get(
        "HGTPU_OBJECT_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "hetu_galvatron_tpu", "s3"))


def _default_client():
    try:
        import boto3
    except ImportError as e:
        raise RuntimeError(
            "s3:// data paths need boto3 (not bundled in this image): "
            "pip install boto3, or pre-download the corpus and point "
            "data.data_path at the local prefix") from e
    return boto3.client("s3")


_ABSENT_MARKERS = ("nosuchkey", "nosuchbucket", "not found", "404")
_ABSENT_CODES = {"404", "NoSuchKey", "NoSuchBucket"}


def _is_absent_error(e: Exception) -> bool:
    """Whether a client exception means 'object does not exist' (the only
    error an OPTIONAL file may swallow — a throttle/auth failure on the
    meta sidecar must not silently disable eod masking / vocab checks).

    boto3 ``ClientError``s are classified STRUCTURALLY via
    ``e.response['Error']['Code']``; other botocore exceptions (connection
    / endpoint failures, whose stringification can accidentally contain
    'not found' — e.g. DNS 'host not found') are never absence. The string
    heuristic survives only for injected test clients that raise plain
    exceptions."""
    resp = getattr(e, "response", None)
    if isinstance(resp, dict) and isinstance(resp.get("Error"), dict):
        return str(resp["Error"].get("Code", "")) in _ABSENT_CODES
    if type(e).__module__.partition(".")[0] in ("botocore", "boto3"):
        return False  # structured error without an absence code: transient
    return any(m in f"{type(e).__name__}: {e}".lower()
               for m in _ABSENT_MARKERS)


def _validate_pair(local_prefix: str) -> bool:
    """The cached .idx/.bin must be the SAME corpus version: the index's
    declared token count times the dtype width must equal the bin size
    (a crash between the two atomic renames, or a re-uploaded remote,
    could otherwise pair an old index with a new bin)."""
    import struct

    import numpy as np

    from hetu_galvatron_tpu.data.indexed_dataset import _DTYPES, _MAGIC

    try:
        with open(local_prefix + ".idx", "rb") as f:
            if f.read(len(_MAGIC)) != _MAGIC:
                return False
            _, dtype_code, num_docs = struct.unpack("<HHq", f.read(12))
            offsets = np.fromfile(f, np.int64, num_docs + 1)
        expect = int(offsets[-1]) * np.dtype(_DTYPES[dtype_code]).itemsize
        return os.path.getsize(local_prefix + ".bin") == expect
    except (OSError, KeyError, struct.error, IndexError):
        return False


def localize_prefix(prefix: str, cache_dir: Optional[str] = None,
                    client=None) -> str:
    """``s3://bucket/path/corpus`` -> local cached prefix. Downloads
    ``.idx`` and ``.bin`` (required) plus ``.meta.json`` (optional) once;
    subsequent calls hit the cache (and need no client at all). Downloads
    land in a temp file and are renamed atomically; the .idx/.bin pair is
    size-validated together, with one purge-and-refetch on mismatch."""
    if not is_object_path(prefix):
        return prefix
    rest = prefix[len(_SCHEME):]
    if "/" not in rest:
        raise ValueError(f"malformed s3 prefix {prefix!r} "
                         "(want s3://bucket/key)")
    bucket, key = rest.split("/", 1)
    cache_dir = cache_dir or _default_cache_dir()
    local_prefix = os.path.join(cache_dir, bucket, key)
    os.makedirs(os.path.dirname(local_prefix), exist_ok=True)

    def get_client():
        nonlocal client
        if client is None:
            # lazy: a fully-warmed cache must work without boto3
            client = _default_client()
        return client

    def fetch(ext: str, required: bool) -> None:
        target = local_prefix + ext
        if os.path.exists(target):
            return
        if not required and os.path.exists(target + ".absent"):
            # negatively-cached 404: a meta-less corpus with a warm
            # .idx/.bin cache must not construct an S3 client (and demand
            # boto3 + network) on every startup just to re-confirm absence
            return
        cl = get_client()  # outside the try: a missing-boto3 RuntimeError
        # must surface as itself, not as a fetch failure
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(target),
                                   prefix=".dl_")
        os.close(fd)
        try:
            # transient errors (throttling, 5xx, resets) retry with
            # jittered backoff; absence (404/NoSuchKey) is permanent and
            # fails fast so the required/optional branches below classify
            # the ORIGINAL error, not a retry-exhaustion wrapper
            retry_call(
                lambda: cl.download_file(bucket, key + ext, tmp),
                attempts=_fetch_attempts(), base=0.2, cap=5.0,
                retryable=lambda e: not _is_absent_error(e),
                op="object_store.fetch")
        except Exception as e:  # noqa: BLE001 — client-specific error types
            os.unlink(tmp)
            if required:
                raise FileNotFoundError(
                    f"failed to fetch {prefix}{ext} from object storage: "
                    f"{e}") from e
            if not _is_absent_error(e):
                raise RuntimeError(
                    f"transient error fetching optional {prefix}{ext}: "
                    f"{e} — refusing to silently run without the "
                    "tokenizer sidecar") from e
            with open(target + ".absent", "w") as f:
                f.write("confirmed absent; delete to re-probe\n")
            return
        os.replace(tmp, target)

    for attempt in range(2):
        for ext, required in ((".idx", True), (".bin", True),
                              (".meta.json", False)):
            fetch(ext, required)
        if _validate_pair(local_prefix):
            break
        if attempt == 1:
            raise ValueError(
                f"cached {local_prefix}.idx/.bin disagree on corpus size "
                "even after refetch; clear the cache dir and check the "
                "remote corpus integrity")
        # purge the pair AND the meta sidecar/absence marker: the refetched
        # corpus version may have gained, changed, or dropped its sidecar —
        # pairing v2 tokens with v1's vocab/eod metadata would be silent
        # corruption of exactly the kind _validate_pair exists to stop
        for ext in (".idx", ".bin", ".meta.json", ".meta.json.absent"):
            if os.path.exists(local_prefix + ext):
                os.unlink(local_prefix + ext)
    return local_prefix
