"""Per-layer parallel strategy representation and (de)serialization.

Capability parity with the reference's strategy spine
(galvatron/utils/strategy_utils.py:1-352): dataclasses describing one layer's
parallel plan, an enum of data-parallel flavours, and converters between a list
of per-layer strategies and the on-disk ``galvatron_config_*.json`` interchange
format (same keys: pp_deg / tp_sizes_enc / tp_consecutive_flags / dp_types_enc /
use_sp / cp_sizes_enc / ep_sizes_enc / tp_of_ep_sizes_enc / checkpoint /
global_bsz / chunks / pp_division / pipeline_type / default_dp_type / vtp /
vsp / embed_sdp; the legacy ``etp_sizes_enc`` spelling is accepted on read), so
strategy JSONs remain the interchange artifact between search engine and
runtime, as in the reference (consumed at
galvatron/core/runtime/hybrid_parallel_config.py:50-101).

TPU note: a strategy here never names ranks or process groups. It is a purely
logical description; ``runtime/mesh.py`` lowers it to a `jax.sharding.Mesh`
view + `PartitionSpec`s.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, replace
from enum import IntEnum
from typing import Any, Dict, List, Optional, Sequence, Tuple


class PlanFormatError(ValueError):
    """A strategy/plan JSON is malformed. Carries the offending ``key``
    (and optionally the file ``path``) so the plan doctor and the runtime
    can say WHICH field is broken instead of surfacing a raw
    KeyError/ValueError traceback from deep inside the parser."""

    def __init__(self, message: str, *, key: Optional[str] = None,
                 path: Optional[str] = None):
        prefix = f"plan file {path}: " if path else ""
        super().__init__(f"{prefix}{message}")
        self.key = key
        self.path = path


class DPType(IntEnum):
    """Data-parallel flavour for one layer.

    Mirrors the reference's ddp/zero2/zero3 choices (runtime/parallel.py:119-123):
      DDP   — parameters replicated across dp; gradients all-reduced (psum).
      ZERO2 — optimizer state + gradients sharded across dp (psum_scatter grads).
      ZERO3 — parameters fully sharded across dp; XLA all-gathers on use.
    """

    DDP = 0
    ZERO2 = 1
    ZERO3 = 2

    @staticmethod
    def from_name(name: str) -> "DPType":
        return {"ddp": DPType.DDP, "zero2": DPType.ZERO2, "zero3": DPType.ZERO3}[
            name.lower()
        ]

    @property
    def short(self) -> str:
        return {DPType.DDP: "ddp", DPType.ZERO2: "zero2", DPType.ZERO3: "zero3"}[self]


@dataclass(frozen=True)
class LayerStrategy:
    """Parallel plan for a single transformer layer.

    world-per-stage invariant: tp_size * cp_size * dp_size == world_size // pp_deg
    (cp and sp are mutually exclusive with each other in the reference; when
    ``sp`` is set the tp degree is reinterpreted as the Ulysses sequence-parallel
    degree — hybrid_parallel_config.py:262-267).
    """

    pp_deg: int = 1
    tp_size: int = 1
    dp_size: int = 1
    cp_size: int = 1
    sp: bool = False  # Ulysses: all_to_all head-scatter attention on the tp axis
    tp_consecutive: bool = True  # tp over adjacent devices (ICI-local) or strided
    dp_type: DPType = DPType.DDP
    checkpoint: bool = False  # activation rematerialization for this layer
    # MoE only:
    ep_size: int = 1  # expert-parallel degree (experts sharded over dp*tp grid)
    etp_size: int = 1  # tensor-parallel degree inside each expert

    @property
    def degrees(self) -> int:
        return self.tp_size * self.cp_size * self.dp_size

    def world_size(self) -> int:
        return self.pp_deg * self.degrees

    def key(self) -> Tuple:
        """Hashable identity used for strategy dedup in the search engine."""
        return (
            self.pp_deg,
            self.tp_size,
            self.dp_size,
            self.cp_size,
            int(self.sp),
            int(self.tp_consecutive),
            int(self.dp_type),
            int(self.checkpoint),
            self.ep_size,
            self.etp_size,
        )

    def with_checkpoint(self, flag: bool) -> "LayerStrategy":
        return replace(self, checkpoint=flag)

    def validate(self, world_size: int) -> None:
        if self.world_size() != world_size:
            raise ValueError(
                f"strategy {form_strategy(self)}: pp*tp*cp*dp="
                f"{self.world_size()} != world_size {world_size}"
            )
        for n, v in (("pp_deg", self.pp_deg), ("tp_size", self.tp_size),
                     ("cp_size", self.cp_size), ("dp_size", self.dp_size),
                     ("ep_size", self.ep_size), ("etp_size", self.etp_size)):
            if v < 1 or (v & (v - 1)) != 0:
                raise ValueError(f"{n}={v} must be a positive power of two")
        if self.sp and self.cp_size > 1:
            raise ValueError("Ulysses sp and ring-attention cp are exclusive per layer")


@dataclass(frozen=True)
class EmbeddingLMHeadStrategy:
    """Strategy for the embedding + LM head ("vocab") layers, searched
    independently of the decoder layers (reference args_schema.py:36-39,
    parallel_state.py:183-305)."""

    vtp: int = 1  # vocab tensor-parallel degree
    vsp: bool = False  # shard the sequence at embedding/head (vocab sp)
    vcp: int = 1  # vocab context-parallel degree
    embed_sdp: bool = False  # ZeRO-3 the embedding/head instead of default dp type

    def key(self) -> Tuple:
        return (self.vtp, int(self.vsp), self.vcp, int(self.embed_sdp))


# ---------------------------------------------------------------------------
# strategy list <-> JSON interchange
# ---------------------------------------------------------------------------


def default_pp_division(num_layers: int, pp_deg: int) -> List[int]:
    """Even stage split with the remainder folded into the last stage, matching
    the reference default (avg*(pp-1) + rest) so sum == num_layers always."""
    pp_deg = max(pp_deg, 1)
    avg = num_layers // pp_deg
    return [avg] * (pp_deg - 1) + [num_layers - avg * (pp_deg - 1)]


def _enc(values: Sequence[Any]) -> str:
    return ",".join(str(int(v)) for v in values)


def _dec(s: str) -> List[int]:
    return [int(x) for x in str(s).split(",") if x != ""]


def strategy_list2config(
    strategies: Sequence[LayerStrategy],
    *,
    global_bsz: int,
    chunks: int,
    pipeline_type: str = "pipedream_flush",
    default_dp_type: str = "ddp",
    vocab: Optional[EmbeddingLMHeadStrategy] = None,
    pp_division: Optional[Sequence[int]] = None,
    num_encoder_layers: Optional[int] = None,
    vpp_deg: Optional[int] = None,
    predicted_layer_compute_ms: Optional[Sequence[float]] = None,
    hier_dp: Optional[bool] = None,
    hier_bucket_mb: float = 0.0,
    dp_schedule: Optional[str] = None,
) -> Dict[str, Any]:
    """Serialize per-layer strategies to the interchange dict.

    ``dp_types_enc`` keeps the reference encoding: 0 means "use
    ``default_dp_type``", 1 means "force ZeRO-3 for this layer". The one-bit
    format can only carry {default, ZERO3}; any other per-layer dp_type would
    be silently coerced on round-trip, so it raises instead.
    """
    if not strategies:
        raise ValueError("empty strategy list")
    pp_deg = strategies[0].pp_deg
    default_dp = DPType.from_name(default_dp_type)
    dp_types = []
    for i, s in enumerate(strategies):
        if s.pp_deg != pp_deg:
            raise ValueError("all layers must share one pp_deg")
        if s.dp_type == default_dp:
            dp_types.append(0)
        elif s.dp_type == DPType.ZERO3:
            dp_types.append(1)
        else:
            raise ValueError(
                f"layer {i}: dp_type {s.dp_type.short} is not representable in "
                f"dp_types_enc with default_dp_type={default_dp.short} "
                f"(only the default type or zero3 can be encoded)"
            )
    vocab = vocab or EmbeddingLMHeadStrategy()
    cfg: Dict[str, Any] = {
        "pp_deg": pp_deg,
        "tp_sizes_enc": _enc([s.tp_size for s in strategies]),
        "tp_consecutive_flags": _enc([s.tp_consecutive for s in strategies]),
        "dp_types_enc": _enc(dp_types),
        "use_sp": _enc([s.sp for s in strategies]),
        "cp_sizes_enc": _enc([s.cp_size for s in strategies]),
        "ep_sizes_enc": _enc([s.ep_size for s in strategies]),
        "tp_of_ep_sizes_enc": _enc([s.etp_size for s in strategies]),
        "checkpoint": _enc([s.checkpoint for s in strategies]),
        "global_bsz": int(global_bsz),
        "chunks": int(chunks),
        "pp_division": _enc(pp_division) if pp_division is not None
        else _enc(default_pp_division(len(strategies), pp_deg)),
        "pipeline_type": pipeline_type,
        "default_dp_type": default_dp.short,
        "vtp": vocab.vtp,
        "vsp": int(vocab.vsp),
        "vcp": vocab.vcp,
        "embed_sdp": int(vocab.embed_sdp),
    }
    if num_encoder_layers is not None:
        # encoder-decoder extension (no reference equivalent — the reference
        # snapshot ships no T5): the per-layer vectors span the COMBINED
        # encoder+decoder stack, encoder layers first; this key records the
        # split point so the runtime can slice.
        cfg["num_encoder_layers"] = int(num_encoder_layers)
    if vpp_deg is not None and vpp_deg > 1:
        # interleaved virtual stages (beyond the reference): pp_division then
        # has pp_deg * vpp_deg entries, chunk c on physical group c % pp_deg
        cfg["vpp_deg"] = int(vpp_deg)
    if predicted_layer_compute_ms is not None:
        # the cost model's per-layer COMPUTE prediction (fct+bct ms, no
        # collectives — those are re-priced from plan_comm_volume at audit
        # time), embedded so the runtime's plan audit diffs the exact model
        # that picked the plan without needing the profile files
        if len(predicted_layer_compute_ms) != len(strategies):
            raise ValueError(
                f"predicted_layer_compute_ms has "
                f"{len(predicted_layer_compute_ms)} entries for "
                f"{len(strategies)} layers")
        cfg["predicted_layer_compute_ms"] = [
            float(x) for x in predicted_layer_compute_ms]
    if hier_dp:
        # the search priced this plan's dp gradient reduction with the
        # hierarchical two-level schedule (ops/hier_reduce.py); the runtime
        # enables the matching execution path (args.parallel.hier_dp ORs in)
        cfg["hier_dp"] = 1
        if hier_bucket_mb > 0:
            # ...and pipelined it at this bucket granularity
            # (cost.hier_dp_best_bucket); the runtime buckets identically
            cfg["hier_bucket_mb"] = float(hier_bucket_mb)
        if dp_schedule:
            # ...and the synthesized collective schedule family whose α-β
            # price won the space (cost.dp_schedule_choice over
            # collectives.synthesize_space); the runtime executes the
            # reduction through the matching emitted program
            cfg["dp_schedule"] = str(dp_schedule)
    return cfg


def _int_field(cfg: Dict[str, Any], key: str, default: Optional[int] = None
               ) -> int:
    """A scalar integer field, with a typed error naming the key on
    absence or a non-integer value."""
    if key not in cfg:
        if default is not None:
            return default
        raise PlanFormatError(f"missing required key '{key}'", key=key)
    v = cfg[key]
    # int() would silently TRUNCATE a fractional float ("pp_deg": 2.5 ->
    # 2) — exactly the malformed-degree class this parser exists to catch;
    # integral floats (2.0, a JSON round-trip artifact) stay accepted
    if isinstance(v, float) and not v.is_integer():
        raise PlanFormatError(
            f"key '{key}' must be an integer, got {v!r}", key=key)
    try:
        return int(v)
    except (TypeError, ValueError):
        raise PlanFormatError(
            f"key '{key}' must be an integer, got {v!r}",
            key=key) from None


def config2strategy(
    cfg: Dict[str, Any], world_size: Optional[int] = None
) -> Tuple[List[LayerStrategy], EmbeddingLMHeadStrategy, Dict[str, Any]]:
    """Parse the interchange dict back into per-layer strategies.

    Returns (layer strategies, vocab strategy, extras) where extras carries the
    non-per-layer fields (global_bsz, chunks, pipeline_type, pp_division).
    Missing optional vectors (cp/ep) default to all-ones, matching the
    reference's tolerance of older config files. Malformed input (missing
    keys, non-integer degrees, wrong-length vectors) raises
    :class:`PlanFormatError` naming the offending key — never a raw
    KeyError from deep inside the parser.
    """
    if not isinstance(cfg, dict):
        raise PlanFormatError(
            f"plan must be a JSON object, got {type(cfg).__name__}")
    pp_deg = _int_field(cfg, "pp_deg")
    if pp_deg < 1:
        raise PlanFormatError(f"pp_deg must be >= 1, got {pp_deg}",
                              key="pp_deg")
    if "tp_sizes_enc" not in cfg:
        raise PlanFormatError("missing required key 'tp_sizes_enc' (the "
                              "per-layer tp vector defines the layer count)",
                              key="tp_sizes_enc")

    def dec(key: str) -> List[int]:
        try:
            return _dec(cfg[key])
        except (TypeError, ValueError):
            raise PlanFormatError(
                f"key '{key}' must be a comma-separated integer vector, "
                f"got {cfg[key]!r}", key=key) from None

    tps = dec("tp_sizes_enc")
    n = len(tps)
    if n == 0:
        raise PlanFormatError("'tp_sizes_enc' encodes zero layers",
                              key="tp_sizes_enc")

    def vec(key: str, default: int) -> List[int]:
        if key not in cfg:
            return [default] * n
        out = dec(key)
        if len(out) != n:
            raise PlanFormatError(
                f"key '{key}' has {len(out)} entries but 'tp_sizes_enc' "
                f"defines {n} layers", key=key)
        return out

    cons = vec("tp_consecutive_flags", 1)
    dpt = vec("dp_types_enc", 0)
    sps = vec("use_sp", 0)
    cps = vec("cp_sizes_enc", 1)
    eps = vec("ep_sizes_enc", 1)
    # reference runtime key is tp_of_ep_sizes_enc; accept the legacy
    # etp_sizes_enc spelling written by early versions of this repo too
    etps = (vec("tp_of_ep_sizes_enc", 1) if "tp_of_ep_sizes_enc" in cfg
            else vec("etp_sizes_enc", 1))
    ckpt = vec("checkpoint", 0)
    try:
        default_dp = DPType.from_name(cfg.get("default_dp_type", "ddp"))
    except (KeyError, AttributeError):
        raise PlanFormatError(
            f"default_dp_type must be one of ddp/zero2/zero3, got "
            f"{cfg.get('default_dp_type')!r}",
            key="default_dp_type") from None
    strategies = []
    for i in range(n):
        dp_type = DPType.ZERO3 if dpt[i] == 1 else default_dp
        dp_size = 0
        if world_size is not None:
            denom = pp_deg * tps[i] * cps[i]
            if world_size % denom != 0:
                raise ValueError(
                    f"layer {i}: world_size {world_size} not divisible by "
                    f"pp*tp*cp = {denom}"
                )
            dp_size = world_size // denom
        s = LayerStrategy(
            pp_deg=pp_deg,
            tp_size=tps[i],
            dp_size=max(dp_size, 1),
            cp_size=cps[i],
            sp=bool(sps[i]),
            tp_consecutive=bool(cons[i]),
            dp_type=dp_type,
            checkpoint=bool(ckpt[i]),
            ep_size=eps[i],
            etp_size=etps[i],
        )
        if world_size is not None:
            s.validate(world_size)
        strategies.append(s)
    vocab = EmbeddingLMHeadStrategy(
        vtp=_int_field(cfg, "vtp", 1),
        vsp=bool(_int_field(cfg, "vsp", 0)),
        vcp=_int_field(cfg, "vcp", 1),
        embed_sdp=bool(_int_field(cfg, "embed_sdp", 0)),
    )
    extras = {
        "global_bsz": _int_field(cfg, "global_bsz", 0),
        "chunks": _int_field(cfg, "chunks", 1),
        "pipeline_type": cfg.get("pipeline_type", "pipedream_flush"),
        "pp_division": dec("pp_division") if "pp_division" in cfg else None,
        "default_dp_type": default_dp.short,
        "num_encoder_layers": (_int_field(cfg, "num_encoder_layers")
                               if "num_encoder_layers" in cfg else None),
        "vpp_deg": _int_field(cfg, "vpp_deg", 1),
        "hier_dp": bool(_int_field(cfg, "hier_dp", 0)),
        # bucketed software-pipelining granularity the search priced the
        # hierarchical reduction at (0 = monolithic); the runtime
        # pipelines at the same size unless parallel.hier_bucket_mb
        # overrides
        "hier_bucket_mb": float(cfg.get("hier_bucket_mb", 0.0) or 0.0),
        # synthesized collective schedule family the search priced the dp
        # reduction with (collectives/); None = the hand-implemented
        # three-stage hierarchical path
        "dp_schedule": str(cfg.get("dp_schedule") or "") or None,
        # optional per-layer compute prediction (see strategy_list2config);
        # a hand-edited plan whose vector no longer matches the layer count
        # is dropped rather than mis-attributed to the wrong layers
        "predicted_layer_compute_ms": (
            [float(x) for x in cfg["predicted_layer_compute_ms"]]
            if isinstance(cfg.get("predicted_layer_compute_ms"), list)
            and len(cfg["predicted_layer_compute_ms"]) == n else None),
    }
    return strategies, vocab, extras


def save_strategy_config(path: str, cfg: Dict[str, Any],
                         world_size: Optional[int] = None) -> None:
    """Write a plan dict, VALIDATING it first: the dict must round-trip
    through :func:`config2strategy` (which runs ``LayerStrategy.validate``
    on every layer when ``world_size`` is given) — a writer bug surfaces at
    save time on the machine that searched the plan, not at load time on
    the TPU fleet."""
    config2strategy(cfg, world_size=world_size)
    import os

    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(cfg, f, indent=4)


def load_strategy_config(path: str) -> Dict[str, Any]:
    """Read a plan JSON with typed errors: unreadable files and non-object
    JSON raise :class:`PlanFormatError` carrying the path, so launchers and
    the plan doctor can report the actual problem instead of a traceback."""
    try:
        with open(path) as f:
            cfg = json.load(f)
    except OSError as e:
        raise PlanFormatError(f"cannot read plan: {e}", path=path) from None
    except json.JSONDecodeError as e:
        raise PlanFormatError(f"invalid JSON: {e}", path=path) from None
    if not isinstance(cfg, dict):
        raise PlanFormatError(
            f"plan must be a JSON object, got {type(cfg).__name__}",
            path=path)
    return cfg


# ---------------------------------------------------------------------------
# pretty printing (reference: form_strategy / print_strategies)
# ---------------------------------------------------------------------------


def form_strategy(s: LayerStrategy) -> str:
    bits = [f"pp{s.pp_deg}", f"tp{s.tp_size}", f"dp{s.dp_size}({s.dp_type.short})"]
    if s.cp_size > 1:
        bits.append(f"cp{s.cp_size}")
    if s.sp:
        bits.append("ulysses")
    if s.ep_size > 1:
        bits.append(f"ep{s.ep_size}xetp{s.etp_size}")
    if s.checkpoint:
        bits.append("ckpt")
    if not s.tp_consecutive:
        bits.append("nonconsec")
    return "-".join(bits)


def print_strategies(strategies: Sequence[LayerStrategy]) -> str:
    """Compress a per-layer list into 'strategy*count' runs for logging."""
    out: List[str] = []
    run_start = 0
    for i in range(1, len(strategies) + 1):
        if i == len(strategies) or strategies[i].key() != strategies[run_start].key():
            count = i - run_start
            txt = form_strategy(strategies[run_start])
            out.append(f"{txt}*{count}" if count > 1 else txt)
            run_start = i
    return ", ".join(out)
