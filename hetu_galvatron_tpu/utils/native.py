"""Lazy build + ctypes binding for the C++ helpers in csrc/.

Shared by the search-engine DP core and the dataset index builder (the
reference compiles its dataset helpers lazily at startup the same way,
runtime/initialize.py:163-187). Builds go through the Makefile so $CXX and
flags are honored; a missing toolchain degrades to the caller's fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Dict, Optional

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "..", "..", "csrc")
_CACHE: Dict[str, Optional[ctypes.CDLL]] = {}
_LOCK = threading.Lock()


def load_native(
    lib_name: str,
    source_name: str,
    configure: Callable[[ctypes.CDLL], None],
) -> Optional[ctypes.CDLL]:
    """Build csrc/<lib_name> from <source_name> via make if stale, load it,
    run `configure` (restype/argtypes setup) once, and cache. Returns None
    when the toolchain is unavailable."""
    if lib_name in _CACHE:
        return _CACHE[lib_name]
    with _LOCK:  # threaded callers (parallel_search) must not race the build
        return _load_locked(lib_name, source_name, configure)


def _load_locked(lib_name, source_name, configure):
    if lib_name in _CACHE:
        return _CACHE[lib_name]
    so = os.path.join(_CSRC, lib_name)
    src = os.path.join(_CSRC, source_name)
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            subprocess.run(["make", "-C", _CSRC, lib_name], check=True,
                           capture_output=True)
        lib = ctypes.CDLL(so)
        configure(lib)
    except (subprocess.CalledProcessError, OSError) as e:
        print(f"native helper {lib_name}: build unavailable ({e}); "
              "using python fallback")
        lib = None
    _CACHE[lib_name] = lib
    return lib
