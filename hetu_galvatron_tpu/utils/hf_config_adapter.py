"""HuggingFace config → ModelArgs adapter.

Capability parity with the reference's hf_config_adapter
(utils/hf_config_adapter.py:196-393): populate our :class:`ModelArgs` from a HF
`AutoConfig` (or a plain dict of HF-style keys), auto-detecting norm type,
activation, rope, and GQA for llama/gpt2/qwen2/mistral/mixtral families, and
expose `model_layer_configs`/`model_name` helpers for the profiler and search
engine.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs

# HF key → ModelArgs key, tried in order per field.
_FIELD_MAP = {
    "hidden_size": ["hidden_size", "n_embd", "d_model"],
    "num_hidden_layers": ["num_hidden_layers", "n_layer", "num_layers"],
    "num_attention_heads": ["num_attention_heads", "n_head", "num_heads"],
    "num_key_value_heads": ["num_key_value_heads", "num_kv_heads"],
    "ffn_hidden_size": ["intermediate_size", "n_inner", "ffn_dim", "d_ff"],
    "vocab_size": ["vocab_size"],
    "max_position_embeddings": ["max_position_embeddings", "n_positions", "n_ctx"],
    "layernorm_epsilon": ["rms_norm_eps", "layer_norm_epsilon", "layer_norm_eps"],
    "rope_theta": ["rope_theta"],
    "rope_scaling": ["rope_scaling"],
    # decoupled head dim (gemma-7b, mistral-nemo, ...); None skipped
    "head_dim_override": ["head_dim"],
    "tie_word_embeddings": ["tie_word_embeddings"],
    "num_experts": ["num_local_experts", "num_experts"],
    "moe_topk": ["num_experts_per_tok"],
}

_GEMMA_FAMILIES = {"gemma"}
_ROPE_FAMILIES = {"llama", "qwen2", "mistral", "mixtral",
                  "qwen"} | _GEMMA_FAMILIES
_RMS_FAMILIES = _ROPE_FAMILIES | {"t5"}
_SWIGLU_FAMILIES = {"llama", "qwen2", "mistral", "mixtral", "qwen"}
# gemma-2/3 add sandwich norms, logit softcapping, query_pre_attn_scalar,
# alternating sliding windows (v3: q/k-norm, dual rope) — none of which this
# stack implements; mapping them through gemma-1 numerics would silently
# produce wrong logits, so they are refused by name
_UNSUPPORTED_FAMILIES = {"gemma2", "gemma3", "gemma3_text"}


def _cfg_to_dict(config: Any) -> Dict[str, Any]:
    if isinstance(config, dict):
        return config
    if hasattr(config, "to_dict"):
        return config.to_dict()
    return vars(config)


def populate_model_args_from_hf(
    config: Any, base: Optional[ModelArgs] = None
) -> ModelArgs:
    """Build ModelArgs from a HF config object/dict, auto-detecting family."""
    d = _cfg_to_dict(config)
    family = str(d.get("model_type", "gpt2")).lower()
    if family in _UNSUPPORTED_FAMILIES:
        raise NotImplementedError(
            f"model family {family!r} has architecture features this stack "
            "does not implement (sandwich norms, logit softcapping, "
            "alternating sliding windows); refusing rather than producing "
            "silently-wrong numerics")
    values: Dict[str, Any] = dict(base.model_dump() if base else {})
    for ours, theirs in _FIELD_MAP.items():
        for key in theirs:
            if key in d and d[key] is not None:
                values[ours] = d[key]
                break
    values["model_name"] = d.get("_name_or_path", family) or family
    if family == "bert":
        values["model_type"] = "bert"
    elif family == "t5":
        values["model_type"] = "t5"
    else:
        values["model_type"] = "moe" if values.get("num_experts", 0) else (
            "llama" if family in _ROPE_FAMILIES else "gpt"
        )
    values["normalization"] = "rmsnorm" if family in _RMS_FAMILIES else "layernorm"
    values["hidden_act"] = "swiglu" if family in _SWIGLU_FAMILIES else "gelu"
    if family in _GEMMA_FAMILIES:
        # gemma numerics: gated-gelu MLP, RMSNorm x*(1+w), sqrt(H)-scaled
        # embeddings (head_dim comes via the shared field map)
        values["hidden_act"] = "geglu"
        values["norm_zero_centered"] = True
        values["scale_embeddings"] = True
    if family == "bert":
        # HF bert uses erf gelu everywhere (BertIntermediate + the MLM
        # transform); our "gelu" is the tanh approximation (gpt2's gelu_new)
        values["hidden_act"] = "gelu_exact"
    if family == "t5":
        # HF t5: num_layers = ENCODER depth, num_decoder_layers = decoder;
        # act is relu (v1.0) or gated-gelu (v1.1)
        if d.get("num_layers") is not None:
            values["num_encoder_layers"] = d["num_layers"]
            values["num_hidden_layers"] = d.get("num_decoder_layers",
                                                d["num_layers"])
        ff = str(d.get("feed_forward_proj", "relu"))
        values["hidden_act"] = "geglu" if "gated" in ff else "relu"
        values["tie_word_embeddings"] = bool(d.get("tie_word_embeddings",
                                                   True))
    values["position_embedding_type"] = (
        "rope" if family in _ROPE_FAMILIES else "learned"
    )
    scaling = values.get("rope_scaling")
    if isinstance(scaling, dict) and "mrope_section" in scaling:
        # qwen2-vl style multimodal rope: rope_scaling carries the section
        # split (type "mrope"/"default"), not a frequency-scaling recipe —
        # route it to mrope_section so _scale_inv_freq never sees it
        values["mrope_section"] = list(scaling["mrope_section"])
        rest = {k: v for k, v in scaling.items()
                if k not in ("mrope_section", "type", "rope_type")}
        values["rope_scaling"] = rest or None
    # bias detection (reference hf_config_adapter.py:196-290 reads
    # attention_bias / mlp_bias / family defaults)
    bias_free = _ROPE_FAMILIES | {"t5"}  # llama-likes and t5 default to no biases
    if "attention_bias" in d:
        values["add_qkv_bias"] = bool(d["attention_bias"])
    elif family in {"qwen", "qwen2"}:
        values["add_qkv_bias"] = True  # qwen2 has qkv bias, no mlp bias
    else:
        values["add_qkv_bias"] = family not in bias_free
    if "mlp_bias" in d:
        values["add_bias_linear"] = bool(d["mlp_bias"])
    else:
        values["add_bias_linear"] = family not in bias_free
    return ModelArgs.model_validate(values)


def resolve_model_config(args: CoreArgs, hf_path: Optional[str] = None) -> CoreArgs:
    """Resolve final ModelArgs: YAML-provided fields win; if ``hf_path`` (or
    args.extra['hf_model_path']) is set, pull architecture from HF AutoConfig.
    Mirrors reference resolve_model_config (hf_config_adapter.py:285)."""
    path = hf_path or args.extra.get("hf_model_path")
    if path:
        from transformers import AutoConfig

        hf_cfg = AutoConfig.from_pretrained(path)
        args = args.model_copy(
            update={"model": populate_model_args_from_hf(hf_cfg, base=args.model)}
        )
    if args.model.seq_length > args.model.max_position_embeddings:
        args.model.max_position_embeddings = args.model.seq_length
    return args


def model_layer_configs(model_args: ModelArgs) -> List[Dict[str, Any]]:
    """Per-layertype dicts consumed by profiler + search engine
    (reference hf_config_adapter.py:384). Dense models have one layertype; MoE
    models alternate dense/MoE according to moe_layer_freq."""
    base = {
        "hidden_size": model_args.hidden_size,
        "seq_len": model_args.seq_length,
        "num_attention_heads": model_args.num_attention_heads,
        "num_key_value_heads": model_args.kv_heads,
        "ffn_hidden_size": model_args.ffn_dim,
        "vocab_size": model_args.padded_vocab_size,
        "layer_num": model_args.num_hidden_layers,
    }
    if model_args.model_type == "t5":
        # layertype 0 = encoder, 1 = decoder (runtime/dataloader.py
        # seq2seq_batches splits each sample in half: source | target)
        n_enc = (model_args.num_encoder_layers
                 if model_args.num_encoder_layers is not None
                 else model_args.num_hidden_layers)
        half = model_args.seq_length // 2
        enc = dict(base, seq_len=half, layer_num=n_enc)
        dec = dict(base, seq_len=model_args.seq_length - half,
                   layer_num=model_args.num_hidden_layers)
        return ([enc] if n_enc else []) + [dec]
    if not model_args.num_experts:
        return [base]
    # dense/MoE alternation: every moe_layer_freq-th layer is MoE, so layer_num
    # is split between the two layertypes (never double-counted).
    freq = max(model_args.moe_layer_freq, 1)
    n = model_args.num_hidden_layers
    n_moe = n // freq
    if n_moe == 0:
        return [base]
    moe = dict(base)
    moe.update(
        layer_num=n_moe,
        num_experts=model_args.num_experts,
        moe_topk=model_args.moe_topk,
        moe_ffn_hidden_size=model_args.moe_ffn_hidden_size or model_args.ffn_dim,
    )
    if n - n_moe == 0:
        return [moe]
    base["layer_num"] = n - n_moe
    return [base, moe]


def model_name(model_args: ModelArgs) -> str:
    return model_args.model_name.replace("/", "_")
