from hetu_galvatron_tpu.utils.strategy import (  # noqa: F401
    DPType,
    LayerStrategy,
    EmbeddingLMHeadStrategy,
    PlanFormatError,
    strategy_list2config,
    config2strategy,
    form_strategy,
    print_strategies,
)
