"""Exponential backoff with jitter, shared by every I/O retry path.

One retry policy for the whole runtime (checkpoint reads, object-store
fetches, the restart supervisor): capped exponential backoff with full
jitter (the AWS architecture-blog scheme — ``sleep = uniform(0, min(cap,
base * 2**attempt))`` — which decorrelates a fleet of preempted workers
all restarting at once), a caller-supplied retryability predicate so
permanent failures (404s, validation faults) surface immediately, and
observability counters (``retry/attempts`` / ``retry/giveups`` labelled
by operation) so flaky dependencies show up on dashboards instead of in
tail latencies.

Two robustness extensions ride the same seam:

* ``deadline_s`` — a TOTAL-elapsed cap on the whole retry loop, distinct
  from the attempt cap: a hung object-store fetch that keeps "almost"
  succeeding must not stall a resume indefinitely. Sleeps are clamped to
  the remaining budget and an expired deadline surfaces the last error
  (counted as ``retry/deadline_exceeded``).
* :func:`set_fault_injector` — a process-global chaos hook consulted
  before every attempt of every ``op``-labelled call, so the fault
  harness (``runtime/chaos.py``) can inject transient I/O errors through
  the REAL retry path instead of monkeypatching call sites.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, TypeVar

T = TypeVar("T")

# chaos seam: fn(op) -> Optional[Exception]. Returning an exception makes
# the current attempt fail with it (subject to the caller's retryable
# predicate and backoff — the injected fault takes the same path a real
# flaky mount would). None = no fault. Process-global by design: the
# injector must reach retry sites deep inside checkpoint/object-store
# code without threading a parameter through every layer.
_FAULT_INJECTOR: Optional[Callable[[str], Optional[Exception]]] = None


def set_fault_injector(
    fn: Optional[Callable[[str], Optional[Exception]]],
) -> Optional[Callable[[str], Optional[Exception]]]:
    """Install (or clear, with None) the process-global fault injector;
    returns the previous one so harnesses can restore it."""
    global _FAULT_INJECTOR
    prev = _FAULT_INJECTOR
    _FAULT_INJECTOR = fn
    return prev


def _default_sleep(seconds: float) -> None:
    if seconds > 0:
        time.sleep(seconds)


def backoff_delay(
    attempt: int,
    *,
    base: float = 0.5,
    cap: float = 30.0,
    jitter: bool = True,
    rng: Optional[random.Random] = None,
) -> float:
    """Delay before retry number ``attempt`` (0-based): full-jitter capped
    exponential. With ``jitter=False`` returns the deterministic envelope
    ``min(cap, base * 2**attempt)`` (useful for tests and for callers that
    jitter elsewhere)."""
    envelope = min(float(cap), float(base) * (2.0 ** attempt))
    if not jitter:
        return envelope
    return (rng or random).uniform(0.0, envelope)


def backoff_delays(
    attempts: int,
    *,
    base: float = 0.5,
    cap: float = 30.0,
    jitter: bool = True,
    rng: Optional[random.Random] = None,
) -> Iterator[float]:
    """The ``attempts - 1`` inter-attempt delays of an ``attempts``-try
    schedule (no sleep after the final failure)."""
    for a in range(max(attempts - 1, 0)):
        yield backoff_delay(a, base=base, cap=cap, jitter=jitter, rng=rng)


def retry_call(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base: float = 0.5,
    cap: float = 30.0,
    retryable: Callable[[Exception], bool] = lambda e: True,
    op: str = "",
    sleep: Optional[Callable[[float], None]] = None,
    rng: Optional[random.Random] = None,
    on_retry: Optional[Callable[[Exception, int, float], None]] = None,
    deadline_s: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
) -> T:
    """Call ``fn`` up to ``attempts`` times with jittered exponential
    backoff between tries.

    A failure where ``retryable(exc)`` is false re-raises immediately (a
    404 must never burn the throttling budget); after the final attempt
    the last exception propagates unchanged. ``op`` labels the
    ``retry/attempts`` / ``retry/giveups`` observability counters;
    ``on_retry(exc, attempt, delay)`` runs before each backoff sleep
    (logging hook). ``sleep`` and ``clock`` are injectable for tests.

    ``deadline_s`` caps TOTAL elapsed wall across all attempts and
    sleeps: once exceeded, the last error surfaces even with attempts
    remaining (``retry/deadline_exceeded``), and each backoff sleep is
    clamped to the remaining budget — an attempt cap alone lets a slow
    failing ``fn`` stall a resume for attempts x its own hang time."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    if sleep is None:
        sleep = _default_sleep
    start = clock()
    last: Optional[Exception] = None
    for attempt in range(attempts):
        try:
            injector = _FAULT_INJECTOR
            if injector is not None:
                injected = injector(op)
                if injected is not None:
                    raise injected
            return fn()
        except Exception as e:  # noqa: BLE001 — policy is caller-supplied
            last = e
            if not retryable(e) or attempt == attempts - 1:
                if op and attempt == attempts - 1 and retryable(e):
                    _count("retry/giveups", op)
                raise
            if deadline_s is not None and clock() - start >= deadline_s:
                if op:
                    _count("retry/deadline_exceeded", op)
                raise
            delay = backoff_delay(attempt, base=base, cap=cap, rng=rng)
            if deadline_s is not None:
                delay = min(delay,
                            max(deadline_s - (clock() - start), 0.0))
            if op:
                _count("retry/attempts", op)
            if on_retry is not None:
                on_retry(e, attempt, delay)
            sleep(delay)
    raise last  # unreachable; keeps type-checkers honest


def _count(name: str, op: str) -> None:
    """Best-effort observability: retries are diagnostics, never a reason
    for the retried operation itself to fail."""
    try:
        from hetu_galvatron_tpu.observability.registry import get_registry

        get_registry().counter(name, op=op).inc()
    except Exception:  # noqa: BLE001
        pass
