"""Pass 4 — the memory doctor: static per-device peak-HBM accounting.

The search engine's analytical memory model decides which hybrid-parallel
plans are feasible, but until this pass nothing independently verified
that a searched (or hand-written) plan actually FITS on-device. Given a
plan JSON and a model config — on CPU, no devices, no training step —
this module accounts every resident byte the runtime will hold per
device and per pipeline stage:

* **model states** — params + grads + two Adam moments (the cost model's
  ``4 x`` fp32-unit convention) under each layer's weight sharding:
  Megatron-TP shards weights over tp, Ulysses does NOT (its tp axes carry
  sequence), ZeRO-2/3 scale by the shard-degree ratios over
  ``sdp = dp * sp * cp``.
* **activations** — the saved-for-backward working set per layer
  (:func:`activation_per_sample_mb`: every matmul input plus the norm
  inputs, flash-style attention so probabilities are never materialized),
  times the 1F1B cumulative in-flight microbatch count
  (``pp - stage_idx`` under pipedream_flush, ``chunks`` under gpipe),
  sequence-sharded over tp_sp and cp; remat layers keep only the
  ``[B, S, H]`` stage input.
* **stage-input buffer** — the compiled 1F1B engine's circular buffer of
  depth ``2*pp - 1`` plus its two rotation carries
  (``runtime/compiled_pipeline.py`` ``buf0``/``fwd_x``/``bwd_dy``), one
  activation slice each, present only under ``schedule_impl=compiled``
  with pp > 1.
* **vocab rows** — embedding (+ learned positions), final norm and LM
  head states sharded over vtp: on the first/last stages under the host
  engine (the cost model's convention), but REPLICATED ACROSS EVERY
  STAGE by the compiled engine (``split_params`` places them so) — the
  replication premium is its own component, visible per stage.
* **KV pool (serving mode)** — the paged pool
  ``serving/kv_cache.py::kv_pool_mb`` will allocate (the sizing helper is
  shared with the engine, so the prediction can't drift), plus the
  prefix-cache block budget.

Every training-side component is cross-checked against the cost model
(``core/cost_model/cost.py::layer_memory_components`` /
``embed_memory_components``) evaluated on a :class:`CostContext` built
from the same analytic quantities: each component ratio must be ~1.0,
and a drifted component is diagnosed BY NAME — so a change to the search
engine's memory arithmetic that this accounting does not mirror (or vice
versa) fails ``cli/check.py`` instead of silently searching plans the
doctor would reject.

The ``--hbm-gb`` budget gate and the search engine's pruning hook
(``core/search_engine/engine.py``) evaluate the SAME predicate
(:func:`hbm_budget_reason` over :func:`plan_stage_memory`), the
``analysis/eligibility.py`` search==check parity discipline.

Plan-doctor contract: report everything at once, never raise on
malformed input.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from hetu_galvatron_tpu.utils.strategy import (
    EmbeddingLMHeadStrategy,
    LayerStrategy,
    PlanFormatError,
    config2strategy,
    default_pp_division,
    load_strategy_config,
)

MB = 1024 * 1024

# the component keys one stage row carries, in render order
STAGE_COMPONENTS = (
    "model_states_mb", "activation_mb", "stage_buffer_mb",
    "vocab_states_mb", "vocab_activation_mb", "kv_pool_mb",
)


# ---------------------------------------------------------------------------
# analytic per-layer quantities (pure model arithmetic, no profile needed)
# ---------------------------------------------------------------------------


def activation_per_sample_mb(model: Any, elem_bytes: int = 2) -> float:
    """Saved-for-backward activation megabytes per sample for ONE decoder
    layer at tp_sp = 1: the inputs of every projection matmul plus the two
    norm inputs, with flash-style attention (scores/probabilities never
    materialized — q/k/v and the context output are what survive).

    Terms (seq s, hidden h, q-heads*head_dim nd, kv-heads*head_dim kd,
    ffn f, gated doubles the fc1 output):
    norm1_in + qkv_in + q/k/v + context_out + proj_out
    + norm2_in + fc1_in + fc1_out(s) + act_out + fc2_out.
    """
    s, h = model.seq_length, model.hidden_size
    nd = model.num_attention_heads * model.head_dim
    kd = model.kv_heads * model.head_dim
    f = model.ffn_dim
    gated = model.hidden_act in ("swiglu", "geglu")
    attn = s * h + s * h + s * (nd + 2 * kd) + s * nd + s * h
    mlp = s * h + s * h + s * f * (2 if gated else 1) + s * f + s * h
    return (attn + mlp) * elem_bytes / MB


def checkpoint_per_sample_mb(model: Any, elem_bytes: int = 2) -> float:
    """Per-sample megabytes a remat layer keeps: just its [S, H] stage
    input (the backward recomputes everything else)."""
    return model.seq_length * model.hidden_size * elem_bytes / MB


def vocab_param_mb(model: Any) -> Dict[str, float]:
    """fp32 megabytes of the vocab-row parameter groups at vtp = 1:
    ``embed`` (token table + learned positions), ``prenorm`` (final norm),
    ``head`` (LM projection; tied heads read the embedding table, so the
    last pipeline stage still RESIDES a table-sized copy — the host
    engine materializes it for the head matmul and exchanges the grad)."""
    h = model.hidden_size
    v = model.padded_vocab_size
    embed = v * h
    if model.position_embedding_type == "learned":
        embed += model.max_position_embeddings * h
    prenorm = h * (1 if model.normalization == "rmsnorm" else 2)
    head = v * h  # tied or not, the last stage resides the table
    return {"embed": embed * 4 / MB, "prenorm": prenorm * 4 / MB,
            "head": head * 4 / MB}


def vocab_act_per_sample_mb(model: Any, tp_sp: int,
                            elem_bytes: int = 2) -> Dict[str, float]:
    """Per-sample activation megabytes of the vocab rows at a given
    activation sharding degree: the embedding output on the first stage,
    the pre-norm hidden + the [S, V] logits (vocab-sharded over tp_sp) on
    the last."""
    s, h, v = model.seq_length, model.hidden_size, model.padded_vocab_size
    first = s * h / tp_sp * elem_bytes / MB
    last = (s * h / tp_sp + s * v / tp_sp) * elem_bytes / MB
    return {"first": first, "last": last}


def _zero_scale(dp_type_short: str, sdp: int, chunks: int,
                mixed_precision: bool) -> float:
    """The ZeRO model-states multiplier for one layer — the cost model's
    ``_zero_ratios`` closures (imported, not re-derived: one arithmetic)."""
    from hetu_galvatron_tpu.core.cost_model.cost import _zero_ratios

    z2, z3 = _zero_ratios(chunks, mixed_precision, async_grad_reduce=True)
    if dp_type_short == "zero3":
        return z3(sdp)
    if dp_type_short == "zero2":
        return z2(sdp)
    return 1.0


# ---------------------------------------------------------------------------
# the per-stage accounting
# ---------------------------------------------------------------------------


@dataclass
class StageMemory:
    """One pipeline stage's per-device resident megabytes, by component."""

    stage: int
    components: Dict[str, float] = field(default_factory=dict)

    @property
    def total_mb(self) -> float:
        return sum(self.components.values())


def plan_stage_memory(
    layers: Sequence[LayerStrategy],
    vocab: EmbeddingLMHeadStrategy,
    model: Any,
    *,
    global_bsz: int,
    chunks: int,
    pp_division: Sequence[int],
    pipeline_type: str = "pipedream_flush",
    schedule_impl: str = "compiled",
    mixed_precision: bool = True,
    serving: Any = None,
    kv_elem_bytes: int = 2,
) -> List[StageMemory]:
    """Per-device resident megabytes for every pipeline stage of a
    resolved plan — THE accounting both ``cli/check.py --memory`` and the
    search engine's HBM gate evaluate. Pure arithmetic over plain values;
    callers must pre-validate (or use :func:`diagnose_memory`, which
    wraps this with the never-raise plan-doctor contract)."""
    pp = max(layers[0].pp_deg, 1)
    chunks = max(chunks, 1)
    elem = 2 if mixed_precision else 4
    param_mb = _layer_param_mb(model)
    act1 = activation_per_sample_mb(model, elem)
    ckpt1 = checkpoint_per_sample_mb(model, elem)
    vparams = vocab_param_mb(model)

    stage_of: List[int] = []
    for st, n in enumerate(pp_division):
        stage_of.extend([st % pp] * n)

    out = [StageMemory(stage=st, components={k: 0.0
                                             for k in STAGE_COMPONENTS})
           for st in range(pp)]

    for i, s in enumerate(layers):
        st = stage_of[i] if i < len(stage_of) else pp - 1
        row = out[st].components
        tp_w = 1 if s.sp else s.tp_size        # Ulysses weights replicate
        tp_sp = s.tp_size                      # activation shard degree
        sdp = s.dp_size * s.cp_size * (s.tp_size if s.sp else 1)
        # integer division, UNCLAMPED — the cost model's lbsz arithmetic
        # exactly (a plan whose grain starves a rank shows 0 here and is
        # rejected structurally elsewhere)
        lbsz = global_bsz // chunks // max(s.dp_size, 1)
        if pp == 1:
            cumulative = 1
        else:
            cumulative = (pp - st if pipeline_type == "pipedream_flush"
                          else chunks)
        states = 4 * param_mb / tp_w * _zero_scale(
            s.dp_type.short, max(sdp, 1), chunks, mixed_precision)
        if s.checkpoint:
            act = ckpt1 / max(tp_sp, 1) * cumulative * lbsz
        else:
            act = act1 / max(tp_sp, 1) * cumulative * lbsz
        act /= max(s.cp_size, 1)
        row["model_states_mb"] += states
        row["activation_mb"] += act

    # vocab rows: first/last stage under the host engine and the cost
    # model; the compiled engine replicates embed+prenorm+head on EVERY
    # stage (split_params), so its middle stages pay the premium too
    s0 = layers[0]
    vtp = max(vocab.vtp, 1)
    vcp = max(vocab.vcp, 1)
    stage_world = s0.tp_size * s0.cp_size * s0.dp_size
    vdp = max(stage_world // vtp // vcp, 1)
    v_sdp = max(stage_world // vtp, 1)  # vdp * vcp: the ZeRO shard group
    vscale = _zero_scale("zero3" if vocab.embed_sdp else "ddp",
                         v_sdp, chunks, mixed_precision)
    v_first = 4 * vparams["embed"] / vtp * vscale
    v_last = 4 * (vparams["prenorm"] + vparams["head"]) / vtp * vscale
    v_lbsz = global_bsz // chunks // vdp
    vact = vocab_act_per_sample_mb(model, vtp, elem)
    compiled_replicates = (schedule_impl == "compiled" and pp > 1)
    for st in range(pp):
        row = out[st].components
        if pp == 1:
            row["vocab_states_mb"] += v_first + v_last
            row["vocab_activation_mb"] += (vact["first"] + vact["last"]) \
                * v_lbsz / vcp
            continue
        cum_first = pp if pipeline_type == "pipedream_flush" else chunks
        cum_last = 1 if pipeline_type == "pipedream_flush" else chunks
        if compiled_replicates:
            row["vocab_states_mb"] += v_first + v_last
        else:
            if st == 0:
                row["vocab_states_mb"] += v_first
            if st == pp - 1:
                row["vocab_states_mb"] += v_last
        if st == 0:
            row["vocab_activation_mb"] += vact["first"] * cum_first \
                * v_lbsz / vcp
        if st == pp - 1:
            row["vocab_activation_mb"] += vact["last"] * cum_last \
                * v_lbsz / vcp

    # compiled engine stage-input buffer: depth 2pp-1 circular buffer + 2
    # rotation carries, one [lbsz, S/shard, H] compute-dtype slice each
    if compiled_replicates:
        seq_shard = s0.cp_size if s0.cp_size > 1 else max(s0.tp_size, 1)
        lbsz = max(global_bsz // chunks // max(s0.dp_size, 1), 1)
        slice_mb = (lbsz * model.seq_length / seq_shard
                    * model.hidden_size * elem / MB)
        depth = 2 * pp - 1 + 2
        for st in range(pp):
            out[st].components["stage_buffer_mb"] += depth * slice_mb

    # serving mode: the paged KV pool rides every stage's device (serving
    # is the pp=1 decode path, but the accounting stays general). The
    # pool's element size follows the ENGINE's kv/compute dtype (bf16 by
    # default, kv_elem_bytes to model an override) — NOT the training
    # mixed_precision flag, which governs activations/grads only: an
    # fp32 training diagnosis must not double the predicted pool.
    if serving is not None:
        from hetu_galvatron_tpu.serving.kv_cache import kv_pool_mb

        tp_kv = 1 if s0.sp else s0.tp_size
        pool = kv_pool_mb(serving, model, kv_elem_bytes=kv_elem_bytes,
                          tp=tp_kv)
        for st in range(pp):
            out[st].components["kv_pool_mb"] += pool
    return out


def _layer_param_mb(model: Any) -> float:
    from hetu_galvatron_tpu.observability.telemetry import layer_param_mb

    return layer_param_mb(model)


def peak_mb(stages: Sequence[StageMemory]) -> float:
    return max((st.total_mb for st in stages), default=0.0)


def search_result_hbm_reason(
    strategy_list: Sequence[Any],
    pp_stage_list: Sequence[int],
    model: Any,
    *,
    global_bsz: int,
    chunks: int,
    pipeline_type: str,
    schedule_impl: str,
    hbm_gb: float,
    vocab_tp_sp: int = 1,
    vocab_sp: bool = False,
    vocab_sdp: bool = False,
    mixed_precision: bool = True,
) -> Optional[str]:
    """The search engine's HBM gate: evaluate a candidate plan (a
    ``SearchStrategy`` list + stage partition, the shape ``TaskResult``
    carries) through the SAME per-stage accounting and budget predicate
    ``cli/check.py --memory --hbm-gb`` applies to the written plan JSON —
    search == check parity, the ``analysis/eligibility.py`` discipline.
    None when the plan fits; otherwise :func:`hbm_budget_reason`'s
    string, which the engine logs for the pruned candidate."""
    layers = [s.to_runtime() for s in strategy_list]
    vocab = EmbeddingLMHeadStrategy(
        vtp=max(vocab_tp_sp, 1), vsp=bool(vocab_sp),
        embed_sdp=bool(vocab_sdp))
    stages = plan_stage_memory(
        layers, vocab, model, global_bsz=global_bsz, chunks=chunks,
        pp_division=pp_stage_list, pipeline_type=pipeline_type,
        schedule_impl=schedule_impl, mixed_precision=mixed_precision)
    return hbm_budget_reason(peak_mb(stages), hbm_gb)


def hbm_budget_reason(peak: float, hbm_gb: float) -> Optional[str]:
    """None when the peak fits the budget; otherwise the reason string —
    THE predicate both ``cli/check.py --memory --hbm-gb`` and the search
    engine's pruning hook evaluate (search == check parity)."""
    budget_mb = hbm_gb * 1024.0
    if peak <= budget_mb:
        return None
    return (f"predicted per-device peak {peak:.1f} MB exceeds the "
            f"--hbm-gb budget {hbm_gb:g} GB ({budget_mb:.0f} MB) — the "
            f"plan would OOM at launch")


# ---------------------------------------------------------------------------
# cost-model cross-check
# ---------------------------------------------------------------------------


def _cost_context(model: Any, chunks: int, world_size: int,
                  pipeline_type: str, mixed_precision: bool):
    """A CostContext carrying the SAME analytic quantities this module
    accounts with, so the cross-check isolates ARITHMETIC drift between
    the doctor and the cost model (a profiled context would conflate
    measurement noise with formula divergence)."""
    from hetu_galvatron_tpu.core.cost_model.cost import CostContext

    elem = 2 if mixed_precision else 4
    act1 = activation_per_sample_mb(model, elem)
    vparams = vocab_param_mb(model)
    degrees = []
    d = 1
    while d <= max(world_size, 1):
        degrees.append(d)
        d *= 2
    act_dict: Dict[Any, float] = {t: act1 / t for t in degrees}
    act_dict["checkpoint"] = checkpoint_per_sample_mb(model, elem)
    first_states = {t: 4 * vparams["embed"] / t for t in degrees}
    last_states = {t: 4 * (vparams["prenorm"] + vparams["head"]) / t
                   for t in degrees}
    off_states = {t: first_states[t] + last_states[t] for t in degrees}
    vact = {t: vocab_act_per_sample_mb(model, t, elem) for t in degrees}
    return CostContext(
        parameter_size=_layer_param_mb(model),
        seq_length=model.seq_length,
        hidden_size=model.hidden_size,
        layer_num=1,
        mixed_precision=mixed_precision,
        async_grad_reduce=True,
        pytorch_context_mem=0.0,
        sequence_parallel=True,
        pipeline_type=pipeline_type,
        tp_activation_per_bsz_dict=act_dict,
        other_memory_pp_off={
            "model_states": off_states,
            "activation": {t: vact[t]["first"] + vact[t]["last"]
                           for t in degrees}},
        other_memory_pp_on={
            "first_stage": {"model_states": first_states,
                            "activation": {t: vact[t]["first"]
                                           for t in degrees}},
            "last_stage": {"model_states": last_states,
                           "activation": {t: vact[t]["last"]
                                          for t in degrees}}},
    )


def _search_strategy(s: LayerStrategy):
    from hetu_galvatron_tpu.core.search_engine.strategies import (
        SearchStrategy,
    )

    return SearchStrategy(
        pp=s.pp_deg, tp=1 if s.sp else s.tp_size,
        sp=s.tp_size if s.sp else 1, cp=s.cp_size, dp=s.dp_size,
        dp_type=s.dp_type, checkpoint=s.checkpoint)


def cross_check_cost_model(
    layers: Sequence[LayerStrategy],
    vocab: EmbeddingLMHeadStrategy,
    model: Any,
    *,
    global_bsz: int,
    chunks: int,
    pp_division: Sequence[int],
    pipeline_type: str,
    world_size: int,
    mixed_precision: bool = True,
    tolerance: float = 1e-6,
) -> Tuple[Dict[str, float], List[str]]:
    """Evaluate ``cost.layer_memory_components`` / ``embed_memory_components``
    on the doctor's analytic context and compare per component against the
    doctor's own accounting (re-run under the HOST-engine convention —
    the convention the cost model defines, so the compiled engine's vocab
    replication premium and stage buffer never pollute the ratio).
    Returns ({component: ratio}, problems); a ratio off ~1.0 names the
    drifted component. The stage buffer, the replication premium and the
    KV pool are the doctor's OWN dimensions (that is the point of the
    pass) and are excluded from the ratio by construction."""
    from hetu_galvatron_tpu.core.cost_model.cost import (
        embed_memory_components,
        layer_memory_components,
    )

    ctx = _cost_context(model, chunks, world_size, pipeline_type,
                        mixed_precision)
    # the doctor's arithmetic under the cost model's own conventions
    stages = plan_stage_memory(
        layers, vocab, model, global_bsz=global_bsz, chunks=chunks,
        pp_division=pp_division, pipeline_type=pipeline_type,
        schedule_impl="host", mixed_precision=mixed_precision)
    pp = max(layers[0].pp_deg, 1)
    stage_of: List[int] = []
    for st, n in enumerate(pp_division):
        stage_of.extend([st % pp] * n)

    cm_states = [0.0] * pp
    cm_act = [0.0] * pp
    for i, s in enumerate(layers):
        st = stage_of[i] if i < len(stage_of) else pp - 1
        comp = layer_memory_components(
            _search_strategy(s), ctx, global_bsz, max(chunks, 1),
            stage_idx=st, pipeline_type=pipeline_type)
        cm_states[st] += comp["model_states_mb"]
        cm_act[st] += comp["activation_mb"]

    vs = _search_strategy(layers[0])
    from dataclasses import replace as _replace

    from hetu_galvatron_tpu.utils.strategy import DPType

    stage_world = layers[0].tp_size * layers[0].cp_size * layers[0].dp_size
    vtp, vcp = max(vocab.vtp, 1), max(vocab.vcp, 1)
    vdp = max(stage_world // vtp // vcp, 1)
    vs = _replace(vs, tp=vtp, sp=1, cp=vcp, dp=vdp,
                  dp_type=DPType.ZERO3 if vocab.embed_sdp else DPType.DDP,
                  checkpoint=False, is_vocab=True)
    vcomp = embed_memory_components(vs, ctx, global_bsz, max(chunks, 1),
                                    pipeline_type=pipeline_type)

    problems: List[str] = []
    ratios: Dict[str, float] = {}

    def check(name: str, doctor: float, cost: float) -> None:
        if doctor < 1e-12 and cost < 1e-12:
            return
        ratio = doctor / cost if cost > 1e-12 else float("inf")
        ratios[name] = ratio
        if abs(ratio - 1.0) > tolerance:
            problems.append(
                f"memory cross-check: component '{name}' diverged — "
                f"doctor {doctor:.3f} MB vs cost model {cost:.3f} MB "
                f"(ratio {ratio:.4f}; the two accountings must agree)")

    doc_states = sum(st.components["model_states_mb"] for st in stages)
    doc_act = sum(st.components["activation_mb"] for st in stages)
    check("layer_model_states", doc_states, sum(cm_states))
    check("layer_activation", doc_act, sum(cm_act))
    # vocab: the cost model bills the first/last stages only; compare the
    # doctor's first/last rows (the compiled replication premium on middle
    # stages is deliberately outside the ratio)
    doc_v_states = (stages[0].components["vocab_states_mb"]
                    + (stages[-1].components["vocab_states_mb"]
                       if pp > 1 else 0.0))
    doc_v_act = (stages[0].components["vocab_activation_mb"]
                 + (stages[-1].components["vocab_activation_mb"]
                    if pp > 1 else 0.0))
    cm_v_states = vcomp["model_states_mb"][0] + (
        vcomp["model_states_mb"][-1] if pp > 1 else 0.0)
    cm_v_act = vcomp["activation_mb"][0] + (
        vcomp["activation_mb"][-1] if pp > 1 else 0.0)
    check("vocab_model_states", doc_v_states, cm_v_states)
    check("vocab_activation", doc_v_act, cm_v_act)
    return ratios, problems


# ---------------------------------------------------------------------------
# the doctor report
# ---------------------------------------------------------------------------


@dataclass
class MemoryDoctorReport:
    """Full verdict: per-stage component table, peak, cross-check ratios,
    and the budget-gate outcome. ``ok`` is False for malformed plans, a
    busted --hbm-gb budget, or a cross-check divergence."""

    plan: str
    world_size: Optional[int] = None
    ok: bool = True
    errors: List[str] = field(default_factory=list)
    warnings: List[str] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)
    stages: List[StageMemory] = field(default_factory=list)
    ratios: Dict[str, float] = field(default_factory=dict)
    hbm_gb: Optional[float] = None

    @property
    def peak_mb(self) -> float:
        return peak_mb(self.stages)

    def render(self, out=None) -> None:
        out = out or sys.stdout
        w = lambda s="": print(s, file=out)
        w(f"== memory doctor: {self.plan} (world {self.world_size}) ==")
        for e in self.errors:
            w(f"ERROR: {e}")
        for x in self.warnings:
            w(f"warning: {x}")
        if self.stages:
            short = {"model_states_mb": "states", "activation_mb": "act",
                     "stage_buffer_mb": "buffer",
                     "vocab_states_mb": "vocab_st",
                     "vocab_activation_mb": "vocab_act",
                     "kv_pool_mb": "kv_pool"}
            w("stage  " + "".join(f"{short[c]:>11}"
                                  for c in STAGE_COMPONENTS)
              + f"{'total':>11}  (MB)")
            for st in self.stages:
                cells = "".join(f"{st.components[c]:>11.2f}"
                                for c in STAGE_COMPONENTS)
                w(f"{st.stage:<7}{cells}{st.total_mb:>11.2f}")
            w(f"per-device peak: {self.peak_mb:.2f} MB"
              + (f" (budget {self.hbm_gb:g} GB)"
                 if self.hbm_gb is not None else ""))
        if self.ratios:
            pretty = ", ".join(f"{k}={v:.4f}"
                               for k, v in sorted(self.ratios.items()))
            w(f"cost-model cross-check ratios: {pretty}")
        for n in self.notes:
            w(f"note: {n}")
        w("memory doctor: " + ("OK" if self.ok else "FAILED"))


def diagnose_memory(
    plan: Union[str, Dict[str, Any]],
    model_cfg: Any,
    world_size: Optional[int] = None,
    *,
    hbm_gb: Optional[float] = None,
    serving: Any = None,
    schedule_impl: str = "compiled",
    mixed_precision: bool = True,
) -> MemoryDoctorReport:
    """Diagnose one plan's memory against one model config (and, in
    serving mode, one ServingArgs). Never raises on malformed input —
    every problem lands in ``report.errors`` (the plan-doctor contract)."""
    name = plan if isinstance(plan, str) else "<dict>"
    report = MemoryDoctorReport(plan=name, world_size=world_size,
                                hbm_gb=hbm_gb)
    if hbm_gb is not None and hbm_gb <= 0:
        report.ok = False
        report.errors.append(
            f"--hbm-gb must be a positive HBM budget in gigabytes, got "
            f"{hbm_gb!r}")
        return report

    try:
        cfg = load_strategy_config(plan) if isinstance(plan, str) else plan
        layers, vocab, extras = config2strategy(cfg)
    except (PlanFormatError, ValueError, TypeError) as e:
        report.ok = False
        report.errors.append(str(e))
        return report

    pp_deg = layers[0].pp_deg
    if world_size is None:
        world_size = pp_deg * max(s.tp_size * s.cp_size for s in layers)
        report.world_size = world_size
        report.warnings.append(
            f"no --world given; assuming the smallest world the plan fits "
            f"({world_size} devices)")
    try:
        layers, vocab, extras = config2strategy(cfg, world_size=world_size)
    except (PlanFormatError, ValueError) as e:
        report.ok = False
        report.errors.append(str(e))
        return report

    if max(vocab.vtp, 0) < 1:
        report.ok = False
        report.errors.append(
            f"vocab config: vtp must be >= 1 (got {vocab.vtp}) — the "
            "embedding/LM-head rows cannot be sharded over a zero-size "
            "group")
    n_layers = len(layers)
    if n_layers != model_cfg.num_hidden_layers and \
            model_cfg.model_type != "t5":
        report.ok = False
        report.errors.append(
            f"plan has {n_layers} layers, model has "
            f"{model_cfg.num_hidden_layers}")
    global_bsz = extras["global_bsz"]
    chunks = max(extras["chunks"], 1)
    vpp = max(extras.get("vpp_deg", 1), 1)
    pp_division = (extras["pp_division"]
                   or default_pp_division(n_layers, pp_deg * vpp))
    for st, n in enumerate(pp_division):
        if n <= 0:
            report.ok = False
            report.errors.append(
                f"pp_division stage {st} has {n} layers — a zero-layer "
                "stage holds no weights and starves the schedule")
    if sum(pp_division) != n_layers:
        report.ok = False
        report.errors.append(
            f"pp_division {list(pp_division)} != layer count {n_layers}")
    if report.errors:
        return report

    pipeline_type = extras["pipeline_type"]
    stages = plan_stage_memory(
        layers, vocab, model_cfg, global_bsz=global_bsz, chunks=chunks,
        pp_division=pp_division, pipeline_type=pipeline_type,
        schedule_impl=schedule_impl, mixed_precision=mixed_precision,
        serving=serving)
    report.stages = stages

    try:
        ratios, problems = cross_check_cost_model(
            layers, vocab, model_cfg, global_bsz=global_bsz,
            chunks=chunks, pp_division=pp_division,
            pipeline_type=pipeline_type, world_size=world_size,
            mixed_precision=mixed_precision)
    except ValueError as e:
        # the memory cost model REJECTS this shape outright (e.g.
        # chunks < pp cannot fill the 1F1B pipeline) — that is itself the
        # diagnosis, not a traceback
        report.ok = False
        report.errors.append(f"memory cost model rejects this plan shape: "
                             f"{e}")
        return report
    report.ratios = ratios
    if problems:
        report.ok = False
        report.errors.extend(problems)

    if schedule_impl == "compiled" and pp_deg > 1:
        report.notes.append(
            "vocab rows replicate across every stage under the compiled "
            "engine (split_params) — middle stages pay the premium the "
            "cost model bills to first/last only")
    if serving is not None:
        from hetu_galvatron_tpu.serving.kv_cache import resolve_num_blocks

        nb = resolve_num_blocks(serving, model_cfg)
        cap = serving.prefix_cache_max_blocks or 0
        budget = (f"{cap} blocks" if cap else
                  "bounded only by the pool")
        report.notes.append(
            f"serving: KV pool {nb} blocks of {serving.kv_block_size} "
            f"tokens; prefix-cache block budget {budget}"
            if serving.prefix_cache else
            f"serving: KV pool {nb} blocks of {serving.kv_block_size} "
            "tokens (prefix cache off)")

    if hbm_gb is not None:
        reason = hbm_budget_reason(report.peak_mb, hbm_gb)
        if reason is not None:
            report.ok = False
            report.errors.append(reason)
    return report
