"""Pass 2 — the jaxpr collective census.

The α-β cost model and ``plan_comm_volume`` predict what a plan SHOULD
communicate; PR 6's plan audit checks those predictions against a measured
device trace. This module closes the same loop from the STATIC side: trace
the hot-path programs with ``jax.make_jaxpr`` (no devices execute, no step
runs) and count the collectives the program actually contains, recursing
into pjit/shard_map/scan/remat/custom-vjp subjaxprs with scan trip-count
multipliers — so a program that silently grew an extra ring hop, lost a
``jax.named_scope`` trace marker, or picked up a host callback in the step
path fails ``cli/check.py`` before any TPU time is burned.

What the census can and cannot see (documented, not hidden): jaxpr-level
collectives are the EXPLICIT ones — the shard_map kernels' ``ppermute``
rings (tp overlap, cp ring attention, pp stage rotation), Ulysses
``all_to_all``, fused-CE ``psum``. GSPMD-inserted collectives (ZeRO
gathers, dp grad all-reduce under ``pjit``) materialize only at partition
time and are the measured audit's job. That split is exactly why the
predicted side (:func:`~hetu_galvatron_tpu.observability.telemetry.
plan_collective_counts`) predicts the explicit kernels' counts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# primitive name -> census category (explicit collectives only; GSPMD
# inserts the rest at partition time, invisible to a jaxpr)
COLLECTIVE_PRIMS: Dict[str, str] = {
    "ppermute": "ppermute",
    "pcollective_permute": "ppermute",
    "psum": "all_reduce",
    "all_gather": "all_gather",
    "psum_scatter": "reduce_scatter",
    "reduce_scatter": "reduce_scatter",
    "all_to_all": "all_to_all",
}

# host-callback primitives that must never ride a hot-path program (each
# one is a device->host sync per execution)
CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                  "outside_call", "host_callback")

# the named_scope markers the kernels stamp their permutes with so trace
# attribution (observability/trace_analysis.py _PERMUTE_MARKERS) can bill
# them to the right plan component; the census fails unmarked permutes so
# the attribution can never silently regress
PERMUTE_MARKERS: Tuple[str, ...] = ("tp_ring", "cp_ring", "pp_rotate",
                                    "dp_sched")


@dataclass
class CensusResult:
    """Executed-collective counts for one traced program."""

    counts: Dict[str, int] = field(default_factory=dict)
    # ppermute counts split by named_scope marker; key "<unmarked>" holds
    # permutes carrying none of PERMUTE_MARKERS
    permutes_by_marker: Dict[str, int] = field(default_factory=dict)
    # name-stack strings of unmarked permute eqns (diagnostics)
    unmarked_permutes: List[str] = field(default_factory=list)
    callbacks: List[str] = field(default_factory=list)
    donated_args: int = 0  # donated invars of the outermost pjit, if any
    notes: List[str] = field(default_factory=list)

    @property
    def total_collectives(self) -> int:
        return sum(self.counts.values())

    def merge_scaled(self, other: "CensusResult", mult: int) -> None:
        for k, v in other.counts.items():
            self.counts[k] = self.counts.get(k, 0) + v * mult
        for k, v in other.permutes_by_marker.items():
            self.permutes_by_marker[k] = \
                self.permutes_by_marker.get(k, 0) + v * mult
        self.unmarked_permutes.extend(other.unmarked_permutes)
        self.callbacks.extend(other.callbacks)
        for n in other.notes:
            if n not in self.notes:
                self.notes.append(n)


def _is_jaxpr(v: Any) -> bool:
    return hasattr(v, "eqns") and hasattr(v, "invars")


def _as_jaxpr(v: Any):
    """ClosedJaxpr -> Jaxpr; Jaxpr passes through; else None."""
    if _is_jaxpr(v):
        return v
    inner = getattr(v, "jaxpr", None)
    if inner is not None and _is_jaxpr(inner):
        return inner
    return None


def _sub_jaxprs(params: Dict[str, Any]):
    """(key, jaxpr) pairs for every subjaxpr value in an eqn's params —
    covers pjit/shard_map/scan/remat ('jaxpr'), custom vjp/jvp
    ('call_jaxpr'/'fun_jaxpr'/'fwd_jaxpr_thunk' is a thunk and skipped),
    and tuple-valued params like cond 'branches'."""
    for key, v in params.items():
        j = _as_jaxpr(v)
        if j is not None:
            yield key, j
            continue
        if isinstance(v, (tuple, list)):
            for x in v:
                j = _as_jaxpr(x)
                if j is not None:
                    yield key, j


def census_jaxpr(jaxpr: Any) -> CensusResult:
    """Count collectives in a (Closed)Jaxpr, recursing into subjaxprs.

    Multipliers: a ``scan`` body is counted ``length`` times (the schedule
    tick loop); ``while`` bodies have no static trip count, so their
    collectives are counted ONCE and flagged in ``notes``; ``cond``
    branches are counted as the element-wise max across branches (the
    program executes one of them), flagged when branches disagree.
    """
    out = CensusResult()
    j = _as_jaxpr(jaxpr)
    if j is None:
        raise TypeError(f"not a jaxpr: {type(jaxpr).__name__}")
    for eqn in j.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            cat = COLLECTIVE_PRIMS[name]
            out.counts[cat] = out.counts.get(cat, 0) + 1
            if cat == "ppermute":
                stack = str(getattr(eqn.source_info, "name_stack", ""))
                for marker in PERMUTE_MARKERS:
                    if marker in stack:
                        out.permutes_by_marker[marker] = \
                            out.permutes_by_marker.get(marker, 0) + 1
                        break
                else:
                    out.permutes_by_marker["<unmarked>"] = \
                        out.permutes_by_marker.get("<unmarked>", 0) + 1
                    out.unmarked_permutes.append(stack or "<no name stack>")
            continue
        if name in CALLBACK_PRIMS:
            cb = str(eqn.params.get("callback", name))
            out.callbacks.append(f"{name}: {cb}")
            continue
        if name == "cond":
            branches = [census_jaxpr(b)
                        for b in eqn.params.get("branches", ())]
            if branches:
                merged = branches[0]
                for b in branches[1:]:
                    if b.counts != merged.counts:
                        merged.notes.append(
                            "cond branches contain differing collective "
                            "counts; census takes the element-wise max")
                    for k, v in b.counts.items():
                        merged.counts[k] = max(merged.counts.get(k, 0), v)
                    for k, v in b.permutes_by_marker.items():
                        merged.permutes_by_marker[k] = max(
                            merged.permutes_by_marker.get(k, 0), v)
                    merged.unmarked_permutes.extend(b.unmarked_permutes)
                    merged.callbacks.extend(b.callbacks)
                out.merge_scaled(merged, 1)
            continue
        mult = 1
        if name == "scan":
            mult = int(eqn.params.get("length", 1))
        elif name == "while":
            sub = None
            for _, sj in _sub_jaxprs(eqn.params):
                sub = census_jaxpr(sj)
                if sub.total_collectives:
                    out.notes.append(
                        "while-loop body contains collectives; trip count "
                        "is dynamic so they are counted once")
                out.merge_scaled(sub, 1)
            continue
        if name == "pjit" and not out.counts and not out.donated_args:
            donated = eqn.params.get("donated_invars", ())
            out.donated_args = int(sum(bool(d) for d in donated))
        for _, sj in _sub_jaxprs(eqn.params):
            out.merge_scaled(census_jaxpr(sj), mult)
    return out


# ---------------------------------------------------------------------------
# tracing the hot-path programs (no devices execute)
# ---------------------------------------------------------------------------


def _tiny_batch(cfg: Any, global_bsz: int, seed: int = 0):
    import numpy as np

    rng = np.random.RandomState(seed)
    toks = rng.randint(0, cfg.padded_vocab_size,
                       (global_bsz, cfg.seq_length + 1))
    return {"tokens": toks[:, :-1].astype(np.int32),
            "labels": toks[:, 1:].astype(np.int32)}


def trace_compiled_step(cfg: Any, hpc: Any, train: Any, *,
                        tp_overlap: bool = True,
                        num_microbatches: Optional[int] = None,
                        devices: Optional[list] = None,
                        donate: bool = True):
    """Build the compiled 1F1B engine on (virtual CPU) devices, split
    freshly initialized params, and return
    ``(step ClosedJaxpr, overlap-ineligibility note or None)`` via
    ``CompiledPipelineEngine.step_jaxpr`` — tracing only, nothing executes
    a training step. Shared by the collective census (Pass 2) and the
    sharding-flow byte census (Pass 5); ``donate=False`` exists for the
    undonated-buffer drill."""
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.runtime.compiled_pipeline import (
        CompiledPipelineEngine,
    )

    import jax
    import jax.numpy as jnp

    eng = CompiledPipelineEngine(cfg, hpc, train, devices=devices,
                                 compute_dtype=jnp.float32,
                                 tp_overlap=tp_overlap, donate=donate)
    params, axes = init_causal_lm(jax.random.key(0), cfg)
    sp = eng.split_params(params, axes)
    so = eng.init_opt(sp, axes)
    jaxpr = eng.step_jaxpr(sp, so, _tiny_batch(cfg, hpc.global_bsz),
                           num_microbatches)
    note = None
    if tp_overlap and not eng.tp_overlap:
        note = f"tp_overlap requested but ineligible: {eng.overlap_reason}"
    return jaxpr, note


def census_compiled_step(cfg: Any, hpc: Any, train: Any, *,
                         tp_overlap: bool = True,
                         num_microbatches: Optional[int] = None,
                         devices: Optional[list] = None) -> CensusResult:
    """Trace the compiled single-program 1F1B step for a plan and census
    it (:func:`trace_compiled_step` + :func:`census_jaxpr`)."""
    jaxpr, note = trace_compiled_step(
        cfg, hpc, train, tp_overlap=tp_overlap,
        num_microbatches=num_microbatches, devices=devices)
    out = census_jaxpr(jaxpr)
    if note is not None:
        out.notes.append(note)
    return out


def trace_spmd_step(cfg: Any, hpc: Any, train: Any, mesh: Any,
                    *, tp_overlap: bool = True, hier_dp: bool = False,
                    dcn_slices: int = 1, hier_bucket_mb: float = 0.0,
                    dp_schedule: Optional[str] = None):
    """ClosedJaxpr of the pp=1 SPMD train step (``parallel.spmd``) —
    tracing only, nothing executes. Shared by the count census and the
    sharding-flow byte census; ``hier_dp`` traces the hierarchical dp
    gradient-reduction variant (``ops/hier_reduce.py``),
    ``hier_bucket_mb`` its bucketed software-pipelined flavour, and
    ``dp_schedule`` the synthesized-collective backend
    (``collectives/``) whose ppermutes carry the ``dp_sched`` marker."""
    import jax
    import jax.numpy as jnp

    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.parallel.spmd import make_spmd_train_step
    from hetu_galvatron_tpu.runtime.optimizer import make_optimizer

    params, axes = init_causal_lm(jax.random.key(0), cfg)
    tx = make_optimizer(train)
    step, pspecs, ospecs, _ = make_spmd_train_step(
        cfg, hpc, mesh, axes, tx, params, compute_dtype=jnp.float32,
        donate=True, tp_overlap=tp_overlap, hier_dp=hier_dp,
        dcn_slices=dcn_slices, hier_bucket_mb=hier_bucket_mb,
        dp_schedule=dp_schedule)
    sp_shape = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params)
    so_shape = jax.eval_shape(tx.init, sp_shape)
    batch = _tiny_batch(cfg, hpc.global_bsz)
    return jax.make_jaxpr(step)(sp_shape, so_shape, batch)


def census_spmd_step(cfg: Any, hpc: Any, train: Any, mesh: Any,
                     *, tp_overlap: bool = True, hier_dp: bool = False,
                     dcn_slices: int = 1, hier_bucket_mb: float = 0.0,
                     dp_schedule: Optional[str] = None) -> CensusResult:
    """Trace the pp=1 SPMD train step (``parallel.spmd``) and census it."""
    return census_jaxpr(trace_spmd_step(
        cfg, hpc, train, mesh, tp_overlap=tp_overlap, hier_dp=hier_dp,
        dcn_slices=dcn_slices, hier_bucket_mb=hier_bucket_mb,
        dp_schedule=dp_schedule))


def trace_serving_programs(cfg: Any, *, mesh: Any = None, hpc: Any = None,
                           bucket: Optional[int] = None,
                           serving: Any = None) -> Dict[str, Any]:
    """ClosedJaxprs of every serving program family
    (``ServingEngine.step_jaxprs``) on a throwaway engine — the shared
    trace entry for the count census and the sharding-flow byte census."""
    import jax

    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.serving.engine import ServingEngine

    params, axes = init_causal_lm(jax.random.key(0), cfg)
    kw = {}
    if mesh is not None:
        kw = {"mesh": mesh, "hpc": hpc, "axes_tree": axes}
    eng = ServingEngine(params, cfg, serving, **kw)
    try:
        return eng.step_jaxprs(bucket=bucket)
    finally:
        eng.close()


def census_serving_programs(cfg: Any, *, mesh: Any = None, hpc: Any = None,
                            bucket: Optional[int] = None,
                            serving: Any = None) -> Dict[str, CensusResult]:
    """Trace the serving prefill + decode programs (``serving/engine.py``)
    and census each — catches a host callback or an unmarked collective
    creeping into the token-latency path."""
    jaxprs = trace_serving_programs(cfg, mesh=mesh, hpc=hpc, bucket=bucket,
                                    serving=serving)
    return {name: census_jaxpr(j) for name, j in jaxprs.items()}


# ---------------------------------------------------------------------------
# census vs plan cross-check
# ---------------------------------------------------------------------------


def check_census(
    census: CensusResult,
    predicted: Optional[Dict[str, int]] = None,
    *,
    program: str = "step",
    allow_callbacks: bool = False,
) -> List[str]:
    """Problems (empty = clean): unmarked permutes, host callbacks in the
    hot path, and — when ``predicted`` counts are given
    (:func:`~hetu_galvatron_tpu.observability.telemetry.
    plan_collective_counts`) — any exact-count mismatch between what the
    plan arithmetic promises and what the traced program contains. The
    ppermute check is TOTAL-strict (per-marker counts AND the overall
    ppermute total must both match the prediction's sum, so a surplus
    permute in any category is caught); other explicit categories
    (psum from shard_map weight-cotangent transposes, all_to_all) are
    counted and reported but gated only when the prediction names them —
    their counts are partitioner-shaped, not plan arithmetic."""
    problems: List[str] = []
    n_unmarked = census.permutes_by_marker.get("<unmarked>", 0)
    if n_unmarked:
        where = "; ".join(sorted(set(census.unmarked_permutes))[:4])
        problems.append(
            f"{program}: {n_unmarked} collective-permute(s) carry no "
            f"tp_ring/cp_ring/pp_rotate/dp_sched named_scope marker "
            f"(trace attribution would mis-bill them) — name stacks: "
            f"{where}")
    if census.callbacks and not allow_callbacks:
        problems.append(
            f"{program}: host callback(s) in the hot path: "
            + "; ".join(sorted(set(census.callbacks))[:4]))
    if predicted is not None:
        marker_of = {"ppermute_tp": "tp_ring", "ppermute_cp": "cp_ring",
                     "ppermute_pp": "pp_rotate", "ppermute_dp": "dp_sched"}
        for key, want in sorted(predicted.items()):
            if key in marker_of:
                got = census.permutes_by_marker.get(marker_of[key], 0)
            else:
                got = census.counts.get(key, 0)
            if got != want:
                problems.append(
                    f"{program}: plan arithmetic predicts {want} x {key}, "
                    f"traced program contains {got}")
        # total-strict on permutes: a surplus ppermute under a marker the
        # prediction did not bill (or double-marked) must not pass just
        # because its own key was absent from `predicted`
        want_total = sum(v for k, v in predicted.items()
                         if k in marker_of)
        got_total = census.counts.get("ppermute", 0)
        if got_total != want_total:
            problems.append(
                f"{program}: plan arithmetic bills {want_total} "
                f"collective-permutes in total, traced program contains "
                f"{got_total}")
    return problems
