"""Pass 5 — the sharding-flow analysis: from collective COUNTS to BYTES.

PR 8's census (``analysis/census.py``) proved the hot-path programs
contain exactly the collectives the plan arithmetic promises — but a
program can pass the count gate while moving the wrong AMOUNT: a ring
hop that silently grew a replicated dimension, an activation resharded
twice at a layer boundary, a donated buffer that quietly stopped being
donated (live memory doubles). This module walks the same traced
programs (``CompiledPipelineEngine.step_jaxpr`` /
``ServingEngine.step_jaxprs``) and accounts the BYTES:

* **byte census** — per-collective message megabytes summed per category
  and per ``named_scope`` marker, with the census's scan trip-count
  multipliers, cross-checked EXACTLY (no tolerance) against
  ``observability/telemetry.py::plan_collective_bytes`` — the byte-side
  companion of ``plan_collective_counts``, derived from
  ``plan_comm_volume``'s message arithmetic. A program that moves one
  byte the plan does not predict fails ``cli/check.py``.
* **reshard detection** — explicit all-gathers materializing arrays the
  plan keeps sharded (a weight-sized gather in the step path means GSPMD
  or a kernel is un-sharding what the plan paid to shard), and
  double-resharded values (back-to-back ``sharding_constraint`` eqns
  with differing shardings: the value moves across the mesh twice where
  once suffices). Each finding names the offending program, eqn, and
  shape.
* **donation audit** — the outermost pjit's ``donated_invars`` weighed
  in megabytes: the train step must donate the majority of its input
  bytes (params + optimizer state; an undonated step double-buffers the
  model), and the largest undonated buffers are named.

What the jaxpr walk can and cannot see mirrors the census's documented
split: jaxpr-level bytes are the EXPLICIT collectives' (shard_map rings,
rotations, a2a); GSPMD-inserted collectives materialize at partition
time. For those, :func:`hlo_collectives` scans the PARTITIONED program's
compiled HLO text (counts + megabytes per collective category, plus
full-weight-sized all-gather detection) — compiling is expensive, so the
full-program HLO walk rides the slow tier
(``tests/analysis/test_sharding_flow.py``), not ``check --all``.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from hetu_galvatron_tpu.analysis.census import (
    COLLECTIVE_PRIMS,
    PERMUTE_MARKERS,
    _sub_jaxprs,
    _as_jaxpr,
)

MB = 1024 * 1024

# HLO dtype token -> bytes per element (the compiled-text walk)
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}


def _aval_mb(v: Any) -> float:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return 0.0
    n = 1
    for d in aval.shape:
        n *= int(d)
    return n * aval.dtype.itemsize / MB


@dataclass
class FlowResult:
    """Executed-collective megabytes for one traced program."""

    mb_by_cat: Dict[str, float] = field(default_factory=dict)
    # ppermute megabytes split by named_scope marker ("<unmarked>" pools
    # the rest, same contract as the count census)
    permute_mb_by_marker: Dict[str, float] = field(default_factory=dict)
    notes: List[str] = field(default_factory=list)

    @property
    def total_mb(self) -> float:
        return sum(self.mb_by_cat.values())

    def merge_scaled(self, other: "FlowResult", mult: float) -> None:
        for k, v in other.mb_by_cat.items():
            self.mb_by_cat[k] = self.mb_by_cat.get(k, 0.0) + v * mult
        for k, v in other.permute_mb_by_marker.items():
            self.permute_mb_by_marker[k] = \
                self.permute_mb_by_marker.get(k, 0.0) + v * mult
        for n in other.notes:
            if n not in self.notes:
                self.notes.append(n)


def flow_jaxpr(jaxpr: Any) -> FlowResult:
    """Byte-account the collectives of a (Closed)Jaxpr, recursing into
    subjaxprs with the census's multipliers: scan bodies count ``length``
    times, while bodies once (flagged — dynamic trip count), cond takes
    the branch with the larger collective total (flagged when branches
    disagree). Bytes are the SUMMED operand megabytes of each collective
    eqn — per-device payloads, since shard_map bodies trace local
    shapes."""
    out = FlowResult()
    j = _as_jaxpr(jaxpr)
    if j is None:
        raise TypeError(f"not a jaxpr: {type(jaxpr).__name__}")
    for eqn in j.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            cat = COLLECTIVE_PRIMS[name]
            mb = sum(_aval_mb(v) for v in eqn.invars)
            out.mb_by_cat[cat] = out.mb_by_cat.get(cat, 0.0) + mb
            if cat == "ppermute":
                stack = str(getattr(eqn.source_info, "name_stack", ""))
                for marker in PERMUTE_MARKERS:
                    if marker in stack:
                        out.permute_mb_by_marker[marker] = \
                            out.permute_mb_by_marker.get(marker, 0.0) + mb
                        break
                else:
                    out.permute_mb_by_marker["<unmarked>"] = \
                        out.permute_mb_by_marker.get("<unmarked>", 0.0) + mb
            continue
        if name == "cond":
            branches = [flow_jaxpr(b)
                        for b in eqn.params.get("branches", ())]
            if branches:
                best = max(branches, key=lambda b: b.total_mb)
                if any(not math.isclose(b.total_mb, best.total_mb)
                       for b in branches):
                    best.notes.append(
                        "cond branches move differing collective bytes; "
                        "byte census takes the larger branch")
                out.merge_scaled(best, 1.0)
            continue
        mult = 1.0
        if name == "scan":
            mult = float(eqn.params.get("length", 1))
        elif name == "while":
            for _, sj in _sub_jaxprs(eqn.params):
                sub = flow_jaxpr(sj)
                if sub.total_mb:
                    out.notes.append(
                        "while-loop body moves collective bytes; trip "
                        "count is dynamic so they are counted once")
                out.merge_scaled(sub, 1.0)
            continue
        for _, sj in _sub_jaxprs(eqn.params):
            out.merge_scaled(flow_jaxpr(sj), mult)
    return out


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------


@dataclass
class DonationReport:
    """Megabyte-weighed view of the outermost pjit's donated_invars."""

    donated_mb: float = 0.0
    undonated_mb: float = 0.0
    # (shape string, mb) of the largest undonated inputs, descending
    largest_undonated: List[Tuple[str, float]] = field(default_factory=list)

    @property
    def majority_donated(self) -> bool:
        return self.donated_mb >= self.undonated_mb


def donation_report(jaxpr: Any, top: int = 4) -> DonationReport:
    """Weigh the outermost pjit's donation decisions: which input bytes
    the program consumes in place vs double-buffers."""
    rep = DonationReport()
    j = _as_jaxpr(jaxpr)
    if j is None:
        return rep
    for eqn in j.eqns:
        if eqn.primitive.name != "pjit":
            continue
        donated = eqn.params.get("donated_invars", ())
        undonated: List[Tuple[str, float]] = []
        for v, d in zip(eqn.invars, donated):
            mb = _aval_mb(v)
            if d:
                rep.donated_mb += mb
            else:
                rep.undonated_mb += mb
                aval = getattr(v, "aval", None)
                undonated.append((str(aval) if aval is not None
                                  else "<unknown>", mb))
        undonated.sort(key=lambda t: -t[1])
        rep.largest_undonated = undonated[:top]
        break
    return rep


def check_donation(rep: DonationReport, *, program: str) -> List[str]:
    """The train-step donation gate: the fused optimizer step must donate
    the MAJORITY of its input bytes (params + opt state dominate; an
    undonated step holds the old and new model states simultaneously —
    live memory doubles). Serving programs keep their params resident by
    design and must NOT run through this check."""
    if rep.majority_donated and rep.donated_mb > 0:
        return []
    worst = "; ".join(f"{shape} ({mb:.2f} MB)"
                      for shape, mb in rep.largest_undonated[:3])
    return [
        f"{program}: donated {rep.donated_mb:.2f} MB but left "
        f"{rep.undonated_mb:.2f} MB undonated — the step must donate "
        f"(params, opt) or live memory doubles; largest undonated "
        f"buffers: {worst or '<none>'}"]


# ---------------------------------------------------------------------------
# reshard detection
# ---------------------------------------------------------------------------


def reshard_findings(jaxpr: Any, *, program: str,
                     gather_mb: float = 1.0,
                     _path: str = "") -> List[str]:
    """Static reshard lint over one traced program:

    * an explicit ``all_gather`` whose OUTPUT is at least ``gather_mb``
      megabytes — an array the plan keeps sharded being materialized in
      full (a weight gather in the step path un-does the plan's sharding
      every step);
    * a ``sharding_constraint`` whose operand comes STRAIGHT from another
      ``sharding_constraint`` with a different sharding — the value is
      moved across the mesh twice where one placement suffices (double
      reshard); identical back-to-back constraints are reported as
      redundant notes-grade findings only if shardings differ.

    Findings name the program, the eqn path, and the offending shape
    (the plan-doctor contract: report everything, never raise).
    """
    problems: List[str] = []
    j = _as_jaxpr(jaxpr)
    if j is None:
        return problems
    constrained_by: Dict[Any, Any] = {}
    for i, eqn in enumerate(j.eqns):
        name = eqn.primitive.name
        where = f"{_path}eqn {i} ({name})"
        if name == "all_gather":
            # the hierarchical dp reduction's gather-back is DELIBERATE
            # re-materialization (the summed grads return to the params'
            # layout); its named_scope marker exempts it — anything else
            # weight-sized is still a finding
            stack = str(getattr(eqn.source_info, "name_stack", ""))
            if "hier_dp_ag" in stack:
                continue
            out_mb = sum(_aval_mb(v) for v in eqn.outvars)
            if out_mb >= gather_mb:
                aval = getattr(eqn.outvars[0], "aval", None)
                problems.append(
                    f"{program}: {where} all-gathers "
                    f"{aval.str_short() if aval is not None else '?'} "
                    f"({out_mb:.2f} MB) — an array the plan shards is "
                    "materialized in full every execution")
        elif name == "sharding_constraint":
            sh = str(eqn.params.get("sharding"))
            src = eqn.invars[0]
            prev = constrained_by.get(src)
            if prev is not None and prev != sh:
                aval = getattr(src, "aval", None)
                problems.append(
                    f"{program}: {where} re-reshards "
                    f"{aval.str_short() if aval is not None else '?'} "
                    f"from {prev} to {sh} — the value crosses the mesh "
                    "twice (double reshard); constrain it once at the "
                    "final placement")
            for ov in eqn.outvars:
                constrained_by[ov] = sh
        for key, sj in _sub_jaxprs(eqn.params):
            problems.extend(reshard_findings(
                sj, program=program, gather_mb=gather_mb,
                _path=f"{_path}eqn {i} ({name}) > "))
    return problems


# ---------------------------------------------------------------------------
# byte census vs plan cross-check
# ---------------------------------------------------------------------------

_MARKER_OF = {"ppermute_tp": "tp_ring", "ppermute_cp": "cp_ring",
              "ppermute_pp": "pp_rotate", "ppermute_dp": "dp_sched"}


def check_flow(
    flow: FlowResult,
    predicted: Optional[Dict[str, float]] = None,
    *,
    program: str = "step",
) -> List[str]:
    """Problems (empty = clean): when ``predicted`` megabytes are given
    (:func:`~hetu_galvatron_tpu.observability.telemetry.
    plan_collective_bytes`), every predicted marker's traced megabytes
    must match EXACTLY (float-equal within 1e-9 relative — the numbers
    are integer byte counts divided by 2**20), and the total ppermute
    megabytes must equal the prediction's sum (total-strict, mirroring
    the count census: surplus bytes under an unbilled marker are still
    caught). Unpredicted categories (psum transposes, a2a) are reported
    by the caller, not gated — their sizes are partitioner-shaped."""
    problems: List[str] = []
    if predicted is None:
        return problems

    def close(a: float, b: float) -> bool:
        return math.isclose(a, b, rel_tol=1e-9, abs_tol=1e-12)

    for key, want in sorted(predicted.items()):
        marker = _MARKER_OF.get(key)
        got = (flow.permute_mb_by_marker.get(marker, 0.0)
               if marker else flow.mb_by_cat.get(key, 0.0))
        if not close(got, want):
            problems.append(
                f"{program}: plan arithmetic predicts {want:.6f} MB of "
                f"{key}, traced program moves {got:.6f} MB")
    want_total = sum(v for k, v in predicted.items() if k in _MARKER_OF)
    got_total = flow.mb_by_cat.get("ppermute", 0.0)
    if not close(got_total, want_total):
        problems.append(
            f"{program}: plan arithmetic bills {want_total:.6f} MB of "
            f"collective-permute traffic in total, traced program moves "
            f"{got_total:.6f} MB")
    return problems


# ---------------------------------------------------------------------------
# program-level entries (shared trace hooks with the count census)
# ---------------------------------------------------------------------------


@dataclass
class ProgramFlow:
    """One program's full sharding-flow verdict."""

    name: str
    flow: FlowResult
    donation: DonationReport
    reshard_problems: List[str] = field(default_factory=list)


def flow_compiled_step(cfg: Any, hpc: Any, train: Any, *,
                       tp_overlap: bool = True,
                       num_microbatches: Optional[int] = None,
                       devices: Optional[list] = None,
                       donate: bool = True,
                       gather_mb: float = 1.0) -> ProgramFlow:
    """Trace the compiled 1F1B step (``census.trace_compiled_step`` — the
    same hook the count census uses) and run the full byte-side analysis
    on it. ``donate=False`` exists for the undonated-buffer drill."""
    from hetu_galvatron_tpu.analysis.census import trace_compiled_step

    jaxpr, note = trace_compiled_step(
        cfg, hpc, train, tp_overlap=tp_overlap,
        num_microbatches=num_microbatches, devices=devices, donate=donate)
    flow = flow_jaxpr(jaxpr)
    if note is not None:
        flow.notes.append(note)
    return ProgramFlow(
        name="compiled_step", flow=flow,
        donation=donation_report(jaxpr),
        reshard_problems=reshard_findings(
            jaxpr, program="compiled_step", gather_mb=gather_mb))


def flow_spmd_step(cfg: Any, hpc: Any, train: Any, mesh: Any, *,
                   tp_overlap: bool = True, hier_dp: bool = False,
                   dcn_slices: int = 1, hier_bucket_mb: float = 0.0,
                   dp_schedule: Optional[str] = None,
                   gather_mb: float = 1.0) -> ProgramFlow:
    """Trace the pp=1 SPMD train step (``census.trace_spmd_step``) and run
    the full byte-side analysis — the hook the hierarchical-dp drill uses
    to cross-check the reduce-scatter/all-reduce/all-gather payloads
    (per-bucket under ``hier_bucket_mb``) against
    ``plan_collective_bytes`` exactly."""
    from hetu_galvatron_tpu.analysis.census import trace_spmd_step

    jaxpr = trace_spmd_step(cfg, hpc, train, mesh, tp_overlap=tp_overlap,
                            hier_dp=hier_dp, dcn_slices=dcn_slices,
                            hier_bucket_mb=hier_bucket_mb,
                            dp_schedule=dp_schedule)
    return ProgramFlow(
        name="spmd_step", flow=flow_jaxpr(jaxpr),
        donation=donation_report(jaxpr),
        reshard_problems=reshard_findings(
            jaxpr, program="spmd_step", gather_mb=gather_mb))


def flow_serving_programs(cfg: Any, *, mesh: Any = None, hpc: Any = None,
                          bucket: Optional[int] = None,
                          serving: Any = None,
                          gather_mb: float = 1.0) -> Dict[str, ProgramFlow]:
    """Byte-side analysis of every serving program family. The donation
    audit is informational here (params legitimately stay undonated —
    they persist across calls); the reshard lint gates."""
    from hetu_galvatron_tpu.analysis.census import trace_serving_programs

    jaxprs = trace_serving_programs(cfg, mesh=mesh, hpc=hpc, bucket=bucket,
                                    serving=serving)
    out = {}
    for name, j in jaxprs.items():
        out[name] = ProgramFlow(
            name=name, flow=flow_jaxpr(j), donation=donation_report(j),
            reshard_problems=reshard_findings(
                j, program=f"serving {name}", gather_mb=gather_mb))
    return out


# ---------------------------------------------------------------------------
# partition-time walk (compiled HLO text) — the slow tier
# ---------------------------------------------------------------------------

_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*(\(?[^=]*?)\s*"
    r"(all-gather|all-reduce|reduce-scatter|collective-permute|all-to-all)"
    r"(-start)?\(")
_HLO_SHAPE_RE = re.compile(r"(\w+)\[([0-9,]*)\]")


def _shape_mb(dtype: str, dims: str) -> Optional[float]:
    elem = _HLO_DTYPE_BYTES.get(dtype)
    if elem is None:
        return None
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * elem / MB


def hlo_collectives(hlo_text: str, *, weight_gather_mb: Optional[float]
                    = None) -> Tuple[Dict[str, Dict[str, float]], List[str]]:
    """Scan a PARTITIONED program's HLO text for the collectives GSPMD
    inserted (invisible to a jaxpr): returns
    ``({category: {count, mb}}, findings)``. With ``weight_gather_mb``
    set, any all-gather whose result is at least that many megabytes is a
    finding — a full weight being re-materialized at partition time means
    the lowered program un-shards what the plan shards (the implicit
    GSPMD weight gather this pass exists to catch).

    Async pairs: the ``-start`` op carries the payload and its tuple
    result lists (operand shard, gathered result) — the LARGEST shape in
    the result is taken, so an async full-weight gather is measured by
    its gathered size, not its pre-gather shard; ``-done`` halves carry
    no new bytes and are skipped."""
    cats: Dict[str, Dict[str, float]] = {}
    findings: List[str] = []
    for line_no, line in enumerate(hlo_text.splitlines(), 1):
        m = _HLO_COLLECTIVE_RE.search(line)
        if m is None:
            continue
        result_seg, op = m.group(1), m.group(2)
        shapes = [(_shape_mb(d, dims), d, dims)
                  for d, dims in _HLO_SHAPE_RE.findall(result_seg)]
        shapes = [s for s in shapes if s[0] is not None]
        if not shapes:
            continue
        mb, dtype, dims = max(shapes, key=lambda s: s[0])
        slot = cats.setdefault(op, {"count": 0, "mb": 0.0})
        slot["count"] += 1
        slot["mb"] += mb
        if (op == "all-gather" and weight_gather_mb is not None
                and mb >= weight_gather_mb):
            findings.append(
                f"partitioned HLO line {line_no}: all-gather materializes "
                f"{dtype}[{dims}] ({mb:.2f} MB) — a plan-sharded weight "
                "is re-gathered at partition time")
    return cats, findings
