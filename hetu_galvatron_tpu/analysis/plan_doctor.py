"""Pass 1 — the plan doctor: static per-layer engine/kernel diagnosis.

Given a strategy-plan JSON and a model config, report — on CPU, with no
devices and no training step — exactly what the runtime will do with the
plan: which pipeline engine it gets (compiled single-program 1F1B vs the
host-sequenced engine vs the pp=1 SPMD path) and why, which attention
kernel and projection path each layer runs (ring / ulysses / flash / XLA,
ring-overlap vs GSPMD collectives), and every structural problem with the
plan itself. Malformed JSONs produce actionable diagnostics naming the
offending key (``utils.strategy.PlanFormatError``), never a traceback.

All eligibility decisions are evaluated through
``analysis/eligibility.py`` — the SAME predicates the runtime and the cost
model call — so the doctor's verdict is the runtime's verdict.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from hetu_galvatron_tpu.analysis import eligibility
from hetu_galvatron_tpu.utils.strategy import (
    PlanFormatError,
    config2strategy,
    default_pp_division,
    form_strategy,
    load_strategy_config,
)


@dataclass
class LayerDiagnosis:
    """What one decoder layer will get at runtime."""

    index: int
    stage: int
    strategy: str       # form_strategy text
    attention: str      # ring / ring(zigzag) / ulysses_a2a / flash / xla
    projections: str    # ring_overlap / gspmd
    overlap_reason: Optional[str] = None  # why projections stay on gspmd


@dataclass
class PlanDoctorReport:
    """The doctor's full verdict; ``ok`` is False only for plans the
    runtime would REJECT (fallbacks to another engine are warnings)."""

    plan: str
    world_size: Optional[int] = None
    ok: bool = True
    errors: List[str] = field(default_factory=list)
    warnings: List[str] = field(default_factory=list)
    engine: Optional[str] = None         # compiled | host | spmd
    engine_reason: Optional[str] = None  # why not the compiled engine
    summary: Optional[str] = None        # hpc.describe()-style line
    layers: List[LayerDiagnosis] = field(default_factory=list)

    def render(self, out=None) -> None:
        out = out or sys.stdout
        w = lambda s="": print(s, file=out)
        w(f"== plan doctor: {self.plan} (world {self.world_size}) ==")
        for e in self.errors:
            w(f"ERROR: {e}")
        for x in self.warnings:
            w(f"warning: {x}")
        if self.summary:
            w(f"plan: {self.summary}")
        if self.engine:
            line = f"pipeline engine: {self.engine}"
            if self.engine_reason:
                line += f" ({self.engine_reason})"
            w(line)
        if self.layers:
            w(f"{'layer':<7}{'stage':<7}{'attention':<16}"
              f"{'projections':<14}strategy")
            for d in self.layers:
                w(f"{d.index:<7}{d.stage:<7}{d.attention:<16}"
                  f"{d.projections:<14}{d.strategy}")
            for d in self.layers:
                if d.overlap_reason:
                    w(f"  layer {d.index}: gspmd projections — "
                      f"{d.overlap_reason}")
        w("plan doctor: " + ("OK" if self.ok else "FAILED"))


def _attention_kernel(s: Any, cfg: Any, cp_zigzag: bool) -> str:
    """Mirror ``parallel.spmd.attention_overrides`` /
    ``CompiledPipelineEngine._build_attention_core`` dispatch, statically:
    cp layers get ring attention, Ulysses layers the head-scatter a2a
    sandwich, flash-enabled models the Pallas kernel on TPU, else the XLA
    core (GSPMD inserts the collectives)."""
    if s.cp_size > 1:
        return "ring(zigzag)" if cp_zigzag else "ring"
    if s.sp and s.tp_size > 1:
        return "ulysses_a2a"
    if cfg.use_flash_attn:
        return "flash(tpu)"
    return "xla"


def diagnose_plan(
    plan: Union[str, Dict[str, Any]],
    model_cfg: Any,
    world_size: Optional[int] = None,
    *,
    schedule_impl: str = "compiled",
    tp_overlap: bool = True,
    cp_zigzag: bool = False,
    data: Any = None,
) -> PlanDoctorReport:
    """Diagnose one plan against one model config.

    ``plan`` is a path to a plan JSON or an already-loaded dict.
    ``world_size`` defaults to the plan's own axis product (layer 0's
    pp*tp*cp*dp cannot be derived without it, so when omitted the smallest
    world the plan can run on is assumed and reported).
    ``schedule_impl``/``tp_overlap``/``cp_zigzag`` mirror the launcher
    knobs so the doctor predicts the engine the launcher would pick.
    Never raises on a malformed plan — problems land in ``report.errors``.
    """
    name = plan if isinstance(plan, str) else "<dict>"
    report = PlanDoctorReport(plan=name, world_size=world_size)

    try:
        cfg = load_strategy_config(plan) if isinstance(plan, str) else plan
    except PlanFormatError as e:
        report.ok = False
        report.errors.append(str(e))
        return report

    # -- parse (typed errors; never a KeyError traceback) -----------------
    try:
        # parse WITHOUT world first: a format-valid plan that merely
        # mismatches the world below still gets its per-layer table
        # (dp sizes unresolved), and the smallest-world default needs the
        # degrees before any world exists
        layers, vocab, extras = config2strategy(cfg)
    except (PlanFormatError, ValueError) as e:
        report.ok = False
        report.errors.append(str(e))
        return report

    pp_deg = layers[0].pp_deg
    if world_size is None:
        # smallest world the plan can express: pp * max(tp*cp) per layer
        world_size = pp_deg * max(
            s.tp_size * s.cp_size for s in layers)
        report.world_size = world_size
        report.warnings.append(
            f"no --world given; assuming the smallest world the plan fits "
            f"({world_size} devices)")
    try:
        layers, vocab, extras = config2strategy(cfg, world_size=world_size)
    except (PlanFormatError, ValueError) as e:
        # keep the world-less parse for the table; the dp degrees it
        # shows are the all-ones defaults, not resolved against the world
        report.ok = False
        report.errors.append(str(e))
        report.warnings.append(
            "plan does not fit the world size; the per-layer table below "
            "shows UNRESOLVED dp degrees (dp1)")

    n_layers = len(layers)
    model_layers = model_cfg.num_hidden_layers
    n_enc = 0
    if model_cfg.model_type == "t5":
        n_enc = (model_cfg.num_encoder_layers
                 if model_cfg.num_encoder_layers is not None
                 else model_cfg.num_hidden_layers)
        model_layers += n_enc
    if n_layers != model_layers:
        report.ok = False
        report.errors.append(
            f"plan has {n_layers} layers, model has {model_layers} "
            f"(encoder {n_enc} + decoder {model_cfg.num_hidden_layers})")
    if extras["num_encoder_layers"] not in (None, n_enc):
        report.ok = False
        report.errors.append(
            f"plan was searched for {extras['num_encoder_layers']} encoder "
            f"layers, model has {n_enc}")

    global_bsz = extras["global_bsz"]
    chunks = max(extras["chunks"], 1)
    vpp = max(extras.get("vpp_deg", 1), 1)
    pp_division = (extras["pp_division"]
                   or default_pp_division(n_layers, pp_deg * vpp))

    # -- structural checks (ALL of them, not just the first) --------------
    structural = eligibility.plan_structure_reasons(
        layers=layers, vocab=vocab, pp_deg=pp_deg, vpp_deg=vpp,
        pp_division=pp_division, n_layers=n_layers, world_size=world_size,
        global_bsz=global_bsz)
    if structural:
        report.ok = False
        report.errors.extend(structural)
    if global_bsz and global_bsz % chunks:
        report.ok = False
        report.errors.append(
            f"global_bsz {global_bsz} not divisible by chunks {chunks} "
            "(microbatches must be equal-shaped)")
    if pp_deg > 1 and chunks < pp_deg:
        report.warnings.append(
            f"chunks {chunks} < pp_deg {pp_deg}: the 1F1B schedule cannot "
            "fill the pipeline (the memory cost model rejects this shape)")

    # -- engine choice (the launcher's exact decision) --------------------
    class _Hpc:  # duck-typed view for the shared predicates
        pass

    hpc = _Hpc()
    hpc.layers, hpc.vocab, hpc.pp_deg = layers, vocab, pp_deg
    hpc.pp_division = pp_division
    hpc.pipeline_type = extras["pipeline_type"]
    hpc.vpp_deg = vpp
    hpc.chunks, hpc.global_bsz = chunks, global_bsz

    if pp_deg <= 1:
        report.engine = "spmd"
    elif schedule_impl == "compiled":
        reason = eligibility.compiled_unsupported_reason(
            model_cfg, hpc, data)
        if reason is None:
            report.engine = "compiled"
        else:
            report.engine = "host"
            report.engine_reason = reason
            report.warnings.append(
                "pipeline.schedule_impl=compiled cannot express this plan "
                f"({reason}); the launcher will fall back to the host "
                "engine")
    else:
        report.engine = "host"

    # -- per-layer kernel dispatch ----------------------------------------
    overlap = dict(eligibility.plan_overlap_reasons(model_cfg, hpc)) \
        if tp_overlap else {}
    stage_of: List[int] = []
    for stage, n in enumerate(pp_division):
        stage_of.extend([stage % max(pp_deg, 1)] * n)
    for i, s in enumerate(layers):
        reason = overlap.get(i) if tp_overlap else \
            "tp_overlap.enable is off"
        report.layers.append(LayerDiagnosis(
            index=i,
            stage=stage_of[i] if i < len(stage_of) else -1,
            strategy=form_strategy(s),
            attention=_attention_kernel(s, model_cfg, cp_zigzag),
            projections=("ring_overlap" if tp_overlap and reason is None
                         else "gspmd"),
            overlap_reason=reason,
        ))

    from hetu_galvatron_tpu.utils.strategy import print_strategies

    report.summary = (
        f"pp{pp_deg} chunks{chunks} bsz{global_bsz} "
        f"[{print_strategies(layers)}] vocab(vtp{vocab.vtp}"
        f"{' vsp' if vocab.vsp else ''})")
    return report
