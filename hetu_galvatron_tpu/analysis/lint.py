"""Pass 3 — custom AST lint over the package (stdlib ``ast`` only).

Six rules encode repo invariants that no off-the-shelf linter knows:

* **GAL001 host-sync-in-hot-path** — ``.item()`` / ``np.asarray`` /
  ``jax.device_get`` in the step-path modules (trainer, both pipeline
  engines, the SPMD assembly, the serving engine). Each one is a
  device->host sync that serializes async dispatch; the "no float() in the
  step loop" contract the CPU smoke test pins, made static.
* **GAL002 jit-in-loop** — ``jax.jit``/``.lower`` calls inside a
  ``for``/``while`` body: a recompile (or retrace) hazard when the loop is
  a step loop. Init-time loops are baselined with a justification.
* **GAL003 mesh-axis canon** — mesh axis-name string literals outside the
  ``runtime/mesh.py`` canon (``pp``, the binary ``d0..dk``, and the
  hierarchical dp reduction's ``slice``/``host`` sub-axes) in
  collective/PartitionSpec positions: a typo'd axis name fails at trace
  time with an opaque error, or silently shards nothing.
* **GAL004 dynamic named_scope** — f-strings/computed names in
  ``jax.named_scope``: trace attribution (``observability/
  trace_analysis.py``) matches markers by exact substring, so a dynamic
  scope name silently breaks permute billing.
* **GAL005 silent exception swallowing** — bare ``except:`` anywhere, and
  ``except Exception`` whose body is only ``pass``/``continue``: the audit
  path (crash-path ``finally`` blocks) must log what it swallows.
* **GAL006 env-read outside the schema** — ``os.environ[...]`` /
  ``os.environ.get`` / ``os.getenv`` anywhere but ``core/args_schema.py``
  and ``cli/``: configuration must flow through the validated schema, not
  ambient process state a run cannot reproduce from its config file.
  (Test/tool code is outside the package walk, so it is exempt by
  construction; audited legitimate hits — retry knobs, launcher env
  contracts — stay baselined with one-line justifications.)

Findings are identified by a line-number-free fingerprint
(rule:file:function:snippet#occurrence), so the committed baseline
(``analysis/lint_baseline.json`` — fingerprint -> one-line justification)
survives unrelated edits. The CI gate is ZERO NEW findings, not zero
findings: legitimate host-boundary syncs stay baselined, each with its
justification.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# step-path modules for GAL001 (relative to the package root).
# serving/scheduler.py and the observability event/recorder modules are
# included so request-lifecycle event emission can never quietly grow a
# host sync into the serving hot loop — events are host-side dicts by
# contract.
HOT_PATH_MODULES = (
    "runtime/trainer.py",
    "runtime/pipeline.py",
    "runtime/compiled_pipeline.py",
    "parallel/spmd.py",
    "serving/engine.py",
    "serving/scheduler.py",
    "observability/events.py",
    "observability/recorder.py",
)

# mesh axis-name canon (runtime/mesh.py): 'pp' + binary d-axes, plus the
# hierarchical dp reduction's slice/host sub-axes (mesh.hier_submesh /
# HIER_SLICE_AXIS / HIER_HOST_AXIS) — any other hand-rolled axis literal
# in the hierarchical path (or anywhere else) is a finding
_AXIS_CANON = re.compile(r"^(pp|d\d+|host|slice)$")

# modules where GAL006 permits ambient-environment reads: the schema is
# where config is DEFINED, and cli/ is the process boundary that feeds it
_ENV_EXEMPT_PREFIXES = ("cli/",)
_ENV_EXEMPT_FILES = ("core/args_schema.py",)

# collective calls whose axis-name argument is checked by GAL003:
# {callee name: positional index of the axis-name arg}
_AXIS_ARG_CALLS = {
    "ppermute": 1, "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1,
    "all_gather": 1, "psum_scatter": 1, "all_to_all": 1, "axis_index": 0,
}
# calls whose EVERY string argument is an axis name
_SPEC_CALLS = ("PartitionSpec", "P")

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "lint_baseline.json")


@dataclass
class Finding:
    rule: str
    path: str          # package-relative, '/'-separated
    line: int
    func: str          # enclosing function ('<module>' at top level)
    snippet: str       # normalized source of the offending expression
    message: str
    occurrence: int = 0  # index among same-snippet findings in one func

    @property
    def fingerprint(self) -> str:
        return (f"{self.rule}:{self.path}:{self.func}:{self.snippet}"
                f"#{self.occurrence}")

    def __str__(self) -> str:
        return (f"{self.path}:{self.line} [{self.rule}] {self.message} "
                f"(in {self.func})")


def _callee(node: ast.Call) -> str:
    """Dotted name of a call target ('jax.jit', 'np.asarray', 'item')."""
    f = node.func
    parts: List[str] = []
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts))


def _snippet(node: ast.AST, src_lines: List[str]) -> str:
    line = src_lines[node.lineno - 1].strip() if node.lineno <= \
        len(src_lines) else ""
    return line[:120]


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, src: str, hot_path: bool):
        self.path = path
        self.src_lines = src.splitlines()
        self.hot_path = hot_path
        self.env_exempt = (path in _ENV_EXEMPT_FILES
                           or path.startswith(_ENV_EXEMPT_PREFIXES))
        self.findings: List[Finding] = []
        self._func_stack: List[str] = []
        self._loop_depth = 0

    # -- helpers ----------------------------------------------------------

    @property
    def func(self) -> str:
        return self._func_stack[-1] if self._func_stack else "<module>"

    def _add(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=self.path, line=getattr(node, "lineno", 0),
            func=self.func, snippet=_snippet(node, self.src_lines),
            message=message))

    # -- scope / loop tracking -------------------------------------------

    def visit_FunctionDef(self, node):
        self._func_stack.append(node.name)
        # a def nested inside a loop runs its body only when CALLED, so
        # the enclosing loop must not taint jit-in-loop detection inside it
        outer_depth, self._loop_depth = self._loop_depth, 0
        self.generic_visit(node)
        self._loop_depth = outer_depth
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    # comprehensions ARE loops: jax.jit inside one is built per element
    visit_For = visit_While = _visit_loop
    visit_ListComp = visit_SetComp = visit_DictComp = _visit_loop
    visit_GeneratorExp = _visit_loop

    # -- the rules --------------------------------------------------------

    def visit_Call(self, node: ast.Call):
        callee = _callee(node)
        # GAL001: host syncs in step-path modules
        if self.hot_path:
            if ((callee == "item" or callee.endswith(".item"))
                    and isinstance(node.func, ast.Attribute)
                    and not node.args):
                self._add("GAL001", node,
                          ".item() forces a device->host sync")
            elif callee in ("np.asarray", "numpy.asarray", "onp.asarray"):
                self._add("GAL001", node,
                          "np.asarray on a device value pulls it to host")
            elif callee.endswith("device_get"):
                self._add("GAL001", node,
                          "jax.device_get forces a device->host transfer")
        # GAL002: jit construction / lowering inside a loop. The .lower
        # arm requires ARGUMENTS so jit AOT lowering (fn.lower(*avals))
        # matches but str.lower() — zero-arg by definition — never does.
        if self._loop_depth > 0 and (
                callee in ("jax.jit", "jit", "pjit", "jax.pjit")
                or (callee.endswith(".lower")
                    and bool(node.args or node.keywords))):
            self._add("GAL002", node,
                      f"{callee}() inside a loop is a recompile/retrace "
                      "hazard")
        # GAL003: axis-name literals outside the mesh canon
        short = callee.rsplit(".", 1)[-1]
        if short in _AXIS_ARG_CALLS:
            idx = _AXIS_ARG_CALLS[short]
            if idx < len(node.args):
                self._check_axis_literals(node.args[idx])
        elif short in _SPEC_CALLS:
            for a in node.args:
                self._check_axis_literals(a)
        # GAL006: ambient-environment reads outside the schema/CLI boundary
        if not self.env_exempt:
            if callee in ("os.getenv", "getenv"):
                self._add("GAL006", node,
                          "os.getenv outside core/args_schema.py / cli/ — "
                          "config must flow through the schema")
            elif callee in ("os.environ.get", "environ.get"):
                self._add("GAL006", node,
                          "os.environ.get outside core/args_schema.py / "
                          "cli/ — config must flow through the schema")
        # GAL004: dynamic named_scope names
        if short == "named_scope" and node.args:
            a = node.args[0]
            if isinstance(a, ast.JoinedStr):
                self._add("GAL004", node,
                          "f-string named_scope breaks trace-marker "
                          "matching (use a module-level constant)")
            elif not (isinstance(a, (ast.Constant, ast.Name, ast.Attribute))
                      or self._is_marker_preserving_scope(a)):
                self._add("GAL004", node,
                          "computed named_scope name breaks trace-marker "
                          "matching (use a module-level constant)")
        self.generic_visit(node)

    @staticmethod
    def _is_marker_preserving_scope(a: ast.AST) -> bool:
        """``hier_stage_scope(CONSTANT-or-NAME, ...)`` calls are
        marker-preserving by contract (ops/hier_reduce.py): the base scope
        stays a PREFIX of the returned name (bare at one bucket,
        ``_b{i}``-suffixed otherwise), so every substring consumer — trace
        attribution's ``_HIER_MARKERS``, the flow pass's ``hier_dp_ag``
        gather exemption — still matches. Only the first argument being a
        constant/name matters; a computed BASE would break matching and
        stays a finding."""
        if not (isinstance(a, ast.Call) and isinstance(
                a.func, (ast.Name, ast.Attribute))):
            return False
        fn = (a.func.id if isinstance(a.func, ast.Name)
              else a.func.attr)
        return (fn == "hier_stage_scope" and bool(a.args)
                and isinstance(a.args[0], (ast.Constant, ast.Name,
                                           ast.Attribute)))

    def _check_axis_literals(self, node: ast.AST) -> None:
        lits: List[Tuple[ast.AST, str]] = []
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            lits.append((node, node.value))
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    lits.append((e, e.value))
        for n, v in lits:
            if not _AXIS_CANON.match(v):
                self._add("GAL003", n,
                          f"mesh axis literal {v!r} is not in the "
                          "runtime/mesh.py canon (pp, d0..dk)")

    def visit_Subscript(self, node: ast.Subscript):
        # GAL006: os.environ["X"] reads (and writes — mutating the
        # process environment outside the CLI boundary is worse)
        if not self.env_exempt:
            v = node.value
            if (isinstance(v, ast.Attribute) and v.attr == "environ"
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "os"):
                self._add("GAL006", node,
                          "os.environ[...] outside core/args_schema.py / "
                          "cli/ — config must flow through the schema")
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if node.type is None:
            self._add("GAL005", node,
                      "bare 'except:' swallows KeyboardInterrupt/"
                      "SystemExit too — name the exception")
        elif (isinstance(node.type, ast.Name)
              and node.type.id in ("Exception", "BaseException")
              and all(isinstance(s, (ast.Pass, ast.Continue))
                      for s in node.body)):
            self._add("GAL005", node,
                      f"except {node.type.id} with a silent body hides "
                      "the audit trail — log what is swallowed")
        self.generic_visit(node)


def lint_file(path: str, rel: str, hot_path: bool) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(rule="GAL000", path=rel, line=e.lineno or 0,
                        func="<module>", snippet=str(e),
                        message=f"syntax error: {e.msg}")]
    v = _Visitor(rel, src, hot_path)
    v.visit(tree)
    _number_occurrences(v.findings)
    return v.findings


def _number_occurrences(findings: List[Finding]) -> None:
    seen: Dict[str, int] = {}
    for f in findings:
        key = f"{f.rule}:{f.path}:{f.func}:{f.snippet}"
        f.occurrence = seen.get(key, 0)
        seen[key] = f.occurrence + 1


def lint_package(root: Optional[str] = None) -> List[Finding]:
    """Lint every .py file of the installed package (``root`` defaults to
    the hetu_galvatron_tpu package directory). The canon source
    ``runtime/mesh.py`` is exempt from GAL003 (it DEFINES the axis names);
    this module and the baseline are data, not subjects."""
    if root is None:
        import hetu_galvatron_tpu

        root = os.path.dirname(os.path.abspath(hetu_galvatron_tpu.__file__))
    findings: List[Finding] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            fs = lint_file(full, rel, hot_path=rel in HOT_PATH_MODULES)
            if rel == "runtime/mesh.py":
                fs = [f for f in fs if f.rule != "GAL003"]
            # occurrence numbering is per-file (lint_file owns it; the
            # fingerprint key includes the path, so no cross-file renumber)
            findings.extend(fs)
    return findings


# ---------------------------------------------------------------------------
# baseline (committed accepted findings, each with a justification)
# ---------------------------------------------------------------------------


def load_baseline(path: Optional[str] = None) -> Dict[str, str]:
    path = path or DEFAULT_BASELINE  # resolved at call time (testable)
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        obj = json.load(f)
    return {k: str(v) for k, v in obj.get("findings", obj).items()}


def save_baseline(findings: List[Finding], path: Optional[str] = None,
                  keep: Optional[Dict[str, str]] = None) -> None:
    """Write the baseline for the CURRENT findings, preserving existing
    justifications; new entries get a TODO placeholder a human must
    replace (the gate treats TODO entries as accepted — the review
    happens at commit time, on the diff)."""
    path = path or DEFAULT_BASELINE
    keep = keep or {}
    out = {f.fingerprint: keep.get(f.fingerprint,
                                   "TODO: justify or fix")
           for f in findings}
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"findings": dict(sorted(out.items()))}, f, indent=1)
        f.write("\n")


def new_findings(findings: List[Finding],
                 baseline: Dict[str, str]) -> List[Finding]:
    return [f for f in findings if f.fingerprint not in baseline]


def stale_baseline(findings: List[Finding],
                   baseline: Dict[str, str]) -> List[str]:
    """Baselined fingerprints that no longer occur (fixed code — prune
    them so the baseline only ever shrinks in meaning)."""
    live = {f.fingerprint for f in findings}
    return [k for k in baseline if k not in live]


def prune_baseline(findings: List[Finding], path: Optional[str] = None
                   ) -> List[str]:
    """Drop the stale entries from the committed baseline IN PLACE and
    return the removed fingerprints. Unlike ``save_baseline`` (which
    rewrites the file from the CURRENT findings, adding TODO entries for
    new ones), this only ever REMOVES: live entries keep their
    justifications untouched and no new finding is auto-accepted — the
    safe way to clear a red stale-baseline gate after deleting code
    (``cli/check.py --prune-baseline``)."""
    path = path or DEFAULT_BASELINE
    baseline = load_baseline(path)
    stale = stale_baseline(findings, baseline)
    if not stale:
        return []
    kept = {k: v for k, v in baseline.items() if k not in stale}
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"findings": dict(sorted(kept.items()))}, f, indent=1)
        f.write("\n")
    return stale
