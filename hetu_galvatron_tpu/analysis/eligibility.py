"""Every plan-eligibility predicate, in one pure module.

Before this module existed, the predicates deciding which engine/kernel a
plan gets were duplicated across six files — ``runtime/compiled_pipeline.py``
(``unsupported_reason``), ``cli/train_dist.py`` (fallback logging),
``parallel/spmd.py`` (``tp_overlap_overrides``), ``ops/overlap.py``
(``layer_overlap_reason``), ``core/cost_model/cost.py``
(``compiled_expressible`` / ``tp_overlap_expressible``) and the structural
checks in ``runtime/hybrid_config.py`` — with nothing stopping the cost
model's gates from silently drifting away from what the runtime actually
accepts (the drift class PR 7's plan-flip tests could only spot-check).
All of those now CALL the functions here; the parity test
(``tests/analysis/test_eligibility_parity.py``) sweeps generated plans
through both sides to pin the contract.

Discipline: everything here is pure python over plain values (no jax, no
mesh, no devices) so the plan doctor (``analysis/plan_doctor.py``) can
evaluate a plan on a machine with no accelerator at all. Reason strings are
part of the contract — the launcher logs them and the plan doctor prints
them — so adapters must not rephrase.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

# ---------------------------------------------------------------------------
# compiled single-program 1F1B schedule expressibility
# ---------------------------------------------------------------------------


def compiled_schedule_unsupported_reason(
    *,
    pp_deg: int,
    pipeline_type: str,
    vpp_deg: int = 1,
    model_type: str = "gpt",
    num_experts: int = 0,
    pp_division: Sequence[int] = (),
    uniform_strategies: bool = True,
    packed_docs: bool = False,
) -> Optional[str]:
    """None when the compiled 1F1B schedule can express a plan with these
    properties; otherwise the human-readable reason every caller logs.

    This is the CANONICAL predicate: the runtime engine
    (``CompiledPipelineEngine.unsupported_reason``), the launcher's
    fallback log, and the cost model's dispatch-waiver gate
    (:func:`search_compiled_expressible`) all evaluate it — the search must
    never price the compiled schedule into a plan the runtime will then
    reject at startup (or vice versa).
    """
    if pp_deg < 2:
        return "pp_deg < 2 routes through the SPMD path"
    if pipeline_type != "pipedream_flush":
        return "compiled schedule implements 1F1B (pipedream_flush) only"
    if vpp_deg > 1:
        return "interleaved virtual stages (vpp > 1)"
    if model_type == "t5":
        return "encoder-decoder (a, b) pair carry"
    if num_experts:
        return "MoE layers alternate tree structures across the stack"
    if len(set(pp_division)) > 1:
        return (f"heterogeneous per-stage layer counts "
                f"{list(pp_division)} (stage stacking needs uniformity)")
    if not uniform_strategies:
        return "heterogeneous per-layer strategies"
    if packed_docs:
        return "packed-document position/segment fields"
    return None


def compiled_unsupported_reason(cfg: Any, hpc: Any,
                                data: Any = None) -> Optional[str]:
    """Runtime adapter: (ModelArgs, HybridParallelConfig, DataArgs) ->
    reason. cp / zigzag-cp plans are expressible since the engine
    de-vmapped its stage axis (the ring kernel runs inside the fused
    program as a stage-stacked full-manual shard_map)."""
    return compiled_schedule_unsupported_reason(
        pp_deg=hpc.pp_deg,
        pipeline_type=hpc.pipeline_type,
        vpp_deg=getattr(hpc, "vpp_deg", 1),
        model_type=cfg.model_type,
        num_experts=cfg.num_experts,
        pp_division=hpc.pp_division,
        uniform_strategies=all(s == hpc.layers[0] for s in hpc.layers),
        packed_docs=data is not None and (
            getattr(data, "reset_position_ids", False)
            or getattr(data, "reset_attention_mask", False)),
    )


def search_compiled_expressible(
    schedule_impl: str,
    pipeline_type: str,
    partition: Sequence[int],
    strategy_list: Sequence[Any],
) -> bool:
    """Cost-model adapter: can the dispatch-overhead waiver apply to this
    candidate (``cost_model.cost.pipeline_time_cost``)? The search works in
    degrees (SearchStrategy), not model configs, so the model-level gates
    (t5 / MoE / packed docs) are resolved by the caller's layertype setup;
    here the structural gates must agree with the runtime exactly."""
    if schedule_impl != "compiled":
        return False
    return compiled_schedule_unsupported_reason(
        pp_deg=max(len(partition), 2),  # pp>1 is the caller's precondition
        pipeline_type=pipeline_type,
        pp_division=partition,
        uniform_strategies=all(s == strategy_list[0] for s in strategy_list),
    ) is None


# ---------------------------------------------------------------------------
# overlapped-TP (ring ag/rs matmul) per-layer eligibility
# ---------------------------------------------------------------------------

# shared fallback-reason strings: the launcher's plan-level logging, the
# actual dispatch (parallel/spmd.py tp_overlap_overrides) and the plan
# doctor must all report the SAME reasons
T5_REASON = "t5 encoder-decoder layers keep the GSPMD projection path"
MOE_REASON = ("MoE layer: expert matmuls route through the ep/etp "
              "dispatcher, not the dense projections")


def overlap_unsupported_reason(
    cfg: Any,
    *,
    ulysses: bool,
    has_cp: bool,
    tp: int,
    seq_len: Optional[int] = None,
) -> Optional[str]:
    """Why one layer cannot run the decomposed ring-overlap matmuls
    (None = eligible). ``cfg`` supplies the concrete widths (seq_length,
    head_dim, heads, ffn_dim, hidden_act); the parallel degrees come in as
    plain values so both the mesh-lowered runtime and the degree-only
    search/doctor views evaluate the same predicate."""
    if ulysses:
        return ("ulysses layer: the tp axes carry sequence (all-to-all "
                "attention), not weight shards")
    if tp <= 1:
        return "tp == 1 (no tensor-parallel collectives to overlap)"
    if has_cp:
        return ("cp layer: the boundary activation is sequence-sharded "
                "over cp, not tp (ring attention owns the sequence axis)")
    seq = seq_len if seq_len is not None else cfg.seq_length
    if seq % tp:
        return (f"tp {tp} does not divide the sequence length {seq} into "
                "ring chunks")
    hd, nq, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.kv_heads
    if ((nq + 2 * nkv) * hd) % tp or (nq * hd) % tp:
        return f"tp {tp} does not divide the qkv/out projection widths"
    f = cfg.ffn_dim
    gated = cfg.hidden_act in ("swiglu", "geglu")
    if f % tp or (gated and (2 * f) % tp):
        return f"tp {tp} does not divide the MLP width {f}"
    return None


def layer_overlap_reason(cfg: Any, sharding: Any, tp: int,
                         seq_len: Optional[int] = None) -> Optional[str]:
    """Mesh-lowered adapter (the historical ``ops/overlap.py`` entry
    point): reads ulysses/cp off a :class:`~hetu_galvatron_tpu.runtime.
    mesh.LayerSharding`-shaped object."""
    return overlap_unsupported_reason(
        cfg,
        ulysses=bool(getattr(sharding, "ulysses", False)),
        has_cp=bool(getattr(sharding, "cp_axes", ())),
        tp=tp,
        seq_len=seq_len,
    )


def plan_overlap_reasons(cfg: Any, hpc: Any) -> List:
    """Per-layer eligibility from the PLAN alone (``hpc.layers``
    LayerStrategy rows; no mesh needed) — what
    ``parallel.spmd.tp_overlap_overrides`` will dispatch. Returns
    [(layer index, reason-or-None)]; reason None = the layer runs
    overlapped."""
    from hetu_galvatron_tpu.models.moe import is_moe_layer

    out = []
    for i, s in enumerate(hpc.layers):
        if cfg.model_type == "t5":
            out.append((i, T5_REASON))
            continue
        if is_moe_layer(cfg, i):
            out.append((i, MOE_REASON))
            continue
        out.append((i, overlap_unsupported_reason(
            cfg, ulysses=s.sp, has_cp=s.cp_size > 1, tp=s.tp_size)))
    return out


def search_tp_overlap_expressible(tp: int, cp: int, enabled: bool) -> bool:
    """Cost-model adapter (``cost_model.cost.tp_overlap_expressible``):
    can this candidate layer earn the ring-overlap discount? Megatron TP
    only (Ulysses has tp == 1 here) and no cp — the degree-level half of
    :func:`overlap_unsupported_reason` (the search works in degrees, not
    concrete widths, so the divisibility checks are resolved at plan-doctor
    / runtime time)."""
    return enabled and tp > 1 and cp == 1


# ---------------------------------------------------------------------------
# hierarchical dp/sdp gradient reduction eligibility (ops/hier_reduce.py)
# ---------------------------------------------------------------------------

# shared reason strings (launcher logging + plan doctor + engine ctors)
HIER_KERNEL_REASON = ("shard_map kernels (tp_overlap rings / flash / "
                      "ring-cp / ulysses a2a) cannot nest under the "
                      "hierarchical path's per-lane vmap")
HIER_DROPOUT_REASON = ("dropout: per-lane rng streams would draw masks "
                       "the flat path never draws (trajectories diverge "
                       "beyond reduction reassociation)")
HIER_ZIGZAG_REASON = ("zigzag-cp: sequences arrive pre-permuted for the "
                      "ring kernel's layout, and the lane path's GSPMD "
                      "attention would causally mask them by array order")


def hier_dp_unsupported_reason(
    *,
    dp: int,
    cp: int = 1,
    ulysses: bool = False,
    tp: int = 1,
    tp_consecutive: bool = True,
    uniform_strategies: bool = True,
    model_type: str = "gpt",
    num_experts: int = 0,
    dropout: float = 0.0,
    vtp: int = 1,
    vcp: int = 1,
    cp_zigzag: bool = False,
) -> Optional[str]:
    """None when the hierarchical dp gradient-reduction path can run this
    plan; otherwise the reason the launcher logs before keeping the flat
    GSPMD all-reduce. The same predicate gates the runtime engines, the
    cost model's hierarchical dp term
    (:func:`search_hier_dp_expressible`), and the count/byte predictions
    (``telemetry.plan_collective_counts/bytes``).

    cp/Ulysses-bearing sdp groups ARE eligible at the plan level: the lane
    vmap covers the dp axes (``spmd_axis_name`` takes the full dp-axis
    tuple) and the per-lane grads stay partial over the cp/sequence axes,
    which the in-lane partitioner reduces over the small ICI-local group —
    the big once-per-microbatch dp ring is still what moves out of the
    scan. The REMAINING cp/sp gate is a kernel-dispatch property: the
    pp>1 engines keep their stage-stacked ring/a2a kernels (cannot nest
    under the lane vmap — they raise :data:`HIER_KERNEL_REASON`), while
    the pp=1 SPMD path swaps those layers to the GSPMD attention core.
    Zigzag-cp stays ineligible here (:data:`HIER_ZIGZAG_REASON`): its
    dataloader-permuted layout is only correct under the ring kernel."""
    if not uniform_strategies:
        return ("heterogeneous per-layer strategies (one dp lane split "
                "must cover every layer)")
    if dp < 2:
        return "dp == 1 (no data-parallel gradient ring to decompose)"
    if cp_zigzag:
        return HIER_ZIGZAG_REASON
    if not tp_consecutive:
        return ("non-consecutive tp: the dp axes are not a contiguous "
                "leading mesh run, so they cannot regroup into "
                "slice x host sub-axes")
    if model_type == "t5":
        return "t5 encoder-decoder stacks keep the flat GSPMD reduction"
    if num_experts:
        return ("MoE layers: expert grads ride the ep/edp axes, not the "
                "plain dp lane split")
    if dropout > 0.0:
        return HIER_DROPOUT_REASON
    if vtp * vcp > tp * cp:
        return (f"vocab tp/cp degree {vtp * vcp} exceeds the layer "
                f"tp*cp {tp * cp}: the vocab weight axes would overlap "
                "the dp lane axes")
    return None


def plan_hier_dp_reason(cfg: Any, hpc: Any) -> Optional[str]:
    """Plan-level adapter: (ModelArgs, HybridParallelConfig) -> reason
    (None = the hierarchical path can run). Kernel nesting (tp_overlap /
    flash / ring) is a runtime dispatch property checked by the engines —
    this is the pure plan-shape half."""
    s = hpc.layers[0]
    return hier_dp_unsupported_reason(
        dp=s.dp_size,
        cp=s.cp_size,
        ulysses=s.sp,
        tp=s.tp_size,
        tp_consecutive=s.tp_consecutive,
        uniform_strategies=all(l == s for l in hpc.layers),
        model_type=cfg.model_type,
        num_experts=cfg.num_experts,
        dropout=max(cfg.hidden_dropout, cfg.attention_dropout),
        vtp=hpc.vocab.vtp,
        vcp=hpc.vocab.vcp,
        cp_zigzag=bool(getattr(hpc, "cp_zigzag", False)),
    )


def search_hier_dp_expressible(s: Any, enabled: bool) -> bool:
    """Cost-model adapter (``cost_model.cost``): can this candidate layer
    earn the hierarchical dp pricing? The degree-level half of
    :func:`hier_dp_unsupported_reason` — dp > 1; cp/Ulysses layers
    qualify on the pp=1 SPMD path only (the pp engines keep their
    stage-stacked ring/a2a kernels, which cannot nest under the lane vmap
    — :data:`HIER_KERNEL_REASON` — so the search must not price what the
    runtime will reject: search==runtime parity). The model-level gates
    (t5/MoE/dropout/zigzag/vocab overlap) are resolved by the runtime and
    the plan doctor."""
    if not (bool(enabled) and s.dp > 1):
        return False
    if s.cp == 1 and s.sp == 1:
        return True
    return s.pp == 1


DP_SCHEDULE_FAMILIES = ("ring", "tree_hd", "tree_bcast", "torus2d",
                        "hier_rings")
# the hand-built reference backends (collectives/reference.py) ride the
# same reducer seam for the bit-parity drills; they are not searched
DP_SCHEDULE_HANDBUILT = ("ring_handbuilt", "tree_handbuilt")


def dp_schedule_unsupported_reason(name: str, lanes: int, cross: int = 1,
                                   bucket_mb: float = 0.0
                                   ) -> Optional[str]:
    """Can an emitted collective schedule ``name``
    (``collectives/synthesize.py``) replace the hand-implemented
    hierarchical rs/ar/ag program for a ``lanes``-wide dp group split
    over ``cross`` slices? Pure shape arithmetic — the synthesis itself
    re-validates via the static verifier before emission."""
    if name not in DP_SCHEDULE_FAMILIES + DP_SCHEDULE_HANDBUILT:
        return (f"unknown dp schedule family {name!r} (expected one of "
                f"{DP_SCHEDULE_FAMILIES + DP_SCHEDULE_HANDBUILT})")
    if lanes < 2:
        return f"dp schedule needs dp > 1, got dp degree {lanes}"
    if bucket_mb > 0:
        return ("emitted dp schedules are monolithic; hier_bucket_mb > 0 "
                "only composes with the hand-implemented wavefront "
                "schedule")
    pow2 = lanes >= 2 and (lanes & (lanes - 1)) == 0
    if name in ("tree_hd", "tree_bcast", "ring_handbuilt",
                "tree_handbuilt") and not pow2:
        return (f"{name} needs a power-of-two dp group, got {lanes}")
    if name == "torus2d" and not (
            (cross >= 2 and lanes // cross >= 2)
            or (lanes >= 4 and lanes % 2 == 0)):
        return (f"torus2d needs a 2D-factorable dp group, got {lanes} "
                f"(cross {cross})")
    if name == "hier_rings" and not (cross >= 2 and lanes // cross >= 2):
        return (f"hier_rings needs cross >= 2 and intra >= 2, got dp "
                f"{lanes} over cross {cross}")
    return None


# ---------------------------------------------------------------------------
# plan structure (divisibility / stage sums / axis products)
# ---------------------------------------------------------------------------


def pp_world_reason(world_size: int, pp_deg: int) -> Optional[str]:
    if pp_deg >= 1 and world_size % pp_deg:
        return f"world {world_size} % pp {pp_deg} != 0"
    return None


def stage_degree_reason(world_size: int, pp_deg: int, tp: int,
                        cp: int) -> Optional[str]:
    stage = world_size // max(pp_deg, 1)
    if stage % (tp * cp):
        return f"stage world {stage} not divisible by tp{tp}*cp{cp}"
    return None


def vpp_layers_reason(pp_deg: int, vpp_deg: int,
                      n_layers: int) -> Optional[str]:
    if pp_deg * vpp_deg > n_layers:
        return (f"pp_deg {pp_deg} * virtual_pp_deg {vpp_deg} exceeds the "
                f"layer count {n_layers}")
    return None


def pp_division_sum_reason(pp_division: Sequence[int],
                           n_layers: int) -> Optional[str]:
    if sum(pp_division) != n_layers:
        return f"pp_division {list(pp_division)} != layer count {n_layers}"
    return None


def pp_division_len_reason(pp_division: Sequence[int], pp_deg: int,
                           vpp_deg: int) -> Optional[str]:
    if len(pp_division) != pp_deg * vpp_deg:
        return (f"pp_division has {len(pp_division)} entries, expected "
                f"pp_deg {pp_deg} * vpp_deg {vpp_deg} = {pp_deg * vpp_deg}")
    return None


def batch_grain_reason(global_bsz: int, world_size: int, pp_deg: int,
                       layers: Sequence[Any], vocab: Any) -> Optional[str]:
    """The batch must divide by the largest dp group any layer carves out
    (world // pp // min_tp // min_cp)."""
    min_tp = min(min(s.tp_size for s in layers), vocab.vtp)
    min_cp = min(min(s.cp_size for s in layers), vocab.vcp)
    grain = world_size // max(pp_deg, 1) // min_tp // min_cp
    if global_bsz % max(grain, 1):
        return (f"global_bsz {global_bsz} must be a multiple of "
                f"world//pp//min_tp//min_cp = {grain}")
    return None


def plan_structure_reasons(
    *,
    layers: Sequence[Any],
    vocab: Any,
    pp_deg: int,
    vpp_deg: int,
    pp_division: Sequence[int],
    n_layers: int,
    world_size: int,
    global_bsz: int,
) -> List[str]:
    """Every structural problem with a resolved plan, in the order
    ``runtime/hybrid_config.py`` raises them (it raises on the FIRST;
    the plan doctor reports them all)."""
    out: List[str] = []
    for r in (
        pp_world_reason(world_size, pp_deg),
        vpp_layers_reason(pp_deg, vpp_deg, n_layers),
        pp_division_sum_reason(pp_division, n_layers),
        pp_division_len_reason(pp_division, pp_deg, vpp_deg),
        batch_grain_reason(global_bsz, world_size, pp_deg, layers, vocab),
    ):
        if r is not None:
            out.append(r)
    return out
