"""Static analysis suite: plan doctor, collective census, AST lint,
memory doctor, sharding-flow analysis.

Five passes that run on CPU with no devices and no training step, so a
malformed, inexpressible, OOM-bound or byte-wasting plan is caught
BEFORE any TPU time is burned
(``python -m hetu_galvatron_tpu.cli.check``):

* :mod:`~hetu_galvatron_tpu.analysis.eligibility` — the ONE home of every
  plan-eligibility predicate (compiled-schedule expressibility, per-layer
  tp_overlap eligibility, plan-structure divisibility checks). The runtime
  engines, the launcher's fallback logging, and the cost model's
  expressibility gates all import from here, so they can never drift.
* :mod:`~hetu_galvatron_tpu.analysis.plan_doctor` — Pass 1: statically
  reports, per layer, which engine/kernels a plan will get and why, with
  actionable errors for malformed plan JSONs.
* :mod:`~hetu_galvatron_tpu.analysis.census` — Pass 2: trace the hot-path
  programs with ``jax.make_jaxpr`` and count their collectives (recursing
  into pjit/shard_map/scan subjaxprs), verify trace-marker coverage, and
  cross-check against the plan's predicted collective counts.
* :mod:`~hetu_galvatron_tpu.analysis.lint` — Pass 3: stdlib-``ast`` lint
  passes (host sync in hot paths, jit-in-loop, mesh-axis canon, dynamic
  named_scope, bare except, env reads outside the schema) with a
  committed baseline so the CI gate is zero-NEW-findings.
* :mod:`~hetu_galvatron_tpu.analysis.memory_doctor` — Pass 4: static
  per-device peak-HBM accounting (model states / activations / compiled
  stage buffer / vocab replication / serving KV pool) cross-checked per
  component against the search engine's memory cost model, with an
  ``--hbm-gb`` budget gate the search engine prunes with too.
* :mod:`~hetu_galvatron_tpu.analysis.sharding_flow` — Pass 5: the census
  extended from counts to BYTES (exact cross-check against
  ``telemetry.plan_collective_bytes``), reshard detection and the
  donation audit, plus the slow-tier partition-time HLO collective walk.
"""

from hetu_galvatron_tpu.analysis.eligibility import (  # noqa: F401
    compiled_schedule_unsupported_reason,
    compiled_unsupported_reason,
    layer_overlap_reason,
    overlap_unsupported_reason,
    plan_overlap_reasons,
    plan_structure_reasons,
    search_compiled_expressible,
    search_tp_overlap_expressible,
)
