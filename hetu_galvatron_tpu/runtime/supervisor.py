"""Preemption-aware training supervisor.

Two halves of surviving a preemptible TPU fleet:

* :class:`PreemptionGuard` — a SIGTERM/SIGINT handler that converts the
  kill signal into a *checkpoint-and-exit request* the train loop reads
  at the next step boundary (Cloud TPU preemption delivers SIGTERM with
  a ~30 s grace window; an uncheckpointed step is a lost step). The
  guard never acts mid-step: the loop finishes the in-flight update,
  commits an atomic checkpoint, and exits with
  :data:`EXIT_CODE_CHECKPOINT_AND_EXIT`.
* :func:`run_with_restarts` — bounded auto-restart with jittered
  exponential backoff around a training attempt, honoring the rerun
  state machine's exit-code contract (``rerun_machine.py``): code 16
  (resume-to-disambiguate) and preemption exits restart; code 17
  (failed result validation — a persistent fault that will reproduce)
  does not. Crashes (exceptions) restart too when ``restart_on_error``
  is set, so a drill-injected or real host crash resumes from the last
  committed checkpoint instead of losing the run. This is the
  IN-PROCESS loop (``supervisor.mode=inprocess``): right for drills,
  but the device list is frozen at backend init and a SIGKILL takes
  the supervisor down with the attempt.
* :class:`ProcessSupervisor` — the CROSS-PROCESS loop
  (``supervisor.mode=process``, ``cli/supervise.py``): relaunches
  ``train_dist`` as a child process per attempt, interprets the same
  exit-code contract plus signal deaths (negative waitpid codes),
  forwards SIGTERM with a kill-after-grace escalation, persists its
  state (attempt count, restart/world-change budgets, last-commit
  receipt) in a tmp+rename-atomic JSON file, and writes the
  ``RESUME_PIN`` lease before each relaunch so retention GC in the
  child can never prune the step dir the relaunch is restoring from.
  Deliberately jax-free (``runtime/ckpt_paths.py``): the supervisor
  must not grab the accelerator its child needs.

Every signal, restart, and give-up is counted in the observability
registry (``supervisor/*``) so fleet dashboards see preemption churn.
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import threading
import time
from dataclasses import asdict, dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

from hetu_galvatron_tpu.runtime import ckpt_paths
from hetu_galvatron_tpu.runtime.rerun_machine import (
    EXIT_CODE_FAILED_ON_RESULT_VALIDATION,
    EXIT_CODE_RESUME_TO_DISAMBIGUATE,
)
from hetu_galvatron_tpu.utils.retrying import backoff_delay

# checkpoint-and-exit after a preemption signal: resumable by contract,
# distinct from the rerun machine's 16/17 so the supervisor can tell
# "the fleet preempted me" from "my step result was suspect"
EXIT_CODE_CHECKPOINT_AND_EXIT = 18
# operator interrupt (SIGINT/Ctrl-C): checkpoints like a preemption but
# is NOT restartable — auto_restart must not resurrect a run the user
# deliberately stopped (128+SIGINT shell convention)
EXIT_CODE_INTERRUPTED = 130

# exit codes run_with_restarts treats as "resume from the last committed
# checkpoint"; 17 is deliberately absent — a persistent validation fault
# reproduces on every restart, so restarting only burns the budget
RESTARTABLE_EXIT_CODES = (
    EXIT_CODE_RESUME_TO_DISAMBIGUATE,
    EXIT_CODE_CHECKPOINT_AND_EXIT,
)


def _registry(registry=None):
    if registry is not None:
        return registry
    from hetu_galvatron_tpu.observability.registry import get_registry

    return get_registry()


class PreemptionGuard:
    """Converts SIGTERM/SIGINT into a step-boundary stop request.

    Use as a context manager around the train loop; ``requested()`` turns
    true once a signal arrives (a second signal of the same kind is
    idempotent). Handlers are installed only on the main thread — on a
    worker thread (some test harnesses) the guard degrades to an inert
    flag that :meth:`request` can still set programmatically (simulated
    preemption drills)."""

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,
                                                 signal.SIGINT),
                 *, enabled: bool = True, registry=None, recorder=None):
        self.signals = tuple(signals)
        self.enabled = enabled
        self._requested = threading.Event()
        self._previous = {}
        self._registry = registry
        # optional observability.recorder.FlightRecorder: the first
        # trapped signal dumps a postmortem (from the main thread, at the
        # boundary check — never inside the async handler); dump() never
        # raises, so the checkpoint-and-exit path is unaffected
        self.recorder = recorder
        self.installed = False
        self.signum: Optional[int] = None  # first signal that fired
        self._counted = False

    # -- signal plumbing ----------------------------------------------------

    def _handler(self, signum, frame):  # noqa: ARG002 — signal signature
        if self._requested.is_set():
            # second signal of the same escalation: the run is presumably
            # hung (stuck step, dead object-store mount) and will never
            # reach the boundary check — restore the previous handler and
            # re-deliver so the operator can still interrupt without
            # SIGKILL
            signal.signal(signum, self._previous.get(signum, signal.SIG_DFL))
            signal.raise_signal(signum)
            return
        self.request(signum=signum)

    def request(self, signum: Optional[int] = None) -> None:
        """Mark preemption as requested (signal handler or drill).
        Async-signal-safe: only sets a flag — no locks, no allocation
        (a registry counter here could deadlock on the non-reentrant
        registry lock the interrupted main thread may hold); the signal
        is counted later, from the main thread, in :meth:`requested`."""
        self._requested.set()
        if self.signum is None:
            self.signum = signum if signum is not None else -1

    def requested(self) -> bool:
        """Polled by the train loop at step boundaries (main thread) —
        also the safe place to count the signal for observability."""
        if self._requested.is_set() and not self._counted:
            self._counted = True
            try:
                name = (signal.Signals(self.signum).name
                        if self.signum not in (None, -1) else "drill")
            except ValueError:
                name = str(self.signum)
            _registry(self._registry).counter(
                "supervisor/preemption_signals", sig=name).inc()
            if self.recorder is not None:
                self.recorder.dump(f"signal:{name}")
        return self._requested.is_set()

    def exit_code(self) -> int:
        """Which checkpoint-and-exit code the triggering signal maps to:
        SIGINT = an operator's deliberate stop (non-restartable 130),
        everything else = fleet preemption (restartable 18)."""
        if self.signum == signal.SIGINT:
            return EXIT_CODE_INTERRUPTED
        return EXIT_CODE_CHECKPOINT_AND_EXIT

    def __enter__(self) -> "PreemptionGuard":
        self._requested.clear()
        self.signum = None
        self._counted = False
        if not self.enabled:
            return self
        for s in self.signals:
            try:
                self._previous[s] = signal.signal(s, self._handler)
                self.installed = True
            except ValueError:
                # not the main thread: signals cannot be trapped here;
                # stay an inert flag rather than failing the run
                self._previous.pop(s, None)
        return self

    def __exit__(self, *exc) -> None:
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._previous.clear()
        self.installed = False


def run_with_restarts(
    attempt_fn: Callable[[], Optional[int]],
    *,
    max_restarts: int = 3,
    base_delay: float = 1.0,
    max_delay: float = 60.0,
    restart_codes: Iterable[int] = RESTARTABLE_EXIT_CODES,
    restart_on_error: bool = True,
    progress_fn: Optional[Callable[[], Any]] = None,
    world_fn: Optional[Callable[[], Any]] = None,
    sleep: Optional[Callable[[float], None]] = None,
    rng: Optional[random.Random] = None,
    registry=None,
    log: Callable[[str], None] = lambda m: print(m, flush=True),
) -> int:
    """Run ``attempt_fn`` (returns an exit code; None/0 = success) with
    bounded auto-restart.

    Restartable exits (preemption, resume-to-disambiguate) and — when
    ``restart_on_error`` — crashes re-invoke ``attempt_fn`` after a
    jittered exponential backoff; the attempt is expected to resume from
    the last committed checkpoint. Non-restartable codes (0, 17, anything
    not listed) return immediately. When the restart budget is exhausted
    the last code is returned (or the last exception re-raised), so the
    process-level exit status still carries the fault classification.

    ``progress_fn`` (e.g. ``lambda: latest_checkpoint(save_dir)``) makes
    the budget bound crash LOOPS, not total faults: whenever its value
    changes between attempts (the attempt checkpointed new progress) the
    restart counter resets, so a healthy multi-day run on a preemptible
    fleet survives arbitrarily many preemptions while a run that loops
    without advancing still stops after ``max_restarts``.

    ``world_fn`` (e.g. ``lambda: len(jax.devices())``) makes a TOPOLOGY
    change progress too: when the visible world differs between attempts
    (half the fleet preempted away, or capacity returned) the next attempt
    re-searches and reshards rather than repeating the fault, so the
    restart counter resets exactly as a committed checkpoint would reset
    it — the budget bounds same-world crash loops, never elasticity.
    World changes are counted (``supervisor/world_changes``) so dashboards
    see fleet churn distinctly from crash churn."""
    if sleep is None:
        from hetu_galvatron_tpu.utils.retrying import _default_sleep as sleep
    restart_codes = tuple(restart_codes)
    reg = _registry(registry)
    restarts = 0
    last_progress = progress_fn() if progress_fn is not None else None
    last_world = world_fn() if world_fn is not None else None

    def note_progress() -> None:
        nonlocal restarts, last_progress, last_world
        advanced = False
        if world_fn is not None:
            world = world_fn()
            if world != last_world:
                reg.counter("supervisor/world_changes").inc()
                log(f"supervisor: world changed {last_world} -> {world}; "
                    "topology change is progress (restart budget reset)")
                last_world = world
                advanced = True
        if progress_fn is not None:
            cur = progress_fn()
            if cur != last_progress:
                advanced = True
                last_progress = cur
        if advanced:
            restarts = 0  # forward progress: this is not a crash loop

    while True:
        try:
            code = attempt_fn()
        except Exception as e:  # noqa: BLE001 — supervisor catches crashes
            note_progress()
            if not restart_on_error or restarts >= max_restarts:
                reg.counter("supervisor/giveups", reason="crash").inc()
                raise
            delay = backoff_delay(restarts, base=base_delay, cap=max_delay,
                                  rng=rng)
            reg.counter("supervisor/restarts", reason="crash").inc()
            # goodput accounting: backoff wall-clock is lost time (the
            # checkpoint-persisted goodput tracker books the full
            # commit-to-resume gap; this counter is the supervisor's own
            # receipt of the deliberately-slept share)
            reg.counter("supervisor/backoff_wait_s").inc(delay)
            log(f"supervisor: attempt crashed ({type(e).__name__}: {e}); "
                f"restart {restarts + 1}/{max_restarts} in {delay:.1f}s")
            restarts += 1
            sleep(delay)
            continue
        code = code or 0
        if code == 0:
            return 0
        if code not in restart_codes:
            if code == EXIT_CODE_FAILED_ON_RESULT_VALIDATION:
                log("supervisor: exit 17 (persistent validation fault) is "
                    "not restartable; surfacing it")
            reg.counter("supervisor/terminal_exits", code=code).inc()
            return code
        note_progress()
        if restarts >= max_restarts:
            reg.counter("supervisor/giveups", reason="budget").inc()
            log(f"supervisor: restart budget ({max_restarts}) exhausted; "
                f"surfacing exit code {code}")
            return code
        delay = backoff_delay(restarts, base=base_delay, cap=max_delay,
                              rng=rng)
        reg.counter("supervisor/restarts", code=code).inc()
        reg.counter("supervisor/backoff_wait_s").inc(delay)
        log(f"supervisor: exit code {code}; restart "
            f"{restarts + 1}/{max_restarts} in {delay:.1f}s")
        restarts += 1
        sleep(delay)


# ---------------------------------------------------------------------------
# Cross-process supervision
# ---------------------------------------------------------------------------


@dataclass
class SupervisorState:
    """Everything the restart loop must remember ACROSS its own deaths:
    persisted tmp+rename-atomically after every transition, reloaded at
    startup, so a supervisor that is itself preempted resumes with the
    budgets and receipts it had — not a fresh allowance."""

    attempt: int = 0                 # lifetime child launches
    restarts: int = 0                # consecutive no-progress restarts
    world_changes: int = 0           # budget spent on topology resets
    last_exit_code: Optional[int] = None
    last_commit_step: Optional[int] = None
    last_commit_wall: Optional[float] = None
    last_world: Optional[int] = None
    backoff_s: float = 0.0           # the delay currently being slept

    @classmethod
    def load(cls, path: Optional[str]) -> "SupervisorState":
        if not path:
            return cls()
        payload, _ = ckpt_paths.try_read_json(path)
        if not payload:
            return cls()
        st = cls()
        for k, v in payload.items():
            if hasattr(st, k):
                setattr(st, k, v)
        return st

    def save(self, path: Optional[str]) -> None:
        if path:
            ckpt_paths.atomic_write_json(path, asdict(self))


class ProcessSupervisor:
    """Relaunching outer wrapper around a ``train_dist`` child process.

    Exit-code contract (see ``cli/supervise.py`` for the operator view):

    * ``0`` — training complete; stop.
    * ``16`` (resume-to-disambiguate) / ``18`` (preempted) — restart
      from the last committed checkpoint, within the budget.
    * ``17`` (persistent validation fault / elastic OOM) and ``130``
      (operator SIGINT) — terminal: never restarted.
    * negative codes (child killed by a signal: SIGKILL'd mid-save,
      OOM-killed) and ``1`` (unhandled exception) — crashes; restart
      when ``restart_on_error``. Other positive codes (usage errors,
      ``2`` from argparse) are terminal — restarting a misconfiguration
      only burns the budget.

    Progress accounting mirrors :func:`run_with_restarts` but reads
    CROSS-PROCESS receipts: a new COMMITTED step dir under ``save_dir``
    resets the restart budget (commit receipts survive the child), and
    a changed world (recorded by the newest commit's plan fingerprint,
    or an injected ``world_fn``) is progress too — bounded by
    ``max_world_changes`` so a flapping fleet cannot reset forever.

    Before every relaunch the supervisor stamps the ``RESUME_PIN`` lease
    on the newest committed step dir, so the child's retention GC (a
    separate process!) cannot prune the dir its resume is reading —
    the cross-process half of the GC-vs-resume race fix.

    SIGTERM/SIGINT to the supervisor forward to the child (SIGTERM
    first), escalate to SIGKILL after ``term_grace_s``, and make the
    loop terminal: a preempted supervisor must hand back quickly, not
    start another attempt. Signal death of the child under OUR
    escalation surfaces as the preemption code 18.
    """

    def __init__(
        self,
        argv_fn: Callable[[SupervisorState], List[str]],
        *,
        save_dir: Optional[str] = None,
        state_file: Optional[str] = None,
        max_restarts: int = 3,
        max_world_changes: int = 8,
        base_delay: float = 1.0,
        max_delay: float = 60.0,
        restart_codes: Iterable[int] = RESTARTABLE_EXIT_CODES,
        restart_on_error: bool = True,
        term_grace_s: float = 15.0,
        poll_interval: float = 0.5,
        world_fn: Optional[Callable[[], Any]] = None,
        sleep: Optional[Callable[[float], None]] = None,
        rng: Optional[random.Random] = None,
        registry=None,
        recorder=None,
        popen: Callable[..., Any] = subprocess.Popen,
        log: Callable[[str], None] = lambda m: print(m, flush=True),
    ):
        self.argv_fn = argv_fn
        self.save_dir = save_dir
        self.state_file = state_file or (
            os.path.join(save_dir, "SUPERVISOR_STATE.json")
            if save_dir else None)
        self.max_restarts = max_restarts
        self.max_world_changes = max_world_changes
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.restart_codes = tuple(restart_codes)
        self.restart_on_error = restart_on_error
        self.term_grace_s = term_grace_s
        self.poll_interval = poll_interval
        self.world_fn = world_fn
        self.rng = rng
        self.recorder = recorder
        self._popen = popen
        self._log = log
        self._reg = _registry(registry)
        if sleep is None:
            from hetu_galvatron_tpu.utils.retrying import (
                _default_sleep as sleep,
            )
        self._sleep = sleep
        self.state = SupervisorState.load(self.state_file)
        self._child = None
        self._stop_signum: Optional[int] = None
        self._commit_at_spawn: Optional[int] = None
        self._kill_timer: Optional[threading.Timer] = None
        self.escalated = False
        self._previous_handlers: Dict[int, Any] = {}
        self._t_start = time.monotonic()

    # -- receipts -----------------------------------------------------------

    def _refresh_commit(self) -> bool:
        """Read the newest commit receipt from disk; True if it advanced
        past the persisted one (cross-process progress)."""
        if not self.save_dir:
            return False
        latest = ckpt_paths.latest_committed_step(self.save_dir)
        if latest is None:
            return False
        step, ckdir = latest
        advanced = (self.state.last_commit_step is None
                    or step > self.state.last_commit_step)
        if advanced:
            self.state.last_commit_step = step
            self.state.last_commit_wall = (
                ckpt_paths.commit_wall_time(ckdir) or time.time())
            self._reg.gauge("supervisor/last_commit_step").set(step)
        return advanced

    def _world(self) -> Optional[int]:
        if self.world_fn is not None:
            try:
                return self.world_fn()
            except Exception:  # noqa: BLE001 — a probe must not kill us
                return None
        if self.save_dir:
            return ckpt_paths.stored_world_of(self.save_dir)
        return None

    def _note_progress(self) -> bool:
        st = self.state
        self._refresh_commit()
        # compare against the receipt AT SPAWN, not the previous refresh:
        # _wait() polls receipts live for /healthz, which would absorb the
        # advancement before this comparison ever saw it
        progressed = (st.last_commit_step is not None
                      and (self._commit_at_spawn is None
                           or st.last_commit_step > self._commit_at_spawn))
        world = self._world()
        if (world is not None and st.last_world is not None
                and world != st.last_world):
            if st.world_changes < self.max_world_changes:
                st.world_changes += 1
                self._reg.counter("supervisor/world_changes").inc()
                self._log(f"supervisor: world changed {st.last_world} -> "
                          f"{world}; topology change is progress "
                          f"({st.world_changes}/{self.max_world_changes} "
                          "of the world-change budget)")
                progressed = True
            else:
                self._reg.counter(
                    "supervisor/world_change_budget_exhausted").inc()
                self._log("supervisor: world changed again but the "
                          f"world-change budget ({self.max_world_changes})"
                          " is spent; NOT resetting the restart budget")
        if world is not None:
            st.last_world = world
        if progressed:
            st.restarts = 0
        return progressed

    # -- signal forwarding --------------------------------------------------

    def _on_signal(self, signum, frame):  # noqa: ARG002 — signal signature
        if self._stop_signum is not None:
            # second signal: operator escalation — kill the child now
            child = self._child
            if child is not None and child.poll() is None:
                try:
                    child.kill()
                except OSError:
                    pass
            return
        self._stop_signum = signum
        child = self._child
        if child is not None and child.poll() is None:
            fwd = signal.SIGINT if signum == signal.SIGINT else \
                signal.SIGTERM
            try:
                child.send_signal(fwd)
            except OSError:
                pass
            t = threading.Timer(self.term_grace_s, self._escalate,
                                args=(child,))
            t.daemon = True
            t.start()
            self._kill_timer = t

    def _escalate(self, child) -> None:
        if child.poll() is None:
            self.escalated = True
            self._reg.counter("supervisor/grace_kills").inc()
            try:
                child.kill()
            except OSError:
                pass

    def _install_signals(self) -> None:
        for s in (signal.SIGTERM, signal.SIGINT):
            try:
                self._previous_handlers[s] = signal.signal(
                    s, self._on_signal)
            except ValueError:
                # not the main thread (tests drive _on_signal directly)
                self._previous_handlers.pop(s, None)

    def _restore_signals(self) -> None:
        for s, prev in self._previous_handlers.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._previous_handlers.clear()
        if self._kill_timer is not None:
            self._kill_timer.cancel()
            self._kill_timer = None

    # -- observability ------------------------------------------------------

    def _emit(self, event: str, **data) -> None:
        payload = {"event": event, "attempt": self.state.attempt,
                   "restarts": self.state.restarts,
                   "commit_step": self.state.last_commit_step, **data}
        try:
            self._reg.event("supervisor", payload)
            self._reg.flush()
        except Exception as e:  # noqa: BLE001 — telemetry is best-effort
            self._log(f"supervisor: warning: timeline event {event!r} not "
                      f"recorded ({type(e).__name__}: {e})")

    def _rpo_s(self) -> Optional[float]:
        if self.state.last_commit_wall is None:
            return None
        return max(time.time() - self.state.last_commit_wall, 0.0)

    def health(self) -> Dict[str, Any]:
        """Merged into ``/healthz`` by ``cli/supervise.py``: liveness a
        fleet prober can alert on without parsing the metrics stream."""
        st = self.state
        return {
            "supervisor_attempt": st.attempt,
            "supervisor_restarts": st.restarts,
            "supervisor_world_changes": st.world_changes,
            "last_child_exit_code": st.last_exit_code,
            "backoff_s": st.backoff_s,
            "child_alive": (self._child is not None
                            and self._child.poll() is None),
            "last_commit_step": st.last_commit_step,
            "last_commit_age_s": self._rpo_s(),
        }

    # -- the loop -----------------------------------------------------------

    def _persist(self) -> None:
        try:
            self.state.save(self.state_file)
        except OSError as e:
            self._log(f"supervisor: warning: could not persist state to "
                      f"{self.state_file}: {e}")

    def _wait(self, child) -> int:
        self._child = child
        try:
            while True:
                rc = child.poll()
                if rc is not None:
                    return rc
                # live commit receipts while the child runs: /healthz
                # last_commit_age_s is the fleet's RPO probe
                self._refresh_commit()
                time.sleep(self.poll_interval)
        finally:
            self._child = None
            if self._kill_timer is not None:
                self._kill_timer.cancel()
                self._kill_timer = None

    def _surface(self, code: int) -> int:
        # shell convention for signal deaths we surface terminally
        return 128 + (-code) if code < 0 else code

    def _pin(self) -> None:
        if not self.save_dir:
            return
        latest = ckpt_paths.latest_committed_step(self.save_dir)
        if latest is not None:
            ckpt_paths.write_resume_pin(self.save_dir, latest[1],
                                        owner=f"supervisor:{os.getpid()}")

    def run(self) -> int:
        st = self.state
        self._install_signals()
        self._refresh_commit()
        if self.state.last_world is None:
            st.last_world = self._world()
        try:
            while True:
                st.attempt += 1
                st.backoff_s = 0.0
                # pin the step the child will resume from BEFORE it can
                # run any retention GC (the child's keep_last prune must
                # not race its own resume read)
                self._pin()
                self._commit_at_spawn = st.last_commit_step
                self._persist()
                cmd = self.argv_fn(st)
                self._emit("spawn", cmd=" ".join(map(str, cmd[:6]))
                           + (" ..." if len(cmd) > 6 else ""))
                self._reg.counter("supervisor/spawns").inc()
                self._log(f"supervisor: attempt {st.attempt} "
                          f"(restarts {st.restarts}/{self.max_restarts})")
                try:
                    child = self._popen(cmd)
                except Exception as e:  # noqa: BLE001 — spawn is terminal
                    self._log(f"supervisor: cannot spawn child: {e}")
                    self._emit("spawn_failed", error=str(e))
                    self._persist()
                    return 1
                code = self._wait(child)
                st.last_exit_code = code
                progressed = self._note_progress()
                self._emit("child_exit", code=code, progressed=progressed,
                           rpo_s=self._rpo_s(),
                           escalated=self.escalated)
                if self.recorder is not None and code != 0:
                    self.recorder.note("child_exit", code=code,
                                       attempt=st.attempt,
                                       commit_step=st.last_commit_step,
                                       rpo_s=self._rpo_s())
                    self.recorder.dump(f"child_exit_{code}")
                if self._stop_signum is not None:
                    # the supervisor itself was told to stop: never
                    # relaunch; keep the pin (a later supervise resumes)
                    self._persist()
                    if self._stop_signum == signal.SIGINT:
                        rc = EXIT_CODE_INTERRUPTED
                    elif code > 0:
                        rc = code
                    else:
                        rc = EXIT_CODE_CHECKPOINT_AND_EXIT
                    self._log("supervisor: stopping on "
                              f"{signal.Signals(self._stop_signum).name} "
                              f"(exit {rc})")
                    self._emit("stopped", code=rc)
                    return rc
                if code == 0:
                    if self.save_dir:
                        ckpt_paths.clear_resume_pin(self.save_dir)
                    self._persist()
                    self._emit("done")
                    return 0
                restartable = (
                    code in self.restart_codes
                    or (self.restart_on_error and (code < 0 or code == 1)))
                if not restartable:
                    if code == EXIT_CODE_FAILED_ON_RESULT_VALIDATION:
                        self._log("supervisor: exit 17 (persistent "
                                  "validation fault) is not restartable; "
                                  "surfacing it")
                    self._reg.counter("supervisor/terminal_exits",
                                      code=code).inc()
                    if self.save_dir:
                        ckpt_paths.clear_resume_pin(self.save_dir)
                    self._persist()
                    self._emit("terminal", code=code)
                    return self._surface(code)
                if st.restarts >= self.max_restarts:
                    self._reg.counter("supervisor/giveups",
                                      reason="budget").inc()
                    self._log("supervisor: restart budget "
                              f"({self.max_restarts}) exhausted; "
                              f"surfacing exit code {self._surface(code)}")
                    self._persist()
                    self._emit("giveup", code=self._surface(code))
                    return self._surface(code)
                delay = backoff_delay(st.restarts, base=self.base_delay,
                                      cap=self.max_delay, rng=self.rng)
                st.backoff_s = delay
                st.restarts += 1
                self._reg.counter("supervisor/restarts", code=code).inc()
                self._reg.counter("supervisor/backoff_wait_s").inc(delay)
                self._log(f"supervisor: child exit {code}; restart "
                          f"{st.restarts}/{self.max_restarts} in "
                          f"{delay:.1f}s")
                self._persist()
                self._sleep(delay)
        finally:
            self._restore_signals()
