"""Preemption-aware training supervisor.

Two halves of surviving a preemptible TPU fleet:

* :class:`PreemptionGuard` — a SIGTERM/SIGINT handler that converts the
  kill signal into a *checkpoint-and-exit request* the train loop reads
  at the next step boundary (Cloud TPU preemption delivers SIGTERM with
  a ~30 s grace window; an uncheckpointed step is a lost step). The
  guard never acts mid-step: the loop finishes the in-flight update,
  commits an atomic checkpoint, and exits with
  :data:`EXIT_CODE_CHECKPOINT_AND_EXIT`.
* :func:`run_with_restarts` — bounded auto-restart with jittered
  exponential backoff around a training attempt, honoring the rerun
  state machine's exit-code contract (``rerun_machine.py``): code 16
  (resume-to-disambiguate) and preemption exits restart; code 17
  (failed result validation — a persistent fault that will reproduce)
  does not. Crashes (exceptions) restart too when ``restart_on_error``
  is set, so a drill-injected or real host crash resumes from the last
  committed checkpoint instead of losing the run.

Every signal, restart, and give-up is counted in the observability
registry (``supervisor/*``) so fleet dashboards see preemption churn.
"""

from __future__ import annotations

import random
import signal
import threading
from typing import Any, Callable, Iterable, Optional

from hetu_galvatron_tpu.runtime.rerun_machine import (
    EXIT_CODE_FAILED_ON_RESULT_VALIDATION,
    EXIT_CODE_RESUME_TO_DISAMBIGUATE,
)
from hetu_galvatron_tpu.utils.retrying import backoff_delay

# checkpoint-and-exit after a preemption signal: resumable by contract,
# distinct from the rerun machine's 16/17 so the supervisor can tell
# "the fleet preempted me" from "my step result was suspect"
EXIT_CODE_CHECKPOINT_AND_EXIT = 18
# operator interrupt (SIGINT/Ctrl-C): checkpoints like a preemption but
# is NOT restartable — auto_restart must not resurrect a run the user
# deliberately stopped (128+SIGINT shell convention)
EXIT_CODE_INTERRUPTED = 130

# exit codes run_with_restarts treats as "resume from the last committed
# checkpoint"; 17 is deliberately absent — a persistent validation fault
# reproduces on every restart, so restarting only burns the budget
RESTARTABLE_EXIT_CODES = (
    EXIT_CODE_RESUME_TO_DISAMBIGUATE,
    EXIT_CODE_CHECKPOINT_AND_EXIT,
)


def _registry(registry=None):
    if registry is not None:
        return registry
    from hetu_galvatron_tpu.observability.registry import get_registry

    return get_registry()


class PreemptionGuard:
    """Converts SIGTERM/SIGINT into a step-boundary stop request.

    Use as a context manager around the train loop; ``requested()`` turns
    true once a signal arrives (a second signal of the same kind is
    idempotent). Handlers are installed only on the main thread — on a
    worker thread (some test harnesses) the guard degrades to an inert
    flag that :meth:`request` can still set programmatically (simulated
    preemption drills)."""

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,
                                                 signal.SIGINT),
                 *, enabled: bool = True, registry=None, recorder=None):
        self.signals = tuple(signals)
        self.enabled = enabled
        self._requested = threading.Event()
        self._previous = {}
        self._registry = registry
        # optional observability.recorder.FlightRecorder: the first
        # trapped signal dumps a postmortem (from the main thread, at the
        # boundary check — never inside the async handler); dump() never
        # raises, so the checkpoint-and-exit path is unaffected
        self.recorder = recorder
        self.installed = False
        self.signum: Optional[int] = None  # first signal that fired
        self._counted = False

    # -- signal plumbing ----------------------------------------------------

    def _handler(self, signum, frame):  # noqa: ARG002 — signal signature
        if self._requested.is_set():
            # second signal of the same escalation: the run is presumably
            # hung (stuck step, dead object-store mount) and will never
            # reach the boundary check — restore the previous handler and
            # re-deliver so the operator can still interrupt without
            # SIGKILL
            signal.signal(signum, self._previous.get(signum, signal.SIG_DFL))
            signal.raise_signal(signum)
            return
        self.request(signum=signum)

    def request(self, signum: Optional[int] = None) -> None:
        """Mark preemption as requested (signal handler or drill).
        Async-signal-safe: only sets a flag — no locks, no allocation
        (a registry counter here could deadlock on the non-reentrant
        registry lock the interrupted main thread may hold); the signal
        is counted later, from the main thread, in :meth:`requested`."""
        self._requested.set()
        if self.signum is None:
            self.signum = signum if signum is not None else -1

    def requested(self) -> bool:
        """Polled by the train loop at step boundaries (main thread) —
        also the safe place to count the signal for observability."""
        if self._requested.is_set() and not self._counted:
            self._counted = True
            try:
                name = (signal.Signals(self.signum).name
                        if self.signum not in (None, -1) else "drill")
            except ValueError:
                name = str(self.signum)
            _registry(self._registry).counter(
                "supervisor/preemption_signals", sig=name).inc()
            if self.recorder is not None:
                self.recorder.dump(f"signal:{name}")
        return self._requested.is_set()

    def exit_code(self) -> int:
        """Which checkpoint-and-exit code the triggering signal maps to:
        SIGINT = an operator's deliberate stop (non-restartable 130),
        everything else = fleet preemption (restartable 18)."""
        if self.signum == signal.SIGINT:
            return EXIT_CODE_INTERRUPTED
        return EXIT_CODE_CHECKPOINT_AND_EXIT

    def __enter__(self) -> "PreemptionGuard":
        self._requested.clear()
        self.signum = None
        self._counted = False
        if not self.enabled:
            return self
        for s in self.signals:
            try:
                self._previous[s] = signal.signal(s, self._handler)
                self.installed = True
            except ValueError:
                # not the main thread: signals cannot be trapped here;
                # stay an inert flag rather than failing the run
                self._previous.pop(s, None)
        return self

    def __exit__(self, *exc) -> None:
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._previous.clear()
        self.installed = False


def run_with_restarts(
    attempt_fn: Callable[[], Optional[int]],
    *,
    max_restarts: int = 3,
    base_delay: float = 1.0,
    max_delay: float = 60.0,
    restart_codes: Iterable[int] = RESTARTABLE_EXIT_CODES,
    restart_on_error: bool = True,
    progress_fn: Optional[Callable[[], Any]] = None,
    world_fn: Optional[Callable[[], Any]] = None,
    sleep: Optional[Callable[[float], None]] = None,
    rng: Optional[random.Random] = None,
    registry=None,
    log: Callable[[str], None] = lambda m: print(m, flush=True),
) -> int:
    """Run ``attempt_fn`` (returns an exit code; None/0 = success) with
    bounded auto-restart.

    Restartable exits (preemption, resume-to-disambiguate) and — when
    ``restart_on_error`` — crashes re-invoke ``attempt_fn`` after a
    jittered exponential backoff; the attempt is expected to resume from
    the last committed checkpoint. Non-restartable codes (0, 17, anything
    not listed) return immediately. When the restart budget is exhausted
    the last code is returned (or the last exception re-raised), so the
    process-level exit status still carries the fault classification.

    ``progress_fn`` (e.g. ``lambda: latest_checkpoint(save_dir)``) makes
    the budget bound crash LOOPS, not total faults: whenever its value
    changes between attempts (the attempt checkpointed new progress) the
    restart counter resets, so a healthy multi-day run on a preemptible
    fleet survives arbitrarily many preemptions while a run that loops
    without advancing still stops after ``max_restarts``.

    ``world_fn`` (e.g. ``lambda: len(jax.devices())``) makes a TOPOLOGY
    change progress too: when the visible world differs between attempts
    (half the fleet preempted away, or capacity returned) the next attempt
    re-searches and reshards rather than repeating the fault, so the
    restart counter resets exactly as a committed checkpoint would reset
    it — the budget bounds same-world crash loops, never elasticity.
    World changes are counted (``supervisor/world_changes``) so dashboards
    see fleet churn distinctly from crash churn."""
    if sleep is None:
        from hetu_galvatron_tpu.utils.retrying import _default_sleep as sleep
    restart_codes = tuple(restart_codes)
    reg = _registry(registry)
    restarts = 0
    last_progress = progress_fn() if progress_fn is not None else None
    last_world = world_fn() if world_fn is not None else None

    def note_progress() -> None:
        nonlocal restarts, last_progress, last_world
        advanced = False
        if world_fn is not None:
            world = world_fn()
            if world != last_world:
                reg.counter("supervisor/world_changes").inc()
                log(f"supervisor: world changed {last_world} -> {world}; "
                    "topology change is progress (restart budget reset)")
                last_world = world
                advanced = True
        if progress_fn is not None:
            cur = progress_fn()
            if cur != last_progress:
                advanced = True
                last_progress = cur
        if advanced:
            restarts = 0  # forward progress: this is not a crash loop

    while True:
        try:
            code = attempt_fn()
        except Exception as e:  # noqa: BLE001 — supervisor catches crashes
            note_progress()
            if not restart_on_error or restarts >= max_restarts:
                reg.counter("supervisor/giveups", reason="crash").inc()
                raise
            delay = backoff_delay(restarts, base=base_delay, cap=max_delay,
                                  rng=rng)
            reg.counter("supervisor/restarts", reason="crash").inc()
            # goodput accounting: backoff wall-clock is lost time (the
            # checkpoint-persisted goodput tracker books the full
            # commit-to-resume gap; this counter is the supervisor's own
            # receipt of the deliberately-slept share)
            reg.counter("supervisor/backoff_wait_s").inc(delay)
            log(f"supervisor: attempt crashed ({type(e).__name__}: {e}); "
                f"restart {restarts + 1}/{max_restarts} in {delay:.1f}s")
            restarts += 1
            sleep(delay)
            continue
        code = code or 0
        if code == 0:
            return 0
        if code not in restart_codes:
            if code == EXIT_CODE_FAILED_ON_RESULT_VALIDATION:
                log("supervisor: exit 17 (persistent validation fault) is "
                    "not restartable; surfacing it")
            reg.counter("supervisor/terminal_exits", code=code).inc()
            return code
        note_progress()
        if restarts >= max_restarts:
            reg.counter("supervisor/giveups", reason="budget").inc()
            log(f"supervisor: restart budget ({max_restarts}) exhausted; "
                f"surfacing exit code {code}")
            return code
        delay = backoff_delay(restarts, base=base_delay, cap=max_delay,
                              rng=rng)
        reg.counter("supervisor/restarts", code=code).inc()
        reg.counter("supervisor/backoff_wait_s").inc(delay)
        log(f"supervisor: exit code {code}; restart "
            f"{restarts + 1}/{max_restarts} in {delay:.1f}s")
        restarts += 1
        sleep(delay)
