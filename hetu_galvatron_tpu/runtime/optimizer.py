"""Optimizer + LR schedule construction (optax-based).

Capability parity with the reference optimizer stack
(runtime/optimizer/utils.py:14-108 ``get_optimizer_and_param_scheduler`` /
``clip_grad_norm``, param_scheduler.py:102 ``OptimizerParamScheduler``):
AdamW with weight-decay masking (no decay on norms/biases), global grad-norm
clipping, and constant/linear/cosine/inverse-square-root/WSD schedules with
warmup.

TPU note: grad-norm clipping needs no TP-duplication bookkeeping here — under
GSPMD the gradient pytree is logically global (sharded, not replicated-with-
duplicates), so `optax.clip_by_global_norm`'s tree-wide L2 norm is already the
true global norm; XLA inserts the cross-device reductions.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from hetu_galvatron_tpu.core.args_schema import TrainArgs


def make_lr_schedule(train: TrainArgs) -> optax.Schedule:
    """Warmup + decay schedule matching the reference styles
    (param_scheduler.py: constant/linear/cosine/inverse-square-root/WSD)."""
    peak, floor = train.lr, train.min_lr
    warmup = max(train.lr_warmup_iters, 0)
    total = train.lr_decay_iters or train.train_iters
    decay_steps = max(total - warmup, 1)
    style = train.lr_decay_style

    if style == "constant":
        body = optax.constant_schedule(peak)
    elif style == "linear":
        body = optax.linear_schedule(peak, floor, decay_steps)
    elif style == "cosine":
        body = optax.cosine_decay_schedule(
            peak, decay_steps, alpha=floor / max(peak, 1e-12))
    elif style == "inverse-square-root":
        def body(step):  # lr = peak * sqrt(warmup+1) / sqrt(step+warmup+1)
            s = jnp.asarray(step, jnp.float32) + warmup + 1.0
            return jnp.maximum(peak * jnp.sqrt(warmup + 1.0) / jnp.sqrt(s), floor)
    elif style == "WSD":
        # warmup-stable-decay: hold peak, then linear-decay the last
        # lr_wsd_decay_iters steps
        wsd = max(train.lr_wsd_decay_iters, 1)
        stable = max(decay_steps - wsd, 0)
        body = optax.join_schedules(
            [optax.constant_schedule(peak),
             optax.linear_schedule(peak, floor, wsd)],
            [stable],
        )
    else:
        raise ValueError(f"unknown lr_decay_style {style}")

    if warmup == 0:
        return body
    return optax.join_schedules(
        [optax.linear_schedule(0.0, peak, warmup), body], [warmup]
    )


def _decay_mask(params: Any) -> Any:
    """True for params that get weight decay: 2D+ weights, not norms/biases
    (reference utils.py splits wd/no-wd groups the same way)."""
    return jax.tree.map(lambda p: p.ndim >= 2, params)


def make_optimizer(
    train: TrainArgs, params: Optional[Any] = None
) -> optax.GradientTransformation:
    """AdamW + global-norm clip + schedule; the returned transformation's
    state is a pytree that the mesh layer shards per DPType (ZeRO-1/2).

    MoE expert-bias buffers (param paths ending in ``expert_bias``) bypass
    the Adam chain and take plain SGD with lr=1: their "gradient" IS the
    negated maintenance update emitted by the router
    (models/moe.py route_tokens), so bias_new = bias + update — the
    reference's aux-loss-free buffer update (router.py:116)."""
    schedule = make_lr_schedule(train)
    chain = []
    if train.clip_grad and train.clip_grad > 0:
        chain.append(optax.clip_by_global_norm(train.clip_grad))
    chain.append(
        optax.scale_by_adam(
            b1=train.adam_beta1, b2=train.adam_beta2, eps=train.adam_eps
        )
    )
    if train.weight_decay:
        chain.append(
            optax.add_decayed_weights(train.weight_decay, mask=_decay_mask)
        )
    chain.append(optax.scale_by_learning_rate(schedule))
    return partition_expert_bias(optax.chain(*chain))


def partition_expert_bias(
    adam: optax.GradientTransformation,
) -> optax.GradientTransformation:
    """Route ``expert_bias`` leaves to SGD(lr=1), everything else to the
    given chain (see :func:`make_optimizer`)."""

    def labels(params):
        return jax.tree_util.tree_map_with_path(
            lambda path, _: ("bias_buffer"
                             if str(path[-1]).find("expert_bias") >= 0
                             else "adam"),
            params)

    return optax.multi_transform(
        {"adam": adam, "bias_buffer": optax.sgd(learning_rate=1.0)}, labels)


def global_grad_norm(grads: Any) -> jax.Array:
    """fp32 global L2 norm across the whole gradient pytree (reference
    get_grad_norm_fp32, clip_grads.py:66)."""
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in jax.tree.leaves(grads)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))
