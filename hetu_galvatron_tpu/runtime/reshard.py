"""Elastic resharding: move a committed checkpoint between hybrid-parallel
plans and worlds.

Galvatron's whole premise is that the optimal plan is a function of the
topology — so when preemption changes the topology, the correct response
is not "resume the same world" but "re-search, reshard, resume". This
module is the reshard leg: a committed checkpoint written under plan A
(by ANY of the three engine layouts) becomes arrays laid out for plan B's
PartitionSpecs, exactly — a generalized ``split_params``.

The three on-disk layouts a checkpoint may carry:

* **spmd** — the pp=1 SPMD path: one plain full-model tree
  (``models/builder.init_causal_lm`` structure).
* **stacked** — the compiled 1F1B engine
  (``runtime/compiled_pipeline.py::split_params``): decoder layer
  ``s*lps + j`` is row ``s`` of ``stages[j]`` (a leading ``[pp]`` axis on
  every layer leaf); embed/prenorm/head replicated.
* **stages** — the host pipeline engine
  (``runtime/pipeline.py::split_params``): a list of per-stage trees,
  embed on the first stage, prenorm/head on the last (the tied head
  carrying a transposed ``whead = wte.T`` copy).

Everything funnels through one canonical form — the full host tree — and
back out through structure-driven placement: the DESTINATION template (the
new engine's freshly initialized, sharded ``(sp, so)``) tells us both the
target layout and the target shardings, so the reshard is
``canonicalize -> re-split -> device_put`` per leaf (gather-to-host per
leaf is the first implementation, per the SNIPPETS NamedSharding +
``device_put`` idiom; a device-to-device path can land later without
changing callers).

Optimizer state rides the same transformations: every params-shaped
subtree inside the optax state (adam mu/nu) is located by pytree-structure
match (:func:`map_params_like`) and re-laid-out with the identical
canonicalize/split functions, so the resumed trajectory is bit-for-bit the
checkpoint's. The optax chain's scalar states (step counts) pass through
untouched. Placement onto the destination optimizer template goes by FLAT
LEAF ORDER with per-leaf shape checks rather than structure equality: the
engines build slightly different optax chains (the SPMD path carries
``clip_by_global_norm``, the pipeline engines clip outside optax), but
the differing states are empty — zero leaves — so the moment/count leaf
sequence is identical across engines while the pytree structures are not.

This module is resume-path code (cold), not step-path code: host syncs
are the point, not a bug.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

Params = Dict[str, Any]

LAYOUT_SPMD = "spmd"
LAYOUT_STACKED = "stacked"
LAYOUT_STAGES = "stages"

# the full-model tree's vocab-row keys (everything that is not a layer)
_VOCAB_KEYS = ("embed", "prenorm", "head", "enc_norm")


class ReshardError(RuntimeError):
    """A checkpoint cannot be resharded onto the target plan (layer-count
    mismatch, unrecognized layout, shape drift) — actionable, names both
    sides."""


# ---------------------------------------------------------------------------
# layout detection + normalization
# ---------------------------------------------------------------------------


def _normalize_raw(tree: Any) -> Any:
    """Orbax raw (target-less) restores may surface sequence pytrees as
    dicts keyed '0','1',...; fold those back into lists so layout
    detection and canonicalization see the structure the engine saved."""
    if isinstance(tree, dict):
        keys = list(tree.keys())
        if keys and all(isinstance(k, str) and k.isdigit() for k in keys) \
                and sorted(int(k) for k in keys) == list(range(len(keys))):
            return [_normalize_raw(tree[str(i)]) for i in range(len(keys))]
        return {k: _normalize_raw(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [_normalize_raw(v) for v in tree]
    return tree


def detect_layout(tree: Any) -> str:
    """Which engine layout a params tree (raw-restored or live) carries."""
    if isinstance(tree, (list, tuple)):
        if tree and isinstance(tree[0], dict) and "layers" in tree[0]:
            return LAYOUT_STAGES
        raise ReshardError(
            f"unrecognized checkpoint params layout: sequence of "
            f"{type(tree[0]).__name__ if tree else 'nothing'}")
    if isinstance(tree, dict):
        if "stages" in tree:
            return LAYOUT_STACKED
        if "layers" in tree:
            return LAYOUT_SPMD
    raise ReshardError(
        "unrecognized checkpoint params layout: expected a full-model "
        "tree, a compiled stage-stacked tree, or a per-stage list "
        f"(got {type(tree).__name__} with keys "
        f"{sorted(tree) if isinstance(tree, dict) else '?'})")


def _np(tree: Any) -> Any:
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _tuple_layers(tree: Params) -> Params:
    out = dict(tree)
    for k in ("layers", "enc_layers"):
        if k in out:
            out[k] = tuple(out[k])
    return out


# ---------------------------------------------------------------------------
# canonicalize: any layout -> the full host tree
# ---------------------------------------------------------------------------


def _unstack_compiled(tree: Params) -> Params:
    """Compiled stacked layout -> full tree (mirrors
    ``CompiledPipelineEngine.merge_params``: layer ``s*lps + j`` is row
    ``s`` of ``stages[j]``)."""
    stages = list(tree["stages"])
    lps = len(stages)
    leaves = jax.tree.leaves(stages[0])
    if not leaves:
        raise ReshardError("stacked checkpoint has no layer leaves")
    pp = int(np.shape(leaves[0])[0])
    layers: List[Params] = []
    for s in range(pp):
        for j in range(lps):
            layers.append(jax.tree.map(lambda x: np.asarray(x)[s],
                                       stages[j]))
    out: Params = {"layers": tuple(layers)}
    for k in _VOCAB_KEYS:
        if k in tree:
            out[k] = _np(tree[k])
    return out


def _merge_stages(stage_list: Sequence[Params], *, tie: bool) -> Params:
    """Host per-stage layout -> full tree (mirrors
    ``PipelineEngine.merge_params``; the tied head's transposed ``whead``
    copy is dropped — ``wte`` carries the canonical value)."""
    layers: List[Params] = []
    enc: List[Params] = []
    out: Params = {}
    for sp in stage_list:
        layers.extend(_np(list(sp["layers"])))
        if "enc_layers" in sp:
            enc.extend(_np(list(sp["enc_layers"])))
        for k in ("embed", "prenorm", "enc_norm"):
            if k in sp:
                out[k] = _np(sp[k])
        if "head" in sp:
            head = {k: v for k, v in sp["head"].items()
                    if not (tie and k == "whead")}
            out["head"] = _np(head)
    out["layers"] = tuple(layers)
    if enc:
        out["enc_layers"] = tuple(enc)
    return out


def canonicalize_params(tree: Any, *, tie_word_embeddings: bool = False,
                        layout: Optional[str] = None) -> Params:
    """Any engine layout -> the canonical full host tree (numpy leaves)."""
    tree = _normalize_raw(tree)
    layout = layout or detect_layout(tree)
    if layout == LAYOUT_SPMD:
        return _np(_tuple_layers(tree))
    if layout == LAYOUT_STACKED:
        return _unstack_compiled(tree)
    return _merge_stages(tree, tie=tie_word_embeddings)


def _fill_empty(canonical: Params, template: Params) -> Params:
    """Orbax drops empty containers at save (a tied model's ``head: {}``
    never lands on disk); recreate whatever empty vocab-row keys the
    destination template expects so structural placement lines up."""
    out = dict(canonical)
    for k in _VOCAB_KEYS:
        if k in template and k not in out:
            out[k] = {}
    return out


# ---------------------------------------------------------------------------
# re-split: canonical tree -> the destination template's layout
# ---------------------------------------------------------------------------


def _check_layer_count(canonical: Params, want: int, what: str) -> None:
    have = len(canonical["layers"])
    if have != want:
        raise ReshardError(
            f"checkpoint has {have} decoder layers but the target "
            f"{what} expects {want}: the plans describe different models")


def _stack_like(canonical: Params, template: Params) -> Params:
    """Canonical -> compiled stacked layout shaped like ``template``."""
    lps = len(template["stages"])
    leaves = jax.tree.leaves(template["stages"][0])
    pp = int(leaves[0].shape[0])
    _check_layer_count(canonical, pp * lps, "compiled plan")
    stages = tuple(
        jax.tree.map(lambda *rows: np.stack([np.asarray(r) for r in rows]),
                     *[canonical["layers"][s * lps + j] for s in range(pp)])
        for j in range(lps))
    out: Params = {"stages": stages}
    for k in _VOCAB_KEYS:
        if k in template:
            out[k] = canonical.get(k, {})
    return out


def _split_stages_like(canonical: Params,
                       template: Sequence[Params]) -> List[Params]:
    """Canonical -> host per-stage layout shaped like ``template`` (the
    engine's placed per-stage trees): layer slices by each stage's count,
    vocab rows by key presence, the tied ``whead`` recreated as the
    transpose of the canonical ``wte``-shaped leaf (for adam moments this
    transposes the moment — exactly what the engine's symmetric tied-grad
    exchange maintains)."""
    total = sum(len(st["layers"]) for st in template)
    _check_layer_count(canonical, total, "pipeline plan")
    out: List[Params] = []
    lo = elo = 0
    for st in template:
        n = len(st["layers"])
        sp: Params = {"layers": tuple(canonical["layers"][lo:lo + n])}
        lo += n
        if "enc_layers" in st:
            ne = len(st["enc_layers"])
            sp["enc_layers"] = tuple(canonical["enc_layers"][elo:elo + ne])
            elo += ne
        for k in ("embed", "prenorm", "enc_norm"):
            if k in st:
                sp[k] = canonical.get(k, {})
        if "head" in st:
            head = canonical.get("head", {})
            if "whead" in st["head"] and "whead" not in head:
                head = {**head,
                        "whead": np.asarray(canonical["embed"]["wte"]).T}
            sp["head"] = head
        out.append(sp)
    return out


def _relayout(canonical: Params, template: Any) -> Any:
    """Canonical tree -> a host tree in the template's layout."""
    layout = detect_layout(template)
    if layout == LAYOUT_SPMD:
        _check_layer_count(canonical, len(template["layers"]), "plan")
        return _fill_empty(canonical, template)
    if layout == LAYOUT_STACKED:
        return _stack_like(canonical, template)
    return _split_stages_like(canonical, template)


def _put(t, s):
    s = np.asarray(s)
    if tuple(t.shape) != tuple(s.shape):
        raise ReshardError(
            f"reshard shape mismatch: checkpoint leaf {s.shape} vs "
            f"target {tuple(t.shape)}")
    if s.dtype != t.dtype:
        s = s.astype(t.dtype)
    return jax.device_put(s, t.sharding)


def place_like(template: Any, host_tree: Any) -> Any:
    """device_put every host leaf under the matching template leaf's
    sharding (the destination engine's freshly initialized tree IS the
    spec sheet). Raises :class:`ReshardError` on any structure or shape
    disagreement."""
    try:
        return jax.tree.map(_put, template, host_tree)
    except ReshardError:
        raise
    except (ValueError, TypeError, KeyError) as e:
        raise ReshardError(
            f"reshard structure mismatch between the checkpoint and the "
            f"target plan's tree: {e}") from e


def place_like_flat(template: Any, host_tree: Any) -> Any:
    """Flat-order placement for OPTIMIZER state: the engines' optax chains
    differ only by zero-leaf empty states (the SPMD chain carries
    ``clip_by_global_norm``; the pipeline engines clip outside optax) and
    by container flavor after a raw restore (namedtuples come back as
    dicts), so the leaf SEQUENCE is the invariant — pair leaves in order,
    check every shape, and rebuild with the template's structure. A
    count/moment misalignment surfaces as a shape mismatch, not silent
    corruption (every adjacent leaf pair in these chains differs in
    shape)."""
    tleaves, tdef = jax.tree_util.tree_flatten(template)
    hleaves = jax.tree.leaves(host_tree)
    if len(tleaves) != len(hleaves):
        raise ReshardError(
            f"optimizer state leaf count mismatch: checkpoint has "
            f"{len(hleaves)}, target optimizer expects {len(tleaves)} — "
            "resume with the optimizer the checkpoint was trained with")
    return jax.tree_util.tree_unflatten(
        tdef, [_put(t, h) for t, h in zip(tleaves, hleaves)])


# ---------------------------------------------------------------------------
# optimizer state: map the params-shaped subtrees through the same moves
# ---------------------------------------------------------------------------


def map_params_like(tree: Any, params_treedef: Any,
                    fn: Callable[[Any], Any]) -> Any:
    """Replace every subtree of ``tree`` whose pytree structure equals
    ``params_treedef`` with ``fn(subtree)`` — how the adam mu/nu clones of
    the params tree inside an optax state get the same layout moves as the
    params themselves. Walks dicts / lists / tuples / namedtuples; every
    other node (arrays, scalars, optax sentinels) passes through."""
    def walk(node):
        try:
            if jax.tree.structure(node) == params_treedef:
                return fn(node)
        except (ValueError, TypeError):
            pass
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            return type(node)(*(walk(getattr(node, f))
                                for f in node._fields))
        if isinstance(node, (list, tuple)):
            return type(node)(walk(c) for c in node)
        return node

    return walk(tree)


def _merge_opt_stages(stage_opts: Sequence[Any], stage_defs: Sequence[Any],
                      merge_fn: Callable[[List[Any]], Any]) -> Any:
    """Lockstep walk over the host engine's per-stage optimizer states
    (one ``tx.init`` per stage, identical outer chain): wherever every
    branch matches its stage's params structure, merge the per-stage trees
    into one canonical tree. Scalar chain state (step counts) is identical
    across stages — the first stage's value is kept."""
    def walk(nodes):
        try:
            if all(jax.tree.structure(n) == d
                   for n, d in zip(nodes, stage_defs)):
                return merge_fn(list(nodes))
        except (ValueError, TypeError):
            pass
        n0 = nodes[0]
        if isinstance(n0, dict):
            return {k: walk([n[k] for n in nodes]) for k in n0}
        if isinstance(n0, tuple) and hasattr(n0, "_fields"):
            return type(n0)(*(walk([getattr(n, f) for n in nodes])
                              for f in n0._fields))
        if isinstance(n0, (list, tuple)):
            return type(n0)(walk([n[i] for n in nodes])
                            for i in range(len(n0)))
        return n0

    return walk(list(stage_opts))


def _host_target(tree: Any) -> Any:
    """Shape/dtype targets pinned to ONE local device, so orbax never
    consults the checkpoint's saved sharding file — which names the OLD
    world's devices and cannot resolve after a topology change (the
    exact situation this module exists for)."""
    from jax.sharding import SingleDeviceSharding

    shd = SingleDeviceSharding(jax.devices()[0])
    return jax.tree.map(
        lambda m: jax.ShapeDtypeStruct(
            tuple(m.shape), m.dtype, sharding=shd),
        tree, is_leaf=lambda x: hasattr(x, "shape") and hasattr(x, "dtype")
        and not isinstance(x, dict))


# ---------------------------------------------------------------------------
# checkpoint -> canonical
# ---------------------------------------------------------------------------


def load_checkpoint_canonical(
    ckpt_dir: str,
    *,
    tie_word_embeddings: bool = False,
    with_opt: bool = True,
) -> Tuple[Params, Any, int, Dict[str, Any]]:
    """Restore a committed checkpoint written under ANY engine layout into
    the canonical full host tree. Returns ``(params, opt_state, step,
    meta)``; ``opt_state`` is the raw-restored optax state (saved
    structure, dict-flavored containers) with every params-shaped subtree
    canonicalized — None when absent or ``with_opt`` is off. The restore
    needs no target tree and no optimizer from the caller: the
    checkpoint's own recorded metadata drives both structure and layout
    detection, and the single-device restore targets keep orbax away from
    the saved sharding file (it names the OLD world's devices)."""
    import orbax.checkpoint as ocp

    from hetu_galvatron_tpu.runtime.checkpoint import read_checkpoint_meta

    ckpt_dir = os.path.abspath(ckpt_dir)
    meta = read_checkpoint_meta(ckpt_dir)
    if "step" not in meta:
        raise FileNotFoundError(
            f"{ckpt_dir} has no meta.json — not a committed checkpoint")
    ckptr = ocp.StandardCheckpointer()
    params_dir = os.path.join(ckpt_dir, "params")
    raw = _normalize_raw(ckptr.restore(
        params_dir, _host_target(ckptr.metadata(params_dir))))
    layout = detect_layout(raw)
    canonical = canonicalize_params(raw, layout=layout,
                                    tie_word_embeddings=tie_word_embeddings)
    opt = None
    opt_dir = os.path.join(ckpt_dir, "opt_state")
    if with_opt and os.path.isdir(opt_dir):
        raw_opt = _normalize_raw(ckptr.restore(
            opt_dir, _host_target(ckptr.metadata(opt_dir))))
        canon = lambda t: canonicalize_params(
            t, layout=layout, tie_word_embeddings=tie_word_embeddings)
        if layout == LAYOUT_STAGES:
            stage_defs = [jax.tree.structure(st) for st in raw]
            opt = _merge_opt_stages(
                raw_opt, stage_defs,
                lambda trees: _merge_stages(trees,
                                            tie=tie_word_embeddings))
        else:
            opt = map_params_like(raw_opt, jax.tree.structure(raw), canon)
    return canonical, opt, int(meta["step"]), meta


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def reshard_params(params: Params, src_plan: Any, dst_plan: Any, mesh: Any,
                   *, axes_tree: Params) -> Params:
    """Re-lay a full-model params tree from plan A onto plan B's
    PartitionSpecs over ``mesh`` — the generalized ``split_params``.
    ``params`` may be sharded under ``src_plan`` or live on the host; each
    leaf is gathered to host and ``device_put`` under the destination
    NamedSharding (the SNIPPETS idiom). ``src_plan`` may be None (host
    trees); when given it is validated against the model's layer count so
    a wrong-model checkpoint fails here, not deep in XLA."""
    from jax.sharding import NamedSharding

    from hetu_galvatron_tpu.parallel.spmd import layer_shardings, param_specs

    n_layers = len(params["layers"]) + len(params.get("enc_layers", ()))
    for plan, name in ((src_plan, "source"), (dst_plan, "destination")):
        if plan is not None and len(plan.layers) != n_layers:
            raise ReshardError(
                f"{name} plan describes {len(plan.layers)} layers but the "
                f"params tree has {n_layers}")
    host = jax.device_get(params)
    per_layer_all, vocab = layer_shardings(dst_plan, mesh)
    n_enc = dst_plan.num_encoder_layers
    pspecs = param_specs(axes_tree, per_layer_all[n_enc:], vocab,
                         enc_per_layer=per_layer_all[:n_enc] or None)
    return jax.tree.map(
        lambda p, s: jax.device_put(np.asarray(p), NamedSharding(mesh, s)),
        _tuple_layers(host), pspecs)


def resume_elastic(
    ckpt_dir: str,
    dst_params: Any,
    dst_opt: Any,
    *,
    tie_word_embeddings: bool = False,
    num_experts: int = 0,
) -> Tuple[Any, Any, int]:
    """The elastic-resume restore: a committed checkpoint written under
    plan A (any engine layout, any world) lands on the NEW engine's
    freshly initialized ``(dst_params, dst_opt)`` templates — same values,
    new layout, new shardings. Returns ``(params, opt_state, step)``.

    ``dst_params``/``dst_opt`` carry both the target layout and the target
    shardings (they are the new engine's ``split_params``/``init_opt``
    output); the destination optimizer must be the one the checkpoint was
    trained with (the flat leaf pairing in :func:`place_like_flat` is
    checked per leaf, so a different optimizer fails loudly)."""
    if num_experts:
        # multi_transform's masked expert-bias lane replaces leaves with
        # optax.MaskedNode, so the moment trees no longer structure-match
        # the params tree and the subtree mapping would silently skip them
        raise ReshardError(
            "elastic reshard of MoE optimizer state is not supported yet "
            "(the expert-bias optimizer lane masks the moment trees); "
            "resume MoE runs on the original topology")
    canonical, canonical_opt, step, _ = load_checkpoint_canonical(
        ckpt_dir, tie_word_embeddings=tie_word_embeddings,
        with_opt=dst_opt is not None)
    sp = place_like(dst_params, _relayout(canonical, dst_params))
    so = dst_opt
    if canonical_opt is not None and dst_opt is not None:
        canon_def = jax.tree.structure(canonical)
        layout = detect_layout(dst_params)
        if layout == LAYOUT_STAGES:
            so = [place_like_flat(
                dst_opt[s],
                map_params_like(
                    canonical_opt, canon_def,
                    lambda t, s=s: _split_stages_like(t, dst_params)[s]))
                for s in range(len(dst_params))]
        else:
            so = place_like_flat(
                dst_opt,
                map_params_like(canonical_opt, canon_def,
                                lambda t: _relayout(t, dst_params)))
    return sp, so, step
