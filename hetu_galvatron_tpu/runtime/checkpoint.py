"""Distributed checkpoint save/load + HF interchange.

Capability parity with the reference checkpoint stack
(runtime/checkpoint/llama_adapter.py:30-172 save/load, tools/
checkpoint_convert_{h2g,g2h}.py, hybrid_parallel_config.py:132-144 config
assert-on-resume): sharded save/restore of params + optimizer state + step,
the parallel-plan JSON stored alongside and verified on resume, and
HuggingFace state-dict import/export for GPT-2- and Llama-family models.

TPU-native: orbax-checkpoint writes each array shard from the device that
owns it (the reference hand-rolls per-(layer, tp-rank) files with dp-rank-0
writers); restore takes a target sharding tree, so a checkpoint saved under
one parallel plan reloads under another — the resharding the reference does
with TP-slicing loaders (llama_adapter.py:51-163) falls out of GSPMD.
"""

from __future__ import annotations

import json
import os
import shutil
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import numpy as np

import orbax.checkpoint as ocp

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.utils.retrying import retry_call

Params = Dict[str, Any]


class WorldSizeMismatchError(ValueError):
    """The checkpoint's recorded world_size differs from the live world.

    Before this error existed a topology-changed resume surfaced as a
    shape error deep inside orbax/device_put; now it surfaces at load with
    both worlds named. The elastic resume path (``cli/train_dist.py``)
    catches exactly this condition to trigger re-search + reshard
    (``runtime/reshard.py``)."""

    def __init__(self, ckpt_dir: str, stored_world: int, live_world: int,
                 stored_plan: Optional[Dict[str, Any]] = None):
        self.ckpt_dir = ckpt_dir
        self.stored_world = int(stored_world)
        self.live_world = int(live_world)
        self.stored_plan = stored_plan
        super().__init__(
            f"checkpoint {ckpt_dir} was committed by a "
            f"{stored_world}-device world but the live world has "
            f"{live_world} devices: its arrays are laid out for the old "
            "plan and will not restore here. Re-search a plan for the "
            "live topology and reshard (runtime/reshard.py) — "
            "cli/train_dist.py does this automatically on resume when "
            "ckpt.load is set.")

# Atomic-commit protocol: a step directory is materialized under
# ``step_<n>.tmp``, fully written (params/opt_state shards + meta.json),
# stamped with the marker file below, and only then renamed to
# ``step_<n>``. Readers treat a step dir without the marker as partial
# garbage from a mid-save crash: never selected, eligible for GC. The
# marker (not just the rename) is kept because object stores mounted via
# FUSE can surface a directory rename non-atomically.
COMMIT_MARKER = "COMMITTED"
_TMP_SUFFIX = ".tmp"
_OLD_SUFFIX = ".old"  # previous committed payload during an overwrite

# transient-read retry policy for checkpoint I/O (flaky object-store
# mounts); override attempts via HGTPU_CKPT_RETRIES
def _io_retries() -> int:
    return max(int(os.environ.get("HGTPU_CKPT_RETRIES", "3")), 1)


def _count(name: str, **labels) -> None:
    from hetu_galvatron_tpu.observability.registry import get_registry

    get_registry().counter(f"checkpoint/{name}", **labels).inc()


def _step_of(entry: str) -> Optional[int]:
    """``step_<int>`` -> int; anything else (orbax temp dirs,
    ``step_5.partial``, our ``.tmp`` staging dirs) -> None."""
    if not entry.startswith("step_"):
        return None
    suffix = entry[len("step_"):]
    if not suffix.isdigit():
        return None
    return int(suffix)


def is_committed(ckpt_dir: str) -> bool:
    """A step dir counts as committed when it carries the commit marker
    (new protocol) or a meta.json (pre-marker checkpoints, which wrote
    meta.json last) — partial dirs from a mid-save crash have neither
    under their final name."""
    return (os.path.exists(os.path.join(ckpt_dir, COMMIT_MARKER))
            or os.path.exists(os.path.join(ckpt_dir, "meta.json")))


def _plan_fingerprint(hpc) -> Dict[str, Any]:
    from hetu_galvatron_tpu.utils.strategy import strategy_list2config

    cfg = strategy_list2config(
        hpc.layers, global_bsz=hpc.global_bsz, chunks=hpc.chunks,
        pipeline_type=hpc.pipeline_type,
        default_dp_type=hpc.default_dp_type.short, vocab=hpc.vocab,
        pp_division=hpc.pp_division,
        num_encoder_layers=hpc.num_encoder_layers or None)
    cfg["world_size"] = hpc.world_size
    return cfg


@dataclass
class _PendingSave:
    """An async save still being written by orbax: the commit (marker +
    rename + retention GC) runs only after ``wait_until_finished``."""

    ckptrs: List[Any]
    tmp_dir: str
    final_dir: str
    root: str
    keep_last: int = 0


_PENDING: List[_PendingSave] = []


def _commit(tmp_dir: str, final_dir: str) -> None:
    """Publish a fully-written staging dir: marker first (fsynced), then
    the atomic rename onto the final step name."""
    marker = os.path.join(tmp_dir, COMMIT_MARKER)
    with open(marker, "w") as f:
        f.write("committed\n")
        f.flush()
        os.fsync(f.fileno())
    old = None
    if os.path.isdir(final_dir):
        # overwriting an existing step (re-save after a rollback): keep
        # the previous payload selectable until the new one lands — rename
        # aside, replace, then delete, so a crash at any point in between
        # still leaves a committed dir (the .old name is never selected
        # and is GC'd as stale)
        old = final_dir + _OLD_SUFFIX
        if os.path.isdir(old):
            shutil.rmtree(old)
        os.replace(final_dir, old)
    os.replace(tmp_dir, final_dir)
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)
    _count("committed")


def save_checkpoint(
    path: str,
    step: int,
    params: Params,
    opt_state: Any = None,
    hpc=None,
    *,
    async_save: bool = False,
    train_state: Optional[Dict[str, Any]] = None,
    keep_last: int = 0,
) -> str:
    """Write step directory ``<path>/step_<n>`` with params/opt_state plus
    the hybrid-parallel plan JSON (reference hybrid_parallel_configs.json).

    The write is atomic: everything lands in ``step_<n>.tmp`` and is
    renamed into place only once complete, so a crash mid-save can never
    produce a directory :func:`latest_checkpoint` would select.
    ``train_state`` is an arbitrary JSON-serializable dict stored in
    meta.json (data-iterator position, RNG seed, rerun records, telemetry
    step — the full-state-resume payload). ``keep_last > 0`` prunes all
    but the newest N committed steps after this one commits."""
    ckpt_dir = os.path.abspath(os.path.join(path, f"step_{step}"))
    tmp_dir = ckpt_dir + _TMP_SUFFIX
    # multi-controller pods share the filesystem: only the commit runner
    # (process 0) cleans stale staging dirs and writes meta — a lagging
    # peer must never rmtree a dir its neighbors already stream into
    primary = jax.process_index() == 0
    if primary:
        if os.path.isdir(tmp_dir):
            # stale staging dir from a crashed earlier attempt at this step
            shutil.rmtree(tmp_dir)
        os.makedirs(tmp_dir, exist_ok=True)
    if jax.process_count() > 1:
        # barrier: no peer may start streaming shards into tmp_dir until
        # the primary's stale-dir cleanup above has finished
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"hgtpu_ckpt_stage_{step}")
    ckptrs = [ocp.StandardCheckpointer()]
    ckptrs[0].save(os.path.join(tmp_dir, "params"), params, force=True)
    if opt_state is not None:
        # separate checkpointer: StandardCheckpointer serializes saves, a
        # second handle lets both trees stream concurrently
        ckptrs.append(ocp.StandardCheckpointer())
        ckptrs[-1].save(os.path.join(tmp_dir, "opt_state"), opt_state,
                        force=True)
    meta: Dict[str, Any] = {"step": step}
    if hpc is not None:
        meta["hybrid_parallel_config"] = _plan_fingerprint(hpc)
    if train_state is not None:
        meta["train_state"] = train_state
    if primary:
        with open(os.path.join(tmp_dir, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2)
    _count("saved")
    pending = _PendingSave(ckptrs, tmp_dir, ckpt_dir,
                           os.path.abspath(path), keep_last)
    if async_save:
        # orbax streams shards in the background; training overlaps the
        # write and wait_for_checkpoints() commits it at the next barrier
        # (before any read of the ckpt, and at exit)
        _PENDING.append(pending)
    else:
        _finish(pending)
    return ckpt_dir


def _finish(p: _PendingSave) -> None:
    # await EVERY checkpointer even when an earlier one fails: an
    # abandoned background write would keep streaming into a staging dir
    # a restarted attempt is about to clean
    first_err: Optional[BaseException] = None
    for c in p.ckptrs:
        try:
            c.wait_until_finished()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err
    # multi-controller pods: every process streams its shards through
    # orbax, but exactly one performs the marker/rename commit and the
    # retention GC (shared filesystem)
    if jax.process_index() == 0:
        _commit(p.tmp_dir, p.final_dir)
        if p.keep_last > 0:
            gc_checkpoints(p.root, keep_last=p.keep_last)


def wait_for_checkpoints() -> None:
    """Block until every async save has committed (reference async_save
    drains at exit). The queue drains completely even when one save
    fails: every checkpointer is awaited (a per-entry except keeps the
    loop going, so no abandoned background write keeps the process alive
    or races a later save) and the first error re-raises after the
    drain. Each entry is popped before finishing so its own final dir is
    not counted as in-flight by its retention GC."""
    first_err: Optional[BaseException] = None
    while _PENDING:
        p = _PENDING.pop(0)
        try:
            _finish(p)
        except BaseException as e:  # noqa: BLE001 — re-raised after drain
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err


def _in_flight_dirs() -> set:
    return {p.tmp_dir for p in _PENDING} | {p.final_dir for p in _PENDING}


# The step dir a live resume just selected, per checkpoint root: retention
# pruning racing a concurrent resume (an async save committing keep_last
# GC between latest_checkpoint() and the meta/shard reads) must never
# delete it out from under the restore. latest_checkpoint() records its
# selection here; the NEXT selection on the same root releases the
# previous one, so a long run retains at most one extra step dir.
# SCOPE: process-local — it closes the in-process race (the async-save
# commit GC and maybe_resume share this process). A SEPARATE process
# reading the root (cli/serve.py watch=) still relies on the shared
# retry/backoff policies; cross-process leases are future work.
_RESUME_PROTECTED: Dict[str, str] = {}


def _recover_orphaned_old(path: str) -> None:
    """Roll back a crash mid-overwrite: if ``step_<n>.old`` (the previous
    committed payload renamed aside by :func:`_commit`) exists without a
    ``step_<n>``, the crash hit between the two renames — restore the old
    payload so the step stays selectable."""
    for entry in os.listdir(path):
        if not entry.endswith(_OLD_SUFFIX):
            continue
        base = entry[:-len(_OLD_SUFFIX)]
        if _step_of(base) is None:
            continue
        full = os.path.join(path, entry)
        final = os.path.join(path, base)
        if not os.path.exists(final) and is_committed(full):
            try:
                os.replace(full, final)
                _count("old_recovered")
            except OSError:
                pass  # a concurrent reader raced the same rollback


def gc_checkpoints(path: str, *, keep_last: int = 0) -> List[str]:
    """Remove partial step dirs (crashed saves) and, with ``keep_last > 0``,
    all but the newest N committed steps. In-flight async saves are never
    touched. Returns the removed paths."""
    if not os.path.isdir(path):
        return []
    _recover_orphaned_old(path)
    busy = _in_flight_dirs()
    protected = _RESUME_PROTECTED.get(os.path.abspath(path))
    removed: List[str] = []
    committed: List[tuple] = []
    for entry in sorted(os.listdir(path)):
        full = os.path.join(path, entry)
        if not os.path.isdir(full) or full in busy:
            continue
        step = _step_of(entry)
        if step is not None and is_committed(full):
            committed.append((step, full))
            continue
        # our own staging/partial/old dirs only — a stray step_x or
        # step_5.partial we did not create is skipped, never deleted.
        # A surviving .old here is superseded (its final dir exists, or
        # _recover_orphaned_old would have rolled it back).
        stale_ours = step is not None or any(
            entry.endswith(suf) and _step_of(entry[:-len(suf)]) is not None
            for suf in (_TMP_SUFFIX, _OLD_SUFFIX))
        if stale_ours:
            shutil.rmtree(full, ignore_errors=True)
            removed.append(full)
            _count("gc_removed", kind="partial")
    if keep_last > 0 and len(committed) > keep_last:
        committed.sort()
        for _, full in committed[:-keep_last]:
            if protected and os.path.abspath(full) == protected:
                # a live resume selected this step: hold it out of the
                # prune set until the next selection releases it
                _count("gc_protected")
                continue
            shutil.rmtree(full, ignore_errors=True)
            removed.append(full)
            _count("gc_removed", kind="retention")
    return removed


def latest_checkpoint(path: str) -> Optional[str]:
    """Newest COMMITTED step dir, or None. Stray ``step_*`` entries with a
    non-integer suffix (orbax temp dirs, ``step_5.partial``) are skipped
    instead of crashing resume, and uncommitted partial dirs from a
    mid-save crash are never selected."""
    if not os.path.isdir(path):
        return None
    _recover_orphaned_old(path)
    best_step, best = -1, None
    for entry in os.listdir(path):
        step = _step_of(entry)
        if step is None:
            continue
        full = os.path.join(path, entry)
        if not os.path.isdir(full) or not is_committed(full):
            _count("partial_skipped")
            continue
        if step > best_step:
            best_step, best = step, full
    root = os.path.abspath(path)
    if best is not None:
        # shield the selection from retention pruning until the next
        # selection on this root (see _RESUME_PROTECTED)
        _RESUME_PROTECTED[root] = os.path.abspath(best)
    else:
        _RESUME_PROTECTED.pop(root, None)
    return best


def read_checkpoint_meta(ckpt_dir: str) -> Dict[str, Any]:
    """The step dir's meta.json (step, plan fingerprint, train_state) —
    {} when absent. Reads retry transient I/O errors (flaky object-store
    mounts) through the shared backoff policy."""
    mp = os.path.join(ckpt_dir, "meta.json")
    if not os.path.exists(mp):
        return {}

    def _read():
        with open(mp) as f:
            return json.load(f)

    return retry_call(_read, attempts=_io_retries(), base=0.2, cap=5.0,
                      retryable=lambda e: isinstance(e, OSError),
                      op="checkpoint.read_meta")


def load_checkpoint(
    ckpt_dir: str,
    params_target: Params,
    opt_target: Any = None,
    hpc=None,
    *,
    strict_plan: bool = False,
    expected_world: Optional[int] = None,
):
    """Restore into the target sharding/shape tree. ``strict_plan`` asserts
    the stored plan matches (the reference asserts equality on resume,
    hybrid_parallel_config.py:132-144); by default a mismatch is allowed —
    orbax reshards into the new plan's shardings. ``expected_world``
    validates the checkpoint's recorded world_size against the live world
    and raises the typed :class:`WorldSizeMismatchError` naming both
    (instead of a shape error deep in device_put) — the condition the
    elastic resume path catches to trigger re-search + reshard. Restores
    retry transient I/O errors with jittered backoff (preemptible fleets
    resume through flaky object-store reads)."""
    ckpt_dir = os.path.abspath(ckpt_dir)  # orbax rejects relative paths
    meta = read_checkpoint_meta(ckpt_dir)
    if "step" not in meta:
        raise FileNotFoundError(
            f"{ckpt_dir} has no meta.json — not a committed checkpoint")
    if expected_world is not None:
        stored_plan = meta.get("hybrid_parallel_config") or {}
        sw = stored_plan.get("world_size")
        if sw is not None and int(sw) != int(expected_world):
            raise WorldSizeMismatchError(ckpt_dir, int(sw),
                                         int(expected_world), stored_plan)
    if strict_plan and hpc is not None:
        stored = meta.get("hybrid_parallel_config")
        current = _plan_fingerprint(hpc)
        if stored != current:
            raise ValueError(
                f"checkpoint plan mismatch:\nstored  {stored}\n"
                f"current {current}")
    ckptr = ocp.StandardCheckpointer()

    def _restore(sub, target):
        return retry_call(
            lambda: ckptr.restore(os.path.join(ckpt_dir, sub), target),
            attempts=_io_retries(), base=0.2, cap=5.0,
            retryable=lambda e: isinstance(e, OSError),
            op="checkpoint.restore")

    params = _restore("params", params_target)
    opt_state = None
    if opt_target is not None and os.path.isdir(
            os.path.join(ckpt_dir, "opt_state")):
        opt_state = _restore("opt_state", opt_target)
    return params, opt_state, meta["step"]


# ---------------------------------------------------------------------------
# HuggingFace interchange (h2g / g2h)
# ---------------------------------------------------------------------------


def hf_to_params(state_dict: Dict[str, Any], cfg: ModelArgs) -> Params:
    """HF torch state dict -> our params pytree (reference h2g converters,
    tools/checkpoint_convert_h2g.py + llama_adapter.py:51-163). Supports the
    gpt2 (Conv1D fused qkv) and llama (separate q/k/v Linear) layouts."""
    import numpy as np

    def arr(t):
        return np.asarray(t.detach().numpy() if hasattr(t, "detach") else t)

    sd = {k: arr(v) for k, v in state_dict.items()}
    n = cfg.num_hidden_layers
    if cfg.model_type == "gpt" or "transformer.wte.weight" in sd:
        layers = []
        for i in range(n):
            pre = f"transformer.h.{i}."
            lp = {
                "ln1": {"scale": sd[pre + "ln_1.weight"],
                        "bias": sd[pre + "ln_1.bias"]},
                "attn": {"wqkv": sd[pre + "attn.c_attn.weight"],
                         "bqkv": sd[pre + "attn.c_attn.bias"],
                         "wo": sd[pre + "attn.c_proj.weight"],
                         "bo": sd[pre + "attn.c_proj.bias"]},
                "ln2": {"scale": sd[pre + "ln_2.weight"],
                        "bias": sd[pre + "ln_2.bias"]},
                "mlp": {"win": sd[pre + "mlp.c_fc.weight"],
                        "bin": sd[pre + "mlp.c_fc.bias"],
                        "wout": sd[pre + "mlp.c_proj.weight"],
                        "bout": sd[pre + "mlp.c_proj.bias"]},
            }
            layers.append(lp)
        wte = sd["transformer.wte.weight"]
        pad = cfg.padded_vocab_size - wte.shape[0]
        if pad > 0:
            wte = np.concatenate([wte, np.zeros((pad, wte.shape[1]),
                                                wte.dtype)])
        # HF gpt2 always ties lm_head to wte; an untied target config needs
        # its own whead or apply_lm_head would KeyError much later (ADVICE r2)
        head: Params = {}
        if not cfg.tie_word_embeddings:
            head = {"whead": (_pad_vocab(sd["lm_head.weight"], cfg).T
                              if "lm_head.weight" in sd else wte.T)}
        return {
            "embed": {"wte": wte, "wpe": sd["transformer.wpe.weight"]},
            "layers": tuple(layers),
            "prenorm": {"scale": sd["transformer.ln_f.weight"],
                        "bias": sd["transformer.ln_f.bias"]},
            "head": head,
        }

    if cfg.model_type == "bert" or "bert.embeddings.word_embeddings.weight" in sd:
        return _bert_hf_to_params(sd, cfg)
    if cfg.model_type == "t5" or "encoder.final_layer_norm.weight" in sd:
        return _t5_hf_to_params(sd, cfg)

    # llama-family: torch Linear stores [out, in] -> transpose
    def lin(name):
        return sd[name].T

    layers = []
    for i in range(n):
        pre = f"model.layers.{i}."
        wqkv = np.concatenate(
            [lin(pre + "self_attn.q_proj.weight"),
             lin(pre + "self_attn.k_proj.weight"),
             lin(pre + "self_attn.v_proj.weight")], axis=1)
        lp = {
            "ln1": {"scale": sd[pre + "input_layernorm.weight"]},
            "attn": {"wqkv": wqkv, "wo": lin(pre + "self_attn.o_proj.weight")},
            "ln2": {"scale": sd[pre + "post_attention_layernorm.weight"]},
        }
        if pre + "block_sparse_moe.gate.weight" in sd:
            # mixtral-style MoE FFN (reference moe_adapter.py:58-266):
            # experts.{e}.w1/w3 fuse into win [E, H, 2F], w2 -> wout [E, F, H]
            if cfg.num_shared_experts:
                raise NotImplementedError(
                    "the Mixtral HF layout has no shared-expert slot; "
                    "import with num_shared_experts=0")
            E = 0
            while pre + f"block_sparse_moe.experts.{E}.w1.weight" in sd:
                E += 1
            if E != cfg.num_experts:
                raise ValueError(
                    f"layer {i}: checkpoint has {E} experts but "
                    f"cfg.num_experts is {cfg.num_experts}")
            win = np.stack([
                np.concatenate(
                    [lin(pre + f"block_sparse_moe.experts.{e}.w1.weight"),
                     lin(pre + f"block_sparse_moe.experts.{e}.w3.weight")],
                    axis=1)
                for e in range(E)])
            wout = np.stack([
                lin(pre + f"block_sparse_moe.experts.{e}.w2.weight")
                for e in range(E)])
            lp["moe"] = {
                "router": lin(pre + "block_sparse_moe.gate.weight"),
                "win": win,
                "wout": wout,
            }
        else:
            win = np.concatenate(
                [lin(pre + "mlp.gate_proj.weight"),
                 lin(pre + "mlp.up_proj.weight")], axis=1)
            lp["mlp"] = {"win": win,
                         "wout": lin(pre + "mlp.down_proj.weight")}
        if cfg.add_qkv_bias:
            lp["attn"]["bqkv"] = np.concatenate(
                [sd[pre + "self_attn.q_proj.bias"],
                 sd[pre + "self_attn.k_proj.bias"],
                 sd[pre + "self_attn.v_proj.bias"]])
        layers.append(lp)
    wte = sd["model.embed_tokens.weight"]
    pad = cfg.padded_vocab_size - wte.shape[0]
    if pad > 0:
        wte = np.concatenate([wte, np.zeros((pad, wte.shape[1]), wte.dtype)])
    out: Params = {
        "embed": {"wte": wte},
        "layers": tuple(layers),
        "prenorm": {"scale": sd["model.norm.weight"]},
    }
    if cfg.tie_word_embeddings:
        out["head"] = {}
    else:
        whead = lin("lm_head.weight")
        if pad > 0:
            whead = np.concatenate(
                [whead, np.zeros((whead.shape[0], pad), whead.dtype)], axis=1)
        out["head"] = {"whead": whead}
    return out


def _pad_vocab(w: "np.ndarray", cfg: ModelArgs) -> "np.ndarray":
    import numpy as np

    pad = cfg.padded_vocab_size - w.shape[0]
    if pad > 0:
        w = np.concatenate(
            [w, np.zeros((pad,) + w.shape[1:], w.dtype)])
    return w


def _bert_hf_to_params(sd: Dict[str, Any], cfg: ModelArgs) -> Params:
    """HF BertForMaskedLM -> our post-norm encoder layout (reference
    tools/checkpoint_convert_h2g.py bert path). Token-type embeddings are
    folded into wpe for single-segment (type-0) training — the parallelism
    framework trains MLM on single segments (runtime/dataloader.py
    mlm_batches)."""
    import numpy as np

    def lin(name):
        return sd[name].T

    n = cfg.num_hidden_layers
    layers = []
    for i in range(n):
        pre = f"bert.encoder.layer.{i}."
        wqkv = np.concatenate(
            [lin(pre + "attention.self.query.weight"),
             lin(pre + "attention.self.key.weight"),
             lin(pre + "attention.self.value.weight")], axis=1)
        bqkv = np.concatenate(
            [sd[pre + "attention.self.query.bias"],
             sd[pre + "attention.self.key.bias"],
             sd[pre + "attention.self.value.bias"]])
        layers.append({
            "attn": {"wqkv": wqkv, "bqkv": bqkv,
                     "wo": lin(pre + "attention.output.dense.weight"),
                     "bo": sd[pre + "attention.output.dense.bias"]},
            "ln1": {"scale": sd[pre + "attention.output.LayerNorm.weight"],
                    "bias": sd[pre + "attention.output.LayerNorm.bias"]},
            "mlp": {"win": lin(pre + "intermediate.dense.weight"),
                    "bin": sd[pre + "intermediate.dense.bias"],
                    "wout": lin(pre + "output.dense.weight"),
                    "bout": sd[pre + "output.dense.bias"]},
            "ln2": {"scale": sd[pre + "output.LayerNorm.weight"],
                    "bias": sd[pre + "output.LayerNorm.bias"]},
        })
    wte = _pad_vocab(sd["bert.embeddings.word_embeddings.weight"], cfg)
    wpe = (sd["bert.embeddings.position_embeddings.weight"]
           + sd["bert.embeddings.token_type_embeddings.weight"][0][None, :])
    head: Params = {
        "wt": lin("cls.predictions.transform.dense.weight"),
        "bt": sd["cls.predictions.transform.dense.bias"],
        "ln": {"scale": sd["cls.predictions.transform.LayerNorm.weight"],
               "bias": sd["cls.predictions.transform.LayerNorm.bias"]},
        "bias": _pad_vocab(sd["cls.predictions.bias"], cfg),
    }
    if not cfg.tie_word_embeddings:
        head["whead"] = _pad_vocab(
            sd.get("cls.predictions.decoder.weight",
                   sd["bert.embeddings.word_embeddings.weight"]), cfg).T
    return {
        "embed": {"wte": wte, "wpe": wpe,
                  "ln": {"scale": sd["bert.embeddings.LayerNorm.weight"],
                         "bias": sd["bert.embeddings.LayerNorm.bias"]}},
        "layers": tuple(layers),
        "prenorm": {},
        "head": head,
    }


def _t5_hf_to_params(sd: Dict[str, Any], cfg: ModelArgs) -> Params:
    """HF T5ForConditionalGeneration -> our encoder-decoder layout.

    All projection/norm/MLP weights map 1:1 (q/k/v fused per stack; the
    decoder's EncDecAttention becomes the fused-KV cross block). HF T5's
    relative_attention_bias has no slot here by design — this runtime is
    position-scheme agnostic (models/encdec.py docstring) and runs the
    configured scheme (RoPE/learned), so imported T5 weights fine-tune
    rather than bit-match HF generation."""
    import numpy as np

    def lin(name):
        return sd[name].T

    inner = sd["encoder.block.0.layer.0.SelfAttention.q.weight"].shape[0]
    if inner != cfg.num_attention_heads * cfg.head_dim:
        raise ValueError(
            f"t5 checkpoint attention inner dim {inner} != heads*head_dim "
            f"{cfg.num_attention_heads * cfg.head_dim}: this runtime derives "
            "head_dim = hidden//heads (t5-small/base/large match; t5-3b/11b "
            "use d_kv=128 and need a config with matching geometry)")

    gated = "encoder.block.0.layer.1.DenseReluDense.wi_0.weight" in sd

    def mlp(pre):
        if gated:  # t5 v1.1 gated-act: wi_0 (gate) | wi_1 (up)
            win = np.concatenate([lin(pre + "DenseReluDense.wi_0.weight"),
                                  lin(pre + "DenseReluDense.wi_1.weight")],
                                 axis=1)
        else:
            win = lin(pre + "DenseReluDense.wi.weight")
        return {"win": win, "wout": lin(pre + "DenseReluDense.wo.weight")}

    n_enc = (cfg.num_encoder_layers if cfg.num_encoder_layers is not None
             else cfg.num_hidden_layers)
    enc_layers = []
    for i in range(n_enc):
        pre = f"encoder.block.{i}."
        wqkv = np.concatenate(
            [lin(pre + "layer.0.SelfAttention.q.weight"),
             lin(pre + "layer.0.SelfAttention.k.weight"),
             lin(pre + "layer.0.SelfAttention.v.weight")], axis=1)
        enc_layers.append({
            "ln1": {"scale": sd[pre + "layer.0.layer_norm.weight"]},
            "attn": {"wqkv": wqkv,
                     "wo": lin(pre + "layer.0.SelfAttention.o.weight")},
            "ln2": {"scale": sd[pre + "layer.1.layer_norm.weight"]},
            "mlp": mlp(pre + "layer.1."),
        })
    dec_layers = []
    for i in range(cfg.num_hidden_layers):
        pre = f"decoder.block.{i}."
        wqkv = np.concatenate(
            [lin(pre + "layer.0.SelfAttention.q.weight"),
             lin(pre + "layer.0.SelfAttention.k.weight"),
             lin(pre + "layer.0.SelfAttention.v.weight")], axis=1)
        wkv = np.concatenate(
            [lin(pre + "layer.1.EncDecAttention.k.weight"),
             lin(pre + "layer.1.EncDecAttention.v.weight")], axis=1)
        dec_layers.append({
            "ln1": {"scale": sd[pre + "layer.0.layer_norm.weight"]},
            "attn": {"wqkv": wqkv,
                     "wo": lin(pre + "layer.0.SelfAttention.o.weight")},
            "lnx": {"scale": sd[pre + "layer.1.layer_norm.weight"]},
            "cross": {"wq": lin(pre + "layer.1.EncDecAttention.q.weight"),
                      "wkv": wkv,
                      "wo": lin(pre + "layer.1.EncDecAttention.o.weight")},
            "ln2": {"scale": sd[pre + "layer.2.layer_norm.weight"]},
            "mlp": mlp(pre + "layer.2."),
        })
    out: Params = {
        "embed": {"wte": _pad_vocab(sd["shared.weight"], cfg)},
        "enc_layers": tuple(enc_layers),
        "enc_norm": {"scale": sd["encoder.final_layer_norm.weight"]},
        "layers": tuple(dec_layers),
        "prenorm": {"scale": sd["decoder.final_layer_norm.weight"]},
    }
    if cfg.tie_word_embeddings:
        out["head"] = {}
    else:
        out["head"] = {"whead": _pad_vocab(sd["lm_head.weight"], cfg).T}
    return out


def _bert_params_to_hf(params: Params, cfg: ModelArgs) -> Dict[str, "np.ndarray"]:
    """Inverse of :func:`_bert_hf_to_params`. Token-type embeddings were
    folded into wpe on import, so type 0 exports as zeros (wpe carries the
    sum) — re-importing reproduces the same forward exactly."""
    import numpy as np

    get = lambda t: np.asarray(jax.device_get(t))
    V, H = cfg.vocab_size, cfg.hidden_size
    hd, nq, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.kv_heads
    sd: Dict[str, np.ndarray] = {
        "bert.embeddings.word_embeddings.weight": get(params["embed"]["wte"])[:V],
        "bert.embeddings.position_embeddings.weight": get(params["embed"]["wpe"]),
        "bert.embeddings.token_type_embeddings.weight": np.zeros((2, H),
                                                                 np.float32),
        "bert.embeddings.LayerNorm.weight": get(params["embed"]["ln"]["scale"]),
        "bert.embeddings.LayerNorm.bias": get(params["embed"]["ln"]["bias"]),
    }
    for i, lp in enumerate(params["layers"]):
        pre = f"bert.encoder.layer.{i}."
        wqkv = get(lp["attn"]["wqkv"])
        q, k, v = np.split(wqkv, [nq * hd, (nq + nkv) * hd], axis=1)
        bq, bk, bv = np.split(get(lp["attn"]["bqkv"]),
                              [nq * hd, (nq + nkv) * hd])
        sd[pre + "attention.self.query.weight"] = q.T
        sd[pre + "attention.self.query.bias"] = bq
        sd[pre + "attention.self.key.weight"] = k.T
        sd[pre + "attention.self.key.bias"] = bk
        sd[pre + "attention.self.value.weight"] = v.T
        sd[pre + "attention.self.value.bias"] = bv
        sd[pre + "attention.output.dense.weight"] = get(lp["attn"]["wo"]).T
        sd[pre + "attention.output.dense.bias"] = get(lp["attn"]["bo"])
        sd[pre + "attention.output.LayerNorm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "attention.output.LayerNorm.bias"] = get(lp["ln1"]["bias"])
        sd[pre + "intermediate.dense.weight"] = get(lp["mlp"]["win"]).T
        sd[pre + "intermediate.dense.bias"] = get(lp["mlp"]["bin"])
        sd[pre + "output.dense.weight"] = get(lp["mlp"]["wout"]).T
        sd[pre + "output.dense.bias"] = get(lp["mlp"]["bout"])
        sd[pre + "output.LayerNorm.weight"] = get(lp["ln2"]["scale"])
        sd[pre + "output.LayerNorm.bias"] = get(lp["ln2"]["bias"])
    hp = params["head"]
    sd["cls.predictions.transform.dense.weight"] = get(hp["wt"]).T
    sd["cls.predictions.transform.dense.bias"] = get(hp["bt"])
    sd["cls.predictions.transform.LayerNorm.weight"] = get(hp["ln"]["scale"])
    sd["cls.predictions.transform.LayerNorm.bias"] = get(hp["ln"]["bias"])
    sd["cls.predictions.bias"] = get(hp["bias"])[:V]
    if not cfg.tie_word_embeddings and "whead" in hp:
        sd["cls.predictions.decoder.weight"] = get(hp["whead"]).T[:V]
    return sd


def _t5_params_to_hf(params: Params, cfg: ModelArgs) -> Dict[str, "np.ndarray"]:
    """Inverse of :func:`_t5_hf_to_params` (gated t5-v1.1 MLP layout when the
    model uses a gated activation)."""
    import numpy as np

    get = lambda t: np.asarray(jax.device_get(t))
    from hetu_galvatron_tpu.models.modules import _is_gated

    V = cfg.vocab_size
    hd, nq, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.kv_heads
    sd: Dict[str, np.ndarray] = {
        "shared.weight": get(params["embed"]["wte"])[:V],
        "encoder.final_layer_norm.weight": get(params["enc_norm"]["scale"]),
        "decoder.final_layer_norm.weight": get(params["prenorm"]["scale"]),
    }

    def put_mlp(pre, mp):
        win = get(mp["win"])
        if _is_gated(cfg.hidden_act):
            gate, up = np.split(win, 2, axis=1)
            sd[pre + "DenseReluDense.wi_0.weight"] = gate.T
            sd[pre + "DenseReluDense.wi_1.weight"] = up.T
        else:
            sd[pre + "DenseReluDense.wi.weight"] = win.T
        sd[pre + "DenseReluDense.wo.weight"] = get(mp["wout"]).T

    def put_self_attn(pre, ap):
        wqkv = get(ap["wqkv"])
        q, k, v = np.split(wqkv, [nq * hd, (nq + nkv) * hd], axis=1)
        sd[pre + "SelfAttention.q.weight"] = q.T
        sd[pre + "SelfAttention.k.weight"] = k.T
        sd[pre + "SelfAttention.v.weight"] = v.T
        sd[pre + "SelfAttention.o.weight"] = get(ap["wo"]).T

    for i, lp in enumerate(params["enc_layers"]):
        pre = f"encoder.block.{i}."
        put_self_attn(pre + "layer.0.", lp["attn"])
        sd[pre + "layer.0.layer_norm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "layer.1.layer_norm.weight"] = get(lp["ln2"]["scale"])
        put_mlp(pre + "layer.1.", lp["mlp"])
    for i, lp in enumerate(params["layers"]):
        pre = f"decoder.block.{i}."
        put_self_attn(pre + "layer.0.", lp["attn"])
        sd[pre + "layer.0.layer_norm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "layer.1.layer_norm.weight"] = get(lp["lnx"]["scale"])
        sd[pre + "layer.1.EncDecAttention.q.weight"] = get(lp["cross"]["wq"]).T
        wkv = get(lp["cross"]["wkv"])
        k, v = np.split(wkv, 2, axis=1)
        sd[pre + "layer.1.EncDecAttention.k.weight"] = k.T
        sd[pre + "layer.1.EncDecAttention.v.weight"] = v.T
        sd[pre + "layer.1.EncDecAttention.o.weight"] = get(lp["cross"]["wo"]).T
        sd[pre + "layer.2.layer_norm.weight"] = get(lp["ln2"]["scale"])
        put_mlp(pre + "layer.2.", lp["mlp"])
    if not cfg.tie_word_embeddings and params.get("head"):
        sd["lm_head.weight"] = get(params["head"]["whead"]).T[:V]
    return sd


def params_to_hf(params: Params, cfg: ModelArgs) -> Dict[str, np.ndarray]:
    """Our params -> HF-layout numpy state dict (reference g2h converters).
    Inverse of :func:`hf_to_params`; vocab padding rows are dropped."""
    get = lambda t: np.asarray(jax.device_get(t))
    sd: Dict[str, np.ndarray] = {}
    V = cfg.vocab_size
    if cfg.model_type == "bert":
        return _bert_params_to_hf(params, cfg)
    if cfg.model_type == "t5":
        return _t5_params_to_hf(params, cfg)
    if cfg.model_type == "gpt":
        sd["transformer.wte.weight"] = get(params["embed"]["wte"])[:V]
        sd["transformer.wpe.weight"] = get(params["embed"]["wpe"])
        for i, lp in enumerate(params["layers"]):
            pre = f"transformer.h.{i}."
            sd[pre + "ln_1.weight"] = get(lp["ln1"]["scale"])
            sd[pre + "ln_1.bias"] = get(lp["ln1"]["bias"])
            sd[pre + "attn.c_attn.weight"] = get(lp["attn"]["wqkv"])
            sd[pre + "attn.c_attn.bias"] = get(lp["attn"]["bqkv"])
            sd[pre + "attn.c_proj.weight"] = get(lp["attn"]["wo"])
            sd[pre + "attn.c_proj.bias"] = get(lp["attn"]["bo"])
            sd[pre + "ln_2.weight"] = get(lp["ln2"]["scale"])
            sd[pre + "ln_2.bias"] = get(lp["ln2"]["bias"])
            sd[pre + "mlp.c_fc.weight"] = get(lp["mlp"]["win"])
            sd[pre + "mlp.c_fc.bias"] = get(lp["mlp"]["bin"])
            sd[pre + "mlp.c_proj.weight"] = get(lp["mlp"]["wout"])
            sd[pre + "mlp.c_proj.bias"] = get(lp["mlp"]["bout"])
        sd["transformer.ln_f.weight"] = get(params["prenorm"]["scale"])
        sd["transformer.ln_f.bias"] = get(params["prenorm"]["bias"])
        return sd

    sd["model.embed_tokens.weight"] = get(params["embed"]["wte"])[:V]
    hd, nq, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.kv_heads
    for i, lp in enumerate(params["layers"]):
        pre = f"model.layers.{i}."
        wqkv = get(lp["attn"]["wqkv"])
        q, k, v = np.split(wqkv, [nq * hd, (nq + nkv) * hd], axis=1)
        sd[pre + "self_attn.q_proj.weight"] = q.T
        sd[pre + "self_attn.k_proj.weight"] = k.T
        sd[pre + "self_attn.v_proj.weight"] = v.T
        sd[pre + "self_attn.o_proj.weight"] = get(lp["attn"]["wo"]).T
        if "bqkv" in lp["attn"]:
            bqkv = get(lp["attn"]["bqkv"])
            bq, bk, bv = np.split(bqkv, [nq * hd, (nq + nkv) * hd])
            sd[pre + "self_attn.q_proj.bias"] = bq
            sd[pre + "self_attn.k_proj.bias"] = bk
            sd[pre + "self_attn.v_proj.bias"] = bv
        if "moe" in lp:
            if "shared" in lp["moe"]:
                raise NotImplementedError(
                    "the Mixtral HF layout has no shared-expert slot; "
                    "export models with num_shared_experts=0")
            sd[pre + "block_sparse_moe.gate.weight"] = \
                get(lp["moe"]["router"]).T
            win = get(lp["moe"]["win"])
            wout = get(lp["moe"]["wout"])
            for e in range(win.shape[0]):
                w1, w3 = np.split(win[e], 2, axis=1)
                sd[pre + f"block_sparse_moe.experts.{e}.w1.weight"] = w1.T
                sd[pre + f"block_sparse_moe.experts.{e}.w3.weight"] = w3.T
                sd[pre + f"block_sparse_moe.experts.{e}.w2.weight"] = \
                    wout[e].T
        else:
            win = get(lp["mlp"]["win"])
            gate, up = np.split(win, 2, axis=1)
            sd[pre + "mlp.gate_proj.weight"] = gate.T
            sd[pre + "mlp.up_proj.weight"] = up.T
            sd[pre + "mlp.down_proj.weight"] = get(lp["mlp"]["wout"]).T
        sd[pre + "input_layernorm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "post_attention_layernorm.weight"] = get(lp["ln2"]["scale"])
    sd["model.norm.weight"] = get(params["prenorm"]["scale"])
    if not cfg.tie_word_embeddings and params.get("head"):
        sd["lm_head.weight"] = get(params["head"]["whead"]).T[:V]
    return sd
