"""Distributed checkpoint save/load + HF interchange.

Capability parity with the reference checkpoint stack
(runtime/checkpoint/llama_adapter.py:30-172 save/load, tools/
checkpoint_convert_{h2g,g2h}.py, hybrid_parallel_config.py:132-144 config
assert-on-resume): sharded save/restore of params + optimizer state + step,
the parallel-plan JSON stored alongside and verified on resume, and
HuggingFace state-dict import/export for GPT-2- and Llama-family models.

TPU-native: orbax-checkpoint writes each array shard from the device that
owns it (the reference hand-rolls per-(layer, tp-rank) files with dp-rank-0
writers); restore takes a target sharding tree, so a checkpoint saved under
one parallel plan reloads under another — the resharding the reference does
with TP-slicing loaders (llama_adapter.py:51-163) falls out of GSPMD.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

import orbax.checkpoint as ocp

from hetu_galvatron_tpu.core.args_schema import ModelArgs

Params = Dict[str, Any]


def _plan_fingerprint(hpc) -> Dict[str, Any]:
    from hetu_galvatron_tpu.utils.strategy import strategy_list2config

    cfg = strategy_list2config(
        hpc.layers, global_bsz=hpc.global_bsz, chunks=hpc.chunks,
        pipeline_type=hpc.pipeline_type,
        default_dp_type=hpc.default_dp_type.short, vocab=hpc.vocab,
        pp_division=hpc.pp_division,
        num_encoder_layers=hpc.num_encoder_layers or None)
    cfg["world_size"] = hpc.world_size
    return cfg


def save_checkpoint(
    path: str,
    step: int,
    params: Params,
    opt_state: Any = None,
    hpc=None,
    *,
    async_save: bool = False,
) -> str:
    """Write step directory ``<path>/step_<n>`` with params/opt_state plus
    the hybrid-parallel plan JSON (reference hybrid_parallel_configs.json)."""
    global _PENDING
    ckpt_dir = os.path.abspath(os.path.join(path, f"step_{step}"))
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(ckpt_dir, "params"), params, force=True)
    if opt_state is not None:
        ckptr.save(os.path.join(ckpt_dir, "opt_state"), opt_state, force=True)
    meta = {"step": step}
    if hpc is not None:
        meta["hybrid_parallel_config"] = _plan_fingerprint(hpc)
    with open(os.path.join(ckpt_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)
    if async_save:
        # orbax commits in the background; training overlaps the write.
        # Call wait_for_checkpoints() before exiting/reading the ckpt.
        _PENDING.append(ckptr)
    else:
        ckptr.wait_until_finished()
    return ckpt_dir


_PENDING = []


def wait_for_checkpoints() -> None:
    """Block until every async save has committed (reference async_save
    drains at exit)."""
    while _PENDING:
        _PENDING.pop().wait_until_finished()


def latest_checkpoint(path: str) -> Optional[str]:
    if not os.path.isdir(path):
        return None
    steps = [d for d in os.listdir(path) if d.startswith("step_")]
    if not steps:
        return None
    latest = max(steps, key=lambda d: int(d.split("_")[1]))
    return os.path.join(path, latest)


def load_checkpoint(
    ckpt_dir: str,
    params_target: Params,
    opt_target: Any = None,
    hpc=None,
    *,
    strict_plan: bool = False,
):
    """Restore into the target sharding/shape tree. ``strict_plan`` asserts
    the stored plan matches (the reference asserts equality on resume,
    hybrid_parallel_config.py:132-144); by default a mismatch is allowed —
    orbax reshards into the new plan's shardings."""
    ckpt_dir = os.path.abspath(ckpt_dir)  # orbax rejects relative paths
    meta = json.load(open(os.path.join(ckpt_dir, "meta.json")))
    if strict_plan and hpc is not None:
        stored = meta.get("hybrid_parallel_config")
        current = _plan_fingerprint(hpc)
        if stored != current:
            raise ValueError(
                f"checkpoint plan mismatch:\nstored  {stored}\n"
                f"current {current}")
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(os.path.join(ckpt_dir, "params"), params_target)
    opt_state = None
    if opt_target is not None and os.path.isdir(
            os.path.join(ckpt_dir, "opt_state")):
        opt_state = ckptr.restore(os.path.join(ckpt_dir, "opt_state"),
                                  opt_target)
    return params, opt_state, meta["step"]


# ---------------------------------------------------------------------------
# HuggingFace interchange (h2g / g2h)
# ---------------------------------------------------------------------------


def hf_to_params(state_dict: Dict[str, Any], cfg: ModelArgs) -> Params:
    """HF torch state dict -> our params pytree (reference h2g converters,
    tools/checkpoint_convert_h2g.py + llama_adapter.py:51-163). Supports the
    gpt2 (Conv1D fused qkv) and llama (separate q/k/v Linear) layouts."""
    import numpy as np

    def arr(t):
        return np.asarray(t.detach().numpy() if hasattr(t, "detach") else t)

    sd = {k: arr(v) for k, v in state_dict.items()}
    n = cfg.num_hidden_layers
    if cfg.model_type == "gpt" or "transformer.wte.weight" in sd:
        layers = []
        for i in range(n):
            pre = f"transformer.h.{i}."
            lp = {
                "ln1": {"scale": sd[pre + "ln_1.weight"],
                        "bias": sd[pre + "ln_1.bias"]},
                "attn": {"wqkv": sd[pre + "attn.c_attn.weight"],
                         "bqkv": sd[pre + "attn.c_attn.bias"],
                         "wo": sd[pre + "attn.c_proj.weight"],
                         "bo": sd[pre + "attn.c_proj.bias"]},
                "ln2": {"scale": sd[pre + "ln_2.weight"],
                        "bias": sd[pre + "ln_2.bias"]},
                "mlp": {"win": sd[pre + "mlp.c_fc.weight"],
                        "bin": sd[pre + "mlp.c_fc.bias"],
                        "wout": sd[pre + "mlp.c_proj.weight"],
                        "bout": sd[pre + "mlp.c_proj.bias"]},
            }
            layers.append(lp)
        wte = sd["transformer.wte.weight"]
        pad = cfg.padded_vocab_size - wte.shape[0]
        if pad > 0:
            wte = np.concatenate([wte, np.zeros((pad, wte.shape[1]),
                                                wte.dtype)])
        # HF gpt2 always ties lm_head to wte; an untied target config needs
        # its own whead or apply_lm_head would KeyError much later (ADVICE r2)
        head: Params = {}
        if not cfg.tie_word_embeddings:
            head = {"whead": (_pad_vocab(sd["lm_head.weight"], cfg).T
                              if "lm_head.weight" in sd else wte.T)}
        return {
            "embed": {"wte": wte, "wpe": sd["transformer.wpe.weight"]},
            "layers": tuple(layers),
            "prenorm": {"scale": sd["transformer.ln_f.weight"],
                        "bias": sd["transformer.ln_f.bias"]},
            "head": head,
        }

    if cfg.model_type == "bert" or "bert.embeddings.word_embeddings.weight" in sd:
        return _bert_hf_to_params(sd, cfg)
    if cfg.model_type == "t5" or "encoder.final_layer_norm.weight" in sd:
        return _t5_hf_to_params(sd, cfg)

    # llama-family: torch Linear stores [out, in] -> transpose
    def lin(name):
        return sd[name].T

    layers = []
    for i in range(n):
        pre = f"model.layers.{i}."
        wqkv = np.concatenate(
            [lin(pre + "self_attn.q_proj.weight"),
             lin(pre + "self_attn.k_proj.weight"),
             lin(pre + "self_attn.v_proj.weight")], axis=1)
        lp = {
            "ln1": {"scale": sd[pre + "input_layernorm.weight"]},
            "attn": {"wqkv": wqkv, "wo": lin(pre + "self_attn.o_proj.weight")},
            "ln2": {"scale": sd[pre + "post_attention_layernorm.weight"]},
        }
        if pre + "block_sparse_moe.gate.weight" in sd:
            # mixtral-style MoE FFN (reference moe_adapter.py:58-266):
            # experts.{e}.w1/w3 fuse into win [E, H, 2F], w2 -> wout [E, F, H]
            if cfg.num_shared_experts:
                raise NotImplementedError(
                    "the Mixtral HF layout has no shared-expert slot; "
                    "import with num_shared_experts=0")
            E = 0
            while pre + f"block_sparse_moe.experts.{E}.w1.weight" in sd:
                E += 1
            if E != cfg.num_experts:
                raise ValueError(
                    f"layer {i}: checkpoint has {E} experts but "
                    f"cfg.num_experts is {cfg.num_experts}")
            win = np.stack([
                np.concatenate(
                    [lin(pre + f"block_sparse_moe.experts.{e}.w1.weight"),
                     lin(pre + f"block_sparse_moe.experts.{e}.w3.weight")],
                    axis=1)
                for e in range(E)])
            wout = np.stack([
                lin(pre + f"block_sparse_moe.experts.{e}.w2.weight")
                for e in range(E)])
            lp["moe"] = {
                "router": lin(pre + "block_sparse_moe.gate.weight"),
                "win": win,
                "wout": wout,
            }
        else:
            win = np.concatenate(
                [lin(pre + "mlp.gate_proj.weight"),
                 lin(pre + "mlp.up_proj.weight")], axis=1)
            lp["mlp"] = {"win": win,
                         "wout": lin(pre + "mlp.down_proj.weight")}
        if cfg.add_qkv_bias:
            lp["attn"]["bqkv"] = np.concatenate(
                [sd[pre + "self_attn.q_proj.bias"],
                 sd[pre + "self_attn.k_proj.bias"],
                 sd[pre + "self_attn.v_proj.bias"]])
        layers.append(lp)
    wte = sd["model.embed_tokens.weight"]
    pad = cfg.padded_vocab_size - wte.shape[0]
    if pad > 0:
        wte = np.concatenate([wte, np.zeros((pad, wte.shape[1]), wte.dtype)])
    out: Params = {
        "embed": {"wte": wte},
        "layers": tuple(layers),
        "prenorm": {"scale": sd["model.norm.weight"]},
    }
    if cfg.tie_word_embeddings:
        out["head"] = {}
    else:
        whead = lin("lm_head.weight")
        if pad > 0:
            whead = np.concatenate(
                [whead, np.zeros((whead.shape[0], pad), whead.dtype)], axis=1)
        out["head"] = {"whead": whead}
    return out


def _pad_vocab(w: "np.ndarray", cfg: ModelArgs) -> "np.ndarray":
    import numpy as np

    pad = cfg.padded_vocab_size - w.shape[0]
    if pad > 0:
        w = np.concatenate(
            [w, np.zeros((pad,) + w.shape[1:], w.dtype)])
    return w


def _bert_hf_to_params(sd: Dict[str, Any], cfg: ModelArgs) -> Params:
    """HF BertForMaskedLM -> our post-norm encoder layout (reference
    tools/checkpoint_convert_h2g.py bert path). Token-type embeddings are
    folded into wpe for single-segment (type-0) training — the parallelism
    framework trains MLM on single segments (runtime/dataloader.py
    mlm_batches)."""
    import numpy as np

    def lin(name):
        return sd[name].T

    n = cfg.num_hidden_layers
    layers = []
    for i in range(n):
        pre = f"bert.encoder.layer.{i}."
        wqkv = np.concatenate(
            [lin(pre + "attention.self.query.weight"),
             lin(pre + "attention.self.key.weight"),
             lin(pre + "attention.self.value.weight")], axis=1)
        bqkv = np.concatenate(
            [sd[pre + "attention.self.query.bias"],
             sd[pre + "attention.self.key.bias"],
             sd[pre + "attention.self.value.bias"]])
        layers.append({
            "attn": {"wqkv": wqkv, "bqkv": bqkv,
                     "wo": lin(pre + "attention.output.dense.weight"),
                     "bo": sd[pre + "attention.output.dense.bias"]},
            "ln1": {"scale": sd[pre + "attention.output.LayerNorm.weight"],
                    "bias": sd[pre + "attention.output.LayerNorm.bias"]},
            "mlp": {"win": lin(pre + "intermediate.dense.weight"),
                    "bin": sd[pre + "intermediate.dense.bias"],
                    "wout": lin(pre + "output.dense.weight"),
                    "bout": sd[pre + "output.dense.bias"]},
            "ln2": {"scale": sd[pre + "output.LayerNorm.weight"],
                    "bias": sd[pre + "output.LayerNorm.bias"]},
        })
    wte = _pad_vocab(sd["bert.embeddings.word_embeddings.weight"], cfg)
    wpe = (sd["bert.embeddings.position_embeddings.weight"]
           + sd["bert.embeddings.token_type_embeddings.weight"][0][None, :])
    head: Params = {
        "wt": lin("cls.predictions.transform.dense.weight"),
        "bt": sd["cls.predictions.transform.dense.bias"],
        "ln": {"scale": sd["cls.predictions.transform.LayerNorm.weight"],
               "bias": sd["cls.predictions.transform.LayerNorm.bias"]},
        "bias": _pad_vocab(sd["cls.predictions.bias"], cfg),
    }
    if not cfg.tie_word_embeddings:
        head["whead"] = _pad_vocab(
            sd.get("cls.predictions.decoder.weight",
                   sd["bert.embeddings.word_embeddings.weight"]), cfg).T
    return {
        "embed": {"wte": wte, "wpe": wpe,
                  "ln": {"scale": sd["bert.embeddings.LayerNorm.weight"],
                         "bias": sd["bert.embeddings.LayerNorm.bias"]}},
        "layers": tuple(layers),
        "prenorm": {},
        "head": head,
    }


def _t5_hf_to_params(sd: Dict[str, Any], cfg: ModelArgs) -> Params:
    """HF T5ForConditionalGeneration -> our encoder-decoder layout.

    All projection/norm/MLP weights map 1:1 (q/k/v fused per stack; the
    decoder's EncDecAttention becomes the fused-KV cross block). HF T5's
    relative_attention_bias has no slot here by design — this runtime is
    position-scheme agnostic (models/encdec.py docstring) and runs the
    configured scheme (RoPE/learned), so imported T5 weights fine-tune
    rather than bit-match HF generation."""
    import numpy as np

    def lin(name):
        return sd[name].T

    inner = sd["encoder.block.0.layer.0.SelfAttention.q.weight"].shape[0]
    if inner != cfg.num_attention_heads * cfg.head_dim:
        raise ValueError(
            f"t5 checkpoint attention inner dim {inner} != heads*head_dim "
            f"{cfg.num_attention_heads * cfg.head_dim}: this runtime derives "
            "head_dim = hidden//heads (t5-small/base/large match; t5-3b/11b "
            "use d_kv=128 and need a config with matching geometry)")

    gated = "encoder.block.0.layer.1.DenseReluDense.wi_0.weight" in sd

    def mlp(pre):
        if gated:  # t5 v1.1 gated-act: wi_0 (gate) | wi_1 (up)
            win = np.concatenate([lin(pre + "DenseReluDense.wi_0.weight"),
                                  lin(pre + "DenseReluDense.wi_1.weight")],
                                 axis=1)
        else:
            win = lin(pre + "DenseReluDense.wi.weight")
        return {"win": win, "wout": lin(pre + "DenseReluDense.wo.weight")}

    n_enc = (cfg.num_encoder_layers if cfg.num_encoder_layers is not None
             else cfg.num_hidden_layers)
    enc_layers = []
    for i in range(n_enc):
        pre = f"encoder.block.{i}."
        wqkv = np.concatenate(
            [lin(pre + "layer.0.SelfAttention.q.weight"),
             lin(pre + "layer.0.SelfAttention.k.weight"),
             lin(pre + "layer.0.SelfAttention.v.weight")], axis=1)
        enc_layers.append({
            "ln1": {"scale": sd[pre + "layer.0.layer_norm.weight"]},
            "attn": {"wqkv": wqkv,
                     "wo": lin(pre + "layer.0.SelfAttention.o.weight")},
            "ln2": {"scale": sd[pre + "layer.1.layer_norm.weight"]},
            "mlp": mlp(pre + "layer.1."),
        })
    dec_layers = []
    for i in range(cfg.num_hidden_layers):
        pre = f"decoder.block.{i}."
        wqkv = np.concatenate(
            [lin(pre + "layer.0.SelfAttention.q.weight"),
             lin(pre + "layer.0.SelfAttention.k.weight"),
             lin(pre + "layer.0.SelfAttention.v.weight")], axis=1)
        wkv = np.concatenate(
            [lin(pre + "layer.1.EncDecAttention.k.weight"),
             lin(pre + "layer.1.EncDecAttention.v.weight")], axis=1)
        dec_layers.append({
            "ln1": {"scale": sd[pre + "layer.0.layer_norm.weight"]},
            "attn": {"wqkv": wqkv,
                     "wo": lin(pre + "layer.0.SelfAttention.o.weight")},
            "lnx": {"scale": sd[pre + "layer.1.layer_norm.weight"]},
            "cross": {"wq": lin(pre + "layer.1.EncDecAttention.q.weight"),
                      "wkv": wkv,
                      "wo": lin(pre + "layer.1.EncDecAttention.o.weight")},
            "ln2": {"scale": sd[pre + "layer.2.layer_norm.weight"]},
            "mlp": mlp(pre + "layer.2."),
        })
    out: Params = {
        "embed": {"wte": _pad_vocab(sd["shared.weight"], cfg)},
        "enc_layers": tuple(enc_layers),
        "enc_norm": {"scale": sd["encoder.final_layer_norm.weight"]},
        "layers": tuple(dec_layers),
        "prenorm": {"scale": sd["decoder.final_layer_norm.weight"]},
    }
    if cfg.tie_word_embeddings:
        out["head"] = {}
    else:
        out["head"] = {"whead": _pad_vocab(sd["lm_head.weight"], cfg).T}
    return out


def _bert_params_to_hf(params: Params, cfg: ModelArgs) -> Dict[str, "np.ndarray"]:
    """Inverse of :func:`_bert_hf_to_params`. Token-type embeddings were
    folded into wpe on import, so type 0 exports as zeros (wpe carries the
    sum) — re-importing reproduces the same forward exactly."""
    import numpy as np

    get = lambda t: np.asarray(jax.device_get(t))
    V, H = cfg.vocab_size, cfg.hidden_size
    hd, nq, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.kv_heads
    sd: Dict[str, np.ndarray] = {
        "bert.embeddings.word_embeddings.weight": get(params["embed"]["wte"])[:V],
        "bert.embeddings.position_embeddings.weight": get(params["embed"]["wpe"]),
        "bert.embeddings.token_type_embeddings.weight": np.zeros((2, H),
                                                                 np.float32),
        "bert.embeddings.LayerNorm.weight": get(params["embed"]["ln"]["scale"]),
        "bert.embeddings.LayerNorm.bias": get(params["embed"]["ln"]["bias"]),
    }
    for i, lp in enumerate(params["layers"]):
        pre = f"bert.encoder.layer.{i}."
        wqkv = get(lp["attn"]["wqkv"])
        q, k, v = np.split(wqkv, [nq * hd, (nq + nkv) * hd], axis=1)
        bq, bk, bv = np.split(get(lp["attn"]["bqkv"]),
                              [nq * hd, (nq + nkv) * hd])
        sd[pre + "attention.self.query.weight"] = q.T
        sd[pre + "attention.self.query.bias"] = bq
        sd[pre + "attention.self.key.weight"] = k.T
        sd[pre + "attention.self.key.bias"] = bk
        sd[pre + "attention.self.value.weight"] = v.T
        sd[pre + "attention.self.value.bias"] = bv
        sd[pre + "attention.output.dense.weight"] = get(lp["attn"]["wo"]).T
        sd[pre + "attention.output.dense.bias"] = get(lp["attn"]["bo"])
        sd[pre + "attention.output.LayerNorm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "attention.output.LayerNorm.bias"] = get(lp["ln1"]["bias"])
        sd[pre + "intermediate.dense.weight"] = get(lp["mlp"]["win"]).T
        sd[pre + "intermediate.dense.bias"] = get(lp["mlp"]["bin"])
        sd[pre + "output.dense.weight"] = get(lp["mlp"]["wout"]).T
        sd[pre + "output.dense.bias"] = get(lp["mlp"]["bout"])
        sd[pre + "output.LayerNorm.weight"] = get(lp["ln2"]["scale"])
        sd[pre + "output.LayerNorm.bias"] = get(lp["ln2"]["bias"])
    hp = params["head"]
    sd["cls.predictions.transform.dense.weight"] = get(hp["wt"]).T
    sd["cls.predictions.transform.dense.bias"] = get(hp["bt"])
    sd["cls.predictions.transform.LayerNorm.weight"] = get(hp["ln"]["scale"])
    sd["cls.predictions.transform.LayerNorm.bias"] = get(hp["ln"]["bias"])
    sd["cls.predictions.bias"] = get(hp["bias"])[:V]
    if not cfg.tie_word_embeddings and "whead" in hp:
        sd["cls.predictions.decoder.weight"] = get(hp["whead"]).T[:V]
    return sd


def _t5_params_to_hf(params: Params, cfg: ModelArgs) -> Dict[str, "np.ndarray"]:
    """Inverse of :func:`_t5_hf_to_params` (gated t5-v1.1 MLP layout when the
    model uses a gated activation)."""
    import numpy as np

    get = lambda t: np.asarray(jax.device_get(t))
    from hetu_galvatron_tpu.models.modules import _is_gated

    V = cfg.vocab_size
    hd, nq, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.kv_heads
    sd: Dict[str, np.ndarray] = {
        "shared.weight": get(params["embed"]["wte"])[:V],
        "encoder.final_layer_norm.weight": get(params["enc_norm"]["scale"]),
        "decoder.final_layer_norm.weight": get(params["prenorm"]["scale"]),
    }

    def put_mlp(pre, mp):
        win = get(mp["win"])
        if _is_gated(cfg.hidden_act):
            gate, up = np.split(win, 2, axis=1)
            sd[pre + "DenseReluDense.wi_0.weight"] = gate.T
            sd[pre + "DenseReluDense.wi_1.weight"] = up.T
        else:
            sd[pre + "DenseReluDense.wi.weight"] = win.T
        sd[pre + "DenseReluDense.wo.weight"] = get(mp["wout"]).T

    def put_self_attn(pre, ap):
        wqkv = get(ap["wqkv"])
        q, k, v = np.split(wqkv, [nq * hd, (nq + nkv) * hd], axis=1)
        sd[pre + "SelfAttention.q.weight"] = q.T
        sd[pre + "SelfAttention.k.weight"] = k.T
        sd[pre + "SelfAttention.v.weight"] = v.T
        sd[pre + "SelfAttention.o.weight"] = get(ap["wo"]).T

    for i, lp in enumerate(params["enc_layers"]):
        pre = f"encoder.block.{i}."
        put_self_attn(pre + "layer.0.", lp["attn"])
        sd[pre + "layer.0.layer_norm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "layer.1.layer_norm.weight"] = get(lp["ln2"]["scale"])
        put_mlp(pre + "layer.1.", lp["mlp"])
    for i, lp in enumerate(params["layers"]):
        pre = f"decoder.block.{i}."
        put_self_attn(pre + "layer.0.", lp["attn"])
        sd[pre + "layer.0.layer_norm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "layer.1.layer_norm.weight"] = get(lp["lnx"]["scale"])
        sd[pre + "layer.1.EncDecAttention.q.weight"] = get(lp["cross"]["wq"]).T
        wkv = get(lp["cross"]["wkv"])
        k, v = np.split(wkv, 2, axis=1)
        sd[pre + "layer.1.EncDecAttention.k.weight"] = k.T
        sd[pre + "layer.1.EncDecAttention.v.weight"] = v.T
        sd[pre + "layer.1.EncDecAttention.o.weight"] = get(lp["cross"]["wo"]).T
        sd[pre + "layer.2.layer_norm.weight"] = get(lp["ln2"]["scale"])
        put_mlp(pre + "layer.2.", lp["mlp"])
    if not cfg.tie_word_embeddings and params.get("head"):
        sd["lm_head.weight"] = get(params["head"]["whead"]).T[:V]
    return sd


def params_to_hf(params: Params, cfg: ModelArgs) -> Dict[str, np.ndarray]:
    """Our params -> HF-layout numpy state dict (reference g2h converters).
    Inverse of :func:`hf_to_params`; vocab padding rows are dropped."""
    get = lambda t: np.asarray(jax.device_get(t))
    sd: Dict[str, np.ndarray] = {}
    V = cfg.vocab_size
    if cfg.model_type == "bert":
        return _bert_params_to_hf(params, cfg)
    if cfg.model_type == "t5":
        return _t5_params_to_hf(params, cfg)
    if cfg.model_type == "gpt":
        sd["transformer.wte.weight"] = get(params["embed"]["wte"])[:V]
        sd["transformer.wpe.weight"] = get(params["embed"]["wpe"])
        for i, lp in enumerate(params["layers"]):
            pre = f"transformer.h.{i}."
            sd[pre + "ln_1.weight"] = get(lp["ln1"]["scale"])
            sd[pre + "ln_1.bias"] = get(lp["ln1"]["bias"])
            sd[pre + "attn.c_attn.weight"] = get(lp["attn"]["wqkv"])
            sd[pre + "attn.c_attn.bias"] = get(lp["attn"]["bqkv"])
            sd[pre + "attn.c_proj.weight"] = get(lp["attn"]["wo"])
            sd[pre + "attn.c_proj.bias"] = get(lp["attn"]["bo"])
            sd[pre + "ln_2.weight"] = get(lp["ln2"]["scale"])
            sd[pre + "ln_2.bias"] = get(lp["ln2"]["bias"])
            sd[pre + "mlp.c_fc.weight"] = get(lp["mlp"]["win"])
            sd[pre + "mlp.c_fc.bias"] = get(lp["mlp"]["bin"])
            sd[pre + "mlp.c_proj.weight"] = get(lp["mlp"]["wout"])
            sd[pre + "mlp.c_proj.bias"] = get(lp["mlp"]["bout"])
        sd["transformer.ln_f.weight"] = get(params["prenorm"]["scale"])
        sd["transformer.ln_f.bias"] = get(params["prenorm"]["bias"])
        return sd

    sd["model.embed_tokens.weight"] = get(params["embed"]["wte"])[:V]
    hd, nq, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.kv_heads
    for i, lp in enumerate(params["layers"]):
        pre = f"model.layers.{i}."
        wqkv = get(lp["attn"]["wqkv"])
        q, k, v = np.split(wqkv, [nq * hd, (nq + nkv) * hd], axis=1)
        sd[pre + "self_attn.q_proj.weight"] = q.T
        sd[pre + "self_attn.k_proj.weight"] = k.T
        sd[pre + "self_attn.v_proj.weight"] = v.T
        sd[pre + "self_attn.o_proj.weight"] = get(lp["attn"]["wo"]).T
        if "bqkv" in lp["attn"]:
            bqkv = get(lp["attn"]["bqkv"])
            bq, bk, bv = np.split(bqkv, [nq * hd, (nq + nkv) * hd])
            sd[pre + "self_attn.q_proj.bias"] = bq
            sd[pre + "self_attn.k_proj.bias"] = bk
            sd[pre + "self_attn.v_proj.bias"] = bv
        if "moe" in lp:
            if "shared" in lp["moe"]:
                raise NotImplementedError(
                    "the Mixtral HF layout has no shared-expert slot; "
                    "export models with num_shared_experts=0")
            sd[pre + "block_sparse_moe.gate.weight"] = \
                get(lp["moe"]["router"]).T
            win = get(lp["moe"]["win"])
            wout = get(lp["moe"]["wout"])
            for e in range(win.shape[0]):
                w1, w3 = np.split(win[e], 2, axis=1)
                sd[pre + f"block_sparse_moe.experts.{e}.w1.weight"] = w1.T
                sd[pre + f"block_sparse_moe.experts.{e}.w3.weight"] = w3.T
                sd[pre + f"block_sparse_moe.experts.{e}.w2.weight"] = \
                    wout[e].T
        else:
            win = get(lp["mlp"]["win"])
            gate, up = np.split(win, 2, axis=1)
            sd[pre + "mlp.gate_proj.weight"] = gate.T
            sd[pre + "mlp.up_proj.weight"] = up.T
            sd[pre + "mlp.down_proj.weight"] = get(lp["mlp"]["wout"]).T
        sd[pre + "input_layernorm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "post_attention_layernorm.weight"] = get(lp["ln2"]["scale"])
    sd["model.norm.weight"] = get(params["prenorm"]["scale"])
    if not cfg.tie_word_embeddings and params.get("head"):
        sd["lm_head.weight"] = get(params["head"]["whead"]).T[:V]
    return sd
