"""Distributed checkpoint save/load + HF interchange.

Capability parity with the reference checkpoint stack
(runtime/checkpoint/llama_adapter.py:30-172 save/load, tools/
checkpoint_convert_{h2g,g2h}.py, hybrid_parallel_config.py:132-144 config
assert-on-resume): sharded save/restore of params + optimizer state + step,
the parallel-plan JSON stored alongside and verified on resume, and
HuggingFace state-dict import/export for GPT-2- and Llama-family models.

TPU-native: orbax-checkpoint writes each array shard from the device that
owns it (the reference hand-rolls per-(layer, tp-rank) files with dp-rank-0
writers); restore takes a target sharding tree, so a checkpoint saved under
one parallel plan reloads under another — the resharding the reference does
with TP-slicing loaders (llama_adapter.py:51-163) falls out of GSPMD.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

import orbax.checkpoint as ocp

from hetu_galvatron_tpu.core.args_schema import ModelArgs

Params = Dict[str, Any]


def _plan_fingerprint(hpc) -> Dict[str, Any]:
    from hetu_galvatron_tpu.utils.strategy import strategy_list2config

    cfg = strategy_list2config(
        hpc.layers, global_bsz=hpc.global_bsz, chunks=hpc.chunks,
        pipeline_type=hpc.pipeline_type,
        default_dp_type=hpc.default_dp_type.short, vocab=hpc.vocab,
        pp_division=hpc.pp_division)
    cfg["world_size"] = hpc.world_size
    return cfg


def save_checkpoint(
    path: str,
    step: int,
    params: Params,
    opt_state: Any = None,
    hpc=None,
    *,
    async_save: bool = False,
) -> str:
    """Write step directory ``<path>/step_<n>`` with params/opt_state plus
    the hybrid-parallel plan JSON (reference hybrid_parallel_configs.json)."""
    global _PENDING
    ckpt_dir = os.path.abspath(os.path.join(path, f"step_{step}"))
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(ckpt_dir, "params"), params, force=True)
    if opt_state is not None:
        ckptr.save(os.path.join(ckpt_dir, "opt_state"), opt_state, force=True)
    meta = {"step": step}
    if hpc is not None:
        meta["hybrid_parallel_config"] = _plan_fingerprint(hpc)
    with open(os.path.join(ckpt_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)
    if async_save:
        # orbax commits in the background; training overlaps the write.
        # Call wait_for_checkpoints() before exiting/reading the ckpt.
        _PENDING.append(ckptr)
    else:
        ckptr.wait_until_finished()
    return ckpt_dir


_PENDING = []


def wait_for_checkpoints() -> None:
    """Block until every async save has committed (reference async_save
    drains at exit)."""
    while _PENDING:
        _PENDING.pop().wait_until_finished()


def latest_checkpoint(path: str) -> Optional[str]:
    if not os.path.isdir(path):
        return None
    steps = [d for d in os.listdir(path) if d.startswith("step_")]
    if not steps:
        return None
    latest = max(steps, key=lambda d: int(d.split("_")[1]))
    return os.path.join(path, latest)


def load_checkpoint(
    ckpt_dir: str,
    params_target: Params,
    opt_target: Any = None,
    hpc=None,
    *,
    strict_plan: bool = False,
):
    """Restore into the target sharding/shape tree. ``strict_plan`` asserts
    the stored plan matches (the reference asserts equality on resume,
    hybrid_parallel_config.py:132-144); by default a mismatch is allowed —
    orbax reshards into the new plan's shardings."""
    meta = json.load(open(os.path.join(ckpt_dir, "meta.json")))
    if strict_plan and hpc is not None:
        stored = meta.get("hybrid_parallel_config")
        current = _plan_fingerprint(hpc)
        if stored != current:
            raise ValueError(
                f"checkpoint plan mismatch:\nstored  {stored}\n"
                f"current {current}")
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(os.path.join(ckpt_dir, "params"), params_target)
    opt_state = None
    if opt_target is not None and os.path.isdir(
            os.path.join(ckpt_dir, "opt_state")):
        opt_state = ckptr.restore(os.path.join(ckpt_dir, "opt_state"),
                                  opt_target)
    return params, opt_state, meta["step"]


# ---------------------------------------------------------------------------
# HuggingFace interchange (h2g / g2h)
# ---------------------------------------------------------------------------


def hf_to_params(state_dict: Dict[str, Any], cfg: ModelArgs) -> Params:
    """HF torch state dict -> our params pytree (reference h2g converters,
    tools/checkpoint_convert_h2g.py + llama_adapter.py:51-163). Supports the
    gpt2 (Conv1D fused qkv) and llama (separate q/k/v Linear) layouts."""
    import numpy as np

    def arr(t):
        return np.asarray(t.detach().numpy() if hasattr(t, "detach") else t)

    sd = {k: arr(v) for k, v in state_dict.items()}
    n = cfg.num_hidden_layers
    if cfg.model_type == "gpt" or "transformer.wte.weight" in sd:
        layers = []
        for i in range(n):
            pre = f"transformer.h.{i}."
            lp = {
                "ln1": {"scale": sd[pre + "ln_1.weight"],
                        "bias": sd[pre + "ln_1.bias"]},
                "attn": {"wqkv": sd[pre + "attn.c_attn.weight"],
                         "bqkv": sd[pre + "attn.c_attn.bias"],
                         "wo": sd[pre + "attn.c_proj.weight"],
                         "bo": sd[pre + "attn.c_proj.bias"]},
                "ln2": {"scale": sd[pre + "ln_2.weight"],
                        "bias": sd[pre + "ln_2.bias"]},
                "mlp": {"win": sd[pre + "mlp.c_fc.weight"],
                        "bin": sd[pre + "mlp.c_fc.bias"],
                        "wout": sd[pre + "mlp.c_proj.weight"],
                        "bout": sd[pre + "mlp.c_proj.bias"]},
            }
            layers.append(lp)
        wte = sd["transformer.wte.weight"]
        pad = cfg.padded_vocab_size - wte.shape[0]
        if pad > 0:
            wte = np.concatenate([wte, np.zeros((pad, wte.shape[1]),
                                                wte.dtype)])
        return {
            "embed": {"wte": wte, "wpe": sd["transformer.wpe.weight"]},
            "layers": tuple(layers),
            "prenorm": {"scale": sd["transformer.ln_f.weight"],
                        "bias": sd["transformer.ln_f.bias"]},
            "head": {},
        }

    # llama-family: torch Linear stores [out, in] -> transpose
    def lin(name):
        return sd[name].T

    layers = []
    for i in range(n):
        pre = f"model.layers.{i}."
        wqkv = np.concatenate(
            [lin(pre + "self_attn.q_proj.weight"),
             lin(pre + "self_attn.k_proj.weight"),
             lin(pre + "self_attn.v_proj.weight")], axis=1)
        lp = {
            "ln1": {"scale": sd[pre + "input_layernorm.weight"]},
            "attn": {"wqkv": wqkv, "wo": lin(pre + "self_attn.o_proj.weight")},
            "ln2": {"scale": sd[pre + "post_attention_layernorm.weight"]},
        }
        if pre + "block_sparse_moe.gate.weight" in sd:
            # mixtral-style MoE FFN (reference moe_adapter.py:58-266):
            # experts.{e}.w1/w3 fuse into win [E, H, 2F], w2 -> wout [E, F, H]
            if cfg.num_shared_experts:
                raise NotImplementedError(
                    "the Mixtral HF layout has no shared-expert slot; "
                    "import with num_shared_experts=0")
            E = 0
            while pre + f"block_sparse_moe.experts.{E}.w1.weight" in sd:
                E += 1
            if E != cfg.num_experts:
                raise ValueError(
                    f"layer {i}: checkpoint has {E} experts but "
                    f"cfg.num_experts is {cfg.num_experts}")
            win = np.stack([
                np.concatenate(
                    [lin(pre + f"block_sparse_moe.experts.{e}.w1.weight"),
                     lin(pre + f"block_sparse_moe.experts.{e}.w3.weight")],
                    axis=1)
                for e in range(E)])
            wout = np.stack([
                lin(pre + f"block_sparse_moe.experts.{e}.w2.weight")
                for e in range(E)])
            lp["moe"] = {
                "router": lin(pre + "block_sparse_moe.gate.weight"),
                "win": win,
                "wout": wout,
            }
        else:
            win = np.concatenate(
                [lin(pre + "mlp.gate_proj.weight"),
                 lin(pre + "mlp.up_proj.weight")], axis=1)
            lp["mlp"] = {"win": win,
                         "wout": lin(pre + "mlp.down_proj.weight")}
        if cfg.add_qkv_bias:
            lp["attn"]["bqkv"] = np.concatenate(
                [sd[pre + "self_attn.q_proj.bias"],
                 sd[pre + "self_attn.k_proj.bias"],
                 sd[pre + "self_attn.v_proj.bias"]])
        layers.append(lp)
    wte = sd["model.embed_tokens.weight"]
    pad = cfg.padded_vocab_size - wte.shape[0]
    if pad > 0:
        wte = np.concatenate([wte, np.zeros((pad, wte.shape[1]), wte.dtype)])
    out: Params = {
        "embed": {"wte": wte},
        "layers": tuple(layers),
        "prenorm": {"scale": sd["model.norm.weight"]},
    }
    if cfg.tie_word_embeddings:
        out["head"] = {}
    else:
        whead = lin("lm_head.weight")
        if pad > 0:
            whead = np.concatenate(
                [whead, np.zeros((whead.shape[0], pad), whead.dtype)], axis=1)
        out["head"] = {"whead": whead}
    return out


def params_to_hf(params: Params, cfg: ModelArgs) -> Dict[str, np.ndarray]:
    """Our params -> HF-layout numpy state dict (reference g2h converters).
    Inverse of :func:`hf_to_params`; vocab padding rows are dropped."""
    get = lambda t: np.asarray(jax.device_get(t))
    sd: Dict[str, np.ndarray] = {}
    V = cfg.vocab_size
    if cfg.model_type == "gpt":
        sd["transformer.wte.weight"] = get(params["embed"]["wte"])[:V]
        sd["transformer.wpe.weight"] = get(params["embed"]["wpe"])
        for i, lp in enumerate(params["layers"]):
            pre = f"transformer.h.{i}."
            sd[pre + "ln_1.weight"] = get(lp["ln1"]["scale"])
            sd[pre + "ln_1.bias"] = get(lp["ln1"]["bias"])
            sd[pre + "attn.c_attn.weight"] = get(lp["attn"]["wqkv"])
            sd[pre + "attn.c_attn.bias"] = get(lp["attn"]["bqkv"])
            sd[pre + "attn.c_proj.weight"] = get(lp["attn"]["wo"])
            sd[pre + "attn.c_proj.bias"] = get(lp["attn"]["bo"])
            sd[pre + "ln_2.weight"] = get(lp["ln2"]["scale"])
            sd[pre + "ln_2.bias"] = get(lp["ln2"]["bias"])
            sd[pre + "mlp.c_fc.weight"] = get(lp["mlp"]["win"])
            sd[pre + "mlp.c_fc.bias"] = get(lp["mlp"]["bin"])
            sd[pre + "mlp.c_proj.weight"] = get(lp["mlp"]["wout"])
            sd[pre + "mlp.c_proj.bias"] = get(lp["mlp"]["bout"])
        sd["transformer.ln_f.weight"] = get(params["prenorm"]["scale"])
        sd["transformer.ln_f.bias"] = get(params["prenorm"]["bias"])
        return sd

    sd["model.embed_tokens.weight"] = get(params["embed"]["wte"])[:V]
    hd, nq, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.kv_heads
    for i, lp in enumerate(params["layers"]):
        pre = f"model.layers.{i}."
        wqkv = get(lp["attn"]["wqkv"])
        q, k, v = np.split(wqkv, [nq * hd, (nq + nkv) * hd], axis=1)
        sd[pre + "self_attn.q_proj.weight"] = q.T
        sd[pre + "self_attn.k_proj.weight"] = k.T
        sd[pre + "self_attn.v_proj.weight"] = v.T
        sd[pre + "self_attn.o_proj.weight"] = get(lp["attn"]["wo"]).T
        if "bqkv" in lp["attn"]:
            bqkv = get(lp["attn"]["bqkv"])
            bq, bk, bv = np.split(bqkv, [nq * hd, (nq + nkv) * hd])
            sd[pre + "self_attn.q_proj.bias"] = bq
            sd[pre + "self_attn.k_proj.bias"] = bk
            sd[pre + "self_attn.v_proj.bias"] = bv
        if "moe" in lp:
            if "shared" in lp["moe"]:
                raise NotImplementedError(
                    "the Mixtral HF layout has no shared-expert slot; "
                    "export models with num_shared_experts=0")
            sd[pre + "block_sparse_moe.gate.weight"] = \
                get(lp["moe"]["router"]).T
            win = get(lp["moe"]["win"])
            wout = get(lp["moe"]["wout"])
            for e in range(win.shape[0]):
                w1, w3 = np.split(win[e], 2, axis=1)
                sd[pre + f"block_sparse_moe.experts.{e}.w1.weight"] = w1.T
                sd[pre + f"block_sparse_moe.experts.{e}.w3.weight"] = w3.T
                sd[pre + f"block_sparse_moe.experts.{e}.w2.weight"] = \
                    wout[e].T
        else:
            win = get(lp["mlp"]["win"])
            gate, up = np.split(win, 2, axis=1)
            sd[pre + "mlp.gate_proj.weight"] = gate.T
            sd[pre + "mlp.up_proj.weight"] = up.T
            sd[pre + "mlp.down_proj.weight"] = get(lp["mlp"]["wout"]).T
        sd[pre + "input_layernorm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "post_attention_layernorm.weight"] = get(lp["ln2"]["scale"])
    sd["model.norm.weight"] = get(params["prenorm"]["scale"])
    if not cfg.tie_word_embeddings and params.get("head"):
        sd["lm_head.weight"] = get(params["head"]["whead"]).T[:V]
    return sd
