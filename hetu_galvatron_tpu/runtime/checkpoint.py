"""Distributed checkpoint save/load + HF interchange.

Capability parity with the reference checkpoint stack
(runtime/checkpoint/llama_adapter.py:30-172 save/load, tools/
checkpoint_convert_{h2g,g2h}.py, hybrid_parallel_config.py:132-144 config
assert-on-resume): sharded save/restore of params + optimizer state + step,
the parallel-plan JSON stored alongside and verified on resume, and
HuggingFace state-dict import/export for GPT-2- and Llama-family models.

TPU-native: orbax-checkpoint writes each array shard from the device that
owns it (the reference hand-rolls per-(layer, tp-rank) files with dp-rank-0
writers); restore takes a target sharding tree, so a checkpoint saved under
one parallel plan reloads under another — the resharding the reference does
with TP-slicing loaders (llama_adapter.py:51-163) falls out of GSPMD.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

import orbax.checkpoint as ocp

from hetu_galvatron_tpu.core.args_schema import ModelArgs
from hetu_galvatron_tpu.runtime import ckpt_paths
from hetu_galvatron_tpu.runtime.ckpt_paths import (
    clear_resume_pin,
    read_resume_pin,
    write_resume_pin,
)
from hetu_galvatron_tpu.utils.retrying import retry_call

Params = Dict[str, Any]


class WorldSizeMismatchError(ValueError):
    """The checkpoint's recorded world_size differs from the live world.

    Before this error existed a topology-changed resume surfaced as a
    shape error deep inside orbax/device_put; now it surfaces at load with
    both worlds named. The elastic resume path (``cli/train_dist.py``)
    catches exactly this condition to trigger re-search + reshard
    (``runtime/reshard.py``)."""

    def __init__(self, ckpt_dir: str, stored_world: int, live_world: int,
                 stored_plan: Optional[Dict[str, Any]] = None):
        self.ckpt_dir = ckpt_dir
        self.stored_world = int(stored_world)
        self.live_world = int(live_world)
        self.stored_plan = stored_plan
        super().__init__(
            f"checkpoint {ckpt_dir} was committed by a "
            f"{stored_world}-device world but the live world has "
            f"{live_world} devices: its arrays are laid out for the old "
            "plan and will not restore here. Re-search a plan for the "
            "live topology and reshard (runtime/reshard.py) — "
            "cli/train_dist.py does this automatically on resume when "
            "ckpt.load is set.")

# Atomic-commit protocol: a step directory is materialized under
# ``step_<n>.tmp``, fully written (params/opt_state shards + meta.json),
# stamped with the marker file below, and only then renamed to
# ``step_<n>``. Readers treat a step dir without the marker as partial
# garbage from a mid-save crash: never selected, eligible for GC. The
# marker (not just the rename) is kept because object stores mounted via
# FUSE can surface a directory rename non-atomically.
# The protocol's pure-path half (these constants, step parsing, commit
# detection, the cross-process RESUME_PIN lease) is defined ONCE in
# runtime/ckpt_paths.py so the jax-free process supervisor speaks the
# same protocol; the aliases below keep this module's historical names.
COMMIT_MARKER = ckpt_paths.COMMIT_MARKER
_TMP_SUFFIX = ckpt_paths.TMP_SUFFIX
_OLD_SUFFIX = ckpt_paths.OLD_SUFFIX

# transient-read retry policy for checkpoint I/O (flaky object-store
# mounts); override attempts via HGTPU_CKPT_RETRIES
def _io_retries() -> int:
    return max(int(os.environ.get("HGTPU_CKPT_RETRIES", "3")), 1)


# total-elapsed watchdog for one retried checkpoint I/O call (meta read
# or shard restore): a mount that hangs rather than erroring must not
# stall resume for attempts x hang; override via HGTPU_CKPT_DEADLINE_S
def _io_deadline() -> float:
    return max(float(os.environ.get("HGTPU_CKPT_DEADLINE_S", "120")), 0.1)


def _count(name: str, **labels) -> None:
    from hetu_galvatron_tpu.observability.registry import get_registry

    get_registry().counter(f"checkpoint/{name}", **labels).inc()


# ``step_<int>`` -> int (else None) / committed-dir detection: shared
# with the jax-free supervisor via ckpt_paths
_step_of = ckpt_paths.step_of
is_committed = ckpt_paths.is_committed


def _plan_fingerprint(hpc) -> Dict[str, Any]:
    from hetu_galvatron_tpu.utils.strategy import strategy_list2config

    cfg = strategy_list2config(
        hpc.layers, global_bsz=hpc.global_bsz, chunks=hpc.chunks,
        pipeline_type=hpc.pipeline_type,
        default_dp_type=hpc.default_dp_type.short, vocab=hpc.vocab,
        pp_division=hpc.pp_division,
        num_encoder_layers=hpc.num_encoder_layers or None)
    cfg["world_size"] = hpc.world_size
    return cfg


class PlanMismatchError(ValueError):
    """``strict_plan`` resume found a different plan fingerprint in the
    checkpoint. Typed (vs a bare ValueError) so the resilient resume
    loop can tell an OPERATOR error that reproduces on every candidate
    apart from per-checkpoint corruption it should fall back past."""


@dataclass
class _PendingSave:
    """An async save still being written by orbax: the commit (marker +
    rename + retention GC) runs only after ``wait_until_finished``."""

    ckptrs: List[Any]
    tmp_dir: str
    final_dir: str
    root: str
    keep_last: int = 0
    # chaos/test seam: hooks["before_commit"](tmp_dir) runs after the
    # payload is fully staged, before the marker/rename — the window a
    # kill-mid-save drill tears
    hooks: Dict[str, Callable[..., Any]] = field(default_factory=dict)


_PENDING: List[_PendingSave] = []


def _commit(tmp_dir: str, final_dir: str) -> None:
    """Publish a fully-written staging dir: marker first (fsynced), then
    the atomic rename onto the final step name."""
    marker = os.path.join(tmp_dir, COMMIT_MARKER)
    with open(marker, "w") as f:
        f.write("committed\n")
        f.flush()
        os.fsync(f.fileno())
    old = None
    if os.path.isdir(final_dir):
        # overwriting an existing step (re-save after a rollback): keep
        # the previous payload selectable until the new one lands — rename
        # aside, replace, then delete, so a crash at any point in between
        # still leaves a committed dir (the .old name is never selected
        # and is GC'd as stale)
        old = final_dir + _OLD_SUFFIX
        if os.path.isdir(old):
            shutil.rmtree(old)
        os.replace(final_dir, old)
    os.replace(tmp_dir, final_dir)
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)
    _count("committed")


def save_checkpoint(
    path: str,
    step: int,
    params: Params,
    opt_state: Any = None,
    hpc=None,
    *,
    async_save: bool = False,
    train_state: Optional[Dict[str, Any]] = None,
    keep_last: int = 0,
    hooks: Optional[Dict[str, Callable[..., Any]]] = None,
) -> str:
    """Write step directory ``<path>/step_<n>`` with params/opt_state plus
    the hybrid-parallel plan JSON (reference hybrid_parallel_configs.json).

    The write is atomic: everything lands in ``step_<n>.tmp`` and is
    renamed into place only once complete, so a crash mid-save can never
    produce a directory :func:`latest_checkpoint` would select.
    ``train_state`` is an arbitrary JSON-serializable dict stored in
    meta.json (data-iterator position, RNG seed, rerun records, telemetry
    step — the full-state-resume payload). ``keep_last > 0`` prunes all
    but the newest N committed steps after this one commits."""
    ckpt_dir = os.path.abspath(os.path.join(path, f"step_{step}"))
    tmp_dir = ckpt_dir + _TMP_SUFFIX
    # multi-controller pods share the filesystem: only the commit runner
    # (process 0) cleans stale staging dirs and writes meta — a lagging
    # peer must never rmtree a dir its neighbors already stream into
    primary = jax.process_index() == 0
    if primary:
        if os.path.isdir(tmp_dir):
            # stale staging dir from a crashed earlier attempt at this step
            shutil.rmtree(tmp_dir)
        os.makedirs(tmp_dir, exist_ok=True)
    if jax.process_count() > 1:
        # barrier: no peer may start streaming shards into tmp_dir until
        # the primary's stale-dir cleanup above has finished
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"hgtpu_ckpt_stage_{step}")
    ckptrs = [ocp.StandardCheckpointer()]
    ckptrs[0].save(os.path.join(tmp_dir, "params"), params, force=True)
    if opt_state is not None:
        # separate checkpointer: StandardCheckpointer serializes saves, a
        # second handle lets both trees stream concurrently
        ckptrs.append(ocp.StandardCheckpointer())
        ckptrs[-1].save(os.path.join(tmp_dir, "opt_state"), opt_state,
                        force=True)
    meta: Dict[str, Any] = {"step": step}
    if hpc is not None:
        meta["hybrid_parallel_config"] = _plan_fingerprint(hpc)
    if train_state is not None:
        meta["train_state"] = train_state
    if primary:
        with open(os.path.join(tmp_dir, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2)
    _count("saved")
    pending = _PendingSave(ckptrs, tmp_dir, ckpt_dir,
                           os.path.abspath(path), keep_last,
                           dict(hooks or {}))
    if async_save:
        # orbax streams shards in the background; training overlaps the
        # write and wait_for_checkpoints() commits it at the next barrier
        # (before any read of the ckpt, and at exit)
        _PENDING.append(pending)
    else:
        _finish(pending)
    return ckpt_dir


def _finish(p: _PendingSave) -> None:
    # await EVERY checkpointer even when an earlier one fails: an
    # abandoned background write would keep streaming into a staging dir
    # a restarted attempt is about to clean
    first_err: Optional[BaseException] = None
    for c in p.ckptrs:
        try:
            c.wait_until_finished()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err
    # multi-controller pods: every process streams its shards through
    # orbax, but exactly one performs the marker/rename commit and the
    # retention GC (shared filesystem)
    if jax.process_index() == 0:
        before_commit = p.hooks.get("before_commit")
        if before_commit is not None:
            # fully staged, not yet committed: the exact window a
            # kill-mid-save chaos drill tears (and a hung-save drill
            # stalls) — real faults die here too, so resume must treat
            # the unmarked staging dir as garbage
            before_commit(p.tmp_dir)
        _commit(p.tmp_dir, p.final_dir)
        if p.keep_last > 0:
            gc_checkpoints(p.root, keep_last=p.keep_last)


def wait_for_checkpoints() -> None:
    """Block until every async save has committed (reference async_save
    drains at exit). The queue drains completely even when one save
    fails: every checkpointer is awaited (a per-entry except keeps the
    loop going, so no abandoned background write keeps the process alive
    or races a later save) and the first error re-raises after the
    drain. Each entry is popped before finishing so its own final dir is
    not counted as in-flight by its retention GC."""
    first_err: Optional[BaseException] = None
    while _PENDING:
        p = _PENDING.pop(0)
        try:
            _finish(p)
        except BaseException as e:  # noqa: BLE001 — re-raised after drain
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err


def _in_flight_dirs() -> set:
    return {p.tmp_dir for p in _PENDING} | {p.final_dir for p in _PENDING}


# The step dir a live resume just selected, per checkpoint root: retention
# pruning racing a concurrent resume (an async save committing keep_last
# GC between latest_checkpoint() and the meta/shard reads) must never
# delete it out from under the restore. latest_checkpoint() records its
# selection here; the NEXT selection on the same root releases the
# previous one, so a long run retains at most one extra step dir.
# SCOPE: process-local — it closes the in-process race (the async-save
# commit GC and maybe_resume share this process). The CROSS-process half
# is the RESUME_PIN lease (runtime/ckpt_paths.py): the relaunching
# supervisor stamps the step dir the next child attempt will restore
# from, and gc_checkpoints below holds a live (unexpired) pin out of the
# retention prune set even though the pinning process is not this one.
_RESUME_PROTECTED: Dict[str, str] = {}


def _recover_orphaned_old(path: str) -> None:
    """Roll back a crash mid-overwrite: if ``step_<n>.old`` (the previous
    committed payload renamed aside by :func:`_commit`) exists without a
    ``step_<n>``, the crash hit between the two renames — restore the old
    payload so the step stays selectable."""
    for entry in os.listdir(path):
        if not entry.endswith(_OLD_SUFFIX):
            continue
        base = entry[:-len(_OLD_SUFFIX)]
        if _step_of(base) is None:
            continue
        full = os.path.join(path, entry)
        final = os.path.join(path, base)
        if not os.path.exists(final) and is_committed(full):
            try:
                os.replace(full, final)
                _count("old_recovered")
            except OSError:
                pass  # a concurrent reader raced the same rollback


def gc_checkpoints(path: str, *, keep_last: int = 0) -> List[str]:
    """Remove partial step dirs (crashed saves) and, with ``keep_last > 0``,
    all but the newest N committed steps. In-flight async saves are never
    touched. Returns the removed paths."""
    if not os.path.isdir(path):
        return []
    _recover_orphaned_old(path)
    busy = _in_flight_dirs()
    protected = {_RESUME_PROTECTED.get(os.path.abspath(path))}
    # cross-process lease: a supervisor that just relaunched a child has
    # pinned the step dir that child is about to restore from — this
    # process's retention GC must not prune it mid-restore
    pinned = read_resume_pin(path)
    if pinned is not None:
        protected.add(os.path.abspath(pinned))
    protected.discard(None)
    removed: List[str] = []
    committed: List[tuple] = []
    for entry in sorted(os.listdir(path)):
        full = os.path.join(path, entry)
        if not os.path.isdir(full) or full in busy:
            continue
        step = _step_of(entry)
        if step is not None and is_committed(full):
            committed.append((step, full))
            continue
        # our own staging/partial/old dirs only — a stray step_x or
        # step_5.partial we did not create is skipped, never deleted.
        # A surviving .old here is superseded (its final dir exists, or
        # _recover_orphaned_old would have rolled it back).
        stale_ours = step is not None or any(
            entry.endswith(suf) and _step_of(entry[:-len(suf)]) is not None
            for suf in (_TMP_SUFFIX, _OLD_SUFFIX))
        if stale_ours:
            shutil.rmtree(full, ignore_errors=True)
            removed.append(full)
            _count("gc_removed", kind="partial")
    if keep_last > 0 and len(committed) > keep_last:
        committed.sort()
        for _, full in committed[:-keep_last]:
            if os.path.abspath(full) in protected:
                # a live resume (in-process selection or cross-process
                # RESUME_PIN) holds this step out of the prune set
                _count("gc_protected")
                continue
            shutil.rmtree(full, ignore_errors=True)
            removed.append(full)
            _count("gc_removed", kind="retention")
    return removed


def latest_checkpoint(path: str) -> Optional[str]:
    """Newest COMMITTED step dir, or None. Stray ``step_*`` entries with a
    non-integer suffix (orbax temp dirs, ``step_5.partial``) are skipped
    instead of crashing resume, and uncommitted partial dirs from a
    mid-save crash are never selected."""
    if not os.path.isdir(path):
        return None
    _recover_orphaned_old(path)
    best_step, best = -1, None
    for entry in os.listdir(path):
        step = _step_of(entry)
        if step is None:
            continue
        full = os.path.join(path, entry)
        if not os.path.isdir(full) or not is_committed(full):
            _count("partial_skipped")
            continue
        if step > best_step:
            best_step, best = step, full
    root = os.path.abspath(path)
    if best is not None:
        # shield the selection from retention pruning until the next
        # selection on this root (see _RESUME_PROTECTED)
        _RESUME_PROTECTED[root] = os.path.abspath(best)
    else:
        _RESUME_PROTECTED.pop(root, None)
    return best


def read_checkpoint_meta(ckpt_dir: str) -> Dict[str, Any]:
    """The step dir's meta.json (step, plan fingerprint, train_state) —
    {} when absent. Reads retry transient I/O errors (flaky object-store
    mounts) through the shared backoff policy."""
    mp = os.path.join(ckpt_dir, "meta.json")
    if not os.path.exists(mp):
        return {}

    def _read():
        with open(mp) as f:
            return json.load(f)

    return retry_call(_read, attempts=_io_retries(), base=0.2, cap=5.0,
                      retryable=lambda e: isinstance(e, OSError),
                      op="checkpoint.read_meta",
                      deadline_s=_io_deadline())


def try_read_checkpoint_meta(
        ckpt_dir: str) -> Tuple[Dict[str, Any], Optional[Exception]]:
    """:func:`read_checkpoint_meta` that never raises: ``(meta, None)``
    on success, ``({}, error)`` on a corrupt/truncated/unreadable
    meta.json. Resume paths must degrade to the previous committed step
    (or a fresh start) with a warning, not a traceback."""
    try:
        return read_checkpoint_meta(ckpt_dir), None
    except Exception as e:  # noqa: BLE001 — defensive read by contract
        return {}, e


def committed_checkpoints(path: str) -> List[str]:
    """Every committed step dir under ``path``, NEWEST first — the
    candidate order for a resilient resume (try the newest, fall back
    on corruption)."""
    return [d for _, d in reversed(ckpt_paths.committed_steps(path))]


def load_latest_resilient(
    path: str,
    params_target: Params,
    opt_target: Any = None,
    hpc=None,
    *,
    strict_plan: bool = False,
    expected_world: Optional[int] = None,
    log: Callable[[str], None] = lambda m: print(m, flush=True),
) -> Optional[Tuple[Params, Any, int, str]]:
    """Restore from the newest READABLE committed checkpoint under
    ``path``: corruption (truncated/garbled meta.json, a missing payload
    leaf, a stray COMMITTED marker over a torn payload) falls back to
    the previous committed step with a warning
    (``checkpoint/corrupt_fallback``), never a traceback.

    Returns ``(params, opt_state, step, ckpt_dir)`` or None when no
    committed checkpoint exists. Two error classes still PROPAGATE by
    contract: :class:`WorldSizeMismatchError` (the elastic resume
    trigger — a topology change is not corruption) and
    :class:`PlanMismatchError` (a strict-plan operator error reproduces
    on every candidate; silently "falling back" to an older step would
    train the wrong plan). If candidates exist but every one is
    unreadable, raises RuntimeError naming them — silently restarting a
    long run from scratch is worse than a loud stop."""
    candidates = committed_checkpoints(path)
    if not candidates:
        return None
    last_err: Optional[Exception] = None
    for ckdir in candidates:
        try:
            params, opt_state, step = load_checkpoint(
                ckdir, params_target, opt_target, hpc=hpc,
                strict_plan=strict_plan, expected_world=expected_world)
        except (WorldSizeMismatchError, PlanMismatchError):
            raise
        except Exception as e:  # noqa: BLE001 — corruption class varies
            # (json decode errors, orbax restore errors, missing files,
            # OSErrors that exhausted the retry budget)
            last_err = e
            _count("corrupt_fallback")
            log(f"warning: checkpoint {ckdir} is unreadable "
                f"({type(e).__name__}: {e}); falling back to the "
                "previous committed step")
            continue
        # success: shield the selection from retention pruning (same
        # registration latest_checkpoint performs)
        _RESUME_PROTECTED[os.path.abspath(path)] = os.path.abspath(ckdir)
        return params, opt_state, step, ckdir
    raise RuntimeError(
        f"all {len(candidates)} committed checkpoint(s) under {path} are "
        f"unreadable (last error: {type(last_err).__name__}: {last_err}); "
        "refusing to silently restart from scratch")


def load_checkpoint(
    ckpt_dir: str,
    params_target: Params,
    opt_target: Any = None,
    hpc=None,
    *,
    strict_plan: bool = False,
    expected_world: Optional[int] = None,
):
    """Restore into the target sharding/shape tree. ``strict_plan`` asserts
    the stored plan matches (the reference asserts equality on resume,
    hybrid_parallel_config.py:132-144); by default a mismatch is allowed —
    orbax reshards into the new plan's shardings. ``expected_world``
    validates the checkpoint's recorded world_size against the live world
    and raises the typed :class:`WorldSizeMismatchError` naming both
    (instead of a shape error deep in device_put) — the condition the
    elastic resume path catches to trigger re-search + reshard. Restores
    retry transient I/O errors with jittered backoff (preemptible fleets
    resume through flaky object-store reads)."""
    ckpt_dir = os.path.abspath(ckpt_dir)  # orbax rejects relative paths
    meta = read_checkpoint_meta(ckpt_dir)
    if "step" not in meta:
        raise FileNotFoundError(
            f"{ckpt_dir} has no meta.json — not a committed checkpoint")
    if expected_world is not None:
        stored_plan = meta.get("hybrid_parallel_config") or {}
        sw = stored_plan.get("world_size")
        if sw is not None and int(sw) != int(expected_world):
            raise WorldSizeMismatchError(ckpt_dir, int(sw),
                                         int(expected_world), stored_plan)
    if strict_plan and hpc is not None:
        stored = meta.get("hybrid_parallel_config")
        current = _plan_fingerprint(hpc)
        if stored != current:
            raise PlanMismatchError(
                f"checkpoint plan mismatch:\nstored  {stored}\n"
                f"current {current}")
    ckptr = ocp.StandardCheckpointer()

    def _restore(sub, target):
        return retry_call(
            lambda: ckptr.restore(os.path.join(ckpt_dir, sub), target),
            attempts=_io_retries(), base=0.2, cap=5.0,
            retryable=lambda e: isinstance(e, OSError),
            op="checkpoint.restore",
            deadline_s=_io_deadline())

    params = _restore("params", params_target)
    opt_state = None
    if opt_target is not None and os.path.isdir(
            os.path.join(ckpt_dir, "opt_state")):
        opt_state = _restore("opt_state", opt_target)
    return params, opt_state, meta["step"]


# ---------------------------------------------------------------------------
# Async snapshot checkpointing
# ---------------------------------------------------------------------------


def _gauge(name: str, value: float) -> None:
    try:
        from hetu_galvatron_tpu.observability.registry import get_registry

        get_registry().gauge(f"checkpoint/{name}").set(float(value))
    except Exception:  # noqa: BLE001 — observability is best-effort
        pass


@dataclass
class _Snapshot:
    """A donation-safe on-device copy of the model state, queued for the
    background writer."""

    step: int
    params: Any
    opt_state: Any
    train_state: Optional[Dict[str, Any]] = None


class AsyncCheckpointer:
    """Split saves: on-step jitted device snapshot + background commit.

    ``snapshot(step, params, opt_state)`` dispatches ONE jitted
    copy-program over the state's device arrays (donation-safe: XLA's
    data dependencies order the copies before the next step may reuse
    donated buffers) and returns immediately — the measured dispatch
    stall is the only step time a save costs, exported as the
    ``checkpoint/snapshot_stall_ms`` gauge. A single daemon writer
    thread host-gathers the copies (``jax.device_get`` blocks until the
    device copies land) and writes/commits through
    :func:`save_checkpoint`'s atomic COMMITTED-marker protocol.

    Single-writer overlap rule: the queue holds at most ONE pending
    snapshot — a new snapshot supersedes an unstarted write
    (``checkpoint/snapshot_superseded``; the newer state strictly
    dominates), but never interrupts a STARTED write (a half-written
    staging dir would just be torn garbage for GC).

    A hung write (exceeding ``save_timeout_s``) is declared by the
    watchdog (``checkpoint/hung_saves``) and :meth:`drain` stops waiting
    on it — the daemon thread cannot block process exit. Writer errors
    are latched and re-raised at the next ``snapshot()``/``drain()``.

    Single-controller only: the writer thread cannot participate in
    multi-process save barriers (``CheckpointCadence`` falls back to the
    orbax async path on pods, with a logged reason).
    """

    def __init__(self, root: str, *, hpc=None, keep_last: int = 0,
                 save_timeout_s: float = 120.0,
                 hooks: Optional[Dict[str, Callable[..., Any]]] = None,
                 log: Callable[[str], None] = lambda m: print(m,
                                                              flush=True)):
        self.root = root
        self.hpc = hpc
        self.keep_last = keep_last
        self.save_timeout_s = float(save_timeout_s)
        self.hooks = dict(hooks or {})
        self._log = log
        self._cv = threading.Condition()
        self._queue: Optional[_Snapshot] = None
        self._inflight: Optional[_Snapshot] = None
        self._started_at: Optional[float] = None
        self._hung_step: Optional[int] = None
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._copy_fn = None
        self.error: Optional[BaseException] = None
        self.last_commit: Optional[Dict[str, Any]] = None

    # -- on-step half -------------------------------------------------------

    def _device_copy(self, tree):
        import jax.numpy as jnp

        leaves, treedef = jax.tree.flatten(tree)
        idx = [i for i, x in enumerate(leaves) if isinstance(x, jax.Array)]
        if idx:
            if self._copy_fn is None:
                self._copy_fn = jax.jit(
                    lambda xs: tuple(jnp.copy(x) for x in xs))
            copies = self._copy_fn(tuple(leaves[i] for i in idx))
            for i, c in zip(idx, copies):
                leaves[i] = c
        return jax.tree.unflatten(treedef, leaves)

    def snapshot(self, step: int, params: Params, opt_state: Any = None,
                 *, train_state: Optional[Dict[str, Any]] = None) -> float:
        """Queue a device snapshot of the state at ``step``; returns the
        dispatch stall in ms (the step's entire save cost)."""
        self.check_watchdog()
        if self.error is not None:
            err, self.error = self.error, None
            raise err
        t0 = time.perf_counter()
        params_c, opt_c = self._device_copy((params, opt_state))
        stall_ms = (time.perf_counter() - t0) * 1e3
        _gauge("snapshot_stall_ms", stall_ms)
        _count("snapshots")
        snap = _Snapshot(step, params_c, opt_c, train_state)
        with self._cv:
            if self._queue is not None:
                _count("snapshot_superseded")
                self._log(
                    f"checkpoint: snapshot at step {step} supersedes the "
                    f"unstarted write at step {self._queue.step}")
            self._queue = snap
            self._cv.notify_all()
        self._ensure_thread()
        return stall_ms

    # -- background half ----------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker, daemon=True, name="ckpt-writer")
            self._thread.start()

    def _worker(self) -> None:
        while True:
            with self._cv:
                while self._queue is None and not self._closed:
                    self._cv.wait(timeout=0.2)
                if self._queue is None:
                    return  # closed and drained
                snap, self._queue = self._queue, None
                self._inflight = snap
                self._started_at = time.monotonic()
            try:
                before_write = self.hooks.get("before_write")
                if before_write is not None:
                    before_write(snap.step)
                # device_get blocks until the on-device copies land, then
                # the write streams from host memory — the training loop
                # is untouched either way
                host_params, host_opt = jax.device_get(
                    (snap.params, snap.opt_state))
                save_checkpoint(
                    self.root, snap.step, host_params, host_opt,
                    hpc=self.hpc, async_save=False,
                    train_state=snap.train_state,
                    keep_last=self.keep_last, hooks=self.hooks)
                self.last_commit = {"step": snap.step,
                                    "t_wall": time.time()}
                _count("async_committed")
            except BaseException as e:  # noqa: BLE001 — latched for caller
                self.error = e
                _count("async_save_errors")
                try:
                    self._log("warning: async checkpoint write at step "
                              f"{snap.step} failed: {e}")
                except Exception:  # noqa: BLE001 — log must not kill worker
                    pass
            finally:
                with self._cv:
                    self._inflight = None
                    self._started_at = None
                    self._cv.notify_all()

    # -- watchdog / drain ---------------------------------------------------

    def check_watchdog(self) -> bool:
        """True when the in-flight write has exceeded ``save_timeout_s``
        (counted once per hung save as ``checkpoint/hung_saves``)."""
        with self._cv:
            started, inflight = self._started_at, self._inflight
        if (started is None or inflight is None
                or time.monotonic() - started <= self.save_timeout_s):
            return False
        if self._hung_step != inflight.step:
            self._hung_step = inflight.step
            _count("hung_saves")
            self._log(f"warning: checkpoint write at step {inflight.step} "
                      f"exceeded the {self.save_timeout_s:.1f}s watchdog "
                      "deadline; it will not be waited on")
        return True

    def pending(self) -> bool:
        with self._cv:
            return self._queue is not None or self._inflight is not None

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Block until queued + in-flight writes finish. Returns False
        (after declaring the save hung) instead of blocking forever when
        the writer exceeds the deadline; re-raises a latched writer
        error once drained."""
        if timeout_s is None:
            timeout_s = self.save_timeout_s
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._queue is not None or self._inflight is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=min(remaining, 0.2))
            drained = self._queue is None and self._inflight is None
        if not drained:
            self.check_watchdog()
            if self._hung_step is None:
                # not yet past the per-save watchdog, but the caller's
                # drain budget is spent — same give-up contract
                _count("hung_saves")
                self._hung_step = (self._inflight.step
                                   if self._inflight else -1)
        if self.error is not None:
            err, self.error = self.error, None
            raise err
        return drained

    def close(self, timeout_s: Optional[float] = None) -> bool:
        drained = self.drain(timeout_s)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None and drained:
            self._thread.join(timeout=5.0)
        return drained


class CheckpointCadence:
    """One save policy for both cadences and both write modes.

    ``due(it)`` is true when the step cadence (``ckpt.save_interval``)
    OR the wall-clock cadence (``ckpt.interval_s``) has elapsed — the
    time cadence bounds elastic RPO in seconds even when steps slow
    down. ``save(step, ...)`` dispatches through the
    :class:`AsyncCheckpointer` snapshot path when ``ckpt.snapshot_async``
    is set (single-controller), else through the classic synchronous /
    orbax-async :func:`save_checkpoint`. Goodput booking matches the
    mode: async saves bill only the snapshot stall (+ the final drain)
    to ``checkpoint_save``, moving write time out of
    ``productive_step``."""

    def __init__(self, ck, *, hpc=None, goodput=None,
                 log: Callable[[str], None] = lambda m: print(m,
                                                              flush=True),
                 hooks: Optional[Dict[str, Callable[..., Any]]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.ck = ck
        self.hpc = hpc
        self.goodput = goodput
        self.hooks = dict(hooks or {})
        self._log = log
        self._clock = clock
        self._last_save_t = clock()
        self.async_ckptr: Optional[AsyncCheckpointer] = None
        if ck.save and ck.snapshot_async:
            if jax.process_count() > 1:
                log("ckpt.snapshot_async: multi-process pod — the writer "
                    "thread cannot join save barriers; falling back to "
                    "the synchronous/orbax-async path")
            else:
                self.async_ckptr = AsyncCheckpointer(
                    ck.save, hpc=hpc, keep_last=ck.keep_last,
                    save_timeout_s=ck.save_timeout_s, hooks=self.hooks,
                    log=log)

    def due(self, it: int) -> bool:
        ck = self.ck
        if not ck.save:
            return False
        if ck.save_interval and (it + 1) % ck.save_interval == 0:
            return True
        if ck.interval_s and \
                self._clock() - self._last_save_t >= ck.interval_s:
            return True
        return False

    def save(self, step: int, params: Params, opt_state: Any = None,
             *, train_state: Optional[Dict[str, Any]] = None) -> None:
        self._last_save_t = self._clock()
        if self.async_ckptr is not None:
            stall_ms = self.async_ckptr.snapshot(
                step, params, opt_state, train_state=train_state)
            if self.goodput is not None:
                # only the dispatch stall steals step time; the write
                # overlaps training and its drain bills at exit
                self.goodput.add("checkpoint_save", stall_ms / 1e3)
            return

        def _save():
            save_checkpoint(self.ck.save, step, params, opt_state,
                            hpc=self.hpc, async_save=self.ck.async_save,
                            train_state=train_state,
                            keep_last=self.ck.keep_last, hooks=self.hooks)

        if self.goodput is not None:
            with self.goodput.measure("checkpoint_save"):
                _save()
        else:
            _save()

    def drain(self) -> None:
        """Exit/preempt barrier: nothing in-flight may outlive (or race)
        what follows — a synchronous exit save, or process exit. A hung
        async write is abandoned after its watchdog deadline rather than
        blocking shutdown."""
        if self.async_ckptr is not None:
            if not self.async_ckptr.drain():
                self._log("warning: abandoning a hung checkpoint write "
                          "at exit (see checkpoint/hung_saves)")
        wait_for_checkpoints()


# ---------------------------------------------------------------------------
# HuggingFace interchange (h2g / g2h)
# ---------------------------------------------------------------------------


def hf_to_params(state_dict: Dict[str, Any], cfg: ModelArgs) -> Params:
    """HF torch state dict -> our params pytree (reference h2g converters,
    tools/checkpoint_convert_h2g.py + llama_adapter.py:51-163). Supports the
    gpt2 (Conv1D fused qkv) and llama (separate q/k/v Linear) layouts."""
    import numpy as np

    def arr(t):
        return np.asarray(t.detach().numpy() if hasattr(t, "detach") else t)

    sd = {k: arr(v) for k, v in state_dict.items()}
    n = cfg.num_hidden_layers
    if cfg.model_type == "gpt" or "transformer.wte.weight" in sd:
        layers = []
        for i in range(n):
            pre = f"transformer.h.{i}."
            lp = {
                "ln1": {"scale": sd[pre + "ln_1.weight"],
                        "bias": sd[pre + "ln_1.bias"]},
                "attn": {"wqkv": sd[pre + "attn.c_attn.weight"],
                         "bqkv": sd[pre + "attn.c_attn.bias"],
                         "wo": sd[pre + "attn.c_proj.weight"],
                         "bo": sd[pre + "attn.c_proj.bias"]},
                "ln2": {"scale": sd[pre + "ln_2.weight"],
                        "bias": sd[pre + "ln_2.bias"]},
                "mlp": {"win": sd[pre + "mlp.c_fc.weight"],
                        "bin": sd[pre + "mlp.c_fc.bias"],
                        "wout": sd[pre + "mlp.c_proj.weight"],
                        "bout": sd[pre + "mlp.c_proj.bias"]},
            }
            layers.append(lp)
        wte = sd["transformer.wte.weight"]
        pad = cfg.padded_vocab_size - wte.shape[0]
        if pad > 0:
            wte = np.concatenate([wte, np.zeros((pad, wte.shape[1]),
                                                wte.dtype)])
        # HF gpt2 always ties lm_head to wte; an untied target config needs
        # its own whead or apply_lm_head would KeyError much later (ADVICE r2)
        head: Params = {}
        if not cfg.tie_word_embeddings:
            head = {"whead": (_pad_vocab(sd["lm_head.weight"], cfg).T
                              if "lm_head.weight" in sd else wte.T)}
        return {
            "embed": {"wte": wte, "wpe": sd["transformer.wpe.weight"]},
            "layers": tuple(layers),
            "prenorm": {"scale": sd["transformer.ln_f.weight"],
                        "bias": sd["transformer.ln_f.bias"]},
            "head": head,
        }

    if cfg.model_type == "bert" or "bert.embeddings.word_embeddings.weight" in sd:
        return _bert_hf_to_params(sd, cfg)
    if cfg.model_type == "t5" or "encoder.final_layer_norm.weight" in sd:
        return _t5_hf_to_params(sd, cfg)

    # llama-family: torch Linear stores [out, in] -> transpose
    def lin(name):
        return sd[name].T

    layers = []
    for i in range(n):
        pre = f"model.layers.{i}."
        wqkv = np.concatenate(
            [lin(pre + "self_attn.q_proj.weight"),
             lin(pre + "self_attn.k_proj.weight"),
             lin(pre + "self_attn.v_proj.weight")], axis=1)
        lp = {
            "ln1": {"scale": sd[pre + "input_layernorm.weight"]},
            "attn": {"wqkv": wqkv, "wo": lin(pre + "self_attn.o_proj.weight")},
            "ln2": {"scale": sd[pre + "post_attention_layernorm.weight"]},
        }
        if pre + "block_sparse_moe.gate.weight" in sd:
            # mixtral-style MoE FFN (reference moe_adapter.py:58-266):
            # experts.{e}.w1/w3 fuse into win [E, H, 2F], w2 -> wout [E, F, H]
            if cfg.num_shared_experts:
                raise NotImplementedError(
                    "the Mixtral HF layout has no shared-expert slot; "
                    "import with num_shared_experts=0")
            E = 0
            while pre + f"block_sparse_moe.experts.{E}.w1.weight" in sd:
                E += 1
            if E != cfg.num_experts:
                raise ValueError(
                    f"layer {i}: checkpoint has {E} experts but "
                    f"cfg.num_experts is {cfg.num_experts}")
            win = np.stack([
                np.concatenate(
                    [lin(pre + f"block_sparse_moe.experts.{e}.w1.weight"),
                     lin(pre + f"block_sparse_moe.experts.{e}.w3.weight")],
                    axis=1)
                for e in range(E)])
            wout = np.stack([
                lin(pre + f"block_sparse_moe.experts.{e}.w2.weight")
                for e in range(E)])
            lp["moe"] = {
                "router": lin(pre + "block_sparse_moe.gate.weight"),
                "win": win,
                "wout": wout,
            }
        else:
            win = np.concatenate(
                [lin(pre + "mlp.gate_proj.weight"),
                 lin(pre + "mlp.up_proj.weight")], axis=1)
            lp["mlp"] = {"win": win,
                         "wout": lin(pre + "mlp.down_proj.weight")}
        if cfg.add_qkv_bias:
            lp["attn"]["bqkv"] = np.concatenate(
                [sd[pre + "self_attn.q_proj.bias"],
                 sd[pre + "self_attn.k_proj.bias"],
                 sd[pre + "self_attn.v_proj.bias"]])
        layers.append(lp)
    wte = sd["model.embed_tokens.weight"]
    pad = cfg.padded_vocab_size - wte.shape[0]
    if pad > 0:
        wte = np.concatenate([wte, np.zeros((pad, wte.shape[1]), wte.dtype)])
    out: Params = {
        "embed": {"wte": wte},
        "layers": tuple(layers),
        "prenorm": {"scale": sd["model.norm.weight"]},
    }
    if cfg.tie_word_embeddings:
        out["head"] = {}
    else:
        whead = lin("lm_head.weight")
        if pad > 0:
            whead = np.concatenate(
                [whead, np.zeros((whead.shape[0], pad), whead.dtype)], axis=1)
        out["head"] = {"whead": whead}
    return out


def _pad_vocab(w: "np.ndarray", cfg: ModelArgs) -> "np.ndarray":
    import numpy as np

    pad = cfg.padded_vocab_size - w.shape[0]
    if pad > 0:
        w = np.concatenate(
            [w, np.zeros((pad,) + w.shape[1:], w.dtype)])
    return w


def _bert_hf_to_params(sd: Dict[str, Any], cfg: ModelArgs) -> Params:
    """HF BertForMaskedLM -> our post-norm encoder layout (reference
    tools/checkpoint_convert_h2g.py bert path). Token-type embeddings are
    folded into wpe for single-segment (type-0) training — the parallelism
    framework trains MLM on single segments (runtime/dataloader.py
    mlm_batches)."""
    import numpy as np

    def lin(name):
        return sd[name].T

    n = cfg.num_hidden_layers
    layers = []
    for i in range(n):
        pre = f"bert.encoder.layer.{i}."
        wqkv = np.concatenate(
            [lin(pre + "attention.self.query.weight"),
             lin(pre + "attention.self.key.weight"),
             lin(pre + "attention.self.value.weight")], axis=1)
        bqkv = np.concatenate(
            [sd[pre + "attention.self.query.bias"],
             sd[pre + "attention.self.key.bias"],
             sd[pre + "attention.self.value.bias"]])
        layers.append({
            "attn": {"wqkv": wqkv, "bqkv": bqkv,
                     "wo": lin(pre + "attention.output.dense.weight"),
                     "bo": sd[pre + "attention.output.dense.bias"]},
            "ln1": {"scale": sd[pre + "attention.output.LayerNorm.weight"],
                    "bias": sd[pre + "attention.output.LayerNorm.bias"]},
            "mlp": {"win": lin(pre + "intermediate.dense.weight"),
                    "bin": sd[pre + "intermediate.dense.bias"],
                    "wout": lin(pre + "output.dense.weight"),
                    "bout": sd[pre + "output.dense.bias"]},
            "ln2": {"scale": sd[pre + "output.LayerNorm.weight"],
                    "bias": sd[pre + "output.LayerNorm.bias"]},
        })
    wte = _pad_vocab(sd["bert.embeddings.word_embeddings.weight"], cfg)
    wpe = (sd["bert.embeddings.position_embeddings.weight"]
           + sd["bert.embeddings.token_type_embeddings.weight"][0][None, :])
    head: Params = {
        "wt": lin("cls.predictions.transform.dense.weight"),
        "bt": sd["cls.predictions.transform.dense.bias"],
        "ln": {"scale": sd["cls.predictions.transform.LayerNorm.weight"],
               "bias": sd["cls.predictions.transform.LayerNorm.bias"]},
        "bias": _pad_vocab(sd["cls.predictions.bias"], cfg),
    }
    if not cfg.tie_word_embeddings:
        head["whead"] = _pad_vocab(
            sd.get("cls.predictions.decoder.weight",
                   sd["bert.embeddings.word_embeddings.weight"]), cfg).T
    return {
        "embed": {"wte": wte, "wpe": wpe,
                  "ln": {"scale": sd["bert.embeddings.LayerNorm.weight"],
                         "bias": sd["bert.embeddings.LayerNorm.bias"]}},
        "layers": tuple(layers),
        "prenorm": {},
        "head": head,
    }


def _t5_hf_to_params(sd: Dict[str, Any], cfg: ModelArgs) -> Params:
    """HF T5ForConditionalGeneration -> our encoder-decoder layout.

    All projection/norm/MLP weights map 1:1 (q/k/v fused per stack; the
    decoder's EncDecAttention becomes the fused-KV cross block). HF T5's
    relative_attention_bias has no slot here by design — this runtime is
    position-scheme agnostic (models/encdec.py docstring) and runs the
    configured scheme (RoPE/learned), so imported T5 weights fine-tune
    rather than bit-match HF generation."""
    import numpy as np

    def lin(name):
        return sd[name].T

    inner = sd["encoder.block.0.layer.0.SelfAttention.q.weight"].shape[0]
    if inner != cfg.num_attention_heads * cfg.head_dim:
        raise ValueError(
            f"t5 checkpoint attention inner dim {inner} != heads*head_dim "
            f"{cfg.num_attention_heads * cfg.head_dim}: this runtime derives "
            "head_dim = hidden//heads (t5-small/base/large match; t5-3b/11b "
            "use d_kv=128 and need a config with matching geometry)")

    gated = "encoder.block.0.layer.1.DenseReluDense.wi_0.weight" in sd

    def mlp(pre):
        if gated:  # t5 v1.1 gated-act: wi_0 (gate) | wi_1 (up)
            win = np.concatenate([lin(pre + "DenseReluDense.wi_0.weight"),
                                  lin(pre + "DenseReluDense.wi_1.weight")],
                                 axis=1)
        else:
            win = lin(pre + "DenseReluDense.wi.weight")
        return {"win": win, "wout": lin(pre + "DenseReluDense.wo.weight")}

    n_enc = (cfg.num_encoder_layers if cfg.num_encoder_layers is not None
             else cfg.num_hidden_layers)
    enc_layers = []
    for i in range(n_enc):
        pre = f"encoder.block.{i}."
        wqkv = np.concatenate(
            [lin(pre + "layer.0.SelfAttention.q.weight"),
             lin(pre + "layer.0.SelfAttention.k.weight"),
             lin(pre + "layer.0.SelfAttention.v.weight")], axis=1)
        enc_layers.append({
            "ln1": {"scale": sd[pre + "layer.0.layer_norm.weight"]},
            "attn": {"wqkv": wqkv,
                     "wo": lin(pre + "layer.0.SelfAttention.o.weight")},
            "ln2": {"scale": sd[pre + "layer.1.layer_norm.weight"]},
            "mlp": mlp(pre + "layer.1."),
        })
    dec_layers = []
    for i in range(cfg.num_hidden_layers):
        pre = f"decoder.block.{i}."
        wqkv = np.concatenate(
            [lin(pre + "layer.0.SelfAttention.q.weight"),
             lin(pre + "layer.0.SelfAttention.k.weight"),
             lin(pre + "layer.0.SelfAttention.v.weight")], axis=1)
        wkv = np.concatenate(
            [lin(pre + "layer.1.EncDecAttention.k.weight"),
             lin(pre + "layer.1.EncDecAttention.v.weight")], axis=1)
        dec_layers.append({
            "ln1": {"scale": sd[pre + "layer.0.layer_norm.weight"]},
            "attn": {"wqkv": wqkv,
                     "wo": lin(pre + "layer.0.SelfAttention.o.weight")},
            "lnx": {"scale": sd[pre + "layer.1.layer_norm.weight"]},
            "cross": {"wq": lin(pre + "layer.1.EncDecAttention.q.weight"),
                      "wkv": wkv,
                      "wo": lin(pre + "layer.1.EncDecAttention.o.weight")},
            "ln2": {"scale": sd[pre + "layer.2.layer_norm.weight"]},
            "mlp": mlp(pre + "layer.2."),
        })
    out: Params = {
        "embed": {"wte": _pad_vocab(sd["shared.weight"], cfg)},
        "enc_layers": tuple(enc_layers),
        "enc_norm": {"scale": sd["encoder.final_layer_norm.weight"]},
        "layers": tuple(dec_layers),
        "prenorm": {"scale": sd["decoder.final_layer_norm.weight"]},
    }
    if cfg.tie_word_embeddings:
        out["head"] = {}
    else:
        out["head"] = {"whead": _pad_vocab(sd["lm_head.weight"], cfg).T}
    return out


def _bert_params_to_hf(params: Params, cfg: ModelArgs) -> Dict[str, "np.ndarray"]:
    """Inverse of :func:`_bert_hf_to_params`. Token-type embeddings were
    folded into wpe on import, so type 0 exports as zeros (wpe carries the
    sum) — re-importing reproduces the same forward exactly."""
    import numpy as np

    get = lambda t: np.asarray(jax.device_get(t))
    V, H = cfg.vocab_size, cfg.hidden_size
    hd, nq, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.kv_heads
    sd: Dict[str, np.ndarray] = {
        "bert.embeddings.word_embeddings.weight": get(params["embed"]["wte"])[:V],
        "bert.embeddings.position_embeddings.weight": get(params["embed"]["wpe"]),
        "bert.embeddings.token_type_embeddings.weight": np.zeros((2, H),
                                                                 np.float32),
        "bert.embeddings.LayerNorm.weight": get(params["embed"]["ln"]["scale"]),
        "bert.embeddings.LayerNorm.bias": get(params["embed"]["ln"]["bias"]),
    }
    for i, lp in enumerate(params["layers"]):
        pre = f"bert.encoder.layer.{i}."
        wqkv = get(lp["attn"]["wqkv"])
        q, k, v = np.split(wqkv, [nq * hd, (nq + nkv) * hd], axis=1)
        bq, bk, bv = np.split(get(lp["attn"]["bqkv"]),
                              [nq * hd, (nq + nkv) * hd])
        sd[pre + "attention.self.query.weight"] = q.T
        sd[pre + "attention.self.query.bias"] = bq
        sd[pre + "attention.self.key.weight"] = k.T
        sd[pre + "attention.self.key.bias"] = bk
        sd[pre + "attention.self.value.weight"] = v.T
        sd[pre + "attention.self.value.bias"] = bv
        sd[pre + "attention.output.dense.weight"] = get(lp["attn"]["wo"]).T
        sd[pre + "attention.output.dense.bias"] = get(lp["attn"]["bo"])
        sd[pre + "attention.output.LayerNorm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "attention.output.LayerNorm.bias"] = get(lp["ln1"]["bias"])
        sd[pre + "intermediate.dense.weight"] = get(lp["mlp"]["win"]).T
        sd[pre + "intermediate.dense.bias"] = get(lp["mlp"]["bin"])
        sd[pre + "output.dense.weight"] = get(lp["mlp"]["wout"]).T
        sd[pre + "output.dense.bias"] = get(lp["mlp"]["bout"])
        sd[pre + "output.LayerNorm.weight"] = get(lp["ln2"]["scale"])
        sd[pre + "output.LayerNorm.bias"] = get(lp["ln2"]["bias"])
    hp = params["head"]
    sd["cls.predictions.transform.dense.weight"] = get(hp["wt"]).T
    sd["cls.predictions.transform.dense.bias"] = get(hp["bt"])
    sd["cls.predictions.transform.LayerNorm.weight"] = get(hp["ln"]["scale"])
    sd["cls.predictions.transform.LayerNorm.bias"] = get(hp["ln"]["bias"])
    sd["cls.predictions.bias"] = get(hp["bias"])[:V]
    if not cfg.tie_word_embeddings and "whead" in hp:
        sd["cls.predictions.decoder.weight"] = get(hp["whead"]).T[:V]
    return sd


def _t5_params_to_hf(params: Params, cfg: ModelArgs) -> Dict[str, "np.ndarray"]:
    """Inverse of :func:`_t5_hf_to_params` (gated t5-v1.1 MLP layout when the
    model uses a gated activation)."""
    import numpy as np

    get = lambda t: np.asarray(jax.device_get(t))
    from hetu_galvatron_tpu.models.modules import _is_gated

    V = cfg.vocab_size
    hd, nq, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.kv_heads
    sd: Dict[str, np.ndarray] = {
        "shared.weight": get(params["embed"]["wte"])[:V],
        "encoder.final_layer_norm.weight": get(params["enc_norm"]["scale"]),
        "decoder.final_layer_norm.weight": get(params["prenorm"]["scale"]),
    }

    def put_mlp(pre, mp):
        win = get(mp["win"])
        if _is_gated(cfg.hidden_act):
            gate, up = np.split(win, 2, axis=1)
            sd[pre + "DenseReluDense.wi_0.weight"] = gate.T
            sd[pre + "DenseReluDense.wi_1.weight"] = up.T
        else:
            sd[pre + "DenseReluDense.wi.weight"] = win.T
        sd[pre + "DenseReluDense.wo.weight"] = get(mp["wout"]).T

    def put_self_attn(pre, ap):
        wqkv = get(ap["wqkv"])
        q, k, v = np.split(wqkv, [nq * hd, (nq + nkv) * hd], axis=1)
        sd[pre + "SelfAttention.q.weight"] = q.T
        sd[pre + "SelfAttention.k.weight"] = k.T
        sd[pre + "SelfAttention.v.weight"] = v.T
        sd[pre + "SelfAttention.o.weight"] = get(ap["wo"]).T

    for i, lp in enumerate(params["enc_layers"]):
        pre = f"encoder.block.{i}."
        put_self_attn(pre + "layer.0.", lp["attn"])
        sd[pre + "layer.0.layer_norm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "layer.1.layer_norm.weight"] = get(lp["ln2"]["scale"])
        put_mlp(pre + "layer.1.", lp["mlp"])
    for i, lp in enumerate(params["layers"]):
        pre = f"decoder.block.{i}."
        put_self_attn(pre + "layer.0.", lp["attn"])
        sd[pre + "layer.0.layer_norm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "layer.1.layer_norm.weight"] = get(lp["lnx"]["scale"])
        sd[pre + "layer.1.EncDecAttention.q.weight"] = get(lp["cross"]["wq"]).T
        wkv = get(lp["cross"]["wkv"])
        k, v = np.split(wkv, 2, axis=1)
        sd[pre + "layer.1.EncDecAttention.k.weight"] = k.T
        sd[pre + "layer.1.EncDecAttention.v.weight"] = v.T
        sd[pre + "layer.1.EncDecAttention.o.weight"] = get(lp["cross"]["wo"]).T
        sd[pre + "layer.2.layer_norm.weight"] = get(lp["ln2"]["scale"])
        put_mlp(pre + "layer.2.", lp["mlp"])
    if not cfg.tie_word_embeddings and params.get("head"):
        sd["lm_head.weight"] = get(params["head"]["whead"]).T[:V]
    return sd


def params_to_hf(params: Params, cfg: ModelArgs) -> Dict[str, np.ndarray]:
    """Our params -> HF-layout numpy state dict (reference g2h converters).
    Inverse of :func:`hf_to_params`; vocab padding rows are dropped."""
    get = lambda t: np.asarray(jax.device_get(t))
    sd: Dict[str, np.ndarray] = {}
    V = cfg.vocab_size
    if cfg.model_type == "bert":
        return _bert_params_to_hf(params, cfg)
    if cfg.model_type == "t5":
        return _t5_params_to_hf(params, cfg)
    if cfg.model_type == "gpt":
        sd["transformer.wte.weight"] = get(params["embed"]["wte"])[:V]
        sd["transformer.wpe.weight"] = get(params["embed"]["wpe"])
        for i, lp in enumerate(params["layers"]):
            pre = f"transformer.h.{i}."
            sd[pre + "ln_1.weight"] = get(lp["ln1"]["scale"])
            sd[pre + "ln_1.bias"] = get(lp["ln1"]["bias"])
            sd[pre + "attn.c_attn.weight"] = get(lp["attn"]["wqkv"])
            sd[pre + "attn.c_attn.bias"] = get(lp["attn"]["bqkv"])
            sd[pre + "attn.c_proj.weight"] = get(lp["attn"]["wo"])
            sd[pre + "attn.c_proj.bias"] = get(lp["attn"]["bo"])
            sd[pre + "ln_2.weight"] = get(lp["ln2"]["scale"])
            sd[pre + "ln_2.bias"] = get(lp["ln2"]["bias"])
            sd[pre + "mlp.c_fc.weight"] = get(lp["mlp"]["win"])
            sd[pre + "mlp.c_fc.bias"] = get(lp["mlp"]["bin"])
            sd[pre + "mlp.c_proj.weight"] = get(lp["mlp"]["wout"])
            sd[pre + "mlp.c_proj.bias"] = get(lp["mlp"]["bout"])
        sd["transformer.ln_f.weight"] = get(params["prenorm"]["scale"])
        sd["transformer.ln_f.bias"] = get(params["prenorm"]["bias"])
        return sd

    sd["model.embed_tokens.weight"] = get(params["embed"]["wte"])[:V]
    hd, nq, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.kv_heads
    for i, lp in enumerate(params["layers"]):
        pre = f"model.layers.{i}."
        wqkv = get(lp["attn"]["wqkv"])
        q, k, v = np.split(wqkv, [nq * hd, (nq + nkv) * hd], axis=1)
        sd[pre + "self_attn.q_proj.weight"] = q.T
        sd[pre + "self_attn.k_proj.weight"] = k.T
        sd[pre + "self_attn.v_proj.weight"] = v.T
        sd[pre + "self_attn.o_proj.weight"] = get(lp["attn"]["wo"]).T
        if "bqkv" in lp["attn"]:
            bqkv = get(lp["attn"]["bqkv"])
            bq, bk, bv = np.split(bqkv, [nq * hd, (nq + nkv) * hd])
            sd[pre + "self_attn.q_proj.bias"] = bq
            sd[pre + "self_attn.k_proj.bias"] = bk
            sd[pre + "self_attn.v_proj.bias"] = bv
        if "moe" in lp:
            if "shared" in lp["moe"]:
                raise NotImplementedError(
                    "the Mixtral HF layout has no shared-expert slot; "
                    "export models with num_shared_experts=0")
            sd[pre + "block_sparse_moe.gate.weight"] = \
                get(lp["moe"]["router"]).T
            win = get(lp["moe"]["win"])
            wout = get(lp["moe"]["wout"])
            for e in range(win.shape[0]):
                w1, w3 = np.split(win[e], 2, axis=1)
                sd[pre + f"block_sparse_moe.experts.{e}.w1.weight"] = w1.T
                sd[pre + f"block_sparse_moe.experts.{e}.w3.weight"] = w3.T
                sd[pre + f"block_sparse_moe.experts.{e}.w2.weight"] = \
                    wout[e].T
        else:
            win = get(lp["mlp"]["win"])
            gate, up = np.split(win, 2, axis=1)
            sd[pre + "mlp.gate_proj.weight"] = gate.T
            sd[pre + "mlp.up_proj.weight"] = up.T
            sd[pre + "mlp.down_proj.weight"] = get(lp["mlp"]["wout"]).T
        sd[pre + "input_layernorm.weight"] = get(lp["ln1"]["scale"])
        sd[pre + "post_attention_layernorm.weight"] = get(lp["ln2"]["scale"])
    sd["model.norm.weight"] = get(params["prenorm"]["scale"])
    if not cfg.tie_word_embeddings and params.get("head"):
        sd["lm_head.weight"] = get(params["head"]["whead"]).T[:V]
    return sd
