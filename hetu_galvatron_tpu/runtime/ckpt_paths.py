"""Checkpoint path/marker protocol helpers — importable WITHOUT jax.

The atomic-commit protocol (``step_<n>.tmp`` staging -> fsynced
``COMMITTED`` marker -> rename) lives in ``runtime/checkpoint.py``, but
two consumers must speak it without initializing a JAX backend:

* the cross-process supervisor (``runtime/supervisor.ProcessSupervisor``
  / ``cli/supervise.py``) reads commit receipts and writes the
  ``RESUME_PIN`` between child processes — importing jax there would
  grab the accelerator the child is about to need;
* tools that inspect checkpoint roots offline.

So the pure-path half of the protocol lives here: step-name parsing,
commit detection, newest-committed selection, safe meta reads, atomic
JSON writes, and the cross-process ``RESUME_PIN`` lease that closes the
GC-vs-concurrent-resume race across processes (the in-process half is
``checkpoint._RESUME_PROTECTED``). ``checkpoint.py`` imports these
constants/helpers, so there is exactly one definition of the protocol.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

# A step dir without this marker is partial garbage from a mid-save
# crash: never selected, eligible for GC. (The marker, not just the
# rename, because FUSE-mounted object stores can surface a directory
# rename non-atomically.)
COMMIT_MARKER = "COMMITTED"
TMP_SUFFIX = ".tmp"
OLD_SUFFIX = ".old"  # previous committed payload during an overwrite

# Cross-process resume lease: the supervisor stamps the step dir the next
# child attempt will restore from; gc_checkpoints holds that dir out of
# the retention prune set. The pin carries a wall-clock stamp and expires
# (a crashed supervisor must not pin a step dir forever).
RESUME_PIN = "RESUME_PIN"
RESUME_PIN_TTL_S = 24 * 3600.0


def step_of(entry: str) -> Optional[int]:
    """``step_<int>`` -> int; anything else (orbax temp dirs,
    ``step_5.partial``, ``.tmp`` staging dirs) -> None."""
    if not entry.startswith("step_"):
        return None
    suffix = entry[len("step_"):]
    if not suffix.isdigit():
        return None
    return int(suffix)


def is_committed(ckpt_dir: str) -> bool:
    """A step dir counts as committed when it carries the commit marker
    (new protocol) or a meta.json (pre-marker checkpoints, which wrote
    meta.json last)."""
    return (os.path.exists(os.path.join(ckpt_dir, COMMIT_MARKER))
            or os.path.exists(os.path.join(ckpt_dir, "meta.json")))


def committed_steps(root: str) -> List[Tuple[int, str]]:
    """Every committed ``(step, abs_dir)`` under ``root``, ascending by
    step. Partial/staging/stray entries are skipped, never raised on."""
    if not os.path.isdir(root):
        return []
    out: List[Tuple[int, str]] = []
    try:
        entries = os.listdir(root)
    except OSError:
        return []
    for entry in entries:
        step = step_of(entry)
        if step is None:
            continue
        full = os.path.join(root, entry)
        if os.path.isdir(full) and is_committed(full):
            out.append((step, os.path.abspath(full)))
    out.sort()
    return out


def latest_committed_step(root: str) -> Optional[Tuple[int, str]]:
    """Newest committed ``(step, abs_dir)``, or None — the jax-free
    counterpart of ``checkpoint.latest_checkpoint`` (which additionally
    registers in-process resume protection)."""
    steps = committed_steps(root)
    return steps[-1] if steps else None


def commit_wall_time(ckpt_dir: str) -> Optional[float]:
    """Wall-clock time of the commit (the marker's mtime; meta.json for
    pre-marker checkpoints) — the supervisor's RPO clock."""
    for name in (COMMIT_MARKER, "meta.json"):
        p = os.path.join(ckpt_dir, name)
        try:
            return os.path.getmtime(p)
        except OSError:
            continue
    return None


def atomic_write_json(path: str, payload: Dict[str, Any]) -> None:
    """tmp + fsync + rename — readers see the old content or the new,
    never a torn file (same discipline as the commit marker)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def try_read_json(path: str) -> Tuple[Optional[Dict[str, Any]],
                                      Optional[Exception]]:
    """Read a JSON file defensively: ``(payload, None)`` on success,
    ``(None, error)`` on absence/corruption — callers on resume paths
    must degrade, not traceback."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except Exception as e:  # noqa: BLE001 — defensive read by contract
        return None, e
    if not isinstance(payload, dict):
        return None, ValueError(f"{path}: expected a JSON object, got "
                                f"{type(payload).__name__}")
    return payload, None


def try_read_meta(ckpt_dir: str) -> Tuple[Dict[str, Any],
                                          Optional[Exception]]:
    """A step dir's meta.json, never raising: ``({}, err)`` when absent,
    unparseable, or truncated. The jax-free sibling of
    ``checkpoint.read_checkpoint_meta`` (no retry policy here — the
    supervisor polls, it does not block on backoff)."""
    meta, err = try_read_json(os.path.join(ckpt_dir, "meta.json"))
    return (meta if meta is not None else {}), err


def stored_world_of(root: str) -> Optional[int]:
    """world_size recorded by the newest commit's plan fingerprint — the
    supervisor's cross-process world probe (a topology change becomes
    visible once the new world commits, without touching jax)."""
    latest = latest_committed_step(root)
    if latest is None:
        return None
    meta, _ = try_read_meta(latest[1])
    world = (meta.get("hybrid_parallel_config") or {}).get("world_size")
    return int(world) if world is not None else None


# -- RESUME_PIN lease --------------------------------------------------------


def write_resume_pin(root: str, ckpt_dir: str, *,
                     owner: Optional[str] = None) -> str:
    """Pin ``ckpt_dir`` against retention GC before a relaunch resumes
    from it. Atomic (tmp+rename); returns the pin path."""
    pin = os.path.join(root, RESUME_PIN)
    atomic_write_json(pin, {
        "ckpt": os.path.abspath(ckpt_dir),
        "owner": owner or f"pid:{os.getpid()}",
        "t_wall": time.time(),
    })
    return pin


def read_resume_pin(root: str, *,
                    ttl_s: float = RESUME_PIN_TTL_S) -> Optional[str]:
    """The pinned step dir (abs path), or None when there is no live pin.
    An unparseable or expired pin reads as absent — a crashed supervisor
    must not protect a step dir forever."""
    payload, _ = try_read_json(os.path.join(root, RESUME_PIN))
    if not payload:
        return None
    ckpt = payload.get("ckpt")
    t_wall = payload.get("t_wall")
    if not isinstance(ckpt, str):
        return None
    if isinstance(t_wall, (int, float)) and \
            time.time() - t_wall > ttl_s:
        return None
    return ckpt


def clear_resume_pin(root: str) -> None:
    try:
        os.remove(os.path.join(root, RESUME_PIN))
    except OSError:
        pass
