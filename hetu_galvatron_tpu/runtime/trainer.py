"""Single-program training loop: jitted train_step + microbatch accumulation.

Capability parity with the reference's no-pipeline execution path
(runtime/pipeline/pipeline.py:306-385 ``no_pipeline_forward_backward`` +
models/gpt/train_dist.py:21-74 train loop): build loss, grads, clip, Adam
update, loss scalar back — but as one jitted pure function over
(params, opt_state, batch) instead of a module graph walk.

Microbatching (the reference's ``chunks``) is a `lax.scan` over the leading
batch-chunk axis with gradient accumulation in fp32, which XLA pipelines
without host round-trips.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from hetu_galvatron_tpu.core.args_schema import CoreArgs, ModelArgs
from hetu_galvatron_tpu.models.builder import causal_lm_loss
from hetu_galvatron_tpu.runtime.optimizer import global_grad_norm, make_optimizer


def make_loss_fn(
    cfg: ModelArgs,
    *,
    compute_dtype=jnp.bfloat16,
    remat_flags=None,
    layer_overrides=None,
) -> Callable[[Any, Dict[str, jax.Array]], jax.Array]:
    def loss_fn(params, batch):
        return causal_lm_loss(
            params, batch, cfg,
            compute_dtype=compute_dtype,
            remat_flags=remat_flags,
            layer_overrides=layer_overrides,
        )
    return loss_fn


def microbatch_weights(loss_mask: Optional[jax.Array], chunks: int
                       ) -> jax.Array:
    """Per-microbatch token-share weights from a ``[chunks, ...]``-stacked
    loss mask: each microbatch's masked-mean loss is weighted by its share
    of valid tokens so gradient accumulation matches the unchunked step
    exactly even under non-uniform masks. ``None`` mask -> uniform
    ``1/chunks``. Shared by the scanned SPMD step and both pipeline
    engines (host and compiled)."""
    if loss_mask is None:
        return jnp.full((chunks,), 1.0 / chunks, jnp.float32)
    counts = jnp.sum(loss_mask.astype(jnp.float32),
                     axis=tuple(range(1, loss_mask.ndim)))
    return counts / jnp.maximum(jnp.sum(counts), 1.0)


def make_train_step(
    loss_fn: Callable[[Any, Dict[str, jax.Array]], jax.Array],
    tx: optax.GradientTransformation,
    *,
    chunks: int = 1,
    aux_stats: bool = False,
    hier: Optional[Any] = None,
    constrain_microbatches: Optional[Callable[[Any], Any]] = None,
) -> Callable:
    """Returns train_step(params, opt_state, batch) -> (params, opt_state,
    metrics). ``chunks`` splits the global batch into microbatches scanned
    with fp32 grad accumulation (reference chunks semantics,
    hybrid_parallel_config.py:359).

    ``aux_stats=True`` means loss_fn returns (loss, stats_pytree); the
    stats land in metrics["moe"] — the reference's per-layer aux-losses
    tracker (moe_utils.py:547-644). Loss-like stats are token-weighted
    across microbatches; "tokens_per_expert" leaves are summed.

    ``hier`` (an ``ops.hier_reduce.HierDpReducer``) swaps the implicit
    GSPMD dp gradient all-reduce for the explicit hierarchical path:
    per-dp-lane grads accumulate lane-local through the microbatch scan
    (zero cross-dp bytes in-scan) and reduce ONCE per step via the
    reducer's three-collective reduce-scatter/all-reduce/all-gather
    program. Per-(microbatch, lane) token-share weighting keeps the
    result equal to the flat path up to reduction reassociation.

    ``constrain_microbatches`` is an optional hook applied to the
    ``[chunks, B/chunks, ...]``-stacked batch tree right after the
    reshape on the flat scanned path. The SPMD path pins the stack so
    the CHUNK axis is replicated and the sample axis keeps the plan's
    batch sharding: without the pin, the reshape naturally absorbs the
    outer dp mesh axis into the chunk dim, every scanned microbatch
    arrives sharded over only the INNER dp axes — and under ZeRO-3 the
    partitioner's gradient program for that layout is numerically WRONG
    (the ROADMAP embed-ZeRO-3 + vtp>1 + chunks>1 bug: wrong wte rows at
    grad magnitude — and in fact every dp-sharded grad leaf drifts).
    The pin makes each microbatch's embed-grad reduce-scatter
    materialize per microbatch in the plan's own layout; the hier path
    has always pinned (``hier.lane_batch``), which is why it was exact
    where flat drifted."""

    if hier is not None and aux_stats:
        raise ValueError(
            "hier_dp does not compose with aux-stats (MoE) steps; see "
            "eligibility.hier_dp_unsupported_reason")

    if aux_stats:
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    else:
        _plain = jax.value_and_grad(loss_fn)

        def grad_fn(p, b):
            l, g = _plain(p, b)
            return (l, {}), g

    def _reduce_stats(stacked, weights):
        def red(path, s):
            if any("tokens_per_expert" in str(k) for k in path):
                return jnp.sum(s, axis=0)
            w = weights.reshape((-1,) + (1,) * (s.ndim - 1))
            return jnp.sum(w * s, axis=0)
        return jax.tree_util.tree_map_with_path(red, stacked)

    def _hier_grads(params, batch):
        """Per-lane grads + one hierarchical reduce (hier is not None).
        Returns (loss, grads); stats are {} (aux gated above)."""
        L = hier.lanes
        # spmd_axis_name pins the lane axis of every batched intermediate
        # (and of constraints inside the loss, which the lane_dp loss
        # variant builds dp-free) onto the dp mesh axes — without it the
        # partitioner re-shards each lane's slice at every interior
        # constraint (measured 3-6x step-time blowup on the CPU mesh)
        vgrad = jax.vmap(grad_fn, in_axes=(None, 0),
                         spmd_axis_name=tuple(hier.dp_axes))
        if chunks <= 1:
            mbl = hier.lane_batch(batch)
            w = microbatch_weights(mbl.get("loss_mask"), L)
            (losses, _), g = vgrad(params, mbl)
            acc = hier.constrain_stacked(jax.tree.map(
                lambda gg: (gg.astype(jnp.float32)
                            * w.reshape((L,) + (1,) * (gg.ndim - 1))), g))
            return jnp.sum(w * losses), hier.reduce(acc)
        bsz = batch["tokens"].shape[0]
        if bsz % chunks:
            raise ValueError(
                f"batch size {bsz} is not divisible by chunks={chunks}; "
                f"adjust global_train_batch_size or chunks")
        mbs = jax.tree.map(
            lambda x: x.reshape((chunks, x.shape[0] // chunks)
                                + x.shape[1:]), batch)
        # per-(microbatch, lane) token shares of the GLOBAL batch: the
        # weighted per-lane masked means recombine to the flat path's
        # token-weighted accumulation exactly
        mask = mbs.get("loss_mask")
        if mask is None:
            w_cl = jnp.full((chunks, L), 1.0 / (chunks * L), jnp.float32)
        else:
            ml = mask.reshape((chunks, L, mask.shape[1] // L)
                              + mask.shape[2:]).astype(jnp.float32)
            counts = jnp.sum(ml, axis=tuple(range(2, ml.ndim)))
            w_cl = counts / jnp.maximum(jnp.sum(counts), 1.0)

        def microbatch(acc, xs):
            mb, w = xs
            mbl = hier.lane_batch(mb)
            (losses, _), g = vgrad(params, mbl)
            acc = jax.tree.map(
                lambda a, b: a + (w.reshape((L,) + (1,) * (b.ndim - 1))
                                  * b.astype(jnp.float32)), acc, g)
            return hier.constrain_stacked(acc), jnp.sum(w * losses)

        zeros = hier.constrain_stacked(jax.tree.map(
            lambda p: jnp.zeros((L,) + p.shape, jnp.float32), params))
        acc, wlosses = jax.lax.scan(microbatch, zeros, (mbs, w_cl))
        return jnp.sum(wlosses), hier.reduce(acc)

    def step(params, opt_state, batch):
        # a "dropout_rng" key rides in the batch dict (so every execution
        # path — single-device, SPMD, chunked — keeps one step signature);
        # it is per-step data, not a [B, ...] array, so the microbatch
        # reshape must not touch it
        batch = dict(batch)
        rng = batch.pop("dropout_rng", None)
        if hier is not None:
            if rng is not None:
                raise ValueError(
                    "hier_dp requires dropout disabled (eligibility."
                    "HIER_DROPOUT_REASON): per-lane rng streams would "
                    "draw masks the flat path never draws")
            loss, grads = _hier_grads(params, batch)
            stats = {}
        elif chunks <= 1:
            if rng is not None:
                batch["dropout_rng"] = rng
            (loss, stats), grads = grad_fn(params, batch)
        else:
            bsz = batch["tokens"].shape[0]
            if bsz % chunks:
                raise ValueError(
                    f"batch size {bsz} is not divisible by chunks={chunks}; "
                    f"adjust global_train_batch_size or chunks")
            mbs = jax.tree.map(
                lambda x: x.reshape((chunks, x.shape[0] // chunks) + x.shape[1:]),
                batch)
            if constrain_microbatches is not None:
                mbs = constrain_microbatches(mbs)
            if rng is not None:
                mbs["dropout_rng"] = jax.random.split(rng, chunks)
            # token-weighted accumulation: each microbatch's masked-mean loss
            # is weighted by its share of valid tokens so chunks>1 matches
            # chunks=1 exactly even under non-uniform loss masks
            weights = microbatch_weights(mbs.get("loss_mask"), chunks)

            def microbatch(acc, xs):
                mb, w = xs
                (l, st), g = grad_fn(params, mb)
                acc = jax.tree.map(
                    lambda a, b: a + w * b.astype(jnp.float32), acc, g)
                return acc, (w * l, st)

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            grads, (wlosses, stacked) = jax.lax.scan(
                microbatch, zeros, (mbs, weights))
            loss = jnp.sum(wlosses)
            stats = _reduce_stats(stacked, weights) if aux_stats else {}
        gnorm = global_grad_norm(grads)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        metrics = {"loss": loss, "grad_norm": gnorm}
        if aux_stats:
            metrics["moe"] = stats
        return new_params, new_opt, metrics

    return step


def train_loop(
    args: CoreArgs,
    params: Any,
    data_iter,
    *,
    train_step: Optional[Callable] = None,
    tx: Optional[optax.GradientTransformation] = None,
    device_put: Callable[[Dict[str, Any]], Dict[str, jax.Array]] = None,
    hooks: Tuple[Callable, ...] = (),
    telemetry: Optional[Any] = None,
    preemption: Optional[Any] = None,
    goodput: Optional[Any] = None,
    checkpoint: Optional[Any] = None,
) -> Tuple[Any, Any, list]:
    """Host-side iteration driver (reference train_dist.py:49-73): fetch
    batch, run jitted step, invoke profiler/logging hooks. Returns final
    (params, opt_state, losses).

    ``hooks`` are ``h(it, metrics)`` callables invoked after every step
    with the step's (possibly still in-flight) device metrics — hooks must
    not force a device sync. ``preemption`` is an optional object with a
    ``requested() -> bool`` method (``runtime.supervisor.PreemptionGuard``)
    checked at every step boundary: once true the loop stops cleanly after
    the in-flight step, returning what it has — the caller checkpoints and
    exits. ``telemetry`` is an optional
    ``observability.TrainingTelemetry`` appended to the hooks; it is
    final-flushed when the loop exits (even on error) and left open for
    the caller to reuse/close. When ``args.observability.enabled`` and no
    instance is passed, one is built from the args (JSONL sink at
    ``observability.metrics_path``) and closed with the loop.
    ``goodput`` is an optional
    ``observability.goodput.GoodputTracker``: each iteration's host wall
    is booked as ``productive_step`` (the first iteration as
    ``recompile`` — it pays the jit), so even this minimal loop feeds
    the goodput partition; flushing/persistence stay the caller's job.
    ``checkpoint`` is an optional
    ``runtime.checkpoint.CheckpointCadence``: when its cadence (step
    interval or wall interval) is due, the post-update state is saved
    through it (async snapshot or sync write per its config) and any
    in-flight write is drained when the loop exits — even on error, so
    a crashing attempt never leaks a background writer."""
    from hetu_galvatron_tpu.models.modules import compute_dtype_of
    from hetu_galvatron_tpu.observability.tracing import span

    # rank-gated like the train_dist launcher: on a multi-host pod only
    # process 0 may configure sinks (every process appending to one
    # shared-storage JSONL would interleave)
    owns_telemetry = (telemetry is None and args.observability.enabled
                      and jax.process_index() == 0)
    if owns_telemetry:
        telemetry = make_telemetry(args)

    tx = tx or make_optimizer(args.train)
    if train_step is None:
        loss_fn = make_loss_fn(
            args.model,
            compute_dtype=compute_dtype_of(args.parallel.mixed_precision),
        )
        # chunks=-1 means "auto"; the hybrid-parallel config layer resolves
        # it properly — without a plan, auto degrades to no microbatching
        chunks = max(args.parallel.chunks, 1)
        train_step = jax.jit(make_train_step(loss_fn, tx, chunks=chunks))
    opt_state = tx.init(params)
    device_losses = []
    put = device_put or (lambda b: jax.tree.map(jnp.asarray, b))
    use_dropout = (args.model.hidden_dropout > 0.0
                   or args.model.attention_dropout > 0.0)
    drop_key = jax.random.key(args.train.seed) if use_dropout else None
    all_hooks = hooks + ((telemetry,) if telemetry is not None else ())
    try:
        for it in range(args.train.train_iters):
            it_t0 = time.perf_counter()
            with span("train/fetch"):
                batch = put(next(data_iter))
            if use_dropout:
                batch["dropout_rng"] = jax.random.fold_in(drop_key, it)
            if it == 0:
                # XLA's own flops/bytes for the step program (cost/* gauges;
                # no-op unless a metrics sink is configured). BEFORE the
                # call: lowering only reads avals, so donated buffers are
                # still valid (and it stays lowering-only — no extra
                # backend compile).
                from hetu_galvatron_tpu.observability.trace_analysis import (
                    maybe_record_jit_cost,
                )

                maybe_record_jit_cost("train/step", train_step,
                                      (params, opt_state, batch))
            with span("train/step"):
                params, opt_state, metrics = train_step(
                    params, opt_state, batch)
            # keep losses on device — a float() here would block async
            # dispatch and serialize host batch-prep against device compute
            device_losses.append(metrics["loss"])
            for h in all_hooks:
                h(it, metrics)
            if goodput is not None:
                goodput.add("recompile" if it == 0 else "productive_step",
                            time.perf_counter() - it_t0)
            if checkpoint is not None and checkpoint.due(it):
                # after the goodput booking: the cadence books its own
                # wall (snapshot stall or full sync write) to
                # checkpoint_save, not to this step's productive time
                checkpoint.save(it + 1, params, opt_state)
            if preemption is not None and preemption.requested():
                # step boundary: the update above is complete and safe to
                # checkpoint; never abandon a step mid-flight
                break
    finally:
        if checkpoint is not None:
            try:
                checkpoint.drain()
            except Exception as e:  # noqa: BLE001 — never mask loop error
                print(f"warning: checkpoint drain at loop exit failed "
                      f"({type(e).__name__}: {e})", flush=True)
        # a loop-owned telemetry is closed here; a caller-supplied one is
        # only final-flushed (the caller may reuse it across loops and
        # closes it when done — close() re-arms on the next __call__)
        if telemetry is not None:
            if owns_telemetry:
                telemetry.close()
            else:
                telemetry.flush(final=True)
    losses = [float(l) for l in device_losses]
    return params, opt_state, losses


def make_telemetry(args: CoreArgs, *, registry: Any = None,
                   world_size: int = 1, global_batch_size: Optional[int] = None
                   ) -> Any:
    """Build a ``TrainingTelemetry`` hook (plus its JSONL/TensorBoard
    sinks) from ``args.observability``. When no ``registry`` is passed the
    process-wide default registry is (re)configured with the sinks, so
    library-level instrumentation (rerun counters, profiler histograms,
    spans) lands in the same file."""
    import os

    from hetu_galvatron_tpu.observability.registry import configure
    from hetu_galvatron_tpu.observability.telemetry import TrainingTelemetry

    obs = args.observability
    if registry is None:
        path = obs.metrics_path or os.path.join(
            args.logging.tensorboard_dir or ".", "metrics.jsonl")
        registry = configure(
            jsonl_path=path,
            tensorboard_dir=(args.logging.tensorboard_dir
                             if obs.tensorboard else None))
    return TrainingTelemetry(
        registry,
        model=args.model,
        global_batch_size=(global_batch_size
                           or args.parallel.global_train_batch_size),
        seq_length=args.model.seq_length,
        world_size=world_size,
        peak_tflops_per_device=obs.peak_tflops,
        flush_interval=obs.flush_interval,
    )
